"""Int8 quantized inference + the fused dihedral symmetry ensemble.

The raw forward has been unchanged f32/bf16 since round 4 (ROADMAP open
item 1); this module is the quantized serving path that closes it. Two
ideas, composable:

  * **per-output-channel symmetric int8 weight quantization**
    (``quantize_params``): each conv kernel ``w[k, k, cin, cout]`` is
    stored as int8 with one f32 scale per OUTPUT channel —
    ``w ≈ w_q * scale[cout]`` with ``scale = max|w|/127`` over the
    channel's taps. Activations stay bf16 and the accumulation runs in
    f32 (``preferred_element_type``), so the only numerics change vs the
    f32 forward is the weight rounding itself. The dequant multiply is
    **folded into the conv epilogue** inside the jitted forward
    (``y = conv(x, w_q) * scale + b``) — per-output-channel scaling
    commutes with the channel-wise conv sum, so this is exact, and XLA
    fuses it with the existing bias-add/ReLU epilogue. The pattern is
    SNIPPETS.md [2]: int8 weights as first-class pytree leaves the
    sharding/serving machinery handles like any other params.
  * **fused 8-fold dihedral ensemble** (``make_fused_sym_policy_fn``):
    the dihedral average that ``make_sym_policy_fn`` computes, restated
    as an ENGINE-FACING forward that rides the compile-once bucket
    ladder: all eight views are stacked on the batch axis inside ONE
    jitted program — permutation gather, plane expansion, conv stack,
    inverse gather, and a log-sum-exp average (``log((1/8)Σ p_k)``
    computed stably in log space, never materializing probabilities).
    ``quant=True`` runs the stack over int8 weights — the ``int8+sym``
    serving variant.

The **tolerance harness** (``tolerance_report`` / ``check_tolerance``)
is the gate that lets a lossy variant near production: per bucket-ladder
rung it measures top-1 agreement and max-abs log-prob drift against the
exact reference forward of the SAME program shape (int8 vs f32 plain;
int8+sym vs f32 fused-sym), publishes ``deepgo_quant_*`` gauges, and
``check_tolerance`` raises a typed :class:`VariantToleranceError` below
the floors — serving/variants.py calls it before a variant may serve,
so a quantization regression refuses loudly instead of silently costing
dan rank (docs/serving.md "Serving variants").
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from .. import NUM_POINTS
from ..ops import get_expand_fn
from . import policy_cnn

# symmetric int8: the full signed range minus the asymmetric -128, so
# the codebook is symmetric around zero and dequant is one multiply
QUANT_MAX = 127.0


class VariantToleranceError(RuntimeError):
    """A lossy serving variant fell below its tolerance floors vs the
    exact reference forward. The variant must refuse to serve — speed is
    never allowed to silently cost correctness. Carries the offending
    ``report`` (the full per-rung measurement)."""

    def __init__(self, message: str, report: dict):
        super().__init__(message)
        self.report = report


def quantize_params(params: dict) -> dict:
    """f32 policy params -> the int8 serving pytree.

    Each layer becomes ``{"w_q": int8 (k,k,cin,cout), "w_scale": f32
    (cout,), "b": f32 (19,19,cout)}``. Symmetric per-output-channel
    with POWER-OF-TWO scales: ``w_scale = 2^ceil(log2(max|w| / 127))``
    over the channel's taps (1.0 for an all-zero channel), ``w_q =
    round(w / w_scale)``. The po2 constraint costs at most one bit of
    codebook resolution, and buys an exact identity: multiplying by a
    power of two is a pure exponent shift, so the epilogue dequant
    commutes BITWISE through the f32 conv accumulation and the bf16
    downcast — the int8 forward is numerically equivalent to running
    the reference forward over the dequantized weights ``w_scale*w_q``
    (which are themselves bf16-exact: 7-bit integers times a po2).
    Tolerance therefore measures weight rounding alone, with zero
    compute-path noise, and weights already on the grid round-trip
    bit-identically (tests assert ``==``). Biases are kept in f32 —
    361 values per channel, nothing on the weight-movement bill.

    Pure jnp, so ``jax.eval_shape`` can derive the quantized avals for
    the AOT cost ledger without touching real weights."""
    layers = []
    for layer in params["layers"]:
        w = layer["w"].astype(jnp.float32)
        amax = jnp.max(jnp.abs(w), axis=(0, 1, 2))
        scale = jnp.where(
            amax > 0,
            jnp.exp2(jnp.ceil(jnp.log2(amax / QUANT_MAX))), 1.0)
        w_q = jnp.clip(jnp.round(w / scale), -QUANT_MAX, QUANT_MAX)
        layers.append({"w_q": w_q.astype(jnp.int8),
                       "w_scale": scale.astype(jnp.float32),
                       "b": layer["b"]})
    return {"layers": layers}


def dequantize_params(qparams: dict) -> dict:
    """The f32 pytree the int8 one rounds to (tests; error bounds)."""
    return {"layers": [
        {"w": layer["w_q"].astype(jnp.float32) * layer["w_scale"],
         "b": layer["b"]}
        for layer in qparams["layers"]]}


def quant_apply(qparams: dict, planes: jax.Array,
                cfg: policy_cnn.ModelConfig) -> jax.Array:
    """planes (B, 19, 19, 37) -> logits (B, 361) over int8 weights.

    Mirrors ``policy_cnn.apply`` exactly except for the weight path:
    int8 kernels upcast to the compute dtype at the conv input (integer
    values <= 127 are exact in bf16), the conv accumulates in f32
    (``preferred_element_type`` — the MXU's native low-precision-in,
    f32-accumulate shape), and the per-output-channel dequant scale is
    folded into the epilogue before the downcast + bias add. Because
    the scales are powers of two (see ``quantize_params``), the
    epilogue multiply is an exact exponent shift: every value here is
    bit-identical to what the REFERENCE forward computes over the
    dequantized weights, so quantization error is the ONLY numerics
    difference vs f32 serving. Row-independent like the f32 forward,
    so bucket padding stays bit-exact per row."""
    dtype = jnp.dtype(cfg.compute_dtype)
    x = planes.astype(dtype)
    n_layers = len(qparams["layers"])

    for i, layer in enumerate(qparams["layers"]):
        y = jax.lax.conv_general_dilated(
            x,
            layer["w_q"].astype(dtype),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.float32,
        )
        # the dequant epilogue: an exact po2 exponent shift per output
        # channel, fused by XLA with the downcast/bias/ReLU it already
        # emits here; the downcast + bf16 bias add mirror the reference
        # layer's epilogue bit for bit
        y = (y * layer["w_scale"][None, None, None, :]).astype(dtype)
        y = y + layer["b"].astype(dtype)[None]
        x = jax.nn.relu(y) if (i < n_layers - 1 or cfg.final_relu) else y
    return x.reshape(x.shape[0], NUM_POINTS).astype(jnp.float32)


def make_quant_log_prob_fn(cfg: policy_cnn.ModelConfig,
                           expand_backend: str = "xla"):
    """predict(qparams, packed, player, rank) -> (B, 361) log-probs —
    the int8 twin of ``serving.make_log_prob_fn``, same engine-facing
    signature, so it rides the bucket ladder / engine / fleet stack
    unchanged (the params argument is simply the quantized pytree)."""
    expand_planes = get_expand_fn(expand_backend)

    @jax.jit
    def log_probs(qparams, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        return jax.nn.log_softmax(quant_apply(qparams, planes, cfg), axis=-1)

    return log_probs


def make_fused_sym_policy_fn(cfg: policy_cnn.ModelConfig,
                             quant: bool = False,
                             expand_backend: str = "xla",
                             symmetries: int | None = None):
    """predict(params, packed, player, rank) -> (B, 361) log-probs
    averaged over the dihedral group, in ONE jitted program.

    Replaces ``make_sym_policy_fn`` as the serving-side ensemble: the
    eight views are stacked on the batch axis (gather by the precomputed
    permutation tables), expanded, pushed through one conv-stack
    invocation, mapped back with the inverse tables, and averaged as a
    proper mixture via log-sum-exp — ``log((1/S) Σ_k p_k)`` computed in
    log space, so no probabilities are materialized and the output is
    finite wherever any view is. ``quant=True`` runs the stack over int8
    weights (the ``int8+sym`` variant; params is then the quantized
    pytree). ``symmetries=1`` degrades to the identity view alone — the
    plumbing check: its output is bit-identical to the plain forward
    (tests assert ``==``). ``expand_backend="pallas"`` fuses the view
    gather INTO the plane expansion via the Pallas kernel in
    ``ops/pallas_expand.py`` when the backend can compile Mosaic
    kernels, and falls back to the XLA path otherwise.

    FLOPs are still S x the plain forward (the AOT ledger's
    ``fused_sym_entry`` says so honestly); what fusion buys is the
    serving economics: one request occupies ONE bucket slot and one
    dispatch instead of eight engine round-trips, so the measured
    per-request cost at serving rungs amortizes to a small multiple of
    a single forward (the bench A/B measures it) while top-1 keeps the
    ensemble's +0.7 gain."""
    from ..ops.augment import _PERM_NP, _TARGET_MAP_NP, NUM_SYMMETRIES

    s = NUM_SYMMETRIES if symmetries is None else int(symmetries)
    if not 1 <= s <= NUM_SYMMETRIES:
        raise ValueError(f"symmetries must be in [1, {NUM_SYMMETRIES}], "
                         f"got {symmetries!r}")
    use_pallas = False
    if expand_backend == "pallas":
        from ..ops.pallas_expand import pallas_supported

        # the fused gather+expand kernel when Mosaic can compile here;
        # the XLA path (identical values) everywhere else
        use_pallas = pallas_supported()
        expand_backend = "xla"
    expand_planes = get_expand_fn(expand_backend)
    apply_fn = quant_apply if quant else policy_cnn.apply
    # hoisted to factory scope (constant-upload discipline): uploaded
    # once, not re-baked from host memory on every trace
    perm = jnp.asarray(_PERM_NP[:s])          # (S, 361) gather tables
    tmap = jnp.asarray(_TARGET_MAP_NP[:s])    # (S, 361) inverse tables

    @jax.jit
    def predict(params, packed, player, rank):
        b, ch = packed.shape[0], packed.shape[1]
        rep = lambda v: jnp.tile(v, s)  # noqa: E731
        if use_pallas:
            from ..ops.pallas_expand import expand_planes_sym_pallas

            planes = expand_planes_sym_pallas(
                packed, player, rank, symmetries=s,
                dtype=jnp.dtype(cfg.compute_dtype))
        else:
            flat = packed.reshape(b, ch, NUM_POINTS)
            views = flat[:, :, perm]              # (B, C, S, 361)
            views = views.transpose(2, 0, 1, 3).reshape(
                s * b, ch, *packed.shape[2:])
            planes = expand_planes(views, rep(player), rep(rank),
                                   dtype=jnp.dtype(cfg.compute_dtype))
        logits = apply_fn(params, planes, cfg)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        logp = logp.reshape(s, b, NUM_POINTS)
        # view k's distribution mapped back: orig point p sits at
        # tmap[k, p]; then the mixture average in log space
        back = jnp.take_along_axis(logp, tmap[:, None, :], axis=2)
        return jax.nn.logsumexp(back, axis=0) - jnp.log(float(s))

    return predict


# ---------------------------------------------------------------------------
# the tolerance harness


@dataclasses.dataclass(frozen=True)
class ToleranceConfig:
    """The floors a lossy variant must clear on EVERY rung before it may
    serve: top-1 agreement vs the exact reference forward (the move the
    policy would actually play), and max-abs log-prob drift over the
    probability mass that matters (points the reference puts at least
    ``prob_floor`` on — drift in the log of a ~0 probability is noise
    amplification, not a serving risk). ``boards`` bounds harness cost;
    rungs larger than it are sampled at ``boards`` rows."""

    top1_floor: float = 0.99
    drift_cap: float = 0.5
    prob_floor: float = 1e-3
    boards: int = 256
    seed: int = 0


def _random_boards(rng: np.random.Generator, n: int):
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


def tolerance_report(reference, ref_params, variant_forward, var_params,
                     buckets=(1, 8, 32, 128, 512),
                     config: ToleranceConfig | None = None,
                     variant: str = "int8", registry=None,
                     sample=None) -> dict:
    """Measure a lossy variant against its exact reference, per rung.

    ``reference`` / ``variant_forward`` are engine-facing forwards of
    the SAME program shape (plain int8 vs plain f32; fused-sym int8 vs
    fused-sym f32 — comparing an ensemble against a non-ensemble would
    gate the ensemble's intended prediction changes, not the
    quantization error). Every rung dispatches at ITS jitted shape and
    accumulates at least ``config.boards`` measured boards (small rungs
    loop; a 1% agreement floor is meaningless over 8 boards), so the
    per-rung percentage carries real statistical weight.

    ``sample(n) -> (packed, player, rank)`` supplies the measurement
    boards. Default is uniform random stones — a deliberately hostile
    out-of-distribution probe. Production gating should pass real
    positions (e.g. ``GoDataset`` rows): a trained net is DECISIVE
    on-distribution, and an argmax flip there is a real strength risk,
    while on noise boards the net is legitimately undecided and a flip
    between two ~equal moves is tie-breaking, not damage
    (docs/serving.md "Serving variants").

    Returns the per-rung table plus an overall ``verdict``
    ("pass"/"fail"), and publishes
    ``deepgo_quant_top1_agreement{variant,bucket}`` /
    ``deepgo_quant_logprob_drift{variant,bucket}`` gauges so a live
    fleet's tolerance standing is scrapeable next to its throughput."""
    cfg = config or ToleranceConfig()
    rng = np.random.default_rng(cfg.seed)
    if sample is None:
        sample = lambda n: _random_boards(rng, n)  # noqa: E731
    if registry is None:
        from ..obs import get_registry

        registry = get_registry()
    g_top1 = registry.gauge(
        "deepgo_quant_top1_agreement",
        "variant-vs-reference top-1 move agreement per ladder rung")
    g_drift = registry.gauge(
        "deepgo_quant_logprob_drift",
        "variant-vs-reference max-abs log-prob drift over "
        "above-floor probability mass, per ladder rung")
    rungs = {}
    worst_top1, worst_drift = 1.0, 0.0
    for b in sorted({int(x) for x in buckets}):
        agree = total = 0
        drift = 0.0
        while total < cfg.boards:
            n = min(b, cfg.boards - total)
            packed, player, rank = sample(n)
            if n < b:  # pad to the rung so the jitted shape is the rung's
                pad = b - n
                packed = np.concatenate(
                    [packed, np.zeros((pad, 9, 19, 19), np.uint8)])
                player = np.concatenate([player, np.ones(pad, np.int32)])
                rank = np.concatenate([rank, np.ones(pad, np.int32)])
            ref = np.asarray(reference(ref_params, packed, player,
                                       rank))[:n]
            var = np.asarray(variant_forward(var_params, packed, player,
                                             rank))[:n]
            agree += int(np.sum(ref.argmax(-1) == var.argmax(-1)))
            total += n
            mass = np.exp(ref) >= cfg.prob_floor
            drift = max(drift, float(
                np.max(np.where(mass, np.abs(var - ref), 0.0))))
        top1 = agree / total
        rungs[b] = {"boards": total, "top1_agreement": round(top1, 4),
                    "max_abs_logprob_drift": round(drift, 5),
                    "ok": top1 >= cfg.top1_floor and drift <= cfg.drift_cap}
        g_top1.set(top1, variant=variant, bucket=b)
        g_drift.set(drift, variant=variant, bucket=b)
        worst_top1 = min(worst_top1, top1)
        worst_drift = max(worst_drift, drift)
    ok = all(r["ok"] for r in rungs.values())
    return {
        "variant": variant,
        "verdict": "pass" if ok else "fail",
        "top1_floor": cfg.top1_floor,
        "drift_cap": cfg.drift_cap,
        "worst_top1": round(worst_top1, 4),
        "worst_drift": round(worst_drift, 5),
        "rungs": {str(b): r for b, r in sorted(rungs.items())},
    }


def check_tolerance(reference, ref_params, variant_forward, var_params,
                    buckets=(1, 8, 32, 128, 512),
                    config: ToleranceConfig | None = None,
                    variant: str = "int8", registry=None,
                    sample=None) -> dict:
    """``tolerance_report`` that REFUSES: a failing report raises a
    typed :class:`VariantToleranceError` carrying the full measurement —
    the gate serving/variants.py runs before a lossy variant may serve.
    Returns the passing report otherwise."""
    report = tolerance_report(reference, ref_params, variant_forward,
                              var_params, buckets=buckets, config=config,
                              variant=variant, registry=registry,
                              sample=sample)
    if report["verdict"] != "pass":
        bad = {b: r for b, r in report["rungs"].items() if not r["ok"]}
        raise VariantToleranceError(
            f"variant {variant!r} refused to serve: tolerance floors "
            f"(top1 >= {report['top1_floor']}, drift <= "
            f"{report['drift_cap']}) failed on rung(s) {sorted(bad)} "
            f"(worst top1 {report['worst_top1']}, worst drift "
            f"{report['worst_drift']})", report)
    return report
