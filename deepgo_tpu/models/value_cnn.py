"""Convolutional value network: position -> P(side to move wins).

The reference (and arXiv:1412.6564) is policy-only; this head is the
framework's step toward value-guided search, motivated by the round-4
expert-iteration finding that a constant tactical wrapper saturates the
self-improvement loop after one distillation round (RESULTS.md) — the
next expert up needs an evaluation whose quality grows with training,
i.e. a learned value function (the direction the paper's successors
took: AlphaGo's value network, Silver et al. 2016).

Architecture: the same SAME-padded conv trunk as the policy net (5x5
then 3x3 convs, per-position biases, ReLU, bf16 on the MXU), then a
1x1 conv to one channel, a 64-unit dense layer over the 361 board
values, and a scalar logit. Input is the identical 37-plane encoding
(`ops/expand`), so the host pipeline, wire formats, and summarizer are
shared with the policy path unchanged.

Functional design mirrors policy_cnn: ``init`` -> params pytree,
``apply(params, planes) -> (B,) logits``, jit/grad-compatible. Labels
come from the winner sidecar (`tools/winner_index.py`): z=1 when the
side to move won the game the position came from.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .. import BOARD_SIZE, NUM_POINTS
from ..features import NUM_PLANES


@dataclass(frozen=True)
class ValueConfig:
    """``num_layers`` counts the trunk convolutions (all hidden; the head's
    1x1 conv is separate, unlike policy_cnn where the final conv IS the
    output)."""

    num_layers: int = 3
    channels: int = 64
    first_kernel: int = 5
    kernel: int = 3
    input_planes: int = NUM_PLANES
    head_hidden: int = 64
    compute_dtype: str = "bfloat16"

    def layer_shapes(self):
        shapes = []
        c_in = self.input_planes
        for i in range(self.num_layers):
            k = self.first_kernel if i == 0 else self.kernel
            shapes.append((k, c_in, self.channels))
            c_in = self.channels
        return shapes


def init(rng: jax.Array, cfg: ValueConfig) -> dict:
    """He-normal conv/dense weights, zero biases (policy_cnn.init style)."""
    params = {"layers": []}
    for k, c_in, c_out in cfg.layer_shapes():
        rng, wkey = jax.random.split(rng)
        w = jax.random.normal(wkey, (k, k, c_in, c_out), jnp.float32)
        w = w * np.sqrt(2.0 / (k * k * c_in))
        b = jnp.zeros((BOARD_SIZE, BOARD_SIZE, c_out), jnp.float32)
        params["layers"].append({"w": w, "b": b})
    rng, k1, k2, k3 = jax.random.split(rng, 4)
    params["head_conv"] = {
        "w": jax.random.normal(k1, (1, 1, cfg.channels, 1), jnp.float32)
        * np.sqrt(2.0 / cfg.channels),
        "b": jnp.zeros((BOARD_SIZE, BOARD_SIZE, 1), jnp.float32),
    }
    params["dense1"] = {
        "w": jax.random.normal(k2, (NUM_POINTS, cfg.head_hidden), jnp.float32)
        * np.sqrt(2.0 / NUM_POINTS),
        "b": jnp.zeros((cfg.head_hidden,), jnp.float32),
    }
    params["dense2"] = {
        "w": jax.random.normal(k3, (cfg.head_hidden, 1), jnp.float32)
        * np.sqrt(2.0 / cfg.head_hidden),
        "b": jnp.zeros((1,), jnp.float32),
    }
    return params


def apply(params: dict, planes: jax.Array, cfg: ValueConfig) -> jax.Array:
    """planes: (B, 19, 19, 37) -> win-probability logits (B,)."""
    dtype = jnp.dtype(cfg.compute_dtype)
    x = planes.astype(dtype)
    for layer in params["layers"]:
        x = jax.lax.conv_general_dilated(
            x, layer["w"].astype(dtype), window_strides=(1, 1),
            padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + layer["b"].astype(dtype)[None])
    hc = params["head_conv"]
    x = jax.lax.conv_general_dilated(
        x, hc["w"].astype(dtype), window_strides=(1, 1),
        padding="SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    x = jax.nn.relu(x + hc["b"].astype(dtype)[None])
    x = x.reshape(x.shape[0], NUM_POINTS)
    d1 = params["dense1"]
    x = jax.nn.relu(x @ d1["w"].astype(dtype) + d1["b"].astype(dtype))
    d2 = params["dense2"]
    logit = x @ d2["w"].astype(dtype) + d2["b"].astype(dtype)
    return logit[:, 0].astype(jnp.float32)
