"""Convolutional policy network for Go move prediction.

The reference architecture (getBasicModel, reference experiments.lua:133-153):
``num_layers`` SAME-padded convolutions — 5x5 on the 37 input planes first,
then 3x3 — each followed by a *per-position, per-channel* bias (the
Reshape/Add/Reshape sandwich at experiments.lua:143-145) and ReLU; the last
convolution emits 1 channel whose 361 values feed a log-softmax.

Functional JAX design: ``init`` builds a params pytree, ``apply`` is a pure
function of (params, planes) -> logits, jit/vmap/grad-compatible. Compute
runs in bfloat16 (MXU-native) with float32 parameters; the loss upcasts.

One deliberate deviation, off by default: the reference applies ReLU to the
final 1-channel conv as well (its layer loop is uniform), clamping logits to
be non-negative before the softmax. ``final_relu=True`` reproduces that;
the default skips it, which is both the paper's architecture
(arXiv:1412.6564) and strictly more expressive.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from .. import BOARD_SIZE, NUM_POINTS
from ..features import NUM_PLANES


@dataclass(frozen=True)
class ModelConfig:
    """num_layers counts every convolution including the final 1-channel one,
    matching the reference's numLayers (experiments.lua:39,88-94).

    ``channels`` is either one width for every hidden conv or a per-layer
    tuple of num_layers - 1 widths — the reference's per-layer channel list
    (its layer expansion appends the final 1-channel conv to the config's
    ``channels`` table, experiments.lua:88-93)."""

    num_layers: int = 3
    channels: int | tuple[int, ...] = 64
    first_kernel: int = 5
    kernel: int = 3
    input_planes: int = NUM_PLANES
    final_relu: bool = False  # True = bit-parity with the reference head
    compute_dtype: str = "bfloat16"
    # rematerialize per-layer activations in backward (jax.checkpoint):
    # trades ~1 extra forward for O(1-layer) activation memory — needed to
    # train the "large" config at big batch sizes within one chip's HBM
    remat: bool = False

    def hidden_channels(self) -> tuple[int, ...]:
        """Per-hidden-layer output widths (everything but the final conv)."""
        if isinstance(self.channels, int):
            return (self.channels,) * (self.num_layers - 1)
        if len(self.channels) != self.num_layers - 1:
            raise ValueError(
                f"channels tuple has {len(self.channels)} entries; "
                f"num_layers={self.num_layers} needs {self.num_layers - 1}"
            )
        return tuple(self.channels)

    def layer_shapes(self):
        """[(kernel, c_in, c_out)] for each conv layer."""
        widths = self.hidden_channels() + (1,)
        shapes = []
        c_in = self.input_planes
        for i, c_out in enumerate(widths):
            k = self.first_kernel if i == 0 else self.kernel
            shapes.append((k, c_in, c_out))
            c_in = c_out
        return shapes


# Named flagship configurations (BASELINE.md benchmark configs).
CONFIGS = {
    "small": ModelConfig(num_layers=3, channels=64),
    "medium": ModelConfig(num_layers=6, channels=64),
    "full": ModelConfig(num_layers=12, channels=128),  # Maddison et al. scale
    "large": ModelConfig(num_layers=13, channels=256),  # AlphaGo SL-policy scale
}


def init(rng: jax.Array, cfg: ModelConfig) -> dict:
    """He-normal conv weights, zero per-position biases.

    (The reference uses Torch's uniform 1/sqrt(fan-in) init; He init is the
    modern equivalent for ReLU stacks and trains strictly better.)
    """
    params = {"layers": []}
    for k, c_in, c_out in cfg.layer_shapes():
        rng, wkey = jax.random.split(rng)
        fan_in = k * k * c_in
        w = jax.random.normal(wkey, (k, k, c_in, c_out), jnp.float32)
        w = w * np.sqrt(2.0 / fan_in)
        b = jnp.zeros((BOARD_SIZE, BOARD_SIZE, c_out), jnp.float32)
        params["layers"].append({"w": w, "b": b})
    return params


def apply(params: dict, planes: jax.Array, cfg: ModelConfig) -> jax.Array:
    """planes: (B, 19, 19, 37) -> logits (B, 361).

    Every conv is SAME-padded so the board never shrinks (the reference
    zero-pads explicitly, experiments.lua:137). Softmax/NLL live in the loss
    (training) or the serving wrapper, not here.
    """
    dtype = jnp.dtype(cfg.compute_dtype)
    x = planes.astype(dtype)
    n_layers = len(params["layers"])

    def conv_layer(x, layer, relu):
        x = jax.lax.conv_general_dilated(
            x,
            layer["w"].astype(dtype),
            window_strides=(1, 1),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        x = x + layer["b"].astype(dtype)[None]
        return jax.nn.relu(x) if relu else x

    if cfg.remat:
        conv_layer = jax.checkpoint(conv_layer, static_argnums=(2,))
    for i, layer in enumerate(params["layers"]):
        x = conv_layer(x, layer, i < n_layers - 1 or cfg.final_relu)
    return x.reshape(x.shape[0], NUM_POINTS).astype(jnp.float32)


def log_policy(params: dict, planes: jax.Array, cfg: ModelConfig) -> jax.Array:
    """Log-probabilities over the 361 board points (the reference model's
    actual output, experiments.lua:150-151)."""
    return jax.nn.log_softmax(apply(params, planes, cfg), axis=-1)


def num_params(params: dict) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
