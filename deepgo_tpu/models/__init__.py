"""Model zoo: policy CNNs for 19x19 move prediction."""

from .policy_cnn import ModelConfig, apply, init, num_params  # noqa: F401
