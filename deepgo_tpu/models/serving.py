"""Batched policy inference (the serving path).

The reference has no separate serving stack — batched ``model:forward`` over
board tensors IS inference (SURVEY.md section 3.4). This module packages
that capability properly: a jitted predict function from packed records to
move probabilities and ranked moves, loadable straight from a checkpoint.

Production callers should not hit these forwards shape-by-shape: the
``deepgo_tpu.serving`` package wraps them in a shape-bucketed
micro-batching engine (compile-once ladder, coalesced dispatch, metrics)
— see docs/serving.md. ``make_log_prob_fn`` below is the engine-facing
raw forward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops import get_expand_fn
from . import policy_cnn


def make_policy_fn(cfg: policy_cnn.ModelConfig, top_k: int = 5,
                   expand_backend: str = "xla"):
    """predict(params, packed, player, rank) ->
    {"log_probs": (B, 361), "top_moves": (B, k), "top_probs": (B, k)}.

    Moves are flat 0-based indices (19*x + y), matching the training target.
    """
    expand_planes = get_expand_fn(expand_backend)

    @functools.partial(jax.jit, static_argnums=())
    def predict(params, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        logp = policy_cnn.log_policy(params, planes, cfg)
        top_probs, top_moves = jax.lax.top_k(jnp.exp(logp), top_k)
        return {"log_probs": logp, "top_moves": top_moves,
                "top_probs": top_probs}

    return predict


def make_log_prob_fn(cfg: policy_cnn.ModelConfig, expand_backend: str = "xla"):
    """predict(params, packed, player, rank) -> (B, 361) log-probs.

    The raw row-independent forward the serving engine batches
    (deepgo_tpu.serving): identical math to ``make_policy_fn`` without
    the top-k ranking, which is host work the engine's consumers do (or
    skip) themselves. Row independence is what makes bucket padding
    bit-exact, so this function must never grow a cross-batch term.
    """
    expand_planes = get_expand_fn(expand_backend)

    @jax.jit
    def log_probs(params, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        return policy_cnn.log_policy(params, planes, cfg)

    return log_probs


def make_sym_policy_fn(cfg: policy_cnn.ModelConfig,
                       expand_backend: str = "xla"):
    """predict(params, packed, player, rank) -> (B, 361) log-probs averaged
    over the 8 dihedral board symmetries.

    Go is invariant under the dihedral group and the training data is
    augmented with it (ops/augment.py — the transform the reference stubbed
    at dataloader.lua:41-44), but a finite net is only approximately
    equivariant; ensembling the 8 views averages that error out. Each view
    is transformed with the precomputed permutation table, pushed through
    one 8B-board forward, softmaxed, mapped back to original coordinates
    with the inverse table, and the PROBABILITIES are averaged (averaging
    distributions, not logits, keeps the ensemble a proper mixture). The
    averaged predictor is exactly equivariant by construction, which the
    unit test asserts. Costs 8x FLOPs per board — measured against its
    accuracy delta by tools/symmetry_eval.py.
    """
    from ..ops.augment import _PERM_NP, _TARGET_MAP_NP, NUM_SYMMETRIES
    from .. import NUM_POINTS

    expand_planes = get_expand_fn(expand_backend)
    # hoisted out of the jitted body (constant-upload): uploaded once at
    # factory time instead of re-baked from host memory on every trace
    perm = jnp.asarray(_PERM_NP)          # (8, 361) gather tables
    tmap = jnp.asarray(_TARGET_MAP_NP)    # (8, 361) inverse tables

    @jax.jit
    def predict(params, packed, player, rank):
        b, ch = packed.shape[0], packed.shape[1]
        flat = packed.reshape(b, ch, NUM_POINTS)
        views = flat[:, :, perm]              # (B, C, 8, 361)
        views = views.transpose(2, 0, 1, 3).reshape(
            NUM_SYMMETRIES * b, ch, *packed.shape[2:])
        rep = lambda v: jnp.tile(v, NUM_SYMMETRIES)  # noqa: E731
        planes = expand_planes(views, rep(player), rep(rank),
                               dtype=jnp.dtype(cfg.compute_dtype))
        logits = policy_cnn.apply(params, planes, cfg)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        probs = probs.reshape(NUM_SYMMETRIES, b, NUM_POINTS)
        # map view k's distribution back: orig point p sits at tmap[k, p]
        back = jnp.take_along_axis(probs, tmap[:, None, :], axis=2)
        return jnp.log(back.mean(axis=0) + 1e-30)

    return predict


def load_policy(checkpoint_path: str, top_k: int = 5):
    """(predict_fn, params, model_cfg) from a training checkpoint."""
    from ..experiments import ExperimentConfig
    from ..experiments import checkpoint as ckpt

    meta, p_leaves, _ = ckpt.load_checkpoint(checkpoint_path)
    config = ExperimentConfig.from_dict(meta["config"])
    cfg = config.model_config()
    template = policy_cnn.init(jax.random.key(0), cfg)
    params = ckpt.unflatten_like(
        template, [jnp.asarray(x) for x in p_leaves], checkpoint_path)
    return make_policy_fn(cfg, top_k=top_k), params, cfg


def make_value_fn(cfg):
    """win_prob(params, packed, player, rank) -> (B,) P(side to move wins),
    the value-net serving twin of make_policy_fn."""
    from . import value_cnn

    expand_planes = get_expand_fn("xla")

    @jax.jit
    def win_prob(params, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        return jax.nn.sigmoid(value_cnn.apply(params, planes, cfg))

    return win_prob


def load_value(checkpoint_path: str):
    """(win_prob_fn, params, value_cfg) from a tools/train_value.py
    checkpoint (kind="value")."""
    from ..experiments import checkpoint as ckpt
    from . import value_cnn

    meta, p_leaves, _ = ckpt.load_checkpoint(checkpoint_path)
    assert meta.get("kind") == "value", (
        f"{checkpoint_path} is not a value checkpoint: {meta.get('kind')!r}")
    cfg = value_cnn.ValueConfig(**meta["config"])
    template = value_cnn.init(jax.random.key(0), cfg)
    params = ckpt.unflatten_like(
        template, [jnp.asarray(x) for x in p_leaves], checkpoint_path)
    return make_value_fn(cfg), params, cfg
