"""Batched policy inference (the serving path).

The reference has no separate serving stack — batched ``model:forward`` over
board tensors IS inference (SURVEY.md section 3.4). This module packages
that capability properly: a jitted predict function from packed records to
move probabilities and ranked moves, loadable straight from a checkpoint.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..ops import get_expand_fn
from . import policy_cnn


def make_policy_fn(cfg: policy_cnn.ModelConfig, top_k: int = 5,
                   expand_backend: str = "xla"):
    """predict(params, packed, player, rank) ->
    {"log_probs": (B, 361), "top_moves": (B, k), "top_probs": (B, k)}.

    Moves are flat 0-based indices (19*x + y), matching the training target.
    """
    expand_planes = get_expand_fn(expand_backend)

    @functools.partial(jax.jit, static_argnums=())
    def predict(params, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        logp = policy_cnn.log_policy(params, planes, cfg)
        top_probs, top_moves = jax.lax.top_k(jnp.exp(logp), top_k)
        return {"log_probs": logp, "top_moves": top_moves,
                "top_probs": top_probs}

    return predict


def load_policy(checkpoint_path: str, top_k: int = 5):
    """(predict_fn, params, model_cfg) from a training checkpoint."""
    from ..experiments import ExperimentConfig
    from ..experiments import checkpoint as ckpt

    meta, p_leaves, _ = ckpt.load_checkpoint(checkpoint_path)
    config = ExperimentConfig.from_dict(meta["config"])
    cfg = config.model_config()
    template = policy_cnn.init(jax.random.key(0), cfg)
    params = ckpt.unflatten_like(template, [jnp.asarray(x) for x in p_leaves])
    return make_policy_fn(cfg, top_k=top_k), params, cfg


def make_value_fn(cfg):
    """win_prob(params, packed, player, rank) -> (B,) P(side to move wins),
    the value-net serving twin of make_policy_fn."""
    from . import value_cnn

    expand_planes = get_expand_fn("xla")

    @jax.jit
    def win_prob(params, packed, player, rank):
        planes = expand_planes(packed, player, rank,
                               dtype=jnp.dtype(cfg.compute_dtype))
        return jax.nn.sigmoid(value_cnn.apply(params, planes, cfg))

    return win_prob


def load_value(checkpoint_path: str):
    """(win_prob_fn, params, value_cfg) from a tools/train_value.py
    checkpoint (kind="value")."""
    from ..experiments import checkpoint as ckpt
    from . import value_cnn

    meta, p_leaves, _ = ckpt.load_checkpoint(checkpoint_path)
    assert meta.get("kind") == "value", (
        f"{checkpoint_path} is not a value checkpoint: {meta.get('kind')!r}")
    cfg = value_cnn.ValueConfig(**meta["config"])
    template = value_cnn.init(jax.random.key(0), cfg)
    params = ckpt.unflatten_like(template, [jnp.asarray(x) for x in p_leaves])
    return make_value_fn(cfg), params, cfg
