"""Experiment management: config, runs, checkpoints, sweeps, plotting."""

from .experiment import Experiment, ExperimentConfig  # noqa: F401
