"""Checkpoint save/resume in a self-describing single-file format.

Same semantics as the reference — one artifact holding config, weights,
optimizer state, iteration count, and validation history, auto-saved at
every validation boundary and loadable to continue training (reference
experiments.lua:57-72,124-131, train.lua:124) — but JAX-native: a .npz of
the flattened params/optimizer pytrees plus a JSON metadata entry. No torch
serialization anywhere (SURVEY.md section 2.2 explicitly forbids
reimplementing it).

Pytrees are stored as ordered flat leaves (params_000, params_001, ...,
opt_000, ...) and rebuilt by unflattening into a template generated from the
stored config, which keeps the format independent of private treedef
serialization details.
"""

from __future__ import annotations

import json

import jax
import numpy as np

FORMAT_VERSION = 1


def save_checkpoint(path: str, params, opt_state, meta: dict) -> None:
    arrays = {}
    p_leaves = jax.tree.leaves(params)
    o_leaves = jax.tree.leaves(opt_state)
    for i, leaf in enumerate(p_leaves):
        arrays[f"params_{i:04d}"] = np.asarray(leaf)
    for i, leaf in enumerate(o_leaves):
        arrays[f"opt_{i:04d}"] = np.asarray(leaf)
    arrays["meta"] = np.frombuffer(
        json.dumps({"format_version": FORMAT_VERSION, **meta}).encode(), dtype=np.uint8
    )
    with open(path, "wb") as f:
        np.savez(f, **arrays)


def load_checkpoint(path: str):
    """Returns (meta dict, params_leaves list, opt_leaves list)."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
        p_keys = sorted(k for k in z.files if k.startswith("params_"))
        o_keys = sorted(k for k in z.files if k.startswith("opt_"))
        params_leaves = [z[k] for k in p_keys]
        opt_leaves = [z[k] for k in o_keys]
    assert meta.get("format_version") == FORMAT_VERSION, meta.get("format_version")
    return meta, params_leaves, opt_leaves


def load_meta(path: str) -> dict:
    """Read only the metadata entry (config, step, validation_history) —
    npz members load lazily, so this skips the weight arrays entirely.
    Lets tools plot or inspect runs straight from a checkpoint (reference
    plot.lua:5-29 plots from .model files the same way)."""
    with np.load(path) as z:
        meta = json.loads(bytes(z["meta"]).decode())
    assert meta.get("format_version") == FORMAT_VERSION, meta.get("format_version")
    return meta


def unflatten_like(template, leaves):
    """Rebuild a pytree with ``template``'s structure from flat ``leaves``."""
    treedef = jax.tree.structure(template)
    assert treedef.num_leaves == len(leaves), (
        f"checkpoint has {len(leaves)} leaves, template needs {treedef.num_leaves}"
    )
    t_leaves = jax.tree.leaves(template)
    for i, (a, b) in enumerate(zip(t_leaves, leaves)):
        assert tuple(a.shape) == tuple(b.shape), (
            f"leaf {i}: checkpoint shape {b.shape} != template {a.shape}"
        )
    return jax.tree.unflatten(treedef, leaves)
