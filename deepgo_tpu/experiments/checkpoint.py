"""Crash-safe checkpointing in a self-describing single-file format.

Same semantics as the reference — one artifact holding config, weights,
optimizer state, iteration count, and validation history, auto-saved at
every validation boundary and loadable to continue training (reference
experiments.lua:57-72,124-131, train.lua:124) — but JAX-native: a .npz of
the flattened params/optimizer pytrees plus a JSON metadata entry. No torch
serialization anywhere (SURVEY.md section 2.2 explicitly forbids
reimplementing it).

Pytrees are stored as ordered flat leaves (params_000, params_001, ...,
opt_000, ...) and rebuilt by unflattening into a template generated from the
stored config, which keeps the format independent of private treedef
serialization details.

Format v2 adds the crash-safety layer (docs/robustness.md):

  * every write is atomic (temp file + fsync + os.replace via
    utils.atomicio), so a preemption mid-save can never tear the only
    recovery artifact;
  * the JSON meta carries an ``integrity`` block — a CRC32 per stored
    array plus a SHA-256 digest over all array payloads — verified on
    load, so bit rot and torn copies are detected instead of silently
    training from garbage;
  * run directories hold rolling ``checkpoint-{step:08d}.npz`` files and
    ``find_latest_valid`` picks the newest one that passes verification,
    skipping corrupt candidates with a logged reason (elastic
    auto-resume).

Metas may additionally carry a ``mesh`` manifest (parallel/reshard.py):
the dp×tp grid that wrote the file plus per-leaf partition specs.
``validate_manifest`` structurally refuses a corrupt one on the verify
path — the integrity block covers array payloads, not the meta member.

v1 files (no integrity block) still load; they just can't be verified.
All validation failures raise :class:`CheckpointError` (never ``assert``,
which vanishes under ``python -O``) carrying the offending path.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
import zipfile
import zlib

import jax
import numpy as np

from ..utils import faults
from ..utils.atomicio import atomic_write

FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)

_CKPT_RE = re.compile(r"^checkpoint-(\d+)\.npz$")


class CheckpointError(RuntimeError):
    """A checkpoint that cannot be trusted: missing, truncated, corrupt,
    from an unknown format, or shaped for a different model. Carries the
    path and a reason; ``find_latest_valid`` treats it as "skip this file",
    direct loads surface it to the caller."""

    def __init__(self, path: str, reason: str):
        self.path = path
        self.reason = reason
        super().__init__(f"checkpoint {path}: {reason}")


def checkpoint_name(step: int) -> str:
    """Rolling per-step artifact name; zero-padded so lexicographic and
    numeric order agree for any run shorter than 10^8 steps."""
    return f"checkpoint-{step:08d}.npz"


# ---- integrity ----


def _leaf_bytes(arr: np.ndarray) -> bytes:
    return np.ascontiguousarray(arr).tobytes()


def _integrity(arrays: dict) -> dict:
    """Per-array CRC32s plus a whole-checkpoint SHA-256 over every array
    payload (sorted key order), stored in the JSON meta. The zip layer has
    its own member CRCs, but those only protect the compressed container —
    this block survives format migrations and catches e.g. a truncated
    copy of an uncompressed member."""
    crcs = {}
    digest = hashlib.sha256()
    for key in sorted(arrays):
        data = _leaf_bytes(arrays[key])
        crcs[key] = zlib.crc32(data)
        digest.update(key.encode())
        digest.update(str(arrays[key].dtype).encode())
        digest.update(repr(tuple(arrays[key].shape)).encode())
        digest.update(data)
    return {"arrays": crcs, "digest": digest.hexdigest()}


def _verify_integrity(path: str, meta: dict, arrays: dict) -> None:
    if meta.get("format_version", 1) < 2:
        return  # v1 predates the integrity block: loadable, unverifiable
    integ = meta.get("integrity")
    if not isinstance(integ, dict) or "arrays" not in integ:
        raise CheckpointError(
            path, "format v2 without an integrity block in meta "
                  "(truncated meta, or written by a broken tool)")
    expected = integ["arrays"]
    if set(expected) != set(arrays):
        missing = sorted(set(expected) - set(arrays))
        extra = sorted(set(arrays) - set(expected))
        raise CheckpointError(
            path, f"array set mismatch vs meta (missing {missing}, "
                  f"unexpected {extra}) — partial or spliced file")
    digest = hashlib.sha256()
    for key in sorted(arrays):
        data = _leaf_bytes(arrays[key])
        if zlib.crc32(data) != expected[key]:
            raise CheckpointError(
                path, f"CRC32 mismatch for array {key!r} — bit corruption; "
                      f"delete this file (auto-resume skips it automatically)")
        digest.update(key.encode())
        digest.update(str(arrays[key].dtype).encode())
        digest.update(repr(tuple(arrays[key].shape)).encode())
        digest.update(data)
    if digest.hexdigest() != integ.get("digest"):
        raise CheckpointError(
            path, "whole-file digest mismatch — bit corruption; delete this "
                  "file (auto-resume skips it automatically)")


def validate_manifest(manifest, path: str, *, n_params: int | None = None,
                      n_opt: int | None = None) -> None:
    """Structural validation of the ``mesh`` manifest (parallel/reshard.py)
    carried in v2+ metas. The integrity block covers array payloads, not
    the meta member itself, so a corrupt manifest must be refused here —
    as a :class:`CheckpointError`, which makes ``find_latest_valid`` skip
    the file exactly like bit rot in a weight array."""
    if not isinstance(manifest, dict):
        raise CheckpointError(
            path, f"mesh manifest is {type(manifest).__name__}, not a dict "
                  f"— corrupt meta")
    for key in ("data", "model", "devices"):
        val = manifest.get(key)
        if not isinstance(val, int) or isinstance(val, bool) or val < 1:
            raise CheckpointError(
                path, f"mesh manifest {key}={val!r} is not a positive int "
                      f"— corrupt meta")
    if manifest["data"] * manifest["model"] != manifest["devices"]:
        raise CheckpointError(
            path, f"mesh manifest inconsistent: data={manifest['data']} × "
                  f"model={manifest['model']} != devices="
                  f"{manifest['devices']}")
    for key, want in (("params", n_params), ("opt_state", n_opt)):
        specs = manifest.get(key)
        if (not isinstance(specs, list)
                or not all(isinstance(s, str) for s in specs)):
            raise CheckpointError(
                path, f"mesh manifest {key} specs are not a list of "
                      f"partition-spec strings — corrupt meta")
        if want is not None and len(specs) != want:
            raise CheckpointError(
                path, f"mesh manifest lists {len(specs)} {key} specs but "
                      f"the checkpoint stores {want} arrays — spliced or "
                      f"corrupt meta")


# ---- save / load ----


def save_checkpoint(path: str, params, opt_state, meta: dict) -> None:
    arrays = {}
    p_leaves = jax.tree.leaves(params)
    o_leaves = jax.tree.leaves(opt_state)
    for i, leaf in enumerate(p_leaves):
        arrays[f"params_{i:04d}"] = np.asarray(leaf)
    for i, leaf in enumerate(o_leaves):
        arrays[f"opt_{i:04d}"] = np.asarray(leaf)
    meta_json = json.dumps({
        "format_version": FORMAT_VERSION,
        "integrity": _integrity(arrays),
        **meta,
    })
    arrays["meta"] = np.frombuffer(meta_json.encode(), dtype=np.uint8)
    # atomic: a crash (or injected ckpt_write fault) anywhere in here leaves
    # the previous checkpoint intact and at most a stray .tmp that
    # find_latest_valid never considers
    with atomic_write(path) as f:
        faults.check("ckpt_write")
        np.savez(f, **arrays)


def _open_npz(path: str):
    try:
        size = os.path.getsize(path)
    except OSError as e:
        raise CheckpointError(path, f"unreadable: {e}") from e
    if size == 0:
        raise CheckpointError(
            path, "zero-length file — crash before any bytes were written")
    try:
        return np.load(path, allow_pickle=False)
    except (zipfile.BadZipFile, ValueError, OSError, EOFError) as e:
        raise CheckpointError(
            path, f"not a readable npz ({e}) — truncated or corrupt") from e


def _read_meta(z, path: str) -> dict:
    if "meta" not in z.files:
        raise CheckpointError(
            path, "no meta entry — not a deepgo checkpoint, or the write "
                  "was torn before the meta member landed")
    try:
        meta = json.loads(bytes(_read_member(z, "meta", path)).decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointError(path, f"meta entry is not valid JSON: {e}") from e
    version = meta.get("format_version")
    if version not in SUPPORTED_VERSIONS:
        raise CheckpointError(
            path, f"format_version {version!r} not in supported "
                  f"{SUPPORTED_VERSIONS} — written by an incompatible "
                  f"deepgo_tpu; re-save or upgrade")
    return meta


def _read_member(z, key: str, path: str) -> np.ndarray:
    """npz members decompress lazily; a flipped byte or truncated tail
    surfaces here as a zip/zlib error, not at np.load time."""
    try:
        return z[key]
    except (zipfile.BadZipFile, zlib.error, ValueError, OSError, EOFError) as e:
        raise CheckpointError(
            path, f"array {key!r} unreadable ({e}) — truncated or corrupt") from e


def load_checkpoint(path: str, verify: bool = True):
    """Returns (meta dict, params_leaves list, opt_leaves list).

    ``verify=True`` (the default) checks every array against the meta's
    CRC32s and the whole-file digest; pass False only when re-reading a
    file already verified this process."""
    with _open_npz(path) as z:
        meta = _read_meta(z, path)
        p_keys = sorted(k for k in z.files if k.startswith("params_"))
        o_keys = sorted(k for k in z.files if k.startswith("opt_"))
        arrays = {k: _read_member(z, k, path) for k in (*p_keys, *o_keys)}
    if verify:
        _verify_integrity(path, meta, arrays)
        if "mesh" in meta:  # pre-reshard checkpoints have no manifest
            validate_manifest(meta["mesh"], path,
                              n_params=len(p_keys), n_opt=len(o_keys))
    return meta, [arrays[k] for k in p_keys], [arrays[k] for k in o_keys]


def load_meta(path: str) -> dict:
    """Read only the metadata entry (config, step, validation_history) —
    npz members load lazily, so this skips the weight arrays entirely.
    Lets tools plot or inspect runs straight from a checkpoint (reference
    plot.lua:5-29 plots from .model files the same way)."""
    with _open_npz(path) as z:
        return _read_meta(z, path)


def verify_checkpoint(path: str) -> dict:
    """Full integrity pass (structure, meta, per-array CRCs, digest).
    Returns the meta on success, raises CheckpointError otherwise."""
    meta, _, _ = load_checkpoint(path, verify=True)
    return meta


def unflatten_like(template, leaves, path: str = "<checkpoint>"):
    """Rebuild a pytree with ``template``'s structure from flat ``leaves``."""
    treedef = jax.tree.structure(template)
    if treedef.num_leaves != len(leaves):
        raise CheckpointError(
            path, f"has {len(leaves)} leaves, template needs "
                  f"{treedef.num_leaves} — checkpoint config and model "
                  f"architecture disagree")
    t_leaves = jax.tree.leaves(template)
    for i, (a, b) in enumerate(zip(t_leaves, leaves)):
        if tuple(a.shape) != tuple(b.shape):
            raise CheckpointError(
                path, f"leaf {i}: checkpoint shape {tuple(b.shape)} != "
                      f"template {tuple(a.shape)} — checkpoint config and "
                      f"model architecture disagree")
    return jax.tree.unflatten(treedef, leaves)


# ---- run-directory scanning (elastic auto-resume) ----


def list_checkpoints(run_dir: str) -> list[tuple[int, str]]:
    """(step, path) for every rolling checkpoint in ``run_dir``, ascending
    by step. Temp files, the legacy single ``checkpoint.npz``, and the
    convenience alias are not included."""
    try:
        names = os.listdir(run_dir)
    except (FileNotFoundError, NotADirectoryError):
        return []
    out = []
    for name in names:
        m = _CKPT_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(run_dir, name)))
    out.sort()
    return out


def find_latest_valid(run_dir: str, log=None) -> str | None:
    """Newest checkpoint in ``run_dir`` that passes full verification.

    Scans rolling ``checkpoint-{step:08d}.npz`` files newest-first, then a
    legacy plain ``checkpoint.npz`` (unless it's just the alias symlink to
    a rolling file already scanned). Truncated / corrupt / partial
    candidates are skipped with a logged reason rather than aborting the
    resume — the whole point is surviving a kill that landed mid-write.
    Returns None when nothing valid exists (callers start fresh)."""
    if log is None:
        def log(msg):
            print(msg, file=sys.stderr, flush=True)
    candidates = [p for _, p in reversed(list_checkpoints(run_dir))]
    legacy = os.path.join(run_dir, "checkpoint.npz")
    if os.path.lexists(legacy) and not os.path.islink(legacy):
        candidates.append(legacy)
    for path in candidates:
        try:
            verify_checkpoint(path)
            return path
        except CheckpointError as e:
            log(f"auto-resume: skipping {e.path}: {e.reason}")
    return None
