"""Warm-restart sweep: continue a trained checkpoint under a fresh run id
with a fresh optimizer.

Parity with the reference's sweep entry (experiments/repeated.lua:6-22):
load a checkpoint, keep weights/step/validation history, re-identify the
run, reset the optimizer to a fresh state at the configured base rate, and
train on. ``--num`` replicates the reference's ``-num`` seed-variant flag by
offsetting the sampling seed.

Usage:
  python -m deepgo_tpu.experiments.repeated --checkpoint runs/<id>/checkpoint.npz \
      --iters 20000 [--num K] [--set rate=0.05 ...]
"""

from __future__ import annotations

import argparse
import uuid

import jax

from ..cli import parse_overrides
from ..parallel import replicated_sharding
from .experiment import Experiment, ExperimentConfig
from . import checkpoint as ckpt


def warm_restart(path: str, overrides: dict, num: int = 0) -> Experiment:
    meta, p_leaves, o_leaves = ckpt.load_checkpoint(path)
    config = ExperimentConfig.from_dict(meta["config"])
    if num:
        overrides = {**overrides, "seed": config.seed + num}
    if overrides:
        config = config.replace(**overrides)
    exp = Experiment(config, run_id=uuid.uuid4().hex[:8])  # fresh identity
    exp.step = meta["step"]
    exp.validation_history = list(meta["validation_history"])
    exp.init()  # fresh optimizer state: reference repeated.lua:17
    exp.params = jax.device_put(
        ckpt.unflatten_like(exp.params, p_leaves, path),
        replicated_sharding(exp.mesh),
    )
    return exp


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--iters", type=int, required=True)
    ap.add_argument("--num", type=int, default=0, help="sweep variant number")
    ap.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    args = ap.parse_args(argv)

    from ..utils import honor_platform_env

    honor_platform_env()

    exp = warm_restart(args.checkpoint, parse_overrides(args.set), args.num)
    print(f"warm restart {exp.id} from {args.checkpoint} at step {exp.step}")
    exp.run(args.iters)
    print(f"saved {exp.save()}")


if __name__ == "__main__":
    main()
