"""The experiment layer: config -> initialized run -> train/validate/resume.

Capability parity with the reference's Experiment prototype
(experiments.lua:8-131) and train loop (train.lua:47-142):

  * config with defaults + per-run overrides, serialized into checkpoints
    (self-describing runs)
  * random run id + git-sha provenance
  * EWMA(0.95/0.05) training cost, samples/sec prints, JSONL metrics
  * periodic validation with NLL + top-1 accuracy, checkpoint-on-validate
  * load-and-continue resume; warm restart lives in
    deepgo_tpu.experiments.repeated

Deliberate improvements over the reference, all noted inline: exactly one
fwd+bwd per step (the reference runs two, train.lua:106-111), a fixed
deterministic validation set (the reference samples a random one per run,
train.lua:62-67), and device feeding via an async double-buffered loader.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import sys
import time
import uuid
from dataclasses import dataclass

import jax
import numpy as np

from ..data.dataset import GoDataset
from ..data.loader import AsyncLoader
from ..models import policy_cnn
from ..obs import JsonlSink, get_registry, span, trace_to
from ..parallel import data_sharding, make_mesh, replicated_sharding
from ..parallel import reshard
from ..training import make_eval_step, make_train_step, make_train_step_many
from ..training.optimizers import OPTIMIZERS
from ..utils import MetricsWriter, append_registry, git_sha
from ..utils import faults
from ..utils.atomicio import atomic_write
from ..utils.retry import retry_with_backoff
from . import checkpoint as ckpt


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "basic"
    # model (reference basicGoExperiment defaults, experiments.lua:33-46)
    num_layers: int = 3
    channels: int = 64
    # per-layer widths, e.g. "128,128,64" (len = num_layers - 1); overrides
    # ``channels`` when set (the reference's per-layer channel list,
    # experiments.lua:88-93)
    channel_schedule: str = ""
    first_kernel: int = 5
    kernel: int = 3
    final_relu: bool = False
    compute_dtype: str = "bfloat16"
    # rematerialize activations in backward (ModelConfig.remat): the
    # HBM-vs-FLOPs trade for the 13L/256 config at large batch
    remat: bool = False
    # optimization
    batch_size: int = 32
    rate: float = 0.01
    rate_decay: float = 1e-7
    optimizer: str = "sgd"
    momentum: float = 0.0
    # validation (reference Experiment defaults, experiments.lua:8-17)
    validation_size: int = 2000
    validation_interval: int = 2000
    print_interval: int = 10
    # steps fused into one device dispatch via lax.scan (0 = match
    # print_interval). Through the TPU relay each dispatch is a host
    # round-trip, so chaining K steps per call lifts small-model training
    # throughput by ~K at no semantic cost (losses come back per step and
    # the EWMA is folded identically).
    steps_per_call: int = 0
    # data
    augment: bool = False  # dihedral board symmetries (reference's stub)
    data_root: str = "data/processed"
    train_split: str = "train"
    validation_split: str = "validation"
    test_split: str = "test"
    scheme: str = "game"
    loader_threads: int = 2
    prefetch: int = 4
    # host->device transfer encoding for packed records: "nibble" ships two
    # cells per byte (half the bytes; lossless for the expanded planes —
    # see deepgo_tpu.ops.wire), "packed" ships raw records. "auto" =
    # nibble on accelerators (the feed is transfer-bound through the
    # relay), packed on CPU (no transfer to save; the pack/unpack would
    # be pure overhead)
    wire_format: str = "auto"
    # (super)batches the loader's uploader thread keeps device_put ahead of
    # the train loop (0 = transfer inline in get()); hides relay-tunnel
    # transfer latency behind device compute
    device_prefetch: int = 2
    # KL-anchored fine-tuning: keep the policy near a frozen reference
    # checkpoint while training on a narrow corpus (the regularizer for
    # the expert-iteration distribution collapse, RESULTS.md). weight 0
    # disables; the anchor may be any architecture.
    anchor_checkpoint: str = ""
    anchor_weight: float = 0.0
    # parallelism (mesh axes; reference analogue: numGPUs, experiments.lua:10)
    data_parallel: int = 0  # 0 = all available devices
    tensor_parallel: int = 1
    # ZeRO-1 optimizer-state sharding over "data" (parallel/zero.py,
    # arXiv:2004.13336), composed with the tp placement — on by default:
    # placement-only, bitwise-neutral, and survives re-meshes through the
    # reshard layer (parallel/reshard.py)
    zero_opt: bool = True
    expand_backend: str = "xla"  # "xla" | "pallas" | "auto"
    # identity / observability
    seed: int = 0
    run_dir: str = "runs"
    profile: bool = False  # capture a jax.profiler trace of train() into the run dir
    # AOT device cost ledger (obs/costmodel.py): price this run's train
    # step (XLA FLOPs / bytes / HBM) at train start, so the attribution
    # report carries MFU. One extra XLA compile before the loop — zero
    # per-step cost; identical programs are memoized process-wide
    cost_ledger: bool = True
    # robustness (docs/robustness.md): rolling retention keeps the newest
    # N checkpoint-{step}.npz files plus the best-validation one (0 = keep
    # everything); ``faults`` installs a fault-injection plan in the
    # DEEPGO_FAULTS grammar (the env var wins when both are set — and note
    # a config-driven kill re-arms on resume, since the config rides in
    # the checkpoint; prefer the env var for kill testing)
    keep_checkpoints: int = 3
    faults: str = ""
    # elastic multi-host run (parallel/elastic.py): threads the
    # dist_collective fault site through the step dispatch boundary and
    # marks the checkpoint as belonging to an elastic fleet; the
    # per-launch liveness knobs (heartbeat interval, miss budget, ...)
    # live in ElasticConfig, not here — they must not ride in checkpoints
    elastic: bool = False

    def model_config(self) -> policy_cnn.ModelConfig:
        channels = self.channels
        if self.channel_schedule:
            channels = tuple(
                int(c) for c in self.channel_schedule.split(",") if c.strip()
            )
        return policy_cnn.ModelConfig(
            num_layers=self.num_layers,
            channels=channels,
            first_kernel=self.first_kernel,
            kernel=self.kernel,
            final_relu=self.final_relu,
            compute_dtype=self.compute_dtype,
            remat=self.remat,
        )

    def replace(self, **overrides) -> "ExperimentConfig":
        return dataclasses.replace(self, **overrides)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ExperimentConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


class Experiment:
    def __init__(self, config: ExperimentConfig, run_id: str | None = None):
        self.config = config
        self.id = run_id or uuid.uuid4().hex[:8]
        self.step = 0
        self.validation_history: list[dict] = []
        # EWMA training cost rides in checkpoints so a resumed run's loss
        # curve continues bit-exactly instead of re-warming from scratch
        self.ewma: float | None = None
        self.last_loss: float = float("nan")
        self.initialized = False
        self.params = None
        self.opt_state = None
        # sharding-claim findings from the most recent resharding restore
        # (Experiment.load); [] for a fresh run — the elastic recovery
        # record reports this count so a silent replicated-instead-of-
        # sharded restore is visible in the run's JSONL
        self.last_restore_findings: list = []
        # optional window hook for the elastic layer: called at every
        # print-window boundary (AFTER metrics/validation/checkpointing)
        # with (step, window_seconds, window_steps); an exception raised
        # here — e.g. a typed HostLost from the heartbeat ledger —
        # propagates out of train() with the loader cleanly closed
        self.on_window = None

    # ---- setup ----

    def init(self) -> None:
        cfg = self.config
        if cfg.faults and not os.environ.get("DEEPGO_FAULTS"):
            faults.install(cfg.faults)
        n_devices = len(jax.devices())
        dp = cfg.data_parallel or max(1, n_devices // cfg.tensor_parallel)
        if cfg.batch_size % dp != 0:
            # config validation must survive `python -O` (same contract
            # as the anchor check below)
            raise ValueError(
                f"batch_size {cfg.batch_size} must divide over {dp} "
                "data-parallel devices")
        self.mesh = make_mesh(dp, cfg.tensor_parallel)
        self.wire = cfg.wire_format
        if self.wire == "auto":
            self.wire = ("nibble" if jax.default_backend() != "cpu"
                         else "packed")
        if self.wire not in ("nibble", "packed"):
            raise ValueError(f"wire_format must be auto|nibble|packed, "
                             f"got {cfg.wire_format!r}")
        self.model_cfg = cfg.model_config()
        opt_fn = OPTIMIZERS[cfg.optimizer]
        if cfg.optimizer == "sgd":
            self.optimizer = opt_fn(cfg.rate, cfg.rate_decay, cfg.momentum)
        else:
            self.optimizer = opt_fn(cfg.rate)
        if self.params is None:
            self.params = policy_cnn.init(jax.random.key(cfg.seed), self.model_cfg)
        # composed dp×tp×ZeRO placement (parallel/reshard.py): params are
        # placed FIRST, then the optimizer state is created from the
        # *placed* params — zeros_like inherits the "model" placement, so
        # zero_sharding merges "data" in on top of it instead of fighting it
        self.params, self.opt_state = reshard.place_state(
            self.params, self.opt_state, self.mesh,
            tensor_parallel=cfg.tensor_parallel, zero_opt=cfg.zero_opt)
        if self.opt_state is None:
            _, self.opt_state = reshard.place_state(
                self.params, self.optimizer.init(self.params), self.mesh,
                tensor_parallel=cfg.tensor_parallel, zero_opt=cfg.zero_opt)
        rep = replicated_sharding(self.mesh)
        anchor = None
        if bool(cfg.anchor_checkpoint) != (cfg.anchor_weight > 0):
            # config validation must survive `python -O`, so no assert: a
            # set anchor_checkpoint with weight 0 would otherwise be
            # silently ignored
            raise ValueError(
                "anchor_checkpoint and anchor_weight > 0 go together: "
                f"got checkpoint={cfg.anchor_checkpoint!r} "
                f"weight={cfg.anchor_weight}")
        if cfg.anchor_weight > 0:
            from ..models.serving import load_policy

            _, a_params, a_cfg = load_policy(cfg.anchor_checkpoint)
            anchor = (jax.device_put(a_params, rep), a_cfg,
                      cfg.anchor_weight)
        # elastic fleets get the dist_collective fault site at the step
        # dispatch boundary (chaos reach into the multi-host layer)
        collective_site = "dist_collective" if cfg.elastic else None
        self.train_step = make_train_step(self.model_cfg, self.optimizer,
                                          expand_backend=cfg.expand_backend,
                                          augment=cfg.augment, anchor=anchor,
                                          wire=self.wire,
                                          collective_site=collective_site)
        # the train loop drives this scan-based variant: K steps per device
        # dispatch (see ExperimentConfig.steps_per_call)
        self.train_step_many = make_train_step_many(
            self.model_cfg, self.optimizer,
            expand_backend=cfg.expand_backend, augment=cfg.augment,
            anchor=anchor, wire=self.wire,
            collective_site=collective_site)
        self.eval_step = make_eval_step(self.model_cfg,
                                        expand_backend=cfg.expand_backend,
                                        wire=self.wire)
        self.batch_sharding = data_sharding(self.mesh)
        self.run_path = os.path.join(self.config.run_dir, self.id)
        os.makedirs(self.run_path, exist_ok=True)
        self.initialized = True

    def _dataset(self, split: str) -> GoDataset:
        return GoDataset(self.config.data_root, split)

    # ---- training ----

    def run(self, iters: int) -> dict:
        """Train for ``iters`` steps; returns the run summary record
        (reference Experiment:run, experiments.lua:110-122)."""
        if iters <= 0:
            raise ValueError(f"iters must be positive, got {iters}")
        if not self.initialized:
            self.init()
        cfg = self.config
        start = time.time()
        summary = self.train(iters)
        summary.update(
            id=self.id,
            name=cfg.name,
            iters=iters,
            total_step=self.step,
            runtime=time.time() - start,
            git_sha=git_sha(),
            config=cfg.to_dict(),
        )
        append_registry(os.path.join(cfg.run_dir, "registry.jsonl"), summary)
        return summary

    def train(self, iters: int) -> dict:
        from ..utils.profiling import trace

        cfg = self.config
        # one metrics stream + one span trace stream per run, both opened
        # here so the profiler wrapper can log its output dir into the
        # metrics (trace discoverability) and spans stream for exactly
        # the duration of the run (obs/spans.trace_to restores the
        # previous sink even when training raises)
        metrics = MetricsWriter(os.path.join(self.run_path, "metrics.jsonl"))
        trace_sink = JsonlSink(os.path.join(self.run_path, "trace.jsonl"))
        try:
            with trace_to(trace_sink), trace(
                    os.path.join(self.run_path, "trace")
                    if cfg.profile else None, metrics=metrics):
                return self._train(iters, metrics)
        finally:
            trace_sink.close()
            metrics.close()

    def _steps_per_call(self) -> int:
        """Resolved scan depth K: print windows must be whole numbers of
        calls so prints/validations land exactly on their boundaries, so K
        is the largest divisor of print_interval <= steps_per_call.

        The auto setting (0) resolves to print_interval on accelerators —
        dispatch amortization is the point there — but to 1 on CPU, where
        XLA's compile time for a scanned conv training step is pathological
        (measured: 2s for the single step vs 309s for a K=10 scan at 3L/64)
        and dispatch latency is negligible anyway. An explicit
        steps_per_call is honored on any backend.
        """
        cfg = self.config
        want = cfg.steps_per_call
        if want == 0:
            want = (cfg.print_interval
                    if jax.default_backend() != "cpu" else 1)
        k = max(d for d in range(1, cfg.print_interval + 1)
                if cfg.print_interval % d == 0 and d <= want)
        if k != want:
            print(f"steps_per_call={want} does not divide "
                  f"print_interval={cfg.print_interval}; using {k}")
        return k

    def _train(self, iters: int, metrics: MetricsWriter) -> dict:
        from ..parallel import superbatch_sharding

        cfg = self.config
        train_set = self._dataset(cfg.train_split)
        # registry aggregates over the same events the JSONL stream
        # records: counters scrape live on /metrics between print
        # windows, the window histogram feeds `cli obs`'s step-time row.
        # Metric objects are bound once here — the loop pays inc/set/
        # observe only (docs/observability.md; overhead budget <= 2%).
        reg = get_registry()
        obs_steps = reg.counter(
            "deepgo_train_steps_total", "optimizer steps completed")
        obs_samples = reg.counter(
            "deepgo_train_samples_total", "training samples consumed")
        obs_window = reg.histogram(
            "deepgo_train_window_seconds",
            "wall time of one print window")
        obs_ewma = reg.gauge(
            "deepgo_train_loss_ewma", "EWMA(0.95/0.05) training cost")
        obs_sps = reg.gauge(
            "deepgo_train_samples_per_sec",
            "samples/sec over the last print window")
        # attribution instrumentation (obs/attribution.py): together with
        # the loader-wait histogram and the validate/checkpoint spans,
        # these decompose the loop's wall-clock into named buckets —
        # phase=first isolates trace+compile from steady-state dispatch
        obs_dispatch = reg.histogram(
            "deepgo_train_dispatch_seconds",
            "host-blocking time inside the jitted step call "
            "(phase=first carries trace+compile)")
        obs_fetch = reg.histogram(
            "deepgo_train_fetch_seconds",
            "host time blocked fetching window losses (the device fence "
            "— a lower bound on un-overlapped device compute)")
        obs_hook = reg.histogram(
            "deepgo_train_hook_seconds",
            "window-hook time (heartbeat write + liveness checks)")
        obs_wall = reg.counter(
            "deepgo_train_wall_seconds_total",
            "train-loop wall time: the attribution denominator")
        # the crash flight recorder dumps into the run directory (kills,
        # restarts, SLO fast burns); honor an earlier configuration (the
        # elastic loop arms it with the shared run dir before train runs)
        from ..obs.sentinel import configure_flight, get_flight_recorder

        flight = get_flight_recorder()
        if not flight.enabled:
            flight = configure_flight(self.run_path)
        if cfg.cost_ledger:
            # price THIS run's step program ahead of time: the ledger
            # gauges ride the close-time obs_snapshot, so the offline
            # attribution join (cli obs) reports MFU without ever seeing
            # this machine. AOT-only — the loop below never touches it.
            from ..obs import costmodel

            try:
                ledger = costmodel.CostLedger(registry=reg, sink=metrics)
                costmodel.train_entry(
                    ledger, self.model_cfg, cfg.batch_size,
                    optimizer=self.optimizer, wire=self.wire,
                    augment=cfg.augment)
                costmodel.set_cost_ledger(ledger)
            except Exception as e:  # noqa: BLE001 — observability never
                # blocks training; a backend that cannot even lower the
                # step still trains, just without an MFU row
                print(f"cost ledger: skipped ({e!r})", flush=True)
        dispatched_programs: set = set()  # phase=first vs phase=steady
        # validation data: fixed and game-balanced (improves on the
        # reference's one random minibatch per run, train.lua:62-67)
        val_batches = self._validation_batches()

        k_steps = self._steps_per_call()
        step_many = self.train_step_many
        # K=1 must NOT go through the scan program: XLA's CPU compile of a
        # scanned conv train step is pathological even at K=1 (measured 64s
        # vs 3.4s unscanned, 3L/64 batch 256), and a 1-step scan buys no
        # dispatch amortization anywhere
        use_scan = k_steps > 1
        if use_scan and jax.default_backend() == "cpu":
            # the fused-scan program is the TPU dispatch-amortization win;
            # XLA CPU executes scanned convs ~100x slower than the same
            # convs dispatched singly (measured 3 vs 390 samples/sec,
            # 3L/64 batch 256) — flag it rather than silently crawling
            print(f"warning: steps_per_call={k_steps} on the CPU backend "
                  "runs the scanned train step, which XLA CPU executes "
                  "~100x slower than steps_per_call=1", flush=True)
        # a resume picks the EWMA up from the checkpoint, so the folded
        # sequence of loss updates is identical to an uninterrupted run's
        ewma = self.ewma
        last_loss = self.last_loss
        last_val: dict = {}
        pending: list = []  # device-resident per-call loss vectors

        def fold_pending(ewma, last_loss):
            # EWMA 0.95/0.05, matching the reference (train.lua:115). One
            # host fetch per call, at window boundaries only. The fetch
            # blocks on every dispatched step completing — it IS the
            # window's device fence, so its duration feeds the compute
            # bucket of the attribution table.
            t0 = time.monotonic()
            for losses in pending:
                # lint: allow[hot-sync] window-boundary fetch IS the declared materialization point (one d2h per window)
                for value in np.atleast_1d(np.asarray(losses)).tolist():
                    ewma = value if ewma is None else 0.95 * ewma + 0.05 * value
                    last_loss = value
            if pending:
                obs_fetch.observe(time.monotonic() - t0)
            pending.clear()
            self.ewma, self.last_loss = ewma, last_loss
            return ewma, last_loss

        def timed_step(step_fn, program, batch):
            # host-blocking dispatch time, compile isolated on the first
            # call per program. The rebind of params/opt_state happens
            # INSIDE the timer on purpose: dropping the previous buffers
            # is where backends that execute synchronously actually block
            # (measured on CPU: the call returns in ~0.3 ms, the dealloc
            # of the in-flight inputs waits ~8 ms for the step), so the
            # dispatch bucket honestly carries un-overlapped execution
            phase = "steady" if program in dispatched_programs else "first"
            t0 = time.monotonic()
            try:
                self.params, self.opt_state, losses = step_fn(
                    self.params, self.opt_state, batch)
                return losses
            finally:
                dispatched_programs.add(program)
                obs_dispatch.observe(time.monotonic() - t0, phase=phase)
        window_t0 = total_t0 = time.time()
        with AsyncLoader(
            train_set,
            cfg.batch_size,
            scheme=cfg.scheme,
            # sync mode: the stream is a pure function of (seed, step), so
            # a resume replays the uninterrupted run bit-exactly; threaded
            # mode continues the stream statistically (loader.py docstring)
            seed=cfg.seed,
            start_step=self.step,
            num_threads=cfg.loader_threads,
            prefetch=cfg.prefetch,
            sharding=self.batch_sharding,
            stack=k_steps if use_scan else 0,
            stack_sharding=superbatch_sharding(self.mesh),
            augment=cfg.augment,
            wire=self.wire,
            device_prefetch=cfg.device_prefetch,
        ) as loader, contextlib.ExitStack() as _wall:
            # the attribution denominator must be credited however this
            # scope exits — a HostLost or injected fault mid-loop still
            # spent the wall-clock the histograms accumulated against
            _wall.callback(lambda: obs_wall.inc(time.time() - total_t0))
            remaining = iters
            window_steps = 0
            while remaining > 0:
                # realign to print-window boundaries first: a resume can
                # start at a step that is not a multiple of print_interval,
                # and advancing by k_steps from there would never land on
                # one (no prints, no validation, no periodic checkpoints)
                align = (-self.step) % cfg.print_interval
                k = min(k_steps, remaining, align or k_steps)

                def dump_bad(batch):
                    # postmortem capture: stash the failing batch for offline
                    # debugging (reference train.lua:106-109 kept it in
                    # globals; a file survives the process). Full-window
                    # superbatches carry the leading (K, B) step dimension.
                    # Atomic so a crash while dumping can't tear an earlier
                    # capture — the postmortem artifact deserves the same
                    # guarantee as the checkpoint.
                    # lint: allow[hot-sync] crash-path postmortem dump — the step already failed, there is no pipeline left to stall
                    bad = {k_: np.asarray(v) for k_, v in batch.items()}
                    with atomic_write(
                        os.path.join(self.run_path, "bad_batch.npz")
                    ) as f:
                        np.savez(f, **bad)

                if k == k_steps and use_scan:
                    batch = loader.get()
                    try:
                        faults.check("train_step")
                        losses = timed_step(step_many, "many", batch)
                    except Exception:
                        dump_bad(batch)
                        raise
                    pending.append(losses)
                    self.step += k
                    remaining -= k
                    window_steps += k
                    obs_steps.inc(k)
                    obs_samples.inc(k * cfg.batch_size)
                    faults.check("kill", step=self.step)
                else:
                    # alignment / tail remainders run through the
                    # single-step program (already compiled) instead of
                    # paying a throwaway XLA compile of a k-step scan;
                    # per-step accounting keeps self.step consistent with
                    # self.params if a mid-tail step fails
                    for _ in range(k):
                        batch = loader.get(stack=0)
                        try:
                            faults.check("train_step")
                            loss = timed_step(self.train_step, "single",
                                              batch)
                        except Exception:
                            dump_bad(batch)
                            raise
                        pending.append(loss)
                        self.step += 1
                        remaining -= 1
                        window_steps += 1
                        obs_steps.inc(1)
                        obs_samples.inc(cfg.batch_size)
                        faults.check("kill", step=self.step)
                # losses stay on device between prints so calls dispatch
                # asynchronously; fetching every call would serialize the
                # loop on the host<->device round-trip
                if self.step % cfg.print_interval == 0:
                    ewma, last_loss = fold_pending(ewma, last_loss)
                    window_dt = time.time() - window_t0
                    window_t0 = time.time()
                    sps = window_steps * cfg.batch_size / window_dt
                    done_steps = window_steps
                    window_steps = 0
                    metrics.write("train", step=self.step, loss=last_loss,
                                  ewma=ewma, samples_per_sec=sps)
                    obs_window.observe(window_dt)
                    obs_ewma.set(ewma)
                    obs_sps.set(sps)
                    if self.step % cfg.validation_interval == 0:
                        with span("validate", step=self.step):
                            last_val = self.validate(val_batches)
                        metrics.write("validation", step=self.step, **last_val)
                        with span("checkpoint_save", step=self.step):
                            self._save_periodic()
                        print(f"validation at iteration {self.step}: "
                              f"cost={last_val['cost']:.4f}, "
                              f"accuracy={last_val['accuracy']:.4f}")
                    else:
                        print(f"training {ewma:.4f} (samples per second {sps:.0f})")
                    # flight-recorder heartbeat: one registry snapshot per
                    # print window keeps the ring current at no hot-path
                    # cost (a no-op while the recorder is unarmed)
                    flight.tick()
                    # elastic hook LAST, after the periodic checkpoint: a
                    # HostLost raised here finds the newest checkpoint
                    # already on disk for the fleet to converge on
                    if self.on_window is not None:
                        with obs_hook.time():
                            self.on_window(self.step, window_dt, done_steps)

            # fold losses from a final partial print window into the EWMA
            # so runs shorter than print_interval still report one (inside
            # the wall-accounted scope: the fold is a device fence)
            ewma, last_loss = fold_pending(ewma, last_loss)
        total_dt = time.time() - total_t0
        total_sps = cfg.batch_size * iters / total_dt
        print(f"total samples per second {total_sps:.0f}")
        metrics.write("summary", step=self.step, ewma=ewma,
                      total_samples_per_sec=total_sps)
        # close-time registry state rides in the event stream so the
        # offline report (cli obs) gets the hot-path histograms —
        # loader wait, window times — without scraping a live process
        metrics.write("obs_snapshot", metrics=reg.snapshot()["metrics"])
        return {
            "final_ewma": ewma,
            "samples_per_sec": total_sps,
            "last_validation": last_val,
        }

    # ---- validation / evaluation ----

    def _validation_batches(self) -> list[dict]:
        cfg = self.config
        try:
            val_set = self._dataset(cfg.validation_split)
        except FileNotFoundError:
            return []
        n = min(cfg.validation_size, len(val_set))
        return self._deterministic_batches(val_set, n)

    def _deterministic_batches(self, dataset: GoDataset, n: int) -> list[dict]:
        """Fixed, game-balanced sample of a split, padded to whole batches
        with a mask (GoDataset.even_indices; covers min(num_games, n) games
        instead of round 1's first-files prefix)."""
        cfg = self.config
        packed, player, rank, target = dataset.even_n(n)
        if self.wire == "nibble":
            from ..ops.wire import nibble_pack_np

            packed = nibble_pack_np(packed)
        batches = []
        bs = cfg.batch_size
        for i in range(0, n, bs):
            chunk = slice(i, min(i + bs, n))
            size = chunk.stop - chunk.start
            pad = bs - size
            batch = {
                # rank-agnostic pad: raw records are (n, 9, 19, 19), the
                # nibble wire is (n, 1625)
                "packed": np.pad(packed[chunk],
                                 ((0, pad),) + ((0, 0),) * (packed.ndim - 1)),
                "player": np.pad(player[chunk], (0, pad), constant_values=1),
                "rank": np.pad(rank[chunk], (0, pad), constant_values=1),
                "target": np.pad(target[chunk], (0, pad)),
                "mask": np.pad(np.ones(size, np.float32), (0, pad)),
            }
            batches.append(jax.device_put(batch, self.batch_sharding))
        return batches

    def validate(self, val_batches: list[dict] | None = None,
                 record_history: bool = True) -> dict:
        """Mean NLL + top-1 accuracy over the fixed validation set
        (reference eval_validation, train.lua:14-45). ``record_history``
        appends to validation_history (what checkpoints persist); one-off
        evaluations pass False."""
        if val_batches is None:
            if not self.initialized:
                self.init()
            val_batches = self._validation_batches()
        if not val_batches:
            return {"cost": float("nan"), "accuracy": float("nan"), "n": 0}
        total_nll = total_correct = total_n = 0.0
        for batch in val_batches:
            sum_nll, correct = self.eval_step(self.params, batch)
            total_nll += float(sum_nll)
            total_correct += float(correct)
            total_n += float(np.sum(np.asarray(batch["mask"])))
        record = {
            "cost": total_nll / total_n,
            "accuracy": total_correct / total_n,
            "n": int(total_n),
        }
        if record_history:
            self.validation_history.append({"step": self.step, **record})
        return record

    def evaluate(self, split: str | None = None, limit: int | None = None) -> dict:
        """Deterministic full-split evaluation (the reference has no fixed
        test evaluation; SURVEY.md section 7.9 calls for one)."""
        if not self.initialized:
            self.init()
        dataset = self._dataset(split or self.config.test_split)
        n = len(dataset) if limit is None else min(limit, len(dataset))
        batches = self._deterministic_batches(dataset, n)
        return self.validate(batches, record_history=False)

    # ---- checkpointing ----

    def save(self, path: str | None = None) -> str:
        """Write one atomic, integrity-checked checkpoint.

        With no explicit ``path`` the run directory gets a rolling
        ``checkpoint-{step:08d}.npz``, the ``checkpoint.npz`` convenience
        alias is refreshed, and retention prunes old files (keep-last-N
        plus the best-validation step, ``config.keep_checkpoints``)."""
        managed = path is None
        path = path or os.path.join(self.run_path,
                                    ckpt.checkpoint_name(self.step))
        meta = {
            "id": self.id,
            "step": self.step,
            "validation_history": self.validation_history,
            "ewma": self.ewma,
            "last_loss": self.last_loss,
            "config": self.config.to_dict(),
            "git_sha": git_sha(),
            # which mesh wrote this file and where each leaf lived —
            # restore under any other layout reshards (parallel/reshard.py)
            "mesh": reshard.manifest(self.mesh, self.params, self.opt_state,
                                     zero_opt=self.config.zero_opt),
        }
        ckpt.save_checkpoint(path, self.params, self.opt_state, meta)
        if managed:
            self._refresh_latest_alias(path)
            self._apply_retention()
        return path

    def _save_periodic(self) -> str | None:
        """The in-loop save: transient I/O faults are retried, and a save
        that still fails is logged and *survived* — losing one periodic
        checkpoint must not kill a healthy training run (the previous
        rolling checkpoint is still on disk and still valid)."""
        try:
            return retry_with_backoff(self.save, attempts=3, base_delay=0.1)
        except (OSError, RuntimeError) as e:
            print(f"warning: checkpoint save failed at step {self.step} "
                  f"({e}); training continues on the previous checkpoint",
                  file=sys.stderr, flush=True)
            return None

    def _refresh_latest_alias(self, path: str) -> None:
        """Best-effort ``checkpoint.npz`` symlink to the newest rolling
        checkpoint, keeping the documented single-file path working. A
        pre-rolling *real* checkpoint.npz is left alone (it's a valid
        artifact, and find_latest_valid still considers it)."""
        alias = os.path.join(self.run_path, "checkpoint.npz")
        if os.path.lexists(alias) and not os.path.islink(alias):
            return
        tmp = alias + ".lnk"
        try:
            if os.path.lexists(tmp):
                os.unlink(tmp)
            os.symlink(os.path.basename(path), tmp)
            os.replace(tmp, alias)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _apply_retention(self) -> None:
        """Prune rolling checkpoints to the newest ``keep_checkpoints``
        plus the best-validation step (lowest cost); 0 keeps everything."""
        keep = self.config.keep_checkpoints
        if keep <= 0:
            return
        entries = ckpt.list_checkpoints(self.run_path)
        keep_steps = {s for s, _ in entries[-keep:]}
        finite = [r for r in self.validation_history
                  if np.isfinite(r.get("cost", float("nan")))]
        if finite:
            keep_steps.add(min(finite, key=lambda r: r["cost"])["step"])
        for s, p in entries:
            if s not in keep_steps:
                try:
                    os.remove(p)
                except OSError:
                    pass

    @classmethod
    def load(cls, path: str, remesh: dict | None = None) -> "Experiment":
        """Rebuild an experiment from a checkpoint and continue
        (reference Experiment:load + unpickle, experiments.lua:65-72,129-131).

        ``remesh`` overrides the stored parallelism layout — e.g.
        ``{"tensor_parallel": 1}`` restores a tp=2 checkpoint onto a tp=1
        mesh. The restore routes through the resharding layer
        (parallel/reshard.py): checkpoint leaves are re-scattered into
        exactly the placement a fresh ``init()`` under the new layout
        produces, and the sharding-claim findings from that restore land
        on ``exp.last_restore_findings``."""
        meta, p_leaves, o_leaves = ckpt.load_checkpoint(path)
        config = ExperimentConfig.from_dict(meta["config"])
        if remesh:
            config = config.replace(**remesh)
        exp = cls(config, run_id=meta["id"])
        exp.step = meta["step"]
        exp.validation_history = list(meta["validation_history"])
        exp.ewma = meta.get("ewma")
        last_loss = meta.get("last_loss")
        exp.last_loss = float("nan") if last_loss is None else last_loss
        exp.init()  # placed templates under the (possibly different) mesh
        p_sh, o_sh = reshard.state_shardings(exp.params, exp.opt_state)
        exp.params, exp.opt_state, exp.last_restore_findings = reshard.restore(
            ckpt.unflatten_like(exp.params, p_leaves, path),
            ckpt.unflatten_like(exp.opt_state, o_leaves, path),
            p_sh, o_sh)
        return exp

    @classmethod
    def auto_resume(cls, run_dir: str, overrides: dict | None = None,
                    log=None, remesh: dict | None = None) -> "Experiment":
        """Elastic resume: continue from the newest *valid* checkpoint in
        ``run_dir`` (corrupt/truncated candidates are skipped with a
        logged reason), or start a fresh run rooted at exactly that
        directory when none exists — so one retry loop of
        ``cli train --auto-resume <run_dir>`` survives any number of
        kills. On resume the stored config wins over ``overrides``: the
        bit-exact continuation guarantee is only meaningful against the
        configuration the run actually started with. ``remesh`` is the
        one sanctioned exception — a parallelism-layout change (e.g.
        shrinking tp after losing hosts) applied through the resharding
        restore, never silently."""
        path = ckpt.find_latest_valid(run_dir, log=log)
        if path is not None:
            if overrides:
                print(f"auto-resume: ignoring overrides {sorted(overrides)} "
                      f"(config comes from {path})", file=sys.stderr)
            return cls.load(path, remesh=remesh)
        run_dir = run_dir.rstrip("/")
        parent, run_id = os.path.split(run_dir)
        config = ExperimentConfig(**{**(overrides or {}), **(remesh or {})})
        config = config.replace(run_dir=parent or ".")
        return cls(config, run_id=run_id or None)
