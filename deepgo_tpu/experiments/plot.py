"""Validation-curve plotting from run metrics or bare checkpoints.

The reference plots validation costs out of checkpoint files inside iTorch
(plot.lua:5-29). Runs here stream JSONL metrics, so plotting prefers those,
but every checkpoint also carries its full ``validation_history``, so a
bare ``checkpoint.npz`` (or a run dir holding only one) plots too — true
parity with the reference's plot-from-.model workflow. Emits a CSV
(always) and a PNG when matplotlib is importable.

Usage:
  python -m deepgo_tpu.experiments.plot runs/<id> [runs/<id2> ...] [--out curves]
  python -m deepgo_tpu.experiments.plot runs/<id>/checkpoint.npz
"""

from __future__ import annotations

import argparse
import os

from ..utils.atomicio import atomic_write
from ..utils.metrics import read_jsonl


def _checkpoint_curve(path: str) -> list[tuple[int, float, float]]:
    from .checkpoint import load_meta

    meta = load_meta(path)
    return [(r["step"], r["cost"], r["accuracy"])
            for r in meta.get("validation_history", [])]


def load_curves(run_dirs: list[str]) -> dict[str, list[tuple[int, float, float]]]:
    """Per-run (step, cost, accuracy) rows. Each argument may be a run dir
    (metrics.jsonl preferred, checkpoint.npz fallback) or a checkpoint file."""
    curves = {}
    for run_dir in run_dirs:
        if run_dir.endswith(".npz"):
            name = os.path.basename(os.path.dirname(run_dir)) or run_dir
            curves[name] = _checkpoint_curve(run_dir)
            continue
        name = os.path.basename(run_dir.rstrip("/"))
        path = os.path.join(run_dir, "metrics.jsonl")
        if os.path.exists(path):
            rows = [r for r in read_jsonl(path) if r["kind"] == "validation"]
            curves[name] = [(r["step"], r["cost"], r["accuracy"]) for r in rows]
        else:
            curves[name] = _checkpoint_curve(
                os.path.join(run_dir, "checkpoint.npz"))
    return curves


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("runs", nargs="+")
    ap.add_argument("--out", default="curves")
    args = ap.parse_args(argv)

    curves = load_curves(args.runs)
    out_dir = os.path.dirname(args.out)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
    csv_path = args.out + ".csv"
    # atomic: repeated plot runs overwrite in place; a crash mid-write
    # must not truncate the previous good CSV (docs/static_analysis.md)
    with atomic_write(csv_path, mode="w") as f:
        f.write("run,step,validation_cost,validation_accuracy\n")
        for run, rows in curves.items():
            for step, cost, acc in rows:
                f.write(f"{run},{step},{cost},{acc}\n")
    print(f"wrote {csv_path}")

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        print("matplotlib unavailable; CSV only")
        return
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(11, 4))
    for run, rows in curves.items():
        if not rows:
            continue
        steps, costs, accs = zip(*rows)
        ax1.plot(steps, costs, label=run)
        ax2.plot(steps, accs, label=run)
    ax1.set_xlabel("step"); ax1.set_ylabel("validation NLL"); ax1.legend()
    ax2.set_xlabel("step"); ax2.set_ylabel("top-1 accuracy"); ax2.legend()
    fig.tight_layout()
    png_path = args.out + ".png"
    fig.savefig(png_path, dpi=120)
    print(f"wrote {png_path}")


if __name__ == "__main__":
    main()
