"""Content digests and dihedral-symmetry tables for packed positions.

One implementation shared by the three consumers that previously risked
drifting apart:

  * ``obs/workload.py`` — the workload recorder stamps every captured
    request with the exact and canonical digests (PR 15);
  * ``serving/cache.py`` — the position cache keys entries on the same
    digests, and on a canonical hit maps the cached canonical-view
    log-probs back to the requested view through the INVERSE permutation;
  * ``ops/augment.py`` — training-time augmentation gathers through the
    same ``PERMS`` / ``INV_PERMS`` pair on device
    (``tests/test_workload.py`` / ``tests/test_cache.py`` pin all three
    equal).

Numpy + hashlib only: the observability layer imports this module and
never imports jax.

Geometry and conventions (fixed by ``ops/augment._dihedral_tables``):

  * ``PERMS[k]`` is a gather table — ``view_flat[:, p] = flat[:, PERMS[k, p]]``
    produces dihedral view ``k`` of a flattened ``(C, 361)`` record.
  * ``INV_PERMS[k]`` is its inverse — a stone (or per-point model output)
    at old position ``p`` lands at new index ``INV_PERMS[k, p]``; augment
    calls the same table ``TARGET_MAP``.
  * For a symmetry-equivariant forward ``f`` over per-point outputs,
    ``f(view_k(x)) == f(x)[PERMS[k]]``, hence
    ``f(x) == f(view_k(x))[INV_PERMS[k]]`` — the remap the cache applies
    on a canonical hit (``remap_from_canonical``).
"""

from __future__ import annotations

import hashlib

import numpy as np

BOARD_SIZE = 19
NUM_POINTS = BOARD_SIZE * BOARD_SIZE

# packed-record geometry (features.py), kept as plain ints so digest math
# stays explicit and jax-free
PACKED_SHAPE = (9, BOARD_SIZE, BOARD_SIZE)

NUM_SYMMETRIES = 8

DIGEST_HEX = 16  # 64-bit keys: ample for any real capture corpus


def dihedral_perms() -> np.ndarray:
    """(8, 361) int32 gather table: ``view_flat[:, p] = flat[:, PERM[k, p]]``.

    Variant k = (r, f) with r quarter-turn rotations (0..3) and f
    horizontal flip (0..1), applied to the (x, y) grid as numpy
    rot90/fliplr — byte-for-byte the construction in
    ``ops/augment._dihedral_tables``.
    """
    base = np.arange(NUM_POINTS).reshape(BOARD_SIZE, BOARD_SIZE)
    perms = []
    for flip in (False, True):
        for rot in range(4):
            grid = np.rot90(base, rot)
            if flip:
                grid = np.fliplr(grid)
            perms.append(grid.reshape(-1))
    out = np.stack(perms).astype(np.int32)
    out.setflags(write=False)
    return out


def inverse_dihedral_perms() -> np.ndarray:
    """(8, 361) int32 inverse tables (augment's ``TARGET_MAP``):
    ``INV[k, PERMS[k, p]] == p`` — where an old position lands under
    view k, and the gather that maps a canonical-view per-point output
    row back to the requested view."""
    perms = dihedral_perms()
    out = np.empty_like(perms)
    for k in range(NUM_SYMMETRIES):
        inv = np.empty(NUM_POINTS, dtype=np.int64)
        inv[perms[k]] = np.arange(NUM_POINTS)
        out[k] = inv
    out.setflags(write=False)
    return out


PERMS = dihedral_perms()
INV_PERMS = inverse_dihedral_perms()


def digest_bytes(payload: bytes, player: int, rank: int) -> str:
    # sha256 (truncated to 64 bits) over blake2b: measurably faster on
    # this container's OpenSSL for the 3.2KB packed record, and the
    # recorder hashes every request on its writer thread
    h = hashlib.sha256(payload)
    h.update(bytes((int(player) & 0xFF, int(rank) & 0xFF)))
    return h.hexdigest()[:DIGEST_HEX]


def _as_packed(packed: np.ndarray) -> np.ndarray:
    arr = np.ascontiguousarray(np.asarray(packed, dtype=np.uint8))
    if arr.shape != PACKED_SHAPE:
        raise ValueError(
            f"packed record shape {arr.shape} != {PACKED_SHAPE}")
    return arr


def exact_digest(packed: np.ndarray, player: int, rank: int) -> str:
    """Content digest of one forward input: the packed planes plus the
    (player, rank) scalars the forward also consumes — two requests
    share a digest iff their dispatch rows are identical."""
    return digest_bytes(_as_packed(packed).tobytes(), player, rank)


def canonical_digest(packed: np.ndarray, player: int, rank: int) -> str:
    """The 8-fold-symmetry canonical key: the lexicographic MINIMUM of
    the exact digests of all eight dihedral views. Go is equivariant
    under the board symmetries and every packed channel is a spatial
    map, so all eight views cost one forward in a symmetry-aware cache;
    the min over a group orbit is view-invariant — every view of a
    position lands on the same key (the canonicalization tests pin
    this orbit property and that distinct positions never collide)."""
    flat = _as_packed(packed).reshape(PACKED_SHAPE[0], NUM_POINTS)
    return min(digest_bytes(np.ascontiguousarray(flat[:, PERMS[k]])
                            .tobytes(), player, rank)
               for k in range(NUM_SYMMETRIES))


def canonicalize(packed: np.ndarray, player: int, rank: int
                 ) -> tuple[str, np.ndarray, int]:
    """(canonical_digest, canonical_view, k): the orbit-minimum digest,
    the packed view that produced it, and its symmetry index.

    Every dihedral view of one position returns the same digest AND the
    same canonical-view bytes (the orbit is view-set-invariant), so a
    cache keyed on the digest can dispatch the canonical view and later
    serve any view via ``remap_from_canonical(row, k)``.
    """
    flat = _as_packed(packed).reshape(PACKED_SHAPE[0], NUM_POINTS)
    best_digest, best_view, best_k = None, None, 0
    for k in range(NUM_SYMMETRIES):
        view = np.ascontiguousarray(flat[:, PERMS[k]])
        d = digest_bytes(view.tobytes(), player, rank)
        if best_digest is None or d < best_digest:
            best_digest, best_view, best_k = d, view, k
    return best_digest, best_view.reshape(PACKED_SHAPE), best_k


def remap_from_canonical(row: np.ndarray, k: int) -> np.ndarray:
    """Map a per-point output row computed on the CANONICAL view back to
    the view that canonicalized with symmetry index ``k``.

    With ``c = view_k(x)`` and an equivariant forward ``f``,
    ``f(x) == f(c)[INV_PERMS[k]]`` — a pure gather, so parity with an
    uncached forward of ``x`` is bitwise (``tests/test_cache.py``
    property-tests this against the ``ops/augment`` tables for all
    eight views).
    """
    arr = np.asarray(row)
    if arr.shape[-1] != NUM_POINTS:
        raise ValueError(
            f"per-point output row has last dim {arr.shape[-1]}, "
            f"expected {NUM_POINTS}; canonical-key remap only applies "
            "to per-point (361-way) outputs")
    if k == 0:
        return arr
    return np.ascontiguousarray(arr[..., INV_PERMS[k]])


def dihedral_views(packed: np.ndarray) -> list[np.ndarray]:
    """All eight dihedral views of one packed record (tests + tools)."""
    arr = np.ascontiguousarray(np.asarray(packed, dtype=np.uint8))
    flat = arr.reshape(PACKED_SHAPE[0], NUM_POINTS)
    return [np.ascontiguousarray(flat[:, PERMS[k]]).reshape(PACKED_SHAPE)
            for k in range(NUM_SYMMETRIES)]
