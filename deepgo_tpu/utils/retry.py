"""Bounded retry with exponential backoff for transient I/O faults.

Cluster-scale training treats transient filesystem and loader hiccups
(NFS timeouts, preemption-adjacent EIO, the relay tunnel dropping a read)
as absorbable noise: retry a few times with growing sleeps, then give up
loudly. The policy is deliberately bounded — unbounded retries turn a hard
fault into a silent hang, which is worse than the crash (the watchdog and
the auto-resume path both prefer a dead process to a wedged one).

Only exceptions in ``retry_on`` (default: ``OSError``) are retried; any
other exception is a logic error and propagates immediately.

``jitter=True`` opts into full-jitter backoff: each sleep is drawn
U(0, d) where d is the deterministic exponential delay. When MANY callers
hit the same fault at the same instant — every selfplay submitter retrying
the same revived engine, every loader thread retrying the same flaky
mount — deterministic delays re-synchronize the herd into periodic
thundering bursts; full jitter decorrelates them while the exponential
envelope still bounds the worst case. Single-caller paths can keep the
deterministic schedule (it's easier to reason about in logs).
"""

from __future__ import annotations

import random
import sys
import time


def retry_with_backoff(
    fn,
    *,
    attempts: int = 5,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    retry_on: tuple = (OSError,),
    on_retry=None,
    sleep=time.sleep,
    jitter: bool = False,
    rng: random.Random | None = None,
):
    """Call ``fn()``; retry ``retry_on`` failures up to ``attempts`` total
    tries, sleeping ``base_delay * factor**k`` (capped at ``max_delay``)
    between tries — or, with ``jitter=True``, a uniform draw from [0,
    that envelope] (full jitter; ``rng`` is injectable for deterministic
    tests). The final failure re-raises. ``on_retry(exc, attempt, delay)``
    observes each absorbed failure with the ACTUAL delay slept (default: a
    stderr note, so absorbed faults stay visible in run logs); ``sleep``
    is injectable for tests."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    if jitter and rng is None:
        rng = random.Random()
    delay = base_delay
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts:
                raise
            actual = rng.uniform(0.0, delay) if jitter else delay
            if on_retry is not None:
                on_retry(e, attempt, actual)
            else:
                print(
                    f"transient fault ({e}); retry {attempt}/{attempts - 1} "
                    f"in {actual:.2f}s",
                    file=sys.stderr,
                    flush=True,
                )
            sleep(actual)
            delay = min(delay * factor, max_delay)
