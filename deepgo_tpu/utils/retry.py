"""Bounded retry with exponential backoff for transient I/O faults.

Cluster-scale training treats transient filesystem and loader hiccups
(NFS timeouts, preemption-adjacent EIO, the relay tunnel dropping a read)
as absorbable noise: retry a few times with growing sleeps, then give up
loudly. The policy is deliberately bounded — unbounded retries turn a hard
fault into a silent hang, which is worse than the crash (the watchdog and
the auto-resume path both prefer a dead process to a wedged one).

Only exceptions in ``retry_on`` (default: ``OSError``) are retried; any
other exception is a logic error and propagates immediately.
"""

from __future__ import annotations

import sys
import time


def retry_with_backoff(
    fn,
    *,
    attempts: int = 5,
    base_delay: float = 0.05,
    factor: float = 2.0,
    max_delay: float = 2.0,
    retry_on: tuple = (OSError,),
    on_retry=None,
    sleep=time.sleep,
):
    """Call ``fn()``; retry ``retry_on`` failures up to ``attempts`` total
    tries, sleeping ``base_delay * factor**k`` (capped at ``max_delay``)
    between tries. The final failure re-raises. ``on_retry(exc, attempt,
    delay)`` observes each absorbed failure (default: a stderr note, so
    absorbed faults stay visible in run logs); ``sleep`` is injectable for
    tests."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    delay = base_delay
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except retry_on as e:
            if attempt == attempts:
                raise
            if on_retry is not None:
                on_retry(e, attempt, delay)
            else:
                print(
                    f"transient fault ({e}); retry {attempt}/{attempts - 1} "
                    f"in {delay:.2f}s",
                    file=sys.stderr,
                    flush=True,
                )
            sleep(delay)
            delay = min(delay * factor, max_delay)
