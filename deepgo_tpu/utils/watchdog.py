"""External-process watchdog for wedged device claims.

A wedged TPU-relay claim blocks inside a C call while holding the GIL, so
neither SIGALRM handlers nor in-process timer threads can run (round-1
postmortem: bench watchdog thread never fired; driver recorded rc=124
timeouts). The only robust watchdog is another *process*: a child — started
with sitecustomize stripped from PYTHONPATH so it can never touch the relay
itself — polls its parentage once a second; if the parent is still alive
after ``timeout_s`` it emits a diagnostic (optionally a JSON line on stdout
for machine consumers like the bench driver) and SIGKILLs it. Fast, loud
failure instead of a silent multi-minute driver timeout.

Capability anchor: the reference's only failure-detection mechanism is the
``pcall`` bad-batch capture (reference ``train.lua:106-109``); a hang
watchdog is the TPU-relay-era equivalent.
"""

from __future__ import annotations

import math
import os
import subprocess
import sys


def _poll_count(timeout_s: float) -> int:
    """1-second child polls needed to cover ``timeout_s``, rounded UP.

    ``int()`` truncation made ``timeout_s=1.5`` fire after ~1s — an early
    kill is strictly worse than a late one for a watchdog (it murders a
    healthy process), so fractional budgets always round away from the
    trigger. The minimum of one poll keeps a zero/negative budget from
    producing an instant kill loop."""
    return max(1, math.ceil(float(timeout_s)))


class Watchdog:
    """Handle for an armed watchdog child; ``disarm()`` on success."""

    def __init__(self, proc: subprocess.Popen | None):
        self._proc = proc

    def disarm(self) -> None:
        if self._proc is not None:
            self._proc.terminate()
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:  # pragma: no cover
                self._proc.kill()
                self._proc.wait()
            self._proc = None


def arm(label: str, timeout_s: float = 120.0,
        diagnostic_json: str | None = None, flight: bool = False) -> Watchdog:
    """Arm an external watchdog that SIGKILLs this process after timeout_s.

    The child exits on its own when this process finishes (reparenting
    check), so even an un-disarmed watchdog cannot kill an innocent later
    process. ``diagnostic_json``, if given, is printed verbatim to the
    shared stdout right before the kill so line-oriented consumers still
    get a parseable record. Disabling is the caller's job (each surface
    owns its knob, e.g. BENCH_WATCHDOG / GRAFT_WATCHDOG): pass through to
    ``Watchdog(None)`` there rather than arming.

    ``flight=True`` sends the parent SIGUSR1 one second before the kill —
    the flight-recorder grace signal (obs/sentinel.install_signal_dump):
    a parent wedged at the *Python* level (deadlocked threads, a stuck
    queue wait) dumps its ring-buffer black box before dying. Only pass
    it after installing the handler: SIGUSR1's default action terminates.
    A parent wedged inside a GIL-held C call cannot run the handler — the
    kill still proceeds, just without the dump."""
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    lines = [
        "import os, signal, sys, time",
        f"ppid = {os.getpid()}",
        f"label = {str(label)!r}",
        f"for _ in range({_poll_count(timeout_s)}):",
        "    time.sleep(1)",
        "    if os.getppid() != ppid:",
        "        sys.exit(0)",
    ]
    if flight:
        lines += [
            "try:",
            "    os.kill(ppid, signal.SIGUSR1)",
            "    time.sleep(1)",
            "except OSError:",
            "    sys.exit(0)",
        ]
    if diagnostic_json is not None:
        lines += [
            f"sys.stdout.write({diagnostic_json + chr(10)!r})",
            "sys.stdout.flush()",
        ]
    lines += [
        "sys.stderr.write('watchdog: %s still blocked after "
        f"{float(timeout_s)}s (device claim likely wedged); "
        "killing %d\\n' % (label, ppid))",
        "sys.stderr.flush()",
        "os.kill(ppid, signal.SIGKILL)",
    ]
    proc = subprocess.Popen([sys.executable, "-c", "\n".join(lines)], env=env)
    return Watchdog(proc)
