"""Profiler integration.

The reference's tracing story is wall-clock prints (SURVEY.md section 5.1);
here the same samples/sec metrics stream to JSONL, and this module adds
real device profiling: a context manager around ``jax.profiler`` writing a
TensorBoard-loadable trace, plus annotation helpers for named regions.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(out_dir: str | None):
    """Capture a device/host trace into ``out_dir`` (no-op when None)."""
    if not out_dir:
        yield
        return
    os.makedirs(out_dir, exist_ok=True)
    jax.profiler.start_trace(out_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation  # named host regions in the trace
step_annotation = jax.profiler.StepTraceAnnotation  # per-step markers
