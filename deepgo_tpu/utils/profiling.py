"""Profiler integration.

The reference's tracing story is wall-clock prints (SURVEY.md section 5.1);
here the same samples/sec metrics stream to JSONL, and this module adds
real device profiling: a context manager around ``jax.profiler`` writing a
TensorBoard-loadable trace, plus annotation helpers for named regions.
Host-side spans (``deepgo_tpu.obs.spans``) ride the same TraceAnnotation
mechanism, so a capture taken here shows the obs stages on the host
timeline aligned with the device ops they caused.
"""

from __future__ import annotations

import contextlib
import os

import jax


@contextlib.contextmanager
def trace(out_dir: str | None, metrics=None):
    """Capture a device/host trace into ``out_dir`` (no-op when None).

    A raised ``start_trace`` (already-active profiler, unwritable dir) is
    cleaned up before propagating — ``stop_trace`` is attempted so no
    half-started profiler session dangles into the next capture attempt.
    ``metrics`` (a MetricsWriter/JsonlSink) gets a ``profile_trace``
    event naming the output dir, so traces are discoverable from the run
    registry instead of only by crawling the filesystem."""
    if not out_dir:
        yield
        return
    os.makedirs(out_dir, exist_ok=True)
    try:
        jax.profiler.start_trace(out_dir)
    except Exception:
        # a partially-started session would poison every later capture
        # with "profiler already active"; best-effort stop, then surface
        # the original failure
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        raise
    if metrics is not None:
        metrics.write("profile_trace", out_dir=os.path.abspath(out_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


annotate = jax.profiler.TraceAnnotation  # named host regions in the trace
step_annotation = jax.profiler.StepTraceAnnotation  # per-step markers
