"""Crash-safe file writes: temp file + fsync + atomic rename.

A plain ``open(path, "wb")`` destroys the previous contents the moment it
runs, so a crash (or an injected fault) mid-write leaves a torn file where
the only recovery artifact used to be — exactly the failure mode the
reference inherited for checkpoints and the bad-batch postmortem dump.
``atomic_write`` guarantees readers only ever observe either the old
complete file or the new complete file:

  1. the payload goes to a uniquely-named temp file in the *same directory*
     (``os.replace`` is only atomic within a filesystem),
  2. the file is flushed and fsync'd so the bytes are durable before they
     become visible,
  3. ``os.replace`` swaps it in atomically,
  4. the directory entry itself is fsync'd (best effort) so the rename
     survives a power cut.

On any failure the temp file is removed and the destination is untouched.
"""

from __future__ import annotations

import contextlib
import os
import tempfile


def _fsync_dir(path: str) -> None:
    """Flush the directory entry after a rename (best effort: some
    filesystems refuse O_RDONLY fsync on directories; losing only the
    rename — never the data — is the acceptable downgrade there)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


@contextlib.contextmanager
def atomic_write(path: str, mode: str = "wb"):
    """Context manager yielding a file object whose contents replace
    ``path`` atomically on successful exit.

        with atomic_write(ckpt_path) as f:
            np.savez(f, **arrays)

    If the body raises, ``path`` is left exactly as it was and the temp
    file is deleted."""
    directory = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".", suffix=".tmp"
    )
    f = os.fdopen(fd, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        tmp = None  # committed: nothing to clean up
        _fsync_dir(directory)
    finally:
        if not f.closed:
            f.close()
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass


def atomic_write_bytes(path: str, data: bytes) -> None:
    """One-shot atomic replacement of ``path`` with ``data``."""
    with atomic_write(path, "wb") as f:
        f.write(data)
