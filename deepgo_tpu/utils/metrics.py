"""Metrics and run-registry logging.

Replaces the reference's three observability channels (SURVEY.md section 5.5)
with local, greppable files:
  * console prints            -> kept (the train loop prints)
  * Google-Forms curl POST    -> append to a JSONL run registry
    (reference logging.lua:3-25 posted hyperparams + results to a form)
  * checkpoint-based plotting -> per-run metrics JSONL consumed by
    deepgo_tpu.experiments.plot

``MetricsWriter`` is now a thin shim over the obs subsystem's
``JsonlSink`` (deepgo_tpu/obs/exporter.py): same path, same one-line
JSON records, same ``write(kind, **fields)`` surface — every existing
call site and consumer keeps working — plus what the bare appender
lacked: idempotent ``close()``, context-manager support, thread-safe
writes, and optional size-based rotation. Aggregation (counters,
histograms, the live /metrics endpoint) lives in ``deepgo_tpu.obs``;
this stream stays the durable event record.
"""

from __future__ import annotations

import json
import os

from ..obs.exporter import JsonlSink


class MetricsWriter(JsonlSink):
    """Append-only JSONL metrics stream for one run (obs JsonlSink shim)."""

    def __init__(self, path: str, max_bytes: int = 0, max_files: int = 5):
        super().__init__(path, max_bytes=max_bytes, max_files=max_files)


def append_registry(registry_path: str, record: dict) -> None:
    """One line per completed run: the reference's results table
    (logging.lua) without the network dependency."""
    os.makedirs(os.path.dirname(registry_path) or ".", exist_ok=True)
    with open(registry_path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
