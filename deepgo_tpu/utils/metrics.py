"""Metrics and run-registry logging.

Replaces the reference's three observability channels (SURVEY.md section 5.5)
with local, greppable files:
  * console prints            -> kept (the train loop prints)
  * Google-Forms curl POST    -> append to a JSONL run registry
    (reference logging.lua:3-25 posted hyperparams + results to a form)
  * checkpoint-based plotting -> per-run metrics JSONL consumed by
    deepgo_tpu.experiments.plot
"""

from __future__ import annotations

import json
import os
import time


class MetricsWriter:
    """Append-only JSONL metrics stream for one run."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._f = open(path, "a", buffering=1)

    def write(self, kind: str, **fields) -> None:
        record = {"kind": kind, "time": time.time(), **fields}
        self._f.write(json.dumps(record) + "\n")

    def close(self) -> None:
        self._f.close()


def append_registry(registry_path: str, record: dict) -> None:
    """One line per completed run: the reference's results table
    (logging.lua) without the network dependency."""
    os.makedirs(os.path.dirname(registry_path) or ".", exist_ok=True)
    with open(registry_path, "a") as f:
        f.write(json.dumps(record) + "\n")


def read_jsonl(path: str) -> list[dict]:
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out
