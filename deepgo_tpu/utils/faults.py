"""Deterministic fault injection for exercising the crash-safety paths.

Real clusters fail in ways unit fixtures don't: preemptions mid-write,
transient EIO from shared storage, SIGKILLs between checkpoint boundaries.
This module turns those into reproducible test inputs. A ``FaultPlan`` is
parsed from the ``DEEPGO_FAULTS`` environment variable (or installed
programmatically / via ``ExperimentConfig.faults``) and consulted at named
*fault points* threaded through the codebase:

  site             where it fires
  ----             ---------------
  ckpt_write       inside the atomic checkpoint write (checkpoint.save_checkpoint)
  loader_io        the memmap gather in GoDataset.batch_at
  train_step       just before a training step executes (experiment._train)
  kill             after a training step completes, keyed on the step number
  serving_dispatch the serving dispatcher loop, once per coalescing window,
                   OUTSIDE the per-batch containment — an injected fault
                   here kills the dispatcher thread (the death the
                   SupervisedEngine restart absorbs)
  serving_forward  inside the serving dispatch, alongside the jitted
                   forward — an injected fault here fails ONE coalesced
                   batch (BatchDispatchError; the poison-isolation path)
  dist_init        the multi-host bootstrap (parallel.distributed.initialize),
                   before the coordinator dial — transients are absorbed by
                   the deadline-wrapped full-jitter retry
                   (parallel.deadlines.initialize_with_deadline), hard
                   faults surface un-retried
  dist_collective  host-side at the elastic step-dispatch boundary (the
                   gradient all-reduce rides inside the dispatched program)
                   and at global_array_from_local — where a batch becomes a
                   cross-host object
  heartbeat        inside HeartbeatWriter.beat (parallel.liveness) —
                   transient write faults are retried, hard ones logged and
                   absorbed (the peers' miss budget exists precisely to
                   tolerate missed beats)
  fleet_route      inside each FleetRouter placement attempt
                   (serving/fleet.py) — an injected fault is absorbed like
                   a replica failure: the candidate is excluded, the
                   request re-routes, the failover counter ticks
  fleet_reload     once per replica swap during a rolling weight reload —
                   a fault surfaces as a typed FleetReloadError while the
                   draining replica rejoins and the fleet keeps serving
  loop_ingest      inside ReplayBuffer.ingest_game (deepgo_tpu/loop) —
                   transients are absorbed by the bounded-jitter retry,
                   hard faults kill the actor BEFORE the game is acked
                   (the loop supervisor restarts it; acked games are
                   already durable, so none are ever lost)
  loop_gate        at the start of ArenaGatekeeper.evaluate — a hard
                   fault kills the gatekeeper component; the service
                   re-queues the challenger so the restarted gatekeeper
                   re-gates it instead of dropping the window

Grammar (comma-separated ``site:kind@arg`` specs):

  DEEPGO_FAULTS="ckpt_write:fail@2,loader_io:transient@5,kill:step@7"

  fail@N       the Nth hit of the site raises InjectedFailure (a hard,
               non-retryable fault; later hits succeed)
  transient@N  the first N hits raise TransientFault — an OSError, so
               retry_with_backoff absorbs it like a real flaky filesystem
  step@K       (kill site only) SIGKILL this process once the training
               step counter reaches K: no cleanup, no atexit, the honest
               preemption

The plan is process-local mutable state on purpose: counters advance as
sites are hit, which is what makes "fail the 2nd write" expressible.
"""

from __future__ import annotations

import os
import signal
import sys
from dataclasses import dataclass, field


class FaultError(Exception):
    """Base for injected faults (never raised by real I/O)."""


class InjectedFailure(FaultError, RuntimeError):
    """A hard injected fault: not retryable, must surface or be survived
    by design (e.g. a failed periodic checkpoint keeps training)."""


class TransientFault(FaultError, OSError):
    """A transient injected fault. Subclasses OSError so the production
    retry policy (retry_with_backoff's default ``retry_on``) treats it
    exactly like a real transient I/O error."""


_KINDS = ("fail", "transient", "step")


@dataclass
class FaultSpec:
    site: str
    kind: str  # one of _KINDS
    arg: int
    hits: int = field(default=0, compare=False)
    fired: bool = field(default=False, compare=False)


class FaultPlan:
    """A parsed set of fault specs, counters included."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = specs

    def __bool__(self) -> bool:
        return bool(self.specs)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for raw in (text or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            site, sep, rest = raw.partition(":")
            kind, sep2, arg = rest.partition("@")
            if not sep or not sep2 or not site or kind not in _KINDS:
                raise ValueError(
                    f"bad fault spec {raw!r}: expected site:kind@arg with "
                    f"kind in {_KINDS} (e.g. ckpt_write:fail@2, "
                    f"loader_io:transient@5, kill:step@7)"
                )
            try:
                arg_n = int(arg)
            except ValueError:
                raise ValueError(
                    f"bad fault spec {raw!r}: arg must be an integer"
                ) from None
            if arg_n < 1:
                raise ValueError(f"bad fault spec {raw!r}: arg must be >= 1")
            if (kind == "step") != (site == "kill"):
                raise ValueError(
                    f"bad fault spec {raw!r}: step@K is for the kill site; "
                    f"other sites take fail@N or transient@N"
                )
            specs.append(FaultSpec(site, kind, arg_n))
        return cls(specs)

    def check(self, site: str, step: int | None = None) -> None:
        """Advance counters for ``site``; raise / kill if a spec is due."""
        for spec in self.specs:
            if spec.site != site:
                continue
            if spec.kind == "step":
                if step is None or spec.fired:
                    continue
                if step >= spec.arg:
                    spec.fired = True
                    print(
                        f"fault injection: SIGKILL at step {step} "
                        f"(kill:step@{spec.arg})",
                        file=sys.stderr,
                        flush=True,
                    )
                    sys.stdout.flush()
                    os.kill(os.getpid(), signal.SIGKILL)
                continue
            spec.hits += 1
            if spec.kind == "fail" and spec.hits == spec.arg:
                raise InjectedFailure(
                    f"injected hard fault at {site} (hit {spec.hits})"
                )
            if spec.kind == "transient" and spec.hits <= spec.arg:
                raise TransientFault(
                    f"injected transient fault at {site} "
                    f"(hit {spec.hits}/{spec.arg})"
                )


_plan: FaultPlan | None = None


def active_plan() -> FaultPlan:
    """The process-wide plan, lazily parsed from DEEPGO_FAULTS."""
    global _plan
    if _plan is None:
        _plan = FaultPlan.parse(os.environ.get("DEEPGO_FAULTS", ""))
    return _plan


def install(plan: FaultPlan | str) -> FaultPlan:
    """Replace the active plan (tests, or ExperimentConfig.faults)."""
    global _plan
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return _plan


def reset() -> None:
    """Drop the active plan; the next check() re-reads DEEPGO_FAULTS."""
    global _plan
    _plan = None


def check(site: str, step: int | None = None) -> None:
    """Fault point hook. A no-op (one truthiness test) when no plan is
    configured, so production paths pay nothing for carrying it."""
    plan = active_plan()
    if plan:
        plan.check(site, step)
