"""Deterministic fault injection for exercising the crash-safety paths.

Real clusters fail in ways unit fixtures don't: preemptions mid-write,
transient EIO from shared storage, SIGKILLs between checkpoint boundaries.
This module turns those into reproducible test inputs. A ``FaultPlan`` is
parsed from the ``DEEPGO_FAULTS`` environment variable (or installed
programmatically / via ``ExperimentConfig.faults``) and consulted at named
*fault points* threaded through the codebase:

  site             where it fires
  ----             ---------------
  ckpt_write       inside the atomic checkpoint write (checkpoint.save_checkpoint)
  loader_io        the memmap gather in GoDataset.batch_at
  train_step       just before a training step executes (experiment._train)
  kill             after a training step completes, keyed on the step number
  serving_dispatch the serving dispatcher loop, once per coalescing window,
                   OUTSIDE the per-batch containment — an injected fault
                   here kills the dispatcher thread (the death the
                   SupervisedEngine restart absorbs)
  serving_forward  inside the serving dispatch, alongside the jitted
                   forward — an injected fault here fails ONE coalesced
                   batch (BatchDispatchError; the poison-isolation path)
  dist_init        the multi-host bootstrap (parallel.distributed.initialize),
                   before the coordinator dial — transients are absorbed by
                   the deadline-wrapped full-jitter retry
                   (parallel.deadlines.initialize_with_deadline), hard
                   faults surface un-retried
  dist_collective  host-side at the elastic step-dispatch boundary (the
                   gradient all-reduce rides inside the dispatched program)
                   and at global_array_from_local — where a batch becomes a
                   cross-host object
  heartbeat        inside HeartbeatWriter.beat (parallel.liveness) —
                   transient write faults are retried, hard ones logged and
                   absorbed (the peers' miss budget exists precisely to
                   tolerate missed beats)
  fleet_route      inside each FleetRouter placement attempt
                   (serving/fleet.py) — an injected fault is absorbed like
                   a replica failure: the candidate is excluded, the
                   request re-routes, the failover counter ticks
  fleet_reload     once per replica swap during a rolling weight reload —
                   a fault surfaces as a typed FleetReloadError while the
                   draining replica rejoins and the fleet keeps serving
  loop_ingest      inside ReplayBuffer.ingest_game (deepgo_tpu/loop) —
                   transients are absorbed by the bounded-jitter retry,
                   hard faults kill the actor BEFORE the game is acked
                   (the loop supervisor restarts it; acked games are
                   already durable, so none are ever lost)
  loop_gate        at the start of ArenaGatekeeper.evaluate — a hard
                   fault kills the gatekeeper component; the service
                   re-queues the challenger so the restarted gatekeeper
                   re-gates it instead of dropping the window
  reshard_gather   the gather-to-host half of a resharding restore
                   (parallel/reshard.py) — transients are absorbed by
                   the bounded full-jitter retry (flaky storage mid-
                   recovery), hard faults surface typed
  reshard_scatter  the device re-scatter half of a resharding restore —
                   same bounded-retry contract as the gather
  reshard_collective  the cross-host convergence barrier a reshard is
                   part of — slow@MS emulates a collective timeout (the
                   bounded retry + deadline watchdogs bound it), hard
                   faults surface typed
  session_wal      inside the session store's fsync'd WAL append
                   (sessions/store.py), BEFORE the ack — transients are
                   absorbed by the loop-ingest retry policy, hard
                   faults surface typed with the move un-acked and the
                   in-memory game untouched
  session_reply    per engine-reply attempt in the interactive game
                   service (sessions/service.py), before the fleet
                   submit — a transient burns one deadline tier and
                   escalates to the next budget; a hard fault surfaces
                   typed (the session state is unchanged either way)

Grammar (comma-separated ``site:kind@arg`` specs):

  DEEPGO_FAULTS="ckpt_write:fail@2,loader_io:transient@5,kill:step@7"

  fail@N       the Nth hit of the site raises InjectedFailure (a hard,
               non-retryable fault; later hits succeed)
  transient@N  the first N hits raise TransientFault — an OSError, so
               retry_with_backoff absorbs it like a real flaky filesystem
  step@K       (kill site only) SIGKILL this process once the training
               step counter reaches K: no cleanup, no atexit, the honest
               preemption
  slow@MS      GRAY failure: every hit of the site sleeps MS milliseconds
               while the spec is installed — a brownout, not a crash. The
               site stays alive and "healthy"; only its latency lies.
               Consulted through ``maybe_slow``, never raised.
  corrupt@N    GRAY failure: the first N hits return silently WRONG
               output — the site must ask ``corrupt_due`` and perturb its
               own result. Nothing raises; the corruption is only
               detectable by checking answers (the canary-probe path).

Gray kinds (slow/corrupt) are value-consulted, not raise-based:
``check()`` ignores them entirely, so a site that only calls ``check``
never pays for — or trips over — a gray spec aimed elsewhere. Sites
that support gray faults consult ``maybe_slow(site, name)`` /
``corrupt_due(site, name)``, which also match the replica-scoped form
``site.<name>`` — ``serving_slow.bench-1:slow@40`` brownouts exactly
one replica of a fleet while its peers stay fast.

The plan is process-local mutable state on purpose: counters advance as
sites are hit, which is what makes "fail the 2nd write" expressible.
``add``/``remove`` mutate the installed plan in place, which is what
lets a chaos scenario schedule (deepgo_tpu/chaos) open and close fault
windows on a timeline instead of arming everything at t=0.
"""

from __future__ import annotations

import os
import signal
import sys
import time
from dataclasses import dataclass, field


class FaultError(Exception):
    """Base for injected faults (never raised by real I/O)."""


class InjectedFailure(FaultError, RuntimeError):
    """A hard injected fault: not retryable, must surface or be survived
    by design (e.g. a failed periodic checkpoint keeps training)."""


class TransientFault(FaultError, OSError):
    """A transient injected fault. Subclasses OSError so the production
    retry policy (retry_with_backoff's default ``retry_on``) treats it
    exactly like a real transient I/O error."""


_KINDS = ("fail", "transient", "step", "slow", "corrupt")

# the raise/kill kinds check() owns; slow/corrupt are value-consulted
_CHECK_KINDS = ("fail", "transient", "step")


@dataclass
class FaultSpec:
    site: str
    kind: str  # one of _KINDS
    arg: int
    hits: int = field(default=0, compare=False)
    fired: bool = field(default=False, compare=False)


class FaultPlan:
    """A parsed set of fault specs, counters included."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = specs

    def __bool__(self) -> bool:
        return bool(self.specs)

    @classmethod
    def parse(cls, text: str) -> "FaultPlan":
        specs = []
        for raw in (text or "").split(","):
            raw = raw.strip()
            if not raw:
                continue
            site, sep, rest = raw.partition(":")
            kind, sep2, arg = rest.partition("@")
            if not sep or not sep2 or not site or kind not in _KINDS:
                raise ValueError(
                    f"bad fault spec {raw!r}: expected site:kind@arg with "
                    f"kind in {_KINDS} (e.g. ckpt_write:fail@2, "
                    f"loader_io:transient@5, kill:step@7)"
                )
            try:
                arg_n = int(arg)
            except ValueError:
                raise ValueError(
                    f"bad fault spec {raw!r}: arg must be an integer"
                ) from None
            if arg_n < 1:
                raise ValueError(f"bad fault spec {raw!r}: arg must be >= 1")
            if (kind == "step") != (site == "kill"):
                raise ValueError(
                    f"bad fault spec {raw!r}: step@K is for the kill site; "
                    f"other sites take fail@N or transient@N"
                )
            specs.append(FaultSpec(site, kind, arg_n))
        return cls(specs)

    def add(self, text: str) -> list[FaultSpec]:
        """Parse ``text`` and merge its specs into this plan (counters of
        existing specs untouched). Returns the specs added."""
        added = FaultPlan.parse(text).specs
        self.specs.extend(added)
        return added

    def remove(self, site: str, kind: str | None = None) -> int:
        """Drop every spec at ``site`` (optionally only of ``kind``);
        returns how many were removed. Closing a chaos fault window."""
        keep, dropped = [], 0
        for spec in self.specs:
            if spec.site == site and (kind is None or spec.kind == kind):
                dropped += 1
            else:
                keep.append(spec)
        self.specs[:] = keep
        return dropped

    def slow_s(self, site: str) -> float:
        """Total injected delay (seconds) due at this hit of ``site`` —
        0.0 when no slow spec matches. Advances slow hit counters."""
        total = 0.0
        for spec in self.specs:
            if spec.kind == "slow" and spec.site == site:
                spec.hits += 1
                total += spec.arg / 1000.0
        return total

    def corrupt_hit(self, site: str) -> bool:
        """True when a corrupt spec at ``site`` still owes corruption
        (first N hits). Advances corrupt hit counters."""
        due = False
        for spec in self.specs:
            if spec.kind == "corrupt" and spec.site == site:
                spec.hits += 1
                if spec.hits <= spec.arg:
                    due = True
        return due

    def check(self, site: str, step: int | None = None) -> None:
        """Advance counters for ``site``; raise / kill if a spec is due.
        Gray kinds (slow/corrupt) are ignored here — they are consulted
        by value through ``maybe_slow`` / ``corrupt_due``."""
        for spec in self.specs:
            if spec.site != site or spec.kind not in _CHECK_KINDS:
                continue
            if spec.kind == "step":
                if step is None or spec.fired:
                    continue
                if step >= spec.arg:
                    spec.fired = True
                    print(
                        f"fault injection: SIGKILL at step {step} "
                        f"(kill:step@{spec.arg})",
                        file=sys.stderr,
                        flush=True,
                    )
                    sys.stdout.flush()
                    os.kill(os.getpid(), signal.SIGKILL)
                continue
            spec.hits += 1
            if spec.kind == "fail" and spec.hits == spec.arg:
                raise InjectedFailure(
                    f"injected hard fault at {site} (hit {spec.hits})"
                )
            if spec.kind == "transient" and spec.hits <= spec.arg:
                raise TransientFault(
                    f"injected transient fault at {site} "
                    f"(hit {spec.hits}/{spec.arg})"
                )


_plan: FaultPlan | None = None


def active_plan() -> FaultPlan:
    """The process-wide plan, lazily parsed from DEEPGO_FAULTS."""
    global _plan
    if _plan is None:
        _plan = FaultPlan.parse(os.environ.get("DEEPGO_FAULTS", ""))
    return _plan


def install(plan: FaultPlan | str) -> FaultPlan:
    """Replace the active plan (tests, or ExperimentConfig.faults)."""
    global _plan
    _plan = FaultPlan.parse(plan) if isinstance(plan, str) else plan
    return _plan


def reset() -> None:
    """Drop the active plan; the next check() re-reads DEEPGO_FAULTS."""
    global _plan
    _plan = None


def check(site: str, step: int | None = None) -> None:
    """Fault point hook. A no-op (one truthiness test) when no plan is
    configured, so production paths pay nothing for carrying it."""
    plan = active_plan()
    if plan:
        plan.check(site, step)


def add(text: str) -> list[FaultSpec]:
    """Merge specs into the active plan (chaos scenario windows)."""
    return active_plan().add(text)


def remove(site: str, kind: str | None = None) -> int:
    """Remove specs at ``site`` from the active plan."""
    plan = _plan
    return plan.remove(site, kind) if plan is not None else 0


def maybe_slow(site: str, name: str | None = None,
               sleep=time.sleep) -> float:
    """Gray-failure hook: sleep any injected brownout delay due at
    ``site`` (and, when ``name`` is given, at the replica-scoped site
    ``site.name``); returns the seconds slept. The sleep happens HERE,
    inside the faults harness, so serving code never needs a bare
    time.sleep for injection (the bare-sleep lint rule)."""
    plan = active_plan()
    if not plan:
        return 0.0
    delay = plan.slow_s(site)
    if name is not None:
        delay += plan.slow_s(f"{site}.{name}")
    if delay > 0.0:
        sleep(delay)
    return delay


def corrupt_due(site: str, name: str | None = None) -> bool:
    """Gray-failure hook: True when this hit of ``site`` (or of the
    replica-scoped ``site.name``) must return a corrupted result. The
    call site owns the perturbation; this only answers "is it due"."""
    plan = active_plan()
    if not plan:
        return False
    due = plan.corrupt_hit(site)
    if name is not None:
        due = plan.corrupt_hit(f"{site}.{name}") or due
    return due
