"""Run provenance: git commit stamping.

The reference's dead run.lua path printed the last git commits at train
start (run.lua:33-36, the one idea SURVEY.md says is worth keeping);
here the sha goes into run metadata and checkpoints.
"""

from __future__ import annotations

import os
import subprocess


def git_sha(cwd: str | None = None) -> str | None:
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=cwd or os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))),
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except Exception:
        return None
