"""Utilities: metrics, timing, run identity."""

from .metrics import MetricsWriter, append_registry  # noqa: F401
from .gitinfo import git_sha  # noqa: F401
