"""Utilities: metrics, timing, run identity, crash safety."""

import os

from .metrics import MetricsWriter, append_registry  # noqa: F401
from .gitinfo import git_sha  # noqa: F401
from .atomicio import atomic_write, atomic_write_bytes  # noqa: F401
from .retry import retry_with_backoff  # noqa: F401


def honor_platform_env() -> None:
    """Re-assert JAX_PLATFORMS after interpreter start.

    In the TPU terminal a sitecustomize force-selects the tunneled device,
    silently overriding the environment variable; a backend config update
    before first device use restores the user's choice (same pin as
    tests/conftest.py). Without this, ``JAX_PLATFORMS=cpu`` CLI runs would
    still dial the TPU relay — and block forever when its claim is wedged.
    Call from CLI entry points before any device use.
    """
    want = os.environ.get("JAX_PLATFORMS")
    if want:
        import jax

        jax.config.update("jax_platforms", want)
