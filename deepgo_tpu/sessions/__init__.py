"""Durable game sessions: crash-resumable interactive play plus bulk
SGF analysis, both riding the serving fleet's QoS tiers.

The package turns the serving stack into a product surface:

  * ``game``      — full-legality per-session Go state (positional
                    superko, suicide refusal, pass-pass end) over the
                    ``go/`` capture primitives, with a canonical
                    ``digest()`` for bit-identical-resume grading;
  * ``store``     — write-ahead-logged session store: per-move fsync'd
                    ack barrier, compacted atomic checkpoints,
                    find_latest_valid recovery with per-session
                    checkpoint fallback;
  * ``service``   — interactive engine replies on the INTERACTIVE tier
                    with deadline-tiered budgets and typed errors;
  * ``analysis``  — resumable batch-tier corpus scans producing policy
                    annotations and blunder flags;
  * ``child``     — the scripted crash-resume driver ``bench --mode
                    mixed`` SIGKILLs and resumes.
"""

from .analysis import AnalysisCursorError, SgfAnalysisService
from .game import GoGame, IllegalMove, SessionError
from .service import DEFAULT_BUDGETS_S, GameService, ReplyExhausted
from .store import SessionCorrupt, SessionNotFound, SessionStore

__all__ = [
    "AnalysisCursorError",
    "DEFAULT_BUDGETS_S",
    "GameService",
    "GoGame",
    "IllegalMove",
    "ReplyExhausted",
    "SessionCorrupt",
    "SessionError",
    "SessionNotFound",
    "SessionStore",
    "SgfAnalysisService",
]
