"""Scripted session-server child for the crash-resume chaos leg.

``python -m deepgo_tpu.sessions.child --store DIR --games N --moves M``
drives N interactive games against a 1-replica in-process fleet,
printing a line-oriented protocol the bench parent parses:

    SESSION_RESUMED <n>         store recovery found n live sessions
    SESSION_ACK <sid> <seq>     one durably acked move (client or engine)
    SESSION_DIGEST <sid> <hex>  full-state digest of a finished game

``--kill-after-acks K`` makes the child SIGKILL ITSELF the instant the
K-th ack has been printed — between the fsync'd ack and whatever would
have come next, the exact window where an undurable implementation
loses a move. The driver is STATE-driven, not script-position-driven:
on resume it looks only at the recovered board (whose turn, which
points are legal, how many moves played), so a killed run continued by
a fresh process replays to the same game as an uninterrupted one; the
bench grades that by comparing SESSION_DIGEST lines against a
never-killed reference child. Engine replies are deterministic (fixed
init key, argmax policy), which is what makes the digest comparison
meaningful.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys

from ..go.board import BLACK, SIZE
from .service import GameService
from .store import SessionStore


def _script(game_index: int) -> list[tuple[int, int]]:
    """The client's move preference order for game ``game_index`` —
    a fixed seeded shuffle of the whole board, so two runs of the same
    game index always prefer the same points."""
    import random

    points = [(x, y) for x in range(SIZE) for y in range(SIZE)]
    random.Random(1000 + game_index).shuffle(points)
    return points


class _AckCounter:
    """Print acks; self-SIGKILL the moment the K-th lands."""

    def __init__(self, kill_after: int | None):
        self.kill_after = kill_after
        self.acks = 0

    def ack(self, sid: str, seq: int) -> None:
        self.acks += 1
        print(f"SESSION_ACK {sid} {seq}", flush=True)
        if self.kill_after is not None and self.acks >= self.kill_after:
            # a real crash: no cleanup, no final checkpoint, no flush
            os.kill(os.getpid(), signal.SIGKILL)


def _scripted_point(game, script) -> tuple[int, int] | None:
    for x, y in script:
        if game.check_move(x, y, game.to_play) is None:
            return x, y
    return None


def _drive(service: GameService, counter: _AckCounter, games: int,
           moves: int, engine: bool) -> None:
    for gi in range(games):
        sid = f"bench-{gi:02d}"
        try:
            game = service.store.get(sid)
        except Exception:  # noqa: BLE001 — SessionNotFound: first run
            service.new_game(sid)
            game = service.store.get(sid)
        script = _script(gi)
        while len(game.moves) < 2 * moves and not game.over:
            elapsed = 0.01 * (len(game.moves) + 1)
            if game.to_play == BLACK or not engine:
                point = _scripted_point(game, script)
                if point is None:
                    out = service.play(sid, None, None, elapsed_s=elapsed,
                                       reply=False)
                else:
                    out = service.play(sid, point[0], point[1],
                                       elapsed_s=elapsed, reply=False)
            else:
                out = service.engine_reply(sid, elapsed_s=elapsed)
            counter.ack(sid, out["seq"])
        print(f"SESSION_DIGEST {sid} {game.digest()}", flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scripted crash-resume session driver")
    ap.add_argument("--store", required=True)
    ap.add_argument("--games", type=int, default=3)
    ap.add_argument("--moves", type=int, default=12,
                    help="client moves per game (total acks ~= 2x)")
    ap.add_argument("--kill-after-acks", type=int, default=None)
    ap.add_argument("--checkpoint-every", type=int, default=8)
    ap.add_argument("--no-engine", action="store_true",
                    help="script both sides (no fleet; WAL-path only)")
    args = ap.parse_args(argv)

    store = SessionStore(args.store,
                         checkpoint_every=args.checkpoint_every)
    print(f"SESSION_RESUMED {store.recovery['sessions']}", flush=True)
    counter = _AckCounter(args.kill_after_acks)

    fleet = None
    if not args.no_engine:
        import jax

        from ..models import policy_cnn
        from ..serving import EngineConfig, fleet_policy_engine

        cfg = policy_cnn.CONFIGS["small"]
        params = policy_cnn.init(jax.random.key(0), cfg)
        fleet = fleet_policy_engine(
            params, cfg, replicas=1,
            config=EngineConfig(buckets=(1,), max_wait_ms=1.0),
            name="session-child")
        fleet.warmup()
    service = GameService(fleet, store, budgets_s=(0.5, 1.0, 2.0))
    try:
        _drive(service, counter, args.games, args.moves,
               engine=fleet is not None)
    finally:
        if fleet is not None:
            fleet.close()
        service.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
