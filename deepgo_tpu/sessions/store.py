"""Write-ahead-logged session store: acked == durable, resume == replay.

The contract mirrors the PR 8 replay buffer (loop/replay.py): a move is
acknowledged to the client ONLY after its WAL record is fsync'd, so a
SIGKILL at any instant loses nothing that was acked. Recovery is a pure
function of the directory:

  1. checkpoints ``ckpt-<seq>.json`` are whole-file atomic
     (utils/atomicio) with an embedded content digest; recovery walks
     them newest-first and takes the first VALID one (the checkpoint
     ``find_latest_valid`` discipline) — corrupt files are skipped and
     counted, never fatal while an older one or the WAL remains;
  2. WAL segments ``wal-<startseq>.jsonl`` are per-record fsync'd
     appends (append-mode streams are torn-TAIL-tolerant by design:
     only the final line can be incomplete, and it is dropped);
  3. records with ``seq`` beyond the checkpoint replay through the SAME
     ``GoGame`` legality methods that produced them, so the recovered
     state is bit-identical — a record that fails to apply marks that
     session corrupt and FALLS BACK to its last checkpointed snapshot
     (``SessionCorrupt`` surfaces only when no good state exists at
     all).

Checkpointing compacts: after an ``atomic_write`` checkpoint at seq N,
every WAL segment is fully covered by N (segments rotate at checkpoint
boundaries) and is deleted; WAL lag — records accumulated since the
last checkpoint, the recovery-replay cost — rides the
``deepgo_session_wal_lag_records`` gauge.

Transient WAL write faults (site ``session_wal``) are absorbed by the
bounded full-jitter retry exactly like loop ingest; a hard fault
surfaces typed with the record UN-acked and the in-memory state
untouched.
"""

from __future__ import annotations

import hashlib
import json
import os
import time

from ..analysis.lockcheck import make_lock
from ..obs.registry import get_registry
from ..utils import faults
from ..utils.atomicio import atomic_write
from ..utils.retry import retry_with_backoff
from .game import GoGame, IllegalMove, SessionError


class SessionNotFound(SessionError):
    """No live session under this id (never opened, or closed)."""

    def __init__(self, session_id: str):
        super().__init__(f"no live session {session_id!r}")
        self.session_id = session_id


class SessionCorrupt(SessionError):
    """A session whose durable state is damaged beyond every fallback:
    its WAL tail failed to apply AND no checkpoint holds it."""

    def __init__(self, session_id: str, reason: str):
        super().__init__(
            f"session {session_id!r} is corrupt: {reason}")
        self.session_id = session_id
        self.reason = reason


class _WalSegment:
    """One fsync'd append-only JSONL stream. ``write`` returns only
    after the bytes are durable — this is the ack barrier."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "ab")

    def write(self, kind: str, **fields) -> None:
        line = json.dumps({"kind": kind, **fields},
                          separators=(",", ":")) + "\n"
        self._f.write(line.encode("utf-8"))
        self._f.flush()
        os.fsync(self._f.fileno())

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()


def _seq_of(name: str, prefix: str, suffix: str) -> int | None:
    if not (name.startswith(prefix) and name.endswith(suffix)):
        return None
    try:
        return int(name[len(prefix):-len(suffix)])
    except ValueError:
        return None


class SessionStore:
    """Durable home of every live game in one directory."""

    def __init__(self, root: str, checkpoint_every: int = 64,
                 keep_checkpoints: int = 3):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.checkpoint_every = int(checkpoint_every)
        self.keep_checkpoints = int(keep_checkpoints)
        self._lock = make_lock("sessions.store")
        self.games: dict[str, GoGame] = {}
        self.corrupt: dict[str, str] = {}      # irrecoverable, by reason
        self.restored_from_checkpoint: list[str] = []
        self.seq = 0
        self.ckpt_seq = 0
        self.wal_retries = 0
        self.closed_sessions = 0
        self._segment: _WalSegment | None = None
        reg = get_registry()
        self._obs_open = reg.gauge(
            "deepgo_session_open_sessions",
            "live interactive game sessions in the store")
        self._obs_lag = reg.gauge(
            "deepgo_session_wal_lag_records",
            "WAL records accumulated since the last compacted "
            "checkpoint (the recovery-replay cost)")
        self._obs_resumes = reg.counter(
            "deepgo_session_resumes_total",
            "live sessions reconstructed from checkpoint + WAL replay "
            "at store startup")
        self.recovery = self._recover()
        self._obs_open.set(len(self.games))
        self._obs_lag.set(self.seq - self.ckpt_seq)

    # -- recovery ----------------------------------------------------------

    def _ckpt_paths(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.root):
            seq = _seq_of(name, "ckpt-", ".json")
            if seq is not None:
                out.append((seq, os.path.join(self.root, name)))
        return sorted(out, reverse=True)

    def _wal_paths(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.root):
            seq = _seq_of(name, "wal-", ".jsonl")
            if seq is not None:
                out.append((seq, os.path.join(self.root, name)))
        return sorted(out)

    @staticmethod
    def _read_checkpoint(path: str) -> dict:
        with open(path, encoding="utf-8") as f:
            wrapped = json.load(f)
        payload = wrapped["payload"]
        body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
        digest = hashlib.sha256(body.encode()).hexdigest()
        if digest != wrapped.get("digest"):
            raise ValueError(f"checkpoint {path} digest mismatch")
        return payload

    def _recover(self) -> dict:
        report = {"checkpoint_seq": 0, "checkpoints_skipped": 0,
                  "wal_records_applied": 0, "torn_tail": False,
                  "restored_from_checkpoint": [], "corrupt": [],
                  "sessions": 0}
        base_snapshots: dict[str, dict] = {}
        for seq, path in self._ckpt_paths():
            try:
                payload = self._read_checkpoint(path)
            except (OSError, ValueError, KeyError, TypeError):
                report["checkpoints_skipped"] += 1
                continue
            base_snapshots = dict(payload.get("sessions", {}))
            self.ckpt_seq = self.seq = int(payload.get("seq", seq))
            report["checkpoint_seq"] = self.ckpt_seq
            break
        for sid, snap in base_snapshots.items():
            try:
                self.games[sid] = GoGame.from_snapshot(snap)
            except (ValueError, KeyError, TypeError) as e:
                self.corrupt[sid] = f"checkpoint snapshot unusable: {e}"
        frozen: set[str] = set()

        def freeze(sid: str, reason: str) -> None:
            """WAL tail for ``sid`` failed to apply: fall back to the
            checkpointed snapshot (find_latest_valid style) or, with no
            checkpoint to fall back to, mark the session corrupt."""
            frozen.add(sid)
            snap = base_snapshots.get(sid)
            if snap is not None:
                try:
                    self.games[sid] = GoGame.from_snapshot(snap)
                    self.restored_from_checkpoint.append(sid)
                    return
                except (ValueError, KeyError, TypeError):
                    pass
            self.games.pop(sid, None)
            self.corrupt[sid] = reason

        wal_paths = self._wal_paths()
        for i, (_, path) in enumerate(wal_paths):
            last_file = i == len(wal_paths) - 1
            try:
                with open(path, "rb") as f:
                    lines = f.read().split(b"\n")
            except OSError:
                continue
            for j, raw in enumerate(lines):
                if not raw.strip():
                    continue
                try:
                    rec = json.loads(raw.decode("utf-8"))
                except (ValueError, UnicodeDecodeError):
                    # torn tail of the newest segment is the expected
                    # crash artifact; a bad line anywhere else means the
                    # rest of this segment cannot be trusted either
                    if last_file and j == len(lines) - 1:
                        report["torn_tail"] = True
                    break
                seq = int(rec.get("seq", 0))
                if seq <= self.seq:
                    continue  # retried duplicate or pre-checkpoint
                self.seq = seq
                sid = str(rec.get("session"))
                if sid in frozen or sid in self.corrupt:
                    continue
                self._apply(rec, sid, freeze)
                report["wal_records_applied"] += 1
        report["restored_from_checkpoint"] = \
            list(self.restored_from_checkpoint)
        report["corrupt"] = sorted(self.corrupt)
        report["sessions"] = len(self.games)
        if self.games:
            self._obs_resumes.inc(len(self.games))
        return report

    def _apply(self, rec: dict, sid: str, freeze) -> None:
        kind = rec.get("kind")
        if kind == "session_open":
            self.games[sid] = GoGame(
                sid, tuple(tuple(h) for h in rec.get("handicaps", ())))
            return
        if kind == "session_close":
            self.games.pop(sid, None)
            self.closed_sessions += 1
            return
        if kind != "session_move":
            return  # unknown kinds are forward-compatible no-ops
        game = self.games.get(sid)
        if game is None:
            freeze(sid, f"move record at seq {rec['seq']} for a session "
                        "never opened")
            return
        try:
            if rec.get("pass"):
                game.play_pass(int(rec["player"]),
                               float(rec.get("elapsed_s", 0.0)))
            else:
                game.play_move(int(rec["x"]), int(rec["y"]),
                               int(rec["player"]),
                               float(rec.get("elapsed_s", 0.0)))
        except (IllegalMove, KeyError, ValueError, TypeError) as e:
            freeze(sid, f"WAL replay failed at seq {rec['seq']}: {e}")

    # -- the durable append (the ack barrier) ------------------------------

    def _wal(self) -> _WalSegment:
        if self._segment is None:
            path = os.path.join(self.root, f"wal-{self.seq + 1:012d}.jsonl")
            self._segment = _WalSegment(path)
        return self._segment

    def _count_retry(self, exc, attempt, delay) -> None:
        self.wal_retries += 1

    def _durable(self, emit) -> None:
        """Run ``emit(segment)`` with the ``session_wal`` fault site
        armed and the loop-ingest retry policy: transients absorbed,
        hard faults surface with nothing acked."""

        def write() -> None:
            faults.check("session_wal")
            emit(self._wal())

        retry_with_backoff(write, attempts=5, base_delay=0.01,
                           jitter=True, on_retry=self._count_retry)

    # -- session lifecycle -------------------------------------------------

    def get(self, session_id: str) -> GoGame:
        with self._lock:
            reason = self.corrupt.get(session_id)
            if reason is not None:
                raise SessionCorrupt(session_id, reason)
            game = self.games.get(session_id)
        if game is None:
            raise SessionNotFound(session_id)
        return game

    def open_session(self, session_id: str,
                     handicaps: tuple = ()) -> GoGame:
        with self._lock:
            if session_id in self.games or session_id in self.corrupt:
                raise SessionError(
                    f"session {session_id!r} already exists")
            seq = self.seq + 1
            hs = [list(map(int, h)) for h in handicaps]
            self._durable(lambda seg: seg.write(
                "session_open", seq=seq, session=session_id, t=time.time(),
                handicaps=hs))
            self.seq = seq
            game = GoGame(session_id, tuple(tuple(h) for h in handicaps))
            self.games[session_id] = game
            self._obs_open.set(len(self.games))
            self._after_append()
        return game

    def append_move(self, session_id: str, player: int,
                    x: int | None = None, y: int | None = None,
                    is_pass: bool = False,
                    elapsed_s: float = 0.0) -> int:
        """Validate -> WAL (fsync) -> apply -> return the acked seq.
        The record is durable BEFORE the in-memory board mutates, so a
        crash between the two replays the move instead of losing it."""
        with self._lock:
            reason = self.corrupt.get(session_id)
            if reason is not None:
                raise SessionCorrupt(session_id, reason)
            game = self.games.get(session_id)
            if game is None:
                raise SessionNotFound(session_id)
            if not is_pass:
                refusal = game.check_move(int(x), int(y), int(player))
                if refusal is not None:
                    raise IllegalMove(session_id, refusal)
            elif game.over or int(player) != game.to_play:
                raise IllegalMove(
                    session_id, "game is over" if game.over
                    else f"out of turn pass by player {player}")
            seq = self.seq + 1
            if is_pass:
                self._durable(lambda seg: seg.write(
                    "session_move", seq=seq, session=session_id,
                    player=int(player), elapsed_s=float(elapsed_s),
                    t=time.time(), **{"pass": True}))
            else:
                self._durable(lambda seg: seg.write(
                    "session_move", seq=seq, session=session_id,
                    player=int(player), x=int(x), y=int(y),
                    elapsed_s=float(elapsed_s), t=time.time()))
            self.seq = seq
            if is_pass:
                game.play_pass(int(player), float(elapsed_s))
            else:
                game.play_move(int(x), int(y), int(player),
                               float(elapsed_s))
            self._after_append()
        return seq

    def close_session(self, session_id: str) -> int:
        with self._lock:
            if session_id not in self.games:
                raise SessionNotFound(session_id)
            seq = self.seq + 1
            self._durable(lambda seg: seg.write(
                "session_close", seq=seq, session=session_id,
                t=time.time()))
            self.seq = seq
            self.games.pop(session_id)
            self.closed_sessions += 1
            self._obs_open.set(len(self.games))
            self._after_append()
        return seq

    def _after_append(self) -> None:
        lag = self.seq - self.ckpt_seq
        self._obs_lag.set(lag)
        if lag >= self.checkpoint_every:
            self._checkpoint_locked()

    # -- compaction --------------------------------------------------------

    def checkpoint(self) -> str:
        with self._lock:
            return self._checkpoint_locked()

    def _checkpoint_locked(self) -> str:
        payload = {
            "seq": self.seq,
            "sessions": {sid: g.snapshot()
                         for sid, g in sorted(self.games.items())},
        }
        body = json.dumps(payload, sort_keys=True, separators=(",", ":"))
        digest = hashlib.sha256(body.encode()).hexdigest()
        path = os.path.join(self.root, f"ckpt-{self.seq:012d}.json")
        with atomic_write(path, "w") as f:
            json.dump({"digest": digest, "payload": payload}, f)
        self.ckpt_seq = self.seq
        self._obs_lag.set(0)
        # compaction: every WAL record is now covered by this checkpoint
        # (segments rotate here), so the segments can go
        if self._segment is not None:
            self._segment.close()
            self._segment = None
        for _, wal_path in self._wal_paths():
            try:
                os.unlink(wal_path)
            except OSError:
                pass
        for seq, ckpt_path in self._ckpt_paths()[self.keep_checkpoints:]:
            try:
                os.unlink(ckpt_path)
            except OSError:
                pass
        return path

    # -- lifecycle ---------------------------------------------------------

    def wal_lag(self) -> int:
        with self._lock:
            return self.seq - self.ckpt_seq

    def stats(self) -> dict:
        with self._lock:
            return {
                "open_sessions": len(self.games),
                "seq": self.seq,
                "checkpoint_seq": self.ckpt_seq,
                "wal_lag_records": self.seq - self.ckpt_seq,
                "wal_retries": self.wal_retries,
                "closed_sessions": self.closed_sessions,
                "corrupt_sessions": sorted(self.corrupt),
                "restored_from_checkpoint":
                    list(self.restored_from_checkpoint),
            }

    def close(self, final_checkpoint: bool = True) -> None:
        with self._lock:
            if final_checkpoint and self.seq > self.ckpt_seq:
                self._checkpoint_locked()
            if self._segment is not None:
                self._segment.close()
                self._segment = None
