"""Bulk SGF analysis: a corpus streamed through the fleet's batch tier.

The scan produces one policy annotation per recorded move — log-prob
and rank of the move actually played under the serving policy, plus a
blunder flag when the played move is both low-rank and low-probability
— and is built to coexist with interactive traffic rather than win
against it: every position rides the BATCH tier (headroom 0.3, the
first to shed), door-sheds are absorbed with one bounded-jitter retry
and then recorded as ``shed`` (the scan keeps walking; a surge replica
may pick the load up instead), and progress is a durable per-file
cursor (``cursor.json`` via utils/atomicio) so a killed scan resumes
at the file+move it had finished, never re-annotating and never
skipping.

Positions come from ``go/replay.replay_positions`` — the same pre-move
boards the training pipeline sees — and requests carry a
``session="scan:<file>"`` workload label so captures distinguish
scan-shaped from session-shaped traffic. Annotations stream to
``annotations.jsonl`` (``session_annotation`` records, one
``session_scan`` summary per file).
"""

from __future__ import annotations

import os
import random
import time

import numpy as np

from ..obs.exporter import JsonlSink
from ..obs.registry import get_registry
from ..serving.resilience import full_jitter_delay
from ..utils.atomicio import atomic_write
from .game import SessionError

_SHED = ("EngineOverloaded", "CircuitOpen", "EngineBusy",
         "FleetUnavailable")


class AnalysisCursorError(SessionError):
    """The cursor file exists but is not a cursor."""


class SgfAnalysisService:
    """Resumable corpus scan on the batch tier."""

    def __init__(self, fleet, out_dir: str, tier: str = "batch",
                 timeout_s: float = 0.5, attempts: int = 2,
                 collect_timeout_s: float = 30.0,
                 blunder_top: int = 10, blunder_logp: float = -4.0,
                 sleep=time.sleep, rng: random.Random | None = None,
                 search_sims: int = 0, search_config=None):
        self.fleet = fleet
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.tier = tier
        self.timeout_s = float(timeout_s)
        self.attempts = max(1, int(attempts))
        self.collect_timeout_s = float(collect_timeout_s)
        self.blunder_top = int(blunder_top)
        self.blunder_logp = float(blunder_logp)
        self._sleep = sleep
        self._rng = rng or random.Random(0)
        # search_sims > 0 adds a second-opinion PUCT search on every
        # blunder-flagged move: the annotation gains the search's
        # preferred point and visit count, still on the batch tier so
        # deep verdicts coexist with interactive traffic the same way
        # the plain scan does
        self._searcher = None
        if search_sims > 0 or search_config is not None:
            from ..search import Search, SearchConfig

            cfg = search_config or SearchConfig(
                simulations=search_sims, tier=tier,
                eval_timeout_s=collect_timeout_s)
            self._searcher = Search(fleet, cfg)
        self.cursor_path = os.path.join(out_dir, "cursor.json")
        self.sink = JsonlSink(os.path.join(out_dir, "annotations.jsonl"),
                              buffering=1 << 16)
        self._obs_positions = get_registry().counter(
            "deepgo_session_analysis_positions_total",
            "bulk-scan positions submitted on the batch tier, by "
            "outcome (annotated / shed / timeout / failed)")

    # -- the durable cursor ------------------------------------------------

    def _load_cursor(self) -> dict:
        import json

        try:
            with open(self.cursor_path, encoding="utf-8") as f:
                cur = json.load(f)
        except OSError:
            return {"files": {}}
        except ValueError as e:
            raise AnalysisCursorError(
                f"unreadable cursor {self.cursor_path!r}: {e}") from e
        if not isinstance(cur, dict) or "files" not in cur:
            raise AnalysisCursorError(
                f"{self.cursor_path!r} is not an analysis cursor")
        return cur

    def _save_cursor(self, cursor: dict) -> None:
        import json

        with atomic_write(self.cursor_path, "w") as f:
            json.dump(cursor, f)

    # -- submission --------------------------------------------------------

    def _submit(self, packed, player: int, rank: int, session: str):
        """(future, outcome) — a None future with outcome 'shed' when
        the door refused through every bounded-backoff attempt."""
        last_outcome = "shed"
        for attempt in range(1, self.attempts + 1):
            try:
                return self.fleet.submit(
                    packed, player, rank, tier=self.tier,
                    timeout_s=self.timeout_s, session=session), "ok"
            except Exception as e:  # noqa: BLE001 — classified below
                if type(e).__name__ not in _SHED:
                    raise
                last_outcome = "shed"
            if attempt < self.attempts:
                self._sleep(full_jitter_delay(attempt, 0.01, 0.1,
                                              self._rng))
        return None, last_outcome

    def _search_verdict(self, packed, player: int) -> dict:
        """Search fields for a blunder annotation, or a marker when the
        search itself was shed — the scan never stalls on a verdict."""
        from ..search import game_from_packed

        try:
            res = self._searcher.search(game_from_packed(packed, player))
        except Exception:  # noqa: BLE001 — verdicts are best-effort
            return {"search_move": None}
        if res.move < 0:
            return {"search_move": None,
                    "search_value": round(float(res.value), 4)}
        sx, sy = divmod(int(res.move), 19)
        return {"search_move": [sx, sy],
                "search_value": round(float(res.value), 4),
                "search_simulations": res.simulations}

    # -- the scan ----------------------------------------------------------

    def run(self, sgf_dir: str, limit_files: int | None = None,
            limit_positions: int | None = None) -> dict:
        """Scan ``sgf_dir`` (sorted walk, resumable). Returns the
        report; annotations and per-file summaries are on disk."""
        from ..go.replay import replay_positions
        from ..sgf import parse_file

        cursor = self._load_cursor()
        files = cursor["files"]
        paths: list[str] = []
        for dirpath, dirnames, filenames in os.walk(sgf_dir):
            dirnames.sort()
            paths.extend(os.path.join(dirpath, n)
                         for n in sorted(filenames) if n.endswith(".sgf"))
        report = {"files_seen": len(paths), "files_done": 0,
                  "files_resumed_past": 0, "positions": 0,
                  "annotated": 0, "blunders": 0, "outcomes": {},
                  "stopped_early": False}

        def count(outcome: str) -> None:
            report["outcomes"][outcome] = \
                report["outcomes"].get(outcome, 0) + 1
            self._obs_positions.inc(outcome=outcome)

        scanned_files = 0
        for path in paths:
            rel = os.path.relpath(path, sgf_dir)
            entry = files.get(rel, {"moves": 0, "done": False})
            if entry.get("done"):
                report["files_resumed_past"] += 1
                continue
            if limit_files is not None and scanned_files >= limit_files:
                report["stopped_early"] = True
                break
            scanned_files += 1
            try:
                game = parse_file(path)
            except (OSError, ValueError):
                files[rel] = {"moves": 0, "done": True, "error": "parse"}
                continue
            positions = list(replay_positions(game))
            start = int(entry.get("moves", 0))
            pending = []
            session = f"scan:{rel}"
            budget_hit = False
            for i in range(start, len(positions)):
                if (limit_positions is not None
                        and report["positions"] >= limit_positions):
                    budget_hit = True
                    break
                packed, move = positions[i]
                report["positions"] += 1
                rank = (game.ranks or (5, 5))[move.player - 1]
                fut, outcome = self._submit(packed, int(move.player),
                                            int(rank), session)
                pending.append((i, move, fut, outcome))
            annotated = shed = blunders = 0
            last_move = start - 1
            for i, move, fut, outcome in pending:
                row = None
                if fut is None:
                    pass
                else:
                    try:
                        row = np.asarray(
                            fut.result(timeout=self.collect_timeout_s),
                            dtype=np.float64).reshape(-1)
                        outcome = "ok"
                    except TimeoutError:
                        outcome = "timeout"
                    except Exception as e:  # noqa: BLE001 — an outcome
                        outcome = ("shed" if type(e).__name__ in _SHED
                                   else "failed")
                last_move = i
                if row is None:
                    count(outcome)
                    shed += outcome == "shed"
                    continue
                idx = int(move.x) * 19 + int(move.y)
                logp = float(row[idx])
                move_rank = int((row > logp).sum()) + 1
                blunder = (move_rank > self.blunder_top
                           and logp < self.blunder_logp)
                record = dict(
                    file=rel, move=i, player=int(move.player),
                    x=int(move.x), y=int(move.y), logp=round(logp, 6),
                    rank=move_rank, blunder=blunder)
                if blunder and self._searcher is not None:
                    record.update(self._search_verdict(positions[i][0],
                                                       int(move.player)))
                self.sink.write("session_annotation", **record)
                count("annotated")
                annotated += 1
                blunders += blunder
            done = not budget_hit
            files[rel] = {"moves": last_move + 1, "done": done}
            self.sink.write("session_scan", file=rel,
                            moves=last_move + 1 - start,
                            annotated=annotated, shed=shed,
                            blunders=blunders, done=done)
            report["annotated"] += annotated
            report["blunders"] += blunders
            report["files_done"] += done
            self._save_cursor(cursor)
            if budget_hit:
                report["stopped_early"] = True
                break
        self.sink.flush()
        return report

    def close(self) -> None:
        self.sink.close()
