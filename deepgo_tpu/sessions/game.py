"""Per-session Go game state with FULL move legality.

The ``go/`` rules engine deliberately tracks no ko and allows suicide
(board.py:15-18): it replays *recorded* games whose legality the source
guarantees. An interactive session serves moves from an untrusted
client, so this layer adds what the replay engine omits — on top of the
same capture/liberty primitives, so board evolution stays bit-identical
to ``go/replay.py`` ground truth for any legal move sequence:

  * occupied-point refusal (wrapping the board engine's own check),
  * suicide refusal via ``simulate_play`` (liberties-after == 0),
  * POSITIONAL SUPERKO: a stone play may not recreate any earlier
    (board, side-to-move) pair of this game — stricter than the simple
    ko selfplay.py uses, because a session must refuse the long cycles
    a deterministic client could otherwise drive forever,
  * turn order, and pass handling with pass-pass game end (the SGF
    parser drops passes, so the replay engine never sees them).

Everything a resumed server must reproduce bit-identically — stones,
age, captures, move history, per-player clock, the superko history
itself — lives in the snapshot, and ``digest()`` hashes the canonical
serialization so "resumed bit-identically" is one string comparison.
"""

from __future__ import annotations

import base64
import hashlib
import json

import numpy as np

from ..go.board import (BLACK, EMPTY, SIZE, WHITE, IllegalMoveError,
                        new_board, play, simulate_play)


class SessionError(RuntimeError):
    """Base for typed session-layer errors."""


class IllegalMove(SessionError):
    """A move the rules refuse; ``reason`` says why, for the client."""

    def __init__(self, session_id: str, reason: str):
        super().__init__(f"illegal move in session {session_id!r}: {reason}")
        self.session_id = session_id
        self.reason = reason


def _board_key(stones: np.ndarray, to_play: int) -> str:
    """Superko identity: the stone configuration plus whose turn it is
    (age is derived bookkeeping, not position identity)."""
    return hashlib.sha1(
        stones.tobytes() + bytes([to_play])).hexdigest()


class GoGame:
    """One live game: board, captures, clock, superko history.

    All mutation goes through ``play_move``/``play_pass`` so the WAL
    layer (store.py) can log exactly what it applied; replaying the
    same records through the same methods reconstructs the same state.
    """

    def __init__(self, session_id: str, handicaps: tuple = ()):
        self.session_id = session_id
        self.stones, self.age = new_board()
        self.handicaps = tuple((int(p), int(x), int(y))
                               for p, x, y in handicaps)
        for p, x, y in self.handicaps:
            play(self.stones, self.age, x, y, p)
        # with setup stones on the board, white moves first (free-placement
        # handicap convention); otherwise black
        self.to_play = WHITE if self.handicaps else BLACK
        self.captures = {BLACK: 0, WHITE: 0}
        self.clock_s = {BLACK: 0.0, WHITE: 0.0}
        self.moves: list[dict] = []
        self.passes = 0
        self.over = False
        self.history: set[str] = {_board_key(self.stones, self.to_play)}

    # -- legality ----------------------------------------------------------

    def check_move(self, x: int, y: int, player: int) -> str | None:
        """The refusal reason for playing ``player`` at (x, y) now, or
        None when the move is legal. Pure — never mutates."""
        if self.over:
            return "game is over (two consecutive passes)"
        if player != self.to_play:
            return (f"out of turn: player {player} moved but "
                    f"{self.to_play} is to play")
        if not (0 <= x < SIZE and 0 <= y < SIZE):
            return f"point ({x}, {y}) is off the board"
        if self.stones[x, y] != EMPTY:
            return f"point ({x}, {y}) is occupied"
        _, liberties_after = simulate_play(self.stones, x, y, player)
        if liberties_after == 0:
            return f"suicide at ({x}, {y})"
        trial = self.stones.copy()
        play(trial, None, x, y, player)
        if _board_key(trial, 3 - player) in self.history:
            return (f"positional superko: ({x}, {y}) recreates an "
                    "earlier position of this game")
        return None

    def legal_points(self) -> list[tuple[int, int]]:
        """Every legal stone play for the side to move (empty when only
        a pass remains)."""
        if self.over:
            return []
        return [(x, y) for x in range(SIZE) for y in range(SIZE)
                if self.stones[x, y] == EMPTY
                and self.check_move(x, y, self.to_play) is None]

    # -- mutation ----------------------------------------------------------

    def play_move(self, x: int, y: int, player: int,
                  elapsed_s: float = 0.0) -> int:
        """Apply one legal stone play; returns stones captured. Raises
        typed ``IllegalMove`` (never the board engine's bare error)."""
        reason = self.check_move(x, y, player)
        if reason is not None:
            raise IllegalMove(self.session_id, reason)
        try:
            kills = play(self.stones, self.age, x, y, player)
        except IllegalMoveError as e:  # unreachable after check_move
            raise IllegalMove(self.session_id, str(e)) from e
        self.captures[player] += kills
        self.clock_s[player] = round(
            self.clock_s[player] + float(elapsed_s), 6)
        self.moves.append({"player": int(player), "x": int(x), "y": int(y)})
        self.passes = 0
        self.to_play = 3 - player
        self.history.add(_board_key(self.stones, self.to_play))
        return kills

    def play_pass(self, player: int, elapsed_s: float = 0.0) -> bool:
        """Record a pass; returns True when this pass ends the game."""
        if self.over:
            raise IllegalMove(self.session_id,
                              "game is over (two consecutive passes)")
        if player != self.to_play:
            raise IllegalMove(
                self.session_id,
                f"out of turn: player {player} passed but "
                f"{self.to_play} is to play")
        self.clock_s[player] = round(
            self.clock_s[player] + float(elapsed_s), 6)
        self.moves.append({"player": int(player), "pass": True})
        self.passes += 1
        self.to_play = 3 - player
        if self.passes >= 2:
            self.over = True
        return self.over

    # -- serialization (checkpoints + the bit-identical comparator) --------

    def snapshot(self) -> dict:
        return {
            "session": self.session_id,
            "stones": base64.b64encode(self.stones.tobytes()).decode(),
            "age": base64.b64encode(
                self.age.astype(np.int32).tobytes()).decode(),
            "handicaps": [list(h) for h in self.handicaps],
            "to_play": int(self.to_play),
            "captures": {str(k): int(v) for k, v in self.captures.items()},
            "clock_s": {str(k): float(v) for k, v in self.clock_s.items()},
            "moves": list(self.moves),
            "passes": int(self.passes),
            "over": bool(self.over),
            "history": sorted(self.history),
        }

    @classmethod
    def from_snapshot(cls, snap: dict) -> "GoGame":
        game = cls.__new__(cls)
        game.session_id = str(snap["session"])
        stones = np.frombuffer(base64.b64decode(snap["stones"]),
                               dtype=np.uint8)
        age = np.frombuffer(base64.b64decode(snap["age"]), dtype=np.int32)
        if stones.size != SIZE * SIZE or age.size != SIZE * SIZE:
            raise ValueError(
                f"snapshot for {game.session_id!r} has a malformed board "
                f"({stones.size}/{age.size} points)")
        game.stones = stones.reshape(SIZE, SIZE).copy()
        game.age = age.reshape(SIZE, SIZE).copy()
        game.handicaps = tuple(tuple(h) for h in snap.get("handicaps", ()))
        game.to_play = int(snap["to_play"])
        game.captures = {int(k): int(v)
                         for k, v in snap["captures"].items()}
        game.clock_s = {int(k): float(v)
                        for k, v in snap["clock_s"].items()}
        game.moves = [dict(m) for m in snap["moves"]]
        game.passes = int(snap["passes"])
        game.over = bool(snap["over"])
        game.history = set(snap["history"])
        return game

    def digest(self) -> str:
        """One hash over the full resumable state; two games are
        bit-identical iff their digests match."""
        body = json.dumps(self.snapshot(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(body.encode()).hexdigest()
