"""The interactive game service: sessions in front of the fleet.

One ``GameService`` owns a ``SessionStore`` (durability) and a
``FleetRouter`` (engine replies). A client move is acked only after its
WAL record is fsync'd; the engine's reply then goes through the
INTERACTIVE tier with deadline-tiered per-move budgets — the first
attempt gets the tight deadline, each retry a looser one (the
escalation a human opponent prefers over a refusal), with PR 3-style
bounded full-jitter backoff between attempts. The ``session_reply``
fault site is consulted per attempt, so chaos can brown out exactly
this path; exhaustion surfaces as typed ``ReplyExhausted`` with the
session state untouched (the client simply retries the reply).

Replies are DETERMINISTIC — argmax of the policy logits over the
game's legal points (suicide/superko/occupied already excluded), pass
when no legal point remains — so a resumed server replays to the same
game as an uninterrupted one. Requests are stamped with the ``session``
label for the workload observatory. With ``search_sims > 0`` the reply
is instead a batched PUCT search (deepgo_tpu.search) whose leaf
evaluations ride the same fleet tier; the search's anytime contract
returns a legal move within the final deadline tier, and any search
failure degrades to the plain argmax path rather than losing the move.
"""

from __future__ import annotations

import random
import time

import numpy as np

from ..analysis.lockcheck import make_lock
from ..go.board import SIZE
from ..go.summarize import summarize
from ..obs.registry import get_registry
from ..serving.resilience import full_jitter_delay
from ..utils import faults
from .game import SessionError
from .store import SessionStore

# deadline tiers for one engine reply: attempt k gets budget[k] seconds
# end-to-end (submit admission + queue + forward). Escalating budgets
# convert a transient stall into one slower reply instead of a refusal.
DEFAULT_BUDGETS_S = (0.25, 0.5, 1.5)


class ReplyExhausted(SessionError):
    """Every deadline-tiered reply attempt failed; the session is
    unchanged and the reply can be retried."""

    def __init__(self, session_id: str, attempts: int, last: str):
        super().__init__(
            f"engine reply for session {session_id!r} exhausted "
            f"{attempts} deadline-tiered attempt(s); last: {last}")
        self.session_id = session_id
        self.attempts = attempts


class GameService:
    """Interactive play over a durable store and a serving fleet."""

    def __init__(self, fleet, store: SessionStore,
                 tier: str = "interactive",
                 budgets_s: tuple = DEFAULT_BUDGETS_S, rank: int = 5,
                 sleep=time.sleep, rng: random.Random | None = None,
                 search_sims: int = 0, search_config=None, metrics=None):
        if not budgets_s:
            raise ValueError("budgets_s needs at least one deadline tier")
        self.fleet = fleet
        self.store = store
        self.tier = tier
        self.budgets_s = tuple(float(b) for b in budgets_s)
        self.rank = int(rank)
        self._sleep = sleep
        self._rng = rng or random.Random(0)
        # search_sims > 0 puts a PUCT search (deepgo_tpu.search) behind
        # every engine reply: leaf evaluations ride the same fleet on
        # the interactive tier, the reply deadline is the LAST budget
        # tier (the anytime contract absorbs mid-search failures the
        # retry ladder would otherwise pay for), and the move is still
        # deterministic and always legal for the session's superko rules
        # (the search only picks inside the game's own legal set)
        self._searcher = None
        if search_sims > 0 or search_config is not None:
            from ..search import Search, SearchConfig

            cfg = search_config or SearchConfig(
                simulations=search_sims, tier=tier, rank=int(rank),
                deadline_s=self.budgets_s[-1])
            self._searcher = Search(fleet, cfg, metrics=metrics)
        self._lock = make_lock("sessions.service")
        self._opened = 0
        self.reply_retries = 0
        self.replies = 0
        reg = get_registry()
        self._obs_moves = reg.counter(
            "deepgo_session_moves_total",
            "durably acked session moves, by source "
            "(client / engine / pass)")
        self._obs_replies = reg.counter(
            "deepgo_session_replies_total",
            "engine reply attempts on the interactive tier, by outcome")

    # -- lifecycle ---------------------------------------------------------

    def new_game(self, session_id: str | None = None,
                 handicaps: tuple = ()) -> str:
        with self._lock:
            if session_id is None:
                session_id = f"g{self._opened:05d}"
            self._opened += 1
        self.store.open_session(session_id, handicaps)
        return session_id

    def resign(self, session_id: str) -> int:
        return self.store.close_session(session_id)

    def state(self, session_id: str) -> dict:
        return self.store.get(session_id).snapshot()

    # -- the client move ---------------------------------------------------

    def play(self, session_id: str, x: int | None, y: int | None,
             elapsed_s: float = 0.0, reply: bool = True) -> dict:
        """Apply one client move (``x is None`` = pass); ack is durable
        on return. With ``reply=True`` the engine answers on the
        interactive tier unless the client's move ended the game."""
        game = self.store.get(session_id)
        player = game.to_play
        is_pass = x is None
        seq = self.store.append_move(session_id, player, x=x, y=y,
                                     is_pass=is_pass,
                                     elapsed_s=elapsed_s)
        self._obs_moves.inc(source="pass" if is_pass else "client")
        out = {"session": session_id, "seq": seq, "player": player,
               "over": game.over}
        if reply and not game.over:
            out["reply"] = self.engine_reply(session_id)
            out["over"] = game.over
        return out

    # -- the engine reply --------------------------------------------------

    def engine_reply(self, session_id: str,
                     elapsed_s: float = 0.0) -> dict:
        game = self.store.get(session_id)
        if game.over:
            raise SessionError(
                f"session {session_id!r} is over; nothing to reply to")
        player = game.to_play
        legal = game.legal_points()
        if not legal:
            seq = self.store.append_move(session_id, player, is_pass=True,
                                         elapsed_s=elapsed_s)
            self._obs_moves.inc(source="pass")
            return {"session": session_id, "seq": seq, "player": player,
                    "pass": True, "over": game.over}
        packed = summarize(game.stones, game.age)
        idx = np.array([x * SIZE + y for x, y in legal], dtype=np.int64)
        pick, extra = -1, {}
        if self._searcher is not None:
            pick, extra = self._search_reply(packed, player, idx)
        if pick < 0 or not (0 <= pick < SIZE * SIZE) or pick not in idx:
            row = self._forward(session_id, packed, player)
            masked = np.full(SIZE * SIZE, -np.inf, dtype=np.float64)
            masked[idx] = np.asarray(row,
                                     dtype=np.float64).reshape(-1)[idx]
            pick = int(masked.argmax())
        x, y = divmod(pick, SIZE)
        seq = self.store.append_move(session_id, player, x=x, y=y,
                                     elapsed_s=elapsed_s)
        self._obs_moves.inc(source="engine")
        self.replies += 1
        out = {"session": session_id, "seq": seq, "player": player,
               "x": x, "y": y, "over": game.over}
        out.update(extra)
        return out

    def _search_reply(self, packed, player: int, idx) -> tuple[int, dict]:
        """One PUCT search for the reply move. The session's own legal
        set (superko-aware) is the root mask, so the search can only
        pick moves the game accepts; any search failure degrades to the
        plain deadline-tiered argmax path rather than losing the move."""
        from ..search import game_from_packed

        root_legal = np.zeros(SIZE * SIZE, dtype=bool)
        root_legal[idx] = True
        try:
            res = self._searcher.search(game_from_packed(packed, player),
                                        root_legal=root_legal)
        except Exception:  # noqa: BLE001 — anytime: argmax still replies
            self._obs_replies.inc(outcome="search_failed")
            return -1, {}
        self._obs_replies.inc(outcome="search")
        extra = {"search": {"search_id": res.search_id,
                            "value": round(float(res.value), 4),
                            "simulations": res.simulations,
                            "deadline_met": res.deadline_met,
                            "pv": res.pv[:8]}}
        return int(res.move), extra

    def _forward(self, session_id: str, packed, player: int):
        """One policy forward under deadline-tiered budgets. Absorbable
        failures (shed, deadline, transient injection) burn one tier
        and back off full-jitter; anything else surfaces typed."""
        last: BaseException | None = None
        for attempt, budget_s in enumerate(self.budgets_s, start=1):
            try:
                faults.check("session_reply")
                fut = self.fleet.submit(packed, player, self.rank,
                                        tier=self.tier,
                                        timeout_s=budget_s,
                                        session=session_id)
                row = fut.result(timeout=budget_s + 5.0)
                self._obs_replies.inc(outcome="ok")
                return row
            except faults.InjectedFailure:
                self._obs_replies.inc(outcome="failed")
                raise  # a hard injected fault is not a deadline problem
            except (TimeoutError, OSError) as e:
                last = e  # deadline verdicts + transient injections
            except Exception as e:  # noqa: BLE001 — classified below
                if type(e).__name__ not in ("EngineOverloaded",
                                            "CircuitOpen", "EngineBusy",
                                            "FleetUnavailable"):
                    self._obs_replies.inc(outcome="failed")
                    raise
                last = e  # shed: the next tier gets more headroom
            self._obs_replies.inc(outcome="retry")
            with self._lock:
                self.reply_retries += 1
            if attempt < len(self.budgets_s):
                self._sleep(full_jitter_delay(attempt, 0.02, 0.2,
                                              self._rng))
        self._obs_replies.inc(outcome="exhausted")
        raise ReplyExhausted(session_id, len(self.budgets_s),
                             repr(last)) from last

    # -- observability -----------------------------------------------------

    def health(self) -> dict:
        """The composed-health component for ``cli serve --sessions``:
        healthy while no session is irrecoverably corrupt and the WAL
        lag stays under one full checkpoint interval of backlog."""
        s = self.store.stats()
        lag = s["wal_lag_records"]
        healthy = (not s["corrupt_sessions"]
                   and lag <= 2 * self.store.checkpoint_every)
        return {"healthy": healthy, "open_sessions": s["open_sessions"],
                "wal_lag_records": lag,
                "corrupt_sessions": len(s["corrupt_sessions"]),
                "reply_retries": self.reply_retries}

    def stats(self) -> dict:
        with self._lock:
            out = {"replies": self.replies,
                   "reply_retries": self.reply_retries}
        out.update(self.store.stats())
        return out

    def close(self) -> None:
        self.store.close()
