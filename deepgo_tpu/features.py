"""Feature schema: packed on-disk records and the 37-plane model encoding.

Two layers of representation, exactly mirroring the reference's split between
what is stored at transcription time and what the network consumes
(reference dataloader.lua:4-92):

**Packed record** (on disk / host->device transfer): (9, 19, 19) uint8 —
see ``deepgo_tpu.go.summarize`` for channel semantics. At ~3.2 KB per
position this is ~16x smaller than the expanded planes, so expansion happens
*on device inside the jitted step* (``deepgo_tpu.ops.expand``); this module
holds the layout constants plus a NumPy reference expansion used by tests
and CPU-only paths.

**Expanded planes** (model input): (37, 19, 19), all binary, from the
to-move player's perspective (reference preprocess, dataloader.lua:50-92):

  planes 0-2    point is empty / mine / opponent's
  planes 3-6    chain liberties == 1, 2, 3, >= 4
  planes 7-13   my liberties-after-playing == 0 (legal-ish empty points
                only), 1, 2, 3, 4, 5, >= 6
  planes 14-20  my kills-by-playing == 1..6, >= 7
  planes 21-25  point age == 1..5
  plane  26     I can launch a working ladder capture here
  plane  27     always zero (the reference's RANK base plane is written only
                at RANK + rank with rank >= 1, dataloader.lua:12,87 — kept
                for bit-parity)
  planes 28-36  one-hot full-plane encoding of my dan rank (1..9)

The training target for a move at 0-based (x, y) is class ``19*x + y``
(reference dataloader.lua:89, shifted to 0-based).
"""

from __future__ import annotations

import numpy as np

from . import BOARD_SIZE

# ---- packed record channel layout (write side) ----
P_STONES = 0
P_LIBERTIES = 1
P_LIB_AFTER = 2  # 2 channels, per player
P_KILLS = 4  # 2 channels, per player
P_AGE = 6
P_LADDERS = 7  # 2 channels, per player
PACKED_CHANNELS = 9

# ---- expanded plane layout (model input) ----
X_STONE = 0  # 3 planes
X_LIBERTIES = 3  # 4 planes
X_LIB_AFTER = 7  # 7 planes
X_KILLS = 14  # 7 planes
X_AGE = 21  # 5 planes
X_LADDER = 26  # 1 plane
X_RANK_BASE = 27  # rank r occupies plane 27 + r; plane 27 itself stays zero
NUM_PLANES = 37


def target_index(x: int, y: int) -> int:
    """0-based move coordinates -> class index in [0, 361)."""
    return BOARD_SIZE * x + y


def expand_planes_np(
    packed: np.ndarray, player: int, rank: int, dtype=np.float32
) -> np.ndarray:
    """NumPy reference expansion of one packed record to the 37 model planes.

    ``player`` is the player to move (1 or 2); ``rank`` their dan rank (1..9).
    The jitted batched equivalent lives in ``deepgo_tpu.ops.expand``; tests
    assert they agree.
    """
    assert packed.shape == (PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE)
    out = np.zeros((NUM_PLANES, BOARD_SIZE, BOARD_SIZE), dtype=dtype)

    stones = packed[P_STONES]
    empty = stones == 0
    out[X_STONE + 0] = empty
    out[X_STONE + 1] = stones == player
    out[X_STONE + 2] = stones == 3 - player

    libs = packed[P_LIBERTIES]
    for i in range(3):
        out[X_LIBERTIES + i] = libs == i + 1
    out[X_LIBERTIES + 3] = libs >= 4

    lib_after = packed[P_LIB_AFTER + player - 1]
    out[X_LIB_AFTER + 0] = empty & (lib_after == 0)
    for i in range(1, 6):
        out[X_LIB_AFTER + i] = lib_after == i
    out[X_LIB_AFTER + 6] = lib_after >= 6

    kills = packed[P_KILLS + player - 1]
    for i in range(6):
        out[X_KILLS + i] = kills == i + 1
    out[X_KILLS + 6] = kills >= 7

    age = packed[P_AGE]
    for i in range(5):
        out[X_AGE + i] = age == i + 1

    out[X_LADDER] = packed[P_LADDERS + player - 1] >= 1

    assert 1 <= rank <= 9
    out[X_RANK_BASE + rank] = 1.0
    return out
