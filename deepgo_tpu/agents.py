"""Agent zoo: batched move selection for matches, self-play, and corpora.

The reference paper's headline evaluation is win rate of the raw policy
net against an opponent (97% vs GnuGo, README.md:5 / arXiv:1412.6564).
These are the players that evaluation machinery (deepgo_tpu.match) runs:
scripted baselines (random / capture-greedy heuristic / 1-ply tactical),
the trained policy net, and the search family that uses the policy as a
pruning prior (1-ply tactical veto, realized-outcome 2-ply, value-net
guided) — each selecting moves for a whole fleet of boards per call, one
TPU forward per ply for the net-backed agents.

Split out of deepgo_tpu.arena (which remains as a compatibility shim
re-exporting everything) when the module crossed 750 lines.
"""

from __future__ import annotations

import numpy as np

import jax

from .features import P_KILLS, P_LIB_AFTER
from .models import policy_cnn
from .selfplay import batched_log_probs, legal_mask, select_from_log_probs


class Agent:
    """Batched move selection: packed boards in, move indices out (-1 = pass)."""

    name = "agent"

    def select_moves(self, packed: np.ndarray, players: np.ndarray,
                     legal: np.ndarray, rng: np.random.Generator) -> np.ndarray:
        raise NotImplementedError


def _no_own_eyes(packed, players, legal):
    """Mask single-point own eyes (all 4 neighbors own stones) from legal.

    Without this, stone-placing baselines fill their own territory forever
    and every game truncates at the move cap; with it they run out of
    sensible moves, pass, and games end properly for scoring (the standard
    naive-rollout eye rule; diagonals deliberately ignored).
    """
    from .features import P_STONES

    n = len(packed)
    stones = packed[:, P_STONES].astype(np.int8)
    own = stones == players[:, None, None]
    # a padded neighbor counts as "own" so edge/corner eyes are masked too
    padded = np.ones((n, 21, 21), dtype=bool)
    padded[:, 1:20, 1:20] = own
    eye = (padded[:, :19, 1:20] & padded[:, 2:, 1:20]
           & padded[:, 1:20, :19] & padded[:, 1:20, 2:])
    return legal & ~eye.reshape(n, -1)


def _argmax_random_tiebreak(score: np.ndarray, legal: np.ndarray,
                            rng: np.random.Generator) -> np.ndarray:
    """Per-row argmax of integer ``score`` over ``legal`` points, ties
    broken uniformly, -1 where nothing is legal — vectorized.

    Adding iid U(0,1) noise to integer-valued scores keeps the order
    between distinct scores (gaps >= 1) while the argmax over a tie set
    follows the noise alone, i.e. uniform over the ties — one argmax for
    the whole batch instead of a flatnonzero + rng.choice Python loop per
    game (the hot loop once move application went native).
    """
    noisy = np.where(legal, score.astype(np.float64) + rng.random(score.shape),
                     -np.inf)
    moves = noisy.argmax(axis=1)
    return np.where(legal.any(axis=1), moves, -1)


class RandomAgent(Agent):
    name = "random"

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        return _argmax_random_tiebreak(
            np.zeros(legal.shape, dtype=np.int64), legal, rng)


class HeuristicAgent(Agent):
    """Capture-greedy: max kills, then max liberties-after, random tie-break."""

    name = "heuristic"

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        n = len(packed)
        idx = np.arange(n)
        kills = packed[idx, P_KILLS + players - 1].reshape(n, -1).astype(np.int64)
        libs = packed[idx, P_LIB_AFTER + players - 1].reshape(n, -1).astype(np.int64)
        # lexicographic (kills, libs, random tie-break) over legal points
        return _argmax_random_tiebreak((kills << 20) + (libs << 10), legal, rng)


class OnePlyAgent(Agent):
    """1-ply lookahead over every packed tactical channel.

    Stronger than HeuristicAgent (71.5% head-to-head over 200 games,
    seed 7, 6 truncated — RESULTS.md win-rate table; tests/test_arena.py
    checks the vs-random floor): for each legal point it weighs, from the
    to-move player's perspective,
      * stones captured by playing there (P_KILLS, own channel),
      * stones SAVED by playing there — the opponent's capture count at the
        same point (P_KILLS, opponent channel): occupying it denies the
        capture,
      * working ladder captures (P_LADDERS, own channel),
      * own liberties after the move, with a self-atari penalty
        (P_LIB_AFTER own channel <= 1), and
      * denial of high-liberty points to the opponent (P_LIB_AFTER,
        opponent channel).
    This is exactly the evaluation a 1-ply search over the feature
    extractor's hypothetical-play data supports (reference
    count_kills_and_liberties, makedata.lua:304-327) without replaying
    moves; the round-1 verdict asked for it as an informative third
    baseline (GnuGo is unavailable: zero egress).
    """

    name = "oneply"

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        return _argmax_random_tiebreak(_oneply_scores(packed, players)[0],
                                       legal, rng)


# Tactical tier weights, shared by every scoring agent (OnePly, veto,
# 2-ply). One table so the agents' arithmetic cannot desynchronize — the
# 2-ply differential in particular relies on W_KILL being identical in
# its gain and threat terms.
W_KILL = 1000      # per stone captured by playing here
W_SAVE = 700       # per own stone the opponent could capture here (1-ply
#                    speculative save credit; TwoPlyAgent deliberately
#                    scores saves through the threat delta instead)
W_LADDER = 400     # per stone capturable via a working ladder from here
W_LIB = 12         # own liberties after playing here
W_OPP_LIB = 6      # opponent liberties denied
W_SELF_ATARI = 900 # penalty for leaving own chain at <= 1 liberty


def _tactical_grids(packed: np.ndarray, players: np.ndarray):
    """The five (n, 361) int64 planes every tactical score derives from:
    (my_kills, opp_kills, my_libs, opp_libs, my_ladders), each read from
    the summarizer's per-player channels for the side to move."""
    from .features import P_LADDERS

    n = len(packed)
    idx = np.arange(n)
    mine, theirs = players - 1, 2 - players
    flat = lambda ch: packed[idx, ch].reshape(n, -1).astype(np.int64)  # noqa: E731
    return (flat(P_KILLS + mine), flat(P_KILLS + theirs),
            flat(P_LIB_AFTER + mine), flat(P_LIB_AFTER + theirs),
            flat(P_LADDERS + mine))


def _oneply_scores(packed: np.ndarray, players: np.ndarray,
                   grids=None) -> tuple[np.ndarray, np.ndarray]:
    """OnePlyAgent's tactical evaluation as two (n, 361) int64 grids.

    Returns ``(score, forcing)``: the full evaluation, and its
    capture/save/ladder component alone — the part that identifies a
    genuinely forcing move, free of the positional liberty terms (which
    can reach hundreds next to a big group). Shared by OnePlyAgent
    (argmax of ``score`` over all legal points) and PolicySearchAgent
    (re-ranking of policy candidates; urgency from ``forcing``). Pass
    ``grids`` (a ``_tactical_grids`` result) to reuse planes the caller
    already extracted."""
    my_kills, opp_kills, my_libs, opp_libs, ladders = (
        grids if grids is not None else _tactical_grids(packed, players))
    forcing = W_KILL * my_kills + W_SAVE * opp_kills + W_LADDER * ladders
    score = (forcing + W_LIB * my_libs + W_OPP_LIB * opp_libs
             - W_SELF_ATARI * (my_libs <= 1))
    return score, forcing


class PolicyAgent(Agent):
    """The trained CNN, one batched TPU forward per ply.

    ``engine`` (a serving.InferenceEngine over the same params) reroutes
    inference through the shared micro-batching engine: this agent's
    batch dissolves into per-board requests that coalesce with every
    other submitter's — both sides of a self-match, a selfplay fleet, an
    eval frontend — into one saturated padded dispatch. Without an
    engine the agent pads its own batch onto the bucket ladder directly
    (same shapes, same bit-identical rows, no dispatcher thread).
    """

    def __init__(self, params, cfg: policy_cnn.ModelConfig, name: str = "policy",
                 temperature: float = 0.0, pass_threshold: float = 1e-4,
                 rank: int = 9, engine=None):
        from .models.serving import make_policy_fn

        self.params = params
        self.cfg = cfg
        self.name = name
        self.temperature = temperature
        self.pass_threshold = pass_threshold
        self.rank = rank
        self.engine = engine
        self._predict = make_policy_fn(cfg, top_k=1)

    def _legal_log_probs(self, packed, players, legal) -> np.ndarray:
        """One batched forward -> log-probs with illegal points at -inf."""
        ranks = np.full(len(packed), self.rank, dtype=np.int32)
        if self.engine is not None:
            logp = self.engine.evaluate(packed, players, ranks)
        else:
            logp = batched_log_probs(self._predict, self.params, packed,
                                     players, ranks)
        return np.where(legal, logp, -np.inf)

    def select_moves(self, packed, players, legal, rng):
        logp = self._legal_log_probs(packed, players, legal)
        moves = np.full(len(packed), -1, dtype=np.int64)
        for i in range(len(packed)):
            moves[i] = select_from_log_probs(logp[i], self.temperature,
                                             self.pass_threshold, rng)
        return moves


class PolicySearchAgent(PolicyAgent):
    """Policy move with a tactical veto — the policy/search combine.

    On a quiet board the agent plays the net's argmax move unchanged. Only
    when a FORCING move exists — the capture/save/ladder component of the
    1-ply evaluation (``_oneply_scores``, positional liberty terms
    excluded) reaches ``urgent`` (default 400: a working ladder or
    better) — does the tactical evaluation take over: the forcing moves
    plus the policy's ``top_k`` candidates are re-ranked by tactical
    score, with the policy probability as tie-break (tactical tiers are
    integers >= 1 apart; a probability in (0, 1] never reorders distinct
    tiers). A live forcing move also vetoes the pass rule; otherwise the
    agent passes exactly when the net's best eye-masked legal move falls
    below ``pass_threshold``.

    Deferring to tactics ONLY on forcing boards is load-bearing:
    re-ranking every move imposes the 1-ply searcher's own style and
    drags a policy that already beats it back toward its level (measured
    60.5% -> 51.0% vs oneply for the winner-fine-tuned net), while the
    veto design preserves the policy's play and only patches its
    blunders (60.5% -> 69.5%; and it lifts a weak pure imitator from
    2.5% -> 45.5% — RESULTS.md win-rate tables, which also state the
    ±~4-point tie-break/binomial noise at 200 games).

    The agent is deterministic given the position; ``rng`` only breaks
    exact score ties, so ``--temperature`` is rejected for ``search:``
    specs rather than silently ignored. This is the cheapest instance of
    the policy-guides-search pattern the paper points at
    (arXiv:1412.6564 §Conclusion: the policy net as a search prior); one
    TPU forward plus one vectorized host check per ply, no tree.
    """

    def __init__(self, params, cfg, name: str = "policy-search",
                 top_k: int = 8, urgent: int = 400, **kw):
        if kw.get("temperature", 0.0):
            raise ValueError("PolicySearchAgent is a deterministic "
                             "re-ranker; temperature is not supported")
        super().__init__(params, cfg, name=name, **kw)
        self.top_k = top_k
        self.urgent = urgent

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        logp = self._legal_log_probs(packed, players, legal)
        tact, forcing = _oneply_scores(packed, players)
        urgent = legal & (forcing >= self.urgent)
        has_urgent = urgent.any(axis=1)
        moves = np.where(legal.any(axis=1), logp.argmax(axis=1), -1)
        if has_urgent.any():
            # re-rank only the rows with a live forcing move — most Go
            # positions are quiet, so the partition/exp work is skipped
            # for the typical all-quiet ply
            cand = _topk_mask(logp, legal, self.top_k) | urgent
            # prob in (0, 1] breaks tactical ties without reordering
            # integer tiers; sub-ulp rng noise breaks exact ties uniformly
            prob = np.exp(logp) + rng.random(logp.shape) * 1e-9
            score = np.where(cand, tact.astype(np.float64) + prob, -np.inf)
            rerank = np.where(cand.any(axis=1), score.argmax(axis=1), -1)
            moves = np.where(has_urgent, rerank, moves)
        # pass when the policy itself would (best legal move below the
        # pass threshold) — unless something forcing is on the board
        best_p = np.exp(logp.max(axis=1, initial=-np.inf))
        do_pass = (best_p < self.pass_threshold) & ~has_urgent
        return np.where(do_pass, -1, moves)


def _topk_mask(logp: np.ndarray, legal: np.ndarray, top_k: int) -> np.ndarray:
    """(n, 361) bool: the top-k log-prob legal points per row. Rows with
    fewer than k legal moves get a kth value of -inf, which admits every
    legal move — the right degradation. Shared by the 1-ply re-ranker and
    the 2-ply candidate set so the rule cannot drift between them."""
    k = min(top_k, logp.shape[1])
    kth = np.partition(logp, -k, axis=1)[:, -k][:, None]
    return legal & (logp >= kth)


def _apply_and_summarize(stones: np.ndarray, age: np.ndarray,
                         moves: np.ndarray, players: np.ndarray
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Apply one move per board in place; return (new packed, ko points).

    Native batched path when the C++ engine is loaded (one FFI crossing for
    the whole fleet); otherwise the tested Python GameState/apply_move
    logic per board. ko[i] is the flat index banned for the opponent's
    immediate recapture, -1 if none.
    """
    from .go import native

    if native.batch_available():
        ko = native.play_batch_native(stones, age, moves, players)
        return native.summarize_batch_native(stones, age), ko
    from .selfplay import GameState, apply_move, summarize_state

    ko = np.full(len(moves), -1, dtype=np.int32)
    packed = np.empty((len(moves), 9, 19, 19), dtype=np.uint8)
    for i in range(len(moves)):
        g = GameState()
        g.stones[:], g.age[:], g.player = stones[i], age[i], int(players[i])
        apply_move(g, *divmod(int(moves[i]), 19))
        stones[i], age[i] = g.stones, g.age
        if g.ko_point is not None:
            ko[i] = g.ko_point[0] * 19 + g.ko_point[1]
        packed[i] = summarize_state(g)
    return packed, ko


def _play_candidates(packed, players, legal, logp, forcing, top_k,
                     urgent_threshold):
    """Candidate set + played after-boards, shared by every deep searcher.

    Returns ``(urgent, cand, rows, cols, after, ko)``: the forcing-point
    mask, the candidate mask (policy top-k | urgent), the candidates in
    nonzero order, and each candidate's after-board + ko point (``after``
    is None when no board has a candidate). One definition so the
    candidate-set rule cannot drift between search agents.
    """
    from .features import P_AGE, P_STONES

    urgent = legal & (forcing >= urgent_threshold)
    cand = _topk_mask(logp, legal, top_k) | urgent
    rows, cols = np.nonzero(cand)
    if rows.size == 0:
        return urgent, cand, rows, cols, None, None
    stones = packed[rows, P_STONES].astype(np.uint8).copy()
    age = packed[rows, P_AGE].astype(np.int32)
    after, ko = _apply_and_summarize(stones, age, cols.astype(np.int32),
                                     players[rows].astype(np.int32))
    return urgent, cand, rows, cols, after, ko


def _veto_select(logp, legal, cand, rows, cols, cand_scores, margin, urgent,
                 pass_threshold, rng, tie_scale=1.0):
    """Differential-veto move selection, shared by every deep searcher.

    ``cand_scores`` aligns with (rows, cols). The policy argmax is kept
    unless some candidate beats ITS score by ``margin``; the pass rule is
    PolicySearchAgent's (policy below threshold, nothing forcing, veto not
    firing). ``tie_scale`` sizes the policy-prob tie-break relative to the
    score units (1.0 for integer tactical tiers, sub-margin for win-prob
    scores).
    """
    n, p = logp.shape
    any_legal = legal.any(axis=1)
    policy_move = np.where(any_legal, logp.argmax(axis=1), -1)
    score = np.full((n, p), -np.inf)
    score[rows, cols] = cand_scores
    score += np.where(cand,
                      tie_scale * (np.exp(logp) + rng.random(logp.shape)
                                   * 1e-9),
                      0.0)
    best = score.argmax(axis=1)
    best_val = score.max(axis=1)
    pol_val = np.where(any_legal, score[np.arange(n), policy_move], -np.inf)
    fire = any_legal & (best_val >= pol_val + margin)
    moves = np.where(fire, best, policy_move)
    # pass exactly when PolicySearchAgent would: policy below the pass
    # threshold AND nothing forcing on the board AND no override. Without
    # the urgency veto, a settled endgame whose argmax IS a live capture
    # would pass over dead stones and hand them to the opponent under
    # area scoring.
    best_p = np.exp(logp.max(axis=1, initial=-np.inf))
    do_pass = (best_p < pass_threshold) & ~fire & ~urgent.any(axis=1)
    return np.where(do_pass, -1, moves)


class TwoPlyAgent(PolicySearchAgent):
    """Policy-pruned 2-ply search: candidates from the net, replies refuted.

    The expert-iteration study (RESULTS.md) showed the strength loop
    saturating because the 1-ply veto expert caps what distillation can
    teach; this agent is the next expert up. Per board it

      1. takes the policy's ``top_k`` moves plus every live forcing move as
         the candidate set (the policy as search prior, arXiv:1412.6564
         §Conclusion — the same pruning role the paper projects),
      2. PLAYS each candidate on a copy of the board (batched native move
         application across the whole fleet x candidate set), and
      3. scores it by REALIZED outcome: the captures/ladders/liberty shape
         the move itself achieves, minus the material the opponent's best
         reply takes on the resulting board (immediate captures + working
         ladders, ko-banned reply excluded) — so snapbacks, self-ataris
         beyond the immediate stone, and captures that hand back a bigger
         recapture are all seen, which the purely-static OnePlyAgent
         cannot do (reference analogue: count_kills_and_liberties,
         makedata.lua:304-327, is exactly one hypothetical ply deep).

    Deliberately NOT in a candidate's own gain: the 1-ply 700-point
    "save" term (``_oneply_scores``' opponent-kills channel). A save is
    speculative — it only worked if the capture threat is actually gone
    from the after-board, which is exactly what the threat term measures.
    Crediting saves up front made the first build of this agent chase
    doomed groups (save k stones -> still capturable as k+1 -> save again
    ...), escalating the horizon effect until it lost every head-to-head
    game against the 1-ply veto agent with half the matches hitting the
    move cap (0/200, measured round 4). Under realized-outcome scoring a
    futile save scores ~-1000(k+1) while the quiet policy move scores
    ~-1000k: giving the group up is correctly preferred, and a WORKING
    save (threat drops to zero) fires on its own merits. Pre-existing
    threats cancel out of the differential veto entirely — both sides of
    the comparison face the same standing board.

    The policy keeps the move unless its own candidate is REFUTED: the best
    candidate must beat the policy move's 2-ply score by ``margin``
    (default 500, half a capture tier) for the search to take over. This
    differential veto generalizes round 3's forcing-move veto — blanket
    re-ranking measurably drags a strong policy down to its evaluator's
    level (RESULTS.md), so the agent only overrides on a demonstrated
    tactical blunder.
    """

    name = "twoply-search"

    def __init__(self, params, cfg, name: str = "twoply-search",
                 margin: int = 500, **kw):
        super().__init__(params, cfg, name=name, **kw)
        self.margin = margin

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        logp = self._legal_log_probs(packed, players, legal)
        grids = _tactical_grids(packed, players)
        _, forcing1 = _oneply_scores(packed, players, grids)
        urgent, cand, rows, cols, after, ko = _play_candidates(
            packed, players, legal, logp, forcing1, self.top_k, self.urgent)
        if after is None:
            any_legal = legal.any(axis=1)
            return np.where(any_legal, logp.argmax(axis=1), -1)

        # realized 1-ply gain: captures, working ladders, liberty shape —
        # WITHOUT the speculative save term (see class docstring)
        my_kills, _, my_libs, opp_libs, ladders = grids
        gain = (W_KILL * my_kills + W_LADDER * ladders + W_LIB * my_libs
                + W_OPP_LIB * opp_libs - W_SELF_ATARI * (my_libs <= 1))

        # measure the material the opponent's best legal reply actually
        # takes on each after-board (immediate captures + working ladders;
        # ko-banned reply excluded)
        opp = (3 - players[rows]).astype(np.int32)
        midx = np.arange(len(rows))
        reply_kills, _, _, _, reply_ladders = _tactical_grids(after, opp)
        reply_take = W_KILL * reply_kills + W_LADDER * reply_ladders
        reply_legal = legal_mask(after, opp)
        banned = ko >= 0
        reply_legal[midx[banned], ko[banned]] = False
        threat = np.where(reply_legal, reply_take, 0).max(axis=1)

        # realized-outcome 2-ply score: what the move takes minus what the
        # best reply takes back; standing threats hit every candidate's
        # after-board alike and so cancel out of the differential veto
        return _veto_select(logp, legal, cand, rows, cols,
                            gain[rows, cols].astype(np.float64) - threat,
                            self.margin, urgent, self.pass_threshold, rng)


class ValueSearchAgent(PolicySearchAgent):
    """Policy-pruned 1-ply search over a LEARNED evaluation (``value:`` spec).

    The round-4 expert-iteration study's conclusion (RESULTS.md): a
    constant tactical wrapper saturates the self-improvement loop after
    one distillation round — climbing further needs an evaluation whose
    quality grows with training. This agent is that next rung's
    scaffold: candidates are the policy's top-k plus every forcing
    point (the same pruning as the tactical searchers), each candidate
    is PLAYED (batched native stepping), and the score is the value
    network's win probability for the mover on the after-board
    (1 - P(opponent-to-move wins), models/value_cnn.py). The
    differential veto fires only when some candidate beats the policy
    move's own after-board value by ``margin`` win-probability (default
    0.08) — the same only-override-demonstrated-blunders asymmetry the
    tactical sweeps showed is optimal.

    Known approximations, documented not hidden: the value net does not
    see the ko ban on the after-board, and a net trained on
    mixed-rank corpora can lean on the rank planes (equal-rank matches
    force it onto board features).
    """

    name = "value-search"

    def __init__(self, params, cfg, value_params, value_cfg,
                 name: str = "value-search", margin: float = 0.08,
                 value_engine=None, **kw):
        from .models.serving import make_value_fn

        super().__init__(params, cfg, name=name, **kw)
        self.value_params = value_params
        self.value_cfg = value_cfg
        self.margin = margin
        self.value_engine = value_engine
        self._win_prob = make_value_fn(value_cfg)

    def _values(self, boards: np.ndarray, to_move: np.ndarray) -> np.ndarray:
        """P(side ``to_move`` wins) per board, padded onto the serving
        bucket ladder so the jitted value forward only ever sees
        precompiled shapes (the same guard as selfplay.batched_log_probs;
        the candidate count varies ply to ply). With a ``value_engine``
        the boards ride the shared micro-batching engine instead, so a
        2-ply search's leaf evaluations coalesce with every other value
        consumer's dispatches."""
        to_move = to_move.astype(np.int32)
        ranks = np.full(len(boards), self.rank, dtype=np.int32)
        if self.value_engine is not None:
            return self.value_engine.evaluate(boards, to_move, ranks)
        from .serving import bucketed_forward, ladder_for

        return bucketed_forward(
            lambda pk, pl, rk: self._win_prob(self.value_params, pk, pl, rk),
            boards, to_move, ranks, ladder_for(len(boards)))

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        logp = self._legal_log_probs(packed, players, legal)
        _, forcing1 = _oneply_scores(packed, players)
        urgent, cand, rows, cols, after, _ = _play_candidates(
            packed, players, legal, logp, forcing1, self.top_k, self.urgent)
        if after is None:
            any_legal = legal.any(axis=1)
            return np.where(any_legal, logp.argmax(axis=1), -1)

        v_opp = self._values(after, 3 - players[rows])
        # tie_scale keeps the policy-prob tie-break under the win-prob
        # margin, preserving the prior's ordering among value-equal moves
        return _veto_select(logp, legal, cand, rows, cols, 1.0 - v_opp,
                            self.margin, urgent, self.pass_threshold, rng,
                            tie_scale=1e-4)


class Value2PlyAgent(ValueSearchAgent):
    """Policy-pruned 2-ply search under the learned evaluation (``value2:``).

    The round-4 factorial's prescribed next expert (RESULTS.md): the 1-ply
    value agent scores a candidate by the value of its after-board, which
    credits moves whose refutation sits one reply away — exactly the
    horizon the tactical TwoPlyAgent closed for the capture game. This
    agent closes it for the learned evaluation: the opponent's best reply
    is found BY VALUE, not by the fixed tactical table.

    Per board it (1) takes the policy top-k plus forcing points as
    candidates and plays each (the same pruning as every search agent),
    (2) on each after-board, takes the OPPONENT's policy top-``reply_k``
    plus forcing points as replies and plays those too (batched native
    stepping across fleet x candidates x replies), (3) scores every leaf
    with the value net from the original mover's perspective (mover is to
    move again at depth 2), and (4) scores a candidate by the WORST leaf
    over the opponent's replies — the opponent also gets the no-op reply
    (pass), whose leaf is the after-board itself, so a candidate can
    never look good merely because every opponent reply would worsen the
    opponent's position. The differential veto then fires only when some
    candidate beats the policy move's own 2-ply score by ``margin`` win
    probability — the only-override-demonstrated-blunders asymmetry every
    search sweep selected.

    The min over replies uses the value net at BOTH plies, so evaluation
    quality still grows with value training — the property the round-4
    study identified as the requirement for the loop to keep climbing.
    Known approximations inherited from the 1-ply agent: leaves don't see
    the depth-2 ko ban, and ranks feed the net's rank planes uniformly.
    """

    name = "value2-search"

    def __init__(self, params, cfg, value_params, value_cfg,
                 name: str = "value2-search", reply_k: int = 6, **kw):
        super().__init__(params, cfg, value_params, value_cfg, name=name, **kw)
        self.reply_k = reply_k

    def select_moves(self, packed, players, legal, rng):
        legal = _no_own_eyes(packed, players, legal)
        logp = self._legal_log_probs(packed, players, legal)
        _, forcing1 = _oneply_scores(packed, players)
        urgent, cand, rows, cols, after, ko = _play_candidates(
            packed, players, legal, logp, forcing1, self.top_k, self.urgent)
        if after is None:
            any_legal = legal.any(axis=1)
            return np.where(any_legal, logp.argmax(axis=1), -1)

        # opponent's turn on each after-board: reply candidates from THEIR
        # policy prior + forcing points, ko-banned recapture excluded
        n_c = len(rows)
        mover = players[rows].astype(np.int32)
        opp = (3 - mover).astype(np.int32)
        reply_legal = legal_mask(after, opp)
        banned = ko >= 0
        reply_legal[np.arange(n_c)[banned], ko[banned]] = False
        reply_legal = _no_own_eyes(after, opp, reply_legal)
        logp2 = self._legal_log_probs(after, opp, reply_legal)
        _, forcing2 = _oneply_scores(after, opp)
        _, _, rrows, rcols, leaves, _ = _play_candidates(
            after, opp, reply_legal, logp2, forcing2, self.reply_k,
            self.urgent)

        # every candidate starts from the pass-reply leaf: the after-board
        # itself with the mover back on move
        score = self._values(after, mover).astype(np.float64)
        if leaves is not None:
            v_leaf = self._values(leaves, mover[rrows])
            np.minimum.at(score, rrows, v_leaf.astype(np.float64))
        return _veto_select(logp, legal, cand, rows, cols, score,
                            self.margin, urgent, self.pass_threshold, rng,
                            tie_scale=1e-4)


class SearchAgent(PolicyAgent):
    """Full PUCT tree search over the serving fleet (``mcts:`` spec).

    The deep end of the policy-guides-search ladder (docs/search.md):
    where the ``search:``/``search2:``/``value2:`` family re-ranks a
    handful of candidates 1-2 plies deep, this agent runs a
    virtual-loss wave-batched MCTS (deepgo_tpu.search) whose leaf
    evaluations ride the shared serving engine as batched futures and
    whose transposition table is keyed on the canonical position
    digests — so both sides of a match, and every symmetry of every
    transposition, share forwards through the content-addressed cache.
    The table persists across moves and games: tree reuse is a table
    hit. With value params the leaves are scored by the value net
    (``mcts:POLICY:VALUE``); without, the search is prior-guided with
    terminal-only values.

    Deterministic at ``temperature=0`` given a fixed simulation budget
    and a deterministic evaluator (the Elo gate's requirement); ``rng``
    only matters for root Dirichlet noise / visit sampling, which the
    arena leaves off.
    """

    def __init__(self, params, cfg, value_params=None, value_cfg=None,
                 name: str = "mcts", simulations: int = 128,
                 search_config=None, value_engine=None, table=None, **kw):
        if kw.get("temperature", 0.0):
            raise ValueError("SearchAgent selects by visit count; "
                             "temperature is not supported in the arena")
        super().__init__(params, cfg, name=name, **kw)
        from .search import Search, SearchConfig, TranspositionTable

        self.simulations = simulations
        if value_engine is None and value_params is not None:
            value_engine = _DirectValue(value_params, value_cfg)
        self.value_engine = value_engine
        cfg_s = search_config or SearchConfig(
            simulations=simulations, rank=self.rank, tier="interactive")
        self.search_config = cfg_s
        self.table = table if table is not None else TranspositionTable(
            cfg_s.max_nodes)
        engine = self.engine if self.engine is not None \
            else _DirectSubmit(self)
        self._search = Search(engine, cfg_s, table=self.table,
                              value_engine=value_engine)

    def select_moves(self, packed, players, legal, rng):
        from .search import game_from_packed

        moves = np.full(len(packed), -1, dtype=np.int64)
        for i in range(len(packed)):
            g = game_from_packed(packed[i], int(players[i]), legal[i])
            r = self._search.search(g, simulations=self.simulations,
                                    root_legal=legal[i])
            moves[i] = r.move
        return moves


class _DirectSubmit:
    """Engine-shaped adapter over the agent's direct forward path: each
    leaf is one (bucket-padded) forward resolved into an
    already-completed future. The no-engine smoke path — real searches
    should share a micro-batching engine so waves coalesce."""

    def __init__(self, agent: PolicyAgent):
        self._agent = agent

    def submit(self, packed, player, rank):
        from concurrent.futures import Future

        a = self._agent
        row = batched_log_probs(
            a._predict, a.params, np.asarray(packed)[None],
            np.array([player], dtype=np.int32),
            np.array([rank], dtype=np.int32))[0]
        f = Future()
        f.set_result(np.asarray(row))
        return f


class _DirectValue:
    """``evaluate``-shaped adapter over a direct value forward (the same
    ladder-padded path ValueSearchAgent uses without an engine)."""

    def __init__(self, value_params, value_cfg):
        from .models.serving import make_value_fn

        self._params = value_params
        self._win_prob = make_value_fn(value_cfg)

    def evaluate(self, boards, to_move, ranks):
        from .serving import bucketed_forward, ladder_for

        return bucketed_forward(
            lambda pk, pl, rk: self._win_prob(self._params, pk, pl, rk),
            boards, np.asarray(to_move, dtype=np.int32),
            np.asarray(ranks, dtype=np.int32), ladder_for(len(boards)))


def _policy_engine_for(params, cfg, use_engine, fleet: int = 1,
                       variant: str = "f32"):
    """The shared policy engine for this checkpoint, or None. Agents built
    from the same params then coalesce their per-ply forwards into the
    same micro-batched dispatches (serving.shared_policy_engine).
    ``use_engine="supervised"`` puts the shared engine under the
    resilience supervisor (serving.SupervisedEngine) so agents ride
    through dispatcher restarts untouched; ``fleet >= 2`` spreads it over
    that many supervised replicas behind the failover router
    (serving.FleetRouter — docs/serving.md). ``variant`` selects the
    serving program (f32 | int8 | sym | int8+sym — serving/variants.py;
    lossy variants tolerance-gate before serving), memoized per
    (checkpoint, variant) so an int8 agent and an f32 agent of the same
    champion coexist for a live arena A/B."""
    if not use_engine and variant != "f32":
        raise ValueError(
            f"variant {variant!r} needs the serving engine path — pass "
            "--engine/--supervised/--fleet (the variant forward lives in "
            "the shared engine registry, docs/serving.md)")
    if not use_engine:
        return None
    from .serving import shared_policy_engine

    return shared_policy_engine(params, cfg,
                                supervised=use_engine == "supervised",
                                fleet=fleet, variant=variant)


def _make_agent(spec: str, seed: int, temperature: float = 0.0,
                rank: int = 9, use_engine=False, fleet: int = 1,
                variant: str = "f32", search_sims: int = 128) -> Agent:
    """``use_engine``: False (direct ladder path), True (shared
    micro-batching engine), or "supervised" (shared engine under the
    resilience supervisor). ``fleet >= 2`` upgrades the shared engines to
    a FleetRouter of that many supervised replicas. ``variant`` routes
    the POLICY forward through a named serving variant (arena A/B:
    quantized vs full-precision champions)."""
    if spec == "random":
        return RandomAgent()
    if spec == "heuristic":
        return HeuristicAgent()
    if spec == "oneply":
        return OnePlyAgent()
    if spec.startswith("checkpoint:"):
        from .models.serving import load_policy

        _, params, cfg = load_policy(spec.split(":", 1)[1])
        return PolicyAgent(params, cfg, name="policy", temperature=temperature,
                           rank=rank,
                           engine=_policy_engine_for(params, cfg, use_engine,
                                                     fleet=fleet,
                                                     variant=variant))
    if spec.startswith("search:"):
        from .models.serving import load_policy

        # --temperature deliberately NOT forwarded: it applies to sampling
        # policy agents only (see the CLI help); the re-ranker stays
        # deterministic even in a mixed policy-vs-search match
        _, params, cfg = load_policy(spec.split(":", 1)[1])
        return PolicySearchAgent(params, cfg, rank=rank,
                                 engine=_policy_engine_for(params, cfg,
                                                           use_engine,
                                                           fleet=fleet,
                                                           variant=variant))
    if spec.startswith("search2:"):
        from .models.serving import load_policy

        _, params, cfg = load_policy(spec.split(":", 1)[1])
        return TwoPlyAgent(params, cfg, rank=rank,
                           engine=_policy_engine_for(params, cfg, use_engine,
                                                     fleet=fleet,
                                                     variant=variant))
    if spec.startswith(("value:", "value2:")):
        from .models.serving import load_policy, load_value

        # value[2]:POLICY_CKPT:VALUE_CKPT — policy prunes, value net scores
        try:
            kind, policy_path, value_path = spec.split(":", 2)
        except ValueError:
            raise ValueError(
                f"value spec needs two checkpoint paths, got {spec!r} "
                "(use value:POLICY.npz:VALUE.npz or value2:...)") from None
        _, params, cfg = load_policy(policy_path)
        _, vparams, vcfg = load_value(value_path)
        cls = Value2PlyAgent if kind == "value2" else ValueSearchAgent
        value_engine = None
        if use_engine:
            from .serving import shared_value_engine

            value_engine = shared_value_engine(
                vparams, vcfg, supervised=use_engine == "supervised",
                fleet=fleet)
        return cls(params, cfg, vparams, vcfg, rank=rank,
                   engine=_policy_engine_for(params, cfg, use_engine,
                                             fleet=fleet),
                   value_engine=value_engine)
    if spec.startswith("mcts:"):
        from .models.serving import load_policy, load_value

        # mcts:POLICY_CKPT[:VALUE_CKPT] — full PUCT tree search
        # (deepgo_tpu.search) with the policy as prior and, when given,
        # the value net at the leaves. Always rides the shared
        # micro-batching engine: wave-batched leaf futures are the point.
        parts = spec.split(":")
        _, params, cfg = load_policy(parts[1])
        vparams = vcfg = None
        if len(parts) > 2:
            _, vparams, vcfg = load_value(parts[2])
        value_engine = None
        if vparams is not None and use_engine:
            from .serving import shared_value_engine

            value_engine = shared_value_engine(
                vparams, vcfg, supervised=use_engine == "supervised",
                fleet=fleet)
        return SearchAgent(params, cfg, vparams, vcfg, rank=rank,
                           simulations=search_sims,
                           engine=_policy_engine_for(params, cfg,
                                                     use_engine or True,
                                                     fleet=fleet,
                                                     variant=variant),
                           value_engine=value_engine)
    if spec.startswith("model:"):  # random-init policy, for smoke runs
        cfg = policy_cnn.CONFIGS[spec.split(":", 1)[1]]
        params = policy_cnn.init(jax.random.key(seed), cfg)
        return PolicyAgent(params, cfg, name=f"init-{spec.split(':', 1)[1]}",
                           temperature=temperature, rank=rank,
                           engine=_policy_engine_for(params, cfg, use_engine,
                                                     fleet=fleet,
                                                     variant=variant))
    raise ValueError(
        f"unknown agent spec {spec!r} "
        "(use random | heuristic | oneply | checkpoint:PATH | search:PATH "
        "| search2:PATH | value:POLICY:VALUE | value2:POLICY:VALUE "
        "| mcts:POLICY[:VALUE] | model:NAME)")
