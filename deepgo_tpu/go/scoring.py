"""Tromp-Taylor area scoring for finished games.

The reference's paper evaluation (README.md:5, arXiv:1412.6564) reports win
rate against GnuGo, which requires scoring finished boards; the reference
repo itself never scores a game. This module supplies the missing half:
area scoring per the Tromp-Taylor rules — a player's score is the number of
their stones plus the number of empty points that reach only their color.
Empty regions touching both colors (dame, seki gaps) count for neither.

Pure host-side NumPy over a 361-point board; one BFS pass over empty
regions per call.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from .board import BLACK, EMPTY, SIZE, WHITE, _NEIGHBORS


@dataclass(frozen=True)
class Score:
    black: float
    white: float
    komi: float

    @property
    def margin(self) -> float:
        """Black's winning margin (negative = white wins)."""
        return self.black - self.white - self.komi

    @property
    def winner(self) -> int:
        """BLACK, WHITE, or EMPTY (0) for a drawn game."""
        if self.margin > 0:
            return BLACK
        if self.margin < 0:
            return WHITE
        return EMPTY

    def result_string(self) -> str:
        """SGF RE[] value, e.g. ``B+12.5`` / ``W+3.5`` / ``0`` (draw)."""
        if self.margin > 0:
            return f"B+{self.margin:g}"
        if self.margin < 0:
            return f"W+{-self.margin:g}"
        return "0"


def area_score(stones: np.ndarray, komi: float = 7.5) -> Score:
    """Tromp-Taylor area count of a (19, 19) board.

    Each empty region is flood-filled once; it scores for a color iff every
    stone adjacent to the region is that color. Stones score for themselves.
    """
    black = int(np.count_nonzero(stones == BLACK))
    white = int(np.count_nonzero(stones == WHITE))

    seen = np.zeros((SIZE, SIZE), dtype=bool)
    for x in range(SIZE):
        for y in range(SIZE):
            if stones[x, y] != EMPTY or seen[x, y]:
                continue
            # BFS one empty region, recording which colors border it
            region = [(x, y)]
            seen[x, y] = True
            borders = 0  # bitmask: 1 = black, 2 = white
            size = 0
            while region:
                a, b = region.pop()
                size += 1
                for n in _NEIGHBORS[a][b]:
                    v = stones[n]
                    if v == EMPTY:
                        if not seen[n]:
                            seen[n] = True
                            region.append(n)
                    else:
                        borders |= 1 << (v - 1)
            if borders == 1:
                black += size
            elif borders == 2:
                white += size

    return Score(black=float(black), white=float(white), komi=komi)
