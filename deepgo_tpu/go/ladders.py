"""Ladder reading: the one lookahead feature.

Decides, for a chain with exactly two liberties, which of those liberties the
opponent can play to capture the chain in a ladder. This is a recursive
search with play-and-undo, matching the reference's decision procedure
(reference ladder_moves, makedata.lua:393-439) exactly:

  for each liberty L (the candidate chasing move), other liberty O:
    opponent plays L (with capture resolution);
    if the chasing stone's chain now has > 2 liberties (the chase is not
    self-defeating):
      the chased player escapes at O;
      if the escaped chain has exactly 1 liberty -> ladder works (atari);
      if it has exactly 2 liberties -> recurse, provided the chasing chain
      itself retains > 1 liberty after the escape.

The chased chain is identified by a representative point (x, y) which keeps
its stone throughout the search (escape moves only extend the chain).
"""

from __future__ import annotations

import numpy as np

from .board import group_and_liberties, play_with_undo, undo_moves


def ladder_moves(
    stones: np.ndarray, x: int, y: int, liberties: set[tuple[int, int]]
) -> list[tuple[int, int]]:
    """Return the liberties of the 2-liberty chain at (x, y) from which the
    opponent can launch a capturing ladder. ``stones`` is temporarily mutated
    and restored before returning."""
    player = int(stones[x, y])
    opponent = 3 - player
    libs = sorted(liberties)
    assert len(libs) == 2, "ladder reading requires exactly two liberties"

    result: list[tuple[int, int]] = []
    for i in (0, 1):
        chase, escape = libs[i], libs[1 - i]
        undo: list = []
        play_with_undo(stones, chase[0], chase[1], opponent, undo)
        _, chaser_libs = group_and_liberties(stones, *chase)
        if len(chaser_libs) > 2:
            play_with_undo(stones, escape[0], escape[1], player, undo)
            _, escaped_libs = group_and_liberties(stones, *escape)
            if len(escaped_libs) == 1:
                result.append(chase)
            elif len(escaped_libs) == 2:
                _, chaser_libs = group_and_liberties(stones, *chase)
                if len(chaser_libs) > 1 and ladder_moves(stones, x, y, escaped_libs):
                    result.append(chase)
        undo_moves(stones, undo)
    return result
