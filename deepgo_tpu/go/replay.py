"""Game replay: SGF game -> per-move training positions.

Equivalent of the reference's all_boards iterator (makedata.lua:156-186):
handicap stones are placed first (through the same aging placement path),
then for every move the *pre-move* board is summarized and yielded together
with the move that was actually played (the training target).
"""

from __future__ import annotations

from collections.abc import Iterator

import numpy as np

from ..sgf import Game, Move
from .board import new_board, play
from .summarize import summarize


def replay_positions(game: Game) -> Iterator[tuple[np.ndarray, Move]]:
    """Yield (packed_planes, move) for each move of the game.

    ``packed_planes`` is the (9, 19, 19) uint8 record of the board *before*
    the move. Passes never reach here (the SGF parser drops them), so the
    board — including the age channel — evolves only on real moves, matching
    the reference.
    """
    stones, age = new_board()
    for h in game.handicaps:
        play(stones, age, h.x, h.y, h.player)
    for move in game.moves:
        yield summarize(stones, age), move
        play(stones, age, move.x, move.y, move.player)
