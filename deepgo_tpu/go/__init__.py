"""Go rules engine: board replay, liberties, captures, ladders, features."""

from .board import (  # noqa: F401
    BLACK,
    EMPTY,
    SIZE,
    WHITE,
    IllegalMoveError,
    find_groups,
    group_and_liberties,
    neighbors,
    new_board,
    play,
    simulate_play,
)
from .ladders import ladder_moves  # noqa: F401
from .summarize import ladders_and_liberties, summarize  # noqa: F401
from .replay import replay_positions  # noqa: F401
