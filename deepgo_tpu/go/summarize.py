"""Per-position feature summary: everything the model sees about a board.

Produces the packed 9-channel record for one position (the write-side schema
of reference dataloader.lua:20-39 / summarize_board makedata.lua:143-153):

  channel 0   stones            0 empty, 1 black, 2 white
  channel 1   liberties         chain liberty count at each stone
  channels 2-3 liberties-after  per player: liberties of the chain formed by
                                playing at each empty point (0 on stones,
                                0 for suicide)
  channels 4-5 kills            per player: opposing stones captured by
                                playing at each empty point
  channel 6   age               moves the point has been in its current state
  channels 7-8 ladders          per player: points from which that player can
                                launch a working ladder capture, valued with
                                the size of the chased chain

Unlike the reference — which re-flood-fills the whole board for each of the
up-to-722 hypothetical plays (makedata.lua:122-141) — this computes chain
labels and liberty sets once and answers the no-capture (common) case with
set unions, simulating only when a capture is involved.
"""

from __future__ import annotations

import numpy as np

from .board import EMPTY, SIZE, _NEIGHBORS, find_groups, simulate_play
from .ladders import ladder_moves


def _clip255(n: int) -> int:
    # Packed channels are uint8; real games never reach the cap (the
    # reference's ByteTensor would wrap instead, which never triggers either).
    return min(n, 255)


def ladders_and_liberties(stones: np.ndarray, labels=None, groups=None):
    """(ladders, liberties): ladders is (2, 19, 19) per chasing player with
    chased-chain size at working ladder points; liberties is (19, 19) chain
    liberty counts (reference all_ladder_moves_and_liberties,
    makedata.lua:441-479)."""
    if groups is None:
        labels, groups = find_groups(stones)
    ladders = np.zeros((2, SIZE, SIZE), dtype=np.uint8)
    liberties = np.zeros((SIZE, SIZE), dtype=np.uint8)
    for group in groups:
        n_libs = _clip255(len(group["liberties"]))
        for p in group["points"]:
            liberties[p] = n_libs
        if len(group["liberties"]) == 2:
            x, y = next(iter(group["points"]))
            chaser = 3 - group["player"]
            for move in ladder_moves(stones, x, y, group["liberties"]):
                ladders[chaser - 1][move] = _clip255(len(group["points"]))
    return ladders, liberties


def kills_and_liberties_after(stones: np.ndarray, labels, groups):
    """(kills, liberties_after), each (2, 19, 19) uint8 indexed by player-1,
    defined at empty points only (reference all_kills_and_liberties_after,
    makedata.lua:122-141)."""
    kills = np.zeros((2, SIZE, SIZE), dtype=np.uint8)
    liberties_after = np.zeros((2, SIZE, SIZE), dtype=np.uint8)
    for x in range(SIZE):
        for y in range(SIZE):
            if stones[x, y] != EMPTY:
                continue
            for player in (1, 2):
                opponent = 3 - player
                captures = False
                own_groups = set()
                lib_union = {(x, y)}
                for n in _NEIGHBORS[x][y]:
                    v = stones[n]
                    if v == EMPTY:
                        lib_union.add(n)
                    else:
                        g = labels[n]
                        if v == opponent:
                            if len(groups[g]["liberties"]) == 1:
                                captures = True
                        else:
                            own_groups.add(g)
                if captures:
                    # A capture frees points whose adjacency to the new chain
                    # needs real resolution: simulate.
                    k, la = simulate_play(stones, x, y, player)
                else:
                    # No capture: the new chain's liberties are the union of
                    # the merged own chains' liberties and the empty
                    # neighbors, minus the played point itself.
                    k = 0
                    for g in own_groups:
                        lib_union |= groups[g]["liberties"]
                    la = len(lib_union) - 1
                kills[player - 1, x, y] = _clip255(k)
                liberties_after[player - 1, x, y] = _clip255(la)
    return kills, liberties_after


def summarize(stones: np.ndarray, age: np.ndarray) -> np.ndarray:
    """Full packed 9-channel record, (9, 19, 19) uint8."""
    labels, groups = find_groups(stones)
    ladders, liberties = ladders_and_liberties(stones, labels, groups)
    kills, liberties_after = kills_and_liberties_after(stones, labels, groups)
    packed = np.empty((9, SIZE, SIZE), dtype=np.uint8)
    packed[0] = stones
    packed[1] = liberties
    packed[2:4] = liberties_after
    packed[4:6] = kills
    packed[6] = np.minimum(age, 255)
    packed[7:9] = ladders
    return packed
