"""Core Go board rules: placement, capture, liberties, hypothetical play.

Semantics mirror the reference engine (reference makedata.lua:188-354) but the
implementation is different: a single connected-components pass labels every
chain once per position (``find_groups``), and hypothetical-play queries use
set unions over precomputed group liberty sets, falling back to a real
play-and-undo simulation only when a capture occurs. The reference instead
re-flood-fills from scratch for every query (makedata.lua:245-282,304-327).

Board representation: ``stones`` is a (19, 19) uint8 array with 0 empty,
1 black, 2 white; axis 0 is the SGF x coordinate. ``age`` is a (19, 19) int32
array counting how many moves each point has been in its current state
(0 = never occupied, capped at 255; reference makedata.lua:329-339).

Deliberately no ko/superko tracking, matching the reference: both engines
replay *recorded* games, where move legality is guaranteed by the source;
only occupied-point plays are rejected (reference makedata.lua:352).
"""

from __future__ import annotations

import numpy as np

SIZE = 19
EMPTY, BLACK, WHITE = 0, 1, 2
MAX_AGE = 255

# Flat neighbor adjacency, precomputed once: _NEIGHBORS[x][y] is a tuple of
# (nx, ny) pairs orthogonally adjacent to (x, y) and on the board.
_NEIGHBORS: list[list[tuple[tuple[int, int], ...]]] = [
    [
        tuple(
            (nx, ny)
            for nx, ny in ((x - 1, y), (x + 1, y), (x, y - 1), (x, y + 1))
            if 0 <= nx < SIZE and 0 <= ny < SIZE
        )
        for y in range(SIZE)
    ]
    for x in range(SIZE)
]


def neighbors(x: int, y: int) -> tuple[tuple[int, int], ...]:
    """On-board orthogonal neighbors of (x, y)."""
    return _NEIGHBORS[x][y]


class IllegalMoveError(Exception):
    pass


def new_board() -> tuple[np.ndarray, np.ndarray]:
    """Fresh empty (stones, age) pair."""
    return (
        np.zeros((SIZE, SIZE), dtype=np.uint8),
        np.zeros((SIZE, SIZE), dtype=np.int32),
    )


def group_and_liberties(stones: np.ndarray, x: int, y: int):
    """Flood-fill the chain containing (x, y).

    Returns (group, liberties) as sets of (x, y) points; both empty if the
    point is unoccupied (the reference's count_liberties returns 0 liberties
    for empty points, makedata.lua:254).
    """
    player = stones[x, y]
    if player == EMPTY:
        return set(), set()
    group = {(x, y)}
    liberties = set()
    stack = [(x, y)]
    while stack:
        a, b = stack.pop()
        for n in _NEIGHBORS[a][b]:
            v = stones[n]
            if v == player:
                if n not in group:
                    group.add(n)
                    stack.append(n)
            elif v == EMPTY:
                liberties.add(n)
    return group, liberties


def find_groups(stones: np.ndarray):
    """Label every chain on the board in one pass.

    Returns (labels, groups): ``labels`` is a (19, 19) int32 array mapping
    each stone to its group index (-1 for empty points); ``groups`` is a list
    of dicts with keys ``player``, ``points`` (set), ``liberties`` (set).
    """
    labels = np.full((SIZE, SIZE), -1, dtype=np.int32)
    groups = []
    for x in range(SIZE):
        for y in range(SIZE):
            if stones[x, y] != EMPTY and labels[x, y] < 0:
                group, liberties = group_and_liberties(stones, x, y)
                idx = len(groups)
                for p in group:
                    labels[p] = idx
                groups.append(
                    {"player": int(stones[x, y]), "points": group, "liberties": liberties}
                )
    return labels, groups


def _remove_dead_neighbors(stones, age, x, y, undo=None):
    """Remove dead opposing chains around (x, y), then (x, y)'s own chain if
    dead (suicide). Returns the number of *opposing* stones removed.

    Mirrors play_with_f/apply_f_to_dead_neighbors (reference
    makedata.lua:224-241,388-391): removed points get age 1, and a killed own
    chain does not count toward the kill total.
    """
    player = stones[x, y]
    opponent = 3 - player
    kills = 0
    checked: set[tuple[int, int]] = set()
    for n in _NEIGHBORS[x][y]:
        if stones[n] == opponent and n not in checked:
            group, liberties = group_and_liberties(stones, *n)
            checked |= group
            if not liberties:
                kills += len(group)
                for p in group:
                    if undo is not None:
                        undo.append((p, opponent))
                    stones[p] = EMPTY
                    if age is not None:
                        age[p] = 1
    own_group, own_liberties = group_and_liberties(stones, x, y)
    if not own_liberties:
        for p in own_group:
            if undo is not None:
                undo.append((p, player))
            stones[p] = EMPTY
            if age is not None:
                age[p] = 1
    return kills


def play(stones: np.ndarray, age: np.ndarray | None, x: int, y: int, player: int) -> int:
    """Apply a real move in place with full capture resolution.

    Ages every occupied point first, places the stone (age 1), removes dead
    opposing chains and then a dead own chain (suicide), stamping removed
    points with age 1 (reference update_board, makedata.lua:329-354).
    Returns the number of opposing stones captured.
    """
    if stones[x, y] != EMPTY:
        raise IllegalMoveError(f"point ({x}, {y}) is already occupied")
    if age is not None:
        np.minimum(age + (age > 0), MAX_AGE, out=age)
    stones[x, y] = player
    if age is not None:
        age[x, y] = 1
    return _remove_dead_neighbors(stones, age, x, y)


def simulate_play(stones: np.ndarray, x: int, y: int, player: int):
    """Hypothetically play at empty (x, y): returns (kills, liberties_after).

    ``kills`` counts opposing stones that would be captured;
    ``liberties_after`` is the liberty count of the newly formed chain (0 for
    suicide). The board is restored before returning (reference
    count_kills_and_liberties, makedata.lua:304-327).
    """
    if stones[x, y] != EMPTY:
        raise IllegalMoveError(f"simulating a play on occupied ({x}, {y})")
    undo: list[tuple[tuple[int, int], int]] = [((x, y), EMPTY)]
    stones[x, y] = player
    kills = _remove_dead_neighbors(stones, None, x, y, undo)
    _, liberties = group_and_liberties(stones, x, y)
    for point, value in reversed(undo):
        stones[point] = value
    return kills, len(liberties)


def play_with_undo(stones: np.ndarray, x: int, y: int, player: int, undo: list) -> None:
    """Play with capture resolution, recording every change into ``undo``
    (a list of ((x, y), previous_value)); used by the ladder reader's
    temp-play search (reference ladder_moves' temp_play, makedata.lua:393-407).
    """
    if stones[x, y] != EMPTY:
        raise IllegalMoveError(f"temp-playing on occupied ({x}, {y})")
    undo.append(((x, y), EMPTY))
    stones[x, y] = player
    _remove_dead_neighbors(stones, None, x, y, undo)


def undo_moves(stones: np.ndarray, undo: list) -> None:
    """Restore a board mutated through ``play_with_undo``."""
    for point, value in reversed(undo):
        stones[point] = value
    undo.clear()
