"""ctypes bridge to the native C++ rules engine (native/goboard.cpp).

The shared library is built on first use (``make -C native``) and cached;
every consumer falls back to the pure-Python engine when a compiler is
unavailable, so the native path is an accelerator, never a requirement.
Python and C++ engines are semantically identical (cross-tested, plus the
same golden parity suite against the reference's records).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..features import PACKED_CHANNELS
from .. import BOARD_SIZE

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libgoboard.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        result = subprocess.run(
            ["make", "-C", _NATIVE_DIR], capture_output=True, text=True, timeout=120
        )
        return result.returncode == 0 and os.path.exists(_SO_PATH)
    except Exception:
        return False


def load() -> ctypes.CDLL | None:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_SO_PATH) and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.goboard_transcribe.restype = ctypes.c_int
        lib.goboard_transcribe.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.goboard_summarize.restype = None
        lib.goboard_summarize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _moves_array(moves) -> np.ndarray:
    return np.array([(m.player, m.x, m.y) for m in moves], dtype=np.int32).reshape(-1, 3)


def transcribe_game_native(handicaps, moves) -> np.ndarray:
    """Replay a whole game natively -> packed (M, 9, 19, 19) records of the
    pre-move boards. Raises on illegal positions (like the Python engine)."""
    lib = load()
    assert lib is not None, "native engine unavailable"
    h = _moves_array(handicaps)
    m = _moves_array(moves)
    out = np.empty(
        (len(moves), PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE), dtype=np.uint8
    )
    rc = lib.goboard_transcribe(
        h.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(handicaps),
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(moves),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc != 0:
        from .board import IllegalMoveError

        if rc <= -1000000:
            raise IllegalMoveError(f"illegal handicap placement #{-(rc + 1000000) - 1}")
        raise IllegalMoveError(f"illegal move #{-rc - 1}")
    return out


def summarize_native(stones: np.ndarray, age: np.ndarray) -> np.ndarray:
    lib = load()
    assert lib is not None, "native engine unavailable"
    s = np.ascontiguousarray(stones, dtype=np.uint8)
    a = np.ascontiguousarray(age, dtype=np.int32)
    out = np.empty((PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE), dtype=np.uint8)
    lib.goboard_summarize(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out
