"""ctypes bridge to the native C++ rules engine (native/goboard.cpp).

The shared library is built on first use (``make -C native``) and cached;
every consumer falls back to the pure-Python engine when a compiler is
unavailable, so the native path is an accelerator, never a requirement.
Python and C++ engines are semantically identical (cross-tested, plus the
same golden parity suite against the reference's records).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from ..features import PACKED_CHANNELS
from .. import BOARD_SIZE

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO, "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "build", "libgoboard.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _build() -> bool:
    try:
        result = subprocess.run(
            ["make", "-C", _NATIVE_DIR], capture_output=True, text=True, timeout=120
        )
        return result.returncode == 0 and os.path.exists(_SO_PATH)
    except Exception:
        return False


def load() -> ctypes.CDLL | None:
    """The loaded library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        # Always invoke make: a no-op when the .so is newer than the
        # source, a rebuild when a checkout left a stale .so missing newer
        # symbols. A failed build with an existing .so (no compiler on
        # this host) still loads the old library.
        if not _build() and not os.path.exists(_SO_PATH):
            return None
        try:
            lib = ctypes.CDLL(_SO_PATH)
        except OSError:
            return None
        lib.goboard_transcribe.restype = ctypes.c_int
        lib.goboard_transcribe.argtypes = [
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.goboard_summarize.restype = None
        lib.goboard_summarize.argtypes = [
            ctypes.POINTER(ctypes.c_uint8),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8),
        ]
        # goboard_summarize_batch is absent from stale pre-built .so files;
        # treat it as optional so consumers can fall back per board.
        try:
            lib.goboard_summarize_batch.restype = None
            lib.goboard_summarize_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.c_int,
            ]
        except AttributeError:
            pass
        try:
            lib.goboard_play_batch.restype = ctypes.c_int
            lib.goboard_play_batch.argtypes = [
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int,
            ]
        except AttributeError:
            pass
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def _moves_array(moves) -> np.ndarray:
    return np.array([(m.player, m.x, m.y) for m in moves], dtype=np.int32).reshape(-1, 3)


def transcribe_game_native(handicaps, moves) -> np.ndarray:
    """Replay a whole game natively -> packed (M, 9, 19, 19) records of the
    pre-move boards. Raises on illegal positions (like the Python engine)."""
    lib = load()
    assert lib is not None, "native engine unavailable"
    h = _moves_array(handicaps)
    m = _moves_array(moves)
    out = np.empty(
        (len(moves), PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE), dtype=np.uint8
    )
    rc = lib.goboard_transcribe(
        h.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(handicaps),
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(moves),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    if rc != 0:
        from .board import IllegalMoveError

        if rc <= -1000000:
            raise IllegalMoveError(f"illegal handicap placement #{-(rc + 1000000) - 1}")
        raise IllegalMoveError(f"illegal move #{-rc - 1}")
    return out


def batch_available() -> bool:
    lib = load()
    return (lib is not None and hasattr(lib, "goboard_summarize_batch")
            and hasattr(lib, "goboard_play_batch"))


def play_batch_native(stones: np.ndarray, age: np.ndarray, moves: np.ndarray,
                      players: np.ndarray, n_threads: int = 0) -> np.ndarray:
    """Apply one move per board IN PLACE across N boards in one native call.

    ``stones`` (N, 19, 19) uint8 and ``age`` (N, 19, 19) int32 are mutated;
    ``moves`` is (N,) int32 flat indices (-1 = pass, board untouched) and
    ``players`` (N,) int32. Returns the (N,) int32 simple-ko points (flat
    index of the banned recapture, -1 = none) — the native twin of
    deepgo_tpu.selfplay.apply_move's ko rule. Raises IllegalMoveError if
    any move lands on an occupied point.
    """
    lib = load()
    assert lib is not None and hasattr(lib, "goboard_play_batch"), (
        "native batch play unavailable")
    assert stones.dtype == np.uint8 and stones.flags.c_contiguous
    assert age.dtype == np.int32 and age.flags.c_contiguous
    assert stones.ndim == 3 and stones.shape[1:] == (BOARD_SIZE, BOARD_SIZE)
    assert age.shape == stones.shape
    m = np.ascontiguousarray(moves, dtype=np.int32)
    p = np.ascontiguousarray(players, dtype=np.int32)
    n = stones.shape[0]
    assert m.shape == (n,) and p.shape == (n,)
    ko = np.empty(n, dtype=np.int32)
    rc = lib.goboard_play_batch(
        stones.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        age.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        m.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        p.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        ko.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n_threads,
    )
    if rc != 0:
        from .board import IllegalMoveError

        raise IllegalMoveError(f"illegal move on board #{-rc - 1}")
    return ko


def summarize_batch_native(stones: np.ndarray, age: np.ndarray,
                           n_threads: int = 0) -> np.ndarray:
    """Summarize N independent boards in one native call.

    ``stones`` is (N, 19, 19) uint8, ``age`` (N, 19, 19) int32; returns
    packed (N, 9, 19, 19) uint8 records. One FFI crossing for the whole
    batch, fanned over C++ threads (n_threads <= 0 = all cores) — the
    self-play/arena host path's replacement for a Python loop of per-board
    calls (round-2 verdict item 6).
    """
    lib = load()
    assert lib is not None and hasattr(lib, "goboard_summarize_batch"), (
        "native batch summarize unavailable")
    s = np.ascontiguousarray(stones, dtype=np.uint8)
    a = np.ascontiguousarray(age, dtype=np.int32)
    assert s.ndim == 3 and s.shape[1:] == (BOARD_SIZE, BOARD_SIZE)
    assert a.shape == s.shape
    n = s.shape[0]
    out = np.empty((n, PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE), dtype=np.uint8)
    lib.goboard_summarize_batch(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        n,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        n_threads,
    )
    return out


def summarize_native(stones: np.ndarray, age: np.ndarray) -> np.ndarray:
    lib = load()
    assert lib is not None, "native engine unavailable"
    s = np.ascontiguousarray(stones, dtype=np.uint8)
    a = np.ascontiguousarray(age, dtype=np.int32)
    out = np.empty((PACKED_CHANNELS, BOARD_SIZE, BOARD_SIZE), dtype=np.uint8)
    lib.goboard_summarize(
        s.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
    )
    return out
