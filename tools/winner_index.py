"""Build the per-position game-winner sidecar for outcome-conditioned
sampling (GoDataset scheme="winner").

Reads each game's SGF RE[] result (written by the corpus generator /
self-play exporter, e.g. "B+23.5", "W+4", "0") and writes
``<split>/winner.npy``: int8 (N,) = winner of the game containing each
position (1 black, 2 white, 0 unknown/draw/truncated). Training on only
the winner's moves biases imitation toward winning play — outcome
information the reference's on-disk format does not carry at all.

Usage:
  python tools/winner_index.py --processed data/corpus/processed/train \
      --sgf data/corpus/sgf/train
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu import sgf  # noqa: E402


def winner_of(result: str) -> int:
    r = result.strip()
    if r.startswith("B+"):
        return 1
    if r.startswith("W+"):
        return 2
    return 0


def build(processed: str, sgf_dir: str) -> dict:
    with open(os.path.join(processed, "games.json")) as f:
        games = json.load(f)
    total = sum(g["count"] for g in games)
    winner = np.zeros(total, dtype=np.int8)
    stats = {"games": len(games), "decided": 0, "undecided": 0, "missing": 0}
    for g in games:
        path = os.path.join(sgf_dir, g["name"])
        if not os.path.exists(path):
            stats["missing"] += 1
            continue
        re_vals = sgf.parse_file(path).properties.get("RE", [])
        w = winner_of(re_vals[0]) if re_vals else 0
        if w:
            stats["decided"] += 1
            winner[g["start"]:g["start"] + g["count"]] = w
        else:
            stats["undecided"] += 1
    np.save(os.path.join(processed, "winner.npy"), winner)
    stats["winner_positions"] = int(
        (winner == np.load(os.path.join(processed, "meta.npy"))[:, 0]).sum())
    return stats


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--processed", required=True)
    ap.add_argument("--sgf", required=True)
    args = ap.parse_args(argv)
    stats = build(args.processed, args.sgf)
    print(stats)


if __name__ == "__main__":
    main()
