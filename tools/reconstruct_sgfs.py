"""Reconstruct SGF game files from the reference's bundled per-move records.

The reference repo (wqzsscc/deep-go) bundles a mini-dataset of transcribed
positions (one torch-serialized file per move; see reference makedata.lua:537-559)
but not the source SGF files. Each record stores the move that was played
(player, x, y), the pre-move board, and both player ranks — which is everything
needed to rebuild the original game script:

  * moves:      record k's ``move`` field, for k = 1..N
  * handicaps:  the stones already on the board in record 1; their placement
    order is recovered from the age plane (the reference places handicap
    stones sequentially through update_board, makedata.lua:173-175, so the
    i-th placed of H stones carries age H-i+1 in record 1)
  * ranks:      record 1's ``ranks`` field (reference get_ranks, makedata.lua:102)

The reconstructed SGFs are committed under data/sgf/ and serve as the seed
corpus for this framework's own transcription pipeline; golden tests then
require our pipeline's packed planes to match the reference records bit-exact.

Usage: python tools/reconstruct_sgfs.py [--reference /root/reference/data] [--out data/sgf]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import t7reader  # noqa: E402

# Plane indices within the packed 9-channel record (0-based; the layout is
# fixed by reference dataloader.lua:20-27).
STONES, AGE = 0, 6

_COORD_CHARS = "abcdefghijklmnopqrs"


def _coord(x: int, y: int) -> str:
    """1-based board coordinates -> SGF two-letter coordinate."""
    return _COORD_CHARS[x - 1] + _COORD_CHARS[y - 1]


def reconstruct_game(game_dir: str) -> str:
    """Rebuild a single game's SGF text from its per-move record directory."""
    n_moves = len([f for f in os.listdir(game_dir) if f.isdigit()])
    first = t7reader.load(os.path.join(game_dir, "1"))
    ranks = first["ranks"]

    # Handicap stones: present on the pre-move-1 board, ordered by descending
    # age so that replaying them reproduces the reference's age plane.
    planes = first["input"]
    stones, ages = planes[STONES], planes[AGE]
    handicaps = []
    for x in range(19):
        for y in range(19):
            if stones[x][y]:
                handicaps.append((int(ages[x][y]), int(stones[x][y]), x + 1, y + 1))
    handicaps.sort(key=lambda h: -h[0])

    # One property per line, CRLF line endings: this keeps the files readable
    # by the reference's line-oriented parser (split_sgf/handicaps/get_ranks
    # split on literal "\r\n" and accept only one X[v] token per piece,
    # makedata.lua:24-58,102-120) in addition to our own parser.
    lines = ["(;GM[1]", "FF[4]", "CA[UTF-8]", "SZ[19]",
             f"BR[{int(ranks[1])}d]", f"WR[{int(ranks[2])}d]"]

    # Emit handicap stones in placement order, as runs of consecutive
    # same-player stones (one AB/AW property line per run). Grouping all AB
    # before all AW would lose cross-player placement order and break the
    # age-plane reconstruction for interleaved setup stones.
    run_player, run_coords = None, []
    for _, p, x, y in handicaps + [(0, None, 0, 0)]:
        if p != run_player:
            if run_coords:
                lines.append(("AB" if run_player == 1 else "AW")
                             + "".join(f"[{c}]" for c in run_coords))
            run_player, run_coords = p, []
        if p is not None:
            run_coords.append(_coord(x, y))

    for k in range(1, n_moves + 1):
        move = t7reader.load(os.path.join(game_dir, str(k)))["move"]
        tag = "B" if move["player"] == 1 else "W"
        lines.append(f";{tag}[{_coord(int(move['x']), int(move['y']))}]")

    return "\r\n".join(lines) + ")\r\n"


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--reference", default="/root/reference/data")
    ap.add_argument("--out", default="data/sgf")
    args = ap.parse_args()

    for split in ("train", "validation", "test"):
        split_dir = os.path.join(args.reference, split)
        for root, dirs, _files in os.walk(split_dir):
            for d in sorted(dirs):
                game_dir = os.path.join(root, d)
                if not os.path.isfile(os.path.join(game_dir, "1")):
                    continue
                rel = os.path.relpath(game_dir, split_dir)
                out_path = os.path.join(args.out, split, rel)
                if not out_path.endswith(".sgf"):
                    out_path += ".sgf"
                os.makedirs(os.path.dirname(out_path), exist_ok=True)
                sgf = reconstruct_game(game_dir)
                with open(out_path, "w") as f:
                    f.write(sgf)
                print(f"{out_path}: {sgf.count(';') - 1} moves")


if __name__ == "__main__":
    main()
