#!/bin/bash
# Round-4 strength-axis pipeline at CPU scale: the TwoPlyAgent evidence
# items from the round-3 verdict (item 4), plus the augmentation
# measurement (item 5) and the warm-restart sweep demo (item 8).
#
#   prereq:  tools/r3_cpu_strength.sh rebuilds cpu-base / cpu-ft2k
#   h2h:     search2:ft2k vs search:ft2k — the new expert vs the round-3
#            champion OPERATOR at a fixed prior, 200 games
#   rungs:   search2:ft2k vs oneply / heuristic — absolute ladder position
#   iter2p:  one distillation round FROM the 2-ply expert (the study's
#            conclusion was that a fixed 1-ply expert saturates the loop;
#            this tests whether a deeper expert un-saturates it):
#            2,560 search2 games -> winner fine-tune 500 steps from ft2k
#            -> raw / +veto / +2ply matches vs oneply
#   iter3p:  second loop round from iter2p (fresh 2-ply games by the new
#            policy, distilled back into it) — does the climb continue?
#   augment: 3L/64 curve protocol +- augment=true at the 40k budget
#   sweep:   tools/restart_sweep.sh from the cpu-base checkpoint
#
# Everything runs under JAX_PLATFORMS=cpu and nice -n 10 (never dials the
# relay; yields the single host core to live chip work). Stages are
# idempotent via find_ckpt / done-markers, same as the other queues.
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r4logs
export JAX_PLATFORMS=cpu
CORPUS=data/corpus/processed
N=${NICE:-10}

# cpu_match <spec_a> <spec_b> <tag> [games]
cpu_match() {
  local a=$1 b=$2 tag=$3 games=${4:-200}
  local mark=runs/r4logs/done_arena_$tag
  [ -f "$mark" ] && { echo "arena $tag already done"; return 0; }
  stage "arena $tag"
  nice -n $N timeout 14400 python -u -m deepgo_tpu.arena \
    --a "$a" --b "$b" --games "$games" --rank 8 --seed 11 \
    >> runs/r4logs/cpu_arena.log 2>&1
  local rc=$?
  [ $rc -eq 0 ] && touch "$mark"
  echo "arena $tag rc=$rc"
  tail -1 runs/r4logs/cpu_arena.log
}


# --- prereq: round-3 CPU checkpoints ---
bash tools/r3_cpu_strength.sh || { echo "prereq pipeline failed"; exit 1; }
read -r BASE BASE_STEP <<< "$(find_ckpt cpu-base)"
read -r FT FT_STEP <<< "$(find_ckpt cpu-ft2k)"
[ -n "${FT:-}" ] || { echo "no cpu-ft2k checkpoint"; exit 1; }
echo "cpu-base: $BASE (step $BASE_STEP); cpu-ft2k: $FT (step $FT_STEP)"

# --- verdict item 4a: head-to-head at fixed prior + ladder rungs ---
cpu_match "search2:$FT" "search:$FT" twoply_vs_search_ft2k
cpu_match "search2:$FT" oneply twoply_ft2k_oneply
cpu_match "search2:$FT" heuristic twoply_ft2k_heuristic

# --- verdict item 4b: distillation round from the 2-ply expert ---
build_selfplay_corpus data/iter2p runs/r4logs/selfplay.log 2560 512 0 23 14400 \
  "search2:$FT,oneply" "search2:$FT,search2:$FT" \
  || { echo "iter2p corpus build failed"; exit 1; }
distill_winner cpu-ft-iter2p "$FT" data/iter2p 500 runs/r4logs/distill.log
read -r I2P I2P_STEP <<< "$(find_ckpt cpu-ft-iter2p)"
[ -n "${I2P:-}" ] || { echo "no iter2p checkpoint"; exit 1; }
echo "cpu-ft-iter2p: $I2P (step $I2P_STEP)"
cpu_match "checkpoint:$I2P" oneply iter2p_raw_oneply
cpu_match "search:$I2P" oneply iter2p_veto_oneply
cpu_match "search2:$I2P" oneply iter2p_twoply_oneply

# --- second loop round: fresh 2-ply games by iter2p, distilled back ---
build_selfplay_corpus data/iter3p runs/r4logs/selfplay.log 2560 512 0 23 14400 \
  "search2:$I2P,oneply" "search2:$I2P,search2:$I2P" \
  || { echo "iter3p corpus build failed"; exit 1; }
distill_winner cpu-ft-iter3p "$I2P" data/iter3p 500 runs/r4logs/distill.log
read -r I3P I3P_STEP <<< "$(find_ckpt cpu-ft-iter3p)"
if [ -n "${I3P:-}" ]; then
  cpu_match "checkpoint:$I3P" oneply iter3p_raw_oneply
  cpu_match "search2:$I3P" oneply iter3p_twoply_oneply
fi

# --- verdict item 5: augmentation's measured payoff (40k budget) ---
# both arms on THIS round's corpus realization so the comparison is
# clean (the round-3 curve row used the round-3 realization)
for aug in false true; do
  if [ ! -f runs/r4logs/done_augment_$aug ]; then
    stage "augment=$aug"
    nice -n $N timeout 28800 python -u tools/accuracy_curve.py \
      --data-root $CORPUS --budgets 40000 --iters 1500 \
      --out docs/accuracy_curve_augment_$aug.jsonl \
      --set num_layers=3 channels=64 batch_size=256 augment=$aug \
      >> runs/r4logs/augment.log 2>&1 \
    && touch runs/r4logs/done_augment_$aug
    echo "augment=$aug rc=$?"
    tail -1 docs/accuracy_curve_augment_$aug.jsonl 2>/dev/null
  fi
done

# --- verdict item 8: multi-seed warm-restart sweep demo ---
if [ ! -f docs/restart_sweep.png ]; then
  stage restart_sweep
  nice -n $N timeout 14400 bash tools/restart_sweep.sh "$BASE" 400 4 \
    >> runs/r4logs/restart_sweep.log 2>&1
  echo "restart sweep rc=$?"
fi

echo "=== r4 cpu strength pipeline done [$(date -u +%H:%M:%S)] ==="
