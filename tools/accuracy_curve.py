"""Accuracy-vs-corpus-size curve: same config, growing data, shared test set.

Round-1 verdict item 4: demonstrate that the framework's accuracy axis is
data-limited with evidence. For each position budget this trains the SAME
model config for the SAME number of steps on a game-aligned subset of the
corpus (tools/subset_split.py) and evaluates top-1 on the shared held-out
test split; small subsets overfit and plateau, larger ones keep gaining —
the curve the paper's 55%@27M-positions sits on (arXiv:1412.6564 via
reference README.md:5).

Writes one JSONL record per point to --out and a CSV next to it.

Usage (flagship, on TPU):
  python tools/accuracy_curve.py --data-root data/corpus/processed \
      --budgets 4000,40000,400000,4000000 --iters 4000 \
      --set num_layers=12 channels=128 batch_size=512
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu.cli import parse_overrides  # noqa: E402
from deepgo_tpu.experiments import Experiment, ExperimentConfig  # noqa: E402
from subset_split import subset_prefix_copy  # noqa: E402


def run_point(cfg: ExperimentConfig, budget: int, iters: int,
              data_root: str, full_size: int) -> dict:
    if budget >= full_size:
        split = "train"  # full corpus: no point copying 100% of the shard
    else:
        split = f"train_{budget}"
        split_dir = os.path.join(data_root, split)
        if not os.path.exists(os.path.join(split_dir, "planes.bin")):
            n = subset_prefix_copy(os.path.join(data_root, "train"),
                                   split_dir, budget)
            print(f"built {split}: {n:,} positions", flush=True)

    from deepgo_tpu.data import GoDataset

    exp = Experiment(cfg.replace(name=f"curve-{budget}", train_split=split))
    t0 = time.time()
    summary = exp.run(iters)
    test = exp.evaluate()  # full test split, deterministic
    record = {
        "budget": budget,
        "actual_positions": (full_size if split == "train"
                             else len(GoDataset(data_root, split))),
        "iters": iters,
        "batch_size": cfg.batch_size,
        "test_top1": test["accuracy"],
        "test_nll": test["cost"],
        "final_ewma": summary["final_ewma"],
        "last_val": summary["last_validation"],
        "samples_per_sec": summary["samples_per_sec"],
        "seconds": time.time() - t0,
        "run_id": exp.id,
    }
    print(json.dumps(record), flush=True)
    return record


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data-root", default="data/corpus/processed")
    ap.add_argument("--budgets", default="4000,40000,400000,4000000")
    ap.add_argument("--iters", type=int, default=4000)
    ap.add_argument("--out", default="docs/accuracy_curve.jsonl")
    ap.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    args = ap.parse_args(argv)

    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()
    cfg = ExperimentConfig(data_root=args.data_root, scheme="uniform")
    cfg = cfg.replace(**parse_overrides(args.set))

    from deepgo_tpu.data import GoDataset

    full_size = len(GoDataset(args.data_root, "train"))
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    # resume-friendly: budgets already recorded in --out are not re-trained
    # (a relay flap mid-sweep then only costs the interrupted point)
    records = []
    if os.path.exists(args.out):
        with open(args.out) as f:
            records = [json.loads(line) for line in f if line.strip()]
    done = {r["budget"] for r in records}
    for budget in [int(b) for b in args.budgets.split(",")]:
        if budget in done:
            print(f"budget {budget} already recorded; skipping", flush=True)
            continue
        record = run_point(cfg, budget, args.iters, args.data_root, full_size)
        records.append(record)
        with open(args.out, "a") as f:
            f.write(json.dumps(record) + "\n")

    csv = args.out.rsplit(".", 1)[0] + ".csv"
    with open(csv, "w") as f:
        f.write("positions,test_top1,test_nll\n")
        for r in records:
            f.write(f"{r['actual_positions']},{r['test_top1']:.4f},"
                    f"{r['test_nll']:.4f}\n")
    print(f"wrote {args.out} and {csv}")
    plot_curve(args.out)


def plot_curve(jsonl_path: str) -> str | None:
    """Accuracy-vs-positions PNG (log x) from every record in the JSONL;
    returns the PNG path, or None without matplotlib."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    with open(jsonl_path) as f:
        rows = sorted((json.loads(line) for line in f if line.strip()),
                      key=lambda r: r["actual_positions"])
    if not rows:
        return None
    xs = [r["actual_positions"] for r in rows]
    ys = [r["test_top1"] for r in rows]
    fig, ax = plt.subplots(figsize=(6, 4))
    ax.semilogx(xs, ys, marker="o")
    for x, y in zip(xs, ys):
        ax.annotate(f"{y:.1%}", (x, y), textcoords="offset points",
                    xytext=(0, 8), ha="center", fontsize=8)
    ax.set_xlabel("training positions (log)")
    ax.set_ylabel("test top-1 accuracy")
    ax.set_title("Accuracy vs corpus size (same config, same steps)")
    fig.tight_layout()
    png = jsonl_path.rsplit(".", 1)[0] + ".png"
    fig.savefig(png, dpi=120)
    print(f"wrote {png}")
    return png


if __name__ == "__main__":
    main()
