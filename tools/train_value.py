"""Train the value network: position -> P(side to move wins).

Labels come from the winner sidecar (tools/winner_index.py): every
position whose game has a decided result gets z = 1 when the side to
move won. Decided-game filtering, the 37-plane expansion, bf16 trunk,
and the fused expand+forward+backward+update step all reuse the
framework's existing pieces (GoDataset memmap shards, ops/expand,
training/optimizers.sgd, experiments/checkpoint) — this tool only adds
the batch loop and the BCE objective (models/value_cnn.py docstring for
why the framework grows a value head at all).

Usage:
  JAX_PLATFORMS=cpu python tools/train_value.py \
      --data-root data/corpus/processed --iters 2000 --out runs/value

--data-root takes a comma-separated list of processed roots; batches are
sampled across them proportionally to decided-position counts (the
round-5 loop retrains the value net on the union of its own expert-game
corpora — tools/r5_value_loop.sh).
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu.utils import honor_platform_env  # noqa: E402

honor_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deepgo_tpu.data.dataset import GoDataset, M_BLACK_RANK, M_PLAYER, \
    M_WHITE_RANK  # noqa: E402
from deepgo_tpu.models import value_cnn  # noqa: E402
from deepgo_tpu.ops.expand import expand_planes  # noqa: E402
from deepgo_tpu.training.optimizers import sgd  # noqa: E402
from deepgo_tpu.experiments.checkpoint import save_checkpoint  # noqa: E402


def decided_indices(ds: GoDataset, equal_rank: bool = False) -> np.ndarray:
    """Positions in decided games; ``equal_rank`` keeps only games whose
    players share a dan rank. The mixed-rank corpus leaks the pairing
    through the rank planes (8d-vs-4d is ~always an 8d win, so outcome
    "accuracy" starts from ~55% chance — RESULTS.md round-4 value table);
    on the equal-rank slice the planes carry no outcome information and
    accuracy measures board reading against ~50% chance."""
    assert ds.winner is not None, (
        f"no winner.npy in {ds.dir} — run tools/winner_index.py first")
    ix = np.nonzero(ds.winner != 0)[0]
    if equal_rank:
        meta = ds.meta[ix]
        ix = ix[meta[:, M_BLACK_RANK] == meta[:, M_WHITE_RANK]]
    return ix


def gather(ds: GoDataset, idx: np.ndarray):
    packed = np.asarray(ds.planes[idx])
    meta = ds.meta[idx]
    player = meta[:, M_PLAYER].astype(np.int32)
    rank = np.where(player == 1, meta[:, M_BLACK_RANK],
                    meta[:, M_WHITE_RANK]).astype(np.int32)
    z = (ds.winner[idx] == player).astype(np.float32)
    return packed, player, rank, z


def make_step(cfg: value_cnn.ValueConfig, optimizer):
    def loss_fn(params, packed, player, rank, z):
        planes = expand_planes(packed, player, rank)
        logits = value_cnn.apply(params, planes, cfg)
        # mean sigmoid BCE in f32 (same upcast rule as the policy NLL)
        return jnp.mean(jnp.maximum(logits, 0) - logits * z
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    # donated like the policy train steps (linter rule `donation`): the
    # caller rebinds params/opt_state every step, so the old buffers are
    # dead weight XLA can reuse in place
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def step(params, opt_state, packed, player, rank, z):
        loss, grads = jax.value_and_grad(loss_fn)(params, packed, player,
                                                  rank, z)
        params, opt_state = optimizer.update(params, grads, opt_state)
        return params, opt_state, loss

    @jax.jit
    def evaluate(params, packed, player, rank, z):
        planes = expand_planes(packed, player, rank)
        logits = value_cnn.apply(params, planes, cfg)
        acc = jnp.mean(((logits > 0) == (z > 0.5)).astype(jnp.float32))
        return loss_fn(params, packed, player, rank, z), acc

    return step, evaluate


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data-root", default="data/corpus/processed")
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--rate", type=float, default=0.02)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--num-layers", type=int, default=3)
    ap.add_argument("--channels", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--val-interval", type=int, default=500)
    ap.add_argument("--val-size", type=int, default=4096)
    ap.add_argument("--print-interval", type=int, default=100)
    ap.add_argument("--out", default="runs/value")
    ap.add_argument("--equal-rank", action="store_true",
                    help="train/evaluate only on games between equal-rank "
                         "players: removes the rank-plane outcome shortcut "
                         "so accuracy is measured against ~50%% chance")
    args = ap.parse_args(argv)

    cfg = value_cnn.ValueConfig(num_layers=args.num_layers,
                                channels=args.channels)
    # --data-root accepts a comma-separated list so a value net can be
    # retrained on the union of the loop's expert-game corpora (the
    # round-5 compounding recipe) — a single root keeps the exact
    # round-4 sampling stream
    roots = [r for r in args.data_root.split(",") if r]
    trains = [GoDataset(r, "train") for r in roots]
    vals = [GoDataset(r, "validation") for r in roots]
    tr_sets = [(d, decided_indices(d, args.equal_rank)) for d in trains]
    rng = np.random.default_rng(args.seed)
    sizes = np.array([len(ix) for _, ix in tr_sets], dtype=np.float64)
    assert sizes.sum() > 0, (
        "no decided training positions after filtering"
        + (" (--equal-rank: no equal-rank decided games in these roots)"
           if args.equal_rank else ""))
    weights = sizes / sizes.sum()
    # validation probe drawn from each root proportionally to its TRAIN
    # decided-position weight — the probe mirrors the sampling mixture
    # the multinomial batches use, not each root's own validation size
    va_parts = []
    for w, d in zip(weights, vals):
        ix = decided_indices(d, args.equal_rank)
        want = max(1, int(round(args.val_size * w))) if w > 0 else 0
        take = min(want, len(ix))
        if take == 0:
            # a zero-weight root, or one with decided train positions but
            # no decided validation positions, contributes nothing —
            # skip explicitly rather than lean on rng.choice(empty, 0)
            if w > 0:
                print(f"warning: {d.dir} has no decided validation "
                      "positions; probe omits this root entirely",
                      flush=True)
            continue
        if take < want:
            print(f"warning: {d.dir} has only {len(ix)} decided validation "
                  f"positions (wanted {want}); probe under-represents this "
                  "root relative to the training mixture", flush=True)
        va_parts.append(gather(d, rng.choice(ix, size=take, replace=False)))
    assert va_parts, "no root contributed validation positions"
    va_batch = tuple(np.concatenate([p[j] for p in va_parts])
                     for j in range(4))
    # the probe's majority-class rate IS the chance floor for outcome
    # accuracy — print it so "accuracy X%" is always read against it
    # (mixed-rank corpora sit near 55%; equal-rank near 50%)
    z_rate = float(np.mean(va_batch[3]))
    print(f"train positions (decided{' equal-rank' if args.equal_rank else ''} "
          f"games): {int(sizes.sum()):,} of "
          f"{sum(len(d) for d in trains):,} across {len(roots)} root(s); "
          f"val probe {len(va_batch[0]):,}, chance floor "
          f"{max(z_rate, 1 - z_rate):.3f}", flush=True)

    def sample_batch(n: int):
        if len(tr_sets) == 1:
            ds, ix = tr_sets[0]
            return gather(ds, rng.choice(ix, size=n))
        counts = rng.multinomial(n, weights)
        parts = [gather(ds, rng.choice(ix, size=c))
                 for c, (ds, ix) in zip(counts, tr_sets) if c]
        return tuple(np.concatenate([p[j] for p in parts])
                     for j in range(4))

    optimizer = sgd(args.rate, 0.0, args.momentum)
    params = value_cnn.init(jax.random.key(args.seed), cfg)
    opt_state = optimizer.init(params)
    step, evaluate = make_step(cfg, optimizer)

    os.makedirs(args.out, exist_ok=True)
    history = []
    ewma = None
    t0 = time.time()
    for i in range(1, args.iters + 1):
        packed, player, rank, z = sample_batch(args.batch_size)
        params, opt_state, loss = step(params, opt_state, packed, player,
                                       rank, z)
        if i % args.print_interval == 0:
            loss = float(loss)
            ewma = loss if ewma is None else 0.95 * ewma + 0.05 * loss
            rate_s = i * args.batch_size / (time.time() - t0)
            print(f"value training {ewma:.4f} "
                  f"(samples per second {rate_s:.0f})", flush=True)
        if i % args.val_interval == 0 or i == args.iters:
            vl, va = evaluate(params, *va_batch)
            history.append({"step": i, "val_loss": float(vl),
                            "val_accuracy": float(va)})
            print(f"value validation at {i}: loss={float(vl):.4f} "
                  f"accuracy={float(va):.4f}", flush=True)
    path = os.path.join(args.out, "value_checkpoint.npz")
    save_checkpoint(path, params, opt_state, {
        "kind": "value",
        "config": {"num_layers": cfg.num_layers, "channels": cfg.channels,
                   "head_hidden": cfg.head_hidden},
        "step": args.iters,
        "equal_rank": args.equal_rank,
        "validation_history": history,
    })
    print(f"saved {path}")
    print(json.dumps({"final": history[-1] if history else None}))


if __name__ == "__main__":
    main()
