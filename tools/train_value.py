"""Train the value network: position -> P(side to move wins).

Labels come from the winner sidecar (tools/winner_index.py): every
position whose game has a decided result gets z = 1 when the side to
move won. Decided-game filtering, the 37-plane expansion, bf16 trunk,
and the fused expand+forward+backward+update step all reuse the
framework's existing pieces (GoDataset memmap shards, ops/expand,
training/optimizers.sgd, experiments/checkpoint) — this tool only adds
the batch loop and the BCE objective (models/value_cnn.py docstring for
why the framework grows a value head at all).

Usage:
  JAX_PLATFORMS=cpu python tools/train_value.py \
      --data-root data/corpus/processed --iters 2000 --out runs/value
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu.utils import honor_platform_env  # noqa: E402

honor_platform_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from deepgo_tpu.data.dataset import GoDataset, M_BLACK_RANK, M_PLAYER, \
    M_WHITE_RANK  # noqa: E402
from deepgo_tpu.models import value_cnn  # noqa: E402
from deepgo_tpu.ops.expand import expand_planes  # noqa: E402
from deepgo_tpu.training.optimizers import sgd  # noqa: E402
from deepgo_tpu.experiments.checkpoint import save_checkpoint  # noqa: E402


def decided_indices(ds: GoDataset) -> np.ndarray:
    assert ds.winner is not None, (
        f"no winner.npy in {ds.dir} — run tools/winner_index.py first")
    return np.nonzero(ds.winner != 0)[0]


def gather(ds: GoDataset, idx: np.ndarray):
    packed = np.asarray(ds.planes[idx])
    meta = ds.meta[idx]
    player = meta[:, M_PLAYER].astype(np.int32)
    rank = np.where(player == 1, meta[:, M_BLACK_RANK],
                    meta[:, M_WHITE_RANK]).astype(np.int32)
    z = (ds.winner[idx] == player).astype(np.float32)
    return packed, player, rank, z


def make_step(cfg: value_cnn.ValueConfig, optimizer):
    def loss_fn(params, packed, player, rank, z):
        planes = expand_planes(packed, player, rank)
        logits = value_cnn.apply(params, planes, cfg)
        # mean sigmoid BCE in f32 (same upcast rule as the policy NLL)
        return jnp.mean(jnp.maximum(logits, 0) - logits * z
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    @jax.jit
    def step(params, opt_state, packed, player, rank, z):
        loss, grads = jax.value_and_grad(loss_fn)(params, packed, player,
                                                  rank, z)
        params, opt_state = optimizer.update(params, grads, opt_state)
        return params, opt_state, loss

    @jax.jit
    def evaluate(params, packed, player, rank, z):
        planes = expand_planes(packed, player, rank)
        logits = value_cnn.apply(params, planes, cfg)
        acc = jnp.mean(((logits > 0) == (z > 0.5)).astype(jnp.float32))
        return loss_fn(params, packed, player, rank, z), acc

    return step, evaluate


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data-root", default="data/corpus/processed")
    ap.add_argument("--iters", type=int, default=2000)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--rate", type=float, default=0.02)
    ap.add_argument("--momentum", type=float, default=0.9)
    ap.add_argument("--num-layers", type=int, default=3)
    ap.add_argument("--channels", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--val-interval", type=int, default=500)
    ap.add_argument("--val-size", type=int, default=4096)
    ap.add_argument("--print-interval", type=int, default=100)
    ap.add_argument("--out", default="runs/value")
    args = ap.parse_args(argv)

    cfg = value_cnn.ValueConfig(num_layers=args.num_layers,
                                channels=args.channels)
    train = GoDataset(args.data_root, "train")
    val = GoDataset(args.data_root, "validation")
    tr_idx = decided_indices(train)
    va_idx = decided_indices(val)
    rng = np.random.default_rng(args.seed)
    va_batch = gather(val, rng.choice(va_idx, size=min(args.val_size,
                                                       len(va_idx)),
                                      replace=False))
    print(f"train positions (decided games): {len(tr_idx):,} of "
          f"{len(train):,}; val probe {len(va_batch[0]):,}", flush=True)

    optimizer = sgd(args.rate, 0.0, args.momentum)
    params = value_cnn.init(jax.random.key(args.seed), cfg)
    opt_state = optimizer.init(params)
    step, evaluate = make_step(cfg, optimizer)

    os.makedirs(args.out, exist_ok=True)
    history = []
    ewma = None
    t0 = time.time()
    for i in range(1, args.iters + 1):
        idx = rng.choice(tr_idx, size=args.batch_size)
        packed, player, rank, z = gather(train, idx)
        params, opt_state, loss = step(params, opt_state, packed, player,
                                       rank, z)
        if i % args.print_interval == 0:
            loss = float(loss)
            ewma = loss if ewma is None else 0.95 * ewma + 0.05 * loss
            rate_s = i * args.batch_size / (time.time() - t0)
            print(f"value training {ewma:.4f} "
                  f"(samples per second {rate_s:.0f})", flush=True)
        if i % args.val_interval == 0 or i == args.iters:
            vl, va = evaluate(params, *va_batch)
            history.append({"step": i, "val_loss": float(vl),
                            "val_accuracy": float(va)})
            print(f"value validation at {i}: loss={float(vl):.4f} "
                  f"accuracy={float(va):.4f}", flush=True)
    path = os.path.join(args.out, "value_checkpoint.npz")
    save_checkpoint(path, params, opt_state, {
        "kind": "value",
        "config": {"num_layers": cfg.num_layers, "channels": cfg.channels,
                   "head_hidden": cfg.head_hidden},
        "step": args.iters,
        "validation_history": history,
    })
    print(f"saved {path}")
    print(json.dumps({"final": history[-1] if history else None}))


if __name__ == "__main__":
    main()
