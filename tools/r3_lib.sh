# Shared helpers for the chip-work and CPU-strength queues. Source from
# a script whose cwd is the repo root:   . tools/r3_lib.sh
#
# All queue scripts (r3_tpu_queue, r3/r4_cpu_strength, r5_value_loop)
# source this lib; per-script variation comes in as parameters (log
# paths, game counts, iters), never as edited copies — the copies were
# how the stalled-grandchild kill bug and the first-artifact idempotence
# guard each had to be fixed twice.

# Real-compute canary: the relay can be in a state where claim probes
# succeed but computation wedges, so gate every stage on an actual jitted
# matmul round-trip. Returns nonzero if the chip is not answering.
canary() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128, 128))
print('canary', float(jax.jit(lambda a: (a @ a).sum())(x)))" \
    >/dev/null 2>&1
}

# supervise <log> <stall_s> <cmd...>: run cmd, kill it if <log> stops
# growing for <stall_s> seconds (a wedge mid-stage otherwise burns the
# stage's whole timeout). rc 97 = killed for stalling. The command runs
# in its own session (setsid) and the whole process GROUP is killed:
# killing only the direct child first could reparent a wedged grandchild
# (e.g. timeout's python) to init before pkill saw it, leaking a process
# that still held the single-tenant chip claim.
supervise() {
  local log=$1 stall=$2; shift 2
  setsid "$@" &
  local pid=$! last=-1 same=0
  while kill -0 $pid 2>/dev/null; do
    sleep 30
    local size=$(stat -c %s "$log" 2>/dev/null || echo 0)
    if [ "$size" = "$last" ]; then
      same=$((same + 30))
      if [ $same -ge $stall ]; then
        echo "supervise: killing stalled group $pid (log $log frozen ${same}s)"
        kill -TERM -$pid 2>/dev/null; sleep 2; kill -9 -$pid 2>/dev/null
        return 97
      fi
    else
      same=0; last=$size
    fi
  done
  wait $pid
}

# newest checkpoint whose config name is $1 -> "path step" (empty if none)
find_ckpt() {
  NAME=$1 python - <<'PY'
import os
from deepgo_tpu.experiments.checkpoint import load_meta
want = os.environ["NAME"]
best = None
for rid in os.listdir("runs"):
    p = os.path.join("runs", rid, "checkpoint.npz")
    if not os.path.exists(p):
        continue
    try:
        m = load_meta(p)
    except Exception:
        continue
    if m.get("config", {}).get("name") == want:
        if best is None or m["step"] > best[1]:
            best = (p, m["step"])
print(f"{best[0]} {best[1]}" if best else "")
PY
}

stage() { echo "=== $1 [$(date -u +%H:%M:%S)] ==="; }

# bench_artifact_ok <file>: true when the file's last line is parseable
# JSON with no TOP-LEVEL "error" key. A per-setting error nested inside
# "settings" (e.g. --mode large's remat=false OOMing at big batch) is a
# valid measured outcome; a stale last-good fallback line carries a
# top-level "error" and so stays not-ok, keeping --until-done loops
# chasing a live measurement. One definition so the done-check and the
# post-run incompleteness check cannot drift across the queue scripts.
bench_artifact_ok() {
  [ -s "$1" ] && BENCH_ARTIFACT="$1" python - <<'PY'
import json, os, sys
try:
    with open(os.environ["BENCH_ARTIFACT"]) as f:
        d = json.loads(f.read().strip().splitlines()[-1])
except Exception:
    sys.exit(1)
sys.exit(1 if "error" in d else 0)
PY
}

# ensure_winner_sidecars <corpus_root> <log>: build the winner.npy
# outcome sidecars for the train+validation shards if absent (the
# transcription finalize deletes stale ones, so "absent" is the only
# state that needs work)
ensure_winner_sidecars() {
  local root=$1 log=$2 s
  for s in train validation; do
    [ -f "$root/processed/$s/winner.npy" ] || nice -n "${NICE:-10}" \
      timeout 3600 python tools/winner_index.py \
      --processed "$root/processed/$s" --sgf "$root/sgf/$s" >> "$log" 2>&1
  done
}

# build_selfplay_corpus <out> <log> <games> <chunk> <opening_plies> <seed> <timeout_s> <pairA> [pairB...]
# Idempotence keys on the LAST transcription artifact (splits run
# train,validation,test in order and finalize writes games.json last),
# so an interrupted build reruns instead of being skipped forever.
build_selfplay_corpus() {
  local out=$1 log=$2 games=$3 chunk=$4 op=$5 seed=$6 tmo=$7; shift 7
  [ -f "$out/processed/test/games.json" ] && { echo "$out already built"; return 0; }
  stage "selfplay corpus $out"
  nice -n "${NICE:-10}" timeout "$tmo" python -u tools/make_selfplay_corpus.py \
    --out "$out" --pairs "$@" --games "$games" --chunk "$chunk" --rank 8 \
    --opening-plies "$op" --seed "$seed" >> "$log" 2>&1
  local rc=$?
  echo "selfplay corpus $out rc=$rc"
  # propagate failure so callers can gate distill/value stages on a
  # complete corpus instead of training against a partial build
  return $rc
}

# distill_winner <name> <from_ckpt> <corpus_root> <iters> <log>
# Winner-conditioned fine-tune (the expert-iteration recipe: rate .005,
# momentum .9, validate once at the end); skips when a checkpoint named
# <name> already reached from_step+iters.
distill_winner() {
  local name=$1 from=$2 corpus=$3 iters=$4 log=$5
  local ck step from_step
  read -r ck step <<< "$(find_ckpt "$name")"
  from_step=$(CKPT="$from" python - <<'PY'
import os
from deepgo_tpu.experiments.checkpoint import load_meta
print(load_meta(os.environ["CKPT"])["step"])
PY
)
  if [ -n "${ck:-}" ] && [ "${step:-0}" -ge $((from_step + iters)) ]; then
    echo "$name already at step $step"; return 0
  fi
  stage "distill $name"
  ensure_winner_sidecars "$corpus" "$log"
  nice -n "${NICE:-10}" timeout 14400 python -u -m deepgo_tpu.experiments.repeated \
    --checkpoint "$from" --iters "$iters" --set \
    name="$name" data_root="$corpus/processed" scheme=winner rate=0.005 \
    momentum=0.9 steps_per_call=1 print_interval=50 \
    validation_interval="$iters" validation_size=2048 >> "$log" 2>&1
  echo "distill $name rc=$?"
}
