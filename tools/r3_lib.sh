# Shared helpers for the round-3 chip-work queues. Source from a script
# whose cwd is the repo root:   . tools/r3_lib.sh
#
# tools/r3_tpu_queue.sh still carries inline copies of these because it
# was already executing when this file was factored out (editing a
# running bash script corrupts its lazy parse); fold it over to this lib
# the next time it is touched while idle.

# Real-compute canary: the relay can be in a state where claim probes
# succeed but computation wedges, so gate every stage on an actual jitted
# matmul round-trip. Returns nonzero if the chip is not answering.
canary() {
  timeout 120 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128, 128))
print('canary', float(jax.jit(lambda a: (a @ a).sum())(x)))" \
    >/dev/null 2>&1
}

# supervise <log> <stall_s> <cmd...>: run cmd, kill it if <log> stops
# growing for <stall_s> seconds (a wedge mid-stage otherwise burns the
# stage's whole timeout). rc 97 = killed for stalling. The command runs
# in its own session (setsid) and the whole process GROUP is killed:
# killing only the direct child first could reparent a wedged grandchild
# (e.g. timeout's python) to init before pkill saw it, leaking a process
# that still held the single-tenant chip claim.
supervise() {
  local log=$1 stall=$2; shift 2
  setsid "$@" &
  local pid=$! last=-1 same=0
  while kill -0 $pid 2>/dev/null; do
    sleep 30
    local size=$(stat -c %s "$log" 2>/dev/null || echo 0)
    if [ "$size" = "$last" ]; then
      same=$((same + 30))
      if [ $same -ge $stall ]; then
        echo "supervise: killing stalled group $pid (log $log frozen ${same}s)"
        kill -TERM -$pid 2>/dev/null; sleep 2; kill -9 -$pid 2>/dev/null
        return 97
      fi
    else
      same=0; last=$size
    fi
  done
  wait $pid
}

# newest checkpoint whose config name is $1 -> "path step" (empty if none)
find_ckpt() {
  NAME=$1 python - <<'PY'
import os
from deepgo_tpu.experiments.checkpoint import load_meta
want = os.environ["NAME"]
best = None
for rid in os.listdir("runs"):
    p = os.path.join("runs", rid, "checkpoint.npz")
    if not os.path.exists(p):
        continue
    try:
        m = load_meta(p)
    except Exception:
        continue
    if m.get("config", {}).get("name") == want:
        if best is None or m["step"] > best[1]:
            best = (p, m["step"])
print(f"{best[0]} {best[1]}" if best else "")
PY
}
