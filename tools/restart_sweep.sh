#!/bin/bash
# Multi-seed warm-restart sweep demo (round-3 verdict item 8; BASELINE
# config 3): K warm restarts of one trained checkpoint under fresh run ids
# + fresh optimizers + offset sampling seeds (reference
# experiments/repeated.lua:6-22 run with -num 1..K), then one fan-out plot
# of every restart's validation curve next to the source run's.
#
# Usage: bash tools/restart_sweep.sh [checkpoint] [iters] [K]
set -eu
cd "$(dirname "$0")/.."
CKPT=${1:-runs/cd164563/checkpoint.npz}
ITERS=${2:-400}
K=${3:-4}

RUNS=$(dirname "$(dirname "$CKPT")")
# capture each restart's run id from repeated.py's own announcement line —
# diffing `ls runs/` before/after would race with any concurrent pipeline
# stage writing run dirs into the same tree
new=""
for k in $(seq 1 "$K"); do
  out=$(python -u -m deepgo_tpu.experiments.repeated \
    --checkpoint "$CKPT" --iters "$ITERS" --num "$k" \
    --set name=restart-sweep validation_interval=100 print_interval=100)
  echo "$out" | tail -3
  rid=$(echo "$out" | sed -n 's/^warm restart \([0-9a-f]*\) from.*/\1/p')
  [ -n "$rid" ] || { echo "restart $k: no run id announced"; exit 1; }
  new="$new $RUNS/$rid"
done
echo "sweep runs:$new"
# shellcheck disable=SC2086
python -u -m deepgo_tpu.experiments.plot $(dirname "$CKPT") $new \
  --out docs/restart_sweep
echo "wrote docs/restart_sweep.csv/.png"
