#!/bin/bash
# Chip-independent strength-axis pipeline at CPU scale (3L/64): rebuilds
# the round-3 CPU checkpoints (the runs/ tree is machine-local and does
# not survive a driver restart) and adds PolicySearchAgent matches.
#
#   base:    3L/64 on the full synthetic corpus, uniform sampling
#   ft2k:    +2,000 winner-conditioned fine-tune steps (the sweep's
#            strength sweet spot; see RESULTS.md)
#   matches: ft2k and search:{base,ft2k} vs the scripted baselines
#
# Everything runs under JAX_PLATFORMS=cpu (never dials the TPU relay) and
# nice -n 10 (yields the single host core to any live chip work). Stages
# are idempotent via find_ckpt / done-markers, same as the main queue.
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r3logs
export JAX_PLATFORMS=cpu
CORPUS=data/corpus/processed
N=${NICE:-10}

read -r BASE BASE_STEP <<< "$(find_ckpt cpu-base)"
if [ -z "${BASE:-}" ] || [ "${BASE_STEP:-0}" -lt 1500 ]; then
  echo "=== cpu-base train [$(date -u +%H:%M:%S)] ==="
  nice -n $N timeout 7200 python -u -m deepgo_tpu.cli train --iters 1500 --set \
    name=cpu-base data_root=$CORPUS scheme=uniform batch_size=256 \
    steps_per_call=1 validation_interval=1500 validation_size=2048 \
    print_interval=50 \
    >> runs/r3logs/cpu_base.log 2>&1
  echo "cpu-base rc=$?"
  read -r BASE BASE_STEP <<< "$(find_ckpt cpu-base)"
fi
[ -n "${BASE:-}" ] || { echo "no cpu-base checkpoint"; exit 1; }
echo "cpu-base: $BASE (step $BASE_STEP)"

for s in train validation; do
  [ -f $CORPUS/$s/winner.npy ] || nice -n $N timeout 1800 python \
    tools/winner_index.py --processed $CORPUS/$s --sgf data/corpus/sgf/$s \
    >> runs/r3logs/cpu_ft2k.log 2>&1
done

FT_WANT=$((BASE_STEP + 2000))
read -r FT FT_STEP <<< "$(find_ckpt cpu-ft2k)"
if [ -z "${FT:-}" ] || [ "${FT_STEP:-0}" -lt "$FT_WANT" ]; then
  echo "=== cpu-ft2k fine-tune [$(date -u +%H:%M:%S)] ==="
  nice -n $N timeout 10800 python -u -m deepgo_tpu.experiments.repeated \
    --checkpoint "$BASE" --iters 2000 --set \
    name=cpu-ft2k scheme=winner rate=0.005 momentum=0.9 steps_per_call=1 \
    print_interval=50 validation_interval=2000 validation_size=2048 \
    >> runs/r3logs/cpu_ft2k.log 2>&1
  echo "cpu-ft2k rc=$?"
  read -r FT FT_STEP <<< "$(find_ckpt cpu-ft2k)"
fi
if [ -z "${FT:-}" ] || [ "${FT_STEP:-0}" -lt "$FT_WANT" ]; then
  echo "cpu-ft2k incomplete (${FT_STEP:-0} < $FT_WANT); rerun to finish"
  exit 1
fi
echo "cpu-ft2k: $FT (step $FT_STEP)"

# cpu_match <spec> <opponent> <tag>
cpu_match() {
  local spec=$1 opp=$2 tag=$3
  local mark=runs/r3logs/done_cpu_arena_$tag
  [ -f "$mark" ] && { echo "cpu arena $tag already done"; return 0; }
  echo "=== cpu arena $tag [$(date -u +%H:%M:%S)] ==="
  nice -n $N timeout 7200 python -u -m deepgo_tpu.arena \
    --a "$spec" --b "$opp" --games 200 --rank 8 --seed 11 \
    >> runs/r3logs/cpu_arena.log 2>&1
  local rc=$?
  [ $rc -eq 0 ] && touch "$mark"
  echo "cpu arena $tag rc=$rc"
  tail -1 runs/r3logs/cpu_arena.log
}

cpu_match "checkpoint:$FT" oneply cpu_ft2k_oneply
cpu_match "search:$FT" oneply cpu_search_ft2k_oneply
cpu_match "search:$BASE" oneply cpu_search_base_oneply
cpu_match "search:$FT" heuristic cpu_search_ft2k_heuristic
echo "=== cpu strength pipeline done [$(date -u +%H:%M:%S)] ==="
