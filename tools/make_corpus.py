"""Generate a synthetic arena corpus at KGS scale.

The 55% KGS top-1 north star needs ~27M human positions that do not exist
in this zero-egress environment (BASELINE.md; reference README.md:5), so
the accuracy axis is exercised on data the framework generates itself:
arena games between the scripted baselines (HeuristicAgent, OnePlyAgent)
plus any checkpoint-backed agents mixed in via ``--extra``, written as
ranked SGFs and pushed through the exact same
transcription -> shard -> loader -> train pipeline a real corpus would use
(reference pipeline anchors: makedata.lua:517-576, data.lua:29-80).

Agent identity is encoded in the dan-rank tags (oneply=8d, heuristic=4d,
``--extra SPEC=RANK`` as given), so the model can condition on "player
strength" through the rank planes exactly like KGS dan ranks (reference
dataloader.lua:12-13,87). Every unordered agent pairing (self-pairs
included) is cycled for move-distribution diversity, and colors alternate
inside each chunk so both color assignments occur (arena.play_match).

``--opening-plies N`` starts every game from N independent uniformly-
random legal moves (per GAME, not per pair): round 4 measured per-game
random openings worth +6.6 points of downstream strength on the
expert-corpus axis — trajectory diversity is the difference between a
corpus a model saturates at 400k positions and one where the data axis
keeps paying (round-4 verdict items 3/weak-2).

Usage:
  python tools/make_corpus.py --out data/corpus --positions 5000000
  # round-5 diversified recipe:
  python tools/make_corpus.py --out data/corpus2 --positions 3400000 \
      --opening-plies 8 \
      --extra search:runs/<id>/checkpoint.npz=9 \
      --extra checkpoint:runs/<id>/checkpoint.npz=6
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu import arena  # noqa: E402
from deepgo_tpu.selfplay import to_sgf  # noqa: E402

RANK_OF = {"heuristic": 4, "oneply": 8}


def split_of(gid: int) -> str:
    """Deterministic 2% validation / 2% test / 96% train by game id."""
    r = gid % 50
    return {1: "validation", 2: "test"}.get(r, "train")


def build_pool(extra: list[str], seed: int,
               temperature: float) -> dict[str, tuple[arena.Agent, int]]:
    """name -> (agent, rank): the scripted baselines plus --extra specs.

    Each extra is SPEC=RANK (e.g. search:ckpt.npz=9); the spec goes
    through arena._make_agent, so every agent family the arena knows is
    available to the generator. Sampling policy agents (checkpoint:/
    model:) get ``temperature`` for extra move diversity; the search
    family ignores it (deterministic re-rankers).
    """
    pool: dict[str, tuple[arena.Agent, int]] = {
        "heuristic": (arena.HeuristicAgent(), RANK_OF["heuristic"]),
        "oneply": (arena.OnePlyAgent(), RANK_OF["oneply"]),
    }
    for i, item in enumerate(extra or []):
        spec, _, rank_s = item.rpartition("=")
        assert spec and rank_s.isdigit(), (
            f"--extra wants SPEC=RANK, got {item!r}")
        agent = arena._make_agent(spec, seed + 1000 + i, temperature,
                                  int(rank_s))
        pool[f"x{i}-{agent.name}"] = (agent, int(rank_s))
    return pool


def generate(out: str, target_positions: int, chunk: int, max_moves: int,
             seed: int, opening_plies: int = 0,
             pool: dict[str, tuple[arena.Agent, int]] | None = None) -> dict:
    if pool is None:
        pool = build_pool([], seed, 0.0)
    # strongest first; with the default pool this reproduces the legacy
    # pair cycle [(oneply,oneply), (oneply,heuristic), (heuristic,
    # heuristic)] so `--positions N --seed 0` still regenerates the
    # round-4 corpus bit-exactly (fresh-machine recipe, RESULTS.md)
    names = sorted(pool, key=lambda n: (-pool[n][1], n))
    pairs = [(a, b) for i, a in enumerate(names) for b in names[i:]]
    for split in ("train", "validation", "test"):
        os.makedirs(os.path.join(out, "sgf", split), exist_ok=True)

    totals = {"games": 0, "positions": 0, "truncated": 0}
    t0 = time.time()
    round_idx = 0
    while totals["positions"] < target_positions:
        name_a, name_b = pairs[round_idx % len(pairs)]
        games, scores, stats = arena.play_match(
            pool[name_a][0], pool[name_b][0], n_games=chunk,
            max_moves=max_moves, seed=seed + round_idx,
            opening_plies=opening_plies,
            # per-GAME openings: a deterministic self-pair from a
            # pair-shared opening is the same game twice, and duplicates
            # can straddle the train/validation split downstream
            shared_openings=False)
        totals["truncated"] += stats["truncated"]
        for i, (g, s) in enumerate(zip(games, scores)):
            gid = totals["games"]
            totals["games"] += 1
            totals["positions"] += len(g.moves)
            split = split_of(gid)
            # colors alternate inside play_match: even game index gives
            # black to agent A
            black, white = (name_a, name_b) if i % 2 == 0 else (name_b, name_a)
            done = g.passes >= 2
            path = os.path.join(out, "sgf", split, f"g{gid:07d}.sgf")
            with open(path, "w") as f:
                f.write(to_sgf(
                    g,
                    black_rank=pool[black][1], white_rank=pool[white][1],
                    result=s.result_string() if done else None, komi=7.5))
        round_idx += 1
        rate = totals["positions"] / (time.time() - t0)
        print(f"{totals['positions']:,}/{target_positions:,} positions "
              f"({totals['games']:,} games, {rate:,.0f} pos/sec)", flush=True)
    totals["gen_seconds"] = time.time() - t0
    return totals


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="data/corpus")
    ap.add_argument("--positions", type=int, default=5_000_000)
    ap.add_argument("--chunk", type=int, default=1024,
                    help="games advanced in lockstep per match call")
    ap.add_argument("--max-moves", type=int, default=350)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--opening-plies", type=int, default=0,
                    help="independent random opening moves per game "
                         "(trajectory diversity; 8 = round-5 recipe)")
    ap.add_argument("--extra", action="append", default=[],
                    help="additional agent as SPEC=RANK (repeatable), e.g. "
                         "search:runs/<id>/checkpoint.npz=9")
    ap.add_argument("--temperature", type=float, default=0.25,
                    help="sampling temperature for checkpoint:/model: "
                         "--extra agents (diversity; search family "
                         "ignores it)")
    ap.add_argument("--transcribe-workers", type=int,
                    default=max(1, (os.cpu_count() or 2) - 1))
    ap.add_argument("--skip-transcribe", action="store_true")
    args = ap.parse_args(argv)

    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()
    pool = build_pool(args.extra, args.seed, args.temperature)
    print({name: (agent.name, rank) for name, (agent, rank) in pool.items()})
    totals = generate(args.out, args.positions, args.chunk, args.max_moves,
                      args.seed, args.opening_plies, pool)
    print(totals)

    if not args.skip_transcribe:
        from deepgo_tpu.data.transcribe import transcribe_split

        for split in ("train", "validation", "test"):
            t0 = time.time()
            n = transcribe_split(
                os.path.join(args.out, "sgf", split),
                os.path.join(args.out, "processed", split),
                workers=args.transcribe_workers, verbose=False)
            print(f"transcribed {split}: {n:,} examples "
                  f"in {time.time() - t0:.0f}s", flush=True)


if __name__ == "__main__":
    main()
