"""Generate a synthetic arena corpus at KGS scale.

The 55% KGS top-1 north star needs ~27M human positions that do not exist
in this zero-egress environment (BASELINE.md; reference README.md:5), so
the accuracy axis is exercised on data the framework generates itself:
arena games between the scripted baselines (HeuristicAgent, OnePlyAgent),
written as ranked SGFs and pushed through the exact same
transcription -> shard -> loader -> train pipeline a real corpus would use
(reference pipeline anchors: makedata.lua:517-576, data.lua:29-80).

Agent identity is encoded in the dan-rank tags (oneply=8d, heuristic=4d),
so the model can condition on "player strength" through the rank planes
exactly like KGS dan ranks (reference dataloader.lua:12-13,87). Game pairs
cycle through the three distinct matchups for move-distribution diversity
(colors alternate inside each chunk, so both color assignments of the
mixed pair occur — arena.play_match).

Usage:
  python tools/make_corpus.py --out data/corpus --positions 5000000
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu import arena  # noqa: E402
from deepgo_tpu.selfplay import to_sgf  # noqa: E402

RANK_OF = {"heuristic": 4, "oneply": 8}


def split_of(gid: int) -> str:
    """Deterministic 2% validation / 2% test / 96% train by game id."""
    r = gid % 50
    return {1: "validation", 2: "test"}.get(r, "train")


def generate(out: str, target_positions: int, chunk: int, max_moves: int,
             seed: int) -> dict:
    pairs = [("oneply", "oneply"), ("oneply", "heuristic"),
             ("heuristic", "heuristic")]
    agents = {"heuristic": arena.HeuristicAgent(), "oneply": arena.OnePlyAgent()}
    for split in ("train", "validation", "test"):
        os.makedirs(os.path.join(out, "sgf", split), exist_ok=True)

    totals = {"games": 0, "positions": 0, "truncated": 0}
    t0 = time.time()
    round_idx = 0
    while totals["positions"] < target_positions:
        name_a, name_b = pairs[round_idx % len(pairs)]
        games, scores, stats = arena.play_match(
            agents[name_a], agents[name_b], n_games=chunk,
            max_moves=max_moves, seed=seed + round_idx)
        totals["truncated"] += stats["truncated"]
        for i, (g, s) in enumerate(zip(games, scores)):
            gid = totals["games"]
            totals["games"] += 1
            totals["positions"] += len(g.moves)
            split = split_of(gid)
            # colors alternate inside play_match: even game index gives
            # black to agent A
            black, white = (name_a, name_b) if i % 2 == 0 else (name_b, name_a)
            done = g.passes >= 2
            path = os.path.join(out, "sgf", split, f"g{gid:07d}.sgf")
            with open(path, "w") as f:
                f.write(to_sgf(
                    g,
                    black_rank=RANK_OF[black], white_rank=RANK_OF[white],
                    result=s.result_string() if done else None, komi=7.5))
        round_idx += 1
        rate = totals["positions"] / (time.time() - t0)
        print(f"{totals['positions']:,}/{target_positions:,} positions "
              f"({totals['games']:,} games, {rate:,.0f} pos/sec)", flush=True)
    totals["gen_seconds"] = time.time() - t0
    return totals


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="data/corpus")
    ap.add_argument("--positions", type=int, default=5_000_000)
    ap.add_argument("--chunk", type=int, default=1024,
                    help="games advanced in lockstep per match call")
    ap.add_argument("--max-moves", type=int, default=350)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--transcribe-workers", type=int,
                    default=max(1, (os.cpu_count() or 2) - 1))
    ap.add_argument("--skip-transcribe", action="store_true")
    args = ap.parse_args(argv)

    totals = generate(args.out, args.positions, args.chunk, args.max_moves,
                      args.seed)
    print(totals)

    if not args.skip_transcribe:
        from deepgo_tpu.data.transcribe import transcribe_split

        for split in ("train", "validation", "test"):
            t0 = time.time()
            n = transcribe_split(
                os.path.join(args.out, "sgf", split),
                os.path.join(args.out, "processed", split),
                workers=args.transcribe_workers, verbose=False)
            print(f"transcribed {split}: {n:,} examples "
                  f"in {time.time() - t0:.0f}s", flush=True)


if __name__ == "__main__":
    main()
