"""Carve a positions-budgeted subset out of a transcribed split.

Game-aligned prefix copy: whole games are taken in order until the position
budget is reached, so the subset is itself a valid split (planes.bin prefix
+ rewritten meta/games.json). Used to build the accuracy-vs-corpus-size
curve (train the same config on 4k / 40k / 400k / 4M positions of the same
distribution and evaluate on the shared held-out split).

Usage:
  python tools/subset_split.py --src data/corpus/processed/train \
      --out data/corpus/processed/train_40k --positions 40000
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu.data.dataset import RECORD_BYTES  # noqa: E402


def subset_prefix_copy(src: str, out: str, positions: int) -> int:
    """Copy only the needed prefix of planes.bin (no full-file copy)."""
    with open(os.path.join(src, "games.json")) as f:
        games = json.load(f)
    keep = []
    total = 0
    for g in games:
        if total >= positions:
            break
        keep.append(g)
        total += g["count"]
    assert keep, "empty subset"

    os.makedirs(out, exist_ok=True)
    meta = np.load(os.path.join(src, "meta.npy"))
    np.save(os.path.join(out, "meta.npy"), meta[:total])
    with open(os.path.join(out, "games.json"), "w") as f:
        json.dump(keep, f)
    remaining = total * RECORD_BYTES
    with open(os.path.join(src, "planes.bin"), "rb") as fin, \
            open(os.path.join(out, "planes.bin"), "wb") as fout:
        while remaining > 0:
            chunk = fin.read(min(64 << 20, remaining))
            assert chunk, "planes.bin shorter than meta implies"
            fout.write(chunk)
            remaining -= len(chunk)
    return total


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--src", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--positions", type=int, required=True)
    args = ap.parse_args(argv)
    n = subset_prefix_copy(args.src, args.out, args.positions)
    print(f"{args.out}: {n:,} positions")


if __name__ == "__main__":
    main()
