"""Measure 8-fold dihedral symmetry-averaged inference vs the plain net.

Round-4 verdict item 8: ensembling the 8 board symmetries at eval time
(models/serving.make_sym_policy_fn) is likely the cheapest accuracy lever
available — this tool measures both sides of the trade on a full split:
test top-1 / NLL delta, and the boards/sec cost of the 8x forward.

Usage:
  python tools/symmetry_eval.py --checkpoint runs/<id>/checkpoint.npz \
      [--data-root data/corpus/processed] [--split test] [--batch 512]
      [--limit N] [--out docs/symmetry_eval.jsonl]

Prints one JSON line per mode; optionally appends them to --out.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def evaluate(predict, params, ds, batch: int, limit: int,
             label: str = "") -> dict:
    """Fixed-order sweep of the split's first ``limit`` positions.

    One warm-up batch runs before the clock starts (compile + first
    dispatch would otherwise skew boards/sec — the exact cost this tool
    measures; a limit=100 smoke run read cost_ratio 2.36 from compile
    alone). Progress prints every few batches keep a log-stall supervisor
    (r5 queue, 600 s) from killing a healthy full-split sweep."""
    n = min(limit, len(ds)) if limit else len(ds)

    def load(i):
        packed, player, rank, target = ds.batch_at(
            np.arange(i, min(i + batch, n)))
        size = len(target)
        if size < batch:  # pad to the jitted shape; score real rows only
            pad = batch - size
            packed = np.concatenate([packed, np.zeros(
                (pad, *packed.shape[1:]), packed.dtype)])
            player = np.concatenate([player, np.ones(pad, player.dtype)])
            rank = np.concatenate([rank, np.ones(pad, rank.dtype)])
        return packed, player, rank, target, size

    packed, player, rank, _, _ = load(0)
    np.asarray(predict(params, packed, player, rank))  # warm: compile+run

    correct = nll = seen = 0.0
    t0 = last = time.time()
    for i in range(0, n, batch):
        packed, player, rank, target, size = load(i)
        logp = np.asarray(predict(params, packed, player, rank))[:size]
        correct += (logp.argmax(axis=1) == target).sum()
        nll += -logp[np.arange(size), target].sum()
        seen += size
        if time.time() - last > 60:
            last = time.time()
            print(f"# {label} {int(seen)}/{n} positions, "
                  f"{seen / (last - t0):.0f} boards/sec", flush=True)
    dt = time.time() - t0
    return {
        "n": int(seen),
        "top1": round(float(correct / seen), 5),
        "nll": round(float(nll / seen), 5),
        "seconds": round(dt, 2),
        "boards_per_sec": round(seen / dt, 1),
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--checkpoint", required=True)
    ap.add_argument("--data-root", default="data/corpus/processed")
    ap.add_argument("--split", default="test")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--limit", type=int, default=0,
                    help="positions to evaluate (0 = whole split)")
    ap.add_argument("--out", help="JSONL file to append results to")
    args = ap.parse_args(argv)

    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()
    from deepgo_tpu.data import GoDataset
    from deepgo_tpu.models.serving import (load_policy, make_policy_fn,
                                           make_sym_policy_fn)

    _, params, cfg = load_policy(args.checkpoint)
    ds = GoDataset(args.data_root, args.split)
    plain_fn = make_policy_fn(cfg, top_k=1)

    def plain(params, packed, player, rank):
        return plain_fn(params, packed, player, rank)["log_probs"]

    sym = make_sym_policy_fn(cfg)
    lines = []
    for mode, fn in (("plain", plain), ("sym8", sym)):
        r = dict(evaluate(fn, params, ds, args.batch, args.limit, label=mode),
                 mode=mode, checkpoint=args.checkpoint, split=args.split)
        lines.append(r)
        print(json.dumps(r), flush=True)
    delta = lines[1]["top1"] - lines[0]["top1"]
    print(json.dumps({"mode": "delta", "top1_delta": round(delta, 5),
                      "cost_ratio": round(lines[0]["boards_per_sec"]
                                          / max(lines[1]["boards_per_sec"],
                                                1e-9), 2)}), flush=True)
    if args.out:
        with open(args.out, "a") as f:
            for r in lines:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    main()
