#!/bin/bash
# Round-3 TPU work queue: every chip-bound measurement, run sequentially so
# only one process holds the single-tenant relay claim at a time. Each
# stage appends to its own log under runs/r3logs/; a stage failure does not
# stop later stages (the chip may recover mid-queue).
#
# Usage: bash tools/r3_tpu_queue.sh [stage ...]   (default: all stages)
set -u
cd "$(dirname "$0")/.."
mkdir -p runs/r3logs
CORPUS=data/corpus/processed

stage() { echo "=== $1 [$(date -u +%H:%M:%S)] ==="; }

run_curve() {
  stage curve
  timeout 7200 python tools/accuracy_curve.py \
    --data-root $CORPUS \
    --budgets 4000,40000,400000,3294221 --iters 4000 \
    --out docs/accuracy_curve.jsonl \
    --set num_layers=12 channels=128 batch_size=512 \
    >> runs/r3logs/curve.log 2>&1
  echo "curve rc=$?"
}

run_converge() {
  stage converge
  timeout 10800 python -m deepgo_tpu.cli train --iters 16000 --set \
    name=converge-12L128 data_root=$CORPUS scheme=uniform \
    num_layers=12 channels=128 batch_size=1024 steps_per_call=20 \
    rate=0.02 momentum=0.9 rate_decay=1e-7 \
    validation_interval=2000 validation_size=4096 print_interval=100 \
    >> runs/r3logs/converge.log 2>&1
  echo "converge rc=$?"
}

# newest checkpoint whose config name is $1 (empty if none)
find_ckpt() {
  NAME=$1 python - <<'PY'
import os
from deepgo_tpu.experiments.checkpoint import load_meta
want = os.environ["NAME"]
best = None
for rid in os.listdir("runs"):
    p = os.path.join("runs", rid, "checkpoint.npz")
    if not os.path.exists(p):
        continue
    try:
        m = load_meta(p)
    except Exception:
        continue
    if m.get("config", {}).get("name") == want:
        if best is None or m["step"] > best[1]:
            best = (p, m["step"])
print(best[0] if best else "")
PY
}

# 200-game matches of checkpoint $1 vs oneply and heuristic, tag $2
match_vs_baselines() {
  for opp in oneply heuristic; do
    timeout 3600 python -m deepgo_tpu.arena \
      --a checkpoint:$1 --b $opp --games 200 --rank 8 --seed 11 \
      --sgf-out runs/r3logs/arena_$2_$opp \
      >> runs/r3logs/arena.log 2>&1
    echo "arena $2 vs $opp rc=$?"
  done
}

run_arena() {
  stage arena
  CKPT=$(find_ckpt converge-12L128)
  echo "arena checkpoint: $CKPT"
  [ -n "$CKPT" ] || { echo "no converge checkpoint; skipping arena"; return; }
  match_vs_baselines "$CKPT" base
  tail -4 runs/r3logs/arena.log
}

run_finetune() {
  stage finetune-winner
  CKPT=$(find_ckpt converge-12L128)
  [ -n "$CKPT" ] || { echo "no converge checkpoint; skipping finetune"; return; }
  for s in train validation; do
    [ -f $CORPUS/$s/winner.npy ] || timeout 900 python tools/winner_index.py \
      --processed $CORPUS/$s --sgf data/corpus/sgf/$s \
      >> runs/r3logs/finetune.log 2>&1
  done
  timeout 7200 python -m deepgo_tpu.experiments.repeated \
    --checkpoint "$CKPT" --iters 4000 --set \
    name=ft-winner scheme=winner rate=0.005 momentum=0.9 steps_per_call=20 \
    print_interval=100 validation_interval=2000 validation_size=4096 \
    >> runs/r3logs/finetune.log 2>&1
  echo "finetune rc=$?"
  FT=$(find_ckpt ft-winner)
  [ -n "$FT" ] || { echo "no finetune checkpoint"; return; }
  match_vs_baselines "$FT" ftwinner
  tail -4 runs/r3logs/arena.log
}

run_large() {
  stage large-13L256
  for remat in false true; do
    timeout 3600 python -m deepgo_tpu.cli train --iters 300 --set \
      name=large-remat-$remat data_root=$CORPUS scheme=uniform \
      num_layers=13 channels=256 batch_size=4096 remat=$remat \
      steps_per_call=10 rate=0.01 validation_interval=300 \
      validation_size=2048 print_interval=50 \
      >> runs/r3logs/large_$remat.log 2>&1
    echo "large remat=$remat rc=$?"
    grep "samples per second" runs/r3logs/large_$remat.log | tail -2
  done
}

run_selfplay() {
  stage selfplay
  CKPT=$(ls -t runs/*/checkpoint.npz 2>/dev/null | head -1)
  [ -n "$CKPT" ] || { echo "no checkpoint; skipping selfplay"; return; }
  timeout 3600 python -m deepgo_tpu.selfplay \
    --games 256 --checkpoint "$CKPT" --max-moves 250 \
    >> runs/r3logs/selfplay.log 2>&1
  echo "selfplay rc=$?"
  tail -1 runs/r3logs/selfplay.log
}

run_bench() {
  stage bench
  for mode in inference train latency; do
    timeout 1200 python bench.py --mode $mode \
      > runs/r3logs/bench_$mode.json 2> runs/r3logs/bench_$mode.err
    echo "bench $mode rc=$?"
    tail -1 runs/r3logs/bench_$mode.json
  done
}

if [ $# -eq 0 ]; then
  set -- curve converge arena finetune selfplay large bench
fi
for s in "$@"; do run_$s; done
echo "=== queue done [$(date -u +%H:%M:%S)] ==="
