#!/bin/bash
# Round-3 TPU work queue: every chip-bound measurement, run sequentially so
# only one process holds the single-tenant relay claim at a time. Each
# stage appends to its own log under runs/r3logs/; a stage failure does not
# stop later stages (the chip may recover mid-queue).
#
# Usage: bash tools/r3_tpu_queue.sh [stage ...]   (default: all stages)
set -u
cd "$(dirname "$0")/.."
mkdir -p runs/r3logs
CORPUS=data/corpus/processed

. tools/r3_lib.sh  # canary / supervise (setsid group-kill) / find_ckpt

run_curve() {
  stage curve
  if [ "$(wc -l < docs/accuracy_curve.jsonl 2>/dev/null || echo 0)" -ge 4 ]; then
    echo "curve already has 4 points; skipping"; return 0
  fi
  canary || { echo "canary failed; skipping curve"; return 1; }
  supervise runs/r3logs/curve.log 600 \
    timeout 7200 python -u tools/accuracy_curve.py \
    --data-root $CORPUS \
    --budgets 4000,40000,400000,3288963 --iters 4000 \
    --out docs/accuracy_curve.jsonl \
    --set num_layers=12 channels=128 batch_size=512 \
    >> runs/r3logs/curve.log 2>&1
  echo "curve rc=$?"
}

CONVERGE_ITERS=16000

run_converge() {
  stage converge
  # batch 512 / rate 0.01 / no momentum = the PROVEN flagship-curve recipe
  # (docs/accuracy_curve.jsonl); the earlier 1024/0.02/0.9 setting NaNs
  # 12L/128 from the first print window
  read -r CKPT STEP <<< "$(find_ckpt converge-12L128)"
  if [ -n "${CKPT:-}" ] && [ "${STEP:-0}" -ge $CONVERGE_ITERS ]; then
    echo "converge already at step $STEP; skipping"; return 0
  fi
  canary || { echo "canary failed; skipping converge"; return 1; }
  if [ -n "${CKPT:-}" ]; then
    # save-on-validate checkpoints make a killed run resumable
    echo "resuming converge from $CKPT (step $STEP)"
    supervise runs/r3logs/converge.log 600 \
      timeout 10800 python -u -m deepgo_tpu.cli train \
      --resume "$CKPT" --iters $((CONVERGE_ITERS - STEP)) \
      >> runs/r3logs/converge.log 2>&1
  else
    supervise runs/r3logs/converge.log 600 \
      timeout 10800 python -u -m deepgo_tpu.cli train --iters $CONVERGE_ITERS --set \
      name=converge-12L128 data_root=$CORPUS scheme=uniform \
      num_layers=12 channels=128 batch_size=512 steps_per_call=20 \
      rate=0.01 momentum=0.0 rate_decay=1e-7 \
      validation_interval=2000 validation_size=4096 print_interval=100 \
      >> runs/r3logs/converge.log 2>&1
  fi
  echo "converge rc=$?"
}


# 200-game matches of checkpoint $1 vs oneply and heuristic, tag $2
match_vs_baselines() {
  for opp in oneply heuristic; do
    local mark=runs/r3logs/done_arena_$2_$opp
    [ -f "$mark" ] && { echo "arena $2 vs $opp already done"; continue; }
    canary || { echo "canary failed; skipping $2 vs $opp"; return 1; }
    supervise runs/r3logs/arena.log 600 \
      timeout 3600 python -u -m deepgo_tpu.arena \
      --a checkpoint:$1 --b $opp --games 200 --rank 8 --seed 11 \
      --sgf-out runs/r3logs/arena_$2_$opp \
      >> runs/r3logs/arena.log 2>&1
    local rc=$?
    [ $rc -eq 0 ] && touch "$mark"
    echo "arena $2 vs $opp rc=$rc"
  done
}

run_arena() {
  stage arena
  read -r CKPT STEP <<< "$(find_ckpt converge-12L128)"
  echo "arena checkpoint: ${CKPT:-none} (step ${STEP:-0})"
  [ -n "${CKPT:-}" ] || { echo "no converge checkpoint; skipping arena"; return; }
  match_vs_baselines "$CKPT" base
  tail -4 runs/r3logs/arena.log
}

run_finetune() {
  stage finetune-winner
  read -r CKPT STEP <<< "$(find_ckpt converge-12L128)"
  [ -n "${CKPT:-}" ] || { echo "no converge checkpoint; skipping finetune"; return; }
  read -r FT FT_STEP <<< "$(find_ckpt ft-winner)"
  if [ -z "${FT:-}" ] || [ "${FT_STEP:-0}" -lt $((STEP + 4000)) ]; then
    for s in train validation; do
      [ -f $CORPUS/$s/winner.npy ] || timeout 900 python tools/winner_index.py \
        --processed $CORPUS/$s --sgf data/corpus/sgf/$s \
        >> runs/r3logs/finetune.log 2>&1
    done
    canary || { echo "canary failed; skipping finetune"; return 1; }
    supervise runs/r3logs/finetune.log 600 \
      timeout 7200 python -u -m deepgo_tpu.experiments.repeated \
      --checkpoint "$CKPT" --iters 4000 --set \
      name=ft-winner scheme=winner rate=0.005 momentum=0.9 steps_per_call=20 \
      print_interval=100 validation_interval=2000 validation_size=4096 \
      >> runs/r3logs/finetune.log 2>&1
    echo "finetune rc=$?"
    read -r FT FT_STEP <<< "$(find_ckpt ft-winner)"
  else
    echo "finetune already at step $FT_STEP; skipping training"
  fi
  [ -n "${FT:-}" ] || { echo "no finetune checkpoint"; return; }
  match_vs_baselines "$FT" ftwinner
  tail -4 runs/r3logs/arena.log
}

run_large() {
  stage large-13L256
  for remat in false true; do
    [ -f runs/r3logs/done_large_$remat ] && { echo "large remat=$remat already done"; continue; }
    canary || { echo "canary failed; skipping large remat=$remat"; return 1; }
    supervise runs/r3logs/large_$remat.log 600 \
      timeout 3600 python -u -m deepgo_tpu.cli train --iters 300 --set \
      name=large-remat-$remat data_root=$CORPUS scheme=uniform \
      num_layers=13 channels=256 batch_size=4096 remat=$remat \
      steps_per_call=10 rate=0.01 validation_interval=300 \
      validation_size=2048 print_interval=50 \
      >> runs/r3logs/large_$remat.log 2>&1
    rc=$?
    [ $rc -eq 0 ] && touch runs/r3logs/done_large_$remat
    echo "large remat=$remat rc=$rc"
    grep "samples per second" runs/r3logs/large_$remat.log | tail -2
  done
}

run_selfplay() {
  stage selfplay
  [ -f runs/r3logs/done_selfplay ] && { echo "selfplay already done"; return 0; }
  CKPT=$(ls -t runs/*/checkpoint.npz 2>/dev/null | head -1)
  [ -n "$CKPT" ] || { echo "no checkpoint; skipping selfplay"; return; }
  canary || { echo "canary failed; skipping selfplay"; return 1; }
  supervise runs/r3logs/selfplay.log 600 \
    timeout 3600 python -u -m deepgo_tpu.selfplay \
    --games 256 --checkpoint "$CKPT" --max-moves 250 \
    >> runs/r3logs/selfplay.log 2>&1
  rc=$?
  [ $rc -eq 0 ] && touch runs/r3logs/done_selfplay
  echo "selfplay rc=$rc"
  tail -1 runs/r3logs/selfplay.log
}

run_bench() {
  stage bench
  for mode in inference train latency large; do
    if bench_artifact_ok runs/r3logs/bench_$mode.json; then
      echo "bench $mode already done"; continue
    fi
    canary || { echo "canary failed; skipping bench $mode"; return 1; }
    # 2400s envelope: worst-case preflight (3 failed 60s canaries +
    # 60/120s backoffs = 360s) + the 900s bench watchdog must both
    # fit, or the outer timeout SIGKILLs before any JSON line is emitted
    timeout 2400 python bench.py --mode $mode \
      > runs/r3logs/bench_$mode.json 2> runs/r3logs/bench_$mode.err
    echo "bench $mode rc=$?"
    tail -1 runs/r3logs/bench_$mode.json
    # a stale-fallback line exits 0 but leaves a TOP-LEVEL "error" key in
    # the artifact; surface that to the --until-done grep so the retry
    # horizon keeps trying for a LIVE measurement
    bench_artifact_ok runs/r3logs/bench_$mode.json \
      || echo "bench $mode incomplete (error/stale artifact)"
  done
}

if [ "${1:-}" = "--until-done" ]; then
  # outer driver for a flapping chip: every stage is idempotent, so just
  # re-run the whole queue until nothing is left to do (or attempts run
  # out), waiting for a live canary between rounds
  for attempt in $(seq 1 30); do
    echo "=== until-done attempt $attempt [$(date -u +%H:%M:%S)] ==="
    until canary; do echo "canary down; waiting"; sleep 120; done
    out=$(bash "$0" 2>&1)
    echo "$out"
    if ! echo "$out" | grep -qE "canary failed|rc=[1-9]|incomplete"; then
      echo "=== all stages complete ==="
      exit 0
    fi
    sleep 60
  done
  echo "=== attempts exhausted ==="
  exit 1
fi

if [ $# -eq 0 ]; then
  set -- curve converge arena finetune selfplay large bench
fi
for s in "$@"; do run_$s; done
echo "=== queue done [$(date -u +%H:%M:%S)] ==="
