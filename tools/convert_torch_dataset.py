"""Convert a reference-format transcribed dataset into deepgo_tpu shards.

For users migrating from the reference framework with an already-transcribed
corpus (one torch-serialized file per move under <root>/<split>/<game>/K,
reference makedata.lua:537-559) but without the source SGFs: decodes each
record with tools/t7reader.py and writes this framework's memmap shard
format directly — no SGF replay involved.

Usage:
  python tools/convert_torch_dataset.py --src /root/reference/data \
      --out data/processed_from_torch [--splits train,validation,test]
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import t7reader  # noqa: E402
from deepgo_tpu.data.dataset import META_COLS, DatasetWriter  # noqa: E402


def convert_game(game_dir: str):
    files = sorted(
        (f for f in os.listdir(game_dir) if f.isdigit()), key=int
    )
    packed, meta = [], []
    for f in files:
        rec = t7reader.load(os.path.join(game_dir, f))
        move, ranks = rec["move"], rec["ranks"]
        packed.append(rec["input"])
        meta.append((int(move["player"]), int(move["x"]) - 1, int(move["y"]) - 1,
                     int(ranks[1]), int(ranks[2]), 0))
    if not packed:
        return None
    return np.stack(packed), np.array(meta, dtype=np.int32).reshape(-1, META_COLS)


def convert_split(src: str, out_dir: str, verbose: bool = True) -> int:
    writer = DatasetWriter(out_dir)
    for root, dirs, _files in os.walk(src):
        for d in sorted(dirs):
            game_dir = os.path.join(root, d)
            if not os.path.isfile(os.path.join(game_dir, "1")):
                continue
            result = convert_game(game_dir)
            if result is not None:
                writer.add_game(os.path.relpath(game_dir, src), *result)
    total = writer.finalize()
    if verbose:
        print(f"{out_dir}: {total} examples")
    return total


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--src", required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--splits", default="train,validation,test")
    args = ap.parse_args()
    for split in args.splits.split(","):
        convert_split(os.path.join(args.src, split),
                      os.path.join(args.out, split))


if __name__ == "__main__":
    main()
