"""Generate a ranked corpus from arbitrary agent pairings (self-play loop).

Where tools/make_corpus.py fixes the scripted-baseline pairings, this one
takes agent SPECS (arena._make_agent syntax: oneply | heuristic | random |
checkpoint:PATH | search:PATH | model:NAME) so a TRAINED policy can
generate its own next training corpus — the data side of the
imitation -> outcome-conditioned -> self-play improvement loop. Games are
written as SGFs with the given dan-rank tags and split train/validation/
test by game id exactly like make_corpus, then transcribed through the
same shard pipeline (reference pipeline anchors: makedata.lua:517-576).

Usage:
  python tools/make_selfplay_corpus.py --out data/iter1 \
      --pairs "checkpoint:runs/X/checkpoint.npz,oneply" \
              "checkpoint:runs/X/checkpoint.npz,checkpoint:runs/X/checkpoint.npz" \
      --games 2048 --temperature 0.25 --rank 8
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu import arena  # noqa: E402
from deepgo_tpu.selfplay import to_sgf  # noqa: E402
from tools.make_corpus import split_of  # noqa: E402


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", required=True)
    ap.add_argument("--pairs", nargs="+", required=True,
                    help="comma-separated agent-spec pairs, cycled per chunk")
    ap.add_argument("--games", type=int, default=2048)
    ap.add_argument("--chunk", type=int, default=512)
    ap.add_argument("--max-moves", type=int, default=350)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--temperature", type=float, default=0.25,
                    help="sampling temperature for checkpoint:/model: agents "
                         "(diversifies otherwise-deterministic games)")
    ap.add_argument("--opening-plies", type=int, default=0,
                    help="start each GAME from this many independent "
                         "uniformly-random plies (per-game, not the "
                         "pair-shared match openings). Search agents "
                         "(search:/search2:/value:) are deterministic and "
                         "ignore temperature, so without openings a "
                         "self-pair chunk collapses to one game duplicated "
                         "chunk-size times")
    ap.add_argument("--rank", type=int, default=8,
                    help="dan-rank tag for policy agents (baselines keep "
                         "their make_corpus tags: oneply=8, heuristic=4)")
    ap.add_argument("--skip-transcribe", action="store_true")
    args = ap.parse_args(argv)

    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()

    baseline_rank = {"oneply": 8, "heuristic": 4, "random": 1}
    pairs = [tuple(p.split(",")) for p in args.pairs]
    assert all(len(p) == 2 for p in pairs), "each --pairs entry is 'specA,specB'"
    agents: dict[str, arena.Agent] = {}
    deterministic_prefixes = ("search:", "search2:", "value:", "value2:")
    for spec in {s for p in pairs for s in p}:
        # search-family agents are deterministic re-rankers; _make_agent
        # silently ignores a temperature for all four specs (it is never
        # forwarded), so the 0.0 pin here changes nothing — it documents
        # at the call site that these agents play greedily
        temp = 0.0 if spec in baseline_rank \
            or spec.startswith(deterministic_prefixes) else args.temperature
        agents[spec] = arena._make_agent(spec, args.seed, temp, args.rank)

    def rank_of(spec: str) -> int:
        return baseline_rank.get(spec, args.rank)

    for split in ("train", "validation", "test"):
        os.makedirs(os.path.join(args.out, "sgf", split), exist_ok=True)

    totals = {"games": 0, "positions": 0, "truncated": 0}
    t0 = time.time()
    round_idx = 0
    while totals["games"] < args.games:
        spec_a, spec_b = pairs[round_idx % len(pairs)]
        n = min(args.chunk, args.games - totals["games"])
        games, scores, stats = arena.play_match(
            agents[spec_a], agents[spec_b], n_games=n,
            max_moves=args.max_moves, seed=args.seed + round_idx,
            # per-game openings: a corpus wants trajectory diversity, not
            # the pair-fairness of a win-rate match (play_match docstring)
            opening_plies=args.opening_plies, shared_openings=False)
        totals["truncated"] += stats["truncated"]
        for i, (g, s) in enumerate(zip(games, scores)):
            gid = totals["games"]
            totals["games"] += 1
            totals["positions"] += len(g.moves)
            black, white = (spec_a, spec_b) if i % 2 == 0 else (spec_b, spec_a)
            done = g.passes >= 2
            path = os.path.join(args.out, "sgf", split_of(gid),
                                f"g{gid:07d}.sgf")
            with open(path, "w") as f:
                f.write(to_sgf(
                    g, black_rank=rank_of(black), white_rank=rank_of(white),
                    result=s.result_string() if done else None, komi=7.5))
        round_idx += 1
        rate = totals["positions"] / (time.time() - t0)
        print(f"{totals['games']:,}/{args.games:,} games "
              f"({totals['positions']:,} positions, {rate:,.0f} pos/sec)",
              flush=True)
    print(totals)

    if not args.skip_transcribe:
        from deepgo_tpu.data.transcribe import transcribe_split

        for split in ("train", "validation", "test"):
            n = transcribe_split(
                os.path.join(args.out, "sgf", split),
                os.path.join(args.out, "processed", split),
                workers=max(1, (os.cpu_count() or 2) - 1), verbose=False)
            print(f"transcribed {split}: {n:,} examples", flush=True)


if __name__ == "__main__":
    main()
