#!/bin/bash
# Round-4 TPU work queue: the chip-bound evidence items from the round-3
# verdict, run sequentially so only one process holds the single-tenant
# relay claim at a time. Stages are idempotent (done-markers / resume
# files), so `--until-done` can re-run the whole queue across relay flaps.
#
# Usage: bash tools/r4_tpu_queue.sh [--until-done | stage ...]
#   stages (default order): bench curve feed large13 flagship
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r4logs
CORPUS=data/corpus/processed
FULL=3288963

stage() { echo "=== $1 [$(date -u +%H:%M:%S)] ==="; }

# verdict item 1: all four bench modes at round-4 HEAD (the driver's own
# BENCH_r04.json run happens at round end; these are the RESULTS.md copies)
run_bench() {
  stage bench
  for mode in inference train latency large; do
    if bench_artifact_ok runs/r4logs/bench_$mode.json; then
      echo "bench $mode already done"; continue
    fi
    canary || { echo "canary failed; skipping bench $mode"; return 1; }
    # 2400s: worst-case preflight (360s) + 900s watchdog, same envelope
    # arithmetic as the r3/r5 queues
    timeout 2400 python bench.py --mode $mode \
      > runs/r4logs/bench_$mode.json 2> runs/r4logs/bench_$mode.err
    echo "bench $mode rc=$?"
    tail -1 runs/r4logs/bench_$mode.json
    bench_artifact_ok runs/r4logs/bench_$mode.json \
      || echo "bench $mode incomplete (error/stale artifact)"
  done
}

# verdict item 2: the flagship 12L/128 curve's 400k and full-corpus points
# (docs/accuracy_curve.jsonl already holds 4k + 40k; the tool skips them)
run_curve() {
  stage curve
  if [ "$(wc -l < docs/accuracy_curve.jsonl 2>/dev/null || echo 0)" -ge 4 ]; then
    echo "curve already has 4 points; skipping"; return 0
  fi
  canary || { echo "canary failed; skipping curve"; return 1; }
  supervise runs/r4logs/curve.log 600 \
    timeout 14400 python -u tools/accuracy_curve.py \
    --data-root $CORPUS \
    --budgets 4000,40000,400000,$FULL --iters 4000 \
    --out docs/accuracy_curve.jsonl \
    --set num_layers=12 channels=128 batch_size=512 \
    >> runs/r4logs/curve.log 2>&1
  echo "curve rc=$?"
  tail -2 runs/r4logs/curve.log
}

# verdict item 3: the streamed-feeding gap, measured under both round-4
# levers (nibble wire x device prefetch)
run_feed() {
  stage feed
  [ -f runs/r4logs/done_feed ] && { echo "feed already done"; return 0; }
  canary || { echo "canary failed; skipping feed"; return 1; }
  supervise runs/r4logs/feed.log 600 \
    timeout 7200 python -u tools/feed_bench.py \
    --data-root $CORPUS --iters 600 \
    >> runs/r4logs/feed.log 2>&1
  rc=$?
  [ $rc -eq 0 ] && touch runs/r4logs/done_feed
  echo "feed rc=$rc"
  grep streamed_training runs/r4logs/feed.log | tail -4
}

LARGE_ITERS=3000

# flagship strength track carried over from round 3 (converge 16k iters ->
# winner fine-tune -> arena matches -> selfplay), delegated to the r3 queue
# whose stages are already idempotent via runs/r3logs markers
run_flagship() {
  stage flagship
  bash tools/r3_tpu_queue.sh converge arena finetune selfplay
  echo "flagship rc=$?"
}

# verdict item 7: train the 13L/256 "large" config to a real validation
# number (BASELINE config 4), not just a step-time benchmark
run_large13() {
  stage large13
  read -r CKPT STEP <<< "$(find_ckpt large13-256)"
  if [ -n "${CKPT:-}" ] && [ "${STEP:-0}" -ge $LARGE_ITERS ]; then
    echo "large13 already at step $STEP; skipping"; return 0
  fi
  canary || { echo "canary failed; skipping large13"; return 1; }
  if [ -n "${CKPT:-}" ]; then
    echo "resuming large13 from $CKPT (step $STEP)"
    supervise runs/r4logs/large13.log 600 \
      timeout 10800 python -u -m deepgo_tpu.cli train \
      --resume "$CKPT" --iters $((LARGE_ITERS - STEP)) \
      >> runs/r4logs/large13.log 2>&1
  else
    supervise runs/r4logs/large13.log 600 \
      timeout 10800 python -u -m deepgo_tpu.cli train --iters $LARGE_ITERS --set \
      name=large13-256 data_root=$CORPUS scheme=uniform \
      num_layers=13 channels=256 batch_size=1024 remat=false \
      steps_per_call=20 rate=0.02 momentum=0.9 rate_decay=1e-7 \
      validation_interval=1000 validation_size=4096 print_interval=100 \
      >> runs/r4logs/large13.log 2>&1
  fi
  echo "large13 rc=$?"
  grep -E "validation at|samples per second" runs/r4logs/large13.log | tail -4
}

if [ "${1:-}" = "--until-done" ]; then
  for attempt in $(seq 1 40); do
    echo "=== until-done attempt $attempt [$(date -u +%H:%M:%S)] ==="
    until canary; do echo "canary down; waiting"; sleep 120; done
    out=$(bash "$0" 2>&1)
    rc=$?
    echo "$out"
    # a stage aborting before its "rc=" echo (set -u, missing script)
    # must count as failure too, hence the exit-status check
    if [ $rc -eq 0 ] && ! echo "$out" | grep -qE "canary failed|rc=[1-9]|incomplete"; then
      echo "=== all stages complete ==="
      exit 0
    fi
    sleep 60
  done
  echo "=== attempts exhausted ==="
  exit 1
fi

if [ $# -eq 0 ]; then
  set -- bench curve feed large13 flagship
fi
for s in "$@"; do run_$s; done
echo "=== queue done [$(date -u +%H:%M:%S)] ==="
