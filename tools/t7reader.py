"""Minimal reader for the Torch7 binary serialization format.

The reference framework (wqzsscc/deep-go) ships its bundled mini-dataset as
per-move records written with ``torch.save`` (reference makedata.lua:554,
dataloader.lua:30-39). This module decodes that public, documented format so
that our tests can use the bundled records as golden data and so that
``tools/reconstruct_sgfs.py`` can rebuild the original SGF game files from the
recorded move sequences.

Only the subset of the format that those records use is implemented:
numbers, strings, booleans, tables, and Byte/Double tensors + storages.
Format layout (little-endian):
  object := int32 type_tag, payload
    1 = number   -> float64
    2 = string   -> int32 length, bytes
    3 = table    -> int32 ref-index, int32 npairs, npairs * (key obj, val obj)
    4 = torch    -> int32 ref-index, string version ("V 1"), string classname,
                    class payload
    5 = boolean  -> int32
  Tensor payload  := int32 ndim, int64 sizes[nd], int64 strides[nd],
                     int64 storage_offset (1-based), object storage
  Storage payload := int64 numel, raw element data
Previously-seen ref-indices dereference to the memoized object.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

import numpy as np

TYPE_NIL = 0
TYPE_NUMBER = 1
TYPE_STRING = 2
TYPE_TABLE = 3
TYPE_TORCH = 4
TYPE_BOOLEAN = 5

_STORAGE_DTYPES = {
    "torch.ByteStorage": np.uint8,
    "torch.CharStorage": np.int8,
    "torch.ShortStorage": np.int16,
    "torch.IntStorage": np.int32,
    "torch.LongStorage": np.int64,
    "torch.FloatStorage": np.float32,
    "torch.DoubleStorage": np.float64,
}

_TENSOR_TO_STORAGE = {
    "torch.ByteTensor": "torch.ByteStorage",
    "torch.CharTensor": "torch.CharStorage",
    "torch.ShortTensor": "torch.ShortStorage",
    "torch.IntTensor": "torch.IntStorage",
    "torch.LongTensor": "torch.LongStorage",
    "torch.FloatTensor": "torch.FloatStorage",
    "torch.DoubleTensor": "torch.DoubleStorage",
}


@dataclass
class _Tensor:
    sizes: tuple
    strides: tuple
    offset: int  # 0-based element offset into storage
    storage: np.ndarray
    dtype: np.dtype

    def to_numpy(self) -> np.ndarray:
        if self.storage is None or not self.sizes:
            return np.zeros(self.sizes, dtype=self.dtype)
        return np.lib.stride_tricks.as_strided(
            self.storage[self.offset:],
            shape=self.sizes,
            strides=tuple(s * self.storage.itemsize for s in self.strides),
        ).copy()


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.memo: dict[int, object] = {}

    def _unpack(self, fmt: str):
        size = struct.calcsize(fmt)
        out = struct.unpack_from(fmt, self.data, self.pos)
        self.pos += size
        return out[0]

    def read_int(self) -> int:
        return self._unpack("<i")

    def read_long(self) -> int:
        return self._unpack("<q")

    def read_double(self) -> float:
        return self._unpack("<d")

    def read_bytes(self, n: int) -> bytes:
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def read_string(self) -> str:
        n = self.read_int()
        return self.read_bytes(n).decode("latin-1")

    def read_object(self):
        tag = self.read_int()
        if tag == TYPE_NIL:
            return None
        if tag == TYPE_NUMBER:
            x = self.read_double()
            return int(x) if x == int(x) else x
        if tag == TYPE_STRING:
            return self.read_string()
        if tag == TYPE_BOOLEAN:
            return bool(self.read_int())
        if tag == TYPE_TABLE:
            index = self.read_int()
            if index in self.memo:
                return self.memo[index]
            table: dict = {}
            self.memo[index] = table
            npairs = self.read_int()
            for _ in range(npairs):
                key = self.read_object()
                table[key] = self.read_object()
            return table
        if tag == TYPE_TORCH:
            index = self.read_int()
            if index in self.memo:
                return self.memo[index]
            version = self.read_string()
            if version.startswith("V "):
                classname = self.read_string()
            else:
                classname = version  # pre-versioning files
            obj = self._read_torch_payload(classname)
            self.memo[index] = obj
            return obj
        raise ValueError(f"unknown torch type tag {tag} at offset {self.pos - 4}")

    def _read_torch_payload(self, classname: str):
        if classname in _TENSOR_TO_STORAGE:
            ndim = self.read_int()
            sizes = tuple(self.read_long() for _ in range(ndim))
            strides = tuple(self.read_long() for _ in range(ndim))
            offset = self.read_long() - 1
            storage = self.read_object()
            dtype = np.dtype(_STORAGE_DTYPES[_TENSOR_TO_STORAGE[classname]])
            tensor = _Tensor(sizes, strides, offset, storage, dtype)
            return tensor.to_numpy()
        if classname in _STORAGE_DTYPES:
            dtype = np.dtype(_STORAGE_DTYPES[classname])
            numel = self.read_long()
            raw = self.read_bytes(numel * dtype.itemsize)
            return np.frombuffer(raw, dtype=dtype)
        raise ValueError(f"unsupported torch class {classname!r}")


def load(path: str):
    """Load a torch.save()-produced file into Python/NumPy objects."""
    with open(path, "rb") as f:
        return _Reader(f.read()).read_object()
