#!/bin/bash
# SUPERSEDED by `python -m deepgo_tpu.cli loop` (docs/loop.md): the
# hand-sequenced selfplay -> corpus -> train -> arena -> champion stages
# below now run as one supervised, always-on service with a live replay
# buffer, bit-exact learner resume, and fleet hot-reload on gate pass.
# This script is kept as the reproducible record of the round-5
# measurement campaign; its arena protocol pins moved into
# match.standard_gate() (used here via --standard-gate) so the two paths
# can never drift.
#
# Value-guided self-improvement loop: reproduce the round-4 rungs on a
# fresh machine, then run the compounding iteration RESULTS.md sketched
# for round 5.
#
# Round 4 measured (ad-hoc, first session): a 3L/64 value net (value1)
# over the main corpus's decided games; the value-guided search agent
# on ft2k (67.6% vs oneply); one winner-distillation round from the
# value expert's games (cpu-ft-iterv, 69.4% wrapped); and the composed
# champion value:iterv:value1 at 73.1%. The runs/ tree those artifacts
# lived in is machine-local, so this script first rebuilds them under
# done-markers, then extends the loop one full turn:
#
#   iterv2 corpus:  1,280 fresh games by the CHAMPION value:iterv:value1
#   value2:         the value net RETRAINED on the loop's own expert
#                   games (iterv2+iterv union — the trainable-expert
#                   half of the compounding thesis)
#   cpu-ft-iterv2:  second winner distillation (from iterv, on iterv2)
#   factorial matches that separate the levers, 1,000 games each:
#     value:iterv:value2     new value net, old prior
#     value:iterv2:value1    new prior, old value net
#     value:iterv2:value2    the full compounding rung (beats 73.1%?)
#
# Protocol pins (RESULTS.md "1,000-game precision"): vs oneply,
# --opening-plies 8 --seed 29 --rank 8. Everything CPU
# (JAX_PLATFORMS=cpu) and nice -n 10: never dials the relay, yields the
# single host core to live chip work. Stages idempotent via
# find_ckpt / done-markers like the other queues.
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r5logs
export JAX_PLATFORMS=cpu
CORPUS=data/corpus/processed
N=${NICE:-10}

vmatch() {  # vmatch <specA> <tag> [games] — vs oneply under the pins
  local a=$1 tag=$2 games=${3:-1000}
  local mark=runs/r5logs/done_arena_$tag
  [ -f "$mark" ] && { echo "arena $tag already done"; return 0; }
  stage "arena $tag"
  # --standard-gate applies the shared protocol pins from
  # match.standard_gate (opening-plies 8, seed 29, rank 8, vs oneply) —
  # one definition for this queue and the expert-iteration gatekeeper
  nice -n $N timeout 43200 python -u -m deepgo_tpu.arena \
    --a "$a" --standard-gate --games "$games" \
    >> runs/r5logs/arena.log 2>&1
  local rc=$?
  [ $rc -eq 0 ] && touch "$mark"
  echo "arena $tag rc=$rc"
  tail -1 runs/r5logs/arena.log
}

value_train() {  # value_train <out_dir> <data_roots_csv> [iters]
  [ -f "$1/value_checkpoint.npz" ] && { echo "$1 already trained"; return 0; }
  stage "value train $1"
  nice -n $N timeout 28800 python -u tools/train_value.py \
    --data-root "$2" --iters "${3:-2000}" --out "$1" \
    >> "runs/r5logs/value_train_$(basename "$1").log" 2>&1
  echo "value train $1 rc=$?"
  grep "value validation" "runs/r5logs/value_train_$(basename "$1").log" | tail -1
}

# --- prereqs: cpu-base / cpu-ft2k + main-corpus winner sidecars ---
bash tools/r3_cpu_strength.sh || { echo "prereq pipeline failed"; exit 1; }
read -r FT FT_STEP <<< "$(find_ckpt cpu-ft2k)"
[ -n "${FT:-}" ] || { echo "no cpu-ft2k checkpoint"; exit 1; }
echo "cpu-ft2k: $FT (step $FT_STEP)"

# --- round-4 rungs rebuilt (value1, the value wrapper, iterv) ---
V1=runs/value1/value_checkpoint.npz
value_train runs/value1 "$CORPUS"
[ -f "$V1" ] || { echo "no value1 checkpoint"; exit 1; }

vmatch "value:$FT:$V1" ft2k_value1

build_selfplay_corpus data/iterv runs/r5logs/selfplay.log 1280 256 8 23 43200 \
  "value:$FT:$V1,oneply" "value:$FT:$V1,value:$FT:$V1" \
  || { echo "iterv corpus build failed"; exit 1; }
distill_winner cpu-ft-iterv "$FT" data/iterv 500 runs/r5logs/distill.log
read -r IV IV_STEP <<< "$(find_ckpt cpu-ft-iterv)"
[ -n "${IV:-}" ] || { echo "no cpu-ft-iterv checkpoint"; exit 1; }
echo "cpu-ft-iterv: $IV (step $IV_STEP)"

vmatch "search:$IV" iterv_veto
vmatch "value:$IV:$V1" iterv_value1

# --- the round-5 compounding turn ---
build_selfplay_corpus data/iterv2 runs/r5logs/selfplay.log 1280 256 8 31 43200 \
  "value:$IV:$V1,oneply" "value:$IV:$V1,value:$IV:$V1" \
  || { echo "iterv2 corpus build failed"; exit 1; }
ensure_winner_sidecars data/iterv2 runs/r5logs/winner.log

ensure_winner_sidecars data/iterv runs/r5logs/winner.log  # distill may have early-returned on resume without rebuilding these
# the 2,000-iter value2 run is kept ONLY to reproduce the overfitting
# measurement (val 72.1% @500 -> 67.1% @2000, loss 0.52 -> 0.89 — the
# same brief-exposure dynamic the policy distillation showed); the
# factorial below uses the early-stopped 500-iter value2b, which by the
# deterministic sampling stream equals the 2,000-run's step-500 state
value_train runs/value2 "data/iterv2/processed,data/iterv/processed"
value_train runs/value2b "data/iterv2/processed,data/iterv/processed" 500
V2=runs/value2b/value_checkpoint.npz
[ -f "$V2" ] || { echo "no value2b checkpoint"; exit 1; }

distill_winner cpu-ft-iterv2 "$IV" data/iterv2 500 runs/r5logs/distill.log
read -r IV2 IV2_STEP <<< "$(find_ckpt cpu-ft-iterv2)"
[ -n "${IV2:-}" ] || { echo "no cpu-ft-iterv2 checkpoint"; exit 1; }
echo "cpu-ft-iterv2: $IV2 (step $IV2_STEP)"

vmatch "value:$IV:$V2" iterv_value2
vmatch "value:$IV2:$V1" iterv2_value1
vmatch "value:$IV2:$V2" iterv2_value2

echo "=== r5 value loop done [$(date -u +%H:%M:%S)] ==="
