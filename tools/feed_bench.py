"""End-to-end streamed-training feed benchmark (round-3 verdict item 3).

Round 3 measured live host-streamed 12L/128 training at ~4.5-5k samples/sec
against a 42.5k resident-superbatch ceiling (RESULTS.md): the feed, not the
chip, was the limit. This tool measures the full streamed path — memmap
sampling -> host batch -> (wire encode) -> device_put -> fused K-step scan —
under each combination of the two round-4 feed levers:

  * wire_format:      "packed" (3.2 KB/position) vs "nibble" (1.7 KB)
  * device_prefetch:  0 (transfer inline in the train loop) vs N (uploader
                      thread overlaps transfer with device compute)

plus a host-sampling-only rate (no device) to show where the host side
saturates. One JSON line per measurement; run on the TPU via
tools/r4_tpu_queue.sh (stage feed).

Usage:
  python tools/feed_bench.py --data-root data/corpus/processed \
      --iters 600 --set num_layers=12 channels=128 batch_size=512
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu.cli import parse_overrides  # noqa: E402
from deepgo_tpu.experiments import Experiment, ExperimentConfig  # noqa: E402


def host_sampling_rate(data_root: str, batch_size: int, wire: str,
                       seconds: float = 5.0) -> dict:
    """Pure host-side sampling rate (memmap gather + wire encode), no JAX."""
    import numpy as np

    from deepgo_tpu.data import GoDataset
    from deepgo_tpu.data.loader import make_host_batch

    ds = GoDataset(data_root, "train")
    rng = np.random.default_rng(0)
    make_host_batch(ds, rng, batch_size, "uniform", wire=wire)  # warm cache
    n = 0
    t0 = time.time()
    while time.time() - t0 < seconds:
        make_host_batch(ds, rng, batch_size, "uniform", wire=wire)
        n += batch_size
    return {"kind": "host_sampling", "wire": wire,
            "samples_per_sec": round(n / (time.time() - t0), 1)}


def host_superbatch_rate(data_root: str, batch_size: int, stack: int,
                         wire: str, seconds: float = 5.0) -> dict:
    """Host-side SUPERBATCH assembly rate — the unit the loader workers
    actually build since round 5 (one K*B gather + chunked wire encode,
    deepgo_tpu.data.loader.make_host_superbatch)."""
    import numpy as np

    from deepgo_tpu.data import GoDataset
    from deepgo_tpu.data.loader import make_host_superbatch

    ds = GoDataset(data_root, "train")
    rng = np.random.default_rng(0)
    make_host_superbatch(ds, rng, batch_size, stack, "uniform", wire=wire)
    n = 0
    t0 = time.time()
    while time.time() - t0 < seconds:
        make_host_superbatch(ds, rng, batch_size, stack, "uniform", wire=wire)
        n += batch_size * stack
    return {"kind": "host_superbatch", "wire": wire, "stack": stack,
            "samples_per_sec": round(n / (time.time() - t0), 1)}


def streamed_training_rate(cfg: ExperimentConfig, iters: int) -> dict:
    """Live streamed training samples/sec for one feed configuration.

    A fresh Experiment per setting (params at the same seed); the first
    print window includes compile, so the reported rate uses the summary's
    total samples/sec minus a warmup discount — we simply drop the first
    window by timing from the second print onwards via metrics.jsonl.
    """
    exp = Experiment(cfg)
    exp.run(iters)
    from deepgo_tpu.utils.metrics import read_jsonl

    rows = [m for m in read_jsonl(os.path.join(exp.run_path, "metrics.jsonl"))
            if m["kind"] == "train"]
    if not rows:
        raise SystemExit(f"no train windows recorded: --iters must be >= "
                         f"print_interval ({cfg.print_interval})")
    # drop the first window (compile) whenever a steady window remains
    steady = rows[1:] if len(rows) > 1 else rows
    sps = sum(m["samples_per_sec"] for m in steady) / len(steady)
    return {
        "kind": "streamed_training",
        "wire": cfg.wire_format,
        "device_prefetch": cfg.device_prefetch,
        "loader_threads": cfg.loader_threads,
        "steps_per_call": cfg.steps_per_call,
        "batch_size": cfg.batch_size,
        "samples_per_sec": round(sps, 1),
        "windows": len(steady),
        "run_id": exp.id,
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data-root", default="data/corpus/processed")
    ap.add_argument("--iters", type=int, default=600)
    ap.add_argument("--out", default="docs/feed_bench.jsonl")
    ap.add_argument("--set", nargs="*", default=[], metavar="KEY=VALUE")
    args = ap.parse_args(argv)

    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()
    base = ExperimentConfig(
        data_root=args.data_root, scheme="uniform", name="feed-bench",
        num_layers=12, channels=128, batch_size=512, steps_per_call=20,
        print_interval=100, validation_interval=10**9, loader_threads=4,
        prefetch=8,
    ).replace(**parse_overrides(args.set))

    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)

    def record(r: dict) -> None:
        # append as produced, so a mid-sweep relay flap keeps earlier rows
        print(json.dumps(r), flush=True)
        with open(args.out, "a") as f:
            f.write(json.dumps(r) + "\n")

    for wire in ("packed", "nibble"):
        record(host_sampling_rate(args.data_root, base.batch_size, wire))
        record(host_superbatch_rate(args.data_root, base.batch_size,
                                    base.steps_per_call, wire))
    for wire, dev_prefetch in (("packed", 0), ("packed", 2),
                               ("nibble", 0), ("nibble", 2)):
        cfg = base.replace(wire_format=wire, device_prefetch=dev_prefetch)
        record(streamed_training_rate(cfg, args.iters))
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
