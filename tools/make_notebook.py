"""Build and execute docs/walkthrough.ipynb (reference `Run Experiment.ipynb`
parity, L6 entry point).

The notebook is generated from the cell sources below (so it stays in sync
with the API by re-running this tool) and executed with nbclient on the CPU
backend against the bundled 22-game fixture; the committed .ipynb carries
real outputs.

Usage:
  python tools/make_notebook.py [--out docs/walkthrough.ipynb] [--no-execute]
"""

from __future__ import annotations

import argparse

import nbformat

CELLS: list[tuple[str, str]] = [
    ("markdown", """\
# deepgo_tpu walkthrough

End-to-end tour of the framework on the bundled 22-game fixture: transcribe
SGF records to packed feature shards, train a small policy CNN, validate,
checkpoint/resume, plot, and play. This is the runnable counterpart of the
reference's `Run Experiment.ipynb` (its cells 0-4 build an experiment and
call `:run`); everything here also works at full scale on a TPU — the
fixture just keeps the notebook executable in seconds on CPU.
"""),
    ("code", """\
# CPU pin for notebook execution: in the TPU terminal a sitecustomize
# force-selects the tunneled device at interpreter start, so the pin is a
# config update after import (same trick as tests/conftest.py).
import os
os.chdir(os.path.dirname(os.path.abspath("__file__")) if os.path.basename(os.getcwd()) == "docs" else os.getcwd())
import jax
jax.config.update("jax_platforms", "cpu")
print(jax.devices())
"""),
    ("markdown", """\
## 1. Data: SGF -> packed feature shards

`data/sgf/` holds 22 real games. Transcription replays each game with the
full rules engine (captures, liberties, ladders; the C++ twin when built)
and writes one packed `(9, 19, 19)` uint8 record per move — the model's 37
binary planes are expanded from these *on device* at train time.
"""),
    ("code", """\
from deepgo_tpu.data.transcribe import transcribe_split

for split in ("train", "validation", "test"):
    out = f"data/processed/{split}"
    n = transcribe_split(f"data/sgf/{split}", out, workers=1, verbose=False)
    print(f"{split}: {n} examples")
"""),
    ("code", """\
# one record, decoded: the position before move 60 of the first train game
import numpy as np
from deepgo_tpu.data import GoDataset
from deepgo_tpu.features import P_STONES

ds = GoDataset("data/processed", "train")
packed, player, rank, target = (a[0] for a in ds.batch_at(np.array([60])))
glyph = {0: ".", 1: "X", 2: "O"}
board = packed[P_STONES]
print("side to move:", "black" if player == 1 else "white",
      f"(rank {rank}d)   target point: {divmod(int(target), 19)}")
print("\\n".join(" ".join(glyph[v] for v in row) for row in board))
"""),
    ("markdown", """\
## 2. Train

One fused XLA program per step (expansion + forward + NLL + backward + SGD
update, buffers donated). `steps_per_call` chains K steps per dispatch via
`lax.scan` on accelerators; on CPU it resolves to 1.
"""),
    ("code", """\
from deepgo_tpu.experiments import Experiment, ExperimentConfig

config = ExperimentConfig(
    name="walkthrough", num_layers=3, channels=32, batch_size=16,
    rate=0.05, validation_size=64, validation_interval=60,
    print_interval=20, loader_threads=1, data_parallel=1, seed=3,
    data_root="data/processed")
exp = Experiment(config)
summary = exp.run(120)
print({k: round(v, 4) if isinstance(v, float) else v
       for k, v in summary.items() if k not in ("config", "last_validation")})
"""),
    ("markdown", """\
## 3. Validate, evaluate, plot

Validation uses a fixed, game-balanced, mask-padded set (deterministic —
improving on the reference's one random minibatch per run). `evaluate()`
runs the full held-out test split. Plotting reads the run's JSONL metrics,
or the history inside any bare checkpoint.
"""),
    ("code", """\
val = exp.validate()
test = exp.evaluate()
print(f"validation: cost={val['cost']:.3f} top1={val['accuracy']:.3f} n={val['n']}")
print(f"test:       cost={test['cost']:.3f} top1={test['accuracy']:.3f} n={test['n']}")
"""),
    ("code", """\
ckpt_path = exp.save()
from deepgo_tpu.experiments import plot as plotmod

curves = plotmod.load_curves([ckpt_path])  # straight from the checkpoint
print(curves)
"""),
    ("markdown", """\
## 4. Checkpoint, resume, warm restart

A checkpoint is one self-describing `.npz`: config + weights + optimizer
state + step + validation history. `Experiment.load` continues a run;
`experiments.repeated` re-IDs it with a fresh optimizer (the reference's
warm-restart sweep workflow).
"""),
    ("code", """\
resumed = Experiment.load(ckpt_path)
print("resumed", resumed.id, "at step", resumed.step)
more = resumed.run(40)
print("EWMA after 40 more steps:", round(more["final_ewma"], 4))
"""),
    ("markdown", """\
## 5. Play: self-play and the arena

The trained policy drives batched self-play (one forward per ply for the
whole fleet of games; per-ply move application is one threaded native
call), and the arena pits agents against each other with Tromp-Taylor
scoring. 120 training steps on 20 games is far too little to beat even the
capture-greedy baseline — the win-rate tables in RESULTS.md come from the
full-scale corpus runs — but the plumbing is identical.
"""),
    ("code", """\
from deepgo_tpu import arena

policy = arena.PolicyAgent(resumed.params, resumed.model_cfg, rank=8)
games, scores, stats = arena.play_match(policy, arena.RandomAgent(),
                                        n_games=8, max_moves=120, seed=0)
print({k: round(v, 3) if isinstance(v, float) else v for k, v in stats.items()})
"""),
    ("code", """\
# full circle: finished games feed back through our own SGF pipeline
from deepgo_tpu.selfplay import to_sgf
from deepgo_tpu import sgf as sgfmod

rec = to_sgf(games[0], komi=7.5)
parsed = sgfmod.parse(rec)
print(f"game 0: {len(parsed.moves)} moves round-trip through SGF")
"""),
]


def build() -> nbformat.NotebookNode:
    nb = nbformat.v4.new_notebook()
    nb.metadata["kernelspec"] = {
        "display_name": "Python 3", "language": "python", "name": "python3"}
    for kind, src in CELLS:
        cell = (nbformat.v4.new_markdown_cell if kind == "markdown"
                else nbformat.v4.new_code_cell)(src.rstrip("\n"))
        nb.cells.append(cell)
    return nb


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--out", default="docs/walkthrough.ipynb")
    ap.add_argument("--no-execute", action="store_true")
    args = ap.parse_args(argv)

    nb = build()
    if not args.no_execute:
        import os

        from nbclient import NotebookClient

        client = NotebookClient(nb, timeout=600,
                                resources={"metadata": {"path": os.getcwd()}})
        client.execute()
    with open(args.out, "w") as f:
        nbformat.write(nb, f)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
