"""Instrumented TwoPly-vs-PolicySearch mini-match: why is head-to-head 0-200?

Counts, per ply: how often the differential veto fires, what it fires on
(tact/threat of policy move vs chosen), and pass decisions. Run on CPU:
  JAX_PLATFORMS=cpu python tools/debug_twoply.py --ckpt runs/<id>/checkpoint.npz
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepgo_tpu import arena  # noqa: E402


class DebugTwoPly(arena.TwoPlyAgent):
    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.stats = dict(plies=0, boards=0, fired=0, passed=0, urgent=0,
                          fire_tact=[])

    def select_moves(self, packed, players, legal, rng):
        moves = super().select_moves(packed, players, legal, rng)
        # re-derive the internals for accounting (cheap at debug scale);
        # report the REALIZED gain the fixed agent scores with (no
        # speculative save credit), not _oneply_scores' save-inflated tact
        legal2 = arena._no_own_eyes(packed, players, legal)
        logp = self._legal_log_probs(packed, players, legal2)
        my_kills, _, my_libs, opp_libs, ladders = arena._tactical_grids(
            packed, players)
        tact1 = (arena.W_KILL * my_kills + arena.W_LADDER * ladders
                 + arena.W_LIB * my_libs + arena.W_OPP_LIB * opp_libs
                 - arena.W_SELF_ATARI * (my_libs <= 1))
        _, forcing1 = arena._oneply_scores(packed, players)
        any_legal = legal2.any(axis=1)
        policy_move = np.where(any_legal, logp.argmax(axis=1), -1)
        n = len(packed)
        self.stats["plies"] += 1
        self.stats["boards"] += n
        self.stats["passed"] += int((moves == -1).sum())
        self.stats["urgent"] += int(
            (legal2 & (forcing1 >= self.urgent)).any(axis=1).sum())
        fired = (moves != policy_move) & (moves != -1)
        self.stats["fired"] += int(fired.sum())
        for i in np.nonzero(fired)[0][:3]:
            self.stats["fire_tact"].append(
                (int(tact1[i, moves[i]]), int(tact1[i, policy_move[i]])))
        return moves


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ckpt", required=True)
    ap.add_argument("--games", type=int, default=16)
    args = ap.parse_args()

    from deepgo_tpu.models.serving import load_policy
    from deepgo_tpu.utils import honor_platform_env

    honor_platform_env()
    _, params, cfg = load_policy(args.ckpt)
    two = DebugTwoPly(params, cfg, rank=8)
    one = arena.PolicySearchAgent(params, cfg, rank=8)
    games, scores, stats = arena.play_match(two, one, n_games=args.games,
                                            seed=11)
    print({k: v for k, v in stats.items()})
    s = two.stats
    print(f"twoply: {s['boards']} boards over {s['plies']} plies; "
          f"fired {s['fired']} ({s['fired']/max(1,s['boards']):.1%}), "
          f"passed {s['passed']}, urgent-boards {s['urgent']} "
          f"({s['urgent']/max(1,s['boards']):.1%})")
    print("sample fired (tact_chosen, tact_policy):", s["fire_tact"][:20])
    # a couple of final positions' last moves for eyeballing
    g = games[0]
    print("game0 moves tail:", g.moves[-12:], "passes", g.passes,
          "done", g.done)


if __name__ == "__main__":
    main()
