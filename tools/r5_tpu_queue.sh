#!/bin/bash
# Round-5 TPU work queue: the chip-bound items from the round-4 verdict,
# run sequentially so only one process holds the single-tenant relay claim
# at a time. Stages are idempotent (done markers / artifact checks /
# save-on-validate resume), so `--until-done` can re-run the whole queue
# across relay flaps.
#
# Usage: bash tools/r5_tpu_queue.sh [--until-done | stage ...]
#   stages (default order): bench large13b feed
#
# verdict item 1: bench   — LIVE captures of all four modes; each success
#                           also refreshes BENCH_LAST_GOOD.json so a wedge
#                           at driver-capture time degrades to stale-not-zero
# verdict item 2: large13b — continue 13L/256 from 54.9%@3000 (0.93 epoch)
#                           for +7000 iters with a decay schedule
#                           (0.02 -> ~0.002) toward >=55.0% validation
# verdict item 5: feed    — re-measure streamed-feed throughput after the
#                           loader assembly parallelization
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r5logs
CORPUS=data/corpus/processed

LARGE_TOTAL=10000   # 3000 (round 4) + 7000 continuation ~= 3 epochs total

run_bench() {
  stage bench
  for mode in inference train latency large; do
    if bench_artifact_ok runs/r5logs/bench_$mode.json; then
      echo "bench $mode already done"; continue
    fi
    canary || { echo "canary failed; skipping bench $mode"; return 1; }
    # 2400s envelope: worst-case preflight (360s) + 900s bench watchdog
    timeout 2400 python bench.py --mode $mode \
      > runs/r5logs/bench_$mode.json 2> runs/r5logs/bench_$mode.err
    echo "bench $mode rc=$?"
    tail -1 runs/r5logs/bench_$mode.json
    bench_artifact_ok runs/r5logs/bench_$mode.json \
      || echo "bench $mode incomplete (error/stale artifact)"
  done
}

run_large13b() {
  stage large13b
  read -r CKPT STEP <<< "$(find_ckpt large13-ft)"
  if [ -n "${CKPT:-}" ] && [ "${STEP:-0}" -ge $LARGE_TOTAL ]; then
    echo "large13b already at step $STEP; skipping"; return 0
  fi
  canary || { echo "canary failed; skipping large13b"; return 1; }
  if [ -n "${CKPT:-}" ]; then
    # save-on-validate checkpoints keep the decayed optimizer state, so a
    # killed continuation resumes mid-schedule instead of restarting hot
    echo "resuming large13b from $CKPT (step $STEP)"
    supervise runs/r5logs/large13b.log 600 \
      timeout 14400 python -u -m deepgo_tpu.cli train \
      --resume "$CKPT" --iters $((LARGE_TOTAL - STEP)) \
      >> runs/r5logs/large13b.log 2>&1
  else
    read -r BASE BASE_STEP <<< "$(find_ckpt large13-256)"
    [ -n "${BASE:-}" ] || { echo "no large13-256 checkpoint; cannot continue"; return 1; }
    echo "continuing from $BASE (step $BASE_STEP) with decay schedule"
    # (1 - 3.3e-4)^7000 ~= 0.10: rate anneals 0.02 -> ~0.002 over the
    # continuation — the round-4 run was cut at 0.93 epoch with NLL still
    # falling at CONSTANT rate; the anneal converts that headroom into
    # the last accuracy points
    supervise runs/r5logs/large13b.log 600 \
      timeout 14400 python -u -m deepgo_tpu.experiments.repeated \
      --checkpoint "$BASE" --iters $((LARGE_TOTAL - BASE_STEP)) --set \
      name=large13-ft scheme=uniform rate=0.02 momentum=0.9 \
      rate_decay=3.3e-4 steps_per_call=20 \
      validation_interval=1000 validation_size=4096 print_interval=100 \
      >> runs/r5logs/large13b.log 2>&1
  fi
  echo "large13b rc=$?"
  grep -E "validation at|samples per second" runs/r5logs/large13b.log | tail -6
}

run_feed() {
  stage feed
  [ -f runs/r5logs/done_feed ] && { echo "feed already done"; return 0; }
  # the parallelized loader assembly this stage re-measures is in HEAD
  # (data/loader.py device_prefetch uploader); no readiness marker needed
  canary || { echo "canary failed; skipping feed"; return 1; }
  supervise runs/r5logs/feed.log 600 \
    timeout 7200 python -u tools/feed_bench.py \
    --data-root $CORPUS --iters 600 \
    >> runs/r5logs/feed.log 2>&1
  rc=$?
  [ $rc -eq 0 ] && touch runs/r5logs/done_feed
  echo "feed rc=$rc"
  grep streamed_training runs/r5logs/feed.log | tail -4
}

# verdict item 3: the corpus-diversity lever applied to the ACCURACY axis
# — re-measure the 400k and full-corpus points of the 12L/128 curve on the
# diversified corpus2 (per-game openings, mixed-rank trained-agent pool).
# Done = the two-point curve shows whether the data axis is live again.
run_curve2() {
  stage curve2
  if [ "$(cat docs/accuracy_curve2.jsonl 2>/dev/null | wc -l)" -ge 2 ]; then
    echo "curve2 already has 2 points; skipping"; return 0
  fi
  if [ ! -f data/corpus2/processed/test/games.json ]; then
    echo "curve2 incomplete (corpus2 still generating)"; return 0
  fi
  canary || { echo "canary failed; skipping curve2"; return 1; }
  supervise runs/r5logs/curve2.log 600 \
    timeout 14400 python -u tools/accuracy_curve.py \
    --data-root data/corpus2/processed \
    --budgets 400000,99000000 --iters 4000 \
    --out docs/accuracy_curve2.jsonl \
    --set num_layers=12 channels=128 batch_size=512 \
    >> runs/r5logs/curve2.log 2>&1
  echo "curve2 rc=$?"
  tail -2 runs/r5logs/curve2.log
}

# verdict item 8: symmetry-averaged inference measured at full-split
# scale on the big nets (the CPU pilot read +0.71 top-1 on 3L/64);
# runs after large13b so the annealed checkpoint gets measured too
run_symm() {
  stage symm
  for name in converge-12L128 large13-256 large13-ft; do
    local mark=runs/r5logs/done_symm_$name
    [ -f "$mark" ] && { echo "symm $name already done"; continue; }
    read -r CKPT STEP <<< "$(find_ckpt $name)"
    if [ -z "${CKPT:-}" ]; then
      echo "symm $name incomplete (no checkpoint yet)"
      continue
    fi
    # a save-on-validate checkpoint exists mid-anneal; measuring it and
    # marking done would skip the FINAL annealed net this stage is for
    if [ "$name" = large13-ft ] && [ "${STEP:-0}" -lt $LARGE_TOTAL ]; then
      echo "symm $name incomplete (still annealing: step $STEP/$LARGE_TOTAL)"
      continue
    fi
    canary || { echo "canary failed; skipping symm $name"; return 1; }
    supervise runs/r5logs/symm_$name.log 600 \
      timeout 3600 python -u tools/symmetry_eval.py \
      --checkpoint "$CKPT" --batch 1024 \
      --out docs/symmetry_eval.jsonl \
      >> runs/r5logs/symm_$name.log 2>&1
    local rc=$?
    [ $rc -eq 0 ] && touch "$mark"
    echo "symm $name rc=$rc"
    tail -3 runs/r5logs/symm_$name.log
  done
  return 0
}

if [ "${1:-}" = "--until-done" ]; then
  for attempt in $(seq 1 60); do
    echo "=== until-done attempt $attempt [$(date -u +%H:%M:%S)] ==="
    until canary; do echo "canary down; waiting"; sleep 180; done
    out=$(bash "$0" 2>&1)
    rc=$?
    echo "$out"
    if [ $rc -eq 0 ] && ! echo "$out" | grep -qE "canary failed|rc=[1-9]|incomplete"; then
      echo "=== all stages complete ==="
      exit 0
    fi
    sleep 60
  done
  echo "=== attempts exhausted ==="
  exit 1
fi

if [ $# -eq 0 ]; then
  set -- bench large13b feed curve2 symm
fi
for s in "$@"; do run_$s; done
echo "=== queue done [$(date -u +%H:%M:%S)] ==="
