#!/bin/bash
# Round-3 follow-up chip work, run AFTER tools/r3_tpu_queue.sh completes:
#
#   1. a 2,000-step winner-conditioned fine-tune of the converged flagship
#      (the CPU-scale sweep found 2k steps is the strength sweet spot and
#      4k regresses — the queue's finetune stage only keeps its final
#      4k-step checkpoint, so the sweet spot needs its own run), and
#   2. PolicySearchAgent (policy prior + 1-ply tactical re-rank) matches
#      for the converged and fine-tuned flagships vs the scripted
#      baselines.
#
# Same conventions as the main queue: idempotent stages, done-markers,
# canary gate, stall supervision, one chip process at a time.
set -u
cd "$(dirname "$0")/.."
. tools/r3_lib.sh
mkdir -p runs/r3logs

# match <spec> <opponent> <tag> [games]
match() {
  local spec=$1 opp=$2 tag=$3 games=${4:-200}
  local mark=runs/r3logs/done_arena_$tag
  [ -f "$mark" ] && { echo "arena $tag already done"; return 0; }
  canary || { echo "canary failed; skipping $tag"; return 1; }
  supervise runs/r3logs/search_arena.log 600 \
    timeout 3600 python -u -m deepgo_tpu.arena \
    --a "$spec" --b "$opp" --games "$games" --rank 8 --seed 11 \
    >> runs/r3logs/search_arena.log 2>&1
  local rc=$?
  [ $rc -eq 0 ] && touch "$mark"
  echo "arena $tag rc=$rc"
  tail -2 runs/r3logs/search_arena.log
}

read -r BASE BASE_STEP <<< "$(find_ckpt converge-12L128)"
[ -n "${BASE:-}" ] || { echo "no converge checkpoint; run the main queue first"; exit 1; }
echo "converge checkpoint: $BASE (step $BASE_STEP)"

# --- stage 1: 2k-step winner fine-tune (the sweep's sweet spot) ---
FT2K_WANT=$((BASE_STEP + 2000))
read -r FT2K FT2K_STEP <<< "$(find_ckpt ft-winner-2k)"
if [ -z "${FT2K:-}" ] || [ "${FT2K_STEP:-0}" -lt "$FT2K_WANT" ]; then
  canary || { echo "canary failed; skipping ft-2k"; exit 1; }
  supervise runs/r3logs/ft2k.log 600 \
    timeout 7200 python -u -m deepgo_tpu.experiments.repeated \
    --checkpoint "$BASE" --iters 2000 --set \
    name=ft-winner-2k scheme=winner rate=0.005 momentum=0.9 \
    steps_per_call=20 print_interval=100 validation_interval=2000 \
    validation_size=4096 \
    >> runs/r3logs/ft2k.log 2>&1
  echo "ft-2k rc=$?"
  read -r FT2K FT2K_STEP <<< "$(find_ckpt ft-winner-2k)"
fi
echo "ft-winner-2k checkpoint: ${FT2K:-none} (step ${FT2K_STEP:-0})"

# --- stage 2: matches ---
match "search:$BASE" oneply search_base_oneply
# only match a COMPLETE fine-tune: a partial checkpoint (relay died
# mid-stage) would otherwise be done-marked as the real ft-winner-2k
if [ -z "${FT2K:-}" ] || [ "${FT2K_STEP:-0}" -lt "$FT2K_WANT" ]; then
  echo "ft-winner-2k incomplete (${FT2K_STEP:-0} < $FT2K_WANT); rerun to finish"
  exit 1
fi
match "checkpoint:$FT2K" oneply ft2k_oneply
match "checkpoint:$FT2K" heuristic ft2k_heuristic
match "search:$FT2K" oneply search_ft2k_oneply
match "search:$FT2K" heuristic search_ft2k_heuristic
echo "=== search arena done [$(date -u +%H:%M:%S)] ==="
