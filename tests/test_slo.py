"""SLO burn-rate tracking (obs/slo.py).

The ISSUE-6 coverage contract, all fake-clock (no sleeps): a fast burn
trips before a slow burn, recovery walks fast_burn -> slow_burn -> ok as
the windows drain, objectives read good/bad honestly from histograms /
gauges / health probes, transitions stream slo_burn events and feed the
deepgo_slo_burn_ratio gauge, and a burning SLO reads as degraded — but
HTTP 200 — on /healthz.
"""

import json
import urllib.request

import pytest

from deepgo_tpu.obs import JsonlSink, MetricsRegistry, ObsExporter
from deepgo_tpu.obs.report import read_events
from deepgo_tpu.obs.slo import (GaugeFloorObjective, HealthObjective,
                                HistogramLatencyObjective, SLOConfig,
                                SloTracker, parse_slo_spec)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_tracker(objective, registry=None, sink=None, **cfg_kw):
    cfg = SLOConfig(**{**dict(fast_window_s=60.0, slow_window_s=600.0,
                              fast_burn=10.0, slow_burn=6.0), **cfg_kw})
    clk = FakeClock()
    tracker = SloTracker([objective], config=cfg,
                         registry=registry or MetricsRegistry(),
                         sink=sink, clock=clk)
    return tracker, clk


def tick(tracker, clk, n, dt=10.0):
    out = None
    for _ in range(n):
        clk.advance(dt)
        out = tracker.evaluate()
    return out


class TestBurnWindows:
    def test_fast_burn_trips_before_slow_burn(self):
        ok = {"v": True}
        tracker, clk = make_tracker(
            HealthObjective("avail", lambda: ok["v"], target=0.99))
        tick(tracker, clk, 60)  # 600s of healthy history
        assert tracker.states["avail"] == "ok"
        ok["v"] = False
        verdict = tick(tracker, clk, 1)["avail"]
        # one bad tick: the 60s window burns hot, the 600s one does not
        assert verdict["state"] == "fast_burn"
        assert verdict["burn_fast"] >= 10.0
        assert verdict["burn_slow"] < 6.0

    def test_recovery_decays_fast_then_slow_then_ok(self):
        ok = {"v": True}
        tracker, clk = make_tracker(
            HealthObjective("avail", lambda: ok["v"], target=0.99))
        tick(tracker, clk, 60)
        ok["v"] = False
        tick(tracker, clk, 6)
        assert tracker.states["avail"] == "fast_burn"
        ok["v"] = True
        tick(tracker, clk, 12)  # 120s: the bad ticks leave the fast window
        assert tracker.states["avail"] == "slow_burn"
        tick(tracker, clk, 60)  # 600s more: they leave the slow window too
        assert tracker.states["avail"] == "ok"

    def test_no_data_is_not_a_violation(self):
        reg = MetricsRegistry()
        tracker, clk = make_tracker(HistogramLatencyObjective(
            "lat", "lat_seconds", 0.1, registry=reg), registry=reg)
        verdict = tick(tracker, clk, 5)["lat"]
        assert verdict["state"] == "ok"
        assert verdict["burn_fast"] == 0.0

    def test_transitions_emit_slo_burn_events(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        ok = {"v": True}
        with JsonlSink(path) as sink:
            tracker, clk = make_tracker(
                HealthObjective("avail", lambda: ok["v"], target=0.99),
                sink=sink)
            tick(tracker, clk, 60)
            ok["v"] = False
            tick(tracker, clk, 2)
            ok["v"] = True
            tick(tracker, clk, 80)
        kinds = [(r["from_state"], r["to_state"])
                 for r in read_events(path) if r.get("kind") == "slo_burn"]
        assert kinds[0] == ("ok", "fast_burn")
        assert kinds[-1][1] == "ok"  # recovered in the end

    def test_burn_gauge_updated_per_window(self):
        reg = MetricsRegistry()
        ok = {"v": True}
        tracker, clk = make_tracker(
            HealthObjective("avail", lambda: ok["v"], target=0.99),
            registry=reg)
        tick(tracker, clk, 60)
        ok["v"] = False
        tick(tracker, clk, 1)
        g = reg.gauge("deepgo_slo_burn_ratio")
        assert g.value(slo="avail", window="fast") >= 10.0
        assert g.value(slo="avail", window="slow") > 0.0


class TestObjectives:
    def test_histogram_latency_counts_buckets_at_threshold(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(0.05, 0.25, 1.0))
        for v in (0.01, 0.2, 0.9):
            h.observe(v, engine="e")
        obj = HistogramLatencyObjective("lat", "lat_seconds", 0.25,
                                        registry=reg)
        good, total = obj.sample()
        assert (good, total) == (2.0, 3.0)  # 0.9 misses the 0.25 bucket

    def test_histogram_latency_label_filter(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_seconds", buckets=(0.1,))
        h.observe(0.05, engine="a")
        h.observe(0.05, engine="b")
        obj = HistogramLatencyObjective("lat", "lat_seconds", 0.1,
                                        registry=reg, engine="a")
        assert obj.sample() == (1.0, 1.0)

    def test_gauge_floor_skips_absent_then_judges(self):
        reg = MetricsRegistry()
        obj = GaugeFloorObjective("sps", "sps_gauge", floor=100.0,
                                  registry=reg)
        assert obj.sample() == (0.0, 0.0)  # never set: no verdict yet
        reg.gauge("sps_gauge").set(150.0)
        assert obj.sample() == (1.0, 1.0)
        reg.gauge("sps_gauge").set(50.0)
        assert obj.sample() == (1.0, 2.0)  # below floor: bad tick

    def test_health_objective_counts_raising_probe_as_bad(self):
        obj = HealthObjective("avail", lambda: 1 / 0, target=0.9)
        assert obj.sample() == (0.0, 1.0)

    def test_target_must_be_a_fraction(self):
        with pytest.raises(ValueError, match="target"):
            HealthObjective("x", lambda: True, target=1.0)


class TestSpecGrammar:
    def test_parse_known_objectives(self):
        reg = MetricsRegistry()
        objs = parse_slo_spec("dispatch_ms=50,train_sps=1000@0.95",
                              registry=reg)
        assert [o.name for o in objs] == ["serving_dispatch",
                                         "train_throughput"]
        assert objs[0].threshold_s == pytest.approx(0.05)
        assert objs[1].floor == 1000.0 and objs[1].target == 0.95

    def test_unknown_objective_fails_loudly(self):
        with pytest.raises(ValueError, match="unknown SLO"):
            parse_slo_spec("made_up=1")

    def test_availability_requires_health_fn(self):
        with pytest.raises(ValueError, match="availability"):
            parse_slo_spec("availability=0.999")
        objs = parse_slo_spec("availability=0.999",
                              health_fn=lambda: {"healthy": True})
        assert objs[0].name == "availability"


class TestHealthzDegraded:
    def test_burning_slo_reads_degraded_but_200(self):
        ok = {"v": True}
        tracker, clk = make_tracker(
            HealthObjective("avail", lambda: ok["v"], target=0.99))
        tick(tracker, clk, 60)
        ok["v"] = False
        tick(tracker, clk, 2)
        assert tracker.states["avail"] == "fast_burn"
        with ObsExporter(port=0, registry=MetricsRegistry()) as exp:
            exp.add_health("slo", tracker.health)
            with urllib.request.urlopen(exp.url + "/healthz",
                                        timeout=5) as r:
                assert r.status == 200  # degraded is NOT a 503
                payload = json.loads(r.read().decode())
        assert payload["healthy"] is True
        assert payload["degraded"] is True
        assert payload["components"]["slo"]["burning"] == {
            "avail": "fast_burn"}


def test_fast_burn_trips_flight_recorder(tmp_path, monkeypatch):
    # entering fast_burn ships the black box (obs/sentinel.py)
    from deepgo_tpu.obs import sentinel

    monkeypatch.setattr(sentinel, "_recorder", None)
    sentinel.configure_flight(str(tmp_path))
    try:
        ok = {"v": True}
        tracker, clk = make_tracker(
            HealthObjective("avail", lambda: ok["v"], target=0.99))
        tick(tracker, clk, 60)
        ok["v"] = False
        tick(tracker, clk, 2)
        dump = json.loads((tmp_path / "flight-0000.json").read_text())
        assert dump["reason"] == "slo_fast_burn"
        assert dump["detail"]["slo"] == "avail"
    finally:
        sentinel.get_flight_recorder().close()
        monkeypatch.setattr(sentinel, "_recorder", None)
