"""Child process for the real 2-process distributed test.

Each of two processes runs this with (process_id, coordinator_port): joins
the jax.distributed runtime over 2 virtual CPU devices per process (the
multi-host analogue of the 8-virtual-device single-process tests), builds
the hybrid data mesh spanning both processes, contributes its own half of
a global batch via ``global_array_from_local``, and executes one
data-parallel train step whose gradient all-reduce crosses the process
boundary. Prints one line the parent asserts on.

Usage: python distributed_child.py <process_id> <port>
"""

import os
import sys

PROC_ID = int(sys.argv[1])
PORT = sys.argv[2]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# PR 4: the production entry — watchdog-armed, full-jitter-retried dial
# (parallel/deadlines.py) — so this harness exercises the same bootstrap a
# pod host uses instead of the raw jax.distributed.initialize
from deepgo_tpu.parallel.deadlines import initialize_with_deadline  # noqa: E402

initialize_with_deadline(
    f"127.0.0.1:{PORT}",
    num_processes=2,
    process_id=PROC_ID,
    timeout_s=180.0,
)

import numpy as np  # noqa: E402

from deepgo_tpu.models import ModelConfig, init  # noqa: E402
from deepgo_tpu.parallel import distributed, replicated_sharding  # noqa: E402
from deepgo_tpu.training import make_train_step, sgd  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 2
assert jax.device_count() == 4

mesh = distributed.hybrid_mesh(n_model=1)
assert mesh.devices.shape == (4, 1)

global_batch = 8
local_n = distributed.per_host_batch(global_batch)
assert local_n == 4

# identical rng on both processes; each contributes its own slice, so the
# assembled global batch equals the single-process batch for these seeds
rng = np.random.default_rng(0)
full = {
    "packed": rng.integers(0, 3, size=(global_batch, 9, 19, 19), dtype=np.uint8),
    "player": rng.integers(1, 3, size=global_batch).astype(np.int32),
    "rank": rng.integers(1, 10, size=global_batch).astype(np.int32),
    "target": rng.integers(0, 361, size=global_batch).astype(np.int32),
}
local = {k: v[PROC_ID * local_n:(PROC_ID + 1) * local_n] for k, v in full.items()}
batch = distributed.global_array_from_local(mesh, local)

cfg = ModelConfig(num_layers=2, channels=8, compute_dtype="float32")
optimizer = sgd(0.01)
params = jax.device_put(init(jax.random.key(0), cfg), replicated_sharding(mesh))
opt_state = jax.device_put(optimizer.init(params), replicated_sharding(mesh))
step = make_train_step(cfg, optimizer)

params, opt_state, loss = step(params, opt_state, batch)
jax.block_until_ready(loss)
print(f"DIST_OK proc={PROC_ID} loss={float(loss):.6f}", flush=True)
