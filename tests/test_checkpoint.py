"""Checkpoint format v2: integrity verification, corruption handling, and
run-directory scanning for elastic auto-resume."""

import json
import os

import numpy as np
import pytest

from deepgo_tpu.experiments import checkpoint as ckpt
from deepgo_tpu.experiments.checkpoint import CheckpointError


def write_ckpt(run_dir, step, value=0.0):
    path = os.path.join(run_dir, ckpt.checkpoint_name(step))
    ckpt.save_checkpoint(
        path,
        {"w": np.full(6, value, np.float32), "b": np.zeros(2, np.float32)},
        {"m": np.zeros(3, np.float32)},
        {"id": "t", "step": step, "validation_history": [], "config": {}},
    )
    return path


# ---- format v2 round trip ----


def test_v2_roundtrip_and_integrity_block(tmp_path):
    path = write_ckpt(str(tmp_path), 7, value=1.5)
    meta, p_leaves, o_leaves = ckpt.load_checkpoint(path)
    assert meta["format_version"] == 2
    assert meta["step"] == 7
    np.testing.assert_array_equal(p_leaves[1], np.full(6, 1.5, np.float32))
    assert len(o_leaves) == 1
    # integrity: a CRC per stored array plus a whole-checkpoint digest
    integ = meta["integrity"]
    assert set(integ["arrays"]) == {"params_0000", "params_0001", "opt_0000"}
    assert len(integ["digest"]) == 64  # sha256 hex


def test_v1_checkpoint_still_loads(tmp_path):
    # a pre-integrity artifact: loadable, just not verifiable
    path = str(tmp_path / "old.npz")
    meta = {"format_version": 1, "step": 3, "validation_history": [],
            "config": {}, "id": "legacy"}
    np.savez(path, params_0000=np.arange(4.0), opt_0000=np.zeros(2),
             meta=np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8))
    got, p_leaves, _ = ckpt.load_checkpoint(path)
    assert got["step"] == 3
    assert ckpt.verify_checkpoint(path)["id"] == "legacy"


def test_unsupported_version_raises_checkpoint_error(tmp_path):
    path = str(tmp_path / "future.npz")
    meta = {"format_version": 99}
    np.savez(path, meta=np.frombuffer(json.dumps(meta).encode(), np.uint8))
    with pytest.raises(CheckpointError, match="format_version 99"):
        ckpt.load_meta(path)
    with pytest.raises(CheckpointError, match="format_version 99"):
        ckpt.load_checkpoint(path)


def test_load_meta_skips_arrays_but_validates(tmp_path):
    path = write_ckpt(str(tmp_path), 11)
    assert ckpt.load_meta(path)["step"] == 11
    with pytest.raises(CheckpointError):
        ckpt.load_meta(str(tmp_path / "missing.npz"))


# ---- unflatten validation ----


def test_unflatten_like_leaf_count_mismatch(tmp_path):
    template = {"a": np.zeros(2), "b": np.zeros(3)}
    with pytest.raises(CheckpointError, match="1 leaves, template needs 2"):
        ckpt.unflatten_like(template, [np.zeros(2)], "some.npz")


def test_unflatten_like_shape_mismatch():
    template = {"a": np.zeros(2)}
    with pytest.raises(CheckpointError, match="shape"):
        ckpt.unflatten_like(template, [np.zeros(5)])


# ---- corruption matrix: every flavor yields a clean skip, not a traceback ----


def corrupt_truncate(path):
    data = open(path, "rb").read()
    open(path, "wb").write(data[: len(data) // 2])


def corrupt_flip_byte(path):
    # flip a byte inside the "w" array's payload (six float32 1.5s — the
    # file midpoint can land in zip padding nothing ever reads)
    data = bytearray(open(path, "rb").read())
    payload = np.full(6, 1.5, np.float32).tobytes()
    at = data.find(payload)
    assert at > 0, "array payload not found uncompressed"
    data[at] ^= 0xFF
    open(path, "wb").write(bytes(data))


def corrupt_no_meta(path):
    np.savez(path, params_0000=np.arange(4.0))


def corrupt_zero_length(path):
    open(path, "wb").close()


@pytest.mark.parametrize("corrupt,reason", [
    (corrupt_truncate, "truncated or corrupt"),
    (corrupt_flip_byte, "corrupt|CRC"),  # zip CRC or our CRC, byte-dependent
    (corrupt_no_meta, "no meta entry"),
    (corrupt_zero_length, "zero-length"),
])
def test_verify_rejects_corruption(tmp_path, corrupt, reason):
    path = write_ckpt(str(tmp_path), 5, value=1.5)
    corrupt(path)
    with pytest.raises(CheckpointError, match=reason) as ei:
        ckpt.verify_checkpoint(path)
    assert ei.value.path == path


def test_our_crc_catches_what_zip_cannot(tmp_path):
    # rewrite the npz with a bit-flipped array but *correct* zip metadata:
    # only the meta-level CRC32/digest can catch this class of corruption
    path = write_ckpt(str(tmp_path), 5)
    with np.load(path) as z:
        arrays = {k: z[k].copy() for k in z.files}
    flipped = arrays["params_0000"].view(np.uint8).copy()
    flipped[0] ^= 0x01
    arrays["params_0000"] = flipped.view(np.float32)
    np.savez(path, **arrays)  # fresh, internally-consistent zip
    with pytest.raises(CheckpointError, match="CRC32 mismatch|digest"):
        ckpt.verify_checkpoint(path)


@pytest.mark.parametrize("corrupt", [
    corrupt_truncate, corrupt_flip_byte, corrupt_no_meta, corrupt_zero_length,
])
def test_find_latest_valid_skips_corrupt_newest(tmp_path, corrupt):
    run = str(tmp_path)
    good = write_ckpt(run, 10)
    bad = write_ckpt(run, 20, value=1.5)
    corrupt(bad)
    logged = []
    assert ckpt.find_latest_valid(run, log=logged.append) == good
    assert len(logged) == 1 and "skipping" in logged[0] and bad in logged[0]


def test_find_latest_valid_logs_to_stderr_by_default(tmp_path, capsys):
    run = str(tmp_path)
    write_ckpt(run, 10)
    corrupt_zero_length(write_ckpt(run, 20))
    assert ckpt.find_latest_valid(run) is not None
    assert "skipping" in capsys.readouterr().err


def test_find_latest_valid_empty_and_missing_dir(tmp_path):
    assert ckpt.find_latest_valid(str(tmp_path)) is None
    assert ckpt.find_latest_valid(str(tmp_path / "nope")) is None


def test_find_latest_valid_considers_legacy_single_file(tmp_path):
    # an old-layout run directory: one plain checkpoint.npz, no rolling files
    legacy = str(tmp_path / "checkpoint.npz")
    ckpt.save_checkpoint(legacy, {"w": np.zeros(2)}, {"m": np.zeros(2)},
                         {"id": "t", "step": 4, "validation_history": [],
                          "config": {}})
    assert ckpt.find_latest_valid(str(tmp_path)) == legacy


def test_find_latest_valid_ignores_alias_symlink(tmp_path):
    run = str(tmp_path)
    newest = write_ckpt(run, 30)
    os.symlink(os.path.basename(newest),
               os.path.join(run, "checkpoint.npz"))
    # the alias must not be scanned twice or shadow the numbered file
    assert ckpt.find_latest_valid(run) == newest


def test_list_checkpoints_orders_and_filters(tmp_path):
    run = str(tmp_path)
    write_ckpt(run, 20)
    write_ckpt(run, 5)
    open(os.path.join(run, "checkpoint-0000abcd.npz"), "w").close()  # not ours
    open(os.path.join(run, "other.npz"), "w").close()
    assert [s for s, _ in ckpt.list_checkpoints(run)] == [5, 20]
