"""Dihedral augmentation tests: identity, bijectivity, and — the real
property — equivariance with the rules engine: summarize(transform(game))
== transform(summarize(game))."""

import numpy as np
import pytest

import jax.numpy as jnp

from deepgo_tpu import sgf
from deepgo_tpu.go import new_board, play, summarize
from deepgo_tpu.ops.augment import _PERM_NP, _TARGET_MAP_NP, augment_batch


def test_sym0_is_identity():
    rng = np.random.default_rng(0)
    packed = rng.integers(0, 255, size=(4, 9, 19, 19), dtype=np.uint8)
    target = rng.integers(0, 361, size=4).astype(np.int32)
    out, new_target = augment_batch(
        jnp.asarray(packed), jnp.asarray(target), jnp.zeros(4, jnp.int32)
    )
    assert np.array_equal(np.asarray(out), packed)
    assert np.array_equal(np.asarray(new_target), target)


def test_tables_are_permutations():
    for k in range(8):
        assert sorted(_PERM_NP[k]) == list(range(361))
        assert sorted(_TARGET_MAP_NP[k]) == list(range(361))
        # TARGET_MAP is PERM's inverse
        assert np.array_equal(_PERM_NP[k][_TARGET_MAP_NP[k]], np.arange(361))


def _transform_moves(moves, k):
    """Apply symmetry k to move coordinates via the target map."""
    out = []
    for m in moves:
        t = int(_TARGET_MAP_NP[k][19 * m.x + m.y])
        out.append(sgf.Move(m.player, t // 19, t % 19))
    return out


@pytest.mark.parametrize("k", range(8))
def test_equivariance_with_rules_engine(k):
    """Playing a transformed game must give the transformed summary: the
    packed features commute with board symmetries."""
    game = sgf.parse(
        "(;BR[5d]WR[5d];B[pd];W[dd];B[pq];W[dp];B[qf];W[cf];B[cq];W[dq]"
        ";B[cp];W[do];B[bn];W[fp])"
    )
    stones, age = new_board()
    for m in game.moves:
        play(stones, age, m.x, m.y, m.player)
    packed = summarize(stones, age)

    stones_t, age_t = new_board()
    for m in _transform_moves(game.moves, k):
        play(stones_t, age_t, m.x, m.y, m.player)
    packed_t = summarize(stones_t, age_t)

    got, _ = augment_batch(
        jnp.asarray(packed[None]),
        jnp.zeros(1, jnp.int32),
        jnp.full((1,), k, jnp.int32),
    )
    assert np.array_equal(np.asarray(got)[0], packed_t), f"symmetry {k}"


def test_augmented_training_runs(tmp_path):
    from test_experiment import tiny_config  # reuse the tiny setup
    from deepgo_tpu.data.transcribe import transcribe_split
    from deepgo_tpu.experiments import Experiment
    import os
    from conftest import REPO_ROOT

    root = tmp_path / "processed"
    for split in ("validation", "test"):
        transcribe_split(os.path.join(REPO_ROOT, "data/sgf", split),
                         str(root / split), workers=1, verbose=False)
    cfg = tiny_config(str(root), run_dir=str(tmp_path / "runs"), augment=True)
    exp = Experiment(cfg)
    summary = exp.run(15)
    assert summary["final_ewma"] < 5.89
