"""Experiment layer tests: train loop, checkpoint resume, warm restart."""

import os

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.data import GoDataset
from deepgo_tpu.data.loader import AsyncLoader
from deepgo_tpu.data.transcribe import transcribe_split
from deepgo_tpu.experiments import Experiment, ExperimentConfig
from deepgo_tpu.experiments.repeated import warm_restart
from deepgo_tpu.utils.metrics import read_jsonl


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("processed")
    for split in ("validation", "test"):
        transcribe_split(
            os.path.join(REPO_ROOT, "data/sgf", split),
            str(root / split),
            workers=1,
            verbose=False,
        )
    return str(root)


def tiny_config(data_root, **kw):
    defaults = dict(
        name="test",
        num_layers=2,
        channels=8,
        batch_size=8,
        rate=0.05,
        validation_size=32,
        validation_interval=10,
        print_interval=10,
        data_root=data_root,
        train_split="validation",  # small split as train data
        validation_split="test",
        test_split="test",
        loader_threads=0,
        data_parallel=1,
    )
    defaults.update(kw)
    return ExperimentConfig(**defaults)


def test_async_loader_matches_sync_sampling(data_root):
    ds = GoDataset(data_root, "validation")
    with AsyncLoader(ds, 8, seed=3, num_threads=2, prefetch=2) as loader:
        batches = [loader.get() for _ in range(5)]
    for b in batches:
        assert b["packed"].shape == (8, 9, 19, 19)
        assert ((np.asarray(b["target"]) >= 0) & (np.asarray(b["target"]) < 361)).all()


def test_async_loader_surfaces_worker_error(data_root, monkeypatch):
    # a sampler raise inside a worker thread must re-raise from get(), not
    # leave the consumer blocked forever on an empty queue (round-3 verdict
    # weak finding 2)
    import deepgo_tpu.data.loader as loader_mod

    def boom(dataset, rng, batch_size, scheme="game", augment=False,
             wire="packed"):
        raise ValueError("synthetic sampler failure")

    monkeypatch.setattr(loader_mod, "make_host_batch", boom)
    ds = GoDataset(data_root, "validation")
    with AsyncLoader(ds, 8, seed=3, num_threads=2, prefetch=2) as loader:
        with pytest.raises(RuntimeError, match="worker thread died") as ei:
            loader.get()
        assert "synthetic sampler failure" in str(ei.value.__cause__)


def test_loader_derives_stack_sharding(data_root):
    import jax
    from jax.sharding import PartitionSpec as P

    from deepgo_tpu.parallel import data_sharding, make_mesh

    ds = GoDataset(data_root, "validation")
    mesh = make_mesh(len(jax.devices()), 1)
    with AsyncLoader(ds, 8, num_threads=0, sharding=data_sharding(mesh),
                     stack=3) as loader:
        b = loader.get()
    assert b["packed"].shape == (3, 8, 9, 19, 19)
    # superbatch placement lifted from the single-batch spec
    assert b["packed"].sharding.spec == P(None, "data")


def test_train_smoke_loss_decreases(data_root, tmp_path):
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"))
    exp = Experiment(cfg)
    summary = exp.run(30)
    assert exp.step == 30
    assert summary["final_ewma"] < 5.89  # below uniform-random NLL ln(361)
    assert summary["last_validation"]["n"] == 32
    # metrics + registry written
    metrics = read_jsonl(os.path.join(exp.run_path, "metrics.jsonl"))
    kinds = {m["kind"] for m in metrics}
    assert {"train", "validation", "summary"} <= kinds
    registry = read_jsonl(os.path.join(cfg.run_dir, "registry.jsonl"))
    assert registry[-1]["id"] == exp.id
    assert registry[-1]["config"]["channels"] == 8


def test_steps_per_call_numerics_match_single_step(data_root, tmp_path):
    """K chained steps in one lax.scan dispatch must produce the params K
    sequential single-step dispatches produce (same synchronous sampling
    stream), so dispatch amortization is a pure perf knob. Equality is at
    float32 precision, not bitwise: K=1 deliberately bypasses the scan
    program (its CPU compile is pathological), and XLA fuses the scanned
    and unscanned programs differently — measured divergence is one ulp
    (~3e-8) per step."""
    import jax

    results = []
    for k in (1, 5):
        cfg = tiny_config(data_root, run_dir=str(tmp_path / f"runs{k}"),
                          steps_per_call=k, validation_interval=100)
        exp = Experiment(cfg)
        exp.run(10)
        results.append(jax.tree.map(np.asarray, exp.params))
    flat1 = jax.tree.leaves(results[0])
    flat5 = jax.tree.leaves(results[1])
    for a, b in zip(flat1, flat5):
        np.testing.assert_allclose(a, b, atol=5e-6, rtol=1e-5)


def test_resume_realigns_to_print_windows(data_root, tmp_path):
    """A resume from a step that is not a multiple of print_interval must
    realign so prints/validation/checkpoints still fire (regression: the
    fixed-K loop advanced 12 -> 22 -> 32 and never validated again)."""
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"))
    exp = Experiment(cfg)
    exp.run(12)
    path = exp.save()
    resumed = Experiment.load(path)
    resumed.run(20)
    assert resumed.step == 32
    # restored history keeps step 10; the resumed run must add 20 and 30
    # (validation_interval=10) despite starting misaligned at step 12
    steps = [v["step"] for v in resumed.validation_history]
    assert steps == [10, 20, 30]
    metrics = read_jsonl(os.path.join(resumed.run_path, "metrics.jsonl"))
    assert any(m["kind"] == "validation" for m in metrics)


def test_even_validation_set_is_deterministic(data_root, tmp_path):
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"))
    exp = Experiment(cfg)
    exp.init()
    b1 = exp._validation_batches()
    b2 = exp._validation_batches()
    assert len(b1) == len(b2) > 0
    for x, y in zip(b1, b2):
        for key in x:
            np.testing.assert_array_equal(np.asarray(x[key]), np.asarray(y[key]))


def test_checkpoint_resume_roundtrip(data_root, tmp_path):
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"))
    exp = Experiment(cfg)
    exp.run(12)
    path = exp.save()
    before = exp.validate()

    resumed = Experiment.load(path)
    assert resumed.step == exp.step
    assert resumed.id == exp.id
    assert resumed.config == exp.config
    after = resumed.validate()
    assert after["cost"] == pytest.approx(before["cost"], rel=1e-5)
    assert after["accuracy"] == pytest.approx(before["accuracy"])
    # optimizer state survives: decayed rate rather than the base rate
    assert float(resumed.opt_state["rate"]) == pytest.approx(
        float(exp.opt_state["rate"])
    )
    resumed.run(5)
    assert resumed.step == exp.step + 5


def test_warm_restart_fresh_optimizer_new_id(data_root, tmp_path):
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"), rate_decay=1e-3)
    exp = Experiment(cfg)
    exp.run(15)
    path = exp.save()
    decayed = float(exp.opt_state["rate"])
    assert decayed < cfg.rate

    restarted = warm_restart(path, overrides={}, num=2)
    assert restarted.id != exp.id
    assert restarted.step == exp.step  # keeps iteration count
    assert float(restarted.opt_state["rate"]) == pytest.approx(cfg.rate)  # fresh
    assert restarted.config.seed == cfg.seed + 2
    # weights were restored: same validation result as the source
    a = exp.validate()
    b = restarted.validate()
    assert b["cost"] == pytest.approx(a["cost"], rel=1e-5)


def test_bad_batch_postmortem_capture(data_root, tmp_path):
    """A failing train step dumps the offending batch to bad_batch.npz
    (the reference kept it in globals, train.lua:106-109)."""
    # steps_per_call is explicit because the auto setting resolves to 1 on
    # the CPU test backend
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"),
                      steps_per_call=10)
    exp = Experiment(cfg)
    exp.init()

    def exploding_step(params, opt_state, batch):
        raise FloatingPointError("synthetic step failure")

    # full print windows go through the scan program; short tails through
    # the single step — both must capture the batch they failed on
    exp.train_step_many = exploding_step
    with pytest.raises(FloatingPointError):
        exp.run(10)
    dump = np.load(os.path.join(exp.run_path, "bad_batch.npz"))
    # packed is stored as transferred — auto wire resolves to raw on CPU
    assert dump["packed"].shape == (10, cfg.batch_size, 9, 19, 19)
    assert set(dump.files) >= {"packed", "player", "rank", "target"}

    exp2 = Experiment(tiny_config(data_root, run_dir=str(tmp_path / "runs2"),
                                  steps_per_call=10))
    exp2.init()
    exp2.train_step = exploding_step
    with pytest.raises(FloatingPointError):
        exp2.run(5)  # < steps_per_call -> single-step tail path
    dump = np.load(os.path.join(exp2.run_path, "bad_batch.npz"))
    assert dump["packed"].shape == (cfg.batch_size, 9, 19, 19)


def test_nibble_wire_trains_and_validates(data_root, tmp_path):
    # the full streamed path under the nibble wire, validation included —
    # the validation builder pads the wire-shaped packed array, which the
    # (n, 1625) flat layout broke once before (rank-specific pad spec)
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"),
                      wire_format="nibble", validation_size=20)
    exp = Experiment(cfg)
    exp.init()
    exp.run(3)
    out = exp.validate()
    assert np.isfinite(out["cost"]) and 0.0 <= out["accuracy"] <= 1.0


def test_unknown_wire_format_rejected(data_root, tmp_path):
    # a typo'd wire_format must fail loudly at init, not silently run the
    # packed (2x-bytes) path with a bogus label
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"),
                      wire_format="nible")
    with pytest.raises(ValueError, match="wire_format"):
        Experiment(cfg).init()


def test_evaluate_full_split(data_root, tmp_path):
    cfg = tiny_config(data_root, run_dir=str(tmp_path / "runs"))
    exp = Experiment(cfg)
    exp.init()
    result = exp.evaluate(split="test")
    assert result["n"] == 125
    assert result["cost"] > 0
    assert exp.validation_history == []
