"""Regression sentinel + crash flight recorder (obs/sentinel.py).

Gate: pass at noise-level drift, warn in the band, fail at a >= 10 %
regression, direction-aware for latency metrics, skip (never fail) on
missing/cross-device baselines — and the bench integration folds the
verdict into the one JSON line with a nonzero exit on fail.

Flight recorder: ring-buffer round-trip (snapshots + spans survive into
an atomically written flight-NNNN.json), time-based eviction, sequential
numbering, a DEEPGO_FAULTS-injected supervisor restart dumping the spans
that preceded the fault (the ISSUE-6 acceptance shape), and the external
watchdog's SIGUSR1 grace signal producing a dump from a Python-level
wedge before the SIGKILL lands.
"""

import json
import os
import random
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.obs import MetricsRegistry, span
from deepgo_tpu.obs.sentinel import (FlightRecorder, GateConfig,
                                     evaluate_gate)


# ---- the gate ----


def fresh(value, metric="boards_per_sec", device="X", **kw):
    return {"metric": metric, "value": value, "device": device, **kw}


def base(value, device="X", **kw):
    return {"value": value, "device": device, **kw}


class TestGate:
    def test_pass_at_noise_level_drift(self):
        v = evaluate_gate(fresh(98.0), base(100.0))
        assert v["verdict"] == "pass"

    def test_warn_band_between_noise_and_gate(self):
        v = evaluate_gate(fresh(93.0), base(100.0))
        assert v["verdict"] == "warn"

    def test_fail_at_ten_percent_regression(self):
        v = evaluate_gate(fresh(90.0), base(100.0))
        assert v["verdict"] == "fail"
        assert v["regression"] == pytest.approx(0.10)

    def test_improvement_passes(self):
        v = evaluate_gate(fresh(130.0), base(100.0))
        assert v["verdict"] == "pass"
        assert v["regression"] < 0

    def test_lower_is_better_direction(self):
        lat = "policy_inference_latency_ms"
        assert evaluate_gate(fresh(115.0, metric=lat),
                             base(100.0))["verdict"] == "fail"
        assert evaluate_gate(fresh(90.0, metric=lat),
                             base(100.0))["verdict"] == "pass"

    def test_recorded_noise_widens_the_threshold(self):
        # 12% regression fails at the default gate but passes when the
        # measurement itself recorded 8% repeat spread (2x headroom)
        v = evaluate_gate(fresh(88.0), base(100.0))
        assert v["verdict"] == "fail"
        v = evaluate_gate(fresh(88.0, noise_frac=0.08), base(100.0))
        assert v["verdict"] != "fail"
        assert v["effective_threshold"] == pytest.approx(0.16)

    def test_device_mismatch_skips_not_fails(self):
        v = evaluate_gate(fresh(10.0, device="cpu"),
                          base(104034.1, device="TPU v5 lite0"))
        assert v["verdict"] == "skip"
        assert "device mismatch" in v["reason"]

    def test_missing_baseline_skips(self):
        assert evaluate_gate(fresh(100.0), None)["verdict"] == "skip"

    def test_stale_fresh_result_skips(self):
        v = evaluate_gate(fresh(100.0, stale=True, error="wedged"),
                          base(100.0))
        assert v["verdict"] == "skip"

    def test_custom_threshold(self):
        cfg = GateConfig(threshold=0.30, warn_threshold=0.25)
        assert evaluate_gate(fresh(75.0), base(100.0),
                             cfg)["verdict"] == "warn"
        assert evaluate_gate(fresh(65.0), base(100.0),
                             cfg)["verdict"] == "fail"


# ---- the flight recorder ----


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestFlightRecorder:
    def test_dump_round_trip_with_spans_and_snapshots(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("evidence_total").inc(7)
        rec = FlightRecorder(registry=reg)
        rec.configure(str(tmp_path))
        try:
            with span("incident_prelude", registry=reg, step=3):
                pass
            rec.tick()
            path = rec.dump("test_fault", detail_key="v")
            assert path is not None and path.endswith("flight-0000.json")
            dump = json.loads(open(path).read())
            assert dump["reason"] == "test_fault"
            assert dump["detail"] == {"detail_key": "v"}
            assert [s["name"] for s in dump["spans"]] == ["incident_prelude"]
            assert dump["snapshots"][0]["metrics"][
                "evidence_total"]["series"][""] == 7
            # the dump-time snapshot rides along even without a tick
            assert dump["final_snapshot"]["metrics"][
                "evidence_total"]["series"][""] == 7
        finally:
            rec.close()

    def test_sequential_numbering(self, tmp_path):
        rec = FlightRecorder(registry=MetricsRegistry())
        rec.configure(str(tmp_path))
        try:
            assert rec.dump("a").endswith("flight-0000.json")
            assert rec.dump("b").endswith("flight-0001.json")
        finally:
            rec.close()

    def test_window_eviction_with_fake_clock(self, tmp_path):
        clk = FakeClock()
        rec = FlightRecorder(registry=MetricsRegistry(), window_s=30.0,
                             clock=clk)
        rec.configure(str(tmp_path))
        try:
            rec.tick()          # t=1000
            clk.t += 100.0
            rec.tick()          # t=1100: the first snapshot is stale
            dump = json.loads(open(rec.dump("evict")).read())
            assert [s["time"] for s in dump["snapshots"]] == [1100.0]
        finally:
            rec.close()

    def test_unconfigured_recorder_is_inert(self):
        rec = FlightRecorder(registry=MetricsRegistry())
        rec.tick()
        assert rec.dump("nothing") is None

    def test_supervisor_restart_dumps_preceding_spans(self, tmp_path,
                                                      monkeypatch):
        """The ISSUE-6 acceptance shape: a DEEPGO_FAULTS-injected
        dispatcher kill produces a valid flight dump containing the spans
        that preceded the fault."""
        from deepgo_tpu.obs import sentinel
        from deepgo_tpu.serving import (EngineConfig, InferenceEngine,
                                        SupervisedEngine)
        from deepgo_tpu.utils import faults

        monkeypatch.setattr(sentinel, "_recorder", None)
        sentinel.configure_flight(str(tmp_path))
        faults.install("serving_dispatch:fail@1")
        try:
            with span("before_fault", registry=MetricsRegistry()):
                pass

            def forward(params, packed, player, rank):
                return np.asarray(packed, np.float32).sum(axis=(1, 2, 3))

            ecfg = EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
            sup = SupervisedEngine(
                lambda: InferenceEngine(forward, None, ecfg, name="inner"),
                name="flight-test", rng=random.Random(0))
            try:
                rng = np.random.default_rng(0)
                board = rng.integers(0, 3, size=(9, 19, 19), dtype=np.uint8)
                # the first dispatch hits the injected kill; the restart
                # replays and the future still resolves
                assert sup.submit(board, 1, 5, timeout_s=30.0).result(
                    timeout=30.0) is not None
            finally:
                sup.close()
            deadline = time.time() + 10.0
            while not sentinel.get_flight_recorder().dumps \
                    and time.time() < deadline:
                time.sleep(0.05)  # the dump happens on the supervisor thread
            dumps = sentinel.get_flight_recorder().dumps
            assert dumps, "supervisor restart produced no flight dump"
            dump = json.loads(open(dumps[0]).read())
            assert dump["reason"] == "serving_restart"
            assert dump["detail"]["engine"] == "flight-test"
            assert "before_fault" in [s["name"] for s in dump["spans"]]
        finally:
            faults.reset()
            sentinel.get_flight_recorder().close()
            monkeypatch.setattr(sentinel, "_recorder", None)


def test_watchdog_grace_signal_dumps_before_kill(tmp_path):
    """arm(flight=True): a Python-level wedge gets SIGUSR1 one second
    before the SIGKILL and leaves its black box behind."""
    code = (
        "import sys, time\n"
        "sys.path.insert(0, sys.argv[1])\n"
        "from deepgo_tpu.obs import sentinel\n"
        "from deepgo_tpu.utils import watchdog\n"
        "sentinel.configure_flight(sys.argv[2])\n"
        "assert sentinel.install_signal_dump()\n"
        "sentinel.get_flight_recorder().tick()\n"
        "watchdog.arm('flight-test', timeout_s=1.0, flight=True)\n"
        "time.sleep(60)\n"  # the wedge: never disarms
        "print('UNREACHABLE')\n"
    )
    r = subprocess.run(
        [sys.executable, "-c", code, REPO_ROOT, str(tmp_path)],
        capture_output=True, text=True, timeout=60,
        env={k: v for k, v in os.environ.items() if k != "PYTHONPATH"})
    assert r.returncode == -9, (r.returncode, r.stderr[-500:])
    assert "UNREACHABLE" not in r.stdout
    dump = json.loads((tmp_path / "flight-0000.json").read_text())
    assert dump["reason"] == "signal"
    assert dump["snapshots"]  # the pre-wedge tick survived into the dump


# ---- bench --gate integration (three quick CPU serving benches) ----


def test_bench_gate_exit_codes_end_to_end(tmp_path):
    """Clean run -> capture value; gate vs an inflated last-good fails
    (exit 1, verdict in the single JSON line); gate vs a beatable
    last-good passes (exit 0)."""
    def run_bench(last_good_path, args=()):
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="",
                   BENCH_PREFLIGHT="0", BENCH_WATCHDOG="0",
                   DEEPGO_FLIGHT="0",
                   BENCH_LAST_GOOD=str(last_good_path))
        return subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "bench.py"),
             "--mode", "serving", *args],
            capture_output=True, text=True, timeout=300, env=env)

    proc = run_bench(tmp_path / "none.json")
    assert proc.returncode == 0, proc.stderr[-1500:]
    clean = json.loads([l for l in proc.stdout.splitlines()
                        if l.startswith("{")][0])
    assert clean["value"] > 0

    def table(baseline_value):
        path = tmp_path / "last_good.json"
        path.write_text(json.dumps({clean["metric"]: {
            "metric": clean["metric"], "value": baseline_value,
            "unit": "boards/sec", "device": clean["device"],
            "timestamp": "2026-01-01T00:00:00Z", "git_sha": "abc"}}))
        return path

    # injected regression: the baseline claims 10x this machine's real
    # throughput, so the fresh run reads >= 10% slower -> exit 1
    proc = run_bench(table(clean["value"] * 10.0), args=["--gate"])
    assert proc.returncode == 1, proc.stderr[-1500:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1  # the verdict rides INSIDE the one line
    record = json.loads(lines[0])
    assert record["gate"]["verdict"] == "fail"

    # clean: the baseline is comfortably beatable -> exit 0
    proc = run_bench(table(clean["value"] * 0.5), args=["--gate"])
    assert proc.returncode == 0, proc.stderr[-1500:]
    record = json.loads([l for l in proc.stdout.splitlines()
                         if l.startswith("{")][0])
    assert record["gate"]["verdict"] == "pass"
