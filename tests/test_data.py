"""Dataset format, transcription, and sampling tests."""

import os

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.data import GoDataset
from deepgo_tpu.data.dataset import M_GAME, DatasetWriter
from deepgo_tpu.data.transcribe import transcribe_game, transcribe_split


@pytest.fixture(scope="module")
def fixture_dataset(tmp_path_factory):
    """Transcribe the two small fixture splits into a temp root."""
    root = tmp_path_factory.mktemp("processed")
    for split in ("validation", "test"):
        n = transcribe_split(
            os.path.join(REPO_ROOT, "data/sgf", split),
            str(root / split),
            workers=1,
            verbose=False,
        )
        assert n > 0
    return str(root)


def test_transcribe_counts_match_reference(fixture_dataset):
    # 134 validation / 125 test examples in the reference's bundled data
    assert len(GoDataset(fixture_dataset, "validation")) == 134
    assert len(GoDataset(fixture_dataset, "test")) == 125


def test_transcribe_idempotent(fixture_dataset):
    n = transcribe_split(
        os.path.join(REPO_ROOT, "data/sgf/test"),
        os.path.join(fixture_dataset, "test"),
        verbose=False,
    )
    assert n == 125  # second call reuses the existing shard


def test_batch_contents(fixture_dataset):
    ds = GoDataset(fixture_dataset, "test")
    packed, player, rank, target = ds.first_n(8)
    assert packed.shape == (8, 9, 19, 19) and packed.dtype == np.uint8
    assert set(np.unique(player)) <= {1, 2}
    assert ((rank >= 1) & (rank <= 9)).all()
    assert ((target >= 0) & (target < 361)).all()
    # first move of the game: empty board, black to move
    assert packed[0, 0].sum() == 0 and player[0] == 1


def test_superbatch_single_gather_shapes(fixture_dataset):
    # one K*B gather reshaped to (K, B, ...), nibble + augment included —
    # the assembly that replaced the uploader's per-batch np.stack
    from deepgo_tpu.data.loader import make_host_superbatch

    ds = GoDataset(fixture_dataset, "test")
    b = make_host_superbatch(ds, np.random.default_rng(0), batch_size=4,
                             stack=3, scheme="uniform", augment=True,
                             wire="nibble")
    assert b["packed"].shape == (3, 4, 1625)  # nibble wire bytes
    assert b["packed"].dtype == np.uint8
    assert b["player"].shape == b["rank"].shape == b["target"].shape == (3, 4)
    assert b["sym"].shape == (3, 4) and b["sym"].dtype == np.int32
    assert ((b["target"] >= 0) & (b["target"] < 361)).all()


def test_loader_off_depth_get_with_stacked_workers(fixture_dataset):
    # workers build full-depth superbatches; an off-depth get (the final
    # partial window) must sample synchronously and still deliver the
    # requested (K', B, ...) shape
    from deepgo_tpu.data.loader import AsyncLoader

    ds = GoDataset(fixture_dataset, "test")
    with AsyncLoader(ds, 4, scheme="uniform", seed=5, num_threads=2,
                     prefetch=2, stack=3) as loader:
        full = loader.get()
        assert np.asarray(full["packed"]).shape == (3, 4, 9, 19, 19)
        part = loader.get(stack=2)
        assert np.asarray(part["packed"]).shape == (2, 4, 9, 19, 19)


def test_loader_close_unblocks_uploader_parked_in_put(fixture_dataset, capfd):
    # the consumer stops pulling with the device queue full, so the
    # uploader is parked inside _dev_queue.put(): close() must drain the
    # queue to let it exit, and return with NO leak warning
    import time

    from deepgo_tpu.data.loader import AsyncLoader

    ds = GoDataset(fixture_dataset, "test")
    loader = AsyncLoader(ds, 2, scheme="uniform", seed=3, num_threads=1,
                         prefetch=2, device_prefetch=1)
    loader.get()  # uploader is live; let it refill the device queue
    deadline = time.monotonic() + 5
    while loader._dev_queue.qsize() < 1 and time.monotonic() < deadline:
        time.sleep(0.01)
    loader.close()
    assert not any(t.is_alive() for t in loader._threads)
    assert "still alive" not in capfd.readouterr().err


def test_loader_close_logs_leaked_thread_loudly(fixture_dataset, capfd,
                                                monkeypatch):
    # an uploader blocked inside jax.device_put (a wedged device/relay)
    # cannot be joined: close() must still return promptly and report the
    # leak on stderr instead of pretending the shutdown was clean
    import threading
    import time

    import deepgo_tpu.data.loader as loader_mod
    from deepgo_tpu.data.loader import AsyncLoader

    release = threading.Event()
    entered = threading.Event()
    armed = threading.Event()
    real_put = loader_mod.jax.device_put

    def wedged_put(batch, *a, **kw):
        if armed.is_set():
            entered.set()
            release.wait(30)  # stand-in for the C call that never returns
        return real_put(batch, *a, **kw)

    monkeypatch.setattr(loader_mod.jax, "device_put", wedged_put)
    ds = GoDataset(fixture_dataset, "test")
    loader = AsyncLoader(ds, 2, scheme="uniform", seed=3, num_threads=1,
                         prefetch=1, device_prefetch=1)
    try:
        loader.get()  # pipeline is live
        armed.set()
        assert entered.wait(10)  # uploader is now wedged in device_put
        t0 = time.monotonic()
        loader.close(timeout=0.5)
        assert time.monotonic() - t0 < 5, "close() hung on the wedge"
        assert loader._uploader.is_alive()
        err = capfd.readouterr().err
        assert "still alive" in err and "loader-uploader" in err
    finally:
        release.set()


def test_game_sampling_in_range(fixture_dataset):
    ds = GoDataset(fixture_dataset, "validation")
    rng = np.random.default_rng(7)
    idx = ds.sample_indices(rng, 1000, scheme="game")
    assert ((idx >= 0) & (idx < len(ds))).all()
    idx = ds.sample_indices(rng, 1000, scheme="uniform")
    assert ((idx >= 0) & (idx < len(ds))).all()


def test_game_scheme_uniform_over_games():
    """The 'game' scheme must weight games equally regardless of length
    (reference Dataset:generate_random_filename, data.lua:29-37)."""
    writer_dir = None
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        writer = DatasetWriter(d)
        # game A: 10 positions, game B: 90 positions
        for name, m in (("a", 10), ("b", 90)):
            packed = np.zeros((m, 9, 19, 19), np.uint8)
            meta = np.zeros((m, 6), np.int32)
            meta[:, 0] = 1
            meta[:, 3:5] = 5
            writer.add_game(name, packed, meta)
        writer.finalize()
        ds = GoDataset(os.path.dirname(d), os.path.basename(d))
        rng = np.random.default_rng(0)
        idx = ds.sample_indices(rng, 4000, scheme="game")
        frac_a = (idx < 10).mean()
        assert 0.45 < frac_a < 0.55  # ~half from the short game
        assert ds.meta[idx][:, M_GAME].max() == 1


def test_even_indices_balanced_deterministic(tmp_path):
    """The fixed validation sampler must cover min(num_games, n) games,
    spread within each game, never repeat a position, and be a pure
    function of the split (round-1 verdict item 8)."""
    d = str(tmp_path / "split")
    writer = DatasetWriter(d)
    counts = {"a": 3, "b": 50, "c": 120, "d": 7}
    for name, m in counts.items():
        packed = np.zeros((m, 9, 19, 19), np.uint8)
        meta = np.zeros((m, 6), np.int32)
        meta[:, 0] = 1
        meta[:, 3:5] = 5
        writer.add_game(name, packed, meta)
    writer.finalize()
    ds = GoDataset(os.path.dirname(d), os.path.basename(d))

    idx = ds.even_indices(40)
    assert len(idx) == 40
    assert len(np.unique(idx)) == 40
    games = ds.meta[idx][:, M_GAME]
    per_game = np.bincount(games, minlength=4)
    # all 4 games covered; the short game contributes everything it has,
    # the rest share the remainder near-equally
    assert (per_game > 0).all()
    assert per_game[0] == 3
    assert abs(per_game[1] - per_game[2]) <= 1
    # deterministic
    assert np.array_equal(idx, ds.even_indices(40))
    # n >= len degenerates to every position exactly once, in order
    assert np.array_equal(ds.even_indices(10_000), np.arange(len(ds)))
    # tiny n still spreads across games (one position from n games)
    tiny = ds.meta[ds.even_indices(3)][:, M_GAME]
    assert len(np.unique(tiny)) == 3


def test_transcribe_game_skips_unranked(tmp_path):
    p = tmp_path / "g.sgf"
    p.write_text("(;BR[5k]WR[1d];B[pd];W[dd])")
    assert transcribe_game(str(p)) is None
    p.write_text("(;BR[3d]WR[1d];B[pd];W[dd])")
    packed, meta = transcribe_game(str(p))
    assert packed.shape == (2, 9, 19, 19)
    assert meta[0].tolist() == [1, 15, 3, 3, 1, 0]


def test_winner_scheme_samples_only_winner_moves(tmp_path):
    """Outcome-conditioned sampling: scheme='winner' draws only positions
    whose side to move won (per the SGF RE tag); undecided games excluded."""
    import sys

    sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
    from winner_index import build

    sgf_dir = tmp_path / "sgf"
    os.makedirs(sgf_dir)
    # black wins game 0, white wins game 1, game 2 has no result
    records = {
        "a.sgf": "(;GM[1]SZ[19]BR[8d]WR[8d]RE[B+10.5];B[aa];W[bb];B[cc])",
        "b.sgf": "(;GM[1]SZ[19]BR[8d]WR[8d]RE[W+3];B[dd];W[ee];B[ff];W[gg])",
        "c.sgf": "(;GM[1]SZ[19]BR[8d]WR[8d];B[hh];W[ii])",
    }
    for name, text in records.items():
        (sgf_dir / name).write_text(text)
    out = tmp_path / "processed"
    n = transcribe_split(str(sgf_dir), str(out), workers=1, verbose=False)
    assert n == 9

    stats = build(str(out), str(sgf_dir))
    assert stats == {"games": 3, "decided": 2, "undecided": 1, "missing": 0,
                     "winner_positions": 2 + 2}  # B moves of a + W moves of b

    ds = GoDataset(str(tmp_path), "processed")
    idx = ds.sample_indices(np.random.default_rng(0), 64, scheme="winner")
    # every sampled position: mover == game winner, and game is decided
    assert (ds.winner[idx] == ds.meta[idx, 0]).all()
    assert set(np.unique(ds.meta[idx, M_GAME])) <= {0, 1}
    # the loader plumbs the scheme through untouched
    from deepgo_tpu.data.loader import AsyncLoader

    with AsyncLoader(ds, 8, scheme="winner", seed=1, num_threads=0,
                     prefetch=2) as loader:
        batch = loader.get(stack=0)
    assert batch["packed"].shape[0] == 8


def test_make_selfplay_corpus_end_to_end(tmp_path):
    """Agent-spec corpus generator: games -> split SGFs -> shards, ranks
    tagged per agent, decided games carry RE[] for the winner sidecar."""
    import make_selfplay_corpus
    from winner_index import build

    out = tmp_path / "corpus"
    make_selfplay_corpus.main([
        "--out", str(out), "--pairs", "oneply,heuristic", "--games", "8",
        "--chunk", "4", "--max-moves", "450", "--seed", "5",
    ])
    ds = GoDataset(str(out / "processed"), "train")
    assert len(ds) > 0 and ds.num_games >= 1
    # rank tags: oneply=8d / heuristic=4d, colors alternating inside a chunk
    pairs = {(b, w) for b, w in ds.meta[:, [3, 4]].tolist()}
    assert pairs <= {(8, 4), (4, 8)} and pairs
    stats = build(str(out / "processed" / "train"), str(out / "sgf" / "train"))
    assert stats["missing"] == 0
    assert stats["games"] == ds.num_games
    # games that finish on double pass must carry RE[] -> decided
    assert stats["decided"] > 0
