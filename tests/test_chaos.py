"""Chaos campaigns and the gray-failure defenses (deepgo_tpu/chaos/,
serving/fleet.py hedging / ejection / integrity, utils/faults slow+corrupt).

The load-bearing contracts:

  * the ``slow`` / ``corrupt`` fault kinds are replica-scoped and
    deterministic: a brownout window sleeps inside the faults harness
    (never a bare ``time.sleep`` in serving code), a corruption budget
    counts down per dispatched batch;
  * a ``Scenario`` round-trips through JSON (a campaign is reproducible
    from its report alone) and the ``ScenarioScheduler`` opens fault
    windows on the timeline and ALWAYS sweeps them shut on ``stop()``;
  * request hedging duplicates a latency-critical request onto a second
    replica after the p99-derived delay — first result wins, the rate
    cap bounds duplicate load, non-hedged tiers never hedge;
  * a browned-out replica is ejected by the latency-outlier scan and a
    corrupt replica by the canary prober — both recycle through the
    standard respawn path and the fleet keeps answering correctly;
  * the per-response integrity check turns silent corruption into a
    failover: callers get right answers, the counter records the saves;
  * a full ``CampaignRunner`` run under a brownout (and under
    corruption with canaries armed) grades PASS: zero lost futures,
    zero wrong answers, detection when corruption was injected.
"""

import json
import random
import threading
import time

import numpy as np
import pytest

from deepgo_tpu.chaos import (CampaignConfig, CampaignRunner, CanaryProber,
                              FaultEvent, Scenario, ScenarioScheduler,
                              acceptance_scenario, brownout_scenario,
                              defended_config, grade_report,
                              log_prob_integrity, make_sentinels)
from deepgo_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                InferenceEngine, SupervisedEngine,
                                SupervisorConfig)
from deepgo_tpu.utils import faults

ECFG = EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
DIE_FAST = SupervisorConfig(max_restarts=0, backoff_base_s=0.001,
                            backoff_cap_s=0.005)
FAST_FLEET = FleetConfig(respawn_base_s=0.001, respawn_cap_s=0.005)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


def lp_forward(params, packed, player, rank):
    """Log-prob-shaped scripted forward: strictly negative, distinct per
    board — passes ``log_prob_integrity`` until the corrupt hook flips
    it positive."""
    return -(np.asarray(packed, np.float32).sum(axis=(1, 2, 3))
             + 1000.0 * np.asarray(player, np.float32) + 1.0)


def make_fleet(name, forward=lp_forward, replicas=2,
               fleet_config=FAST_FLEET, sup_config=DIE_FAST,
               engine_config=ECFG, **kw):
    """Replicas named ``{name}-{i}`` — the ScenarioScheduler's default
    index->engine-name map, so scenario events land on these engines."""
    def make_replica(i):
        return SupervisedEngine(
            lambda: InferenceEngine(forward, None, engine_config,
                                    name=f"{name}-{i}"),
            config=sup_config, name=f"{name}-{i}")

    kw.setdefault("rng", random.Random(0))
    return FleetRouter(make_replica, replicas, config=fleet_config,
                       name=name, **kw)


def make_trace(n=30, rate=60.0, tier="interactive", seed=0):
    rng = np.random.default_rng(seed)
    items, t = [], 0.0
    for _ in range(n):
        t += 1.0 / rate
        items.append({
            "t": t,
            "packed": rng.integers(0, 3, size=(9, 19, 19), dtype=np.uint8),
            "player": int(rng.integers(1, 3)),
            "rank": int(rng.integers(1, 10)),
            "tier": tier,
        })
    return items


def wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def no_sleep(_):
    pass


# ---------------------------------------------------------------------------
# the fault grammar: slow + corrupt kinds


class TestFaultKinds:
    def test_slow_sleeps_inside_the_harness(self):
        faults.add("serving_slow.x:slow@50")
        slept = []
        dt = faults.maybe_slow("serving_slow", "x", sleep=slept.append)
        assert dt == pytest.approx(0.05)
        assert slept == [pytest.approx(0.05)]
        # a different replica's window does not leak across names
        assert faults.maybe_slow("serving_slow", "y",
                                 sleep=no_sleep) == 0.0

    def test_slow_site_and_replica_scopes_sum(self):
        faults.add("serving_slow:slow@20")
        faults.add("serving_slow.x:slow@30")
        dt = faults.maybe_slow("serving_slow", "x", sleep=no_sleep)
        assert dt == pytest.approx(0.05)

    def test_slow_window_closes_on_remove(self):
        faults.add("serving_slow.x:slow@50")
        assert faults.maybe_slow("serving_slow", "x",
                                 sleep=no_sleep) > 0.0
        faults.remove("serving_slow.x", "slow")
        assert faults.maybe_slow("serving_slow", "x",
                                 sleep=no_sleep) == 0.0

    def test_corrupt_budget_counts_down(self):
        faults.add("serving_corrupt.x:corrupt@2")
        assert faults.corrupt_due("serving_corrupt", "x")
        assert faults.corrupt_due("serving_corrupt", "x")
        assert not faults.corrupt_due("serving_corrupt", "x")
        assert not faults.corrupt_due("serving_corrupt", "y")


# ---------------------------------------------------------------------------
# scenarios: validation, JSON round-trip, the scheduler thread


class TestScenario:
    def test_event_validation(self):
        with pytest.raises(ValueError):
            FaultEvent(at_s=0.0, kind="meteor")
        with pytest.raises(ValueError):
            FaultEvent(at_s=-1.0, kind="kill")
        with pytest.raises(ValueError):  # unbounded brownout
            FaultEvent(at_s=0.0, kind="slow", duration_s=0.0)
        with pytest.raises(ValueError):
            FaultEvent(at_s=0.0, kind="corrupt", arg=0)

    def test_json_round_trip(self):
        sc = Scenario(name="rt", seed=7, events=(
            FaultEvent(at_s=0.1, kind="slow", replica=0,
                       duration_s=0.5, arg=120),
            FaultEvent(at_s=0.2, kind="corrupt", replica=1, arg=9),
            FaultEvent(at_s=0.3, kind="kill", replica=0),
            FaultEvent(at_s=0.4, kind="saturate", arg=32),
        ))
        back = Scenario.from_dict(json.loads(json.dumps(sc.to_dict())))
        assert back == sc
        assert back.span_s() == pytest.approx(0.6)

    def test_presets_scale_to_span(self):
        b = brownout_scenario(span_s=10.0, brownout_ms=150)
        assert len(b.events) == 1 and b.events[0].kind == "slow"
        assert b.events[0].duration_s == pytest.approx(8.8)
        a = acceptance_scenario(span_s=10.0)
        assert {e.kind for e in a.events} == {"slow", "corrupt", "kill"}

    def test_scheduler_opens_windows_and_sweeps_on_stop(self):
        sc = Scenario(name="sweep", events=(
            FaultEvent(at_s=0.0, kind="slow", replica=0,
                       duration_s=30.0, arg=40),
            FaultEvent(at_s=0.0, kind="corrupt", replica=1, arg=100),
            FaultEvent(at_s=0.0, kind="kill", replica=0),
        ))
        sched = ScenarioScheduler(sc, fleet_name="swp")
        sched.start()
        assert wait_until(lambda: len(sched.executed) >= 3)
        # the brownout window is open, replica-scoped
        assert faults.maybe_slow("serving_slow", "swp-0",
                                 sleep=no_sleep) == pytest.approx(0.04)
        assert faults.corrupt_due("serving_corrupt", "swp-1")
        with pytest.raises(faults.FaultError):
            faults.check("serving_dispatch.swp-0")
        sched.stop()
        # stop() swept the open windows shut — chaos never outlives
        # its campaign
        assert faults.maybe_slow("serving_slow", "swp-0",
                                 sleep=no_sleep) == 0.0
        assert not faults.corrupt_due("serving_corrupt", "swp-1")
        phases = [(e["kind"], e["phase"]) for e in sched.executed]
        assert ("slow", "open") in phases and ("kill", "open") in phases

    def test_scheduler_saturate_calls_burst_hook(self):
        bursts = []
        sc = Scenario(name="sat", events=(
            FaultEvent(at_s=0.0, kind="saturate", arg=7),))
        sched = ScenarioScheduler(sc, fleet_name="sat",
                                  submit_burst=bursts.append)
        sched.start()
        assert wait_until(lambda: bursts == [7])
        sched.stop()


# ---------------------------------------------------------------------------
# request hedging


class TestHedging:
    def test_hedge_fires_and_first_result_wins(self):
        cfg = FleetConfig(
            respawn_base_s=0.001, respawn_cap_s=0.005,
            hedge_tiers=("interactive",), hedge_min_delay_s=0.01,
            hedge_max_frac=1.0)
        fleet = make_fleet("hedge1", fleet_config=cfg)
        try:
            faults.add("serving_slow.hedge1-0:slow@400")
            trace = make_trace(8, rate=200.0, seed=1)
            t0 = time.monotonic()
            futs = [fleet.submit(it["packed"], it["player"], it["rank"],
                                 tier="interactive") for it in trace]
            got = [np.atleast_1d(f.result(timeout=20))[0] for f in futs]
            wall = time.monotonic() - t0
            for it, g in zip(trace, got):
                want = lp_forward(None, it["packed"][None],
                                  np.array([it["player"]]), None)[0]
                assert g == pytest.approx(want)
            h = fleet.health()
            assert h["hedges"] >= 1, h
            assert h["hedge_wins"] >= 1, h
            # hedge wins mean nobody waited out the full 400ms brownout
            # serially on every slow-placed request
            assert wall < 8 * 0.4
        finally:
            fleet.close()
            faults.reset()

    def test_hedge_rate_cap_zero_disables(self):
        cfg = FleetConfig(
            respawn_base_s=0.001, respawn_cap_s=0.005,
            hedge_tiers=("interactive",), hedge_min_delay_s=0.001,
            hedge_max_frac=0.0)
        fleet = make_fleet("hedge0", fleet_config=cfg)
        try:
            faults.add("serving_slow.hedge0-0:slow@50")
            for it in make_trace(4, rate=200.0, seed=2):
                fleet.submit(it["packed"], it["player"], it["rank"],
                             tier="interactive").result(timeout=20)
            assert fleet.health()["hedges"] == 0
        finally:
            fleet.close()
            faults.reset()

    def test_unhedged_tier_never_hedges(self):
        cfg = FleetConfig(
            respawn_base_s=0.001, respawn_cap_s=0.005,
            hedge_tiers=("interactive",), hedge_min_delay_s=0.001,
            hedge_max_frac=1.0)
        fleet = make_fleet("hedgeb", fleet_config=cfg)
        try:
            faults.add("serving_slow.hedgeb-0:slow@50")
            for it in make_trace(4, rate=200.0, seed=3):
                fleet.submit(it["packed"], it["player"], it["rank"],
                             tier="batch").result(timeout=20)
            assert fleet.health()["hedges"] == 0
        finally:
            fleet.close()
            faults.reset()


# ---------------------------------------------------------------------------
# latency-outlier ejection + canary integrity probes


class TestEjectionAndCanary:
    def test_straggler_ejected_and_recycled(self):
        cfg = FleetConfig(
            respawn_base_s=0.001, respawn_cap_s=0.005,
            eject_stragglers=True, eject_min_samples=4,
            eject_consecutive=1, eject_factor=3.0)
        fleet = make_fleet("eject", fleet_config=cfg)
        try:
            faults.add("serving_slow.eject-0:slow@120")
            trace = make_trace(200, rate=200.0, seed=4)

            def pump_until_ejected():
                for it in trace:
                    fleet.submit(it["packed"], it["player"],
                                 it["rank"]).result(timeout=20)
                    if fleet.health()["ejections"] >= 1:
                        return True
                return fleet.health()["ejections"] >= 1

            assert pump_until_ejected(), fleet.health()
            faults.reset()  # close the brownout so the respawn is clean
            assert wait_until(
                lambda: fleet.health()["replicas_serving"] == 2)
        finally:
            fleet.close()
            faults.reset()

    def test_eject_replica_is_a_respawn_not_an_outage(self):
        fleet = make_fleet("recyc")
        try:
            assert fleet.eject_replica(0, reason="operator")
            assert not fleet.eject_replica(0, reason="operator"), \
                "a replica already respawning cannot be ejected twice"
            assert wait_until(
                lambda: fleet.health()["replicas_serving"] == 2)
            assert fleet.health()["ejections"] == 1
            assert fleet.health()["respawns"] >= 1
        finally:
            fleet.close()

    def test_make_sentinels_dedups_and_limits(self):
        packed = np.zeros((9, 19, 19), np.uint8)
        items = [{"packed": packed, "player": 1, "rank": 5,
                  "digest": d} for d in ("a", "a", "b", "c", "d")]
        expected = {"a": np.float32(1), "b": np.float32(2),
                    "c": np.float32(3)}  # "d" has no known-good answer
        sents = make_sentinels(items, expected, limit=2)
        assert [s["digest"] for s in sents] == ["a", "b"]

    def test_canary_detects_corrupt_replica_and_recycles(self):
        fleet = make_fleet("canary")
        try:
            it = make_trace(1, seed=5)[0]
            want = fleet.submit(it["packed"], it["player"],
                                it["rank"]).result(timeout=20)
            sentinels = [{"packed": it["packed"], "player": it["player"],
                          "rank": it["rank"], "digest": "s0",
                          "expected": np.asarray(want)}]
            faults.add("serving_corrupt.canary-1:corrupt@1000")
            prober = CanaryProber(fleet, sentinels, timeout_s=5.0)
            assert prober.probe_once() == 1
            rep = prober.report()
            assert rep["failures"] == 1
            assert [d["replica"] for d in rep["detected"]] == [1]
            assert fleet.health()["ejections"] == 1
            faults.reset()  # the respawned replica comes back clean...
            assert wait_until(
                lambda: fleet.health()["replicas_serving"] == 2)
            assert prober.probe_once() == 0  # ...and probes clean
        finally:
            fleet.close()
            faults.reset()

    def test_probe_errors_are_not_integrity_failures(self):
        fleet = make_fleet("proberr", replicas=1)
        try:
            it = make_trace(1, seed=6)[0]
            want = fleet.submit(it["packed"], it["player"],
                                it["rank"]).result(timeout=20)
            sentinels = [{"packed": it["packed"], "player": it["player"],
                          "rank": it["rank"], "digest": "s0",
                          "expected": np.asarray(want)}]
            prober = CanaryProber(fleet, sentinels, timeout_s=0.0)
            assert prober.probe_once() == 0  # timeout != wrong answer
            assert prober.failures == 0 and prober.probes == 1
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# the integrity check: silent corruption becomes a failover


class TestIntegrity:
    def test_corrupt_response_fails_over_to_a_right_answer(self):
        cfg = FleetConfig(
            respawn_base_s=0.001, respawn_cap_s=0.005,
            integrity_check=log_prob_integrity)
        fleet = make_fleet("integ", fleet_config=cfg)
        try:
            faults.add("serving_corrupt.integ-0:corrupt@100")
            saved = 0
            for it in make_trace(12, rate=200.0, seed=7):
                got = np.atleast_1d(fleet.submit(
                    it["packed"], it["player"],
                    it["rank"]).result(timeout=20))[0]
                want = lp_forward(None, it["packed"][None],
                                  np.array([it["player"]]), None)[0]
                assert got == pytest.approx(want), \
                    "a corrupted answer reached the caller"
                saved = fleet.health()["integrity_failures"]
                if saved >= 2:
                    break
            assert saved >= 1, fleet.health()
        finally:
            fleet.close()
            faults.reset()

    def test_log_prob_integrity_predicate(self):
        assert log_prob_integrity(np.array([-3.2, -0.1, 0.0]))
        assert not log_prob_integrity(np.array([-3.2, 1.1]))
        assert not log_prob_integrity(1.0 - np.array([-3.2, -0.1]))


# ---------------------------------------------------------------------------
# the campaign runner: replay + grade


class TestCampaign:
    def test_grade_report_rules(self):
        base = {"answers": {"lost": 0, "wrong": 0},
                "slo": {"ok": True}, "expects_corruption": False}
        assert grade_report(base)["pass"]
        assert not grade_report(
            {**base, "answers": {"lost": 1, "wrong": 0}})["pass"]
        assert not grade_report(
            {**base, "answers": {"lost": 0, "wrong": 2}})["pass"]
        assert not grade_report({**base, "slo": {"ok": False}})["pass"]
        g = grade_report({**base, "expects_corruption": True,
                          "canary": {"detected": []}})
        assert not g["pass"] and "canary" in " ".join(g["reasons"])
        assert grade_report({**base, "expects_corruption": True,
                             "canary": {"detected": [{"replica": 1}]}
                             })["pass"]

    def test_brownout_campaign_defended_grades_pass(self):
        fleet = make_fleet("camp-b",
                           fleet_config=defended_config(FAST_FLEET))
        try:
            trace = make_trace(40, rate=50.0, seed=8)
            span = trace[-1]["t"]
            runner = CampaignRunner(
                fleet, trace, brownout_scenario(span, brownout_ms=100),
                CampaignConfig(slo_threshold_s=2.0, slo_target=0.5,
                               canary=False))
            report = runner.run()
            assert report["grade"]["pass"], report["grade"]
            assert report["answers"]["lost"] == 0
            assert report["answers"]["wrong"] == 0
            assert report["answers"]["checked"] > 0
            assert report["slo"]["requests"] >= len(trace)
            assert report["defenses"]["hedge_tiers"] == ["interactive"]
            # the scheduler's executed log made it into the report
            assert any(e["kind"] == "slow" for e in report["executed"])
        finally:
            fleet.close()
            faults.reset()

    def test_corruption_campaign_canary_detected(self, tmp_path):
        fleet = make_fleet("camp-c",
                           fleet_config=defended_config(FAST_FLEET))
        try:
            trace = make_trace(50, rate=40.0, seed=9)
            span = trace[-1]["t"]
            scenario = Scenario(name="corrupt-only", events=(
                FaultEvent(at_s=0.1 * span, kind="corrupt", replica=1,
                           duration_s=0.8 * span, arg=1000),))
            out = str(tmp_path / "report.json")
            report = CampaignRunner(
                fleet, trace, scenario,
                CampaignConfig(slo_threshold_s=2.0, slo_target=0.5,
                               canary_interval_s=0.05)).run(
                                   report_path=out)
            assert report["expects_corruption"]
            assert report["answers"]["wrong"] == 0, \
                "corruption reached a caller"
            assert report["answers"]["lost"] == 0
            assert report["canary"]["detected"], report["canary"]
            assert report["counters"]["ejections"] >= 1
            assert report["grade"]["pass"], report["grade"]
            # the report file round-trips and re-grades identically —
            # the `cli chaos report` contract
            with open(out, encoding="utf-8") as fh:
                loaded = json.load(fh)
            assert grade_report(loaded) == loaded["grade"]
        finally:
            fleet.close()
            faults.reset()
