"""Nibble wire format: losslessness, step equivalence, loader integration."""

import numpy as np
import pytest

import jax

from deepgo_tpu.features import expand_planes_np
from deepgo_tpu.ops.wire import nibble_pack_np, nibble_unpack


def _random_packed(rng, shape_prefix=()):
    # realistic value ranges, including values past the clamp (liberties of
    # a huge chain can exceed 15; the expansion only sees >= thresholds)
    return rng.integers(0, 40, size=(*shape_prefix, 9, 19, 19)).astype(np.uint8)


def test_roundtrip_preserves_clamped_values():
    rng = np.random.default_rng(0)
    packed = _random_packed(rng, (4,))
    wire = nibble_pack_np(packed)
    assert wire.shape == (4, 1625) and wire.dtype == np.uint8
    out = np.asarray(nibble_unpack(wire))
    np.testing.assert_array_equal(out, np.minimum(packed, 15))


def test_clamp_is_lossless_for_expanded_planes():
    # the whole argument for the format: every comparison in the expansion
    # has threshold <= 15, so clamping cannot change any plane
    rng = np.random.default_rng(1)
    packed = _random_packed(rng)
    for player, rank in ((1, 3), (2, 9)):
        a = expand_planes_np(packed, player, rank)
        b = expand_planes_np(np.asarray(nibble_unpack(nibble_pack_np(packed))),
                             player, rank)
        np.testing.assert_array_equal(a, b)


def test_train_step_nibble_matches_packed():
    from deepgo_tpu.models import policy_cnn
    from deepgo_tpu.training import make_train_step
    from deepgo_tpu.training.optimizers import OPTIMIZERS

    cfg = policy_cnn.ModelConfig(num_layers=2, channels=8,
                                 compute_dtype="float32")
    optimizer = OPTIMIZERS["sgd"](0.05, 0.0, 0.0)
    params = policy_cnn.init(jax.random.key(0), cfg)
    opt_state = optimizer.init(params)
    rng = np.random.default_rng(2)
    packed = np.minimum(_random_packed(rng, (8,)), 15)  # pre-clamped input
    batch = {
        "packed": packed,
        "player": rng.integers(1, 3, size=8).astype(np.int32),
        "rank": rng.integers(1, 10, size=8).astype(np.int32),
        "target": rng.integers(0, 361, size=8).astype(np.int32),
    }
    nib_batch = dict(batch, packed=nibble_pack_np(packed))

    step_p = make_train_step(cfg, optimizer, wire="packed")
    step_n = make_train_step(cfg, optimizer, wire="nibble")
    p1, _, l1 = step_p(jax.tree.map(np.copy, params),
                       jax.tree.map(np.copy, opt_state), batch)
    p2, _, l2 = step_n(jax.tree.map(np.copy, params),
                       jax.tree.map(np.copy, opt_state), nib_batch)
    assert float(l1) == pytest.approx(float(l2), rel=1e-6)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


def test_experiment_wire_auto_resolves_by_backend(tmp_path):
    # "auto" = packed on the CPU backend (no transfer to save), nibble on
    # accelerators; an explicit setting is honored anywhere
    from deepgo_tpu.experiments import Experiment, ExperimentConfig

    cfg = ExperimentConfig(num_layers=2, channels=8, batch_size=8,
                           data_parallel=1, run_dir=str(tmp_path))
    exp = Experiment(cfg)
    exp.init()
    assert exp.wire == "packed"  # tests run on the CPU backend
    exp2 = Experiment(cfg.replace(wire_format="nibble"))
    exp2.init()
    assert exp2.wire == "nibble"


def test_loader_device_prefetch_and_wire(tmp_path):
    import os

    from conftest import REPO_ROOT
    from deepgo_tpu.data import GoDataset
    from deepgo_tpu.data.loader import AsyncLoader
    from deepgo_tpu.data.transcribe import transcribe_split

    root = tmp_path / "processed"
    transcribe_split(os.path.join(REPO_ROOT, "data/sgf", "validation"),
                     str(root / "validation"), workers=1, verbose=False)
    ds = GoDataset(str(root), "validation")
    with AsyncLoader(ds, 8, seed=3, num_threads=2, prefetch=2, stack=2,
                     wire="nibble", device_prefetch=2) as loader:
        batches = [loader.get() for _ in range(4)]
        tail = loader.get(stack=0)  # off-depth request bypasses the queue
    for b in batches:
        assert b["packed"].shape == (2, 8, 1625)
    assert tail["packed"].shape == (8, 1625)
    # close() must terminate the uploader thread even when it was blocked
    # draining the host queue (it held no batch when the workers exited)
    import time

    deadline = time.time() + 5
    while any(t.is_alive() for t in loader._threads):
        assert time.time() < deadline, "loader threads survived close()"
        time.sleep(0.05)
