"""Resilience layer over the serving path (serving/supervisor.py).

The load-bearing contracts, each asserted deterministically (injectable
clock / sleep / rng — no wall-time races):

  * dispatcher death is absorbed: the engine is rebuilt with bounded
    exponential full-jitter backoff and in-flight requests REPLAY with
    bit-identical results (the forward is pure);
  * batch poison is isolated: one bad row fails alone with a typed
    PoisonedRequest + atomic quarantine dump, while its coalesced
    neighbors succeed; transient faults never condemn an innocent;
  * the circuit breaker walks closed -> open -> half-open -> closed with
    single-probe recovery, shedding typed CircuitOpen while open;
  * admission control sheds typed EngineOverloaded when the estimated
    queue wait already exceeds the deadline;
  * under DEEPGO_FAULTS chaos (dispatcher kill + transient forwards) a
    mixed selfplay/evaluate workload completes with every future
    resolved and results bit-identical to a fault-free run.
"""

import os
import random
import threading
import time

import numpy as np
import pytest

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.models.serving import make_log_prob_fn
from deepgo_tpu.serving import (BatchDispatchError, CircuitBreaker,
                                CircuitOpen, EngineClosed, EngineConfig,
                                EngineOverloaded, InferenceEngine,
                                PoisonedRequest, RestartsExhausted,
                                SupervisedEngine, SupervisorConfig,
                                full_jitter_delay)
from deepgo_tpu.utils import faults
from deepgo_tpu.utils.metrics import MetricsWriter, read_jsonl


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Each test starts (and leaves) with no active plan and no env."""
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


def tiny():
    cfg = ModelConfig(num_layers=2, channels=8)
    return cfg, init(jax.random.key(0), cfg)


def boards(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


def one_board(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(9, 19, 19), dtype=np.uint8), 1, 5)


POISON_BOARD = np.full((9, 19, 19), 255, dtype=np.uint8)


def marker_forward(params, packed, player, rank):
    """Row-independent toy forward that detonates iff the poison marker
    (an all-255 board) rides the batch — the deterministic stand-in for a
    request whose content crashes the real model."""
    if (packed == 255).all(axis=(1, 2, 3)).any():
        raise ValueError("poison row in batch")
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3)) \
        + 1000.0 * np.asarray(player, np.float32)


def ok_forward(params, packed, player, rank):
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_sup(forward, engine_config=None, sup_config=None, **kw):
    ecfg = engine_config or EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
    kw.setdefault("rng", random.Random(0))
    return SupervisedEngine(
        lambda: InferenceEngine(forward, None, ecfg, name="inner"),
        config=sup_config, name="test", **kw)


# ---- circuit breaker unit ----


class TestCircuitBreaker:
    def test_opens_after_consecutive_failures_only(self):
        clk = FakeClock()
        br = CircuitBreaker(failures=3, reset_timeout_s=10, clock=clk)
        for _ in range(2):
            br.record_failure()
        br.record_success()  # resets the consecutive count
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed" and br.allow()
        br.record_failure()  # third consecutive
        assert br.state == "open" and not br.allow()

    def test_single_probe_recovery(self):
        clk = FakeClock()
        transitions = []
        br = CircuitBreaker(failures=1, reset_timeout_s=10, clock=clk,
                            on_transition=lambda a, b: transitions.append(
                                (a, b)))
        br.record_failure()
        assert not br.allow()
        clk.advance(9.9)
        assert not br.allow()  # recovery timer not yet due
        clk.advance(0.2)
        assert br.allow()          # THE probe
        assert br.state == "half_open"
        assert not br.allow()      # everyone else sheds while it's out
        br.record_success()
        assert br.state == "closed" and br.allow()
        assert transitions == [("closed", "open"), ("open", "half_open"),
                               ("half_open", "closed")]

    def test_failed_probe_reopens_and_rearms_timer(self):
        clk = FakeClock()
        br = CircuitBreaker(failures=1, reset_timeout_s=10, clock=clk)
        br.record_failure()
        clk.advance(11)
        assert br.allow()
        br.record_failure()  # probe failed
        assert br.state == "open"
        assert not br.allow()  # timer restarted: no instant second probe
        clk.advance(11)
        assert br.allow()

    def test_cancelled_probe_returns_to_next_caller(self):
        clk = FakeClock()
        br = CircuitBreaker(failures=1, reset_timeout_s=10, clock=clk)
        br.record_failure()
        clk.advance(11)
        assert br.allow()
        br.cancel_probe()  # granted but never sent (e.g. EngineBusy)
        assert br.state == "open"
        assert br.allow()  # immediately re-granted, not timed out again
        assert br.state == "half_open"

    def test_any_success_closes_from_open(self):
        # internal replays after a restart are real traffic; their success
        # must not wait out reset_timeout_s
        clk = FakeClock()
        br = CircuitBreaker(failures=1, reset_timeout_s=1e9, clock=clk)
        br.record_failure()
        assert br.state == "open"
        br.record_success()
        assert br.state == "closed" and br.allow()


class TestFullJitter:
    def test_bounds_and_determinism(self):
        rng = random.Random(7)
        ref = random.Random(7)
        for attempt in range(6):
            d = full_jitter_delay(attempt, 0.05, 2.0, rng)
            envelope = min(2.0, 0.05 * 2 ** attempt)
            assert 0.0 <= d <= envelope
            assert d == ref.uniform(0.0, envelope)  # seeded-reproducible


# ---- engine-level containment (the primitives the supervisor rides) ----


class TestEngineContainment:
    def test_forward_error_fails_batch_not_dispatcher(self):
        engine = InferenceEngine(marker_forward, None,
                                 EngineConfig(buckets=(1,), max_wait_ms=0.0))
        try:
            bad = engine.submit(POISON_BOARD, 1, 5)
            with pytest.raises(BatchDispatchError) as ei:
                bad.result(timeout=5)
            assert ei.value.batch_size == 1
            assert isinstance(ei.value.__cause__, ValueError)
            # the dispatcher survived: later submits still serve
            ok = engine.submit(*one_board())
            assert ok.result(timeout=5).shape == ()
            assert engine.stats()["dispatch_failures"] == 1
        finally:
            engine.close()

    def test_solo_lane_dispatches_strictly_alone(self):
        sizes = []

        def recording(params, packed, player, rank):
            sizes.append(len(packed))
            return np.zeros(len(packed), np.float32)

        # a huge coalescing window would normally glue these together
        engine = InferenceEngine(recording, None,
                                 EngineConfig(buckets=(1, 8),
                                              max_wait_ms=200.0))
        try:
            futs = [engine.submit(*one_board(i), solo=True)
                    for i in range(3)]
            for f in futs:
                f.result(timeout=5)
            assert sizes == [1, 1, 1]
        finally:
            engine.close()

    def test_serving_dispatch_fault_kills_dispatcher(self):
        faults.install("serving_dispatch:fail@1")
        engine = InferenceEngine(ok_forward, None,
                                 EngineConfig(buckets=(1,), max_wait_ms=0.0))
        f = engine.submit(*one_board())
        with pytest.raises(faults.InjectedFailure):
            f.result(timeout=5)
        engine.close()


# ---- restart + replay ----


class TestRestart:
    def test_dispatcher_death_restarts_and_replays_bitwise(self):
        cfg, params = tiny()
        forward = make_log_prob_fn(cfg)
        packed, players, ranks = boards(4)
        direct = np.asarray(forward(params, packed, players, ranks))

        faults.install("serving_dispatch:fail@1")
        delays = []
        sup = SupervisedEngine(
            lambda: InferenceEngine(forward, params,
                                    EngineConfig(buckets=(1, 4),
                                                 max_wait_ms=0.0)),
            name="t", sleep=delays.append, rng=random.Random(0))
        try:
            got = sup.evaluate(packed, players, ranks)
            assert np.array_equal(got, direct)
            h = sup.health()
            assert h["restarts"] == 1
            assert h["consecutive_restarts"] == 0  # reset by the successes
            assert h["replayed"] >= 1
            assert h["state"] == "serving"
            # full-jitter backoff: seeded rng, first-attempt envelope
            assert delays == [random.Random(0).uniform(0.0, 0.05)]
        finally:
            sup.close()

    def test_submits_during_outage_ride_through(self):
        # kill the dispatcher, then submit AGAINST THE CORPSE before the
        # supervisor has rebuilt: the request must park, replay, resolve
        faults.install("serving_dispatch:fail@1")
        release = threading.Event()
        sup = make_sup(ok_forward, sleep=lambda d: release.wait(5))
        f1 = sup.submit(*one_board())  # dies with the first window
        deadline = time.monotonic() + 5
        while sup._engine._error is None and time.monotonic() < deadline:
            time.sleep(0.005)
        f2 = sup.submit(*one_board(1))  # lands on the corpse
        release.set()
        assert f1.result(timeout=5) is not None
        assert f2.result(timeout=5) is not None
        assert sup.health()["restarts"] == 1
        sup.close()

    def test_restart_backoff_envelope_grows(self):
        # three consecutive deaths, no success in between: delays must
        # stay inside the doubling envelope and match the seeded rng
        faults.install("serving_dispatch:transient@3")
        delays = []
        sup = make_sup(ok_forward, sleep=delays.append,
                       rng=random.Random(3))
        f = sup.submit(*one_board())
        assert f.result(timeout=10) is not None
        ref = random.Random(3)
        assert delays == [ref.uniform(0, 0.05), ref.uniform(0, 0.1),
                          ref.uniform(0, 0.2)]
        assert sup.health()["restarts"] == 3
        sup.close()

    def test_restart_budget_exhaustion_is_typed_not_stranded(self):
        faults.install("serving_dispatch:transient@100")
        sup = make_sup(ok_forward, sleep=lambda d: None,
                       sup_config=SupervisorConfig(max_restarts=2))
        f = sup.submit(*one_board())
        with pytest.raises(RestartsExhausted):
            f.result(timeout=10)
        with pytest.raises(RestartsExhausted):
            sup.submit(*one_board())
        assert sup.health()["state"] == "failed"
        sup.close()

    def test_restart_reuses_warm_jit_cache(self):
        # the factory closes over ONE jitted forward, so the rebuilt
        # engine replays on already-compiled shapes: zero new compiles
        cfg, params = tiny()
        forward = make_log_prob_fn(cfg)
        sup = SupervisedEngine(
            lambda: InferenceEngine(forward, params,
                                    EngineConfig(buckets=(1, 4),
                                                 max_wait_ms=0.0)),
            name="t", sleep=lambda d: None, rng=random.Random(0))
        try:
            sup.warmup()
            warm = sup.compile_cache_size()
            faults.install("serving_dispatch:fail@1")
            got = sup.evaluate(*boards(4))
            assert got.shape == (4, 361)
            assert sup.health()["restarts"] == 1
            assert sup.compile_cache_size() == warm, \
                "restart triggered XLA recompilation"
        finally:
            sup.close()


# ---- batch-poison isolation ----


class TestPoisonIsolation:
    def test_one_bad_row_fails_alone_neighbors_succeed(self, tmp_path):
        writer = MetricsWriter(str(tmp_path / "m.jsonl"))
        qdir = str(tmp_path / "quarantine")
        sup = make_sup(
            marker_forward,
            engine_config=EngineConfig(buckets=(1, 8), max_wait_ms=100.0),
            sup_config=SupervisorConfig(quarantine_dir=qdir),
            metrics=writer)
        try:
            packed, players, ranks = boards(3, seed=9)
            innocents = [sup.submit(packed[i], int(players[i]),
                                    int(ranks[i])) for i in range(3)]
            bad = sup.submit(POISON_BOARD, 2, 7)
            # neighbors bit-identical to a solo fault-free forward
            want = marker_forward(None, packed, players, ranks)
            for i, f in enumerate(innocents):
                assert f.result(timeout=10) == want[i]
            with pytest.raises(PoisonedRequest) as ei:
                bad.result(timeout=10)
            assert isinstance(ei.value.__cause__, BatchDispatchError)

            h = sup.health()
            assert h["poisoned"] == 1
            assert h["restarts"] == 0, "poison must not restart the engine"
            # atomic quarantine dump carries the offending inputs
            [qpath] = h["quarantined"]
            dump = np.load(qpath)
            assert np.array_equal(dump["packed"], POISON_BOARD)
            assert int(dump["player"]) == 2 and int(dump["rank"]) == 7
            assert "poison row" in str(dump["error"])
            assert sorted(os.listdir(qdir)) == ["poison-0001.npz"]
        finally:
            sup.close()
            writer.close()
        kinds = [r["kind"] for r in read_jsonl(str(tmp_path / "m.jsonl"))]
        assert "serving_poison" in kinds

    def test_transient_batch_fault_poisons_nobody(self):
        # the first two forward dispatches fail transiently: the batch is
        # bisected, the solo retries exhaust the transient budget, and
        # every request succeeds — poison_threshold >= 2 keeps one-shot
        # weather from condemning an innocent
        faults.install("serving_forward:transient@2")
        sup = make_sup(ok_forward,
                       engine_config=EngineConfig(buckets=(1, 4),
                                                  max_wait_ms=50.0))
        try:
            futs = [sup.submit(*one_board(i)) for i in range(4)]
            for f in futs:
                assert f.result(timeout=10) is not None
            h = sup.health()
            assert h["poisoned"] == 0
            assert h["restarts"] == 0
        finally:
            sup.close()

    def test_quarantine_optional(self):
        # no quarantine_dir: the poison verdict still lands, typed
        sup = make_sup(marker_forward)
        try:
            with pytest.raises(PoisonedRequest):
                sup.submit(POISON_BOARD, 1, 5).result(timeout=10)
            assert sup.health()["quarantined"] == []
        finally:
            sup.close()


# ---- deadline-aware admission control ----


def _blocked_engine_sup(release, entered, **kw):
    def slow(params, packed, player, rank):
        entered.set()
        assert release.wait(10)
        return np.zeros(len(packed), np.float32)

    return make_sup(slow,
                    engine_config=EngineConfig(buckets=(1,), max_wait_ms=0.0),
                    **kw)


class TestAdmissionControl:
    def test_sheds_when_estimated_wait_exceeds_deadline(self):
        release, entered = threading.Event(), threading.Event()
        sup = _blocked_engine_sup(release, entered)
        try:
            inflight = sup.submit(*one_board())
            assert entered.wait(5)
            queued = [sup.submit(*one_board(i)) for i in range(1, 4)]
            # seed the rolling dispatch-latency window: p50 = 0.2s, three
            # queued one-request windows -> estimated wait 0.6s
            sup._engine._dispatch_secs.extend([0.2] * 5)
            assert sup.estimated_wait_s() == pytest.approx(0.6)
            with pytest.raises(EngineOverloaded):
                sup.submit(*one_board(9), timeout_s=0.5)
            # a deadline the queue CAN meet is admitted
            ok = sup.submit(*one_board(10), timeout_s=30.0)
            # no deadline: never shed by admission
            nodl = sup.submit(*one_board(11))
            assert sup.health()["shed_overload"] == 1
            release.set()
            for f in (inflight, *queued, ok, nodl):
                assert f.result(timeout=10) is not None
        finally:
            release.set()
            sup.close()

    def test_no_estimate_before_first_dispatch(self):
        sup = make_sup(ok_forward)
        try:
            assert sup.estimated_wait_s() is None
            # and admission therefore never rejects
            assert sup.submit(*one_board(),
                              timeout_s=1e-9) is not None
        finally:
            sup.close()


# ---- breaker integration ----


class TestBreakerIntegration:
    def test_open_sheds_then_probe_recovers(self, tmp_path):
        # forward faults with no interleaved successes open the breaker;
        # a fake clock drives the recovery window; the half-open probe's
        # success closes it — all transitions land in the metrics stream
        writer = MetricsWriter(str(tmp_path / "m.jsonl"))
        clk = FakeClock()
        faults.install("serving_forward:transient@6")
        sup = make_sup(
            ok_forward,
            engine_config=EngineConfig(buckets=(1,), max_wait_ms=0.0),
            sup_config=SupervisorConfig(breaker_failures=2,
                                        breaker_reset_s=30.0,
                                        poison_threshold=1000),
            metrics=writer, clock=clk)
        try:
            f = sup.submit(*one_board())
            # the lone request keeps failing solo (transient budget 6 >
            # any retry it gets) until the breaker opens; wait for it
            deadline = time.monotonic() + 5
            while (sup._breaker.state != "open"
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert sup._breaker.state == "open"
            with pytest.raises(CircuitOpen):
                sup.submit(*one_board(1))
            assert sup.health()["shed_breaker"] == 1

            clk.advance(31)  # recovery due: next submit is THE probe
            probe = sup.submit(*one_board(2))
            assert probe.result(timeout=10) is not None
            assert sup._breaker.state == "closed"
            sup.submit(*one_board(3)).result(timeout=10)
        finally:
            sup.close()
            writer.close()
        records = read_jsonl(str(tmp_path / "m.jsonl"))
        moves = [(r["from_state"], r["to_state"]) for r in records
                 if r["kind"] == "serving_breaker"]
        assert ("closed", "open") in moves
        assert ("open", "half_open") in moves
        # the retried first request may close it from open before the
        # probe; either closing edge is a correct recovery
        assert ("half_open", "closed") in moves or ("open", "closed") in moves
        del f


# ---- chaos: the acceptance scenario ----


class TestChaos:
    def test_mixed_selfplay_evaluate_chaos_bitwise(self, tmp_path):
        """Dispatcher kill + transient forward faults under a mixed
        selfplay + evaluate workload: every future resolves, results are
        bit-identical to the fault-free run, restarts are counted, and
        the metrics stream records them."""
        from deepgo_tpu.selfplay import self_play

        cfg, params = tiny()
        forward = make_log_prob_fn(cfg)

        # fault-free references
        ref_games, _ = self_play(params, cfg, n_games=4, max_moves=20,
                                 seed=5)
        packed_fix, players_fix, ranks_fix = boards(6, seed=11)
        ref_rows = np.asarray(
            forward(params, packed_fix, players_fix, ranks_fix))

        faults.install(
            "serving_dispatch:fail@2,serving_forward:transient@2")
        writer = MetricsWriter(str(tmp_path / "chaos.jsonl"))
        sup = SupervisedEngine(
            lambda: InferenceEngine(forward, params,
                                    EngineConfig(buckets=(1, 2, 4, 8),
                                                 max_wait_ms=2.0)),
            config=SupervisorConfig(breaker_failures=50),
            name="chaos", metrics=writer, rng=random.Random(1))
        errors = []

        def arena_like():
            try:
                for _ in range(3):
                    got = sup.evaluate(packed_fix, players_fix, ranks_fix)
                    assert np.array_equal(got, ref_rows)
            except BaseException as e:  # noqa: BLE001
                errors.append(e)

        side = threading.Thread(target=arena_like)
        try:
            sup.warmup()
            side.start()
            games, stats = self_play(params, cfg, n_games=4, max_moves=20,
                                     seed=5, engine=sup)
            side.join(timeout=60)
            assert not side.is_alive() and not errors, errors

            # bit-identical trajectories: replayed/bisected requests
            # returned exactly the rows the fault-free run saw
            assert [[(m.player, m.x, m.y) for m in g.moves]
                    for g in games] == \
                   [[(m.player, m.x, m.y) for m in g.moves]
                    for g in ref_games]

            h = sup.health()
            assert h["restarts"] >= 1
            assert h["poisoned"] == 0
            assert h["state"] == "serving"
            assert stats["engine"]["supervisor"]["restarts"] >= 1
        finally:
            sup.close()
            writer.close()
        kinds = {r["kind"] for r in read_jsonl(str(tmp_path / "chaos.jsonl"))}
        assert "serving_restart" in kinds
        assert "serving_supervisor_close" in kinds

    def test_close_resolves_everything(self):
        # close() on a supervisor with parked work: futures resolve with
        # typed EngineClosed, never strand
        release, entered = threading.Event(), threading.Event()
        sup = _blocked_engine_sup(release, entered)
        inflight = sup.submit(*one_board())
        assert entered.wait(5)
        queued = [sup.submit(*one_board(i)) for i in range(1, 4)]
        closer = threading.Thread(target=lambda: sup.close(drain=False))
        closer.start()
        deadline = time.monotonic() + 5
        while not sup._closing.is_set() and time.monotonic() < deadline:
            time.sleep(0.005)
        release.set()
        closer.join(timeout=10)
        assert not closer.is_alive(), "close() hung"
        assert inflight.result(timeout=5) is not None
        for f in queued:
            # the contract is RESOLVED, never stranded: depending on how
            # far the dispatcher got before the cancel landed, a queued
            # request either drained (result) or failed typed
            try:
                assert f.result(timeout=5) is not None
            except EngineClosed:
                pass
        with pytest.raises(EngineClosed):
            sup.submit(*one_board())


# ---- shared-registry + agent routing ----


class TestSupervisedRouting:
    def test_shared_registry_supervised_is_distinct_and_duck_typed(self):
        from deepgo_tpu.serving import (close_shared_engines,
                                        shared_policy_engine)

        cfg, params = tiny()
        try:
            plain = shared_policy_engine(params, cfg)
            sup = shared_policy_engine(params, cfg, supervised=True)
            assert plain is not sup
            assert isinstance(sup, SupervisedEngine)
            assert sup is shared_policy_engine(params, cfg, supervised=True)
            packed, players, ranks = boards(2, seed=3)
            assert np.array_equal(sup.evaluate(packed, players, ranks),
                                  plain.evaluate(packed, players, ranks))
        finally:
            close_shared_engines()

    def test_policy_agent_on_supervised_engine_matches_direct(self):
        from deepgo_tpu.agents import PolicyAgent
        from deepgo_tpu.selfplay import legal_mask

        cfg, params = tiny()
        packed, players, _ = boards(5, seed=9)
        legal = legal_mask(packed, players)
        forward = make_log_prob_fn(cfg)
        faults.install("serving_dispatch:fail@1")  # restart mid-agent-call
        with SupervisedEngine(
                lambda: InferenceEngine(forward, params,
                                        EngineConfig(buckets=(1, 8),
                                                     max_wait_ms=0.0)),
                name="agent", rng=random.Random(0)) as sup:
            on_engine = PolicyAgent(params, cfg, engine=sup)
            direct = PolicyAgent(params, cfg)
            got = on_engine._legal_log_probs(packed, players, legal)
            want = direct._legal_log_probs(packed, players, legal)
            assert np.array_equal(got, want)
            assert sup.health()["restarts"] == 1
