"""Tromp-Taylor scoring and the match/arena harness."""

import numpy as np
import pytest

from deepgo_tpu.go import BLACK, WHITE, new_board, play
from deepgo_tpu.go.scoring import area_score
from deepgo_tpu import arena, sgf
from deepgo_tpu.selfplay import to_sgf


class TestAreaScore:
    def test_empty_board_white_wins_by_komi(self):
        stones, _ = new_board()
        s = area_score(stones, komi=7.5)
        assert (s.black, s.white) == (0.0, 0.0)
        assert s.winner == WHITE
        assert s.result_string() == "W+7.5"

    def test_single_stone_owns_whole_board(self):
        stones, age = new_board()
        play(stones, age, 3, 3, BLACK)
        s = area_score(stones, komi=7.5)
        assert s.black == 361.0 and s.white == 0.0
        assert s.winner == BLACK
        assert s.result_string() == "B+353.5"

    def test_region_touching_both_colors_is_neutral(self):
        stones, age = new_board()
        play(stones, age, 0, 0, BLACK)
        play(stones, age, 18, 18, WHITE)
        s = area_score(stones, komi=7.5)
        assert (s.black, s.white) == (1.0, 1.0)
        assert s.winner == WHITE  # komi decides

    def test_wall_partitions_territory(self):
        stones, age = new_board()
        for y in range(19):
            play(stones, age, 9, y, BLACK)
        play(stones, age, 14, 14, WHITE)
        s = area_score(stones, komi=7.5)
        # x<9 empty region reaches only black; x>9 region reaches both
        assert s.black == 9 * 19 + 19
        assert s.white == 1.0

    def test_draw(self):
        stones, age = new_board()
        play(stones, age, 0, 0, BLACK)
        play(stones, age, 18, 18, WHITE)
        s = area_score(stones, komi=0.0)
        assert s.margin == 0.0 and s.winner == 0
        assert s.result_string() == "0"

    def test_captured_area_flips_owner(self):
        stones, age = new_board()
        # white stone at (0,0) captured by black (0,1)+(1,0)
        play(stones, age, 0, 0, WHITE)
        play(stones, age, 0, 1, BLACK)
        play(stones, age, 1, 0, BLACK)
        s = area_score(stones, komi=0.0)
        assert s.white == 0.0 and s.black == 361.0


class TestArena:
    def test_random_vs_heuristic_match(self):
        games, scores, stats = arena.play_match(
            arena.RandomAgent(), arena.HeuristicAgent(),
            n_games=4, max_moves=30, seed=1)
        assert stats["games"] == 4
        assert (stats["random_wins"] + stats["heuristic_wins"]
                + stats["draws"]) == 4
        assert len(scores) == 4
        for g in games:
            assert g.done and len(g.moves) <= 30

    def test_colors_alternate_across_games(self):
        class FirstLegal(arena.Agent):
            name = "first"

            def __init__(self):
                self.colors_seen = set()

            def select_moves(self, packed, players, legal, rng):
                self.colors_seen.update(int(p) for p in players)
                moves = np.full(len(packed), -1, dtype=np.int64)
                for i in range(len(packed)):
                    nz = np.flatnonzero(legal[i])
                    if nz.size:
                        moves[i] = nz[0]
                return moves

        a, b = FirstLegal(), arena.RandomAgent()
        arena.play_match(a, b, n_games=2, max_moves=6, seed=0)
        assert a.colors_seen == {1, 2}  # plays black in game 0, white in game 1

    def test_heuristic_prefers_capture(self):
        # white at (0,0) in atari: black to move must capture at (1,0)
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        g = arena.GameState()
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 0, 1, BLACK)
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        legal = legal_mask(packed, players)
        moves = arena.HeuristicAgent().select_moves(
            packed, players, legal, np.random.default_rng(0))
        assert moves[0] == 19 * 1 + 0

    def test_policy_agent_smoke(self):
        import jax

        from deepgo_tpu.models import policy_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        agent = arena.PolicyAgent(params, cfg, name="p")
        games, scores, stats = arena.play_match(
            agent, arena.RandomAgent(), n_games=2, max_moves=6, seed=0)
        assert stats["games"] == 2
        assert all(g.done for g in games)

    def test_opening_plies_paired_and_distinct(self):
        # two deterministic agents, 4 games, 6-ply random openings: games
        # 2i/2i+1 share their opening exactly (balanced color swap) while
        # the two pairs get different openings (distinct trajectories)
        games, _, _ = arena.play_match(
            arena.OnePlyAgent(), arena.HeuristicAgent(), n_games=4,
            max_moves=30, seed=5, opening_plies=6)
        op = [[(m.x, m.y) for m in g.moves[:6]] for g in games]
        assert op[0] == op[1] and op[2] == op[3]
        assert op[0] != op[2]

    def test_per_game_openings_break_pair_duplication(self):
        # corpus-generation mode (shared_openings=False): every game gets
        # its own opening, so a deterministic self-pair no longer produces
        # the same game twice (tools/make_selfplay_corpus.py uses this —
        # pair-shared openings would leak duplicate games across
        # train/validation splits)
        games, _, _ = arena.play_match(
            arena.OnePlyAgent(), arena.OnePlyAgent(), n_games=4,
            max_moves=30, seed=5, opening_plies=6, shared_openings=False)
        op = [[(m.x, m.y) for m in g.moves[:6]] for g in games]
        assert len({tuple(o) for o in op}) == 4

    def test_scored_sgf_roundtrip(self):
        games, scores, _ = arena.play_match(
            arena.RandomAgent(), arena.RandomAgent(),
            n_games=1, max_moves=10, seed=3)
        text = to_sgf(games[0], result=scores[0].result_string(), komi=7.5)
        parsed = sgf.parse(text)
        assert len(parsed.moves) == len(games[0].moves)

    def test_oneply_beats_random_and_reports_truncation(self):
        games, scores, stats = arena.play_match(
            arena.OnePlyAgent(), arena.RandomAgent(), n_games=8,
            max_moves=350, seed=11)
        assert stats["oneply_win_rate"] >= 0.9
        # truncation accounting: every game is either double-pass finished
        # or counted truncated
        finished = sum(1 for g in games if g.passes >= 2)
        assert stats["truncated"] == len(games) - finished

    def test_oneply_takes_capture(self):
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        g = arena.GameState()
        # white stone at (0,0) in atari: black (0,1),(1,0) capture at... the
        # white group's last liberty is its own point? Build: white (0,0),
        # black at (1,0); black to move at (0,1) captures.
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 1, 0, BLACK)
        g.player = 1
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        legal = legal_mask(packed, players, [g])
        rng = np.random.default_rng(0)
        move = arena.OnePlyAgent().select_moves(packed, players, legal, rng)[0]
        assert move == 0 * 19 + 1  # (0,1), the capturing point

    def test_no_own_eyes_mask(self):
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        g = arena.GameState()
        # black corner eye at (0,0); white center eye at (10,10)
        for x, y in [(0, 1), (1, 0)]:
            play(g.stones, g.age, x, y, BLACK)
        for x, y in [(9, 10), (11, 10), (10, 9), (10, 11)]:
            play(g.stones, g.age, x, y, WHITE)
        packed = np.stack([summarize_state(g)] * 2)
        players = np.array([1, 2], dtype=np.int32)
        legal = legal_mask(packed, players)
        masked = arena._no_own_eyes(packed, players, legal)
        assert legal[0, 0] and not masked[0, 0]        # black's own eye
        # White playing inside black's one-point eye captures nothing and
        # ends with zero liberties: suicide.  legal_mask must already
        # exclude it, so the eye mask can never re-admit it.
        assert not legal[1, 0] and not masked[1, 0]
        center = 19 * 10 + 10
        assert legal[1, center] and not masked[1, center]  # white's own eye
        # Same for black invading white's one-point eye: suicide.
        assert not legal[0, center] and not masked[0, center]

    def test_simple_ko_ban(self):
        from deepgo_tpu.selfplay import apply_move, legal_mask, summarize_state

        g = arena.GameState()
        for x, y in [(1, 2), (2, 1), (2, 3)]:
            play(g.stones, g.age, x, y, BLACK)
        for x, y in [(2, 2), (3, 1), (3, 3), (4, 2)]:
            play(g.stones, g.age, x, y, WHITE)
        g.player = 1
        apply_move(g, 3, 2)  # black captures the ko stone at (2,2)
        assert g.ko_point == (2, 2)
        g.player = 2
        packed = summarize_state(g)[None]
        legal = legal_mask(packed, np.array([2], dtype=np.int32), [g])
        assert not legal[0, 19 * 2 + 2]  # immediate recapture banned
        assert legal[0, 19 * 10 + 10]
        g.player = 2
        apply_move(g, 10, 10)  # any other move clears the ban
        assert g.ko_point is None

    def test_batched_log_probs_padding_matches_direct(self):
        import jax
        import jax.numpy as jnp

        from deepgo_tpu.models import policy_cnn
        from deepgo_tpu.models.serving import make_policy_fn
        from deepgo_tpu.selfplay import batched_log_probs

        cfg = policy_cnn.ModelConfig(num_layers=1, channels=4)
        params = policy_cnn.init(jax.random.key(0), cfg)
        predict = make_policy_fn(cfg, top_k=1)
        rng = np.random.default_rng(0)
        packed = rng.integers(0, 2, size=(3, 9, 19, 19), dtype=np.uint8)
        players = np.array([1, 2, 1], dtype=np.int32)
        ranks = np.array([9, 9, 9], dtype=np.int32)
        padded = batched_log_probs(predict, params, packed, players, ranks)
        direct = np.asarray(predict(params, jnp.asarray(packed),
                                    jnp.asarray(players),
                                    jnp.asarray(ranks))["log_probs"])
        assert padded.shape == (3, 361)
        np.testing.assert_allclose(padded, direct, rtol=1e-5, atol=1e-5)

    def test_generated_sgf_feeds_transcription(self, tmp_path):
        # the "full circle": arena games -> SGF -> training shard records
        from deepgo_tpu.data.transcribe import transcribe_game

        games, scores, _ = arena.play_match(
            arena.RandomAgent(), arena.HeuristicAgent(),
            n_games=1, max_moves=40, seed=5)
        path = tmp_path / "g.sgf"
        path.write_text(to_sgf(games[0], result=scores[0].result_string(),
                               komi=7.5))
        packed, meta = transcribe_game(str(path))
        assert packed.shape == (len(games[0].moves), 9, 19, 19)
        assert meta.shape[0] == len(games[0].moves)

    def test_make_agent_specs(self):
        assert isinstance(arena._make_agent("random", 0), arena.RandomAgent)
        assert isinstance(arena._make_agent("heuristic", 0),
                          arena.HeuristicAgent)
        with pytest.raises(ValueError):
            arena._make_agent("gnugo", 0)

    def test_search_agent_urgency_override_takes_capture(self):
        # Random-init net knows nothing; the capture at (0,1) scores
        # tactically >= 1000 and must be admitted + chosen via the urgency
        # override even when the policy's top-k misses it.
        import jax

        from deepgo_tpu.models import policy_cnn
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        agent = arena.PolicySearchAgent(params, cfg, top_k=1)
        g = arena.GameState()
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 1, 0, BLACK)
        g.player = 1
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        legal = legal_mask(packed, players, [g])
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == 0 * 19 + 1

    def test_search_agent_urgent_move_vetoes_pass(self):
        # pass_threshold=2.0 is unsatisfiable (prob <= 1), so the policy
        # rule alone would always pass — the urgent capture must still be
        # played.
        import jax

        from deepgo_tpu.models import policy_cnn
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        agent = arena.PolicySearchAgent(params, cfg, top_k=1,
                                        pass_threshold=2.0)
        g = arena.GameState()
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 1, 0, BLACK)
        g.player = 1
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        legal = legal_mask(packed, players, [g])
        rng = np.random.default_rng(0)
        assert agent.select_moves(packed, players, legal, rng)[0] == 1
        # and on an empty board (nothing urgent) the same threshold passes
        g2 = arena.GameState()
        packed2 = summarize_state(g2)[None]
        legal2 = legal_mask(packed2, players, [g2])
        assert agent.select_moves(packed2, players, legal2, rng)[0] == -1

    def test_search_agent_rejects_temperature(self):
        import jax

        from deepgo_tpu.models import policy_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        with pytest.raises(ValueError):
            arena.PolicySearchAgent(params, cfg, temperature=0.5)

    def test_search_agent_liberty_terms_are_not_urgent(self):
        # a long safe chain makes liberties-after exceed 400/12 next to it,
        # but nothing on this board is forcing (no capture, save, or
        # ladder): with an unsatisfiable pass threshold the agent must
        # still pass — positional liberty terms alone must never trip the
        # urgency veto
        import jax

        from deepgo_tpu.models import policy_cnn
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        agent = arena.PolicySearchAgent(params, cfg, pass_threshold=2.0)
        g = arena.GameState()
        for y in range(19):
            play(g.stones, g.age, 9, y, BLACK)
        g.player = 1
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        from deepgo_tpu.features import P_LIB_AFTER

        libs = packed[0, P_LIB_AFTER].reshape(-1)
        assert int(libs.max()) * 12 >= 400  # the board really has the hazard
        legal = legal_mask(packed, players, [g])
        rng = np.random.default_rng(0)
        assert agent.select_moves(packed, players, legal, rng)[0] == -1

    def test_search_agent_quiet_board_plays_policy_argmax(self):
        # no forcing move on the board -> the agent must play exactly the
        # net's (eye-masked) argmax move, not a tactically re-ranked one
        import jax

        from deepgo_tpu.models import policy_cnn
        from deepgo_tpu.selfplay import (batched_log_probs, legal_mask,
                                         summarize_state)

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(2), cfg)
        agent = arena.PolicySearchAgent(params, cfg)
        g = arena.GameState()
        play(g.stones, g.age, 3, 3, BLACK)
        play(g.stones, g.age, 15, 15, WHITE)
        packed = summarize_state(g)[None]
        players = np.array([1], dtype=np.int32)
        legal = legal_mask(packed, players, [g])
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        masked = arena._no_own_eyes(packed, players, legal)
        logp = batched_log_probs(agent._predict, params, packed, players,
                                 np.array([9], dtype=np.int32))
        expect = int(np.where(masked[0], logp[0], -np.inf).argmax())
        assert move == expect

    def test_search_agent_plays_full_games(self):
        import jax

        from deepgo_tpu.models import policy_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(1), cfg)
        agent = arena.PolicySearchAgent(params, cfg)
        games, scores, stats = arena.play_match(
            agent, arena.RandomAgent(), n_games=2, max_moves=40, seed=5)
        assert stats["games"] == 2
        for g in games:
            for move in g.moves:
                assert 0 <= move.x < 19 and 0 <= move.y < 19


class TestValueSearchAgent:
    @staticmethod
    def _agent(**kw):
        import jax

        from deepgo_tpu.models import policy_cnn, value_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        vcfg = value_cnn.ValueConfig(num_layers=2, channels=8)
        vparams = value_cnn.init(jax.random.key(1), vcfg)
        return arena.ValueSearchAgent(params, cfg, vparams, vcfg, **kw)

    def test_huge_margin_keeps_policy_argmax(self):
        # an unreachable margin disables the veto entirely: the move must
        # be exactly the policy argmax, whatever the value net thinks
        agent = self._agent(margin=1e9)
        g = arena.GameState()
        play(g.stones, g.age, 10, 10, BLACK)
        play(g.stones, g.age, 4, 15, WHITE)
        g.player = 1
        packed, players, legal = TestTwoPlyAgent._position(g)
        masked = arena._no_own_eyes(packed, players, legal)
        logp = agent._legal_log_probs(packed, players, masked)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == int(logp[0].argmax())

    def test_negative_margin_always_fires_to_value_argmax(self):
        # margin -inf-ish means the veto always fires; the chosen move must
        # be a legal candidate (value-argmax), exercising the full
        # play-candidates -> value-forward -> override path
        agent = self._agent(margin=-1e9, top_k=4)
        g = arena.GameState()
        play(g.stones, g.age, 3, 3, BLACK)
        play(g.stones, g.age, 15, 15, WHITE)
        g.player = 1
        packed, players, legal = TestTwoPlyAgent._position(g)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move >= 0 and legal[0, move]

    def test_value_spec_needs_two_paths(self):
        with pytest.raises(ValueError, match="two checkpoint paths"):
            arena._make_agent("value:only_one.npz", seed=0)


class TestValue2PlyAgent:
    @staticmethod
    def _agent(**kw):
        import jax

        from deepgo_tpu.models import policy_cnn, value_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        vcfg = value_cnn.ValueConfig(num_layers=2, channels=8)
        vparams = value_cnn.init(jax.random.key(1), vcfg)
        return arena.Value2PlyAgent(params, cfg, vparams, vcfg, **kw)

    def test_huge_margin_keeps_policy_argmax(self):
        # an unreachable margin disables the veto: the move is exactly the
        # policy argmax even after the full 2-ply candidate/reply expansion
        agent = self._agent(margin=1e9)
        g = arena.GameState()
        play(g.stones, g.age, 10, 10, BLACK)
        play(g.stones, g.age, 4, 15, WHITE)
        g.player = 1
        packed, players, legal = TestTwoPlyAgent._position(g)
        masked = arena._no_own_eyes(packed, players, legal)
        logp = agent._legal_log_probs(packed, players, masked)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == int(logp[0].argmax())

    def test_negative_margin_fires_to_a_candidate(self):
        # margin -inf-ish means the veto always fires; the chosen move must
        # be a legal candidate, exercising candidates -> replies -> leaf
        # values -> min-aggregation -> override end to end
        agent = self._agent(margin=-1e9, top_k=4, reply_k=3)
        g = arena.GameState()
        play(g.stones, g.age, 3, 3, BLACK)
        play(g.stones, g.age, 15, 15, WHITE)
        g.player = 1
        packed, players, legal = TestTwoPlyAgent._position(g)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move >= 0 and legal[0, move]

    def test_candidate_score_is_min_over_replies(self, monkeypatch):
        # the pass reply caps every candidate's score at the after-board
        # value: force the value net to love after-boards (0.9) and hate
        # every deeper reply leaf (0.1) — the score each candidate carries
        # into the veto must be the WORST leaf, 0.1 (a max or mean
        # aggregation, or a 1-ply agent seeing only the rosy after-board,
        # would report ~0.9 and reintroduce the horizon blunder this
        # agent exists to close)
        from deepgo_tpu import agents as agents_mod

        agent = self._agent(margin=-1e9, top_k=2, reply_k=2)
        calls = []

        def fake_values(boards, to_move):
            calls.append(len(boards))
            return np.full(len(boards), 0.9 if len(calls) == 1 else 0.1)

        monkeypatch.setattr(agent, "_values", fake_values)
        seen = {}
        real_veto = agents_mod._veto_select

        def spy_veto(logp, legal, cand, rows, cols, cand_scores, *a, **kw):
            seen["scores"] = np.asarray(cand_scores)
            return real_veto(logp, legal, cand, rows, cols, cand_scores,
                             *a, **kw)

        monkeypatch.setattr(agents_mod, "_veto_select", spy_veto)
        g = arena.GameState()
        play(g.stones, g.age, 9, 9, BLACK)
        play(g.stones, g.age, 10, 10, WHITE)
        g.player = 1
        packed, players, legal = TestTwoPlyAgent._position(g)
        agent.select_moves(packed, players, legal, np.random.default_rng(0))
        # both value passes ran: once for pass-leaves, once for reply leaves
        assert len(calls) == 2
        assert calls[1] > calls[0]  # replies outnumber candidates
        # on an open board every candidate has replies, so min-aggregation
        # must pull every score down to the 0.1 leaves
        assert np.all(seen["scores"] <= 0.1 + 1e-9)

    def test_plays_full_games(self):
        agent = self._agent(top_k=3, reply_k=2)
        games, scores, stats = arena.play_match(
            agent, arena.RandomAgent(), n_games=2, max_moves=30, seed=5)
        assert stats["games"] == 2


class TestTwoPlyAgent:
    @staticmethod
    def _agent(**kw):
        import jax

        from deepgo_tpu.models import policy_cnn

        cfg = policy_cnn.ModelConfig(num_layers=2, channels=8)
        params = policy_cnn.init(jax.random.key(0), cfg)
        return arena.TwoPlyAgent(params, cfg, **kw)

    @staticmethod
    def _position(game):
        from deepgo_tpu.selfplay import legal_mask, summarize_state

        packed = summarize_state(game)[None]
        players = np.array([game.player], dtype=np.int32)
        legal = legal_mask(packed, players, [game])
        return packed, players, legal

    def test_apply_and_summarize_fallback_matches_native(self, monkeypatch):
        # the Python fallback path must produce the same packed boards and
        # ko points the native batched path does
        from deepgo_tpu.go import native

        if not native.batch_available():
            pytest.skip("native batch engine not built")
        g = arena.GameState()
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 1, 0, BLACK)
        stones = np.stack([g.stones, g.stones])
        age = np.stack([g.age, g.age])
        moves = np.array([0 * 19 + 1, 5 * 19 + 5], dtype=np.int32)
        players = np.array([1, 1], dtype=np.int32)
        pk_n, ko_n = arena._apply_and_summarize(
            stones.copy(), age.copy(), moves, players)
        monkeypatch.setattr(native, "batch_available", lambda: False)
        pk_p, ko_p = arena._apply_and_summarize(
            stones.copy(), age.copy(), moves, players)
        np.testing.assert_array_equal(pk_n, pk_p)
        np.testing.assert_array_equal(ko_n, ko_p)

    def test_quiet_board_plays_policy_argmax(self):
        # no tactics anywhere: the differential veto must not fire and the
        # move must be exactly the policy's argmax
        agent = self._agent()
        g = arena.GameState()
        play(g.stones, g.age, 10, 10, BLACK)
        play(g.stones, g.age, 3, 16, WHITE)
        g.player = 1
        packed, players, legal = self._position(g)
        masked = arena._no_own_eyes(packed, players, legal)
        logp = agent._legal_log_probs(packed, players, masked)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == int(logp[0].argmax())

    def test_fires_on_clean_capture_policy_missed(self):
        # a random-init policy knows nothing; the 1-stone capture is the
        # only tactic on the board, is unrefuted (capturing stone keeps
        # liberties), and beats any quiet move's 2-ply score by >= margin
        agent = self._agent(top_k=1)
        g = arena.GameState()
        # white stone at (5,5) with black on three sides; capture at (5,6)
        play(g.stones, g.age, 5, 5, WHITE)
        for x, y in ((4, 5), (6, 5), (5, 4)):
            play(g.stones, g.age, x, y, BLACK)
        g.player = 1
        packed, players, legal = self._position(g)
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == 5 * 19 + 6

    def test_prefers_working_escape_over_refuted_one(self):
        # black chain in atari; two candidate saves exist: extending into
        # the open center (works: no immediate recapture, no ladder) vs a
        # same-tier option whose result is still capturable. The 2-ply
        # threat term must pick the working one. Construct: black stone at
        # (0,3) edge, white at (0,2) and (1,3) -> last liberty (0,4).
        # Extending to (0,4) leaves a 2-liberty chain on the edge that
        # white ladders/captures; capturing the atari-giver is impossible,
        # but black ALSO has a working counter-atari: white stone (1,3)
        # has liberties (1,4),(2,3) -> no. Instead give black a clean
        # capture of the (0,2) attacker: black at (1,2) and (0,1) makes
        # (0,2) a 1-liberty white stone capturable at... (0,2)'s liberties:
        # none left -> use (1,1) black and capture point (0,1).
        g = arena.GameState()
        play(g.stones, g.age, 0, 3, BLACK)   # the chain in atari
        play(g.stones, g.age, 0, 2, WHITE)   # attacker A
        play(g.stones, g.age, 1, 3, WHITE)   # attacker B
        play(g.stones, g.age, 1, 2, BLACK)   # takes A's south liberty
        play(g.stones, g.age, 1, 1, BLACK)   # helps surround A
        # A=(0,2) liberties now: (0,1) only -> black can capture A at (0,1),
        # which also rescues the chain (frees (0,2)).
        g.player = 1
        packed, players, legal = self._position(g)
        move = self._agent(top_k=1).select_moves(
            packed, players, legal, np.random.default_rng(0))[0]
        # capturing A at (0,1) is the working save: gains a liberty for the
        # chain and removes the attacker with no comeback; extending to
        # (0,4) leaves the chain still capturable (threat stays high)
        assert move == 0 * 19 + 1

    def test_futile_save_does_not_fire(self):
        # regression for the round-4 horizon-effect collapse: a 4-stone
        # black chain in atari whose only "save" (0,0) leaves the bigger
        # chain still in atari (white recaptures 5 at (1,0)). Under the
        # old save-credited scoring the save carried 700*4 of speculative
        # credit and outscored every quiet move by ~900 >= margin, so the
        # agent chased the doomed group; realized-outcome scoring must
        # keep the policy's own move instead
        g = arena.GameState()
        for y in (1, 2, 3, 4):
            play(g.stones, g.age, 0, y, BLACK)
            play(g.stones, g.age, 1, y, WHITE)
        play(g.stones, g.age, 0, 5, WHITE)   # cap: chain liberty = (0,0) only
        g.player = 1
        packed, players, legal = self._position(g)
        agent = self._agent(top_k=1)
        masked = arena._no_own_eyes(packed, players, legal)
        logp = agent._legal_log_probs(packed, players, masked)
        policy_move = int(logp[0].argmax())
        assert policy_move != 0, "vacuous fixture: policy argmax is the save"
        move = agent.select_moves(packed, players, legal,
                                  np.random.default_rng(0))[0]
        assert move == policy_move

    def test_urgent_capture_vetoes_pass(self):
        # pass_threshold=2.0 is unsatisfiable, so the policy rule alone
        # would always pass; with a live capture on the board the agent
        # must play on (same contract as PolicySearchAgent — passing over
        # dead stones hands them to the opponent under area scoring)
        agent = self._agent(top_k=1, pass_threshold=2.0)
        g = arena.GameState()
        play(g.stones, g.age, 0, 0, WHITE)
        play(g.stones, g.age, 1, 0, BLACK)
        g.player = 1
        packed, players, legal = self._position(g)
        rng = np.random.default_rng(0)
        assert agent.select_moves(packed, players, legal, rng)[0] == 1
        # and on a quiet board the same threshold does pass
        g2 = arena.GameState()
        play(g2.stones, g2.age, 10, 10, BLACK)
        g2.player = 1
        packed, players, legal = self._position(g2)
        assert agent.select_moves(packed, players, legal, rng)[0] == -1
