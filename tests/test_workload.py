"""Workload observatory (obs/workload.py + serving/replay.py).

The load-bearing contracts:

  * canonicalization: all 8 dihedral views of a position map to ONE
    canonical key (the group-orbit property), the permutation tables
    match ops/augment's, and distinct positions never collide over a
    real-game corpus;
  * capture reads are torn-line tolerant and round-trip through the
    deduplicated position store (a capture is replayable);
  * the recorder is FREE when off (``note_request`` returns None, no
    token rides the request, nothing is written) and counts every
    request exactly once when on — fleet -> supervisor -> engine is one
    record, not three;
  * open-loop replay reproduces the recorded request count and tier mix
    exactly, and the replayed inter-arrival timeline sits within the
    10% fidelity bar;
  * the synthetic opening-heavy generator is a pure function of its
    seed;
  * ``cli workload record|analyze|replay`` and the ``cli obs`` workload
    section surface all of it.
"""

import json
import os
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from deepgo_tpu.obs import workload as wl
from deepgo_tpu.obs.exporter import JsonlSink
from deepgo_tpu.serving import replay as rp
from deepgo_tpu.serving import (EngineConfig, FleetRouter, InferenceEngine,
                                SupervisedEngine)

SGF_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "data", "sgf", "train")


@pytest.fixture(autouse=True)
def _clean():
    wl.disable_workload()
    yield
    wl.disable_workload()


def ok_forward(params, packed, player, rank):
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3)) \
        + 1000.0 * np.asarray(player, np.float32)


def rand_packed(rng, n=1):
    return rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8)


def make_engine(name="wl-test", buckets=(1, 8)):
    eng = InferenceEngine(ok_forward, None,
                          EngineConfig(buckets=buckets, max_wait_ms=1.0),
                          name=name)
    eng.warmup()
    return eng


# ---------------------------------------------------------------------------
# digests + canonicalization


class TestCanonicalization:
    def test_perm_tables_match_ops_augment(self):
        from deepgo_tpu.ops import augment

        np.testing.assert_array_equal(wl._PERMS, augment._PERM_NP)

    def test_all_eight_views_share_one_canonical_key(self):
        rng = np.random.default_rng(0)
        for i in range(5):
            packed = rand_packed(rng)[0]
            views = wl.dihedral_views(packed)
            assert len(views) == 8
            canon = {wl.canonical_digest(v, 1, 5) for v in views}
            assert len(canon) == 1
            # the views themselves are genuinely distinct inputs
            exact = {wl.exact_digest(v, 1, 5) for v in views}
            assert len(exact) == 8

    def test_real_corpus_positions_never_collide(self):
        # every position of a few real games: distinct boards -> distinct
        # exact digests AND distinct canonical keys (a canonical
        # collision would alias two different positions in the cache)
        pool = rp._opening_pool(SGF_DIR, games=4, opening_moves=30)
        exact = {}
        canon = {}
        for p in pool:
            d = wl.exact_digest(p["packed"], p["player"], p["rank"])
            c = wl.canonical_digest(p["packed"], p["player"], p["rank"])
            if d in exact:
                # identical boards may legitimately repeat across games
                # (shared opening tree) — only DIFFERENT boards colliding
                # is a failure
                assert np.array_equal(exact[d], p["packed"])
            else:
                exact[d] = p["packed"]
            if c in canon:
                views = [v.tobytes() for v in
                         wl.dihedral_views(canon[c])]
                assert p["packed"].tobytes() in views
            else:
                canon[c] = p["packed"]
        assert len(exact) > 20

    def test_player_and_rank_key_the_digest(self):
        packed = rand_packed(np.random.default_rng(1))[0]
        assert wl.exact_digest(packed, 1, 5) != wl.exact_digest(packed, 2, 5)
        assert wl.exact_digest(packed, 1, 5) != wl.exact_digest(packed, 1, 6)
        assert wl.canonical_digest(packed, 1, 5) \
            != wl.canonical_digest(packed, 2, 5)

    def test_canonical_stable_under_view_of_view(self):
        packed = rand_packed(np.random.default_rng(2))[0]
        base = wl.canonical_digest(packed, 2, 3)
        for v in wl.dihedral_views(packed):
            for vv in wl.dihedral_views(v):
                assert wl.canonical_digest(vv, 2, 3) == base

    def test_bad_shape_is_typed(self):
        with pytest.raises(ValueError):
            wl.exact_digest(np.zeros((3, 19, 19), np.uint8), 1, 1)
        with pytest.raises(ValueError):
            wl.canonical_digest(np.zeros((9, 9, 9), np.uint8), 1, 1)


# ---------------------------------------------------------------------------
# the recorder


class TestRecorder:
    def test_off_mode_is_free(self, tmp_path):
        assert wl.note_request(np.zeros((9, 19, 19), np.uint8), 1, 1) is None
        assert not wl.workload_enabled()
        with make_engine("wl-off") as eng:
            fut = eng.submit(rand_packed(np.random.default_rng(0))[0], 1, 5)
            fut.result()
        # nothing recorded, nothing written anywhere
        assert wl.get_workload_recorder() is None

    def test_engine_capture_end_to_end(self, tmp_path):
        cap = str(tmp_path / "cap")
        rec = wl.configure_workload(cap)
        rng = np.random.default_rng(0)
        boards = rand_packed(rng, 3)
        with make_engine("wl-e2e") as eng:
            futs = [eng.submit(boards[i % 3], 1, 5) for i in range(12)]
            for f in futs:
                f.result()
        rec.drain()
        stats = rec.stats()
        assert stats["started"] == 12
        assert stats["finished"] == 12
        assert stats["dropped"] == 0
        assert stats["unique"] == 3
        assert stats["by_outcome"] == {"ok": 12}
        wl.disable_workload()
        report = wl.analyze_capture(cap)
        assert report["requests"] == 12
        assert report["unique"] == 3
        assert report["dup_ratio"] == 0.75
        assert report["projected_hit_rate"] == 0.75
        assert report["replayable"] is True
        assert report["positions_stored"] == 3
        # the engine stamped the coalesced bucket on every record
        assert set(report["buckets"]) <= {"1", "8"}
        assert sum(report["buckets"].values()) == 12

    def test_symmetry_duplicates_fold_onto_one_canonical_key(self, tmp_path):
        cap = str(tmp_path / "cap")
        rec = wl.configure_workload(cap)
        packed = rand_packed(np.random.default_rng(3))[0]
        views = wl.dihedral_views(packed)
        with make_engine("wl-sym") as eng:
            for v in views:
                eng.submit(v, 1, 5).result()
        rec.drain()
        wl.disable_workload()
        report = wl.analyze_capture(cap)
        assert report["requests"] == 8
        assert report["unique"] == 8             # 8 distinct exact inputs
        assert report["canonical_unique"] == 1   # one orbit
        assert report["symmetry_dedup_gain"] == 8.0
        assert report["projected_hit_rate"] == 0.0
        assert report["projected_hit_rate_canonical"] == 0.875

    def test_one_record_per_request_through_the_full_stack(self, tmp_path):
        # fleet -> supervisor -> engine: the fleet door owns the token;
        # inner layers must not double-count
        cap = str(tmp_path / "cap")
        rec = wl.configure_workload(cap)
        rng = np.random.default_rng(1)
        boards = rand_packed(rng, 2)

        def make_replica(i):
            return SupervisedEngine(
                lambda: InferenceEngine(
                    ok_forward, None,
                    EngineConfig(buckets=(1, 8), max_wait_ms=1.0),
                    name=f"wl-fleet-{i}"),
                name=f"wl-fleet-{i}")

        with FleetRouter(make_replica, 2, name="wl-fleet") as fleet:
            fleet.warmup()
            futs = [fleet.submit(boards[i % 2], 1, 5,
                                 tier=("interactive" if i % 2 else "batch"))
                    for i in range(10)]
            for f in futs:
                f.result()
        rec.drain()
        stats = rec.stats()
        wl.disable_workload()
        assert stats["started"] == 10
        assert stats["finished"] == 10
        assert stats["by_tier"] == {"interactive": 5, "batch": 5}
        report = wl.analyze_capture(cap)
        assert report["requests"] == 10
        assert report["tiers"] == {"batch": 5, "interactive": 5}

    def test_requests_counter_labeled_by_tier(self, tmp_path):
        from deepgo_tpu.obs import get_registry

        before = {}
        snap = get_registry().snapshot()["metrics"].get(
            "deepgo_workload_requests_total")
        if snap:
            before = dict(snap["series"])
        rec = wl.configure_workload(str(tmp_path / "cap"))
        rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1,
                 tier="interactive").finish("ok")
        rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1).finish("ok")
        rec.drain()
        wl.disable_workload()
        snap = get_registry().snapshot()["metrics"][
            "deepgo_workload_requests_total"]["series"]
        assert snap.get("tier=interactive", 0) \
            - before.get("tier=interactive", 0) == 1
        assert snap.get("tier=untiered", 0) \
            - before.get("tier=untiered", 0) == 1

    def test_outcome_classification(self, tmp_path):
        rec = wl.configure_workload(str(tmp_path / "cap"))
        from deepgo_tpu.serving import EngineOverloaded, PoisonedRequest

        cases = [
            (None, "ok"),
            (TimeoutError("t"), "timeout"),
            (EngineOverloaded("s"), "shed"),
            (PoisonedRequest("p"), "poisoned"),
            (RuntimeError("x"), "failed"),
        ]
        for exc, _expected in cases:
            token = rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1)
            f = Future()
            if exc is None:
                f.set_result(1)
            else:
                f.set_exception(exc)
            token.finish_future(f)
        rec.drain()
        stats = rec.stats()
        wl.disable_workload()
        assert stats["by_outcome"] == {"ok": 1, "timeout": 1, "shed": 1,
                                       "poisoned": 1, "failed": 1}

    def test_finish_is_idempotent(self, tmp_path):
        rec = wl.configure_workload(str(tmp_path / "cap"))
        token = rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1)
        token.finish("ok")
        token.finish("failed")
        rec.drain()
        stats = rec.stats()
        wl.disable_workload()
        assert stats["finished"] == 1
        assert stats["by_outcome"] == {"ok": 1}

    def test_full_queue_drops_instead_of_blocking(self, tmp_path):
        class SlowSink:
            def write(self, kind, **fields):
                time.sleep(0.05)

            def close(self):
                pass

        rec = wl.WorkloadRecorder(SlowSink(), max_queue=2)
        for _ in range(8):
            token = rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1)
            token.finish("ok")
        stats = rec.stats()
        assert stats["dropped"] > 0
        assert stats["dropped"] + stats["finished"] \
            + stats["pending"] == 8
        rec.close(timeout_s=2.0)

    def test_capture_summary_record_on_close(self, tmp_path):
        cap = str(tmp_path / "cap")
        rec = wl.configure_workload(cap)
        token = rec.note(np.zeros((9, 19, 19), np.uint8), 1, 1)
        token.finish("ok")
        wl.disable_workload()
        loaded = wl.load_capture(cap)
        assert loaded["summary"] is not None
        assert loaded["summary"]["started"] == 1
        assert loaded["summary"]["unique"] == 1


# ---------------------------------------------------------------------------
# capture reads


class TestCaptureReads:
    def _write_capture(self, cap, requests=6, uniques=2):
        rng = np.random.default_rng(7)
        boards = rand_packed(rng, uniques)
        items = [{"t": 0.01 * i, "packed": boards[i % uniques],
                  "player": 1, "rank": 5,
                  "tier": ("interactive", "batch")[i % 2]}
                 for i in range(requests)]
        rp.write_synthetic_capture(cap, items)
        return items

    def test_torn_line_tolerated(self, tmp_path):
        cap = str(tmp_path / "cap")
        self._write_capture(cap)
        # tear the request stream mid-record (a SIGKILLed recorder) and
        # the position stream too
        for name in ("workload.jsonl", "positions.jsonl"):
            path = os.path.join(cap, name)
            with open(path, "a") as f:
                f.write('{"kind": "workload_requ')
        report = wl.analyze_capture(cap)
        assert report["requests"] == 6
        assert report["unique"] == 2
        assert report["replayable"] is True

    def test_missing_capture_is_typed(self, tmp_path):
        with pytest.raises(wl.WorkloadCaptureError):
            wl.load_capture(str(tmp_path / "nope"))

    def test_digest_only_capture_refuses_strict_replay(self, tmp_path):
        cap = str(tmp_path / "cap")
        self._write_capture(cap)
        os.remove(os.path.join(cap, "positions.jsonl"))
        with pytest.raises(wl.WorkloadCaptureError):
            rp.load_trace(cap)
        assert rp.load_trace(cap, strict=False) == []

    def test_round_trip_payloads_bitwise(self, tmp_path):
        cap = str(tmp_path / "cap")
        items = self._write_capture(cap)
        trace = rp.load_trace(cap)
        assert len(trace) == len(items)
        for got, want in zip(trace, items):
            np.testing.assert_array_equal(got["packed"], want["packed"])
            assert got["tier"] == want["tier"]


# ---------------------------------------------------------------------------
# the analyzer


class TestAnalyzer:
    def test_characterize_known_distribution(self):
        # 10 requests over 3 canonical positions: 6/3/1
        base = 1700000000.0
        recs = []
        for i, (d, n) in enumerate([("a", 6), ("b", 3), ("c", 1)]):
            for j in range(n):
                recs.append({"t": base + len(recs) * 0.1, "digest": d,
                             "canonical": d, "tier": "interactive",
                             "outcome": "ok"})
        stats = wl.characterize(recs)
        assert stats["requests"] == 10
        assert stats["unique"] == 3
        assert stats["canonical_unique"] == 3
        assert stats["dup_ratio"] == 0.7
        assert stats["projected_hit_rate"] == 0.7
        assert stats["top_mass"]["1"] == 0.6
        assert stats["zipf_exponent"] is not None
        assert stats["interarrival"]["cv"] == 0.0        # metronome
        assert stats["interarrival"]["burstiness"] == -1.0
        assert stats["requests_per_sec"] == pytest.approx(10 / 0.9, rel=0.01)

    def test_symmetry_gain_separates_exact_and_canonical(self):
        recs = [{"t": i * 0.1, "digest": f"d{i}", "canonical": "same",
                 "outcome": "ok"} for i in range(4)]
        stats = wl.characterize(recs)
        assert stats["unique"] == 4
        assert stats["canonical_unique"] == 1
        assert stats["symmetry_dedup_gain"] == 4.0
        assert stats["projected_hit_rate"] == 0.0
        assert stats["projected_hit_rate_canonical"] == 0.75

    def test_empty_capture(self):
        assert wl.characterize([]) == {"requests": 0}
        assert "empty capture" in wl.format_workload({"requests": 0})

    def test_format_renders_all_sections(self, tmp_path):
        cap = str(tmp_path / "cap")
        TestCaptureReads()._write_capture(cap)
        text = wl.format_workload(wl.analyze_capture(cap))
        for needle in ("projected cache hit rate", "popularity",
                       "arrivals", "tiers", "replayable: True"):
            assert needle in text


# ---------------------------------------------------------------------------
# replay


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += max(dt, 0.0)


class _ScriptedEngine:
    """Instant-resolve engine; records what it saw."""

    def __init__(self, tiered=True):
        self.seen = []
        self.tiered = tiered

    def submit(self, packed, player, rank, timeout_s=None, tier=None):
        self.seen.append({"player": player, "rank": rank, "tier": tier})
        f = Future()
        f.set_result(np.float32(packed.sum()))
        return f


class TestReplay:
    def _trace(self, n=20, gap=0.05):
        rng = np.random.default_rng(5)
        boards = rand_packed(rng, 4)
        return [{"t": 100.0 + i * gap, "packed": boards[i % 4],
                 "player": 1 + i % 2, "rank": 5,
                 "tier": ("interactive", "selfplay", "batch")[i % 3]}
                for i in range(n)]

    def test_fake_clock_replay_is_exact(self):
        clk = _FakeClock()
        eng = _ScriptedEngine()
        report = rp.WorkloadReplayer(eng, self._trace(), speed=2.0,
                                     clock=clk, sleep=clk.sleep).run()
        assert report["requests"] == 20
        assert report["span_error_frac"] == 0.0
        assert report["mean_lag_ms"] == 0.0
        assert report["fidelity_ok"] is True
        # tier mix reproduced exactly, and the engine saw the tiers
        assert report["tiers"] == {"batch": 6, "interactive": 7,
                                   "selfplay": 7}
        assert [s["tier"] for s in eng.seen[:3]] \
            == ["interactive", "selfplay", "batch"]
        # recorded span 19*0.05 = 0.95s, replayed at 2x = 0.475s
        assert report["target_span_s"] == pytest.approx(0.475)

    def test_real_clock_fidelity_within_bar(self):
        # generous gaps (25ms) so scheduler overhead sits far inside the
        # 10% bar even on a loaded CI box
        report = rp.WorkloadReplayer(_ScriptedEngine(),
                                     self._trace(n=12, gap=0.025)).run()
        assert report["fidelity_ok"] is True
        assert report["span_error_frac"] <= 0.10
        assert report["lag_frac"] <= 0.10

    def test_untiered_target_still_served(self):
        class NoTier:
            def __init__(self):
                self.n = 0

            def submit(self, packed, player, rank, timeout_s=None):
                self.n += 1
                f = Future()
                f.set_result(np.float32(0))
                return f

        eng = NoTier()
        clk = _FakeClock()
        report = rp.WorkloadReplayer(eng, self._trace(), clock=clk,
                                     sleep=clk.sleep).run()
        assert eng.n == 20
        assert report["outcomes"] == {"ok": 20}

    def test_shed_and_failed_outcomes_counted(self):
        from deepgo_tpu.serving import EngineOverloaded

        class Flaky:
            def __init__(self):
                self.n = 0

            def submit(self, packed, player, rank, timeout_s=None,
                       tier=None):
                self.n += 1
                if self.n % 3 == 0:
                    raise EngineOverloaded("full")
                f = Future()
                if self.n % 3 == 1:
                    f.set_result(np.float32(0))
                else:
                    f.set_exception(RuntimeError("boom"))
                return f

        clk = _FakeClock()
        report = rp.WorkloadReplayer(Flaky(), self._trace(n=9), clock=clk,
                                     sleep=clk.sleep).run()
        assert report["outcomes"] == {"ok": 3, "shed": 3, "failed": 3}

    def test_empty_and_bad_speed_typed(self):
        with pytest.raises(ValueError):
            rp.WorkloadReplayer(_ScriptedEngine(), [])
        with pytest.raises(ValueError):
            rp.WorkloadReplayer(_ScriptedEngine(), self._trace(), speed=0)


# ---------------------------------------------------------------------------
# the synthetic generator


class TestSyntheticGenerator:
    def test_deterministic_from_seed(self):
        a = rp.build_synthetic_requests(SGF_DIR, requests=32, games=4,
                                        opening_moves=6, seed=11)
        b = rp.build_synthetic_requests(SGF_DIR, requests=32, games=4,
                                        opening_moves=6, seed=11)
        assert [x["t"] for x in a] == [y["t"] for y in b]
        assert [x["tier"] for x in a] == [y["tier"] for y in b]
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x["packed"], y["packed"])
        c = rp.build_synthetic_requests(SGF_DIR, requests=32, games=4,
                                        opening_moves=6, seed=12)
        assert [x["t"] for x in a] != [z["t"] for z in c]

    def test_opening_heavy_duplication(self, tmp_path):
        items = rp.build_synthetic_requests(SGF_DIR, requests=128, games=8,
                                            opening_moves=8, seed=0)
        cap = str(tmp_path / "cap")
        rp.write_synthetic_capture(cap, items)
        stats = wl.analyze_capture(cap)
        # the whole point: heavy duplication from the shared opening tree
        assert stats["dup_ratio"] > 0.4
        assert stats["projected_hit_rate"] > 0.4
        assert stats["top_mass"]["1"] > 0.1   # the empty board dominates
        assert stats["replayable"] is True
        assert set(stats["tiers"]) == {"interactive", "selfplay", "batch"}

    def test_missing_sgf_dir_typed(self, tmp_path):
        with pytest.raises(wl.WorkloadCaptureError):
            rp.build_synthetic_requests(str(tmp_path / "none"), requests=4)


# ---------------------------------------------------------------------------
# surfaces: cli workload / cli obs / bench block


class TestSurfaces:
    def test_cli_workload_analyze(self, tmp_path, capsys):
        from deepgo_tpu import cli

        cap = str(tmp_path / "cap")
        items = rp.build_synthetic_requests(SGF_DIR, requests=24, games=4,
                                            opening_moves=4, seed=2)
        rp.write_synthetic_capture(cap, items)
        cli.main(["workload", "analyze", cap])
        out = capsys.readouterr().out
        assert "projected cache hit rate" in out
        cli.main(["workload", "analyze", cap, "--json"])
        data = json.loads(capsys.readouterr().out)
        assert data["requests"] == 24
        assert data["replayable"] is True

    def test_cli_obs_workload_section(self, tmp_path, capsys):
        from deepgo_tpu.obs.report import format_report, summarize_run

        run = tmp_path / "run"
        cap = str(run / "workload")
        items = rp.build_synthetic_requests(SGF_DIR, requests=16, games=4,
                                            opening_moves=4, seed=4)
        rp.write_synthetic_capture(cap, items)
        summary = summarize_run(str(run))
        assert summary["workload"]["requests"] == 16
        assert "projected_hit_rate" in summary["workload"]
        text = format_report(summary)
        assert "workload" in text
        assert "projected cache hit rate" in text

    def test_watchlist_carries_workload_counter(self):
        from deepgo_tpu.obs.anomaly import DEFAULT_WATCHLIST

        specs = {s.metric: s for s in DEFAULT_WATCHLIST}
        assert "deepgo_workload_requests_total" in specs
        assert specs["deepgo_workload_requests_total"].mode == "counter_rate"

    @pytest.mark.slow
    def test_cli_record_then_replay_live(self, tmp_path, capsys):
        """The end-to-end witness: record a live fleet serving run,
        analyze it, replay it — request count and tier mix exact,
        timeline within the 10% bar."""
        from deepgo_tpu import cli

        cap = str(tmp_path / "cap")
        cli.main(["workload", "record", "--out", cap, "--requests", "48",
                  "--games", "4", "--opening-moves", "6", "--rate", "60",
                  "--fleet", "2", "--sgf-dir", SGF_DIR, "--json"])
        recorded = json.loads(capsys.readouterr().out)
        assert recorded["workload"]["requests"] == 48
        assert recorded["workload"]["replayable"] is True
        assert recorded["workload"]["dup_ratio"] > 0.2
        cli.main(["workload", "replay", cap, "--fleet", "2", "--json"])
        replayed = json.loads(capsys.readouterr().out)
        assert replayed["requests"] == 48
        assert replayed["mix_match"] is True
        assert replayed["tiers"] == recorded["workload"]["tiers"]
        assert replayed["fidelity_ok"] is True
        assert replayed["span_error_frac"] <= 0.10
