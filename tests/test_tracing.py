"""Request-scoped tracing (obs/tracing.py) + the cross-thread span handoff.

The load-bearing contracts:

  * spans: ``capture_context``/``attach_context`` carry a parent span
    across an explicit thread handoff (contextvars alone do not);
  * one trace id survives a supervisor restart replay AND a fleet
    failover, with the placement attempts recorded (routed events +
    failover hops), and results stay bitwise identical to the untraced
    path — observability never changes outcomes;
  * the exemplar sampler keeps bounded memory under sustained load while
    always retaining the slowest-k, p99+ outliers, and notable traces;
  * kept exemplars stream as ``trace_request`` JSONL records, ride the
    flight-recorder dump, and ``cli trace`` renders the waterfall;
  * the lineage chain (game -> segment -> window -> gate -> champion)
    reconstructs from the ``lineage_*`` event stream;
  * ``cli obs`` surfaces fleet/loop sections and the exemplar table.
"""

import json
import os
import random
import threading
import time

import numpy as np
import pytest

from deepgo_tpu.obs import tracing
from deepgo_tpu.obs.spans import attach_context, capture_context, span
from deepgo_tpu.serving import (EngineConfig, FleetConfig, FleetRouter,
                                InferenceEngine, SupervisedEngine,
                                SupervisorConfig)
from deepgo_tpu.utils import faults
from deepgo_tpu.utils.metrics import MetricsWriter


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    tracing.disable_tracing()
    yield
    tracing.disable_tracing()
    faults.reset()


def ok_forward(params, packed, player, rank):
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3)) \
        + 1000.0 * np.asarray(player, np.float32)


def boards(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


ECFG = EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
DIE_FAST = SupervisorConfig(max_restarts=0, backoff_base_s=0.001,
                            backoff_cap_s=0.005)
FAST_SUP = SupervisorConfig(backoff_base_s=0.001, backoff_cap_s=0.005)
FAST_FLEET = FleetConfig(respawn_base_s=0.001, respawn_cap_s=0.005)


def trace_records(sink_path):
    out = []
    with open(sink_path) as f:
        for line in f:
            r = json.loads(line)
            if r.get("kind") == "trace_request":
                out.append(r)
    return out


# ---------------------------------------------------------------------------
# satellite: cross-thread span parenting


class TestSpanHandoff:
    def test_plain_thread_detaches(self):
        """The regression the handoff fixes: without it, a worker
        thread's span roots a new tree."""
        seen = []

        def listener(r):
            seen.append(r)

        from deepgo_tpu.obs.spans import (add_span_listener,
                                          remove_span_listener)

        add_span_listener(listener)
        try:
            with span("parent"):
                def worker():
                    with span("child"):
                        pass

                t = threading.Thread(target=worker,
                                     name="tracing-test-detached",
                                     daemon=True)
                t.start()
                t.join()
        finally:
            remove_span_listener(listener)
        child = [r for r in seen if r["name"] == "child"][0]
        assert child["parent_id"] is None

    def test_capture_attach_crosses_thread(self):
        """The handoff: capture in the submitting thread, attach in the
        worker — the worker's span parents under the submitter's."""
        seen = []

        def listener(r):
            seen.append(r)

        from deepgo_tpu.obs.spans import (add_span_listener,
                                          remove_span_listener)

        add_span_listener(listener)
        try:
            with span("parent"):
                captured = capture_context()
                assert captured is not None

                def worker():
                    with attach_context(captured):
                        with span("child"):
                            pass
                    # context restored: a second span roots again
                    with span("after"):
                        pass

                t = threading.Thread(target=worker,
                                     name="tracing-test-handoff",
                                     daemon=True)
                t.start()
                t.join()
        finally:
            remove_span_listener(listener)
        parent = [r for r in seen if r["name"] == "parent"][0]
        child = [r for r in seen if r["name"] == "child"][0]
        after = [r for r in seen if r["name"] == "after"][0]
        assert child["parent_id"] == parent["span_id"]
        assert after["parent_id"] is None

    def test_trace_context_captures_parent_span(self):
        tracing.configure_tracing()
        with span("submitting"):
            from deepgo_tpu.obs.spans import current_span_id

            sid = current_span_id()
            ctx = tracing.start_request()
        assert ctx.parent_span == sid


# ---------------------------------------------------------------------------
# the recorder


class TestRecorder:
    def test_timeline_marks_and_idempotent_finish(self):
        rec = tracing.TraceRecorder()
        ctx = rec.start(tier="batch")
        ctx.mark("queued", engine="e")
        ctx.mark("dispatched", engine="e")
        ctx.mark("resolved", engine="e")
        ctx.set(bucket=4)
        ctx.finish("ok")
        ctx.finish("error", error="Late")  # second finish is a no-op
        s = rec.stats()
        assert s["started"] == s["finished"] == 1
        assert s["errors"] == 0 and s["incomplete"] == 0
        r = rec.exemplars()[0]
        assert r["tier"] == "batch" and r["bucket"] == 4
        assert [e["name"] for e in r["events"]] == [
            "queued", "dispatched", "resolved"]
        assert [e["t_ms"] for e in r["events"]] == \
            sorted(e["t_ms"] for e in r["events"])

    def test_incomplete_ok_timeline_counted(self):
        rec = tracing.TraceRecorder()
        ctx = rec.start()
        ctx.mark("queued")
        ctx.finish("ok")  # never dispatched/resolved
        assert rec.stats()["incomplete"] == 1

    def test_notable_traces_always_kept(self):
        rec = tracing.TraceRecorder(slowest_k=1)
        fast = rec.start()
        fast.mark("queued")
        fast.finish("ok")  # occupies the slowest-1 slot
        hopper = rec.start()
        hopper.hop(0, "EngineClosed")
        hopper.finish("ok")
        ids = {r["trace_id"] for r in rec.exemplars()}
        assert hopper.trace_id in ids
        assert rec.stats()["multi_hop"] == 1

    def test_bounded_memory_under_sustained_load(self):
        """50k synthetic finishes: every internal structure stays at its
        bound, the slowest requests are retained."""
        rec = tracing.TraceRecorder(slowest_k=4, ring_size=64,
                                    p99_window=512, window_s=3600.0)
        rng = np.random.default_rng(0)
        slow_ids = []
        for i in range(50_000):
            ctx = rec.start()
            ctx.mark("queued")
            ctx.mark("dispatched")
            ctx.mark("resolved")
            # synthetic duration: mostly fast, occasional huge outlier
            dur = float(rng.exponential(0.001))
            if i % 10_000 == 9_999:
                dur = 5.0 + i / 50_000
                slow_ids.append(ctx.trace_id)
            rec.record(ctx, dur, "ok", None)
            ctx._finished = True
        s = rec.stats()
        assert s["finished"] == 50_000
        assert len(rec._ring) <= 64
        assert len(rec._durations) <= 512
        assert len(rec._window_heap) <= 4
        kept = {r["trace_id"] for r in rec.exemplars()}
        # the very slowest of the run are in the ring (slowest-k window
        # never rotated: one 3600s window)
        assert slow_ids[-1] in kept

    def test_exemplars_stream_to_sink(self, tmp_path):
        from deepgo_tpu.obs import JsonlSink

        path = tmp_path / "trace.jsonl"
        with JsonlSink(str(path)) as sink:
            rec = tracing.TraceRecorder(sink=sink)
            ctx = rec.start(tier="interactive")
            ctx.mark("queued")
            ctx.mark("dispatched")
            ctx.mark("resolved")
            ctx.finish("ok")
        records = trace_records(path)
        assert len(records) == 1
        assert records[0]["trace_id"] == ctx.trace_id
        assert records[0]["status"] == "ok"


# ---------------------------------------------------------------------------
# serving-path integration


class TestEngineTracing:
    def test_untraced_by_default_zero_cost_path(self):
        eng = InferenceEngine(ok_forward, None, ECFG, name="plain")
        try:
            packed, players, ranks = boards(3)
            got = eng.evaluate(packed, players, ranks)
            assert np.array_equal(got.ravel(),
                                  ok_forward(None, packed, players,
                                             ranks).ravel())
        finally:
            eng.close()
        # nothing recorded anywhere: tracing was never armed
        assert tracing.get_trace_recorder() is None

    def test_complete_timeline_and_bitwise_parity(self):
        rec = tracing.configure_tracing()
        eng = InferenceEngine(ok_forward, None, ECFG, name="traced")
        try:
            packed, players, ranks = boards(4, seed=1)
            got = eng.evaluate(packed, players, ranks)
        finally:
            eng.close()
        tracing.disable_tracing()
        untraced = InferenceEngine(ok_forward, None, ECFG, name="bare")
        try:
            again = untraced.evaluate(packed, players, ranks)
        finally:
            untraced.close()
        assert np.array_equal(np.asarray(got), np.asarray(again))
        assert np.array_equal(
            np.asarray(got).ravel(),
            ok_forward(None, packed, players, ranks).ravel())
        s = rec.stats()
        assert s["started"] == 4
        assert s["orphans"] == 0 and s["incomplete"] == 0
        for r in rec.exemplars():
            names = [e["name"] for e in r["events"]]
            for needed in ("queued", "coalesced", "dispatched", "resolved"):
                assert needed in names, (needed, names)
            assert r["bucket"] in (1, 4)

    def test_trace_id_survives_supervisor_restart_replay(self):
        """THE continuity contract: a dispatcher death mid-request is
        replayed on the fresh engine under the SAME trace id, with the
        replay visible in the timeline, and the result bitwise identical
        to an untouched run."""
        rec = tracing.configure_tracing()
        faults.install("serving_dispatch:fail@2")
        sup = SupervisedEngine(
            lambda: InferenceEngine(ok_forward, None, ECFG, name="sup-t"),
            config=FAST_SUP, name="sup-t")
        try:
            packed, players, ranks = boards(6, seed=2)
            futs = [sup.submit(packed[i], int(players[i]), int(ranks[i]))
                    for i in range(6)]
            got = np.stack([np.atleast_1d(f.result(timeout=20))[0]
                            for f in futs])
        finally:
            sup.close()
        assert np.array_equal(got, ok_forward(None, packed, players, ranks))
        s = rec.stats()
        assert s["started"] == 6 and s["orphans"] == 0
        assert s["incomplete"] == 0
        replayed = [r for r in rec.exemplars()
                    if any(e["name"] == "replayed" for e in r["events"])]
        assert replayed, "the restart replay must appear in a timeline"
        r = replayed[0]
        assert r["status"] == "ok"
        names = [e["name"] for e in r["events"]]
        # one id, two submission legs: queued before and after the replay
        assert names.count("queued") >= 2
        assert names.index("replayed") < len(names) - 1
        assert "resolved" in names

    def test_trace_id_survives_fleet_failover_with_hops(self):
        """A replica death renders as a multi-hop trace: the failed
        placement is a hop (replica + error), the re-route a second
        routed event — same trace id front to back, results bitwise
        identical to the untraced forward."""
        rec = tracing.configure_tracing()
        faults.install("serving_dispatch:fail@2")

        def make_replica(i):
            return SupervisedEngine(
                lambda: InferenceEngine(ok_forward, None, ECFG,
                                        name=f"ft-rep{i}"),
                config=DIE_FAST, name=f"ft-rep{i}")

        fleet = FleetRouter(make_replica, 2, config=FAST_FLEET,
                            name="trace-fleet", rng=random.Random(0))
        try:
            packed, players, ranks = boards(12, seed=3)
            futs = [fleet.submit(packed[i], int(players[i]), int(ranks[i]),
                                 tier="selfplay")
                    for i in range(12)]
            got = np.stack([np.atleast_1d(f.result(timeout=20))[0]
                            for f in futs])
        finally:
            fleet.close()
        assert np.array_equal(got, ok_forward(None, packed, players, ranks))
        s = rec.stats()
        assert s["started"] == 12 and s["orphans"] == 0
        assert s["multi_hop"] >= 1
        hopped = [r for r in rec.exemplars() if r["hops"]]
        assert hopped
        r = hopped[0]
        assert r["status"] == "ok" and r["tier"] == "selfplay"
        hop = r["hops"][0]
        assert "replica" in hop and hop["error"]
        names = [e["name"] for e in r["events"]]
        # both placement attempts are on the timeline
        assert names.count("routed") >= 2
        assert "resolved" in names
        # the final server is a DIFFERENT replica than the hopped one
        routed = [e["replica"] for e in r["events"]
                  if e["name"] == "routed"]
        assert routed[-1] != hop["replica"]

    def test_flight_dump_carries_exemplar_ring(self, tmp_path):
        from deepgo_tpu.obs.sentinel import get_flight_recorder

        flight = get_flight_recorder()
        flight.configure(str(tmp_path))
        try:
            rec = tracing.configure_tracing()
            ctx = rec.start(tier="interactive")
            ctx.hop(1, "EngineClosed")
            ctx.finish("error", error="FailoverExhausted")
            path = flight.dump("test_incident")
            assert path is not None
            with open(path) as f:
                dump = json.load(f)
            section = dump["trace_exemplars"]
            assert section["stats"]["multi_hop"] == 1
            assert section["exemplars"][0]["trace_id"] == ctx.trace_id
            assert section["exemplars"][0]["hops"][0]["error"] \
                == "EngineClosed"
        finally:
            flight.close()


# ---------------------------------------------------------------------------
# offline reconstruction: cli trace + lineage


def write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")


class TestReconstruction:
    def test_waterfall_renders_sampled_exemplar(self, tmp_path, capsys):
        from deepgo_tpu.obs import JsonlSink

        run_dir = tmp_path
        with JsonlSink(str(run_dir / "trace.jsonl")) as sink:
            rec = tracing.TraceRecorder(sink=sink)
            ctx = rec.start(tier="interactive")
            ctx.mark("queued", fleet="f")
            ctx.mark("routed", replica=0)
            ctx.hop(0, "RestartsExhausted")
            ctx.mark("routed", replica=1)
            ctx.mark("coalesced", engine="rep1", batch=3, bucket=4)
            ctx.mark("dispatched", engine="rep1")
            ctx.mark("resolved", engine="rep1")
            ctx.set(bucket=4, replica=1)
            ctx.finish("ok")
        from deepgo_tpu.cli import main

        main(["trace", str(run_dir), ctx.trace_id[:6]])
        out = capsys.readouterr().out
        assert f"trace {ctx.trace_id}" in out
        assert "status=ok" in out and "hops=1" in out
        # chronological waterfall with the hop merged in
        import re

        names = re.findall(r"\+\s*[\d.]+ms\s+(\w+)", out)
        assert names == ["queued", "routed", "hop", "routed", "coalesced",
                         "dispatched", "resolved"]

    def test_lineage_chain_from_real_seal(self, tmp_path, capsys):
        """The provenance walk over a REAL buffer seal record plus the
        learner/gate/champion events keyed on one digest."""
        from deepgo_tpu.data.dataset import META_COLS, RECORD_SHAPE
        from deepgo_tpu.loop.replay import ReplayBuffer

        run_dir = tmp_path
        metrics = MetricsWriter(str(run_dir / "loop.jsonl"))
        buf = ReplayBuffer(str(run_dir / "buffer"), segment_games=2,
                           metrics=metrics)
        rng = np.random.default_rng(0)
        for g in range(2):
            m = 5 + g
            packed = rng.integers(0, 3, size=(m, *RECORD_SHAPE),
                                  dtype=np.uint8)
            meta = np.ones((m, META_COLS), np.int32)
            gid = buf.ingest_game(packed, meta, winner=1,
                                  source="actor-0")
            metrics.write("lineage_game", gid=gid, positions=m, winner=1,
                          source="actor-0", round=0)
        lo, hi, version = buf.extent()
        assert hi - lo == 11  # both games sealed
        digest = "abcd1234" * 8
        metrics.write("lineage_window", window=1, step0=0, step1=10,
                      extent=[lo, hi], version=version, scheme="game",
                      digest=digest, checkpoint="checkpoint-00000010.npz")
        metrics.write("lineage_gate", outcome="passed", digest=digest,
                      win_rate=0.625, games=16)
        metrics.write("lineage_champion", digest=digest, step=10,
                      path="champion.npz", source="gate")
        metrics.close()

        events = tracing.load_trace_events(str(run_dir))
        chain = tracing.build_lineage(events, "champion")
        assert chain is not None
        assert chain["champion"]["digest"] == digest
        assert chain["gate"]["outcome"] == "passed"
        assert chain["window"]["extent"] == [lo, hi]
        assert len(chain["segments"]) == 1
        assert len(chain["games"]) == 2
        # the digest prefix resolves the same chain
        assert tracing.build_lineage(events, digest[:8])["window"] \
            == chain["window"]
        from deepgo_tpu.cli import main

        main(["trace", str(run_dir), "champion"])
        out = capsys.readouterr().out
        assert "champion" in out and "window" in out
        assert "games   2 ingested by actor-0 (2)" in out

    def test_trace_listing_on_unknown_id(self, tmp_path, capsys):
        write_jsonl(tmp_path / "trace.jsonl", [
            {"kind": "trace_request", "trace_id": "feedbeef", "status": "ok",
             "duration_s": 0.01, "hops": [], "events": []}])
        from deepgo_tpu.cli import main

        main(["trace", str(tmp_path), "nope"])
        out = capsys.readouterr().out
        assert "no trace or lineage matches" in out
        assert "feedbeef" in out


# ---------------------------------------------------------------------------
# cli obs: fleet/loop sections + the exemplar table


class TestReportSections:
    def _snapshot(self):
        def counter(series):
            return {"kind": "counter", "help": "", "series": series}

        return {"kind": "obs_snapshot", "metrics": {
            "deepgo_fleet_failovers_total": counter({"fleet=f": 3}),
            "deepgo_fleet_respawns_total": counter({"fleet=f": 1}),
            "deepgo_fleet_reloads_total": counter({"fleet=f": 2}),
            "deepgo_fleet_shed_total": counter(
                {"fleet=f,reason=admission,tier=batch": 4}),
            "deepgo_serving_restarts_total": counter(
                {"engine=rep0": 2, "engine=rep1": 1}),
            "deepgo_loop_games_ingested_total": counter({"": 40}),
            "deepgo_loop_windows_trained_total": counter({"": 3}),
            "deepgo_loop_gates_passed_total": counter({"": 1}),
            "deepgo_loop_component_restarts_total": counter(
                {"component=actor": 2}),
            "deepgo_loop_learner_step": {
                "kind": "gauge", "help": "", "series": {"": 150.0}},
        }}

    def test_fleet_and_loop_sections(self, tmp_path):
        from deepgo_tpu.obs.report import summarize_run

        write_jsonl(tmp_path / "metrics.jsonl", [self._snapshot()])
        write_jsonl(tmp_path / "loop.jsonl", [
            {"kind": "fleet_respawn", "fleet": "f", "replica": 1,
             "attempt": 1, "total_respawns": 1},
            {"kind": "loop_restart", "component": "actor-0", "attempt": 1,
             "error": "x"},
            {"kind": "loop_close", "games_acked": 40, "games_durable": 40,
             "champion_step": 150},
        ])
        s = summarize_run(str(tmp_path))
        fleet = s["events"]["fleet"]
        assert fleet["failovers"] == 3
        assert fleet["respawns"] == 1
        assert fleet["reloads"] == 2
        assert fleet["shed"] == {"fleet=f,reason=admission,tier=batch": 4}
        assert fleet["replica_restarts"] == {"engine=rep0": 2,
                                             "engine=rep1": 1}
        assert fleet["respawns_by_replica"] == {"1": 1}
        loop = s["events"]["loop"]
        assert loop["games_ingested"] == 40
        assert loop["windows_trained"] == 3
        assert loop["gates_passed"] == 1
        assert loop["component_restarts"] == {"component=actor": 2}
        assert loop["learner_step"] == 150
        assert loop["games_durable"] == 40

    def test_exemplar_table(self, tmp_path):
        from deepgo_tpu.obs.report import format_report, summarize_run

        write_jsonl(tmp_path / "trace.jsonl", [
            {"kind": "trace_request", "trace_id": f"id{i:02d}",
             "status": "ok", "tier": "interactive", "replica": i % 2,
             "bucket": 4, "duration_s": 0.001 * (i + 1),
             "hops": [{"replica": 0, "error": "EngineClosed",
                       "t_ms": 1.0}] if i == 11 else [],
             "events": [{"name": "queued", "t_ms": 0.0}]}
            for i in range(12)])
        s = summarize_run(str(tmp_path))
        ex = s["exemplars"]
        assert len(ex) == 10  # top-10 of 12
        assert ex[0]["trace_id"] == "id11"  # slowest first
        assert ex[0]["hops"] == 1
        rendered = format_report(s)
        assert "slowest requests" in rendered
        assert "id11" in rendered

    def test_loop_sections_without_snapshot(self, tmp_path):
        """A loop run has no obs_snapshot: the sections build from the
        event stream alone."""
        from deepgo_tpu.obs.report import summarize_run

        write_jsonl(tmp_path / "loop.jsonl", [
            {"kind": "loop_ingest", "gid": 0, "positions": 9, "winner": 1,
             "source": "actor-0"},
            {"kind": "loop_window", "window": 1, "step0": 0, "step1": 50},
            {"kind": "loop_gate", "outcome": "passed", "win_rate": 0.6},
        ])
        s = summarize_run(str(tmp_path))
        loop = s["events"]["loop"]
        assert loop["games_ingested"] == 1
        assert loop["windows_trained"] == 1
        assert loop["gates_passed"] == 1
