"""Shape-bucketed micro-batching engine tests (deepgo_tpu.serving).

The two load-bearing properties:
  * padded+masked engine outputs are BIT-identical (``==``, not allclose)
    to a direct unpadded forward, for every bucket size — padding is a
    pure throughput move with zero numerical consequence;
  * after warming the ladder, a selfplay run with mixed game lengths
    performs zero additional XLA compilations — asserted via the jitted
    forward's compile-cache counter.
Plus the lifecycle contract: dispatcher death surfaces on the next
submit() (the AsyncLoader worker-death pattern), and close() drains or
cancels pending futures instead of hanging.
"""

import threading
import time

import numpy as np
import pytest

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.models.serving import make_log_prob_fn, make_policy_fn
from deepgo_tpu.serving import (BucketLadder, EngineBusy, EngineClosed,
                                EngineConfig, EngineError, InferenceEngine,
                                bucketed_forward, ladder_for, policy_engine)


def tiny():
    cfg = ModelConfig(num_layers=2, channels=8)
    return cfg, init(jax.random.key(0), cfg)


def boards(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


class TestBucketLadder:
    def test_bucket_for(self):
        ladder = BucketLadder((1, 8, 32))
        assert ladder.bucket_for(1) == 1
        assert ladder.bucket_for(2) == 8
        assert ladder.bucket_for(8) == 8
        assert ladder.bucket_for(9) == 32
        with pytest.raises(ValueError):
            ladder.bucket_for(33)
        with pytest.raises(ValueError):
            ladder.bucket_for(0)

    def test_plan_covers_and_chunks(self):
        ladder = BucketLadder((1, 8, 32))
        assert ladder.plan(5) == [(0, 5, 8)]
        assert ladder.plan(32) == [(0, 32, 32)]
        # oversize: full top-rung chunks (unpadded) + padded remainder
        assert ladder.plan(70) == [(0, 32, 32), (32, 32, 32), (64, 6, 8)]

    def test_invalid_buckets_rejected(self):
        with pytest.raises(ValueError):
            BucketLadder(())
        with pytest.raises(ValueError):
            BucketLadder((0, 8))

    def test_ladder_for_trims_and_keeps_full(self):
        assert ladder_for(32).buckets == (1, 8, 32)
        assert ladder_for(3).buckets == (1, 8)
        # fleets over the top rung keep the full ladder and chunk
        assert ladder_for(600).buckets == (1, 8, 32, 128, 512)

    def test_pad_is_noop_on_rung(self):
        ladder = BucketLadder((4,))
        p, pl, rk = boards(4)
        out = ladder.pad(p, pl, rk, 4)
        assert out[0] is p and out[1] is pl and out[2] is rk


class TestBitwiseParity:
    """Engine log-probs must equal a direct make_policy_fn call with ==."""

    def test_every_bucket_bitwise_identical(self):
        cfg, params = tiny()
        predict = make_policy_fn(cfg, top_k=1)
        buckets = (1, 4, 16)
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=buckets,
                                               max_wait_ms=0.0)) as engine:
            engine.warmup()
            for n in (1, 2, 3, 4, 5, 16):
                packed, players, ranks = boards(n, seed=n)
                direct = np.asarray(
                    predict(params, packed, players, ranks)["log_probs"])
                got = engine.evaluate(packed, players, ranks)
                assert np.array_equal(got, direct), f"n={n} not bit-identical"

    def test_one_request_into_largest_bucket(self):
        # the worst-case pad: a single board into the top rung must still
        # be bitwise the unpadded single-row forward
        cfg, params = tiny()
        predict = make_policy_fn(cfg, top_k=1)
        packed, players, ranks = boards(1, seed=7)
        direct = np.asarray(
            predict(params, packed, players, ranks)["log_probs"])
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=(32,))) as engine:
            got = engine.evaluate(packed, players, ranks)
        assert np.array_equal(got, direct)

    def test_oversize_batch_chunks_bitwise(self):
        # more rows than the top rung: plan() splits into chunks, rows
        # still bitwise equal to the whole-batch direct forward
        cfg, params = tiny()
        fwd = make_log_prob_fn(cfg)
        packed, players, ranks = boards(11, seed=3)
        direct = np.asarray(fwd(params, packed, players, ranks))
        got = bucketed_forward(
            lambda pk, pl, rk: fwd(params, pk, pl, rk),
            packed, players, ranks, BucketLadder((1, 4)))
        assert np.array_equal(got, direct)


class TestZeroRecompile:
    def test_mixed_length_selfplay_never_recompiles(self):
        # the acceptance criterion: warm the ladder, then play games that
        # finish at different plies (measured lengths for this seed are
        # spread over ~3..14 moves), so the live fleet shrinks through
        # many sizes — and the compile counter must not move
        from deepgo_tpu.selfplay import self_play

        cfg, params = tiny()
        engine = policy_engine(
            params, cfg, config=EngineConfig(buckets=(1, 2, 4, 8)))
        try:
            assert engine.warmup() == 4
            warm = engine.compile_cache_size()
            assert warm == 4
            games, stats = self_play(params, cfg, n_games=6, max_moves=40,
                                     temperature=1.0, pass_threshold=2.6e-3,
                                     seed=3, engine=engine)
            lengths = {len(g.moves) for g in games}
            assert len(lengths) > 2, f"lengths not mixed: {sorted(lengths)}"
            assert engine.compile_cache_size() == warm, \
                "selfplay triggered XLA recompilation after warmup"
            assert stats["engine"]["dispatches"] > 0
        finally:
            engine.close()

    def test_direct_ladder_path_never_recompiles(self):
        # the threadless bucketed_forward path (agents without an engine)
        # holds the same property: every request count 1..top rung maps
        # onto the warmed shapes
        cfg, params = tiny()
        fwd = make_log_prob_fn(cfg)
        ladder = BucketLadder((1, 2, 4, 8))
        for b in ladder.buckets:  # warmup
            bucketed_forward(lambda pk, pl, rk: fwd(params, pk, pl, rk),
                             *boards(b), ladder)
        warm = fwd._cache_size()
        for n in range(1, 9):
            bucketed_forward(lambda pk, pl, rk: fwd(params, pk, pl, rk),
                             *boards(n, seed=n), ladder)
        assert fwd._cache_size() == warm


class TestLifecycle:
    def test_dispatcher_death_surfaces_on_next_submit(self):
        # mirror of the AsyncLoader worker-death contract: the in-flight
        # request's future carries the error, and every later submit()
        # raises instead of deadlocking its waiter. (A FORWARD exception
        # no longer kills the dispatcher — that's batch containment,
        # tests/test_supervisor.py — so death is injected at the
        # dispatch-loop fault point, outside the containment.)
        from deepgo_tpu.utils import faults

        faults.install("serving_dispatch:fail@1")
        try:
            engine = InferenceEngine(
                lambda p, pk, pl, rk: np.zeros(len(pk), np.float32), None,
                EngineConfig(buckets=(4,), max_wait_ms=0.0))
            f = engine.submit(*_one_board())
            with pytest.raises(faults.InjectedFailure):
                f.result(timeout=5)
            deadline = time.monotonic() + 5
            while engine._thread.is_alive() and time.monotonic() < deadline:
                time.sleep(0.01)
            with pytest.raises(EngineError, match="dispatcher thread died"):
                engine.submit(*_one_board())
            engine.close()  # must not hang on a dead dispatcher
        finally:
            faults.reset()

    def test_forward_error_contained_to_its_batch(self):
        # one exploding dispatch fails typed (cause attached) and the
        # dispatcher keeps serving later submitters
        from deepgo_tpu.serving import BatchDispatchError

        calls = {"n": 0}

        def flaky(params, packed, player, rank):
            calls["n"] += 1
            if calls["n"] == 1:
                raise ValueError("model exploded")
            return np.zeros(len(packed), np.float32)

        engine = InferenceEngine(flaky, None,
                                 EngineConfig(buckets=(4,), max_wait_ms=0.0))
        try:
            f = engine.submit(*_one_board())
            with pytest.raises(BatchDispatchError) as ei:
                f.result(timeout=5)
            assert isinstance(ei.value.__cause__, ValueError)
            assert engine.submit(*_one_board()).result(timeout=5).shape == ()
            assert engine.stats()["dispatch_failures"] == 1
        finally:
            engine.close()

    def test_wedged_close_is_loud_not_silent(self, capfd):
        # a dispatcher that won't exit by the close deadline must be
        # visible: stderr warning + stats flag, not a clean-looking return
        release = threading.Event()
        entered = threading.Event()

        def slow(params, packed, player, rank):
            entered.set()
            assert release.wait(10)
            return np.zeros(len(packed), np.float32)

        engine = InferenceEngine(slow, None,
                                 EngineConfig(buckets=(1,), max_wait_ms=0.0))
        f = engine.submit(*_one_board())
        assert entered.wait(5)  # dispatcher now stuck inside the forward
        engine.close(timeout=0.2)
        assert engine.stats()["dispatcher_wedged"] is True
        assert "did not exit" in capfd.readouterr().err
        release.set()  # let the wedged thread finish; its future resolves
        assert f.result(timeout=5).shape == ()

    def test_close_drains_pending_futures(self):
        cfg, params = tiny()
        engine = policy_engine(
            params, cfg, config=EngineConfig(buckets=(1, 4), max_wait_ms=0.0))
        futures = [engine.submit(*_one_board(seed=i)) for i in range(6)]
        engine.close(drain=True)
        for f in futures:
            assert f.result(timeout=1).shape == (361,)

    def test_close_cancels_pending_futures(self):
        release = threading.Event()
        entered = threading.Event()

        def slow(params, packed, player, rank):
            entered.set()
            assert release.wait(10)
            return np.zeros((len(packed), 361), dtype=np.float32)

        engine = InferenceEngine(slow, None,
                                 EngineConfig(buckets=(1,), max_wait_ms=0.0))
        in_flight = engine.submit(*_one_board())
        assert entered.wait(5)  # dispatcher is now stuck inside forward
        pending = [engine.submit(*_one_board(seed=i)) for i in range(3)]

        closer = threading.Thread(target=lambda: engine.close(drain=False))
        closer.start()
        deadline = time.monotonic() + 5
        while not engine._closing.is_set() and time.monotonic() < deadline:
            time.sleep(0.01)
        release.set()
        closer.join(timeout=10)
        assert not closer.is_alive(), "close() hung"

        assert in_flight.result(timeout=1).shape == (361,)
        for f in pending:
            with pytest.raises(EngineClosed):
                f.result(timeout=1)
        with pytest.raises(EngineClosed):
            engine.submit(*_one_board())

    def test_per_request_timeout(self):
        release = threading.Event()
        entered = threading.Event()

        def slow(params, packed, player, rank):
            entered.set()
            assert release.wait(10)
            return np.zeros((len(packed), 361), dtype=np.float32)

        engine = InferenceEngine(slow, None,
                                 EngineConfig(buckets=(1,), max_wait_ms=0.0))
        try:
            first = engine.submit(*_one_board())
            assert entered.wait(5)
            # queued behind the stuck dispatch with an already-short
            # deadline: by the time it dispatches it must fail, not run
            doomed = engine.submit(*_one_board(seed=1), timeout_s=0.01)
            time.sleep(0.05)
            entered.clear()
            release.set()
            assert first.result(timeout=5).shape == (361,)
            with pytest.raises(TimeoutError, match="expired"):
                doomed.result(timeout=5)
            assert engine.stats()["timeouts"] == 1
        finally:
            release.set()
            engine.close()

    def test_backpressure_queue_full(self):
        release = threading.Event()
        entered = threading.Event()

        def slow(params, packed, player, rank):
            entered.set()
            assert release.wait(10)
            return np.zeros((len(packed), 361), dtype=np.float32)

        engine = InferenceEngine(
            slow, None,
            EngineConfig(buckets=(1,), max_wait_ms=0.0, max_queue=2))
        try:
            engine.submit(*_one_board())          # in flight
            assert entered.wait(5)
            engine.submit(*_one_board(seed=1))    # queue slot 1
            engine.submit(*_one_board(seed=2))    # queue slot 2
            with pytest.raises(EngineBusy, match="queue full"):
                engine.submit(*_one_board(seed=3), block=False)
        finally:
            release.set()
            engine.close()


class TestStats:
    def test_stats_shape_and_accounting(self):
        cfg, params = tiny()
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=(1, 4),
                                               max_wait_ms=0.0)) as engine:
            engine.warmup()
            for n in (1, 3, 4):
                engine.evaluate(*boards(n, seed=n))
            s = engine.stats()
        assert s["boards"] == 8
        assert s["dispatches"] == sum(s["bucket_hits"].values())
        assert 0 < s["occupancy"] <= 1
        assert s["p50_ms"] is not None and s["p99_ms"] >= s["p50_ms"]
        assert s["warm_shapes"] == 2
        assert s["boards_per_sec"] > 0

    def test_warmup_seeds_admission_latency_prior(self):
        # under a tight-deadline flood, queued requests expire before any
        # dispatch succeeds — if warmup left the latency window empty the
        # admission estimate would stay None and the door could never
        # shed. Warmup's timed post-compile forwards are the prior.
        cfg, params = tiny()
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=(1, 4),
                                               max_wait_ms=0.0)) as engine:
            assert engine.dispatch_p50_s() is None
            assert engine.window_p50_s() is None
            engine.warmup()
            assert engine.dispatch_p50_s() > 0
            # the max-bucket rung seeded the full-window cost too
            assert engine.window_p50_s() > 0

    def test_window_p50_tracks_full_windows_not_the_mix(self):
        # a backlog drains in max-bucket windows; 1-board interactive
        # dispatches must not collapse the admission cost-per-window
        cfg, params = tiny()
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=(1, 4),
                                               max_wait_ms=0.0)) as engine:
            with engine._lock:
                engine._dispatch_secs.extend([0.001] * 40)  # 1-board mix
                engine._window_secs.extend([0.05] * 4)      # full windows
            assert engine.dispatch_p50_s() == pytest.approx(0.001)
            assert engine.window_p50_s() == pytest.approx(0.05)
            # before any full window has run, fall back to the mix
            with engine._lock:
                engine._window_secs.clear()
            assert engine.window_p50_s() == pytest.approx(0.001)

    def test_metrics_writer_records(self, tmp_path):
        from deepgo_tpu.utils.metrics import MetricsWriter, read_jsonl

        cfg, params = tiny()
        writer = MetricsWriter(str(tmp_path / "serving.jsonl"))
        engine = policy_engine(
            params, cfg, metrics=writer,
            config=EngineConfig(buckets=(1, 4), max_wait_ms=0.0,
                                metrics_interval=1))
        engine.evaluate(*boards(3))
        engine.close()
        writer.close()
        records = read_jsonl(str(tmp_path / "serving.jsonl"))
        kinds = {r["kind"] for r in records}
        assert "serving" in kinds and "serving_close" in kinds
        assert records[-1]["boards"] == 3


class TestAgentsOnEngine:
    def test_policy_agent_engine_path_matches_direct(self):
        from deepgo_tpu.agents import PolicyAgent
        from deepgo_tpu.selfplay import legal_mask

        cfg, params = tiny()
        packed, players, _ = boards(5, seed=9)
        legal = legal_mask(packed, players)
        with policy_engine(params, cfg,
                           config=EngineConfig(buckets=(1, 8))) as engine:
            on_engine = PolicyAgent(params, cfg, engine=engine)
            direct = PolicyAgent(params, cfg)
            got = on_engine._legal_log_probs(packed, players, legal)
            want = direct._legal_log_probs(packed, players, legal)
        assert np.array_equal(got, want)


def _one_board(seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(9, 19, 19), dtype=np.uint8), 1, 5)
