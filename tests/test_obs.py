"""Observability subsystem: registry, spans, exporter, sink, report.

The ISSUE-5 coverage contract: registry concurrency (N threads hammering
one counter, exact total), histogram percentile snapshots against known
data, exporter /metrics + /healthz round-trip on an ephemeral port, span
nesting/exception capture, and the sink's rotation boundary — plus the
MetricsWriter back-compat shim and the profiling trace guard.
"""

import json
import threading
import urllib.error
import urllib.request

import pytest

from deepgo_tpu.obs import (JsonlSink, MetricsRegistry, ObsExporter,
                            get_registry, health_from_ledger,
                            render_prometheus, sink_files, span, trace_to)
from deepgo_tpu.obs.report import format_report, read_events, summarize_run
from deepgo_tpu.utils.metrics import MetricsWriter, read_jsonl


# ---- registry ----


class TestRegistry:
    def test_counter_concurrent_increments_exact_total(self):
        reg = MetricsRegistry()
        c = reg.counter("hammer_total")
        n_threads, per_thread = 8, 5000

        def hammer():
            for _ in range(per_thread):
                c.inc(worker="shared")

        threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value(worker="shared") == n_threads * per_thread

    def test_histogram_concurrent_observes_exact_count(self):
        reg = MetricsRegistry()
        h = reg.histogram("conc_seconds", buckets=(0.5, 1.0, 2.0))

        def observe():
            for i in range(2000):
                h.observe((i % 3) * 0.7)

        threads = [threading.Thread(target=observe) for _ in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert h.snapshot()["count"] == 12000

    def test_counter_labels_are_independent_series(self):
        reg = MetricsRegistry()
        c = reg.counter("labeled_total")
        c.inc(engine="a")
        c.inc(2, engine="b")
        c.inc()
        assert c.value(engine="a") == 1
        assert c.value(engine="b") == 2
        assert c.value() == 1

    def test_counter_rejects_decrease(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="cannot decrease"):
            reg.counter("mono_total").inc(-1)

    def test_gauge_set_and_callback(self):
        reg = MetricsRegistry()
        g = reg.gauge("depth")
        g.set(3)
        g.set_function(lambda: 7, queue="live")
        assert g.value() == 3
        assert g.value(queue="live") == 7
        # a raising callback reads as 0.0, never a scrape crash
        g.set_function(lambda: 1 / 0, queue="dying")
        assert g.value(queue="dying") == 0.0

    def test_histogram_percentiles_against_known_data(self):
        # buckets at every integer: each value 1..100 owns a bucket, so
        # interpolation is exact and percentiles are the textbook answer
        reg = MetricsRegistry()
        h = reg.histogram("known_seconds",
                          buckets=tuple(float(i) for i in range(1, 101)))
        for v in range(1, 101):
            h.observe(float(v))
        snap = h.snapshot()
        assert snap["count"] == 100
        assert snap["min"] == 1.0 and snap["max"] == 100.0
        assert snap["p50"] == pytest.approx(50.0)
        assert snap["p95"] == pytest.approx(95.0)
        assert snap["p99"] == pytest.approx(99.0)
        assert snap["mean"] == pytest.approx(50.5)

    def test_histogram_single_bucket_pins_to_observed_extremes(self):
        reg = MetricsRegistry()
        h = reg.histogram("coarse_seconds", buckets=(1000.0,))
        for v in (2.0, 3.0, 4.0):
            h.observe(v)
        snap = h.snapshot()
        # everything sits in one bucket; min/max clamp the interpolation
        assert 2.0 <= snap["p50"] <= 4.0
        assert snap["p99"] <= 4.0

    def test_histogram_overflow_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("small_seconds", buckets=(1.0,))
        h.observe(0.5)
        h.observe(99.0)  # beyond the last edge -> +Inf bucket
        snap = h.snapshot()
        assert snap["count"] == 2 and snap["max"] == 99.0
        assert snap["p99"] <= 99.0

    def test_get_or_create_same_kind_returns_same_metric(self):
        reg = MetricsRegistry()
        assert reg.counter("x_total") is reg.counter("x_total")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_invalid_metric_name_rejected(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="invalid metric name"):
            reg.counter("bad name!")

    def test_snapshot_is_json_serializable(self):
        reg = MetricsRegistry(clock=lambda: 123.0)
        reg.counter("a_total").inc(engine="e")
        reg.histogram("b_seconds").observe(0.01)
        snap = reg.snapshot()
        assert snap["time"] == 123.0
        rt = json.loads(json.dumps(snap))
        assert rt["metrics"]["a_total"]["series"]["engine=e"] == 1

    def test_histogram_time_context_with_fake_clock(self):
        reg = MetricsRegistry()
        h = reg.histogram("timed_seconds", buckets=(1.0, 5.0, 10.0))
        ticks = iter([10.0, 13.0])
        with h.time(clock=lambda: next(ticks)):
            pass
        snap = h.snapshot()
        assert snap["count"] == 1
        assert snap["min"] == 3.0


# ---- prometheus rendering ----


def test_render_prometheus_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests").inc(3, engine="a")
    reg.gauge("depth").set(2)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = render_prometheus(reg)
    assert '# TYPE req_total counter' in text
    assert 'req_total{engine="a"} 3' in text
    assert "depth 2" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text


def test_render_prometheus_histogram_le_contract_per_labeled_series():
    # the external-Prometheus quantile contract (ISSUE-6 satellite): every
    # labeled series gets its own cumulative, monotone `le=` ladder whose
    # +Inf bucket equals its _count, plus matching _sum — histogram_quantile
    # over a scrape must be computable without this process's help
    import re

    reg = MetricsRegistry()
    h = reg.histogram("disp_seconds", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.7, 5.0, 50.0):
        h.observe(v, engine="a")
    h.observe(0.01, engine="b")
    text = render_prometheus(reg)
    assert "# TYPE disp_seconds histogram" in text
    for engine, expect in (("a", [1, 3, 4, 5]), ("b", [1, 1, 1, 1])):
        pat = re.compile(
            rf'disp_seconds_bucket\{{engine="{engine}",le="([^"]+)"\}} (\d+)')
        ladder = [(le, int(c)) for le, c in pat.findall(text)]
        assert [le for le, _ in ladder] == ["0.1", "1", "10", "+Inf"]
        counts = [c for _, c in ladder]
        assert counts == expect                       # cumulative...
        assert counts == sorted(counts)               # ...and monotone
        assert f'disp_seconds_count{{engine="{engine}"}} {expect[-1]}' \
            in text
    assert 'disp_seconds_sum{engine="a"} 56.25' in text
    assert 'disp_seconds_sum{engine="b"} 0.01' in text


# ---- exporter ----


def _get(url):
    with urllib.request.urlopen(url, timeout=5) as r:
        return r.status, r.read().decode()


class TestExporter:
    def test_metrics_and_healthz_round_trip_on_ephemeral_port(self):
        reg = MetricsRegistry()
        reg.counter("rt_total").inc(5)
        reg.histogram("rt_seconds", buckets=(0.1, 1.0)).observe(0.05)
        with ObsExporter(port=0, registry=reg) as exp:
            assert exp.port != 0
            status, body = _get(exp.url + "/metrics")
            assert status == 200
            assert "rt_total 5" in body
            assert 'rt_seconds_bucket{le="0.1"} 1' in body
            status, body = _get(exp.url + "/healthz")
            assert status == 200
            assert json.loads(body)["healthy"] is True

    def test_healthz_flips_to_503_when_a_component_degrades(self):
        with ObsExporter(port=0, registry=MetricsRegistry()) as exp:
            healthy = {"ok": True}
            exp.add_health("engine", lambda: {"healthy": healthy["ok"]})
            assert _get(exp.url + "/healthz")[0] == 200
            healthy["ok"] = False
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(exp.url + "/healthz")
            assert e.value.code == 503
            payload = json.loads(e.value.read().decode())
            assert payload["healthy"] is False
            assert payload["components"]["engine"]["healthy"] is False

    def test_raising_health_check_reads_unhealthy_not_crash(self):
        with ObsExporter(port=0, registry=MetricsRegistry()) as exp:
            exp.add_health("dying", lambda: 1 / 0)
            payload, healthy = exp.check_health()
            assert healthy is False
            assert "ZeroDivisionError" in payload["components"]["dying"]["error"]

    def test_unknown_path_404(self):
        with ObsExporter(port=0, registry=MetricsRegistry()) as exp:
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(exp.url + "/nope")
            assert e.value.code == 404

    def test_close_is_idempotent(self):
        exp = ObsExporter(port=0, registry=MetricsRegistry())
        exp.close()
        exp.close()

    def test_healthz_from_heartbeat_ledger_flips_within_budget(self):
        # the acceptance shape: a killed peer's silence crosses
        # interval x miss_budget and /healthz flips to 503
        from deepgo_tpu.parallel.liveness import (HeartbeatLedger,
                                                  HeartbeatWriter)

        import tempfile

        d = tempfile.mkdtemp()
        now = {"t": 100.0}
        clock = lambda: now["t"]  # noqa: E731
        writer = HeartbeatWriter(d, 1, clock=clock)
        ledger = HeartbeatLedger(d, interval_s=1.0, miss_budget=3,
                                 clock=clock, log=lambda m: None)
        writer.beat(step=5)
        with ObsExporter(port=0, registry=MetricsRegistry()) as exp:
            exp.add_health("heartbeats", health_from_ledger(
                ledger, lambda: {1}))
            assert _get(exp.url + "/healthz")[0] == 200
            now["t"] += 3.5  # one heartbeat miss-budget, and no more beats
            with pytest.raises(urllib.error.HTTPError) as e:
                _get(exp.url + "/healthz")
            assert e.value.code == 503
            payload = json.loads(e.value.read().decode())
            assert payload["components"]["heartbeats"]["lost_process_id"] == 1


# ---- JSONL sink / MetricsWriter shim ----


class TestSink:
    def test_rotation_boundary_loses_no_records(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        # each record is ~45 bytes; a 120-byte cap forces rotations mid-run
        with JsonlSink(path, max_bytes=120, max_files=20) as sink:
            for i in range(40):
                sink.write("ev", i=i)
        files = sink_files(path, max_files=20)
        assert len(files) > 1  # rotation actually happened
        records = read_events(path)
        assert [r["i"] for r in records] == list(range(40))

    def test_rotation_retention_drops_oldest(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with JsonlSink(path, max_bytes=60, max_files=2) as sink:
            for i in range(30):
                sink.write("ev", i=i)
        files = sink_files(path, max_files=10)
        assert len(files) <= 3  # path + at most max_files rotations
        records = read_events(path)
        assert records[-1]["i"] == 29  # newest records always survive

    def test_metrics_writer_is_backcompat_shim(self, tmp_path):
        path = str(tmp_path / "metrics.jsonl")
        w = MetricsWriter(path)
        w.write("train", step=1, loss=0.5)
        w.close()
        w.close()  # idempotent: the satellite contract
        rows = read_jsonl(path)
        assert rows[0]["kind"] == "train" and rows[0]["step"] == 1
        assert "time" in rows[0]

    def test_metrics_writer_context_manager(self, tmp_path):
        path = str(tmp_path / "metrics.jsonl")
        with MetricsWriter(path) as w:
            w.write("summary", ewma=1.0)
        assert w.closed
        assert read_jsonl(path)[0]["kind"] == "summary"

    def test_write_after_close_raises(self, tmp_path):
        w = MetricsWriter(str(tmp_path / "m.jsonl"))
        w.close()
        with pytest.raises(ValueError, match="closed"):
            w.write("ev")


# ---- spans ----


class TestSpans:
    def test_nesting_parent_ids_and_stream(self, tmp_path):
        sink = JsonlSink(str(tmp_path / "trace.jsonl"))
        with trace_to(sink):
            with span("outer", step=3):
                with span("inner"):
                    pass
        sink.close()
        records = read_events(str(tmp_path / "trace.jsonl"))
        inner, outer = records  # inner closes (and streams) first
        assert inner["name"] == "inner" and outer["name"] == "outer"
        assert inner["parent_id"] == outer["span_id"]
        assert outer["parent_id"] is None
        assert outer["step"] == 3
        assert inner["status"] == outer["status"] == "ok"
        assert inner["duration_s"] >= 0

    def test_exception_capture_and_propagation(self, tmp_path):
        sink = JsonlSink(str(tmp_path / "trace.jsonl"))
        with trace_to(sink):
            with pytest.raises(ValueError, match="boom"):
                with span("failing"):
                    raise ValueError("boom")
        sink.close()
        rec = read_events(str(tmp_path / "trace.jsonl"))[0]
        assert rec["status"] == "error"
        assert "boom" in rec["error"]

    def test_spans_feed_registry_histogram(self):
        reg = MetricsRegistry()
        with span("staged", registry=reg):
            pass
        snap = reg.histogram("deepgo_span_seconds").snapshot(
            name="staged", status="ok")
        assert snap is not None and snap["count"] == 1

    def test_trace_to_restores_previous_sink(self, tmp_path):
        from deepgo_tpu.obs import get_trace_sink

        before = get_trace_sink()
        with trace_to(JsonlSink(str(tmp_path / "t.jsonl"))):
            assert get_trace_sink() is not before or before is None
        assert get_trace_sink() is before

    def test_span_without_sink_is_silent(self):
        with span("unsunk"):
            pass  # no sink configured: must not raise


# ---- profiling trace guard (satellite) ----


class TestProfilingTraceGuard:
    def test_raised_start_trace_attempts_cleanup_and_propagates(
            self, monkeypatch, tmp_path):
        import jax

        from deepgo_tpu.utils.profiling import trace

        calls = []
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d: (_ for _ in ()).throw(RuntimeError("profiler busy")))
        monkeypatch.setattr(jax.profiler, "stop_trace",
                            lambda: calls.append("stop"))
        with pytest.raises(RuntimeError, match="profiler busy"):
            with trace(str(tmp_path / "t")):
                pass
        assert calls == ["stop"]  # no dangling profiler state

    def test_trace_logs_output_dir_to_metrics(self, monkeypatch, tmp_path):
        import jax

        from deepgo_tpu.utils.profiling import trace

        monkeypatch.setattr(jax.profiler, "start_trace", lambda d: None)
        monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
        m = MetricsWriter(str(tmp_path / "m.jsonl"))
        with trace(str(tmp_path / "tb"), metrics=m):
            pass
        m.close()
        rows = read_jsonl(str(tmp_path / "m.jsonl"))
        assert rows[0]["kind"] == "profile_trace"
        assert rows[0]["out_dir"].endswith("tb")

    def test_trace_none_is_noop(self):
        from deepgo_tpu.utils.profiling import trace

        with trace(None):
            pass


# ---- offline report ----


class TestReport:
    def _fake_run(self, tmp_path) -> str:
        run = tmp_path / "run"
        run.mkdir()
        with JsonlSink(str(run / "metrics.jsonl")) as m:
            m.write("train", step=10, loss=0.4, ewma=0.5,
                    samples_per_sec=100.0)
            m.write("train", step=20, loss=0.3, ewma=0.4,
                    samples_per_sec=120.0)
            m.write("validation", step=20, cost=0.35, accuracy=0.42, n=64)
            reg = MetricsRegistry()
            reg.histogram("deepgo_loader_wait_seconds").observe(0.002)
            reg.counter("deepgo_train_steps_total").inc(20)
            m.write("obs_snapshot", metrics=reg.snapshot()["metrics"])
        with JsonlSink(str(run / "trace.jsonl")) as t:
            with trace_to(t):
                with span("validate", step=20):
                    pass
        with JsonlSink(str(run / "elastic-0000.jsonl")) as e:
            e.write("host_lost", host=0, process_id=1)
            e.write("recovery", host=0, process_id=1, steps_lost=5,
                    recovery_latency_s=2.5, detect_latency_s=1.0)
        return str(run)

    def test_summarize_joins_all_three_streams(self, tmp_path):
        summary = summarize_run(self._fake_run(tmp_path))
        assert summary["stages"]["train"]["last_step"] == 20
        assert summary["stages"]["loader_wait"]["count"] == 1
        assert summary["stages"]["span:validate"]["count"] == 1
        assert summary["stages"]["validation"]["best_cost"] == 0.35
        assert summary["events"]["elastic"]["recoveries"] == 1
        assert summary["events"]["elastic"]["steps_lost_total"] == 5
        assert summary["events"]["counters"][
            "deepgo_train_steps_total"] == 20

    def test_format_report_renders_table(self, tmp_path):
        text = format_report(summarize_run(self._fake_run(tmp_path)))
        assert "loader_wait" in text
        assert "span:validate" in text
        assert "elastic:" in text

    def test_report_tolerates_empty_run_dir(self, tmp_path):
        summary = summarize_run(str(tmp_path))
        assert summary["stages"] == {}
        assert "no stage data" in format_report(summary)

    def test_report_tolerates_torn_final_line(self, tmp_path):
        run = tmp_path / "run"
        run.mkdir()
        with open(run / "metrics.jsonl", "w") as f:
            f.write(json.dumps({"kind": "train", "step": 5, "loss": 1.0,
                                "ewma": 1.0, "samples_per_sec": 9.0}) + "\n")
            f.write('{"kind": "train", "step": 10, "lo')  # killed mid-write
        summary = summarize_run(str(run))
        assert summary["stages"]["train"]["last_step"] == 5

    def test_cli_obs_subcommand(self, tmp_path, capsys):
        from deepgo_tpu.cli import main

        run = self._fake_run(tmp_path)
        main(["obs", run])
        out = capsys.readouterr().out
        assert "loader_wait" in out
        main(["obs", run, "--json"])
        out = capsys.readouterr().out
        assert json.loads(out)["stages"]["train"]["last_step"] == 20


# ---- default registry wiring ----


def test_default_registry_is_process_wide():
    assert get_registry() is get_registry()
    # the built-in instrumentation points register here on import
    c = get_registry().counter("deepgo_obs_selftest_total")
    c.inc()
    assert c.value() >= 1
