"""Self-play driver tests (CPU, random policy)."""

import numpy as np

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.selfplay import self_play, to_sgf
from deepgo_tpu import sgf
from deepgo_tpu.data.transcribe import transcribe_game


def test_selfplay_produces_legal_games(tmp_path):
    cfg = ModelConfig(num_layers=2, channels=8)
    params = init(jax.random.key(0), cfg)
    games, stats = self_play(params, cfg, n_games=3, max_moves=40, seed=1)
    assert stats["games"] == 3
    assert stats["positions"] > 0
    for g in games:
        assert g.done
        assert 0 < len(g.moves) <= 40
        # every played point was empty at the time => replay never raises
        from deepgo_tpu.go import new_board, play

        stones, age = new_board()
        for m in g.moves:
            play(stones, age, m.x, m.y, m.player)


def test_selfplay_sgf_roundtrip_through_transcription(tmp_path):
    """Self-play games feed back into our own transcription pipeline."""
    cfg = ModelConfig(num_layers=2, channels=8)
    params = init(jax.random.key(0), cfg)
    games, _ = self_play(params, cfg, n_games=1, max_moves=30, seed=2)
    p = tmp_path / "g.sgf"
    p.write_text(to_sgf(games[0]))
    parsed = sgf.parse_file(str(p))
    assert [(m.player, m.x, m.y) for m in parsed.moves] == [
        (m.player, m.x, m.y) for m in games[0].moves
    ]
    packed, meta = transcribe_game(str(p), engine="python")
    assert packed.shape[0] == len(games[0].moves)


def test_selfplay_temperature_sampling():
    cfg = ModelConfig(num_layers=2, channels=8)
    params = init(jax.random.key(0), cfg)
    g1, _ = self_play(params, cfg, n_games=1, max_moves=15, temperature=1.0, seed=3)
    g2, _ = self_play(params, cfg, n_games=1, max_moves=15, temperature=1.0, seed=4)
    # different seeds explore different moves
    assert [m.x for m in g1[0].moves] != [m.x for m in g2[0].moves]
