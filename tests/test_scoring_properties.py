"""Property tests for Tromp-Taylor scoring against a brute-force oracle."""

import numpy as np

from deepgo_tpu.go import BLACK, EMPTY, WHITE
from deepgo_tpu.go.board import SIZE, neighbors
from deepgo_tpu.go.scoring import area_score


def brute_force_score(stones):
    """Independent implementation: per empty point, BFS the reachable
    colors; the point scores for a color iff only that color is reachable."""
    black = int((stones == BLACK).sum())
    white = int((stones == WHITE).sum())
    for x in range(SIZE):
        for y in range(SIZE):
            if stones[x, y] != EMPTY:
                continue
            seen = {(x, y)}
            stack = [(x, y)]
            colors = set()
            while stack:
                p = stack.pop()
                for n in neighbors(*p):
                    v = stones[n]
                    if v == EMPTY:
                        if n not in seen:
                            seen.add(n)
                            stack.append(n)
                    else:
                        colors.add(int(v))
            if colors == {BLACK}:
                black += 1
            elif colors == {WHITE}:
                white += 1
    return black, white


def random_board(rng, fill):
    return rng.choice(
        np.array([EMPTY, BLACK, WHITE], dtype=np.uint8),
        size=(SIZE, SIZE),
        p=[1 - fill, fill / 2, fill / 2],
    )


def test_matches_brute_force_on_random_boards():
    rng = np.random.default_rng(0)
    for fill in (0.0, 0.05, 0.3, 0.7, 0.95):
        for _ in range(8):
            stones = random_board(rng, fill)
            s = area_score(stones, komi=0.0)
            assert (s.black, s.white) == brute_force_score(stones), (
                f"mismatch at fill={fill}"
            )


def test_color_swap_symmetry():
    rng = np.random.default_rng(1)
    for _ in range(10):
        stones = random_board(rng, 0.4)
        swapped = stones.copy()
        swapped[stones == BLACK] = WHITE
        swapped[stones == WHITE] = BLACK
        s, t = area_score(stones, komi=0.0), area_score(swapped, komi=0.0)
        assert (s.black, s.white) == (t.white, t.black)


def test_totals_bounded_by_board():
    rng = np.random.default_rng(2)
    for _ in range(10):
        s = area_score(random_board(rng, 0.5), komi=0.0)
        assert 0 <= s.black + s.white <= SIZE * SIZE
