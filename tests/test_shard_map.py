"""shard_map + explicit psum DP step: numerics identical to the
sharding-propagation path and to single-device execution."""

import numpy as np
import pytest

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.parallel import data_sharding, make_mesh, replicated_sharding
from deepgo_tpu.parallel.shard_map_step import (make_shard_map_train_step,
                                                shard_map_available)
from deepgo_tpu.training import make_train_step, sgd

from test_parallel import _batch

pytestmark = [
    pytest.mark.skipif(len(jax.devices()) < 8,
                       reason="needs 8 (virtual) devices"),
    pytest.mark.skipif(not shard_map_available(),
                       reason="installed jax exposes no shard_map"),
]


def test_shard_map_matches_spmd_path():
    cfg = ModelConfig(num_layers=3, channels=16, compute_dtype="float32")
    opt = sgd(0.05, rate_decay=1e-4)
    mesh = make_mesh(8, 1)

    p_a = jax.device_put(init(jax.random.key(0), cfg), replicated_sharding(mesh))
    s_a = jax.device_put(opt.init(p_a), replicated_sharding(mesh))
    p_b, s_b = jax.tree.map(lambda x: x.copy(), (p_a, s_a))

    spmd_step = make_train_step(cfg, opt)
    explicit_step = make_shard_map_train_step(cfg, opt, mesh)

    for i in range(3):
        batch = jax.device_put(_batch(seed=i), data_sharding(mesh))
        p_a, s_a, loss_a = spmd_step(p_a, s_a, batch)
        batch = jax.device_put(_batch(seed=i), data_sharding(mesh))
        p_b, s_b, loss_b = explicit_step(p_b, s_b, batch)
        assert float(loss_a) == pytest.approx(float(loss_b), rel=1e-6), i

    for a, b in zip(jax.tree.leaves(p_a), jax.tree.leaves(p_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-7)
