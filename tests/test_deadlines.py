"""Deadline-wrapped bootstrap and first-step guard (parallel/deadlines.py).

Everything is driven with injected arm/sleep/rng fakes — no real watchdog
children are spawned and no test sleeps; the one real-watchdog integration
path (arm + SIGKILL) is pinned in test_watchdog.py / test_graft_entry.py.
"""

import pytest

from deepgo_tpu.parallel import deadlines, distributed
from deepgo_tpu.parallel.liveness import CoordinatorUnreachable
from deepgo_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


class FakeArm:
    """Records arm/disarm pairs; stands in for utils.watchdog.arm."""

    def __init__(self):
        self.armed: list[tuple] = []
        self.disarmed = 0

    def __call__(self, label, timeout_s, diagnostic_json=None):
        self.armed.append((label, timeout_s))
        outer = self

        class Handle:
            def disarm(self):
                outer.disarmed += 1

        return Handle()


def test_deadline_arms_and_always_disarms():
    arm = FakeArm()
    with deadlines.deadline("claim", 7.5, arm=arm):
        assert arm.armed == [("claim", 7.5)]
        assert arm.disarmed == 0
    assert arm.disarmed == 1
    # the fuse must not survive an exception either
    with pytest.raises(RuntimeError):
        with deadlines.deadline("boom", 2.0, arm=arm):
            raise RuntimeError("x")
    assert arm.disarmed == 2


def test_deadline_zero_timeout_disables():
    arm = FakeArm()
    with deadlines.deadline("off", 0.0, arm=arm):
        pass
    with deadlines.deadline("off", -1.0, arm=arm):
        pass
    assert arm.armed == []  # nothing armed, nothing to kill


def test_initialize_single_process_is_still_a_noop():
    arm = FakeArm()
    deadlines.initialize_with_deadline(num_processes=1, timeout_s=30.0,
                                       arm=arm)
    # the watchdog covered the (instant) local path and was disarmed
    assert arm.armed and arm.disarmed == len(arm.armed)


def test_unreachable_coordinator_retried_with_full_jitter(monkeypatch):
    calls = {"n": 0}

    def refuse_twice(coordinator, num_processes, process_id):
        calls["n"] += 1
        if calls["n"] <= 2:
            raise ConnectionRefusedError("dial 127.0.0.1:1 refused")

    monkeypatch.setattr(distributed, "initialize", refuse_twice)
    slept: list[float] = []

    class Rng:  # deterministic full-jitter draws at the top of the envelope
        def uniform(self, lo, hi):
            return hi

    deadlines.initialize_with_deadline(
        "127.0.0.1:1", 2, 0, timeout_s=60.0, attempts=5, base_delay=0.5,
        max_delay=8.0, rng=Rng(), sleep=slept.append, arm=FakeArm())
    assert calls["n"] == 3
    # full-jitter: each sleep drawn from U(0, base * 2**k); Rng pins the top
    assert slept == [0.5, 1.0]


def test_unreachable_coordinator_exhausts_typed(monkeypatch):
    def always_refuse(coordinator, num_processes, process_id):
        raise ConnectionRefusedError("nobody home")

    monkeypatch.setattr(distributed, "initialize", always_refuse)
    arm = FakeArm()
    with pytest.raises(CoordinatorUnreachable, match="10.0.0.7:1234"):
        deadlines.initialize_with_deadline(
            "10.0.0.7:1234", 2, 0, timeout_s=60.0, attempts=3,
            sleep=lambda s: None, arm=arm)
    # ONE watchdog spans the whole retry envelope, and it was disarmed
    assert arm.armed == [("dist-init(10.0.0.7:1234)", 60.0)]
    assert arm.disarmed == 1


def test_dist_init_transients_absorbed_by_retry():
    faults.install("dist_init:transient@2")
    deadlines.initialize_with_deadline(num_processes=1, timeout_s=30.0,
                                       sleep=lambda s: None, arm=FakeArm())
    # both injected transients absorbed; the bootstrap completed


def test_dist_init_hard_fault_surfaces_unretried():
    faults.install("dist_init:fail@1")
    slept: list[float] = []
    with pytest.raises(faults.InjectedFailure):
        deadlines.initialize_with_deadline(
            num_processes=1, timeout_s=30.0, sleep=slept.append,
            arm=FakeArm())
    assert slept == []  # a logic-level fault is not a dial to re-try


def test_guard_first_call_arms_exactly_once():
    import jax.numpy as jnp

    arm = FakeArm()
    calls = {"n": 0}

    def step(x):
        calls["n"] += 1
        return jnp.asarray(x) * 2

    guarded = deadlines.guard_first_call(step, "first-step", 30.0, arm=arm)
    assert float(guarded(3)) == 6.0
    assert arm.armed == [("first-step", 30.0)] and arm.disarmed == 1
    for x in (4, 5):
        guarded(x)
    assert calls["n"] == 3
    assert arm.armed == [("first-step", 30.0)]  # later calls pass through


def test_guard_first_call_failed_first_call_stays_guarded():
    arm = FakeArm()
    attempts = {"n": 0}

    def step():
        attempts["n"] += 1
        if attempts["n"] == 1:
            raise RuntimeError("compile blew up")
        return 1

    guarded = deadlines.guard_first_call(step, "first", 10.0, arm=arm)
    with pytest.raises(RuntimeError):
        guarded()
    assert arm.disarmed == 1  # no leaked fuse
    assert guarded() == 1     # the RETRY is still the guarded first call
    assert len(arm.armed) == 2
