"""Test configuration.

JAX-related env vars must be set before jax is first imported anywhere, so
they are set here at conftest import time: tests run on the CPU backend with
8 virtual devices, the TPU-native analogue of testing multi-device code
without a cluster (SURVEY.md section 4).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# The terminal's axon sitecustomize force-registers the tunneled TPU and
# overrides JAX_PLATFORMS at interpreter start; pin the config back to CPU
# before any backend initializes so tests never run over the tunnel.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

REFERENCE_DATA = "/root/reference/data"


def reference_available() -> bool:
    return os.path.isdir(REFERENCE_DATA)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running integration tests (e.g. the subprocess "
        "kill-and-resume path); tier-1 excludes them via -m 'not slow', "
        "`make verify-faults` includes them",
    )
