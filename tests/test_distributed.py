"""Multi-host scaffolding (parallel/distributed.py), single-process paths.

Real multi-process DCN runs need a pod; what is testable here is every
code path a single process exercises: the initialize() no-op, hybrid mesh
layout over the 8 virtual devices, per-host batch arithmetic, and global
array assembly from process-local data.
"""

import numpy as np

import jax

from deepgo_tpu.parallel import distributed


def test_initialize_single_process_is_noop():
    # must not raise and must not try to reach a coordinator
    distributed.initialize()
    distributed.initialize(num_processes=1)


def test_hybrid_mesh_spans_all_devices():
    mesh = distributed.hybrid_mesh(n_model=2)
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (len(jax.devices()) // 2, 2)
    # hosts-major ordering: device ids ascend within the data axis
    ids = [[d.id for d in row] for row in mesh.devices]
    assert ids == sorted(ids)


def test_per_host_batch_divides_evenly(monkeypatch):
    import pytest

    monkeypatch.setattr(distributed.jax, "process_count", lambda: 4)
    assert distributed.per_host_batch(256) == 64
    with pytest.raises(AssertionError):
        distributed.per_host_batch(254)  # not divisible by 4 processes


def test_global_array_from_local_roundtrip():
    mesh = distributed.hybrid_mesh(n_model=1)
    n = mesh.devices.size
    local = {
        "packed": np.arange(n * 9 * 19 * 19, dtype=np.uint8).reshape(
            n, 9, 19, 19),
        "target": np.arange(n, dtype=np.int32),
    }
    out = distributed.global_array_from_local(mesh, local)
    assert out["packed"].shape == (n, 9, 19, 19)
    assert out["target"].sharding.spec == jax.sharding.PartitionSpec("data")
    np.testing.assert_array_equal(np.asarray(out["target"]), local["target"])
