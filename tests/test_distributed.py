"""Multi-host scaffolding (parallel/distributed.py), single-process paths.

Real multi-process DCN runs need a pod; what is testable here is every
code path a single process exercises: the initialize() no-op, hybrid mesh
layout over the 8 virtual devices, per-host batch arithmetic, and global
array assembly from process-local data.
"""

import numpy as np

import jax

from deepgo_tpu.parallel import distributed
from deepgo_tpu.parallel.liveness import ConfigError


def test_initialize_single_process_is_noop():
    # must not raise and must not try to reach a coordinator
    distributed.initialize()
    distributed.initialize(num_processes=1)


def test_hybrid_mesh_spans_all_devices():
    mesh = distributed.hybrid_mesh(n_model=2)
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (len(jax.devices()) // 2, 2)
    # hosts-major ordering: device ids ascend within the data axis
    ids = [[d.id for d in row] for row in mesh.devices]
    assert ids == sorted(ids)


def test_per_host_batch_divides_evenly(monkeypatch):
    import pytest

    monkeypatch.setattr(distributed.jax, "process_count", lambda: 4)
    assert distributed.per_host_batch(256) == 64
    # typed, not assert (asserts vanish under python -O); names both numbers
    with pytest.raises(ConfigError, match=r"254.*4"):
        distributed.per_host_batch(254)  # not divisible by 4 processes


def test_per_host_batch_rebalances_over_survivors():
    import pytest

    # the elastic recovery path passes the SURVIVING count explicitly
    assert distributed.per_host_batch(256, process_count=2) == 128
    with pytest.raises(ConfigError, match=r"256.*3"):
        distributed.per_host_batch(256, process_count=3)
    with pytest.raises(ConfigError):
        distributed.per_host_batch(256, process_count=0)


class FakeDevice:
    """Stand-in for a jax Device on a simulated multi-host topology."""

    def __init__(self, process_index: int, device_id: int):
        self.process_index = process_index
        self.id = device_id

    def __repr__(self):
        return f"fake(p{self.process_index}/d{self.id})"


def fake_pod(hosts: int, per_host: int) -> list:
    return [FakeDevice(p, p * per_host + i)
            for p in range(hosts) for i in range(per_host)]


def test_hybrid_mesh_data_axis_is_hosts_major_2x4():
    """Satellite: for a simulated 2-host x 4-device layout the data axis
    must be hosts-major — all of host 0's devices before any of host 1's,
    intra-host neighbors adjacent (they stay on ICI; the host boundary is
    the only DCN hop)."""
    import random

    devices = fake_pod(hosts=2, per_host=4)
    random.Random(7).shuffle(devices)  # discovery order is no contract
    mesh = distributed.hybrid_mesh(n_model=1, devices=devices)
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.shape == (8, 1)
    flat = [d for row in mesh.devices for d in row]
    assert [(d.process_index, d.id) for d in flat] == [
        (p, p * 4 + i) for p in range(2) for i in range(4)]
    # and with a model axis: each model-parallel pair lives on ONE host
    mesh2 = distributed.hybrid_mesh(n_model=2, devices=fake_pod(2, 4))
    assert mesh2.devices.shape == (4, 2)
    for row in mesh2.devices:
        assert len({d.process_index for d in row}) == 1


def test_hybrid_mesh_processes_filter_remeshes_survivors():
    """The re-mesh entry point: restricting to the surviving process set
    keeps only their devices (hosts-major ordering preserved)."""
    import pytest

    devices = fake_pod(hosts=3, per_host=2)
    mesh = distributed.hybrid_mesh(n_model=1, devices=devices,
                                   processes={0, 2})
    flat = [d for row in mesh.devices for d in row]
    assert [(d.process_index, d.id) for d in flat] == [
        (0, 0), (0, 1), (2, 4), (2, 5)]
    with pytest.raises(ConfigError, match="no devices"):
        distributed.hybrid_mesh(n_model=1, devices=devices, processes={9})
    with pytest.raises(ConfigError, match="n_model"):
        distributed.hybrid_mesh(n_model=4, devices=fake_pod(1, 2))


def test_two_process_train_step():
    """REAL multi-process run: two local processes join a coordinator
    (jax.distributed.initialize), build the hybrid mesh across processes,
    assemble a global batch from per-process shards, and take one
    data-parallel train step whose gradient all-reduce crosses the process
    boundary (round-1 verdict item 6 — previously only single-process
    no-op paths were exercised)."""
    import os
    import socket
    import subprocess
    import sys

    from conftest import REPO_ROOT

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    child = os.path.join(REPO_ROOT, "tests", "distributed_child.py")
    # hermetic env: no relay sitecustomize, no inherited device pins
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")}
    procs = [
        subprocess.Popen(
            [sys.executable, child, str(i), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            cwd=REPO_ROOT, env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=240)
            if ("Multiprocess computations aren't implemented" in err
                    and p.returncode != 0):
                # this jax build can form the multi-process runtime but not
                # execute cross-process collectives on CPU; the real DCN
                # path needs a pod (parallel/elastic.py simulates hosts
                # through the shared filesystem for exactly this reason)
                import pytest

                pytest.skip("CPU backend lacks multiprocess collectives")
            assert p.returncode == 0, err[-3000:]
            outs.append(out)
    finally:
        # a failing child must not orphan its peer blocked on the
        # coordinator (it would tie up the port for jax's connect timeout)
        for q in procs:
            if q.poll() is None:
                q.kill()
    losses = []
    for out in outs:
        line = [l for l in out.splitlines() if l.startswith("DIST_OK")][0]
        losses.append(float(line.split("loss=")[1]))
    # both processes computed the same globally-reduced loss
    assert losses[0] == losses[1]
    assert np.isfinite(losses[0]) and losses[0] > 0


def test_global_array_from_local_roundtrip():
    mesh = distributed.hybrid_mesh(n_model=1)
    n = mesh.devices.size
    local = {
        "packed": np.arange(n * 9 * 19 * 19, dtype=np.uint8).reshape(
            n, 9, 19, 19),
        "target": np.arange(n, dtype=np.int32),
    }
    out = distributed.global_array_from_local(mesh, local)
    assert out["packed"].shape == (n, 9, 19, 19)
    assert out["target"].sharding.spec == jax.sharding.PartitionSpec("data")
    np.testing.assert_array_equal(np.asarray(out["target"]), local["target"])
