"""CLI override parsing (the reference's torch.CmdLine + prototype tables)."""

import pytest

from deepgo_tpu.cli import parse_overrides


def test_overrides_dispatch_on_default_value_types():
    out = parse_overrides([
        "batch_size=64", "rate=0.5", "augment=true", "name=sweep",
        "channel_schedule=128,64", "rate_decay=1e-6",
    ])
    assert out == {"batch_size": 64, "rate": 0.5, "augment": True,
                   "name": "sweep", "channel_schedule": "128,64",
                   "rate_decay": 1e-6}
    assert type(out["batch_size"]) is int
    assert type(out["augment"]) is bool


def test_overrides_bool_falsey_spellings():
    assert parse_overrides(["augment=0"]) == {"augment": False}
    assert parse_overrides(["augment=no"]) == {"augment": False}
    assert parse_overrides(["augment=1"]) == {"augment": True}


def test_overrides_unknown_field_rejected():
    with pytest.raises(SystemExit):
        parse_overrides(["no_such_field=1"])


def test_overrides_bad_int_raises():
    with pytest.raises(ValueError):
        parse_overrides(["batch_size=many"])


def test_resume_flags_mutually_exclusive():
    from deepgo_tpu.cli import main

    with pytest.raises(SystemExit, match="mutually exclusive"):
        main(["train", "--iters", "1",
              "--resume", "x.npz", "--auto-resume", "rundir"])
