"""Golden parity tests: our pipeline vs the reference's transcribed records.

The SGF corpus under data/sgf/ was reconstructed from the reference's bundled
per-move records (tools/reconstruct_sgfs.py). Replaying those games through
our rules engine must reproduce the reference's packed planes bit-exact.
Full verification of all 4,398 positions runs in ~11 s; the default test run
checks the two small splits completely plus a sampled sweep of every train
game. Set DEEPGO_GOLDEN_FULL=1 to verify every position of every game.
"""

import os

import numpy as np
import pytest

import t7reader
from conftest import REFERENCE_DATA, reference_available
from deepgo_tpu import sgf
from deepgo_tpu.go import replay_positions

pytestmark = pytest.mark.skipif(
    not reference_available(), reason="reference dataset not mounted"
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FULL = os.environ.get("DEEPGO_GOLDEN_FULL") == "1"


def _games(split):
    base = os.path.join(REPO, "data/sgf", split)
    for root, _, files in os.walk(base):
        for f in sorted(files):
            yield os.path.join(root, f), os.path.relpath(os.path.join(root, f), base)


def _check_game(sgf_path, ref_dir, stride=1):
    from deepgo_tpu.go import new_board, play, summarize

    game = sgf.parse_file(sgf_path)
    stones, age = new_board()
    for h in game.handicaps:
        play(stones, age, h.x, h.y, h.player)
    checked = 0
    for k, move in enumerate(game.moves, start=1):
        # summarize only sampled positions — it dominates the runtime — but
        # replay every move so the board state stays exact.
        if stride == 1 or k % stride == 1:
            packed = summarize(stones, age)
            ref = t7reader.load(os.path.join(ref_dir, str(k)))
            assert ref["move"] == {
                "player": move.player,
                "x": move.x + 1,
                "y": move.y + 1,
            }, (sgf_path, k)
            assert tuple(ref["ranks"][i] for i in (1, 2)) == game.ranks, sgf_path
            if not np.array_equal(packed, ref["input"]):
                bad = [
                    c for c in range(9) if not np.array_equal(packed[c], ref["input"][c])
                ]
                raise AssertionError(f"{sgf_path} move {k}: packed channels {bad} differ")
            checked += 1
        play(stones, age, move.x, move.y, move.player)
    assert checked > 0
    return checked


@pytest.mark.parametrize("split", ["validation", "test"])
def test_small_splits_fully_bit_exact(split):
    for sgf_path, rel in _games(split):
        ref_dir = os.path.join(REFERENCE_DATA, split, rel)
        _check_game(sgf_path, ref_dir)


def test_train_split_bit_exact():
    stride = 1 if FULL else 7  # sampled sweep still touches every game
    total = 0
    for sgf_path, rel in _games("train"):
        ref_dir = os.path.join(REFERENCE_DATA, "train", rel)
        total += _check_game(sgf_path, ref_dir, stride=stride)
    assert total >= (4139 if FULL else 500)
