"""Expert-iteration loop suite (deepgo_tpu/loop, docs/loop.md).

Covers the four components and their composition:

  * replay buffer — durable ingest (acked == survives), sealing +
    window-versioned index, crash recovery as a pure function of the
    directory, bounded eviction that never crosses a live cursor,
    logical-index gathers, the loop_ingest fault site;
  * continuous learner — deterministic windowed streams, the checkpointed
    read cursor, and THE resume property: grow the corpus mid-run, kill
    the learner mid-window, auto-resume, and the resumed stream is
    bit-identical to an uninterrupted run over the same ingestion
    schedule (in-process crash + slow subprocess SIGKILL variants);
  * arena gatekeeper — standard_gate protocol pins, the deterministic
    50%-self-match rejection, pass → atomic champion publish + fleet
    reload, corrupt challengers rejected before they touch serving,
    the loop_gate fault site;
  * the service — one full in-process loop turn (selfplay → ingest →
    train window → gate pass → fleet hot-reload) with zero lost games,
    the `make verify-loop` acceptance shape.
"""

import dataclasses
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from deepgo_tpu import match
from deepgo_tpu.experiments import ExperimentConfig
from deepgo_tpu.experiments import checkpoint as ckpt
from deepgo_tpu.loop import (ArenaGatekeeper, ContinuousLearner,
                             ExpertIterationLoop, GateRejected, LoopConfig,
                             LoopStalled, ReplayBuffer, ReplayError,
                             count_durable_games, params_digest,
                             read_windows, replay_window)
from deepgo_tpu.loop.replay import GAMES_DIR
from deepgo_tpu.utils import faults

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TINY = ExperimentConfig(name="loop-test", num_layers=2, channels=8,
                        batch_size=8, rate=0.05, seed=3)


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.install("")


def synth_game(gid: int, moves: int = 10):
    """Deterministic synthetic records keyed on gid alone, so two buffers
    fed the same schedule hold byte-identical segments."""
    r = np.random.default_rng(gid + 1000)
    packed = r.integers(0, 3, size=(moves, 9, 19, 19)).astype(np.uint8)
    meta = np.zeros((moves, 6), np.int32)
    meta[:, 0] = r.integers(1, 3, size=moves)
    meta[:, 1] = r.integers(0, 19, size=moves)
    meta[:, 2] = r.integers(0, 19, size=moves)
    meta[:, 3] = 8
    meta[:, 4] = 8
    return packed, meta


def fill(buffer: ReplayBuffer, start: int, n: int, winner_of=None) -> None:
    for g in range(start, start + n):
        winner = winner_of(g) if winner_of else 1 + g % 2
        buffer.ingest_game(*synth_game(g), winner=winner)


def make_policy_checkpoint(path: str, seed: int = 0,
                           step: int = 0) -> None:
    """A loadable, verifiable policy checkpoint at TINY scale."""
    import jax

    from deepgo_tpu.models import policy_cnn
    from deepgo_tpu.training.optimizers import OPTIMIZERS

    cfg = TINY.replace(seed=seed)
    params = policy_cnn.init(jax.random.key(seed), cfg.model_config())
    optimizer = OPTIMIZERS[cfg.optimizer](cfg.rate, cfg.rate_decay,
                                          cfg.momentum)
    ckpt.save_checkpoint(path, params, optimizer.init(params), {
        "id": f"test-{seed}", "step": step, "validation_history": [],
        "config": cfg.to_dict()})


# ---------------------------------------------------------------------------
# replay buffer


class TestReplayBuffer:
    def test_ingest_seal_version_and_gather(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=2)
        fill(buf, 0, 5)
        # 5 games at 2/segment: two seals happened, one game still open
        assert buf.version == 2
        assert buf.stats()["open_games"] == 1
        lo, hi, version = buf.extent()
        assert (lo, version) == (0, 2)
        view = buf.view(lo, hi)
        assert len(view) == hi - lo
        # gather a known game bit-exactly through its logical range
        packed0, meta0 = synth_game(0)
        start, count = view.game_ranges[0]
        assert count == packed0.shape[0]
        got_packed, player, rank, target = view.batch_at(
            np.arange(start, start + count))
        np.testing.assert_array_equal(got_packed, packed0)
        np.testing.assert_array_equal(player, meta0[:, 0])
        np.testing.assert_array_equal(rank, np.full(count, 8))
        np.testing.assert_array_equal(
            target, meta0[:, 1] * 19 + meta0[:, 2])

    def test_reopen_recovers_sealed_and_open(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=2)
        fill(buf, 0, 5)
        stats = buf.stats()
        buf2 = ReplayBuffer(str(tmp_path), segment_games=2)
        assert buf2.stats() == stats
        assert buf2.total_games == 5
        # the open game seals after reopen, proving it truly survived
        buf2.seal()
        assert buf2.stats()["open_games"] == 0
        assert buf2.extent()[1] > stats["sealed_hi"]

    def test_torn_seal_and_stale_game_recovery(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=2)
        fill(buf, 0, 4)
        # debris: a half-built segment dir the index never committed, and
        # a stale game file at a gid the watermark says is already sealed
        os.makedirs(tmp_path / "seg-000099")
        (tmp_path / "seg-000099" / "planes.bin").write_bytes(b"torn")
        packed, meta = synth_game(0)
        stale = tmp_path / GAMES_DIR / "g-00000001.npz"
        np.savez(stale, packed=packed, meta=meta, winner=np.int32(0))
        buf2 = ReplayBuffer(str(tmp_path), segment_games=2)
        assert not (tmp_path / "seg-000099").exists()
        assert not stale.exists()
        assert buf2.total_games == 4
        assert count_durable_games(str(tmp_path)) == 4

    def test_ingest_fault_site(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=10)
        faults.install("loop_ingest:fail@1")
        with pytest.raises(faults.InjectedFailure):
            buf.ingest_game(*synth_game(0))
        # the failed ingest acked nothing and left nothing on disk
        assert buf.total_games == 0
        assert count_durable_games(str(tmp_path)) == 0
        # the next attempt (the restarted actor's replay) lands cleanly
        buf.ingest_game(*synth_game(0))
        assert buf.total_games == 1

    def test_ingest_transient_absorbed(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=10)
        faults.install("loop_ingest:transient@2")
        buf.ingest_game(*synth_game(0))  # retried, no error escapes
        assert buf.total_games == 1

    def test_eviction_respects_protect_lo(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=2,
                           capacity_positions=30)
        fill(buf, 0, 8)  # 4 segments x 20 positions
        lo, hi, _ = buf.extent()
        # a cursor pinned at the second segment blocks eviction past it
        protect = buf._segments[1].lo
        buf.evict(protect_lo=protect)
        assert buf.base_lo == protect
        # the protected extent still resolves; anything older is typed
        buf.view(protect, hi)
        with pytest.raises(ReplayError):
            buf.view(lo, hi)

    def test_winner_scheme_filters(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path), segment_games=4)
        fill(buf, 0, 4, winner_of=lambda g: 1)  # black always won
        buf.seal()
        view = buf.view(*buf.extent()[:2])
        cand = view.winner_positions()
        _, player, _, _ = view.batch_at(cand)
        assert (player == 1).all() and cand.size > 0
        idx = view.sample_indices(np.random.default_rng(0), 16, "winner")
        assert np.isin(idx, cand).all()

    def test_rejects_malformed_games(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path))
        with pytest.raises(ValueError):
            buf.ingest_game(np.zeros((0, 9, 19, 19), np.uint8),
                            np.zeros((0, 6), np.int32))
        with pytest.raises(ValueError):
            buf.ingest_game(np.zeros((3, 9, 19, 19), np.float32),
                            np.zeros((3, 6), np.int32))


# ---------------------------------------------------------------------------
# continuous learner


def make_learner(buf, run_dir, **kw):
    kw.setdefault("steps_per_window", 3)
    kw.setdefault("min_window_positions", 16)
    return ContinuousLearner(buf, str(run_dir), TINY, **kw)


class TestLearner:
    def test_windows_deterministic_across_learners(self, tmp_path):
        digests = []
        for side in ("a", "b"):
            buf = ReplayBuffer(str(tmp_path / f"buf-{side}"),
                               segment_games=4)
            fill(buf, 0, 4)
            learner = make_learner(buf, tmp_path / f"run-{side}")
            rec1 = learner.train_window()
            fill(buf, 4, 4)  # the corpus grows between windows
            rec2 = learner.train_window()
            digests.append((rec1["digest"], rec2["digest"]))
        assert digests[0] == digests[1]
        assert digests[0][0] != digests[0][1]

    def test_offline_replay_matches_live_digests(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path / "buf"), segment_games=4)
        fill(buf, 0, 4)
        learner = make_learner(buf, tmp_path / "run")
        learner.train_window()
        fill(buf, 4, 4)
        learner.train_window()
        for rec in read_windows(str(tmp_path / "run")):
            assert replay_window(str(tmp_path / "run"), buf, rec) \
                == rec["digest"]

    def test_crash_mid_window_resumes_bit_exact_despite_growth(
            self, tmp_path):
        """THE resume property: corpus grows mid-run, the learner dies
        mid-window, more games land while it is down, and the resumed
        stream is still bit-identical to an uninterrupted run over the
        same ingestion schedule — because the checkpointed cursor pins
        the extent the window froze, not whatever the buffer holds at
        resume time."""
        # uninterrupted reference
        buf_a = ReplayBuffer(str(tmp_path / "buf-a"), segment_games=4)
        fill(buf_a, 0, 4)
        ref = make_learner(buf_a, tmp_path / "run-a")
        ref.train_window()
        fill(buf_a, 4, 4)
        rec_a = ref.train_window()
        # killed-and-resumed run over the identical schedule
        buf_b = ReplayBuffer(str(tmp_path / "buf-b"), segment_games=4)
        fill(buf_b, 0, 4)
        victim = make_learner(buf_b, tmp_path / "run-b")
        victim.train_window()
        fill(buf_b, 4, 4)
        faults.install("train_step:fail@2")  # dies inside window 2
        with pytest.raises(faults.InjectedFailure):
            victim.train_window()
        faults.install("")
        # the corpus keeps growing while the learner is down — the part
        # a naive "re-freeze at resume" implementation gets wrong
        fill(buf_b, 8, 4)
        resumed = make_learner(buf_b, tmp_path / "run-b")
        assert resumed.resumed_from is not None
        rec_b = resumed.train_window()
        assert rec_b["extent"] == rec_a["extent"]
        assert rec_b["digest"] == rec_a["digest"]
        assert params_digest(resumed.params) == params_digest(ref.params)

    def test_clean_boundary_resume_freezes_fresh_extent(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path / "buf"), segment_games=4)
        fill(buf, 0, 4)
        learner = make_learner(buf, tmp_path / "run")
        rec1 = learner.train_window()
        fill(buf, 4, 4)
        # a kill BETWEEN windows: checkpoint and cursor agree the window
        # completed, so the resume freezes the grown extent, exactly as
        # the uninterrupted run would have
        resumed = make_learner(buf, tmp_path / "run")
        rec2 = resumed.train_window()
        assert rec2["extent"][1] > rec1["extent"][1]

    def test_publish_is_loadable_and_verified(self, tmp_path):
        from deepgo_tpu.models.serving import load_policy

        buf = ReplayBuffer(str(tmp_path / "buf"), segment_games=4)
        fill(buf, 0, 4)
        challenger = tmp_path / "challenger.npz"
        learner = make_learner(buf, tmp_path / "run",
                               publish_path=str(challenger))
        rec = learner.train_window()
        assert rec["published"] == str(challenger)
        ckpt.verify_checkpoint(str(challenger))
        _, params, _ = load_policy(str(challenger))
        assert params_digest(params) == rec["digest"]

    def test_starved_buffer_raises_typed_stall(self, tmp_path):
        buf = ReplayBuffer(str(tmp_path / "buf"), segment_games=4)
        fill(buf, 0, 1)
        t = [0.0]

        def clock():
            return t[0]

        def sleep(s):
            t[0] += s

        learner = ContinuousLearner(
            buf, str(tmp_path / "run"), TINY, steps_per_window=3,
            min_window_positions=10_000, stall_timeout_s=5.0,
            clock=clock, sleep=sleep)
        with pytest.raises(LoopStalled):
            learner.train_window()

    @pytest.mark.slow
    def test_sigkill_resume_matches_uninterrupted_subprocess(
            self, tmp_path):
        """The honest preemption: the learner subprocess is SIGKILLed
        mid-window (kill:step@6 — no cleanup, no atexit), re-running the
        identical command resumes and completes, and every window digest
        matches a never-killed run of the same schedule."""
        child = os.path.join(REPO_ROOT, "tests", "loop_learner_child.py")

        def run(workdir, faults_spec=None):
            env = {k: v for k, v in os.environ.items()
                   if k != "DEEPGO_FAULTS"}
            env["JAX_PLATFORMS"] = "cpu"
            if faults_spec:
                env["DEEPGO_FAULTS"] = faults_spec
            return subprocess.run(
                [sys.executable, child, "--dir", str(workdir),
                 "--windows", "3", "--steps", "4"],
                env=env, capture_output=True, text=True, timeout=300)

        r = run(tmp_path / "killed", faults_spec="kill:step@6")
        assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
        r = run(tmp_path / "killed")
        assert r.returncode == 0, r.stderr[-2000:]
        killed = json.loads(r.stdout.split("CHILD_DONE ", 1)[1])
        r = run(tmp_path / "clean")
        assert r.returncode == 0, r.stderr[-2000:]
        clean = json.loads(r.stdout.split("CHILD_DONE ", 1)[1])
        assert killed == clean and len(killed) == 3


# ---------------------------------------------------------------------------
# standard gate + gatekeeper


class _FakeFleet:
    def __init__(self):
        self.reloaded = []

    def reload(self, path):
        self.reloaded.append(path)
        return {"replicas": 2, "seconds": 0.0}


class TestStandardGate:
    def test_protocol_pins_match_r5_queue(self):
        # the values tools/r5_value_loop.sh pinned by hand, now owned by
        # one definition (the satellite's whole point)
        assert match.GATE_GAMES == 1000
        assert match.GATE_OPENING_PLIES == 8
        assert match.GATE_SEED == 29
        assert match.GATE_RANK == 8

    def test_standard_gate_records_protocol(self):
        from deepgo_tpu.agents import RandomAgent

        a, b = RandomAgent(), RandomAgent()
        _, _, stats = match.standard_gate(a, b, n_games=2, max_moves=10)
        assert stats["protocol"]["opening_plies"] == 8
        assert stats["protocol"]["seed"] == 29
        assert 0.0 <= stats["win_rate_a"] <= 1.0


class TestGatekeeper:
    def test_identical_agents_split_the_pairs_and_reject(self, tmp_path):
        """Challenger == incumbent under shared openings is exactly 50%
        (the color-swapped rematch of a deterministic self-pair mirrors
        every game), so the 55% gate deterministically rejects — the
        no-evidence-no-promotion property."""
        champ = tmp_path / "champion.npz"
        chal = tmp_path / "challenger.npz"
        make_policy_checkpoint(str(champ), seed=1)
        make_policy_checkpoint(str(chal), seed=1)
        gk = ArenaGatekeeper(str(champ), games=4, threshold=0.55,
                             max_moves=20)
        with pytest.raises(GateRejected) as err:
            gk.evaluate(str(chal))
        assert err.value.win_rate == pytest.approx(0.5)
        assert gk.gates_rejected == 1

    def test_pass_publishes_champion_and_reloads_fleet(self, tmp_path):
        champ = tmp_path / "champion.npz"
        chal = tmp_path / "challenger.npz"
        make_policy_checkpoint(str(champ), seed=1, step=0)
        make_policy_checkpoint(str(chal), seed=2, step=11)
        fleet = _FakeFleet()
        gk = ArenaGatekeeper(str(champ), games=2, threshold=0.0,
                             max_moves=16, fleet=fleet)
        record = gk.evaluate(str(chal))
        assert record["outcome"] == "passed"
        assert fleet.reloaded == [str(champ)]
        # the champion slot now holds the challenger, atomically
        assert ckpt.load_meta(str(champ))["step"] == 11
        assert record["champion_step"] == 11
        assert gk.gates_passed == 1

    def test_corrupt_challenger_never_reaches_the_fleet(self, tmp_path):
        champ = tmp_path / "champion.npz"
        chal = tmp_path / "challenger.npz"
        make_policy_checkpoint(str(champ), seed=1)
        make_policy_checkpoint(str(chal), seed=2)
        data = bytearray(chal.read_bytes())
        data[len(data) // 2] ^= 0xFF  # one flipped byte mid-payload
        chal.write_bytes(bytes(data))
        fleet = _FakeFleet()
        gk = ArenaGatekeeper(str(champ), games=2, threshold=0.0,
                             max_moves=16, fleet=fleet)
        with pytest.raises(ckpt.CheckpointError):
            gk.evaluate(str(chal))
        assert fleet.reloaded == []
        assert ckpt.load_meta(str(champ))["id"] == "test-1"

    def test_loop_gate_fault_site(self, tmp_path):
        champ = tmp_path / "champion.npz"
        make_policy_checkpoint(str(champ), seed=1)
        gk = ArenaGatekeeper(str(champ), games=2, threshold=0.0)
        faults.install("loop_gate:fail@1")
        with pytest.raises(faults.InjectedFailure):
            gk.evaluate(str(champ))


# ---------------------------------------------------------------------------
# cli serve --watch verification


class TestServeWatchVerification:
    def test_corrupt_watch_checkpoint_is_not_reloaded(self, tmp_path):
        from deepgo_tpu.cli import verified_reload

        path = tmp_path / "champion.npz"
        make_policy_checkpoint(str(path), seed=1)
        data = bytearray(path.read_bytes())
        data[len(data) // 2] ^= 0xFF
        path.write_bytes(bytes(data))
        fleet = _FakeFleet()
        assert verified_reload(fleet, str(path)) is None
        assert fleet.reloaded == []

    def test_valid_watch_checkpoint_reloads(self, tmp_path):
        from deepgo_tpu.cli import verified_reload

        path = tmp_path / "champion.npz"
        make_policy_checkpoint(str(path), seed=1)
        fleet = _FakeFleet()
        assert verified_reload(fleet, str(path)) is not None
        assert fleet.reloaded == [str(path)]


# ---------------------------------------------------------------------------
# the full in-process loop turn (the `make verify-loop` acceptance shape)


class TestLoopTurn:
    def test_one_full_turn_selfplay_to_champion(self, tmp_path):
        cfg = LoopConfig(actors=1, fleet=2, games_per_round=2,
                         max_moves=16, temperature=0.5,
                         steps_per_window=4, min_window_positions=24,
                         segment_games=2, gate_games=4,
                         gate_threshold=0.0, windows=1,
                         stall_timeout_s=180.0)
        loop = ExpertIterationLoop(str(tmp_path / "run"), cfg,
                                   TINY.replace(name="loop-turn"))
        summary = loop.run()
        assert summary["fatal"] == {}
        assert summary["windows_trained"] == 1
        assert summary["gates_passed"] == 1
        # zero lost games: every game the actors acked is on disk
        assert summary["games_acked"] == summary["games_durable"] > 0
        # the served champion is the gated window-1 checkpoint
        assert summary["champion_step"] == summary["learner_step"] == 4
        assert summary["fleet_reloads"] >= 1
        # the champion slot verifies end to end (what serve --watch and
        # the next gate both rely on)
        ckpt.verify_checkpoint(str(tmp_path / "run" / "champion.npz"))
        # and the loop's own event stream recorded the turn
        events = [json.loads(l)["kind"]
                  for l in (tmp_path / "run" / "loop.jsonl")
                  .read_text().splitlines() if l.strip()]
        for kind in ("loop_start", "loop_ingest", "loop_window",
                     "loop_gate", "loop_close"):
            assert kind in events, (kind, set(events))

    def test_rerun_resumes_and_extends(self, tmp_path):
        """Re-running the identical command over the same run_dir picks
        the loop up where the last run left it — the operational resume
        contract cli loop documents."""
        cfg = LoopConfig(actors=1, fleet=2, games_per_round=2,
                         max_moves=16, temperature=0.5,
                         steps_per_window=4, min_window_positions=24,
                         segment_games=2, gate_games=4,
                         gate_threshold=0.0, windows=1,
                         stall_timeout_s=180.0)
        ExpertIterationLoop(str(tmp_path / "run"), cfg,
                            TINY.replace(name="loop-turn")).run()
        cfg2 = dataclasses.replace(cfg, windows=2)
        summary = ExpertIterationLoop(str(tmp_path / "run"), cfg2,
                                      TINY.replace(name="loop-turn")).run()
        assert summary["windows_trained"] == 2
        assert summary["learner_step"] == 8
        assert summary["champion_step"] == 8
