"""Content-addressed position cache + CPU surge tier (serving/cache.py).

The load-bearing contracts:

  * one digest implementation: ``utils/digest.py`` tables pinned equal
    to ``ops/augment``'s and to the workload recorder's;
  * exact-key hits are the SAME bytes as an uncached forward; canonical
    hits are bitwise-identical for all 8 dihedral views of a position
    (property-tested with an equivariant-by-construction forward);
  * coalescing: N in-flight submits of one digest cost exactly one
    forward, and a failed leader never poisons followers — the next
    follower is promoted and re-dispatched;
  * ``fleet.reload()`` invalidates: mid-reload submits resolve to
    exactly old-or-new-checkpoint outputs with ZERO stale cache hits
    (the PR 13 old-or-new proof extended to the cached path);
  * batch-tier bypass keeps bulk scans out of the LRU;
  * the offline simulator reports the ACHIEVED hit rate per capacity
    (``cli workload analyze --simulate-cache``);
  * the CPU surge tier: heterogeneous-platform fleets route batch-tier
    traffic to CPU replicas, fail over across platforms when replicas
    die, and scope the straggler-ejection baseline per platform.
"""

import json
import os
import random
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.obs import workload as wl
from deepgo_tpu.ops import augment
from deepgo_tpu.serving import (CacheConfig, EngineConfig, FailoverExhausted,
                                FleetConfig, FleetRouter, InferenceEngine,
                                PositionCache, SupervisedEngine,
                                SupervisorConfig, fleet_policy_engine,
                                simulate_cache)
from deepgo_tpu.serving.cache import CacheKeyingError, Waiter
from deepgo_tpu.utils import digest as dg
from deepgo_tpu.utils import faults

SGF_DIR = os.path.join(os.path.dirname(__file__), "..", "data", "sgf",
                       "test")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


def boards(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


def ok_forward(params, packed, player, rank):
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3)) \
        + 1000.0 * np.asarray(player, np.float32)


def point_forward(params, packed, player, rank):
    """Per-point local forward: out[b, p] depends only on the channel
    column at p, so it is equivariant under any spatial permutation —
    the property the canonical-key remap requires — and bitwise stable
    (same channel order, same summation order, at every point)."""
    b = len(packed)
    flat = np.asarray(packed, np.float32).reshape(b, 9, 361)
    return flat.sum(axis=1) * 0.125 \
        + np.asarray(player, np.float32)[:, None]


def weight_forward(params, packed, player, rank):
    w = np.float32(0.0) if params is None else np.float32(params["w"])
    return ok_forward(params, packed, player, rank) + 1000.0 * w


ECFG = EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
FAST_FLEET = FleetConfig(respawn_base_s=0.001, respawn_cap_s=0.005)


def make_fleet(forward=ok_forward, replicas=2, fleet_config=FAST_FLEET,
               sup_config=None, engine_config=ECFG, params=None, **kw):
    def make_replica(i):
        return SupervisedEngine(
            lambda: InferenceEngine(forward, params, engine_config,
                                    name=f"rep{i}"),
            config=sup_config, name=f"rep{i}")

    kw.setdefault("rng", random.Random(0))
    return FleetRouter(make_replica, replicas, config=fleet_config,
                       name=kw.pop("name", "cache-fleet"), **kw)


def wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class ScriptedReplica:
    """Duck-typed replica whose futures the TEST resolves — makes the
    leader-failure/promotion protocol fully deterministic."""

    def __init__(self, idx, platform=None, est=None):
        self.idx = idx
        self.est = est
        self.futs = []
        self.fail_next = 0
        self.auto_value = None
        if platform is not None:
            self.platform = platform

    def submit(self, packed, player, rank, timeout_s=None, block=True):
        if self.fail_next > 0:
            self.fail_next -= 1
            from deepgo_tpu.serving import EngineClosed

            raise EngineClosed("scripted submit failure")
        f = Future()
        if self.auto_value is not None:
            f.set_result(self.auto_value)
        self.futs.append(f)
        return f

    def estimated_wait_s(self):
        return self.est

    def health(self):
        return {"state": "serving", "estimated_wait_s": self.est,
                "breaker": {"state": "closed"}}

    def stats(self):
        return {"boards": len(self.futs)}

    def warmup(self):
        return 0

    def compile_cache_size(self):
        return None

    def set_params(self, params):
        pass

    @property
    def params(self):
        return None

    def close(self, drain=True, timeout=1.0):
        pass


def scripted_fleet(reps, fleet_config=None, **kw):
    kw.setdefault("rng", random.Random(0))
    return FleetRouter(lambda i: reps[i], len(reps), config=fleet_config,
                       name=kw.pop("name", "scripted"), **kw)


# ---------------------------------------------------------------------------
# one digest implementation


class TestDigestModule:
    def test_tables_pinned_to_augment(self):
        assert np.array_equal(dg.PERMS, augment._PERM_NP)
        assert np.array_equal(dg.INV_PERMS, augment._TARGET_MAP_NP)

    def test_tables_frozen(self):
        for table in (dg.PERMS, dg.INV_PERMS):
            with pytest.raises(ValueError):
                table[0, 0] = 0

    def test_workload_recorder_shares_the_implementation(self):
        assert wl.exact_digest is dg.exact_digest
        assert wl.canonical_digest is dg.canonical_digest
        assert wl._PERMS is dg.PERMS

    def test_inverse_really_inverts(self):
        for k in range(8):
            assert np.array_equal(dg.INV_PERMS[k][dg.PERMS[k]],
                                  np.arange(361))

    def test_canonicalize_orbit_invariant(self):
        packed, players, ranks = boards(1, seed=3)
        base, player, rank = packed[0], int(players[0]), int(ranks[0])
        key0, view0, _ = dg.canonicalize(base, player, rank)
        assert key0 == dg.canonical_digest(base, player, rank)
        for v in dg.dihedral_views(base):
            key, view, k = dg.canonicalize(v, player, rank)
            assert key == key0
            assert np.array_equal(view, view0)
            # the returned k maps the canonical view back to THIS view
            flat = np.ascontiguousarray(v).reshape(9, 361)
            assert np.array_equal(
                view.reshape(9, 361), flat[:, dg.PERMS[k]])

    def test_remap_is_bitwise_for_equivariant_forward(self):
        packed, players, ranks = boards(1, seed=4)
        base, player, rank = packed[0], int(players[0]), int(ranks[0])
        for v in dg.dihedral_views(base):
            _, canon, k = dg.canonicalize(v, player, rank)
            via_cache = dg.remap_from_canonical(
                point_forward(None, canon[None], [player], [rank])[0], k)
            direct = point_forward(None, v[None], [player], [rank])[0]
            assert np.array_equal(via_cache, direct)

    def test_remap_rejects_unmappable_shapes(self):
        with pytest.raises(ValueError):
            dg.remap_from_canonical(np.zeros(7, np.float32), 3)


# ---------------------------------------------------------------------------
# the cache core (no fleet)


def _put(cache, key, row, k=0, tier="interactive"):
    w = Waiter(Future(), k, tier, None, None)
    role, _ = cache.join(key, w)
    assert role == "leader"
    cache.lead(key, np.zeros((9, 19, 19), np.uint8), 1, 1, w)
    for waiter, value in cache.complete_ok(key, row):
        waiter.future.set_result(value)
    return w.future.result(timeout=1)


class TestCacheCore:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            CacheConfig(capacity=-1)
        with pytest.raises(ValueError):
            CacheConfig(keying="fuzzy")

    def test_hit_returns_stored_bytes(self):
        cache = PositionCache(CacheConfig(capacity=4))
        row = np.arange(4, dtype=np.float32)
        _put(cache, "k1", row)
        w = Waiter(Future(), 0, "interactive", None, None)
        role, got = cache.join("k1", w)
        assert role == "hit"
        assert np.array_equal(got, row)
        s = cache.stats()
        assert s["hits"] == 1 and s["misses"] == 1
        assert s["entries"] == 1 and s["bytes"] == row.nbytes

    def test_lru_eviction_order_and_counter(self):
        cache = PositionCache(CacheConfig(capacity=2))
        _put(cache, "a", np.float32([1]))
        _put(cache, "b", np.float32([2]))
        # touch "a" so "b" is the LRU victim
        role, _ = cache.join("a", Waiter(Future(), 0, None, None, None))
        assert role == "hit"
        _put(cache, "c", np.float32([3]))
        assert cache.stats()["entries"] == 2
        assert cache.stats()["evictions"] == 1
        role, _ = cache.join("b", Waiter(Future(), 0, None, None, None))
        assert role == "leader"  # evicted
        cache.drop_flight("b")
        role, _ = cache.join("a", Waiter(Future(), 0, None, None, None))
        assert role == "hit"     # survived

    def test_followers_resolved_with_per_view_remap(self):
        cache = PositionCache(CacheConfig(capacity=4, keying="canonical"))
        leader = Waiter(Future(), 0, "interactive", None, None)
        role, _ = cache.join("k", leader)
        cache.lead("k", np.zeros((9, 19, 19), np.uint8), 1, 1, leader)
        f1 = Waiter(Future(), 1, "interactive", None, None)
        assert cache.join("k", f1)[0] == "follower"
        row = np.arange(361, dtype=np.float32)
        resolved = cache.complete_ok("k", row)
        assert len(resolved) == 2
        for w, value in resolved:
            w.future.set_result(value)
        assert np.array_equal(leader.future.result(), row)
        assert np.array_equal(f1.future.result(),
                              row[dg.INV_PERMS[1]])
        assert cache.stats()["coalesced"] == 1

    def test_canonical_remap_of_non_row_output_is_typed(self):
        cache = PositionCache(CacheConfig(keying="canonical"))
        leader = Waiter(Future(), 2, None, None, None)
        cache.join("k", leader)
        cache.lead("k", np.zeros((9, 19, 19), np.uint8), 1, 1, leader)
        (w, value), = cache.complete_ok("k", np.zeros(5, np.float32))
        assert isinstance(value, CacheKeyingError)

    def test_scalar_outputs_are_symmetry_invariant(self):
        cache = PositionCache(CacheConfig(keying="canonical"))
        _put(cache, "k", np.float32(7.5), k=3)
        w = Waiter(Future(), 5, None, None, None)
        role, got = cache.join("k", w)
        assert role == "hit" and got == np.float32(7.5)

    def test_promotion_consumes_leader_first(self):
        cache = PositionCache(CacheConfig(capacity=4))
        ws = [Waiter(Future(), 0, None, None, None) for _ in range(3)]
        cache.join("k", ws[0])
        cache.lead("k", np.zeros((9, 19, 19), np.uint8), 1, 1, ws[0])
        assert cache.join("k", ws[1])[0] == "follower"
        assert cache.join("k", ws[2])[0] == "follower"
        leader, promoted, dispatch = cache.complete_err("k")
        assert leader is ws[0] and promoted is ws[1]
        assert dispatch is not None
        # the promoted leader succeeds: remaining waiters all resolve
        resolved = cache.complete_ok("k", np.float32([9]))
        assert [w for w, _ in resolved] == [ws[1], ws[2]]
        leader2, promoted2, _ = cache.complete_err("k")
        assert leader2 is None and promoted2 is None

    def test_invalidate_clears_and_refuses_old_generation_fills(self):
        cache = PositionCache(CacheConfig(capacity=4))
        _put(cache, "old", np.float32([1]))
        w = Waiter(Future(), 0, None, None, None)
        cache.join("inflight", w)
        cache.lead("inflight", np.zeros((9, 19, 19), np.uint8), 1, 1, w)
        dropped = cache.invalidate("reload_start")
        assert dropped == 1
        assert cache.stats()["entries"] == 0
        # the in-flight leader still serves its waiter ...
        resolved = cache.complete_ok("inflight", np.float32([2]))
        assert len(resolved) == 1
        # ... but its fill was refused: the old generation never lands
        role, _ = cache.join(
            "inflight", Waiter(Future(), 0, None, None, None))
        assert role == "leader"
        cache.drop_flight("inflight")
        s = cache.stats()
        assert s["invalidations"] == 1
        assert s["stale_hits"] == 0

    def test_simulator_reports_achieved_hit_rate(self):
        keys = ["a", "b", "a", "c", "a", "b", "d", "a"]
        big = simulate_cache(keys, capacity=64)
        assert big["hits"] == 4 and big["misses"] == 4
        assert big["hit_rate"] == 0.5
        one = simulate_cache(keys, capacity=1)
        assert one["hits"] < big["hits"]
        assert one["requests"] == len(keys)
        assert simulate_cache([], capacity=4)["hit_rate"] is None
        with pytest.raises(ValueError):
            simulate_cache(keys, capacity=-2)


# ---------------------------------------------------------------------------
# the cached fleet door


class TestCachedFleet:
    def test_exact_hits_bitwise_and_one_forward(self):
        fleet = make_fleet(replicas=2, cache=CacheConfig(capacity=64))
        try:
            packed, players, ranks = boards(1, seed=1)
            args = (packed[0], int(players[0]), int(ranks[0]))
            first = fleet.submit(*args).result(timeout=10)
            second = fleet.submit(*args).result(timeout=10)
            direct = ok_forward(None, packed[:1], players[:1], ranks[:1])[0]
            assert np.array_equal(first, direct)
            assert np.array_equal(second, direct)
            s = fleet.cache.stats()
            assert s["hits"] == 1 and s["misses"] == 1
            assert fleet.stats()["boards"] == 1  # one real forward
        finally:
            fleet.close()

    def test_canonical_hits_bitwise_for_all_eight_views(self):
        fleet = make_fleet(point_forward, replicas=2,
                           cache=CacheConfig(capacity=64,
                                             keying="canonical"))
        try:
            packed, players, ranks = boards(1, seed=2)
            player, rank = int(players[0]), int(ranks[0])
            views = dg.dihedral_views(packed[0])
            # prime with the FIRST view; every view must then hit
            fleet.submit(views[0], player, rank).result(timeout=10)
            assert fleet.cache.stats()["misses"] == 1
            for v in views:
                got = fleet.submit(v, player, rank).result(timeout=10)
                direct = point_forward(None, v[None], [player], [rank])[0]
                assert np.array_equal(got, direct)
            s = fleet.cache.stats()
            assert s["hits"] == len(views)
            assert s["misses"] == 1
            assert fleet.stats()["boards"] == 1
        finally:
            fleet.close()

    def test_coalescing_costs_one_forward(self):
        release = threading.Event()
        calls = []

        def gated_forward(params, packed, player, rank):
            calls.append(len(packed))
            release.wait(timeout=10)
            return ok_forward(params, packed, player, rank)

        fleet = make_fleet(gated_forward, replicas=1,
                           cache=CacheConfig(capacity=64))
        try:
            packed, players, ranks = boards(1, seed=5)
            args = (packed[0], int(players[0]), int(ranks[0]))
            futs = [fleet.submit(*args) for _ in range(6)]
            assert wait_until(
                lambda: fleet.cache.stats()["coalesced"] == 5, timeout=5)
            release.set()
            rows = [f.result(timeout=10) for f in futs]
            direct = ok_forward(None, packed[:1], players[:1], ranks[:1])[0]
            for row in rows:
                assert np.array_equal(row, direct)
            assert sum(calls) == 1
            s = fleet.cache.stats()
            assert s["misses"] == 1 and s["coalesced"] == 5
        finally:
            release.set()
            fleet.close()

    def test_failed_leader_promotes_follower(self):
        rep = ScriptedReplica(0)
        fleet = scripted_fleet([rep], cache=CacheConfig(capacity=16))
        try:
            packed, players, ranks = boards(1, seed=6)
            args = (packed[0], int(players[0]), int(ranks[0]))
            leader_fut = fleet.submit(*args)
            assert len(rep.futs) == 1
            followers = [fleet.submit(*args) for _ in range(2)]
            assert fleet.cache.stats()["coalesced"] == 2
            # the replica dies under the leader's forward: terminal for
            # the leader (its only candidate is excluded), never for
            # the followers
            rep.futs[0].set_exception(RuntimeError("died mid-forward"))
            with pytest.raises(FailoverExhausted):
                leader_fut.result(timeout=10)
            assert wait_until(lambda: len(rep.futs) == 2, timeout=5)
            rep.futs[1].set_result(np.float32(42.0))
            for f in followers:
                assert f.result(timeout=10) == np.float32(42.0)
            # the promoted forward's fill landed: next submit hits
            assert fleet.submit(*args).result(timeout=10) \
                == np.float32(42.0)
            assert fleet.cache.stats()["hits"] == 1
        finally:
            fleet.close()

    def test_reload_invalidates_no_stale_hits(self):
        fleet = make_fleet(weight_forward, replicas=2,
                           cache=CacheConfig(capacity=64),
                           params={"w": np.float32(0.0)})
        try:
            packed, players, ranks = boards(1, seed=7)
            args = (packed[0], int(players[0]), int(ranks[0]))
            v0 = fleet.submit(*args).result(timeout=10)
            assert fleet.submit(*args).result(timeout=10) == v0  # cached
            fleet.reload({"w": np.float32(1.0)})
            v1 = fleet.submit(*args).result(timeout=10)
            assert v1 == v0 + np.float32(1000.0)
            s = fleet.cache.stats()
            assert s["invalidations"] >= 2  # reload start + end
            assert s["stale_hits"] == 0 and s["stale_blocked"] == 0
        finally:
            fleet.close()

    def test_mid_reload_submits_resolve_old_or_new_zero_stale(self):
        """The PR 13 old-or-new proof through the CACHED door: while a
        reload rolls, every cached-path result is exactly the old or
        the new checkpoint's output; after the roll, only the new."""
        fleet = make_fleet(weight_forward, replicas=2,
                           cache=CacheConfig(capacity=64),
                           params={"w": np.float32(0.0)})
        try:
            packed, players, ranks = boards(4, seed=8)
            reqs = [(packed[i], int(players[i]), int(ranks[i]))
                    for i in range(4)]
            olds = {i: fleet.submit(*reqs[i]).result(timeout=10)
                    for i in range(4)}
            stop = threading.Event()
            got, errs = [], []

            def spam():
                i = 0
                while not stop.is_set():
                    i += 1
                    try:
                        got.append(
                            (i % 4,
                             fleet.submit(*reqs[i % 4]).result(timeout=10)))
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

            t = threading.Thread(target=spam)
            t.start()
            try:
                fleet.reload({"w": np.float32(1.0)})
            finally:
                stop.set()
                t.join(timeout=15)
            assert not errs
            news = {i: olds[i] + np.float32(1000.0) for i in olds}
            for i, value in got:
                assert value in (olds[i], news[i])
            # post-reload: the new weights only, and zero stale serves
            for i in range(4):
                assert fleet.submit(*reqs[i]).result(timeout=10) == news[i]
            s = fleet.cache.stats()
            assert s["stale_hits"] == 0 and s["stale_blocked"] == 0
        finally:
            fleet.close()

    def test_batch_tier_bypasses_the_lru(self):
        fleet = make_fleet(replicas=1,
                           cache=CacheConfig(capacity=16,
                                             bypass_tiers=("batch",)))
        try:
            packed, players, ranks = boards(1, seed=9)
            args = (packed[0], int(players[0]), int(ranks[0]))
            for _ in range(2):
                fleet.submit(*args, tier="batch").result(timeout=10)
            s = fleet.cache.stats()
            assert s["bypassed"] == 2
            assert s["entries"] == 0 and s["hits"] == 0
            assert fleet.stats()["boards"] == 2  # both really computed
            fleet.submit(*args, tier="interactive").result(timeout=10)
            assert fleet.cache.stats()["entries"] == 1
        finally:
            fleet.close()

    def test_stats_and_health_carry_the_cache_block(self):
        fleet = make_fleet(replicas=1, cache=CacheConfig(capacity=8))
        try:
            assert fleet.stats()["fleet"]["cache"]["capacity"] == 8
            assert fleet.health()["cache"]["keying"] == "exact"
        finally:
            fleet.close()

    def test_uncached_fleet_unchanged(self):
        fleet = make_fleet(replicas=1)
        try:
            assert fleet.cache is None
            assert "cache" not in fleet.stats()["fleet"]
            packed, players, ranks = boards(1, seed=10)
            args = (packed[0], int(players[0]), int(ranks[0]))
            a = fleet.submit(*args).result(timeout=10)
            b = fleet.submit(*args).result(timeout=10)
            assert a == b
            assert fleet.stats()["boards"] == 2
        finally:
            fleet.close()


# ---------------------------------------------------------------------------
# the CPU surge tier


class TestSurgeTier:
    def test_batch_prefers_cpu_interactive_prefers_accelerator(self):
        tpu = ScriptedReplica(0, platform="tpu")
        cpu = ScriptedReplica(1, platform="cpu")
        for rep in (tpu, cpu):
            rep.auto_value = np.float32(rep.idx)
        fleet = scripted_fleet([tpu, cpu])
        try:
            packed, players, ranks = boards(8, seed=11)
            for i in range(4):
                args = (packed[i], int(players[i]), int(ranks[i]))
                assert fleet.submit(*args, tier="batch") \
                    .result(timeout=5) == np.float32(1)
                assert fleet.submit(*args, tier="interactive") \
                    .result(timeout=5) == np.float32(0)
            assert len(cpu.futs) == 4 and len(tpu.futs) == 4
        finally:
            fleet.close()

    def test_batch_falls_back_when_no_cpu_serves(self):
        tpu = ScriptedReplica(0, platform="tpu")
        tpu.auto_value = np.float32(0)
        fleet = scripted_fleet([tpu])
        try:
            packed, players, ranks = boards(1, seed=12)
            assert fleet.submit(packed[0], int(players[0]), int(ranks[0]),
                                tier="batch").result(timeout=5) \
                == np.float32(0)
        finally:
            fleet.close()

    def test_interactive_fails_over_to_cpu_replica(self):
        tpu = ScriptedReplica(0, platform="tpu")
        cpu = ScriptedReplica(1, platform="cpu")
        cpu.auto_value = np.float32(1)
        tpu.fail_next = 10  # the accelerator is dead at submit time
        fleet = scripted_fleet([tpu, cpu])
        try:
            packed, players, ranks = boards(1, seed=13)
            got = fleet.submit(packed[0], int(players[0]), int(ranks[0]),
                               tier="interactive").result(timeout=5)
            assert got == np.float32(1)
        finally:
            fleet.close()

    def test_ejection_baseline_is_platform_scoped(self):
        cfg = FleetConfig(respawn_base_s=0.001, respawn_cap_s=0.005,
                          eject_stragglers=True, eject_min_samples=4,
                          eject_consecutive=1, eject_factor=3.0)
        # a slow CPU replica among fast TPU peers: with a POOLED
        # baseline it would be ejected for simply being a CPU; with the
        # platform-scoped baseline it has no same-platform peer and is
        # left alone
        reps = [ScriptedReplica(0, platform="tpu"),
                ScriptedReplica(1, platform="tpu"),
                ScriptedReplica(2, platform="cpu")]
        fleet = scripted_fleet(reps, fleet_config=cfg)
        try:
            for rep, lat in zip(fleet._replicas, (0.01, 0.01, 0.5)):
                rep.lat.extend([lat] * 8)
            fleet._eject_outliers()
            assert fleet._ejections == 0
            assert all(r.state == "serving" for r in fleet._replicas)
        finally:
            fleet.close()

        # a straggler among SAME-platform peers is still ejected
        reps = [ScriptedReplica(0, platform="cpu"),
                ScriptedReplica(1, platform="cpu"),
                ScriptedReplica(2, platform="cpu")]
        fleet = scripted_fleet(reps, fleet_config=cfg)
        try:
            for rep, lat in zip(fleet._replicas, (0.01, 0.01, 0.5)):
                rep.lat.extend([lat] * 8)
            fleet._eject_outliers()
            assert fleet._ejections == 1
            assert fleet._replicas[2].state != "serving"
        finally:
            fleet.close()

    def test_fleet_policy_engine_heterogeneous_platforms(self):
        cfg = ModelConfig(num_layers=2, channels=8)
        params = init(jax.random.key(0), cfg)
        fleet = fleet_policy_engine(params, cfg, replicas=2, config=ECFG,
                                    fleet=FAST_FLEET,
                                    platforms=("tpu", "cpu"),
                                    cache=CacheConfig(capacity=16))
        try:
            plats = [getattr(r.engine, "platform", None)
                     for r in fleet._replicas]
            assert plats == ["tpu", "cpu"]
            detail = fleet.health()["replicas"]
            assert [d.get("platform") for d in detail] == ["tpu", "cpu"]
            packed, players, ranks = boards(2, seed=14)
            row = fleet.submit(packed[0], int(players[0]),
                               int(ranks[0]), tier="batch").result(30)
            assert row.shape == (361,)
            # kill the "tpu" replica: the CPU surge replica absorbs
            # interactive traffic without losing an answer
            assert fleet.eject_replica(0, reason="test-kill")
            got = fleet.submit(packed[1], int(players[1]), int(ranks[1]),
                               tier="interactive").result(30)
            assert got.shape == (361,)
            stats = fleet.stats()
            assert {s.get("platform") for s in stats["replicas"]} \
                == {"tpu", "cpu"}
        finally:
            fleet.close()

    def test_platforms_reject_non_f32_variants(self):
        cfg = ModelConfig(num_layers=2, channels=8)
        params = init(jax.random.key(0), cfg)
        with pytest.raises(ValueError):
            fleet_policy_engine(params, cfg, replicas=2,
                                platforms=("cpu",), variants=("int8",))


# ---------------------------------------------------------------------------
# surfaces: cli workload analyze --simulate-cache


class TestSimulateCacheCli:
    def test_cli_reports_achieved_hit_rate_per_size(self, tmp_path,
                                                    capsys):
        from deepgo_tpu import cli
        from deepgo_tpu.serving import replay as rp

        cap = str(tmp_path / "cap")
        items = rp.build_synthetic_requests(SGF_DIR, requests=48, games=4,
                                            opening_moves=4, seed=3)
        rp.write_synthetic_capture(cap, items)
        cli.main(["workload", "analyze", cap, "--simulate-cache", "1,256",
                  "--json"])
        data = json.loads(capsys.readouterr().out)
        sim = data["simulated_cache"]
        assert set(sim) == {"1", "256"}
        for size in sim:
            for keying in ("exact", "canonical"):
                assert 0.0 <= sim[size][keying]["hit_rate"] <= 1.0
        # an unbounded cache achieves exactly the projection
        assert sim["256"]["exact"]["hit_rate"] \
            == pytest.approx(data["projected_hit_rate"], abs=1e-4)
        assert sim["256"]["exact"]["hits"] >= sim["1"]["exact"]["hits"]
        cli.main(["workload", "analyze", cap, "--simulate-cache", "256"])
        out = capsys.readouterr().out
        assert "simulated cache" in out
