"""Elastic multi-host training (parallel/elastic.py): liveness-driven
detection, checkpoint convergence, re-mesh, and bit-exact resume.

The fast cases simulate a peer host through its heartbeat file alone — the
orchestration under test (detect -> converge -> re-mesh -> resume) never
needs a live second process. The slow case is the real thing: two
``cli train --elastic`` processes over one shared run directory, one
SIGKILLed mid-training by the ``kill`` fault site.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.data.transcribe import transcribe_split
from deepgo_tpu.experiments import Experiment, ExperimentConfig
from deepgo_tpu.experiments import checkpoint as ckpt
from deepgo_tpu.parallel import elastic
from deepgo_tpu.parallel.elastic import ElasticConfig, run_elastic
from deepgo_tpu.parallel.liveness import ConfigError, HeartbeatWriter
from deepgo_tpu.utils import faults
from deepgo_tpu.utils.metrics import read_jsonl


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("processed")
    for split in ("validation", "test"):
        transcribe_split(
            os.path.join(REPO_ROOT, "data/sgf", split),
            str(root / split),
            workers=1,
            verbose=False,
        )
    return str(root)


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


def tiny_overrides(data_root, **kw):
    defaults = dict(
        name="elastic-test",
        num_layers=2,
        channels=8,
        batch_size=8,
        rate=0.05,
        validation_size=16,
        validation_interval=5,
        print_interval=5,
        data_root=data_root,
        train_split="validation",
        validation_split="test",
        test_split="test",
        loader_threads=0,
        data_parallel=2,
        keep_checkpoints=0,
    )
    defaults.update(kw)
    return defaults


def leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


# ---- re-mesh ----


def test_remesh_single_process_spans_local_world():
    import jax

    mesh = elastic.remesh(1, survivors={0})
    assert mesh.axis_names == ("data", "model")
    assert mesh.devices.size == len(jax.devices())
    mesh2 = elastic.remesh(2, survivors={0, 1})
    assert mesh2.shape["model"] == 2


# ---- config validation (typed, raised before any training state) ----


def test_elastic_config_validation_is_typed(tmp_path):
    with pytest.raises(ConfigError, match="expected_hosts"):
        run_elastic(str(tmp_path), 5,
                    ecfg=ElasticConfig(expected_hosts=0))
    with pytest.raises(ConfigError, match="process_id"):
        run_elastic(str(tmp_path), 5,
                    ecfg=ElasticConfig(process_id=2, expected_hosts=2))


def test_cli_elastic_requires_auto_resume():
    from deepgo_tpu import cli

    with pytest.raises(SystemExit, match="auto-resume"):
        cli.main(["train", "--iters", "5", "--elastic"])


# ---- single-host elastic: completion, observability, idempotence ----


def test_single_host_elastic_completes_and_is_idempotent(
        data_root, tmp_path, capsys):
    run_dir = str(tmp_path / "run")
    ecfg = ElasticConfig(process_id=0, expected_hosts=1,
                         heartbeat_interval_s=0.2, miss_budget=3)
    summary = run_elastic(run_dir, 10,
                          overrides=tiny_overrides(data_root), ecfg=ecfg)
    assert summary["final_step"] == 10
    assert summary["recoveries"] == 0
    assert summary["steps_lost_total"] == 0
    assert summary["survivors"] == [0]
    assert summary["heartbeats"] >= 1
    # observable: heartbeat file, elastic metrics stream, DONE stdout line
    assert os.path.exists(os.path.join(run_dir, "heartbeats",
                                       "heartbeat-0000.json"))
    events = [r["kind"] for r in
              read_jsonl(os.path.join(run_dir, "elastic-0000.jsonl"))]
    assert events[0] == "elastic_start" and "elastic_done" in events
    done = [l for l in capsys.readouterr().out.splitlines()
            if l.startswith("ELASTIC_DONE ")]
    assert json.loads(done[-1].split(" ", 1)[1])["final_step"] == 10

    # --iters is the TOTAL target: a re-run of the same command is a no-op
    again = run_elastic(run_dir, 10,
                        overrides=tiny_overrides(data_root), ecfg=ecfg)
    assert again["final_step"] == 10 and again["recoveries"] == 0


# ---- host loss: detection, convergence, recovery accounting ----


def test_host_loss_before_any_checkpoint_recovers_fresh(
        data_root, tmp_path, capsys):
    """A peer that beat once and went silent is detected at the first
    liveness check; with no checkpoint on disk yet the survivors converge
    on a FRESH start — steps since step 0 are the rollback cost."""
    run_dir = str(tmp_path / "run")
    hb_dir = os.path.join(run_dir, "heartbeats")
    HeartbeatWriter(hb_dir, 1).beat(0)  # the peer's only sign of life

    ecfg = ElasticConfig(process_id=0, expected_hosts=2,
                         heartbeat_interval_s=0.05, miss_budget=4)
    # validation_interval=10: the first window (step 5) has NO checkpoint
    summary = run_elastic(
        run_dir, 15,
        overrides=tiny_overrides(data_root, validation_interval=10),
        ecfg=ecfg)
    assert summary["final_step"] == 15
    assert summary["recoveries"] == 1
    assert summary["survivors"] == [0]
    assert summary["steps_lost_total"] == 5  # detection at 5, restart at 0

    rec_lines = [l for l in capsys.readouterr().out.splitlines()
                 if l.startswith("ELASTIC_RECOVERY ")]
    rec = json.loads(rec_lines[0].split(" ", 1)[1])
    assert rec["process_id"] == 1
    assert rec["step_at_detection"] == 5
    assert rec["resumed_step"] == 0
    assert rec["steps_lost"] == 5
    assert rec["silent_for_s"] > ecfg.heartbeat_interval_s * ecfg.miss_budget
    assert rec["survivors"] == [0]
    events = read_jsonl(os.path.join(run_dir, "elastic-0000.jsonl"))
    kinds = [r["kind"] for r in events]
    assert kinds.count("host_lost") == 1 and kinds.count("recovery") == 1


def test_recovery_converges_on_checkpoint_bit_exact(data_root, tmp_path):
    """The acceptance property in-process: detection lands right after the
    step-5 checkpoint, the survivor converges on it, re-meshes, and the
    continuation is bit-identical to an uninterrupted run over the same
    step indices (loader.step_rng's guarantee, asserted across a re-mesh)."""
    lossy = str(tmp_path / "lossy")
    HeartbeatWriter(os.path.join(lossy, "heartbeats"), 1).beat(0)
    summary = run_elastic(
        lossy, 15, overrides=tiny_overrides(data_root),
        ecfg=ElasticConfig(process_id=0, expected_hosts=2,
                           heartbeat_interval_s=0.05, miss_budget=4))
    assert summary["recoveries"] == 1
    assert summary["steps_lost_total"] == 0  # checkpoint@5, detection@5
    assert summary["final_step"] == 15

    clean = str(tmp_path / "clean")
    ref = run_elastic(clean, 15, overrides=tiny_overrides(data_root),
                      ecfg=ElasticConfig(process_id=0, expected_hosts=1))
    assert ref["recoveries"] == 0

    meta_l, p_l, o_l = ckpt.load_checkpoint(summary["checkpoint"])
    meta_c, p_c, o_c = ckpt.load_checkpoint(ref["checkpoint"])
    assert meta_l["step"] == meta_c["step"] == 15
    for a, b in zip(p_l + o_l, p_c + o_c):
        np.testing.assert_array_equal(a, b)
    assert meta_l["ewma"] == meta_c["ewma"]


def test_recovery_budget_exhaustion_surfaces_host_lost(data_root, tmp_path):
    """max_recoveries=0: the very first HostLost must surface instead of
    being absorbed — a bounded budget, like every retry in this codebase."""
    from deepgo_tpu.parallel.liveness import HostLost

    run_dir = str(tmp_path / "run")
    HeartbeatWriter(os.path.join(run_dir, "heartbeats"), 1).beat(0)
    with pytest.raises(HostLost):
        run_elastic(run_dir, 15, overrides=tiny_overrides(data_root),
                    ecfg=ElasticConfig(process_id=0, expected_hosts=2,
                                       heartbeat_interval_s=0.05,
                                       miss_budget=4, max_recoveries=0))


# ---- the dist_collective chaos site ----


def test_dist_collective_site_threaded_only_when_elastic(data_root, tmp_path):
    faults.install("dist_collective:fail@1")
    cfg = ExperimentConfig(run_dir=str(tmp_path / "a"), elastic=True,
                           **tiny_overrides(data_root))
    exp = Experiment(cfg)
    with pytest.raises(faults.InjectedFailure):
        exp.run(2)

    faults.install("dist_collective:fail@1")
    cfg2 = ExperimentConfig(run_dir=str(tmp_path / "b"),
                            **tiny_overrides(data_root))
    exp2 = Experiment(cfg2)
    exp2.run(2)  # non-elastic runs never consult the site
    assert exp2.step == 2


# ---- the real thing: two processes, one SIGKILL ----


def run_host(rundir, data_root, *, host=0, hosts=1, iters=800,
             faults_env=None, budget=(0.5, 8)):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("DEEPGO_FAULTS", None)
    if faults_env:
        env["DEEPGO_FAULTS"] = faults_env
    sets = [
        "name=elastic-chaos", "num_layers=2", "channels=8", "batch_size=8",
        "rate=0.05", "validation_size=16", "validation_interval=100",
        "print_interval=5", f"data_root={data_root}",
        "train_split=validation", "validation_split=test",
        "loader_threads=0", "data_parallel=2", "keep_checkpoints=0",
    ]
    interval, miss = budget
    cmd = [sys.executable, "-m", "deepgo_tpu.cli", "train",
           "--iters", str(iters), "--elastic", "--auto-resume", rundir,
           "--process-id", str(host), "--expected-hosts", str(hosts),
           "--heartbeat-interval", str(interval), "--miss-budget", str(miss),
           "--init-deadline", "120", "--step-deadline", "300",
           "--set", *sets]
    return subprocess.Popen(cmd, cwd=REPO_ROOT, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


@pytest.mark.slow
def test_two_host_sigkill_chaos_recovers_bit_exact(data_root, tmp_path):
    """Acceptance: two elastic hosts over one shared run dir; host 1 is
    SIGKILLed mid-training by the ``kill`` fault site. The survivor must
    detect the loss within the heartbeat miss budget (modulo its window
    cadence), converge on the latest valid checkpoint, re-mesh, resume,
    and land on a final state bit-identical to an uninterrupted
    single-host run over the same step indices."""
    shared = str(tmp_path / "fleet")
    iters, interval, miss = 800, 0.5, 8
    budget_s = interval * miss

    procs = [
        run_host(shared, data_root, host=0, hosts=2, iters=iters,
                 budget=(interval, miss)),
        # the victim: last beat at its step-5 window, SIGKILL at step 7
        run_host(shared, data_root, host=1, hosts=2, iters=iters,
                 faults_env="kill:step@7", budget=(interval, miss)),
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    (rc0, out0, err0), (rc1, out1, err1) = outs
    assert rc1 == -9, (rc1, err1[-800:])        # the kill site is honest
    assert rc0 == 0, (rc0, err0[-2000:])        # the survivor finishes

    recs = [json.loads(l.split(" ", 1)[1]) for l in out0.splitlines()
            if l.startswith("ELASTIC_RECOVERY ")]
    done = [json.loads(l.split(" ", 1)[1]) for l in out0.splitlines()
            if l.startswith("ELASTIC_DONE ")]
    assert done and done[-1]["final_step"] == iters
    assert done[-1]["recoveries"] >= 1
    assert recs, "survivor never reported a recovery"
    rec = recs[0]
    assert rec["process_id"] == 1
    # detected within the miss budget, modulo one liveness-check window
    # (checks ride the print-window cadence; generous slack for CI load)
    assert rec["detect_latency_s"] > budget_s
    assert rec["detect_latency_s"] < budget_s + 20.0
    assert rec["steps_lost"] >= 0
    assert rec["resumed_step"] <= rec["step_at_detection"]
    assert rec["survivors"] == [0]

    # uninterrupted single-host reference over the same step indices
    ref_dir = str(tmp_path / "ref")
    ref = run_host(ref_dir, data_root, host=0, hosts=1, iters=iters,
                   budget=(interval, miss))
    ref_out, ref_err = ref.communicate(timeout=300)
    assert ref.returncode == 0, ref_err[-2000:]

    meta_s, p_s, o_s = ckpt.load_checkpoint(
        os.path.join(shared, ckpt.checkpoint_name(iters)))
    meta_r, p_r, o_r = ckpt.load_checkpoint(
        os.path.join(ref_dir, ckpt.checkpoint_name(iters)))
    for a, b in zip(p_s + o_s, p_r + o_r):
        np.testing.assert_array_equal(a, b)
    assert meta_s["ewma"] == meta_r["ewma"]
    keys = ("step", "cost", "accuracy", "n")
    assert ([{k: v[k] for k in keys} for v in meta_s["validation_history"]]
            == [{k: v[k] for k in keys} for v in meta_r["validation_history"]])
