"""Device cost-model ledger (obs/costmodel.py) — the ISSUE-12 acceptance.

The load-bearing claims, each pinned here:

  * the analytic FLOPs estimator agrees with XLA's ``cost_analysis()``
    to a tolerance band (the cross-check that caught the old dense
    formula's ~10% border-tap overcount);
  * per-rung ledger monotonicity: FLOPs and bytes never decrease going
    up the bucket ladder;
  * degraded mode: a backend with no cost model (or a failing lower)
    yields an ``estimated`` row with the analytic count, never a crash;
  * the exporter's ``/cost`` route round-trips the installed ledger;
  * the MFU-floor gate matrix (pass / fail / skip) and its fold into
    ``bench --gate``'s verdict;
  * the attribution join: a real dryrun train with the ledger armed
    reports MFU next to its wall-clock buckets — offline, from the
    snapshot alone;
  * the engine's per-bucket dispatch histogram joins into per-rung
    achieved FLOP/s.
"""

import json
import os
import urllib.request

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.models import policy_cnn
from deepgo_tpu.obs import costmodel
from deepgo_tpu.obs.registry import MetricsRegistry

SMALL = policy_cnn.CONFIGS["small"]


class ListSink:
    def __init__(self):
        self.events = []

    def write(self, kind, **fields):
        self.events.append({"kind": kind, **fields})


@pytest.fixture(scope="module")
def ladder_ledger():
    """One AOT sweep of the small config's first three rungs, shared by
    every test that only reads it (each rung is a real XLA compile)."""
    reg = MetricsRegistry()
    sink = ListSink()
    ledger = costmodel.CostLedger(registry=reg, sink=sink)
    costmodel.ladder_entries(ledger, SMALL, buckets=(1, 8, 32))
    return ledger, reg, sink


# ---- the analytic estimator vs the compiler ----


def test_analytic_flops_matches_xla_cost_analysis(ladder_ledger):
    ledger, _, _ = ladder_ledger
    for bucket in (1, 8, 32):
        entry = ledger.get("policy_forward", bucket)
        assert entry is not None and entry.source == "xla"
        analytic = costmodel.analytic_flops(SMALL, bucket)
        # the band: expansion/bias/softmax ops ride in the XLA count but
        # not the conv-only estimate; border-tap accounting must agree
        assert abs(analytic - entry.flops) / entry.flops < 0.05, (
            bucket, analytic, entry.flops)


def test_dense_formula_would_fail_the_band():
    # the regression the cross-check exists to catch: the old dense
    # k^2*cin*cout*361 count overstates the 19x19 stack by ~10%
    dense = sum(2.0 * k * k * cin * cout * 361
                for k, cin, cout in SMALL.layer_shapes())
    exact = costmodel.analytic_flops(SMALL)
    assert (dense - exact) / exact > 0.05


def test_analytic_train_flops_is_3x_forward():
    assert costmodel.analytic_train_flops(SMALL, 4) == \
        3.0 * costmodel.analytic_flops(SMALL, 4)


# ---- ladder monotonicity + the published surfaces ----


def test_ladder_flops_and_bytes_monotonic_up_the_rungs(ladder_ledger):
    ledger, _, _ = ladder_ledger
    entries = [ledger.get("policy_forward", b) for b in (1, 8, 32)]
    flops = [e.flops for e in entries]
    bytes_ = [e.bytes_accessed for e in entries]
    hbm = [e.hbm_peak_bytes for e in entries]
    assert flops == sorted(flops) and flops[0] < flops[-1]
    assert bytes_ == sorted(bytes_) and bytes_[0] < bytes_[-1]
    assert hbm == sorted(hbm)


def test_ledger_publishes_gauges_and_versioned_events(ladder_ledger):
    ledger, reg, sink = ladder_ledger
    entry = ledger.get("policy_forward", 8)
    assert reg.gauge("deepgo_cost_flops").value(
        fn="policy_forward", bucket=8) == entry.flops
    assert reg.gauge("deepgo_cost_hbm_peak_bytes").value(
        fn="policy_forward", bucket=8) == entry.hbm_peak_bytes
    assert reg.gauge("deepgo_cost_compile_seconds").value(
        fn="policy_forward", bucket=8) > 0
    events = [e for e in sink.events if e["kind"] == "cost_ledger"]
    assert len(events) == 3
    for e in events:
        assert e["version"] == costmodel.VERSION
        assert e["fn"] == "policy_forward" and e["source"] == "xla"
        assert e["flops"] > 0 and e["platform"] == ledger.peak.platform


def test_hbm_bill_reflects_argument_output_temp(ladder_ledger):
    ledger, _, _ = ladder_ledger
    e = ledger.get("policy_forward", 8)
    assert e.hbm_argument_bytes > 0 and e.hbm_output_bytes > 0
    assert e.hbm_peak_bytes >= e.hbm_argument_bytes + e.hbm_output_bytes


# ---- degraded mode ----


class _LowerRaises:
    def lower(self, *a, **k):
        raise RuntimeError("backend has no AOT path")


class _NoCostModel:
    """lower/compile succeed; cost_analysis returns nothing (the shape
    some backends actually have)."""

    class _Compiled:
        def cost_analysis(self):
            return []

        def memory_analysis(self):
            return None

    class _Lowered:
        def compile(self):
            return _NoCostModel._Compiled()

    def lower(self, *a, **k):
        return self._Lowered()


@pytest.mark.parametrize("broken", [_LowerRaises(), _NoCostModel()],
                         ids=["lower-raises", "empty-cost-model"])
def test_degraded_mode_marks_estimated_and_never_crashes(broken):
    ledger = costmodel.CostLedger(registry=MetricsRegistry())
    entry = ledger.measure("broken", broken, (), bucket=4,
                           analytic=costmodel.analytic_flops(SMALL, 4))
    assert entry.source == "estimated"
    assert entry.flops == costmodel.analytic_flops(SMALL, 4)
    assert entry.bytes_accessed is None and entry.hbm_peak_bytes is None
    # degraded rows still join: no bytes -> no AI -> no bound, mfu from
    # the analytic count when a timing exists
    block = ledger.roofline({("broken", 4): 0.5})
    row = block["entries"]["broken/b4"]
    assert row["bound"] is None
    assert row["achieved_flops_per_s"] == pytest.approx(entry.flops / 0.5)


def test_degraded_mode_without_estimator_is_a_zero_row():
    ledger = costmodel.CostLedger(registry=MetricsRegistry())
    entry = ledger.measure("broken", _LowerRaises(), ())
    assert entry.source == "estimated" and entry.flops == 0.0


# ---- platform peak detection ----


def test_detect_peak_cpu_is_estimated_with_capacity():
    peak = costmodel.detect_peak()
    assert peak.platform == "cpu" and peak.source == "estimated"
    assert peak.flops_per_s > 0 and peak.ridge_flops_per_byte > 0


def test_detect_peak_tpu_table_and_unknown():
    class Dev:
        def __init__(self, platform, kind):
            self.platform, self.device_kind = platform, kind

    v5e = costmodel.detect_peak(Dev("tpu", "TPU v5 lite"))
    assert v5e.source == "table" and v5e.flops_per_s == 197e12
    assert v5e.hbm_capacity_bytes == 16 * 2**30
    mystery = costmodel.detect_peak(Dev("tpu", "TPU v99"))
    assert mystery.source == "unknown" and mystery.flops_per_s is None
    # unknown peaks must yield honest Nones, not crashes
    e = costmodel.CostEntry("f", 1, 1e9, 1e6, None, None, None, None,
                            0.1, "xla", "tpu")
    row = costmodel.roofline_entry(e, mystery, seconds_per_call=0.01)
    assert row["mfu"] is None and row["bound"] is None
    assert row["achieved_flops_per_s"] == pytest.approx(1e11)


# ---- /cost route ----


def test_cost_route_roundtrip(ladder_ledger):
    from deepgo_tpu.obs.exporter import ObsExporter

    ledger, _, _ = ladder_ledger
    exporter = ObsExporter(port=0)
    try:
        costmodel.set_cost_ledger(None)
        with urllib.request.urlopen(exporter.url + "/cost", timeout=5) as r:
            empty = json.loads(r.read())
        assert empty == {"enabled": False}
        costmodel.set_cost_ledger(ledger)
        with urllib.request.urlopen(exporter.url + "/cost", timeout=5) as r:
            payload = json.loads(r.read())
        assert payload["enabled"] is True
        led = payload["ledger"]
        assert led["version"] == costmodel.VERSION
        assert len(led["entries"]) == 3
        assert led["peak"]["flops_per_s"] > 0
        keys = {(e["fn"], e["bucket"]) for e in led["entries"]}
        assert keys == {("policy_forward", b) for b in (1, 8, 32)}
    finally:
        costmodel.set_cost_ledger(None)
        exporter.close()


# ---- the MFU-floor gate ----


def _block(**mfus):
    return {"entries": {k: {"mfu": v} for k, v in mfus.items()}}


class TestMfuFloor:
    def test_within_floor_passes(self):
        out = costmodel.evaluate_mfu_floor(
            _block(a=0.48, b=0.30), _block(a=0.50, b=0.29))
        assert out["verdict"] == "pass" and out["checked"] == 2

    def test_drop_past_floor_fails_with_the_entry_named(self):
        out = costmodel.evaluate_mfu_floor(
            _block(a=0.50, b=0.20), _block(a=0.50, b=0.30))
        assert out["verdict"] == "fail"
        assert out["failures"][0]["entry"] == "b"
        assert "b" in out["reason"]

    def test_floor_is_configurable(self):
        fresh, base = _block(a=0.45), _block(a=0.50)
        assert costmodel.evaluate_mfu_floor(
            fresh, base, floor=0.05)["verdict"] == "fail"
        assert costmodel.evaluate_mfu_floor(
            fresh, base, floor=0.20)["verdict"] == "pass"

    def test_missing_roofline_skips(self):
        assert costmodel.evaluate_mfu_floor(
            None, _block(a=0.5))["verdict"] == "skip"
        assert costmodel.evaluate_mfu_floor(
            _block(a=0.5), None)["verdict"] == "skip"

    def test_no_comparable_mfu_skips(self):
        # AOT-only entries (mfu None) and disjoint keys never fail
        assert costmodel.evaluate_mfu_floor(
            _block(a=None), _block(a=0.5))["verdict"] == "skip"
        assert costmodel.evaluate_mfu_floor(
            _block(a=0.5), _block(b=0.5))["verdict"] == "skip"

    def test_improvement_never_fails(self):
        out = costmodel.evaluate_mfu_floor(
            _block(a=0.60), _block(a=0.30))
        assert out["verdict"] == "pass"

    def test_bench_gate_folds_mfu_floor_into_the_verdict(self):
        # the bench fold: throughput passed, MFU dropped -> gate fails
        import bench

        class Args:
            gate = 0.10

        result = {
            "metric": "m", "value": 100.0, "device": "d",
            "roofline": _block(**{"policy_forward/b8": 0.2}),
        }
        entry = {"metric": "m", "value": 100.0, "device": "d",
                 "roofline": _block(**{"policy_forward/b8": 0.5})}
        real = bench.LAST_GOOD_PATH
        import tempfile

        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"m": entry}, f)
        bench.LAST_GOOD_PATH = f.name
        try:
            bench._apply_gate(result, Args())
        finally:
            bench.LAST_GOOD_PATH = real
            os.unlink(f.name)
        gate = result["gate"]
        assert gate["mfu_floor"]["verdict"] == "fail"
        assert gate["verdict"] == "fail"
        assert "MFU floor" in gate["reason"]

    def test_bench_gate_mfu_pass_keeps_throughput_verdict(self):
        import tempfile

        import bench

        class Args:
            gate = 0.10

        result = {"metric": "m", "value": 100.0, "device": "d",
                  "roofline": _block(**{"policy_forward/b8": 0.5})}
        entry = {"metric": "m", "value": 100.0, "device": "d",
                 "roofline": _block(**{"policy_forward/b8": 0.5})}
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"m": entry}, f)
        real = bench.LAST_GOOD_PATH
        bench.LAST_GOOD_PATH = f.name
        try:
            bench._apply_gate(result, Args())
        finally:
            bench.LAST_GOOD_PATH = real
            os.unlink(f.name)
        assert result["gate"]["verdict"] == "pass"
        assert result["gate"]["mfu_floor"]["verdict"] == "pass"


# ---- the serving join: per-bucket dispatch histogram -> per-rung MFU ----


def test_engine_dispatch_join_produces_per_rung_mfu():
    import jax

    from deepgo_tpu.models.serving import make_log_prob_fn
    from deepgo_tpu.obs import get_registry
    from deepgo_tpu.serving import EngineConfig, InferenceEngine

    params = policy_cnn.init(jax.random.key(0), SMALL)
    engine = InferenceEngine(make_log_prob_fn(SMALL), params,
                             EngineConfig(buckets=(1, 8), max_wait_ms=0.5),
                             name="costjoin")
    try:
        engine.warmup()
        rng = np.random.default_rng(0)
        packed = rng.integers(0, 3, size=(9, 19, 19), dtype=np.uint8)
        for _ in range(3):
            engine.submit(packed, 1, 1).result(timeout=30)
    finally:
        engine.close()
    snap = get_registry().snapshot()["metrics"]
    secs = costmodel.dispatch_seconds_by_bucket(snap)
    assert 1 in secs and secs[1] > 0
    ledger = costmodel.CostLedger(registry=MetricsRegistry())
    costmodel.ladder_entries(ledger, SMALL, buckets=(1,))
    block = ledger.roofline({("policy_forward", 1): secs[1]})
    row = block["entries"]["policy_forward/b1"]
    assert row["achieved_flops_per_s"] > 0
    assert row["mfu"] is not None and 0 < row["mfu"] < 1.5
    assert row["bound"] in ("compute", "memory")


# ---- the train entrypoint + memoization ----


def test_train_entry_prices_fwd_plus_bwd_and_memoizes():
    reg = MetricsRegistry()
    ledger = costmodel.CostLedger(registry=reg)
    entry = costmodel.train_entry(ledger, SMALL, 8)
    fwd = ledger.measure("fwd", _LowerRaises(), (),
                         analytic=costmodel.analytic_flops(SMALL, 8))
    assert entry.source == "xla"
    # backward ~ 1.5-2x forward (XLA skips the input-grad conv of the
    # first layer): the step must cost 2-3.5x the forward
    assert 2.0 < entry.flops / fwd.flops < 3.5
    # second ledger, same program: memoized (no recompile -> same object)
    ledger2 = costmodel.CostLedger(registry=MetricsRegistry())
    again = costmodel.train_entry(ledger2, SMALL, 8)
    assert again is entry
    assert ledger2.get("train_step", 8) is entry


# ---- the attribution join on a real dryrun train ----


@pytest.fixture(scope="module")
def trained_run(tmp_path_factory):
    from deepgo_tpu.data.transcribe import transcribe_split
    from deepgo_tpu.experiments import Experiment, ExperimentConfig

    data_root = tmp_path_factory.mktemp("processed")
    for split in ("validation", "test"):
        transcribe_split(os.path.join(REPO_ROOT, "data/sgf", split),
                         str(data_root / split), workers=1, verbose=False)
    cfg = ExperimentConfig(
        name="cost-dryrun", num_layers=2, channels=8, batch_size=8,
        validation_size=16, validation_interval=10, print_interval=5,
        data_root=str(data_root), train_split="validation",
        validation_split="test", loader_threads=0, data_parallel=1,
        run_dir=str(tmp_path_factory.mktemp("runs")))
    exp = Experiment(cfg)
    exp.run(10)
    return exp.run_path


def test_dryrun_train_attribution_carries_mfu(trained_run):
    from deepgo_tpu.obs.attribution import attribute_run

    att = attribute_run(trained_run)
    roof = att["hosts"]["0"].get("roofline")
    assert roof is not None, att["hosts"]["0"]
    assert roof["flops_per_step"] > 0
    assert roof["achieved_flops_per_s"] > 0
    assert roof["mfu"] is not None and roof["mfu"] > 0
    assert roof.get("bound") in ("compute", "memory")


def test_dryrun_train_streams_cost_ledger_event(trained_run):
    from deepgo_tpu.obs.report import read_events

    events = [r for r in read_events(os.path.join(trained_run,
                                                  "metrics.jsonl"))
              if r.get("kind") == "cost_ledger"]
    assert events, "train start must stream its step's bill"
    assert events[0]["fn"] == "train_step"
    assert events[0]["version"] == costmodel.VERSION
    assert events[0]["bucket"] == 8  # the config's batch size


def test_cli_obs_renders_cost_ledger_and_mfu(trained_run, capsys):
    from deepgo_tpu.cli import main

    main(["obs", trained_run])
    out = capsys.readouterr().out
    assert "device cost ledger" in out
    assert "roofline: MFU" in out
    main(["obs", trained_run, "--json"])
    summary = json.loads(capsys.readouterr().out)
    assert summary["cost_ledger"]["entries"][0]["fn"] == "train_step"
    assert summary["attribution"]["hosts"]["0"]["roofline"]["mfu"] > 0


def test_cost_ledger_off_switch(tmp_path):
    # cost_ledger=False: no AOT pass, no gauges, attribution has no
    # roofline — the join degrades, never breaks
    from deepgo_tpu.obs.attribution import attribute_snapshot

    reg = MetricsRegistry()
    reg.counter("deepgo_train_wall_seconds_total").inc(10.0)
    reg.counter("deepgo_train_steps_total").inc(5)
    att = attribute_snapshot(reg.snapshot()["metrics"])
    assert att is not None and "roofline" not in att


# ---- cli cost ----


def test_cli_cost_json(capsys):
    from deepgo_tpu.cli import main

    try:
        main(["cost", "--model", "small", "--buckets", "1,8",
              "--train-batch", "0", "--sym-bucket", "0", "--json"])
        out = json.loads(capsys.readouterr().out)
        # the ladder is priced for BOTH the f32 and the int8 serving
        # programs (ISSUE 13: the MFU floor covers every program the
        # fleet can serve, not just the f32 ladder)
        assert set(out["entries"]) == {"policy_forward/b1",
                                       "policy_forward/b8",
                                       "quant_forward/b1",
                                       "quant_forward/b8"}
        for row in out["entries"].values():
            assert row["flops"] > 0 and row["mfu"] is None
        # the command installs the ledger for a live /cost route
        assert costmodel.get_cost_ledger() is not None
    finally:
        costmodel.set_cost_ledger(None)


def test_cli_cost_table_renders(capsys):
    from deepgo_tpu.cli import main

    try:
        main(["cost", "--model", "small", "--buckets", "1",
              "--train-batch", "8", "--sym-bucket", "0"])
        out = capsys.readouterr().out
        assert "device cost ledger v1" in out
        assert "policy_forward/b1" in out and "train_step/b8" in out
        assert "eval_step/b8" in out
    finally:
        costmodel.set_cost_ledger(None)
