"""Step-time attribution (obs/attribution.py) + its report/CLI surfaces.

The ISSUE-6 acceptance shape lives here: a real dryrun train must have
>= 95 % of its measured wall-clock attributed to named buckets, with the
residual reported (not hidden). Plus: the snapshot decomposition math on
synthetic data, the cross-host join over elastic streams with the
FireCaffe-style scaling block, and the `cli obs` report growing the
attribution table and the serving supervisor counter section.
"""

import json
import os

import pytest

from conftest import REPO_ROOT
from deepgo_tpu.data.transcribe import transcribe_split
from deepgo_tpu.experiments import Experiment, ExperimentConfig
from deepgo_tpu.obs import JsonlSink, MetricsRegistry
from deepgo_tpu.obs.attribution import (attribute_run, attribute_snapshot,
                                        format_attribution)
from deepgo_tpu.obs.report import format_report, summarize_run


def snapshot_of(reg: MetricsRegistry) -> dict:
    return reg.snapshot()["metrics"]


def synthetic_registry(wall=10.0, loader=2.0, h2d_inline=0.5,
                       compile_s=3.0, dispatch=1.0, compute=2.0,
                       sps_samples=1000) -> MetricsRegistry:
    reg = MetricsRegistry()
    reg.counter("deepgo_train_wall_seconds_total").inc(wall)
    reg.counter("deepgo_train_steps_total").inc(10)
    reg.counter("deepgo_train_samples_total").inc(sps_samples)
    reg.histogram("deepgo_loader_wait_seconds").observe(loader)
    reg.histogram("deepgo_h2d_seconds").observe(h2d_inline, path="inline")
    reg.histogram("deepgo_h2d_seconds").observe(9.9, path="uploader")
    h = reg.histogram("deepgo_train_dispatch_seconds")
    h.observe(compile_s, phase="first")
    h.observe(dispatch, phase="steady")
    reg.histogram("deepgo_train_fetch_seconds").observe(compute)
    return reg


class TestSnapshotMath:
    def test_buckets_partition_and_residual_is_explicit(self):
        att = attribute_snapshot(snapshot_of(synthetic_registry()))
        b = att["buckets"]
        # inline h2d is carved OUT of loader_wait: no double counting
        assert b["loader_wait"]["seconds"] == pytest.approx(1.5)
        assert b["h2d"]["seconds"] == pytest.approx(0.5)
        assert b["compile"]["seconds"] == pytest.approx(3.0)
        assert b["dispatch"]["seconds"] == pytest.approx(1.0)
        assert b["compute"]["seconds"] == pytest.approx(2.0)
        assert att["attributed_fraction"] == pytest.approx(0.8)
        assert att["residual_s"] == pytest.approx(2.0)
        assert att["residual_fraction"] == pytest.approx(0.2)
        assert att["useful_compute_fraction"] == pytest.approx(0.2)
        # the uploader-path h2d overlaps compute: outside the partition
        assert att["overlapped_h2d_s"] == pytest.approx(9.9)
        assert att["samples_per_sec"] == pytest.approx(100.0)

    def test_no_wall_metric_means_no_attribution(self):
        assert attribute_snapshot(snapshot_of(MetricsRegistry())) is None

    def test_span_buckets_checkpoint_and_validate(self):
        reg = synthetic_registry()
        h = reg.histogram("deepgo_span_seconds")
        h.observe(0.4, name="checkpoint_save", status="ok")
        h.observe(0.6, name="validate", status="ok")
        h.observe(99.0, name="unrelated_span", status="ok")
        b = attribute_snapshot(snapshot_of(reg))["buckets"]
        assert b["checkpoint"]["seconds"] == pytest.approx(0.4)
        assert b["validate"]["seconds"] == pytest.approx(0.6)


class TestCrossHostJoin:
    def _elastic_run(self, tmp_path):
        run = tmp_path / "run"
        run.mkdir()
        for host, wall in ((0, 10.0), (1, 12.0)):
            reg = synthetic_registry(wall=wall)
            with JsonlSink(str(run / f"elastic-{host:04d}.jsonl")) as s:
                s.write("elastic_start", host=host)
                s.write("obs_snapshot", host=host,
                        metrics=snapshot_of(reg))
        return str(run)

    def test_joins_per_host_elastic_snapshots(self, tmp_path):
        att = attribute_run(self._elastic_run(tmp_path))
        assert att["num_hosts"] == 2
        assert att["hosts"]["0"]["wall_s"] == pytest.approx(10.0)
        assert att["hosts"]["1"]["wall_s"] == pytest.approx(12.0)
        scaling = att["scaling"]
        assert scaling["aggregate_samples_per_sec"] == pytest.approx(
            100.0 + 1000 / 12.0, abs=0.1)
        assert 0 < scaling["useful_compute_fraction_mean"] < 1
        assert scaling["non_compute_fraction_mean"] == pytest.approx(
            1 - scaling["useful_compute_fraction_mean"], abs=1e-3)

    def test_falls_back_to_metrics_stream(self, tmp_path):
        run = tmp_path / "run"
        run.mkdir()
        with JsonlSink(str(run / "metrics.jsonl")) as s:
            s.write("obs_snapshot",
                    metrics=snapshot_of(synthetic_registry()))
        att = attribute_run(str(run))
        assert att["num_hosts"] == 1 and "0" in att["hosts"]

    def test_empty_run_dir_returns_none(self, tmp_path):
        assert attribute_run(str(tmp_path)) is None

    def test_format_renders_hosts_and_fleet_line(self, tmp_path):
        text = format_attribution(attribute_run(self._elastic_run(tmp_path)))
        assert "2 hosts" in text
        assert "loader_wait" in text and "(residual)" in text
        assert "fleet:" in text and "scaling efficiency" in text


# ---- the acceptance bar: >= 95 % attributed on a real dryrun train ----


@pytest.fixture(scope="module")
def data_root(tmp_path_factory):
    root = tmp_path_factory.mktemp("processed")
    for split in ("validation", "test"):
        transcribe_split(os.path.join(REPO_ROOT, "data/sgf", split),
                         str(root / split), workers=1, verbose=False)
    return str(root)


@pytest.fixture(scope="module")
def trained_run(data_root, tmp_path_factory):
    cfg = ExperimentConfig(
        name="attribution-dryrun", num_layers=2, channels=8, batch_size=8,
        validation_size=16, validation_interval=10, print_interval=5,
        data_root=data_root, train_split="validation",
        validation_split="test", loader_threads=0, data_parallel=1,
        run_dir=str(tmp_path_factory.mktemp("runs")))
    exp = Experiment(cfg)
    exp.run(30)
    return exp.run_path


def test_dryrun_train_attributes_95_percent_of_wall(trained_run):
    att = attribute_run(trained_run)
    host = att["hosts"]["0"]
    assert host["attributed_fraction"] >= 0.95, host
    # the residual is REPORTED, not hidden — and stays sane
    assert abs(host["residual_fraction"]) <= 0.05
    assert host["steps"] == 30
    # the dominant CPU-dryrun buckets all materialized
    for bucket in ("loader_wait", "compile", "dispatch", "validate",
                   "checkpoint"):
        assert bucket in host["buckets"], host["buckets"].keys()


def test_cli_obs_report_includes_attribution_table(trained_run, capsys):
    from deepgo_tpu.cli import main

    main(["obs", trained_run])
    out = capsys.readouterr().out
    assert "step-time attribution" in out
    assert "(residual)" in out
    main(["obs", trained_run, "--json"])
    payload = json.loads(capsys.readouterr().out)
    assert payload["attribution"]["hosts"]["0"]["attributed_fraction"] \
        >= 0.95


def test_report_surfaces_supervisor_counters(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    reg = MetricsRegistry()
    reg.counter("deepgo_serving_restarts_total").inc(2, engine="e")
    reg.counter("deepgo_serving_shed_total").inc(3, engine="e",
                                                reason="overload")
    reg.counter("deepgo_serving_poisoned_total").inc(1, engine="e")
    with JsonlSink(str(run / "metrics.jsonl")) as s:
        s.write("obs_snapshot", metrics=snapshot_of(reg))
    summary = summarize_run(str(run))
    sup = summary["events"]["serving"]["supervisor"]
    assert sup == {"restarts": 2, "shed": 3, "poisoned": 1}
    assert "supervisor" in format_report(summary)
