"""Heartbeat liveness (parallel/liveness.py): writer, ledger, miss budget,
stragglers, and the typed distributed error family. Every transition is
driven by a fake clock — no sleeps."""

import json
import os

import pytest

from deepgo_tpu.parallel import liveness
from deepgo_tpu.parallel.liveness import (
    ConfigError,
    CoordinatorUnreachable,
    DistributedError,
    HeartbeatLedger,
    HeartbeatWriter,
    HostLost,
    StragglerDetected,
)
from deepgo_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def test_error_family_is_typed_and_routable():
    for cls in (ConfigError, HostLost, StragglerDetected,
                CoordinatorUnreachable):
        assert issubclass(cls, DistributedError)
        assert issubclass(cls, RuntimeError)
    # a coordinator failure is ALSO an OSError, so generic transient-I/O
    # retry policies (retry_with_backoff's default retry_on) retry it
    assert issubclass(CoordinatorUnreachable, OSError)
    # a config error is ALSO a ValueError (it is a bad argument)
    assert issubclass(ConfigError, ValueError)


def test_writer_writes_atomic_json_record(tmp_path):
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 3, clock=clock)
    assert w.beat(40, step_latency_s=0.25)
    rec = json.loads(open(w.path).read())
    assert rec == {"process_id": 3, "beat": 0, "step": 40,
                   "time": 1000.0, "step_latency_s": 0.25}
    clock.advance(2.0)
    assert w.beat(45)
    rec = json.loads(open(w.path).read())
    assert rec["beat"] == 1 and rec["time"] == 1002.0
    assert "step_latency_s" not in rec
    assert w.beats == 2
    # no stray temp files: the write is atomic
    assert sorted(os.listdir(tmp_path)) == [liveness.heartbeat_name(3)]


def test_writer_absorbs_transient_write_faults(tmp_path):
    faults.install("heartbeat:transient@2")
    w = HeartbeatWriter(str(tmp_path), 0, clock=FakeClock())
    assert w.beat(1)  # two transients absorbed by the bounded retry
    assert w.misses == 0 and w.beats == 1


def test_writer_survives_hard_write_fault_loudly(tmp_path, capsys):
    faults.install("heartbeat:fail@1")
    w = HeartbeatWriter(str(tmp_path), 0, clock=FakeClock())
    assert not w.beat(1)  # hard fault: absorbed, logged, counted
    assert w.misses == 1 and w.beats == 0
    assert "heartbeat" in capsys.readouterr().err
    assert w.beat(2)  # next beat lands fine
    assert json.loads(open(w.path).read())["step"] == 2


def test_liveness_within_budget_is_quiet(tmp_path):
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 1, clock=clock)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=3,
                             clock=clock)
    w.beat(10)
    clock.advance(3.0)  # silence == budget exactly: still alive
    ledger.check_liveness({1})


def test_liveness_past_budget_raises_typed_host_lost(tmp_path):
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 1, clock=clock)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=3,
                             clock=clock)
    w.beat(10)
    clock.advance(3.01)
    with pytest.raises(HostLost) as err:
        ledger.check_liveness({1})
    e = err.value
    assert e.process_id == 1
    assert e.last_seen == 1000.0
    assert e.silent_for_s == pytest.approx(3.01)
    assert e.budget_s == 3.0
    assert e.last_step == 10
    assert "host 1 lost" in str(e)


def test_never_seen_host_lost_after_grace_from_first_poll(tmp_path):
    clock = FakeClock()
    ledger = HeartbeatLedger(str(tmp_path), interval_s=0.5, miss_budget=4,
                             clock=clock)
    ledger.poll()  # starts the grace window
    clock.advance(1.9)
    ledger.check_liveness({7})  # within budget: bootstrap grace
    clock.advance(0.2)
    with pytest.raises(HostLost) as err:
        ledger.check_liveness({7})
    assert err.value.process_id == 7
    assert err.value.last_step is None  # never beat at all


def test_longest_silent_host_reported_first(tmp_path):
    clock = FakeClock()
    a = HeartbeatWriter(str(tmp_path), 1, clock=clock)
    a.beat(5)
    clock.advance(2.0)
    b = HeartbeatWriter(str(tmp_path), 2, clock=clock)
    b.beat(5)
    clock.advance(10.0)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=3,
                             clock=clock)
    with pytest.raises(HostLost) as err:
        ledger.check_liveness({1, 2})
    assert err.value.process_id == 1  # silent longest


def test_corrupt_heartbeat_file_reads_as_silence_not_crash(tmp_path):
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 0, clock=clock)
    w.beat(1)
    with open(os.path.join(str(tmp_path), liveness.heartbeat_name(1)),
              "w") as f:
        f.write('{"process_id": 1, "time": ')  # torn json
    logged = []
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=2,
                             clock=clock, log=logged.append)
    assert set(ledger.read()) == {0}
    assert any("skipping" in m for m in logged)
    clock.advance(2.01)  # the corrupt host is silent -> detectable
    with pytest.raises(HostLost):
        ledger.check_liveness({1})


def test_straggler_detection_from_rolling_latencies(tmp_path):
    clock = FakeClock()
    fast = HeartbeatWriter(str(tmp_path), 0, clock=clock)
    slow = HeartbeatWriter(str(tmp_path), 1, clock=clock)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=3,
                             clock=clock)
    for step in range(4):
        fast.beat(step, step_latency_s=0.01)
        slow.beat(step, step_latency_s=0.10)
        ledger.poll()
        clock.advance(0.5)
    report = ledger.straggler_report(factor=3.0, min_beats=3)
    assert [s.process_id for s in report] == [1]
    s = report[0]
    assert s.latency_s == pytest.approx(0.10)
    assert "straggling" in str(s)
    # tightest factor that still clears the slow host's own median
    assert ledger.straggler_report(factor=50.0) == []


def test_straggler_ratio_gauge_per_host(tmp_path):
    """ISSUE-6 satellite: straggler_report is no longer report-only — each
    call refreshes deepgo_straggler_ratio{host=N} (median over peers'
    median), so a slow host is visible on any /metrics scrape."""
    from deepgo_tpu.obs import MetricsRegistry

    clock = FakeClock()
    reg = MetricsRegistry()
    fast = HeartbeatWriter(str(tmp_path), 0, clock=clock)
    slow = HeartbeatWriter(str(tmp_path), 1, clock=clock)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=3,
                             clock=clock, registry=reg)
    for step in range(4):
        fast.beat(step, step_latency_s=0.01)
        slow.beat(step, step_latency_s=0.10)
        ledger.poll()
        clock.advance(0.5)
    ledger.straggler_report(factor=3.0, min_beats=3)
    g = reg.gauge("deepgo_straggler_ratio")
    assert g.value(host="1") == pytest.approx(10.0)   # 0.10 / 0.01
    assert g.value(host="0") == pytest.approx(0.1)    # 0.01 / 0.10
    # the fleet healing (the slow host speeding up) moves the gauge, not
    # just future report calls — the gauge is live state, not an archive
    for step in range(4, 12):
        fast.beat(step, step_latency_s=0.01)
        slow.beat(step, step_latency_s=0.01)
        ledger.poll()
        clock.advance(0.5)
    ledger.straggler_report(factor=3.0, min_beats=3)
    assert g.value(host="1") < 3.0


def test_straggler_needs_min_beats_and_a_peer(tmp_path):
    clock = FakeClock()
    lone = HeartbeatWriter(str(tmp_path), 0, clock=clock)
    ledger = HeartbeatLedger(str(tmp_path), clock=clock)
    for step in range(5):
        lone.beat(step, step_latency_s=0.5)
        ledger.poll()
    assert ledger.straggler_report() == []  # no fleet to compare against


def test_poll_keys_latency_samples_on_beat_sequence(tmp_path):
    """Re-reading the same unchanged beat must not double-count its
    latency sample into the rolling window."""
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 0, clock=clock)
    w.beat(1, step_latency_s=0.2)
    ledger = HeartbeatLedger(str(tmp_path), clock=clock)
    for _ in range(5):
        ledger.poll()
    assert len(ledger._latencies[0]) == 1


def test_ledger_snapshot_reports_silence_and_latency(tmp_path):
    clock = FakeClock()
    w = HeartbeatWriter(str(tmp_path), 2, clock=clock)
    w.beat(30, step_latency_s=0.05)
    ledger = HeartbeatLedger(str(tmp_path), interval_s=1.0, miss_budget=5,
                             clock=clock)
    ledger.poll()
    clock.advance(1.5)
    snap = ledger.snapshot()
    assert snap["budget_s"] == 5.0
    assert snap["hosts"][2]["step"] == 30
    assert snap["hosts"][2]["silent_for_s"] == pytest.approx(1.5)
    assert snap["hosts"][2]["median_latency_s"] == pytest.approx(0.05)


def test_ledger_config_validation_is_typed():
    with pytest.raises(ConfigError):
        HeartbeatLedger("x", interval_s=0.0)
    with pytest.raises(ConfigError):
        HeartbeatLedger("x", miss_budget=0)
