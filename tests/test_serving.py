"""Serving path and remat tests."""

import numpy as np

import jax
import jax.numpy as jnp

from deepgo_tpu.models import ModelConfig, init, apply
from deepgo_tpu.models.serving import load_policy, make_policy_fn


def _inputs(b=8, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.integers(0, 3, size=(b, 9, 19, 19), dtype=np.uint8)),
        jnp.asarray(rng.integers(1, 3, size=b).astype(np.int32)),
        jnp.asarray(rng.integers(1, 10, size=b).astype(np.int32)),
    )


def test_policy_fn_outputs():
    cfg = ModelConfig(num_layers=2, channels=8)
    params = init(jax.random.key(0), cfg)
    predict = make_policy_fn(cfg, top_k=3)
    out = predict(params, *_inputs())
    assert out["log_probs"].shape == (8, 361)
    assert out["top_moves"].shape == (8, 3)
    np.testing.assert_allclose(
        np.exp(np.asarray(out["log_probs"])).sum(-1), 1.0, rtol=1e-4
    )
    # top-1 agrees with argmax of the distribution
    assert np.array_equal(
        np.asarray(out["top_moves"])[:, 0],
        np.asarray(out["log_probs"]).argmax(-1),
    )
    # top probs sorted descending
    tp = np.asarray(out["top_probs"])
    assert (np.diff(tp, axis=1) <= 1e-7).all()


def test_sym_policy_fn_is_exactly_equivariant():
    # averaging over the full dihedral group makes the predictor
    # equivariant BY CONSTRUCTION: transforming the input must transform
    # the output distribution, for any net (random init included) —
    # the property that makes the 8-view ensemble a principled average
    # rather than 8 unrelated evaluations
    from deepgo_tpu.models.serving import make_sym_policy_fn
    from deepgo_tpu.ops.augment import _PERM_NP, _TARGET_MAP_NP

    cfg = ModelConfig(num_layers=2, channels=8, compute_dtype="float32")
    params = init(jax.random.key(0), cfg)
    predict = make_sym_policy_fn(cfg)
    packed, player, rank = _inputs(b=4, seed=2)
    base = np.asarray(predict(params, packed, player, rank))
    assert base.shape == (4, 361)
    np.testing.assert_allclose(np.exp(base).sum(-1), 1.0, rtol=1e-4)

    k = 3  # an arbitrary non-identity symmetry
    flat = np.asarray(packed).reshape(4, 9, 361)
    t_packed = jnp.asarray(flat[:, :, _PERM_NP[k]].reshape(4, 9, 19, 19))
    t_out = np.asarray(predict(params, t_packed, player, rank))
    # the distribution must move WITH the board: original point p now
    # lives at _TARGET_MAP_NP[k, p]
    np.testing.assert_allclose(t_out[:, _TARGET_MAP_NP[k]], base,
                               rtol=2e-4, atol=1e-6)


def test_sym_policy_fn_matches_reference_mixture():
    # independent re-derivation: sym8(x) must equal
    # log((1/8) sum_k  T_k^-1(softmax(net(T_k(x))))) computed here with
    # the PLAIN predictor and the numpy tables — catching any error in
    # the fused transform/inverse-map/average (a doubly-wrong map can
    # still pass the equivariance test alone)
    from deepgo_tpu.models.serving import make_sym_policy_fn
    from deepgo_tpu.ops.augment import _PERM_NP, _TARGET_MAP_NP

    cfg = ModelConfig(num_layers=2, channels=8, compute_dtype="float32")
    params = init(jax.random.key(1), cfg)
    plain = make_policy_fn(cfg, top_k=1)
    sym = make_sym_policy_fn(cfg)
    packed, player, rank = _inputs(b=4, seed=5)
    flat = np.asarray(packed).reshape(4, 9, 361)

    mix = np.zeros((4, 361))
    for k in range(8):
        view = jnp.asarray(flat[:, :, _PERM_NP[k]].reshape(4, 9, 19, 19))
        logp = np.asarray(plain(params, view, player, rank)["log_probs"])
        mix += np.exp(logp)[:, _TARGET_MAP_NP[k]]
    expected = np.log(mix / 8 + 1e-30)
    out = np.asarray(sym(params, packed, player, rank))
    np.testing.assert_allclose(out, expected, rtol=2e-4, atol=1e-5)


def test_load_policy_from_checkpoint(tmp_path):
    import os
    from conftest import REPO_ROOT
    from deepgo_tpu.data.transcribe import transcribe_split
    from deepgo_tpu.experiments import Experiment
    from test_experiment import tiny_config

    root = tmp_path / "processed"
    for split in ("validation", "test"):
        transcribe_split(os.path.join(REPO_ROOT, "data/sgf", split),
                         str(root / split), workers=1, verbose=False)
    exp = Experiment(tiny_config(str(root), run_dir=str(tmp_path / "runs")))
    exp.run(5)
    path = exp.save()

    predict, params, cfg = load_policy(path)
    out = predict(params, *_inputs())
    assert np.isfinite(np.asarray(out["log_probs"])).all()


def test_remat_same_values_and_grads():
    cfg = ModelConfig(num_layers=3, channels=16, compute_dtype="float32")
    cfg_r = ModelConfig(num_layers=3, channels=16, compute_dtype="float32",
                        remat=True)
    params = init(jax.random.key(0), cfg)
    planes = jnp.asarray(
        np.random.default_rng(0).random((4, 19, 19, 37)), jnp.float32
    )

    def loss(p, c):
        return apply(p, planes, c).sum()

    v1, g1 = jax.value_and_grad(lambda p: loss(p, cfg))(params)
    v2, g2 = jax.value_and_grad(lambda p: loss(p, cfg_r))(params)
    assert float(v1) == float(v2)
    for a, b in zip(jax.tree.leaves(g1), jax.tree.leaves(g2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
