"""Int8 quantized serving + fused dihedral ensemble + serving variants.

The load-bearing contracts:

  * po2 per-output-channel int8 is EXACTLY trackable: weights already on
    the int8 grid round-trip bit-identically through the quantized
    forward (the epilogue dequant commutes through the f32 accumulation
    and bf16 downcast), so tolerance measures weight rounding alone;
  * the fused sym ensemble with symmetries=1 is BITWISE the plain
    forward (plumbing check), and at 8 views reproduces the reference
    probability mixture and is equivariant by construction;
  * per-rung tolerance floors (1/8/32/128/512) pass on a representable
    net and genuinely REFUSE (typed) on a near-uniform random net whose
    argmax quant noise flips — a failing variant never serves;
  * a mixed-variant fleet performs zero steady-state compiles under
    DEEPGO_XLACHECK=1 and hot-swaps weights mid-traffic with every
    future resolving to exactly the old- or new-checkpoint output;
  * the Pallas fused gather+expand kernel matches the XLA path bit for
    bit (interpret mode), and the cost ledger prices every variant
    program under the right entrypoint names.
"""

import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepgo_tpu.models import ModelConfig, init, quant
from deepgo_tpu.models.serving import make_log_prob_fn, make_sym_policy_fn
from deepgo_tpu.serving import (EngineConfig, VariantToleranceError,
                                fleet_policy_engine, policy_engine,
                                variant_spec, verify_variant)

CFG = ModelConfig(num_layers=2, channels=8)
ECFG = EngineConfig(buckets=(1, 8), max_wait_ms=0.0)
FAST_TOL = quant.ToleranceConfig(boards=32)


def boards(n, seed=0, hi=3):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, hi, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


def grid_net(cfg=CFG, seed=0, sharp=4.0):
    """A net the int8 scheme represents exactly: weights snapped onto
    the po2 grid (quantization is then lossless) plus a sharp final
    per-position bias so argmax has real margins."""
    params = init(jax.random.key(seed), cfg)
    snapped = quant.dequantize_params(quant.quantize_params(params))
    rng = np.random.default_rng(seed)
    snapped["layers"][-1]["b"] = jnp.asarray(
        rng.normal(0.0, sharp, size=(19, 19, 1)).astype(np.float32))
    return snapped


class TestQuantization:
    def test_quantize_shapes_dtypes_and_po2_scales(self):
        params = init(jax.random.key(0), CFG)
        qp = quant.quantize_params(params)
        for layer, qlayer in zip(params["layers"], qp["layers"]):
            w = np.asarray(layer["w"])
            assert np.asarray(qlayer["w_q"]).dtype == np.int8
            assert qlayer["w_q"].shape == w.shape
            scale = np.asarray(qlayer["w_scale"])
            assert scale.shape == (w.shape[-1],)
            assert (scale > 0).all()
            # power-of-two scales: log2 is integral
            assert np.allclose(np.log2(scale), np.round(np.log2(scale)))
            # symmetric: round-trip error bounded by half a step
            err = np.abs(np.asarray(qlayer["w_q"], np.float32) * scale - w)
            assert (err <= scale / 2 + 1e-7).all()

    def test_grid_weights_roundtrip_bitwise(self):
        params = grid_net()
        qp = quant.quantize_params(params)
        dq = quant.dequantize_params(qp)
        for a, b in zip(params["layers"], dq["layers"]):
            assert (np.asarray(a["w"]) == np.asarray(b["w"])).all()

    def test_grid_net_int8_forward_bitwise_equals_f32(self):
        # THE po2 identity: the epilogue-folded int8 forward is
        # numerically equivalent to the reference forward over the
        # dequantized weights — for grid weights, bit-identical
        params = grid_net()
        qp = quant.quantize_params(params)
        ref = make_log_prob_fn(CFG)
        var = quant.make_quant_log_prob_fn(CFG)
        pk, pl, rk = boards(16, seed=1)
        a = np.asarray(ref(params, pk, pl, rk))
        b = np.asarray(var(qp, pk, pl, rk))
        assert (a == b).all()

    def test_nongrid_equals_reference_over_dequantized_weights(self):
        # arbitrary weights: int8 path == reference path run on the
        # dequantized tree, bit for bit — zero compute-path noise is
        # what makes the tolerance floors meaningful
        params = init(jax.random.key(2), CFG)
        qp = quant.quantize_params(params)
        ref = make_log_prob_fn(CFG)
        var = quant.make_quant_log_prob_fn(CFG)
        pk, pl, rk = boards(8, seed=2)
        a = np.asarray(ref(quant.dequantize_params(qp), pk, pl, rk))
        b = np.asarray(var(qp, pk, pl, rk))
        assert (a == b).all()


class TestFusedSym:
    def test_sym_disabled_bitwise_equals_plain(self):
        # symmetries=1 is the identity view alone: the fused program
        # must reproduce the plain forward BIT FOR BIT
        params = init(jax.random.key(0), CFG)
        plain = make_log_prob_fn(CFG)
        one = quant.make_fused_sym_policy_fn(CFG, symmetries=1)
        pk, pl, rk = boards(8, seed=3)
        assert (np.asarray(plain(params, pk, pl, rk))
                == np.asarray(one(params, pk, pl, rk))).all()

    def test_fused_matches_reference_mixture(self):
        # log-sum-exp averaging == log of the softmax mixture the
        # unfused make_sym_policy_fn computes
        cfg = ModelConfig(num_layers=2, channels=8,
                          compute_dtype="float32")
        params = init(jax.random.key(1), cfg)
        fused = quant.make_fused_sym_policy_fn(cfg)
        old = make_sym_policy_fn(cfg)
        pk, pl, rk = boards(4, seed=5)
        np.testing.assert_allclose(
            np.asarray(fused(params, pk, pl, rk)),
            np.asarray(old(params, pk, pl, rk)), rtol=2e-4, atol=1e-5)

    def test_fused_is_equivariant(self):
        from deepgo_tpu.ops.augment import _PERM_NP, _TARGET_MAP_NP

        cfg = ModelConfig(num_layers=2, channels=8,
                          compute_dtype="float32")
        params = init(jax.random.key(1), cfg)
        fused = quant.make_fused_sym_policy_fn(cfg)
        pk, pl, rk = boards(4, seed=6)
        base = np.asarray(fused(params, pk, pl, rk))
        k = 5
        flat = pk.reshape(4, 9, 361)
        t_pk = flat[:, :, _PERM_NP[k]].reshape(4, 9, 19, 19)
        t_out = np.asarray(fused(params, t_pk, pl, rk))
        np.testing.assert_allclose(t_out[:, _TARGET_MAP_NP[k]], base,
                                   rtol=2e-4, atol=1e-6)

    def test_int8_sym_bitwise_on_grid_net(self):
        params = grid_net()
        qp = quant.quantize_params(params)
        f8 = quant.make_fused_sym_policy_fn(CFG)
        f8q = quant.make_fused_sym_policy_fn(CFG, quant=True)
        pk, pl, rk = boards(8, seed=7)
        assert (np.asarray(f8(params, pk, pl, rk))
                == np.asarray(f8q(qp, pk, pl, rk))).all()

    def test_bad_symmetries_rejected(self):
        with pytest.raises(ValueError):
            quant.make_fused_sym_policy_fn(CFG, symmetries=9)


class TestToleranceHarness:
    def test_grid_net_passes_every_rung(self):
        # the full ladder, every rung at its own jitted shape, pooled
        # boards — bitwise representability means exactly 1.0 / 0.0
        params = grid_net()
        qp = quant.quantize_params(params)
        rep = quant.tolerance_report(
            make_log_prob_fn(CFG), params,
            quant.make_quant_log_prob_fn(CFG), qp,
            buckets=(1, 8, 32, 128, 512), config=FAST_TOL)
        assert rep["verdict"] == "pass"
        assert set(rep["rungs"]) == {"1", "8", "32", "128", "512"}
        for rung in rep["rungs"].values():
            assert rung["top1_agreement"] == 1.0
            assert rung["max_abs_logprob_drift"] == 0.0

    def test_undecided_net_refuses_typed(self):
        # a near-uniform random-init net: quant noise flips argmax on
        # real tie-breaks, the floors fail, and the variant REFUSES —
        # this is the genuine failure path, not a rigged threshold
        params = init(jax.random.key(9), CFG)
        with pytest.raises(VariantToleranceError) as ei:
            verify_variant(CFG, params, "int8", buckets=(8, 32),
                           tolerance=quant.ToleranceConfig(boards=64))
        report = ei.value.report
        assert report["verdict"] == "fail"
        assert report["worst_top1"] < 0.99

    def test_exact_variants_pass_trivially(self):
        params = init(jax.random.key(0), CFG)
        for v in ("f32", "sym"):
            out = verify_variant(CFG, params, v)
            assert out == {"variant": v, "verdict": "pass", "exact": True}

    def test_int8_sym_gated_against_f32_sym_reference(self):
        params = grid_net()
        out = verify_variant(CFG, params, "int8+sym", buckets=(1, 8),
                             tolerance=FAST_TOL)
        assert out["verdict"] == "pass"
        assert out["variant"] == "int8+sym"

    def test_tolerance_publishes_gauges(self):
        from deepgo_tpu.obs import get_registry

        params = grid_net()
        qp = quant.quantize_params(params)
        quant.tolerance_report(
            make_log_prob_fn(CFG), params,
            quant.make_quant_log_prob_fn(CFG), qp, buckets=(8,),
            config=FAST_TOL, variant="int8")
        snap = get_registry().snapshot()["metrics"]
        assert "deepgo_quant_top1_agreement" in snap
        assert "deepgo_quant_logprob_drift" in snap

    def test_unknown_variant_rejected(self):
        with pytest.raises(ValueError):
            variant_spec(CFG, "fp4")


class TestVariantEngines:
    def test_engine_stamped_and_bitwise(self):
        params = grid_net()
        eng = policy_engine(params, CFG, config=ECFG, variant="int8",
                            tolerance=FAST_TOL, name="q-stamp")
        try:
            assert eng.variant == "int8"
            assert eng.prepare_params is quant.quantize_params
            pk, pl, rk = boards(4, seed=11)
            got = eng.evaluate(pk, pl, rk)
            ref = np.asarray(make_log_prob_fn(CFG)(params, pk, pl, rk))
            assert (got == ref).all()
        finally:
            eng.close()

    def test_mixed_fleet_zero_steady_state_recompiles_xlacheck(self):
        from deepgo_tpu.analysis import xlacheck

        params = grid_net()
        xlacheck.enable(True)
        xlacheck.reset()
        try:
            fleet = fleet_policy_engine(
                params, CFG, replicas=2, config=ECFG,
                variants=("f32", "int8"), tolerance=FAST_TOL,
                name="q-xla")
            try:
                fleet.warmup()
                warm = fleet.compile_cache_size()
                # mixed-count traffic over both variants' replicas
                for n, seed in ((1, 1), (3, 2), (8, 3), (5, 4)):
                    pk, pl, rk = boards(n, seed=seed)
                    fleet.evaluate(pk, pl, rk)
                report = xlacheck.report()
                assert report["steady_state_compiles"] == 0
                assert report["transfers"] == []
                assert fleet.compile_cache_size() == warm
            finally:
                fleet.close()
        finally:
            xlacheck.enable(None)
            xlacheck.reset()

    def test_hot_swap_mid_reload_exactly_old_or_new(self):
        # futures streaming through a mixed-variant fleet during a
        # reload must each resolve to EXACTLY the old- or new-checkpoint
        # output (grid nets: the int8 replica's rows are bitwise f32's,
        # so the old/new reference pair covers both variants)
        old_params = grid_net(seed=0)
        new_params = grid_net(seed=5)
        ref_fn = make_log_prob_fn(CFG)
        fleet = fleet_policy_engine(
            old_params, CFG, replicas=2, config=ECFG,
            variants=("f32", "int8"), tolerance=FAST_TOL, name="q-swap")
        try:
            fleet.warmup()
            warm = fleet.compile_cache_size()
            pk, pl, rk = boards(6, seed=13)
            old_ref = np.asarray(ref_fn(old_params, pk, pl, rk))
            new_ref = np.asarray(ref_fn(new_params, pk, pl, rk))
            stop = threading.Event()
            results, errors = [], []

            def submitter(i):
                while not stop.is_set():
                    try:
                        row = fleet.submit(pk[i], int(pl[i]),
                                           int(rk[i])).result(timeout=10)
                        results.append((i, np.asarray(row)))
                    except Exception as e:  # noqa: BLE001 — the assert
                        errors.append(repr(e))
                        return

            threads = [threading.Thread(target=submitter, args=(i,),
                                        name=f"q-swap-{i}", daemon=True)
                       for i in range(len(pk))]
            for t in threads:
                t.start()
            out = fleet.reload(new_params)
            stop.set()
            for t in threads:
                t.join(timeout=10)
            assert out["replicas"] == 2
            assert not errors, f"futures dropped mid-reload: {errors[:3]}"
            assert results
            for i, row in results:
                ok = (row == old_ref[i]).all() or (row == new_ref[i]).all()
                assert ok, f"row {i} is neither old nor new output"
            # the swap (including the int8 replica's re-quantization)
            # must not recompile: same shapes, same dtypes, warm cache
            assert fleet.compile_cache_size() == warm
            # steady state converges on the new checkpoint
            post = fleet.evaluate(pk, pl, rk)
            assert (post == new_ref).all()
        finally:
            fleet.close()

    def test_failing_variant_never_builds_a_fleet(self):
        params = init(jax.random.key(9), CFG)  # undecided net
        with pytest.raises(VariantToleranceError):
            fleet_policy_engine(params, CFG, replicas=2, config=ECFG,
                                variants=("f32", "int8"),
                                tolerance=quant.ToleranceConfig(boards=64),
                                name="q-refuse")


class TestPallasSymExpand:
    def test_interpret_parity_with_xla_path(self):
        from deepgo_tpu.ops import expand_planes
        from deepgo_tpu.ops.augment import _PERM_NP
        from deepgo_tpu.ops.pallas_expand import expand_planes_sym_pallas

        pk, pl, rk = boards(4, seed=17, hi=7)
        flat = pk.reshape(4, 9, 361)
        views = flat[:, :, _PERM_NP].transpose(2, 0, 1, 3) \
            .reshape(32, 9, 19, 19)
        ref = np.asarray(expand_planes(
            jnp.asarray(views), jnp.asarray(np.tile(pl, 8)),
            jnp.asarray(np.tile(rk, 8)), dtype=jnp.float32))
        got = np.asarray(expand_planes_sym_pallas(
            jnp.asarray(pk), jnp.asarray(pl), jnp.asarray(rk),
            dtype=jnp.float32, interpret=True))
        assert (ref == got).all()

    def test_block_fallback_for_odd_batches(self):
        from deepgo_tpu.ops import expand_planes
        from deepgo_tpu.ops.augment import _PERM_NP
        from deepgo_tpu.ops.pallas_expand import expand_planes_sym_pallas

        pk, pl, rk = boards(3, seed=18, hi=7)
        got = np.asarray(expand_planes_sym_pallas(
            jnp.asarray(pk), jnp.asarray(pl), jnp.asarray(rk),
            dtype=jnp.float32, interpret=True))
        flat = pk.reshape(3, 9, 361)
        views = flat[:, :, _PERM_NP].transpose(2, 0, 1, 3) \
            .reshape(24, 9, 19, 19)
        ref = np.asarray(expand_planes(
            jnp.asarray(views), jnp.asarray(np.tile(pl, 8)),
            jnp.asarray(np.tile(rk, 8)), dtype=jnp.float32))
        assert (ref == got).all()


class TestCostLedgerVariants:
    def test_variant_entries_named_and_bucketed(self):
        from deepgo_tpu.obs import costmodel
        from deepgo_tpu.serving.variants import variant_fn_name

        led = costmodel.CostLedger()
        costmodel.quant_entries(led, CFG, buckets=(1, 8))
        costmodel.fused_sym_entry(led, CFG, bucket=8)
        costmodel.fused_sym_entry(led, CFG, bucket=8, quant=True)
        costmodel.variant_entries(led, CFG, "sym", buckets=(1,))
        keys = {e.key for e in led.entries}
        assert {"quant_forward/b1", "quant_forward/b8",
                "fused_sym_forward/b8", "fused_sym_int8_forward/b8",
                "fused_sym_forward/b1"} <= keys
        assert variant_fn_name("int8") == "quant_forward"
        # conv FLOPs are precision-independent; the fused program's are
        # the ensemble's 8x (fusion buys dispatch economics, not math)
        q8 = led.get("quant_forward", 8)
        f8 = led.get("fused_sym_forward", 8)
        assert q8.flops > 0 and f8.flops > 0
        if q8.source == "xla" and f8.source == "xla":
            assert f8.flops > 6 * q8.flops

    def test_dispatch_seconds_engine_filter(self):
        from deepgo_tpu.obs import costmodel

        snap = {"deepgo_serving_dispatch_seconds": {"series": {
            "engine=a,bucket=8": {"sum": 2.0, "count": 2},
            "engine=b,bucket=8": {"sum": 8.0, "count": 2},
        }}}
        assert costmodel.dispatch_seconds_by_bucket(snap) == {8: 2.5}
        assert costmodel.dispatch_seconds_by_bucket(snap, engine="a") \
            == {8: 1.0}
        assert costmodel.dispatch_seconds_by_bucket(snap, engine="b") \
            == {8: 4.0}


class TestBenchGateFold:
    def test_variant_tolerance_failure_fails_the_gate(self):
        # --variant fold: a refused/failed variant fails the --gate
        # verdict even when throughput itself passed
        import json
        import os
        import tempfile

        import bench

        class Args:
            gate = 0.10

        result = {
            "metric": "m", "value": 100.0, "device": "d",
            "variant": {"name": "int8", "served": False,
                        "tolerance": {"verdict": "fail"}},
        }
        entry = {"metric": "m", "value": 100.0, "device": "d"}
        real = bench.LAST_GOOD_PATH
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"m": entry}, f)
        bench.LAST_GOOD_PATH = f.name
        try:
            bench._apply_gate(result, Args())
        finally:
            bench.LAST_GOOD_PATH = real
            os.unlink(f.name)
        gate = result["gate"]
        assert gate["variant_tolerance"] == "fail"
        assert gate["verdict"] == "fail"
        assert "int8" in gate["reason"]

    def test_variant_tolerance_pass_leaves_gate_alone(self):
        import json
        import os
        import tempfile

        import bench

        class Args:
            gate = 0.10

        result = {
            "metric": "m", "value": 100.0, "device": "d",
            "variant": {"name": "int8", "served": True,
                        "tolerance": {"verdict": "pass"}},
        }
        entry = {"metric": "m", "value": 100.0, "device": "d"}
        real = bench.LAST_GOOD_PATH
        with tempfile.NamedTemporaryFile("w", suffix=".json",
                                         delete=False) as f:
            json.dump({"m": entry}, f)
        bench.LAST_GOOD_PATH = f.name
        try:
            bench._apply_gate(result, Args())
        finally:
            bench.LAST_GOOD_PATH = real
            os.unlink(f.name)
        assert result["gate"]["verdict"] == "pass"
        assert result["gate"]["variant_tolerance"] == "pass"


class TestArenaVariantGate:
    @pytest.mark.slow
    def test_standard_gate_int8_vs_f32_champion(self):
        # the live A/B: the int8 champion against the f32 one under the
        # pinned arena protocol, both sides riding shared variant
        # engines. Grid net => the quantized side plays BIT-IDENTICAL
        # moves, so the color-balanced match cannot show a strength gap.
        from deepgo_tpu.agents import PolicyAgent
        from deepgo_tpu.match import standard_gate
        from deepgo_tpu.serving import (close_shared_engines,
                                        shared_policy_engine)

        params = grid_net()
        try:
            e_f32 = shared_policy_engine(params, CFG, config=ECFG)
            e_int8 = shared_policy_engine(params, CFG, config=ECFG,
                                          variant="int8")
            a = PolicyAgent(params, CFG, name="int8", engine=e_int8)
            b = PolicyAgent(params, CFG, name="f32", engine=e_f32)
            _, _, stats = standard_gate(a, b, n_games=4, max_moves=24)
            assert stats["games"] == 4
            assert stats["protocol"]["opening_plies"] == 8
            # bit-identical policies + color-swapped shared openings:
            # every decided pair splits, so A cannot lose the gate
            assert 0.0 <= stats["win_rate_a"] <= 1.0
            assert stats["int8_wins"] + stats["f32_wins"] \
                + stats["draws"] == 4
        finally:
            close_shared_engines()
