"""Invariant linter: fixture exactness, pragma grammar, clean-tree run,
and the code<->docs grammar drift checker (docs/static_analysis.md).

The fixture tests pin EXACT (rule, line) sets over known-bad snippets —
a rule that drifts to a different line or stops firing fails loudly. The
clean-tree test is the PR's own acceptance gate: the real repo must lint
with zero strict findings, in both directions of the grammar check.
"""

import json
import os

import pytest

from deepgo_tpu.analysis.config import LintConfig
from deepgo_tpu.analysis.grammar import (extract_code_grammar,
                                         extract_doc_grammar, lint_grammar)
from deepgo_tpu.analysis.linter import format_report, run_lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join("tests", "lint_fixtures")


def fixture_findings(name):
    return run_lint(REPO, paths=[os.path.join(FIXTURES, name)])


def keyed(findings):
    return sorted((f.rule, f.line) for f in findings)


# ---------------------------------------------------------------------------
# per-rule fixtures: exact rule ids at exact lines


def test_atomic_write_fixture():
    assert keyed(fixture_findings("bad_atomic.py")) == [
        ("atomic-write", 9),   # open(path, "w")
        ("atomic-write", 14),  # np.save to a path expression
        ("atomic-write", 18),  # np.savez to a path expression
    ]  # the append-mode open is NOT here: JSONL streams are legal


def test_determinism_fixture():
    assert keyed(fixture_findings("bad_determinism.py")) == [
        ("determinism", 10),  # time.time()
        ("determinism", 14),  # random.random()
        ("determinism", 18),  # unseeded random.Random()
        ("determinism", 22),  # np.random.rand
    ]  # default_rng / monotonic / seeded Random are NOT findings


def test_thread_fixture():
    assert keyed(fixture_findings("bad_thread.py")) == [
        ("thread-discipline", 7),  # anonymous
        ("thread-discipline", 7),  # neither daemon nor joined
        ("thread-discipline", 13),  # named but never daemon/joined
    ]


def test_typed_error_fixture():
    assert keyed(fixture_findings("bad_typed_error.py")) == [
        ("typed-error", 7),   # bare except
        ("typed-error", 12),  # assert (explicit paths open the scope)
    ]


def test_pragma_fixture():
    # the reasoned pragma (line 6/7) suppresses its finding entirely;
    # a reasonless pragma and an unknown rule id are findings themselves
    # AND fail to suppress
    assert keyed(fixture_findings("bad_pragma.py")) == [
        ("atomic-write", 12),
        ("atomic-write", 18),
        ("pragma", 12),
        ("pragma", 17),
    ]


def test_jit_boundary_fixture():
    assert keyed(fixture_findings("bad_jit_boundary.py")) == [
        ("jit-boundary", 15),  # jitted method reads self.scale
        ("jit-boundary", 20),  # jit bakes module-level mutable array
        ("jit-boundary", 24),  # str-default param without static_argnames
        ("jit-boundary", 34),  # shard_map'd fn bakes module state
        ("jit-boundary", 41),  # jit-wrapped-by-assignment fn
    ]  # ok_static / the pragma'd read / plain host reads are NOT here


def test_hot_sync_fixture():
    assert keyed(fixture_findings("bad_hot_sync.py")) == [
        ("hot-sync", 8),   # np.asarray on a forward result
        ("hot-sync", 12),  # .item()
        ("hot-sync", 16),  # jax.block_until_ready
        ("hot-sync", 21),  # .block_until_ready() method form
        ("hot-sync", 26),  # jax.device_get
        ("hot-sync", 30),  # float(<device call>)
    ]  # float(np.percentile(...)) and the pragma'd site are NOT here


def test_donation_fixture():
    assert keyed(fixture_findings("bad_donation.py")) == [
        ("donation", 8),   # params+opt_state jit without donate_argnums
        ("donation", 13),  # *step taking params, no donation
        ("donation", 24),  # donated buffer read after the call
    ]  # good_step / run_ok's rebind / the pragma'd def are NOT here


def test_constant_upload_fixture():
    assert keyed(fixture_findings("bad_constant_upload.py")) == [
        ("constant-upload", 10),  # per-call jnp.asarray(CONST)
        ("constant-upload", 16),  # re-baked per trace inside a jit
    ]  # factory-scope hoist / lowercase locals / pragma are NOT here


def test_bare_sleep_fixture():
    assert keyed(fixture_findings("bad_bare_sleep.py")) == [
        ("bare-sleep", 8),   # time.sleep by attribute
        ("bare-sleep", 12),  # from-import sleep() call
    ]  # the pragma'd call and the injected wait= hook are NOT here


def test_clean_fixture_has_no_findings():
    assert fixture_findings("clean_ok.py") == []


def test_format_report_shape():
    findings = fixture_findings("bad_atomic.py")
    text = format_report(findings)
    assert "bad_atomic.py:9: [strict] atomic-write:" in text
    assert "fix[atomic-write]" in text
    assert "3 finding(s)" in text


# ---------------------------------------------------------------------------
# the real tree: the repo must lint clean (strict) after this PR's fixes


def test_repo_lints_clean_strict():
    findings = run_lint(REPO)
    strict = [f for f in findings if f.level == "strict"]
    assert strict == [], "\n" + format_report(strict)


def test_tools_are_warn_level_only():
    findings = run_lint(REPO)
    tool_findings = [f for f in findings if f.path.startswith("tools/")]
    # the checked-in exemption: legacy one-offs are surfaced, not blocking
    assert tool_findings, "expected the known tools/ legacy findings"
    assert all(f.level == "warn" for f in tool_findings)


def test_grammar_drift_clean_on_repo():
    findings = lint_grammar(REPO)
    assert findings == [], "\n" + format_report(findings)


# ---------------------------------------------------------------------------
# grammar drift: both directions over a synthetic tree


def _mini_repo(tmp_path, code, docs):
    (tmp_path / "pkg").mkdir()
    (tmp_path / "pkg" / "mod.py").write_text(code)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "grammar.md").write_text(docs)
    return LintConfig(grammar_code_roots=("pkg",),
                      grammar_docs=("docs/grammar.md",))


CODE = """
def setup(reg, metrics, faults):
    c = reg.counter("deepgo_widget_spins_total", "spins")
    reg.gauge("deepgo_widget_depth", "depth")
    metrics.write("loop_widget_turn", n=1)
    faults.check("widget_io")
    return c
"""

DOCS = """
| metric | kind |
|---|---|
| `deepgo_widget_spins_total` / `_stops_total` | counter |
| `deepgo_widget_depth` | gauge |

Events: `loop_widget_turn` is emitted per turn.

| site | location |
|---|---|
| `widget_io` | the widget gather |
"""


def test_grammar_clean_when_docs_match(tmp_path):
    # deepgo_widget_stops_total is documented via continuation but never
    # emitted -> one docs->code finding; everything else is in parity
    cfg = _mini_repo(tmp_path, CODE, DOCS)
    findings = lint_grammar(str(tmp_path), cfg)
    assert [f.rule for f in findings] == ["grammar-drift"]
    assert "_stops_total" in findings[0].message


def test_grammar_flags_undocumented_code(tmp_path):
    cfg = _mini_repo(
        tmp_path,
        CODE + """

def more(reg, metrics, faults):
    reg.histogram("deepgo_widget_latency_seconds", "latency")
    metrics.write("fleet_widget_died")
    faults.check("widget_write")
""",
        DOCS.replace(" / `_stops_total`", ""))
    findings = lint_grammar(str(tmp_path), cfg)
    messages = "\n".join(f.message for f in findings)
    assert "deepgo_widget_latency_seconds" in messages  # metric undoc'd
    assert "fleet_widget_died" in messages              # event undoc'd
    assert "widget_write" in messages                   # site undoc'd
    assert all(f.rule == "grammar-drift" for f in findings)
    # code-side findings point at the emitting file
    assert {f.path for f in findings} == {"pkg/mod.py"}


def test_grammar_flags_orphaned_docs(tmp_path):
    cfg = _mini_repo(
        tmp_path, CODE,
        DOCS.replace(" / `_stops_total`", "")
        + "\nAlso `deepgo_widget_renamed_total` and the `obs_widget_gone`"
          " event.\n")
    findings = lint_grammar(str(tmp_path), cfg)
    messages = "\n".join(f.message for f in findings)
    assert "deepgo_widget_renamed_total" in messages
    assert "obs_widget_gone" in messages
    assert {f.path for f in findings} == {"docs/grammar.md"}


def test_grammar_continuation_expansion(tmp_path):
    # `deepgo_widget_spins_total` / `_stops_total` documents BOTH names
    code = CODE + """

def also(reg):
    reg.counter("deepgo_widget_stops_total", "stops")
"""
    cfg = _mini_repo(tmp_path, code, DOCS)
    assert lint_grammar(str(tmp_path), cfg) == []


def test_grammar_site_table_direction(tmp_path):
    cfg = _mini_repo(tmp_path, CODE,
                     DOCS + "| `widget_never_fires` | nowhere |\n")
    findings = lint_grammar(str(tmp_path), cfg)
    assert any("widget_never_fires" in f.message
               and "fault site" in f.message for f in findings)


def test_code_and_doc_extraction_shapes(tmp_path):
    cfg = _mini_repo(tmp_path, CODE, DOCS)
    code = extract_code_grammar(str(tmp_path), cfg)
    assert set(code["metrics"]) == {"deepgo_widget_spins_total",
                                    "deepgo_widget_depth"}
    assert set(code["events"]) == {"loop_widget_turn"}
    assert set(code["sites"]) == {"widget_io"}
    rel, line = code["metrics"]["deepgo_widget_spins_total"]
    assert rel == "pkg/mod.py" and line == 3
    docs = extract_doc_grammar(str(tmp_path), cfg)
    assert "deepgo_widget_depth" in docs["full"]
    assert ("deepgo_widget_spins_total", "_stops_total") in [
        (b, c) for b, c, _d, _l in docs["continuations"]]
    assert set(docs["sites"]) == {"widget_io"}


# ---------------------------------------------------------------------------
# cli integration


def test_cli_lint_json_exit_code(capsys):
    from deepgo_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["lint", "--root", REPO, "--json", "--no-grammar",
                  os.path.join(FIXTURES, "bad_atomic.py")])
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out)
    assert out["strict"] == 3
    rules = {f["rule"] for f in out["findings"]}
    assert rules == {"atomic-write"}
    assert all(f["hint"] for f in out["findings"])


def test_cli_lint_json_includes_xla_rule_ids(capsys):
    from deepgo_tpu import cli

    with pytest.raises(SystemExit) as exc:
        cli.main(["lint", "--root", REPO, "--json", "--no-grammar",
                  os.path.join(FIXTURES, "bad_jit_boundary.py"),
                  os.path.join(FIXTURES, "bad_hot_sync.py"),
                  os.path.join(FIXTURES, "bad_donation.py"),
                  os.path.join(FIXTURES, "bad_constant_upload.py")])
    assert exc.value.code == 1
    out = json.loads(capsys.readouterr().out)
    rules = {f["rule"] for f in out["findings"]}
    assert {"jit-boundary", "hot-sync", "donation",
            "constant-upload"} <= rules
    assert all(f["hint"] for f in out["findings"])


def test_cli_lint_clean_tree_exits_zero(capsys):
    from deepgo_tpu import cli

    cli.main(["lint", "--root", REPO])  # must not raise SystemExit(1)
    out = capsys.readouterr().out
    assert "0 finding(s)" in out
