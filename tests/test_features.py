"""Feature schema tests: packed record -> 37 expanded model planes."""

import numpy as np
import pytest

from deepgo_tpu import features
from deepgo_tpu.go import new_board, play, summarize


def _sample_packed():
    stones, age = new_board()
    moves = [(3, 3, 1), (15, 15, 2), (3, 4, 1), (15, 16, 2), (16, 16, 1)]
    for x, y, p in moves:
        play(stones, age, x, y, p)
    return summarize(stones, age)


@pytest.mark.parametrize("player", [1, 2])
def test_expand_shapes_and_binarity(player):
    packed = _sample_packed()
    planes = features.expand_planes_np(packed, player=player, rank=5)
    assert planes.shape == (37, 19, 19)
    assert set(np.unique(planes)) <= {0.0, 1.0}


def test_stone_planes_perspective():
    packed = _sample_packed()
    for player in (1, 2):
        planes = features.expand_planes_np(packed, player=player, rank=1)
        stones = packed[features.P_STONES]
        assert np.array_equal(planes[0], (stones == 0).astype(np.float32))
        assert np.array_equal(planes[1], (stones == player).astype(np.float32))
        assert np.array_equal(planes[2], (stones == 3 - player).astype(np.float32))
        # the three stone planes partition the board
        assert np.array_equal(planes[0] + planes[1] + planes[2], np.ones((19, 19)))


def test_rank_planes_one_hot():
    packed = _sample_packed()
    for rank in range(1, 10):
        planes = features.expand_planes_np(packed, player=1, rank=rank)
        rank_planes = planes[features.X_RANK_BASE:]
        assert rank_planes.shape[0] == 10  # base plane + 9 rank planes
        assert np.array_equal(rank_planes.sum(axis=(1, 2)) > 0,
                              np.arange(10) == rank)
        # the base plane (reference's unused RANK slot) is always zero
        assert planes[features.X_RANK_BASE].sum() == 0


def test_liberties_after_zero_plane_masked_to_empty():
    # plane X_LIB_AFTER is (empty AND lib_after == 0): occupied points have
    # lib_after 0 in the packed record but must not fire the plane.
    packed = _sample_packed()
    planes = features.expand_planes_np(packed, player=1, rank=3)
    stones = packed[features.P_STONES]
    assert planes[features.X_LIB_AFTER][stones != 0].sum() == 0


def test_age_planes_exact_match_only():
    packed = _sample_packed()
    planes = features.expand_planes_np(packed, player=1, rank=3)
    age = packed[features.P_AGE]
    for i in range(5):
        assert np.array_equal(planes[features.X_AGE + i], (age == i + 1).astype(np.float32))


def test_target_index():
    assert features.target_index(0, 0) == 0
    assert features.target_index(18, 18) == 360
    assert features.target_index(1, 0) == 19
