"""Batched PUCT MCTS over the fleet (deepgo_tpu.search, docs/search.md).

The contracts pinned here:

  * **determinism** — a fixed-budget search over a deterministic
    evaluator is a pure function of the position: same move, same root
    visit distribution, same principal variation, twice;
  * **virtual loss never double-counts** — after any search (including
    one with failed/timed-out leaf evaluations) every surviving visit
    is a completed simulation: root visits sum to exactly the completed
    count and, under a zero-value evaluator, no residual virtual loss
    survives in W (lost simulations revert bitwise);
  * **transposition entries map back through the inverse dihedral
    perms bitwise** — searching any dihedral view of a position yields
    the same canonical root digest, the `PERMS`-mapped move, and the
    exact permuted visit array (the tests/test_cache.py remap property
    lifted to whole trees, using the same gather-table conventions);
  * **the anytime contract** — a dead or stalled engine still produces
    a legal move (fallback accounted), a deadline bounds the wall;
  * **the acceptance gate** — the search agent beats the shallow
    ``value2:`` 2-ply agent at >= 55% under ``match.standard_gate`` at
    a pinned simulation budget (slow-marked; ``make verify-search``
    runs it).
"""

import time
from concurrent.futures import Future

import numpy as np
import pytest

from deepgo_tpu.agents import SearchAgent, Value2PlyAgent, _oneply_scores
from deepgo_tpu.features import P_STONES
from deepgo_tpu.models import policy_cnn
from deepgo_tpu.models.value_cnn import ValueConfig
from deepgo_tpu.match import standard_gate
from deepgo_tpu.search import (Search, SearchConfig, TranspositionTable,
                               game_from_packed, make_move_selector)
from deepgo_tpu.search.mcts import NUM_POINTS, PASS_EDGE
from deepgo_tpu.selfplay import (GameState, legal_mask, step_game,
                                 summarize_state, summarize_states)
from deepgo_tpu.serving import EngineClosed
from deepgo_tpu.utils.digest import INV_PERMS, PERMS


def prior_row(view, player):
    """Deterministic per-point 'log-prob' row from a packed view — the
    test_cache.py point_forward idiom (a pure per-point function of the
    channel column, bitwise stable), so two searches that submit the
    same canonical view get the same prior, and nothing else matters."""
    flat = np.asarray(view, np.float32).reshape(9, NUM_POINTS)
    return (flat.sum(axis=0) * 0.125
            + np.float32(player)).astype(np.float64)


class RowEngine:
    """Engine fake for the search's leaf path: deterministic rows in
    already-resolved futures, with scriptable failure modes.

    ``fail_at`` — submit indices (0-based) that raise EngineClosed at
    the door; ``error_at`` — submit indices whose FUTURE fails (the
    mid-flight kill shape); ``stall`` — futures are never resolved
    (deadline-expiry shape)."""

    def __init__(self, fail_at=(), error_at=(), stall=False):
        self.calls = []          # (view_bytes, player, tier, session)
        self.fail_at = set(fail_at)
        self.error_at = set(error_at)
        self.stall = stall

    def submit(self, packed, player, rank, tier=None, session=None,
               timeout_s=None):
        i = len(self.calls)
        self.calls.append((np.asarray(packed).tobytes(), int(player),
                           tier, session))
        if i in self.fail_at:
            raise EngineClosed("scripted door failure")
        f = Future()
        if i in self.error_at:
            f.set_exception(EngineClosed("scripted in-flight failure"))
        elif not self.stall:
            f.set_result(prior_row(packed, player))
        return f


class Sink:
    def __init__(self):
        self.events = []

    def write(self, kind, **fields):
        self.events.append((kind, fields))


def fresh_search(engine=None, metrics=None, **cfg_kw):
    cfg_kw.setdefault("simulations", 24)
    cfg_kw.setdefault("wave_size", 8)
    cfg_kw.setdefault("tier", "interactive")
    eng = engine if engine is not None else RowEngine()
    return Search(eng, SearchConfig(**cfg_kw), metrics=metrics), eng


def root_node(search, result):
    node = search.table.get(result.root_digest)
    assert node is not None
    return node


def played_game(moves):
    g = GameState()
    for m in moves:
        step_game(g, m, 450)
    return g


# -- basics + accounting ----------------------------------------------------


def test_search_returns_legal_move_with_exact_accounting():
    s, eng = fresh_search()
    g = GameState()
    legal = legal_mask(summarize_state(g)[None],
                       np.array([1], dtype=np.int32), [g])[0]
    res = s.search(g)

    assert res.move >= 0 and legal[res.move]
    assert not res.fallback and res.deadline_met
    assert res.simulations == 24 and res.lost == 0
    # every completed simulation passes the root exactly once
    node = root_node(s, res)
    assert float(node.N.sum()) == float(res.simulations)
    assert float(res.visits.sum() + res.pass_visits) == float(
        res.simulations)
    # leaf submits ride the search session label (trace/workload join)
    sessions = {c[3] for c in eng.calls}
    assert sessions == {f"search:{res.search_id}"}
    assert {c[2] for c in eng.calls} == {"interactive"}


def test_virtual_loss_fully_converts_to_real_visits():
    # no value engine + no terminals => every backed-up value is 0, so
    # any residue in W is exactly un-reverted virtual loss
    s, _ = fresh_search(simulations=32)
    res = s.search(GameState())
    node = root_node(s, res)
    assert res.lost == 0
    np.testing.assert_array_equal(node.W, np.zeros_like(node.W))


def test_lost_simulations_revert_bitwise():
    # fail some submits at the door AND some futures in flight: both
    # revert paths must leave N == completed count and W == 0 exactly
    eng = RowEngine(fail_at={3, 7}, error_at={5, 9, 11})
    s, _ = fresh_search(engine=eng, simulations=40, wave_size=8)
    g = GameState()
    legal = legal_mask(summarize_state(g)[None],
                       np.array([1], dtype=np.int32), [g])[0]
    res = s.search(g)

    assert res.lost >= 5
    assert res.simulations + res.lost == 40
    assert res.move >= 0 and legal[res.move]
    node = root_node(s, res)
    assert float(node.N.sum()) == float(res.simulations)
    np.testing.assert_array_equal(node.W, np.zeros_like(node.W))


def test_wave_dedup_one_submit_per_canonical_position():
    # within a wave, descents reaching the same position share one
    # submit; across waves the node is expanded — so every successful
    # submit carries a distinct (canonical view, player)
    s, eng = fresh_search(simulations=48, wave_size=16)
    s.search(GameState())
    keys = [(c[0], c[1]) for c in eng.calls]
    assert len(keys) == len(set(keys))


def test_search_determinism():
    g_moves = [3 * 19 + 3, 15 * 19 + 15, 3 * 19 + 15]
    r1 = fresh_search(simulations=32)[0].search(played_game(g_moves))
    r2 = fresh_search(simulations=32)[0].search(played_game(g_moves))
    assert r1.move == r2.move
    assert r1.pv == r2.pv
    assert r1.root_digest == r2.root_digest
    assert r1.value == r2.value
    np.testing.assert_array_equal(r1.visits, r2.visits)


# -- transposition table: canonical-frame remap -----------------------------


def dihedral_game(g, k):
    """View k of a game (digest.py gather convention:
    new_flat[p] = old_flat[PERMS[k][p]]); a stone at old position q
    lands at new index INV_PERMS[k][q]."""
    t = GameState()
    t.stones = g.stones.reshape(-1)[PERMS[k]].reshape(19, 19).copy()
    t.age = g.age.reshape(-1)[PERMS[k]].reshape(19, 19).copy()
    t.player = g.player
    return t


@pytest.mark.parametrize("k", range(8))
def test_transposition_remaps_through_inverse_perms_bitwise(k):
    # searching any dihedral view of a position: the tree lives in the
    # shared canonical frame, so the root digest is identical, the move
    # maps through INV_PERMS, and the actual-frame visit array is the
    # EXACT gather-permuted original (float64 visit counts, bitwise)
    g = played_game([3 * 19 + 3, 15 * 19 + 15, 3 * 19 + 4, 15 * 19 + 3])
    res_a = fresh_search(simulations=24)[0].search(g)
    res_b = fresh_search(simulations=24)[0].search(dihedral_game(g, k))

    assert res_b.root_digest == res_a.root_digest
    assert res_a.move >= 0
    assert res_b.move == int(INV_PERMS[k][res_a.move])
    np.testing.assert_array_equal(res_b.visits, res_a.visits[PERMS[k]])
    assert res_b.value == res_a.value


def test_shared_table_across_searchers_and_tree_reuse():
    table = TranspositionTable()
    eng = RowEngine()
    s1 = Search(eng, SearchConfig(simulations=24, wave_size=8), table=table)
    g = GameState()
    res1 = s1.search(g)

    # tree reuse: the chosen child's node is already in the table, so
    # the NEXT move's root is a hit, not a fresh expansion
    g2 = played_game([res1.move])
    from deepgo_tpu.utils.digest import canonicalize

    d2, _, _ = canonicalize(summarize_state(g2), g2.player, s1.cfg.rank)
    child = table.get(d2)
    assert child is not None and child.expanded

    # a second searcher over the same table starts warm: the root
    # expansion is a table hit (a cold 24-sim search pays 24 leaf
    # submits PLUS the root expand — 25)
    before = len(eng.calls)
    s2 = Search(eng, SearchConfig(simulations=24, wave_size=8), table=table)
    res2 = s2.search(g2)
    assert res2.move >= 0
    assert len(eng.calls) - before <= 24
    assert table.stats()["hits"] > 0


# -- anytime contract -------------------------------------------------------


def test_dead_engine_falls_back_to_lowest_legal():
    eng = RowEngine(fail_at=set(range(1000)))
    s, _ = fresh_search(engine=eng)
    res = s.search(GameState())
    assert res.fallback and res.simulations == 0
    assert res.move == 0  # lowest-index legal point on an empty board

    mask = np.ones(NUM_POINTS, dtype=bool)
    mask[:5] = False
    res2 = fresh_search(engine=RowEngine(fail_at=set(range(1000))))[0] \
        .search(GameState(), root_legal=mask)
    assert res2.fallback and res2.move == 5


def test_deadline_bounds_a_stalled_engine():
    s, _ = fresh_search(engine=RowEngine(stall=True))
    t0 = time.monotonic()
    res = s.search(GameState(), deadline_s=0.3)
    wall = time.monotonic() - t0
    assert res.fallback and res.move == 0
    assert wall < 2.0
    assert res.deadline_met


def test_root_legal_restricts_the_root_only():
    # ban everything but one point at the root: the verdict must honor
    # the caller's (superko-style) mask even though descents below the
    # root may still use the full board
    mask = np.zeros(NUM_POINTS, dtype=bool)
    mask[77] = True
    s, _ = fresh_search(simulations=16)
    res = s.search(GameState(), root_legal=mask)
    assert res.move == 77


# -- verdict event + selfplay hook + reconstruction -------------------------


def test_search_request_event_is_emitted():
    sink = Sink()
    s, _ = fresh_search(metrics=sink)
    res = s.search(GameState())
    kinds = [k for k, _ in sink.events]
    assert kinds == ["search_request"]
    rec = sink.events[0][1]
    assert rec["search_id"] == res.search_id
    assert rec["digest"] == res.root_digest
    assert rec["move"] == res.move
    assert rec["simulations"] == res.simulations
    assert rec["deadline_met"] is True and rec["fallback"] is False
    assert rec["pv"] == list(res.pv) and rec["tier"] == "interactive"


def test_make_move_selector_selfplay_hook():
    selector = make_move_selector(
        RowEngine(), SearchConfig(simulations=8, wave_size=4,
                                  temperature=1.0, root_noise_frac=0.25,
                                  tier="selfplay"))
    games = [GameState(), played_game([60, 80])]
    packed = summarize_states(games)
    players = np.array([g.player for g in games], dtype=np.int32)
    legal = legal_mask(packed, players, games)
    moves = selector(games, packed, players, legal, np.random.default_rng(0))
    assert len(moves) == 2
    for i, m in enumerate(moves):
        assert m == -1 or legal[i][m]
    assert selector.search.table.stats()["entries"] > 0


def test_game_from_packed_roundtrip_and_ko_recovery():
    g = played_game([3 * 19 + 3, 15 * 19 + 15, 3 * 19 + 4, 15 * 19 + 3,
                     10 * 19 + 10, -1])
    packed = summarize_state(g)
    g2 = game_from_packed(packed, g.player)
    assert g2.player == g.player
    np.testing.assert_array_equal(summarize_state(g2), packed)

    # classic ko: white at (1,1) inside a black mouth; black captures at
    # (1,2) -> the recapture at (1,1) is banned; the ban is recoverable
    # from the caller's legal row alone
    ko = GameState()
    for x, y in [(1, 0), (0, 1), (2, 1)]:
        ko.stones[x, y] = 1
    for x, y in [(0, 2), (2, 2), (1, 3), (1, 1)]:
        ko.stones[x, y] = 2
    ko.age[ko.stones > 0] = 1
    step_game(ko, 1 * 19 + 2, 450)
    assert ko.ko_point == (1, 1)
    pk = summarize_state(ko)
    row = legal_mask(pk[None], np.array([ko.player], dtype=np.int32),
                     [ko])[0]
    back = game_from_packed(pk, ko.player, row)
    assert back.ko_point == (1, 1)
    np.testing.assert_array_equal(summarize_state(back), pk)


def test_search_agent_selects_legal_batch():
    agent = SearchAgent(None, policy_cnn.CONFIGS["small"], simulations=8,
                        engine=RowEngine(),
                        search_config=SearchConfig(simulations=8,
                                                   wave_size=4))
    games = [GameState(), played_game([60])]
    packed = summarize_states(games)
    players = np.array([g.player for g in games], dtype=np.int32)
    legal = legal_mask(packed, players, games)
    moves = agent.select_moves(packed, players, legal,
                               np.random.default_rng(0))
    for i, m in enumerate(moves):
        assert m == -1 or legal[i][m]


# -- the acceptance gate: search beats the shallow value2 agent -------------
#
# The match design, tuned so the verdict measures SEARCH and not
# protocol noise:
#   * both agents share one prior (the tactical 1-ply row) and one value
#     function (exact Tromp-Taylor below), so the margin is the tree's;
#   * games truncate at an ODD move cap with komi 0.5 — an even cap with
#     equal stone counts hands every quiet game to white by komi alone,
#     i.e. color (not skill) would decide; the odd cap gives black the
#     offsetting extra stone, so capture/territory differentials decide;
#   * the value's sigmoid scale (0.15/point) sits against value2's
#     documented 0.08 veto margin: value2 ignores sub-half-stone 2-ply
#     gains by design, the search (a pure maximizer) banks them.

GATE_SIMS = 128      # the pinned simulation budget the gate is quoted at
GATE_N_GAMES = 12    # deterministic agents + pinned seed: one exact outcome
GATE_MAX_MOVES = 81  # truncated games, Tromp-Taylor scored at the cap
GATE_KOMI = 0.5


class TacticalPrior:
    """The SHARED policy prior of the gate match: the 1-ply tactical
    evaluation scaled into log-prob space. Both agents prune/guide with
    the same prior, so the gate isolates the SEARCH — 2-ply minimax over
    a handful of candidates vs a full PUCT tree at a pinned budget."""

    def evaluate(self, packed, players, ranks):
        score, _ = _oneply_scores(np.asarray(packed),
                                  np.asarray(players, dtype=np.int64))
        return score.astype(np.float64) / 400.0

    def submit(self, packed, player, rank, tier=None, session=None,
               timeout_s=None):
        f = Future()
        f.set_result(self.evaluate(
            np.asarray(packed)[None],
            np.array([player], dtype=np.int32), None)[0])
        return f


class AreaValue:
    """The SHARED evaluation: EXACT Tromp-Taylor area (stones plus empty
    regions reaching only one color, computed by vectorized iterative
    dilation — the flood fill as a fixpoint), squashed to a win
    probability for the side to move. Deterministic and identical to
    the match's final scoring, so both agents optimize the true
    objective; the deeper optimizer should realize more of it."""

    def __init__(self, scale=0.15, komi=GATE_KOMI):
        self.scale = scale
        self.komi = komi

    def evaluate(self, boards, to_move, ranks):
        stones = np.asarray(boards)[:, P_STONES]
        black, white = stones == 1, stones == 2
        empty = stones == 0

        def adj(mask):
            p = np.zeros((len(mask), 21, 21), dtype=bool)
            p[:, 1:20, 1:20] = mask
            return (p[:, :19, 1:20] | p[:, 2:, 1:20]
                    | p[:, 1:20, :19] | p[:, 1:20, 2:])

        reach_b, reach_w = black.copy(), white.copy()
        while True:
            grow_b = reach_b | (empty & adj(reach_b))
            grow_w = reach_w | (empty & adj(reach_w))
            if (grow_b == reach_b).all() and (grow_w == reach_w).all():
                break
            reach_b, reach_w = grow_b, grow_w
        margin = (black.sum((1, 2))
                  + (empty & reach_b & ~reach_w).sum((1, 2))
                  - white.sum((1, 2))
                  - (empty & reach_w & ~reach_b).sum((1, 2))
                  - self.komi).astype(np.float64)
        signed = np.where(np.asarray(to_move) == 1, margin, -margin)
        return 1.0 / (1.0 + np.exp(-self.scale * signed))


@pytest.mark.slow
def test_search_agent_beats_value2_under_standard_gate():
    """ISSUE 20's Elo gate: mcts >= 55% vs value2 under the pinned arena
    protocol (shared openings, color-swapped pairs, seed 29) at the
    pinned GATE_SIMS budget."""
    prior, value = TacticalPrior(), AreaValue()
    pcfg = policy_cnn.CONFIGS["small"]
    mcts = SearchAgent(
        None, pcfg, rank=8, simulations=GATE_SIMS, engine=prior,
        value_engine=value,
        search_config=SearchConfig(simulations=GATE_SIMS, wave_size=8,
                                   rank=8, tier=None, komi=GATE_KOMI))
    value2 = Value2PlyAgent(None, pcfg, None,
                            ValueConfig(num_layers=1, channels=4),
                            rank=8, engine=prior, value_engine=value)
    _, _, stats = standard_gate(mcts, value2, n_games=GATE_N_GAMES,
                                max_moves=GATE_MAX_MOVES, komi=GATE_KOMI)
    assert stats["win_rate_a"] >= 0.55, stats
