"""Fleet router over supervised replicas (serving/fleet.py).

The load-bearing contracts:

  * placement resolves every submit with rows bit-identical to the
    direct forward, spreading load over the replicas;
  * a replica death mid-request fails over WITH EXCLUSION to a healthy
    replica (bounded budget, typed exhaustion) while the fleet respawns
    the corpse in the background and /health degrades then recovers;
  * poison is final — a request whose own content fails the forward is
    never retried fleet-wide;
  * tiered admission sheds the cheap tier first (batch before selfplay
    before interactive), with per-tier counters;
  * ``reload`` rolls new weights through the replicas one at a time:
    results bitwise-identical to a fresh engine on the new weights,
    futures submitted mid-reload all resolve, zero recompiles (jit-cache
    counter), and an injected ``fleet_reload`` fault is typed while the
    replica rejoins;
  * every submitted future RESOLVES — result or typed error — through
    deaths, reloads, and close().
"""

import os
import random
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

import jax

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.models.serving import make_log_prob_fn
from deepgo_tpu.serving import (TIERS, CircuitOpen, EngineBusy,
                                EngineClosed, EngineConfig,
                                EngineOverloaded, FailoverExhausted,
                                FleetConfig, FleetReloadError, FleetRouter,
                                FleetUnavailable, InferenceEngine,
                                PoisonedRequest, SupervisedEngine,
                                SupervisorConfig, fleet_policy_engine)
from deepgo_tpu.utils import faults


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


def tiny():
    cfg = ModelConfig(num_layers=2, channels=8)
    return cfg, init(jax.random.key(0), cfg)


def boards(n, seed=0):
    rng = np.random.default_rng(seed)
    return (rng.integers(0, 3, size=(n, 9, 19, 19), dtype=np.uint8),
            rng.integers(1, 3, size=n).astype(np.int32),
            rng.integers(1, 10, size=n).astype(np.int32))


POISON_BOARD = np.full((9, 19, 19), 255, dtype=np.uint8)


def ok_forward(params, packed, player, rank):
    return np.asarray(packed, np.float32).sum(axis=(1, 2, 3)) \
        + 1000.0 * np.asarray(player, np.float32)


def marker_forward(params, packed, player, rank):
    if (packed == 255).all(axis=(1, 2, 3)).any():
        raise ValueError("poison row in batch")
    return ok_forward(params, packed, player, rank)


ECFG = EngineConfig(buckets=(1, 4), max_wait_ms=0.0)
# chaos replicas: no supervisor-level restarts, so a dispatcher death
# becomes a replica death and exercises the FLEET failure domain
DIE_FAST = SupervisorConfig(max_restarts=0, backoff_base_s=0.001,
                            backoff_cap_s=0.005)
FAST_FLEET = FleetConfig(respawn_base_s=0.001, respawn_cap_s=0.005)


def make_fleet(forward=ok_forward, replicas=2, fleet_config=FAST_FLEET,
               sup_config=None, engine_config=ECFG, **kw):
    def make_replica(i):
        return SupervisedEngine(
            lambda: InferenceEngine(forward, None, engine_config,
                                    name=f"rep{i}"),
            config=sup_config, name=f"rep{i}")

    kw.setdefault("rng", random.Random(0))
    return FleetRouter(make_replica, replicas, config=fleet_config,
                       name=kw.pop("name", "test-fleet"), **kw)


def wait_until(cond, timeout=10.0, interval=0.01):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


class FakeReplica:
    """Duck-typed replica with scripted behavior, for deterministic
    placement / shed / failover tests without threads or wall time."""

    def __init__(self, idx, est=None, submit_error=None):
        self.idx = idx
        self.est = est
        self.submit_error = submit_error
        self.submitted = 0

    def submit(self, packed, player, rank, timeout_s=None, block=True):
        if self.submit_error is not None:
            raise self.submit_error
        self.submitted += 1
        f = Future()
        f.set_result(np.float32(self.idx))
        return f

    def estimated_wait_s(self):
        return self.est

    def health(self):
        return {"state": "serving", "estimated_wait_s": self.est,
                "breaker": {"state": "closed"}}

    def stats(self):
        return {"boards": self.submitted}

    def warmup(self):
        return 0

    def compile_cache_size(self):
        return None

    def set_params(self, params):
        pass

    @property
    def params(self):
        return None

    def close(self, drain=True, timeout=1.0):
        pass


def fake_fleet(reps, fleet_config=None, **kw):
    return FleetRouter(lambda i: reps[i], len(reps),
                       config=fleet_config, name=kw.pop("name", "fakes"),
                       **kw)


class TestRouting:
    def test_submits_resolve_bitwise_and_spread(self):
        fleet = make_fleet(replicas=3)
        try:
            packed, players, ranks = boards(24, seed=1)
            futs = [fleet.submit(packed[i], int(players[i]), int(ranks[i]))
                    for i in range(24)]
            got = np.stack([np.atleast_1d(f.result(timeout=10))[0]
                            for f in futs])
            exp = ok_forward(None, packed, players, ranks)
            assert np.array_equal(got, exp)
            used = [s.get("boards", 0) for s in fleet.stats()["replicas"]]
            assert sum(b > 0 for b in used) >= 2, \
                f"placement never spread: {used}"
        finally:
            fleet.close()

    def test_least_wait_placement_prefers_idle_replica(self):
        busy = FakeReplica(0, est=5.0)
        idle = FakeReplica(1, est=0.01)
        fleet = fake_fleet([busy, idle])
        try:
            for _ in range(4):
                fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5) \
                     .result(timeout=5)
            assert idle.submitted == 4 and busy.submitted == 0
        finally:
            fleet.close()

    def test_invalid_tier_rejected(self):
        fleet = fake_fleet([FakeReplica(0)])
        try:
            with pytest.raises(ValueError, match="tier"):
                fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5,
                             tier="platinum")
        finally:
            fleet.close()

    def test_evaluate_matches_direct(self):
        fleet = make_fleet(replicas=2)
        try:
            packed, players, ranks = boards(6, seed=3)
            got = fleet.evaluate(packed, players, ranks)
            exp = ok_forward(None, packed, players, ranks)
            assert np.array_equal(np.asarray(got).ravel(), exp.ravel())
        finally:
            fleet.close()


class TestTiers:
    def test_cheap_tier_sheds_first(self):
        # est wait 0.5s vs a 1.0s deadline: batch headroom (0.3) is
        # exceeded, selfplay (0.6) and interactive (1.0) are not
        fleet = fake_fleet([FakeReplica(0, est=0.5)])
        try:
            board = np.zeros((9, 19, 19), np.uint8)
            with pytest.raises(EngineOverloaded):
                fleet.submit(board, 1, 5, tier="batch", timeout_s=1.0)
            fleet.submit(board, 1, 5, tier="selfplay",
                         timeout_s=1.0).result(timeout=5)
            fleet.submit(board, 1, 5, tier="interactive",
                         timeout_s=1.0).result(timeout=5)
            shed = fleet.health()["shed"]
            assert shed == {"interactive": 0, "selfplay": 0, "batch": 1}
        finally:
            fleet.close()

    def test_interactive_sheds_only_past_full_deadline(self):
        fleet = fake_fleet([FakeReplica(0, est=2.0)])
        try:
            board = np.zeros((9, 19, 19), np.uint8)
            with pytest.raises(EngineOverloaded):
                fleet.submit(board, 1, 5, tier="interactive", timeout_s=1.0)
            # no deadline -> never shed at admission
            fleet.submit(board, 1, 5, tier="batch").result(timeout=5)
        finally:
            fleet.close()

    def test_all_replicas_shedding_is_a_fleet_shed(self):
        reps = [FakeReplica(0, submit_error=CircuitOpen("r0 open")),
                FakeReplica(1, submit_error=EngineBusy("r1 full"))]
        fleet = fake_fleet(reps)
        try:
            with pytest.raises((CircuitOpen, EngineBusy)):
                fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5,
                             tier="batch")
            assert fleet.health()["shed"]["batch"] == 1
        finally:
            fleet.close()

    def test_replica_shed_reroutes_transparently(self):
        reps = [FakeReplica(0, est=0.0,
                            submit_error=EngineOverloaded("r0 loaded")),
                FakeReplica(1, est=1.0)]
        fleet = fake_fleet(reps)
        try:
            f = fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5)
            assert float(f.result(timeout=5)) == 1.0  # served by replica 1
            assert sum(fleet.health()["shed"].values()) == 0
        finally:
            fleet.close()


class TestFailover:
    def test_replica_death_fails_over_and_respawns(self):
        faults.install("serving_dispatch:fail@2")
        fleet = make_fleet(replicas=2, sup_config=DIE_FAST)
        try:
            packed, players, ranks = boards(12, seed=2)
            futs = [fleet.submit(packed[i], int(players[i]), int(ranks[i]))
                    for i in range(12)]
            got = np.stack([np.atleast_1d(f.result(timeout=20))[0]
                            for f in futs])
            # every future resolves bit-identically despite the death
            assert np.array_equal(
                got, ok_forward(None, packed, players, ranks))
            h = fleet.health()
            assert h["failovers"] >= 1
            # the corpse is rebuilt in the background
            assert wait_until(
                lambda: fleet.health()["respawns"] >= 1
                and fleet.health()["state"] == "serving"), fleet.health()
        finally:
            fleet.close()

    def test_poison_is_final_not_retried_fleetwide(self):
        fleet = make_fleet(forward=marker_forward, replicas=2)
        try:
            f = fleet.submit(POISON_BOARD, 1, 5)
            with pytest.raises(PoisonedRequest):
                f.result(timeout=20)
            h = fleet.health()
            assert h["poisoned"] == 1
            assert h["failovers"] == 0, \
                "poison must not burn the failover budget"
            # neighbors keep being served
            packed, players, ranks = boards(3, seed=4)
            got = fleet.evaluate(packed, players, ranks)
            assert np.array_equal(
                np.asarray(got).ravel(),
                ok_forward(None, packed, players, ranks).ravel())
        finally:
            fleet.close()

    def test_failover_budget_bounded_and_typed(self):
        err = EngineClosed("replica gone")
        reps = [FakeReplica(i, submit_error=err) for i in range(3)]
        cfg = FleetConfig(max_failovers=2)
        fleet = fake_fleet(reps, fleet_config=cfg)
        try:
            with pytest.raises((FleetUnavailable, FailoverExhausted)):
                f = fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5)
                raise f.exception(timeout=5)
        finally:
            fleet.close()

    def test_fleet_route_fault_absorbed(self):
        faults.install("fleet_route:transient@1")
        fleet = make_fleet(replicas=2)
        try:
            packed, players, ranks = boards(1, seed=5)
            f = fleet.submit(packed[0], int(players[0]), int(ranks[0]))
            got = np.atleast_1d(f.result(timeout=10))[0]
            assert got == ok_forward(None, packed, players, ranks)[0]
            assert fleet.health()["failovers"] == 1
        finally:
            fleet.close()

    def test_respawn_in_flight_widens_the_failover_budget(self):
        """The PR 12 fleet-2 chaos flake: a request whose hops land while
        the fleet is temporarily below strength (a replica mid-respawn)
        must NOT burn typed exhaustion against the missing capacity —
        with a zero failover budget and a single dying replica, the
        request parks, rides out the rebuild, and resolves with the
        correct row."""
        faults.install("serving_dispatch:fail@1")
        cfg = FleetConfig(max_failovers=0, respawn_base_s=0.01,
                          respawn_cap_s=0.02)
        fleet = make_fleet(replicas=1, sup_config=DIE_FAST,
                           fleet_config=cfg)
        try:
            packed, players, ranks = boards(1, seed=21)
            f = fleet.submit(packed[0], int(players[0]), int(ranks[0]))
            got = np.atleast_1d(f.result(timeout=20))[0]
            assert got == ok_forward(None, packed, players, ranks)[0], \
                "the request must ride the respawn, not exhaust against it"
            h = fleet.health()
            assert h["failovers"] >= 1
            assert h["respawns"] >= 1
        finally:
            fleet.close()

    def test_unroutable_request_parks_until_the_respawn_lands(self):
        """A submit arriving while the only replica is mid-respawn parks
        (counted) instead of resolving FleetUnavailable, and the landed
        rebuild re-dispatches it."""
        faults.install("serving_dispatch:fail@1")
        cfg = FleetConfig(max_failovers=0, respawn_base_s=0.05,
                          respawn_cap_s=0.1)
        fleet = make_fleet(replicas=1, sup_config=DIE_FAST,
                           fleet_config=cfg)
        try:
            packed, players, ranks = boards(2, seed=22)
            f0 = fleet.submit(packed[0], int(players[0]), int(ranks[0]))
            # wait for the death to be noticed, then submit INTO the hole
            assert wait_until(
                lambda: fleet.health()["replicas_serving"] == 0
                or f0.done(), timeout=10)
            f1 = fleet.submit(packed[1], int(players[1]), int(ranks[1]))
            exp = ok_forward(None, packed, players, ranks)
            assert np.atleast_1d(f0.result(timeout=20))[0] == exp[0]
            assert np.atleast_1d(f1.result(timeout=20))[0] == exp[1]
            assert fleet.health()["parks"] >= 1, \
                "the below-strength window never parked a request"
        finally:
            fleet.close()

    def test_single_replica_death_is_down_then_unavailable(self):
        faults.install("serving_dispatch:fail@1")
        cfg = FleetConfig(max_respawns=0, respawn_base_s=0.001,
                          respawn_cap_s=0.002)
        fleet = make_fleet(replicas=1, sup_config=DIE_FAST,
                           fleet_config=cfg)
        try:
            packed, players, ranks = boards(1, seed=6)
            f = fleet.submit(packed[0], int(players[0]), int(ranks[0]))
            with pytest.raises((FailoverExhausted, FleetUnavailable)):
                raise f.exception(timeout=20)
            assert wait_until(lambda: fleet.health()["state"] == "down")
            with pytest.raises((FleetUnavailable, EngineClosed)):
                fleet.submit(packed[0], 1, 5)
        finally:
            fleet.close()


def test_compile_cache_sizes_per_replica():
    """The recompile sentinel's attribution surface: one count per
    replica (not replica 0 echoed), with the scalar surface the SUM."""
    cfg, params = tiny()
    fleet = fleet_policy_engine(params, cfg, replicas=2, config=ECFG,
                                name="cache-fleet")
    try:
        fleet.warmup()
        sizes = fleet.compile_cache_sizes()
        assert len(sizes) == 2
        assert all(isinstance(s, int) and s > 0 for s in sizes)
        assert fleet.compile_cache_size() == sum(sizes)
    finally:
        fleet.close()


class TestReload:
    def test_reload_parity_bitwise_with_fresh_engine(self):
        cfg, params_a = tiny()
        params_b = init(jax.random.key(7), cfg)
        fleet = fleet_policy_engine(params_a, cfg, replicas=2,
                                    config=ECFG, name="reload-fleet")
        try:
            assert fleet.warmup() == 2
            warm = fleet.compile_cache_size()
            packed, players, ranks = boards(6, seed=8)
            out = fleet.reload(params_b)
            assert out["replicas"] == 2
            got = fleet.evaluate(packed, players, ranks)
            with InferenceEngine(make_log_prob_fn(cfg), params_b,
                                 ECFG) as fresh:
                exp = fresh.evaluate(packed, players, ranks)
            assert np.array_equal(np.asarray(got), np.asarray(exp)), \
                "post-reload rows differ from a fresh engine on the " \
                "new checkpoint"
            assert fleet.compile_cache_size() == warm, \
                "weight hot-swap recompiled"
        finally:
            fleet.close()

    def test_reload_from_checkpoint_path(self, tmp_path):
        from deepgo_tpu.experiments import checkpoint as ckpt

        cfg, params_a = tiny()
        params_b = init(jax.random.key(9), cfg)
        path = os.path.join(tmp_path, "checkpoint.npz")
        ckpt.save_checkpoint(path, params_b, {}, {
            "id": "reload-test", "step": 1, "validation_history": [],
            "config": {}, "git_sha": "none"})
        fleet = fleet_policy_engine(params_a, cfg, replicas=2, config=ECFG,
                                    name="ckpt-fleet")
        try:
            fleet.warmup()
            packed, players, ranks = boards(4, seed=10)
            fleet.reload(path)
            got = fleet.evaluate(packed, players, ranks)
            with InferenceEngine(make_log_prob_fn(cfg), params_b,
                                 ECFG) as fresh:
                exp = fresh.evaluate(packed, players, ranks)
            assert np.array_equal(np.asarray(got), np.asarray(exp))
        finally:
            fleet.close()

    def test_futures_mid_reload_all_resolve_zero_recompiles(self):
        cfg, params_a = tiny()
        params_b = init(jax.random.key(11), cfg)
        fleet = fleet_policy_engine(params_a, cfg, replicas=2, config=ECFG,
                                    name="midreload-fleet")
        try:
            fleet.warmup()
            warm = fleet.compile_cache_size()
            packed, players, ranks = boards(4, seed=12)
            fwd = make_log_prob_fn(cfg)
            exp_a = np.asarray(fwd(params_a, packed, players, ranks))
            exp_b = np.asarray(fwd(params_b, packed, players, ranks))
            results = []
            errors = []
            stop = threading.Event()

            def submitter(i):
                while not stop.is_set():
                    try:
                        row = fleet.submit(packed[i], int(players[i]),
                                           int(ranks[i])).result(timeout=30)
                    except BaseException as e:  # noqa: BLE001
                        errors.append(e)
                        return
                    results.append((i, np.asarray(row)))

            threads = [threading.Thread(target=submitter, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            time.sleep(0.05)  # requests in flight before the roll starts
            out = fleet.reload(params_b)
            time.sleep(0.05)  # and after it finishes
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, f"futures dropped mid-reload: {errors[:3]}"
            assert out["replicas"] == 2
            assert len(results) > 0
            # every row is bit-identical to EXACTLY the old or the new
            # weights — never a torn or dropped result
            for i, row in results:
                assert (np.array_equal(row, exp_a[i])
                        or np.array_equal(row, exp_b[i])), \
                    f"row {i} matches neither checkpoint"
            # requests after the roll see only the new weights
            got = fleet.evaluate(packed, players, ranks)
            assert np.array_equal(np.asarray(got), exp_b)
            assert fleet.compile_cache_size() == warm, \
                "mid-reload traffic triggered a recompile"
        finally:
            fleet.close()

    def test_reload_fault_typed_and_replica_rejoins(self):
        faults.install("fleet_reload:fail@1")
        fleet = make_fleet(replicas=2)
        try:
            with pytest.raises(FleetReloadError):
                fleet.reload(None)
            assert fleet.health()["state"] == "serving", \
                "a failed reload must leave the fleet serving"
            packed, players, ranks = boards(2, seed=13)
            got = fleet.evaluate(packed, players, ranks)
            assert np.array_equal(
                np.asarray(got).ravel(),
                ok_forward(None, packed, players, ranks).ravel())
            # the spec fired once; the retry completes the roll
            assert fleet.reload(None)["replicas"] == 2
        finally:
            fleet.close()

    def test_restart_after_reload_keeps_new_weights(self):
        # the set_params override: a post-reload dispatcher death must
        # not resurrect the factory's original checkpoint
        cfg, params_a = tiny()
        params_b = init(jax.random.key(14), cfg)
        forward = make_log_prob_fn(cfg)
        sup = SupervisedEngine(
            lambda: InferenceEngine(forward, params_a, ECFG, name="swap"),
            config=SupervisorConfig(backoff_base_s=0.0, backoff_cap_s=0.0),
            name="swap")
        try:
            sup.set_params(params_b)
            faults.install("serving_dispatch:fail@1")
            packed, players, ranks = boards(2, seed=15)
            got = sup.evaluate(packed, players, ranks)  # rides the restart
            exp = np.asarray(forward(params_b, packed, players, ranks))
            assert np.array_equal(np.asarray(got), exp)
        finally:
            sup.close()


class TestHealthAndClose:
    def test_degraded_then_recovered_health(self):
        faults.install("serving_dispatch:fail@1")
        fleet = make_fleet(replicas=2, sup_config=DIE_FAST,
                           fleet_config=FleetConfig(
                               respawn_base_s=0.05, respawn_cap_s=0.05))
        try:
            packed, players, ranks = boards(1, seed=16)
            fleet.submit(packed[0], int(players[0]),
                         int(ranks[0])).result(timeout=20)
            # the kill landed on one replica: health dips below full
            # strength (degraded -> 503 on a composed /healthz), then the
            # background respawn restores "serving"
            assert wait_until(
                lambda: fleet.health()["respawns"] >= 1), fleet.health()
            assert wait_until(
                lambda: fleet.health()["state"] == "serving")
            assert fleet.health()["replicas_serving"] == 2
        finally:
            fleet.close()

    def test_health_snapshot_shape(self):
        fleet = make_fleet(replicas=2)
        try:
            h = fleet.health()
            assert h["state"] == "serving"
            assert h["replicas_total"] == 2
            assert set(h["shed"]) == set(TIERS)
            assert len(h["replicas"]) == 2
            assert {r["replica"] for r in h["replicas"]} == {0, 1}
        finally:
            fleet.close()

    def test_close_then_submit_typed(self):
        fleet = make_fleet(replicas=2)
        fleet.close()
        with pytest.raises(EngineClosed):
            fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5)
        fleet.close()  # idempotent

    def test_selfplay_rides_a_fleet(self):
        from deepgo_tpu.selfplay import self_play

        cfg, params = tiny()
        games, stats = self_play(params, cfg, n_games=4, max_moves=10,
                                 temperature=1.0, pass_threshold=2.6e-3,
                                 seed=3, fleet=2)
        assert len(games) == 4
        assert stats["engine"]["fleet"]["replicas_total"] == 2
        assert stats["engine"]["boards"] > 0


# ---------------------------------------------------------------------------
# chaos-campaign satellites: lifecycle races the gray-failure work hardened


class TestShutdownRespawnRace:
    def test_close_during_inflight_respawn_neither_hangs_nor_leaks(self):
        """close(drain=True) racing an in-flight _respawn: close must
        return (the spawner thread is joined, not abandoned) and the
        replacement engine the respawn built mid-shutdown must be
        CLOSED, not leaked with a live dispatcher thread."""
        gate = threading.Event()
        entered = threading.Event()
        engines = []

        def make_replica(i):
            if len(engines) >= 2:  # a rebuild, not the initial pair:
                entered.set()      # the rebuild is provably in flight
                gate.wait(10.0)    # hold it here while close() runs
            eng = SupervisedEngine(
                lambda: InferenceEngine(ok_forward, None, ECFG,
                                        name=f"rep{i}"),
                config=DIE_FAST, name=f"rep{i}")
            engines.append(eng)
            return eng

        fleet = FleetRouter(make_replica, 2, config=FAST_FLEET,
                            name="close-race", rng=random.Random(0))
        try:
            faults.add("serving_dispatch.rep0:fail@1")
            packed, players, ranks = boards(16, seed=7)
            for i in range(16):  # submit until the kill lands on rep0
                f = fleet.submit(packed[i], int(players[i]),
                                 int(ranks[i]))
                assert np.atleast_1d(f.result(timeout=20))[0] == \
                    ok_forward(None, packed, players, ranks)[i]
                if fleet.health()["failovers"] >= 1:
                    break
            assert fleet.health()["failovers"] >= 1
            # wait for the corpse's rebuild to block INSIDE the factory
            # (not merely for the "respawning" state, which precedes the
            # factory call — close() landing in that gap would let
            # _respawn bail out before ever building engine #3)
            assert entered.wait(10.0), \
                "respawn never reached the factory"
            closer = threading.Thread(target=fleet.close, name="closer")
            closer.start()
            closer.join(timeout=0.3)
            gate.set()  # release the rebuild under a closing fleet
            closer.join(timeout=20.0)
            assert not closer.is_alive(), \
                "close() hung on the in-flight respawn"
        finally:
            gate.set()
            fleet.close()  # idempotent; a no-op when the race path ran
        # the replacement engine built mid-shutdown was discarded CLOSED
        assert wait_until(lambda: len(engines) >= 3), \
            "respawn never reached the factory"
        # the corpse keeps its terminal "failed" state; every OTHER
        # engine — the survivor and the mid-shutdown replacement — must
        # be closed, or a dispatcher thread leaked past close()
        assert engines[0].health()["state"] in ("failed", "closed")
        for eng in engines[1:]:
            assert wait_until(
                lambda e=eng: e.health()["state"] == "closed"), \
                f"engine leaked open after close: {eng.health()}"
        with pytest.raises(EngineClosed):
            fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5)


class TestExpiredDeadlineFailover:
    class _FakeClock:
        def __init__(self):
            self.now = 0.0

        def __call__(self):
            return self.now

    class _HoldReplica(FakeReplica):
        """Scripted replica whose inner futures the test resolves."""

        def __init__(self, idx, est=None):
            super().__init__(idx, est=est)
            self.inners = []

        def submit(self, packed, player, rank, timeout_s=None, block=True):
            self.submitted += 1
            f = Future()
            self.inners.append(f)
            return f

    def test_expired_deadline_resolves_timeout_not_resurrected(self):
        """A request whose deadline lapsed while it rode a dying replica
        gets its TimeoutError verdict from the failover path — it is
        NOT requeued onto a healthy replica as an already-dead zombie
        (placement after expiry wastes capacity and can double-serve)."""
        clk = self._FakeClock()
        dying = self._HoldReplica(0, est=0.0)
        healthy = FakeReplica(1, est=1.0)
        fleet = fake_fleet([dying, healthy], clock=clk)
        try:
            f = fleet.submit(np.zeros((9, 19, 19), np.uint8), 1, 5,
                             timeout_s=0.05)
            assert dying.submitted == 1 and healthy.submitted == 0
            clk.now = 1.0  # the deadline lapses in flight...
            dying.inners[0].set_exception(
                EngineClosed("replica dying under the request"))
            with pytest.raises(TimeoutError):  # ...then the replica dies
                f.result(timeout=10)
            assert healthy.submitted == 0, \
                "failover resurrected an expired request"
            assert fleet.health()["failovers"] == 1
        finally:
            fleet.close()
