"""Fleet telemetry plane: sampler cadence, store retention, anomaly
matrix, federation, dash, trend (ISSUE 14).

The coverage contract: fake-clock sampler cadence (no sleeping),
chunk-roll + power-of-two downsample boundaries that lose no pinned
points, the anomaly matrix (step change fires / slow drift fires /
noisy-but-healthy stays quiet / failure-counter increase fires with no
warmup / a planned drain does not), federation over live exporters with
one dead endpoint tolerated as a ``ts_scrape_failed`` event, the
``/series`` route, ``cli dash --once``/``--json`` round-trip, ``cli
trend`` over synthetic BENCH files of both committed shapes, and the
``cli obs`` timeseries/anomalies sections.
"""

import json
import os
import random

import pytest

from deepgo_tpu.obs import (AnomalyDetector, DEFAULT_WATCHLIST,
                            FederatedView, JsonlSink, MetricsRegistry,
                            ObsExporter, TelemetrySampler, TimeSeriesStore,
                            WatchSpec, flatten_snapshot, parse_prometheus,
                            render_prometheus, set_live_store,
                            store_series, with_labels)
from deepgo_tpu.obs.sentinel import FlightRecorder
from deepgo_tpu.obs.timeseries import (chunk_paths, key_matches,
                                       load_samples, series_from_samples,
                                       series_key, split_key)


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_plane(tmp_path, clock=None, watchlist=None, **det_kw):
    """One wired (registry, store, detector, sampler) quartet over a
    private registry and a fake clock."""
    clock = clock or FakeClock()
    reg = MetricsRegistry(clock=clock)
    store = TimeSeriesStore(str(tmp_path / "ts"), clock=clock,
                            registry=reg)
    det = AnomalyDetector(watchlist=watchlist, registry=reg, store=store,
                          flight=False, clock=clock, **det_kw)
    sampler = TelemetrySampler(store, registry=reg, interval_s=1.0,
                               clock=clock, listeners=[det.observe],
                               flight_tick=False)
    return clock, reg, store, det, sampler


# ---- keys + flattening ----


class TestKeys:
    def test_flatten_covers_all_kinds(self):
        reg = MetricsRegistry()
        reg.counter("deepgo_a_total").inc(3, engine="e")
        reg.gauge("deepgo_b").set(2.5)
        reg.histogram("deepgo_c_seconds").observe(0.1, engine="e")
        values = flatten_snapshot(reg.snapshot()["metrics"])
        assert values["deepgo_a_total{engine=e}"] == 3.0
        assert values["deepgo_b"] == 2.5
        assert values["deepgo_c_seconds{engine=e}:count"] == 1.0
        assert values["deepgo_c_seconds{engine=e}:p99"] == pytest.approx(0.1)

    def test_series_key_split_round_trip(self):
        key = series_key("deepgo_x", "engine=a,tier=b", "p99")
        assert key == "deepgo_x{engine=a,tier=b}:p99"
        assert split_key(key) == ("deepgo_x", "engine=a,tier=b", "p99")
        assert split_key("deepgo_x") == ("deepgo_x", "", None)

    def test_key_matches_family_and_exact(self):
        assert key_matches("deepgo_x", "deepgo_x")
        assert key_matches("deepgo_x", "deepgo_x{engine=a}")
        assert key_matches("deepgo_x", "deepgo_x{engine=a}:p99")
        assert not key_matches("deepgo_x", "deepgo_xy{engine=a}")


# ---- sampler cadence (fake clock, no sleeping) ----


class TestSamplerCadence:
    def test_fixed_rate_cadence(self, tmp_path):
        clock, _reg, _store, _det, sampler = make_plane(tmp_path)
        took = sum(sampler.maybe_sample() for _ in range(1))
        for _ in range(40):  # 10s of quarter-second polls
            clock.advance(0.25)
            took += sampler.maybe_sample()
        # first sample + one per full second elapsed
        assert took == 1 + 10
        assert sampler.samples_taken == took

    def test_stall_skips_forward_no_burst(self, tmp_path):
        clock, _reg, _store, _det, sampler = make_plane(tmp_path)
        sampler.maybe_sample()
        clock.advance(7.3)  # a long stall misses ~7 ticks
        assert sampler.maybe_sample() is True
        assert sampler.maybe_sample() is False  # no backfill burst
        clock.advance(1.0)
        assert sampler.maybe_sample() is True

    def test_samples_counter_and_listener_isolation(self, tmp_path):
        clock, reg, store, _det, sampler = make_plane(tmp_path)
        boom = []

        def bad_listener(t, values):
            boom.append(t)
            raise RuntimeError("listener crash")

        sampler.add_listener(bad_listener)
        sampler.sample_once()
        clock.advance(1.0)
        sampler.sample_once()  # the bad listener must not kill sampling
        assert len(boom) == 2
        assert reg.counter("deepgo_ts_samples_total").value() == 2.0
        assert len(store.samples()) == 2

    def test_background_thread_lifecycle(self, tmp_path):
        store = TimeSeriesStore(str(tmp_path / "ts"),
                                registry=MetricsRegistry())
        sampler = TelemetrySampler(store, registry=MetricsRegistry(),
                                   interval_s=0.01, flight_tick=False)
        with sampler:
            deadline = 200
            while sampler.samples_taken < 3 and deadline:
                deadline -= 1
                import time as _t
                _t.sleep(0.01)
        assert sampler.samples_taken >= 3
        sampler.stop()  # idempotent


# ---- store: chunking, retention, downsampling, torn lines ----


class TestStore:
    def test_chunks_roll_at_sample_count(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), chunk_samples=4,
                                max_chunks=100, clock=clock,
                                registry=MetricsRegistry())
        for i in range(10):
            store.append({"deepgo_x": float(i)}, t=clock.advance(1.0))
        assert len(chunk_paths(str(tmp_path))) == 3
        points = store.series("deepgo_x")["deepgo_x"]
        assert [v for _, v in points] == [float(i) for i in range(10)]

    def test_retention_bounds_chunks_and_halves_resolution(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), chunk_samples=8,
                                max_chunks=3, clock=clock,
                                registry=MetricsRegistry())
        n = 200
        for i in range(n):
            store.append({"deepgo_x": float(i)}, t=clock.advance(1.0))
        chunks = chunk_paths(str(tmp_path))
        assert len(chunks) <= 4  # budget + the just-opened chunk
        points = store.series("deepgo_x")["deepgo_x"]
        assert 0 < len(points) < n  # decimated, not truncated to nothing
        ts = [t for t, _ in points]
        assert ts == sorted(ts)
        # the newest chunk keeps full resolution: the last samples survive
        assert points[-1][1] == float(n - 1)
        # old history survives at reduced resolution (not dropped outright)
        assert points[0][1] < n / 4

    def test_pinned_points_survive_downsampling(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), chunk_samples=8,
                                max_chunks=2, clock=clock,
                                registry=MetricsRegistry())
        pinned_ts = []
        for i in range(120):
            t = clock.advance(1.0)
            pin = i % 17 == 0
            store.append({"deepgo_x": float(i)}, t=t, pin=pin)
            if pin:
                pinned_ts.append(t)
        kept = {t for t, _ in store.series("deepgo_x")["deepgo_x"]}
        assert set(pinned_ts) <= kept

    def test_pin_recent_marks_live_tail(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), chunk_samples=4,
                                max_chunks=2, clock=clock,
                                registry=MetricsRegistry())
        tail_ts = []
        for i in range(40):
            t = clock.advance(1.0)
            store.append({"deepgo_x": float(i)}, t=t)
            if i < 6:
                tail_ts.append(t)
            if i == 5:
                assert store.pin_recent(6) == 6
        # keep decimating well past the pinned region
        for i in range(200):
            store.append({"deepgo_x": 0.0}, t=clock.advance(1.0))
        kept = {t for t, _ in store.series("deepgo_x")["deepgo_x"]}
        assert set(tail_ts) <= kept

    def test_torn_line_tolerance(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), clock=clock,
                                registry=MetricsRegistry())
        for i in range(3):
            store.append({"deepgo_x": float(i)}, t=clock.advance(1.0))
        store.close()
        path = chunk_paths(str(tmp_path))[-1]
        with open(path, "a") as f:
            f.write('{"kind": "ts_sample", "t": 99, "values": {"deepgo_x')
        points = load_samples(str(tmp_path))
        assert len(points) == 3  # the torn line is skipped, not fatal

    def test_reopen_resumes_numbering(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), chunk_samples=2,
                                clock=clock, registry=MetricsRegistry())
        for i in range(5):
            store.append({"deepgo_x": float(i)}, t=clock.advance(1.0))
        store.close()
        store2 = TimeSeriesStore(str(tmp_path), chunk_samples=2,
                                 clock=clock, registry=MetricsRegistry())
        store2.append({"deepgo_x": 5.0}, t=clock.advance(1.0))
        store2.close()
        assert len(load_samples(str(tmp_path))) == 6

    def test_recent_series_window(self, tmp_path):
        clock = FakeClock()
        store = TimeSeriesStore(str(tmp_path), clock=clock,
                                registry=MetricsRegistry())
        for i in range(10):
            store.append({"deepgo_x": float(i)}, t=clock.advance(1.0))
        recent = store.recent_series("deepgo_x", 4)["deepgo_x"]
        assert [v for _, v in recent] == [6.0, 7.0, 8.0, 9.0]

    def test_bad_config_typed(self, tmp_path):
        with pytest.raises(ValueError):
            TimeSeriesStore(str(tmp_path), chunk_samples=1,
                            registry=MetricsRegistry())
        with pytest.raises(ValueError):
            TelemetrySampler(
                TimeSeriesStore(str(tmp_path), registry=MetricsRegistry()),
                registry=MetricsRegistry(), interval_s=0.0)


# ---- the anomaly matrix ----


def drive(sampler, clock, setter, values):
    for v in values:
        setter(v)
        clock.advance(1.0)
        sampler.sample_once()


class TestAnomalyMatrix:
    def test_step_change_fires(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(
            tmp_path, watchlist=(WatchSpec("deepgo_train_samples_per_sec"),))
        g = reg.gauge("deepgo_train_samples_per_sec")
        rnd = random.Random(0)
        drive(sampler, clock, g.set,
              [1000 + rnd.gauss(0, 5) for _ in range(40)])
        assert det.count == 0
        drive(sampler, clock, g.set, [400.0])  # the step
        assert det.count == 1
        a = det.anomalies[-1]
        assert a.kind == "step"
        assert a.metric == "deepgo_train_samples_per_sec"
        # hysteresis: the same incident does not re-fire every sample
        drive(sampler, clock, g.set, [400.0] * 5)
        assert det.count == 1

    def test_noisy_but_healthy_stays_quiet(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(
            tmp_path, watchlist=(WatchSpec("deepgo_train_samples_per_sec"),))
        g = reg.gauge("deepgo_train_samples_per_sec")
        rnd = random.Random(7)
        drive(sampler, clock, g.set,
              [1000 + rnd.gauss(0, 25) for _ in range(300)])
        assert det.count == 0

    def test_slow_drift_fires_drift_not_step(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(
            tmp_path, watchlist=(WatchSpec("deepgo_train_samples_per_sec"),))
        g = reg.gauge("deepgo_train_samples_per_sec")
        rnd = random.Random(3)
        drive(sampler, clock, g.set,
              [1000 + rnd.gauss(0, 8) for _ in range(60)])
        # ~0.7%/sample decay: each step is noise-sized, the trend is not
        drive(sampler, clock, g.set,
              [1000 - 7 * i + rnd.gauss(0, 8) for i in range(120)])
        assert det.count >= 1
        assert "drift" in det.by_kind

    def test_failure_counter_increase_fires_without_warmup(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(tmp_path)
        c = reg.counter("deepgo_fleet_failovers_total")
        sampler.sample_once()  # primes; the labeled series does not exist yet
        clock.advance(1.0)
        c.inc(1, fleet="f")  # the kill
        sampler.sample_once()
        assert det.count == 1
        assert det.anomalies[-1].kind == "rate"
        # detection latency is one sample window by construction
        assert det.first.t - clock.t == 0.0

    def test_planned_drain_quiet_failed_replica_fires(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(tmp_path)
        g = reg.gauge("deepgo_fleet_replica_state")
        g.set(1.0, fleet="f", replica="0")
        drive(sampler, clock, lambda v: g.set(v, fleet="f", replica="0"),
              [1.0, 0.5, 1.0, 1.0])  # a rolling reload's drain dip
        assert det.count == 0
        drive(sampler, clock, lambda v: g.set(v, fleet="f", replica="0"),
              [0.0])  # the replica actually dies
        assert det.count == 1
        assert det.anomalies[-1].kind == "step"

    def test_counter_rate_derives_per_second(self, tmp_path):
        clock, reg, _store, det, sampler = make_plane(
            tmp_path, watchlist=(WatchSpec("deepgo_serving_boards_total",
                                           mode="counter_rate"),))
        c = reg.counter("deepgo_serving_boards_total")
        c.inc(0, engine="e")
        total = 0.0
        rnd = random.Random(1)
        # steady ~100 boards/sec with noise: quiet
        for _ in range(60):
            total += 100 + rnd.gauss(0, 3)
            c.inc(100 + rnd.gauss(0, 3), engine="e")
            clock.advance(1.0)
            sampler.sample_once()
        assert det.count == 0
        # throughput collapses: the rate steps down and fires
        for _ in range(3):
            c.inc(5, engine="e")
            clock.advance(1.0)
            sampler.sample_once()
        assert det.count >= 1

    def test_anomaly_counter_and_event_stream(self, tmp_path):
        clock = FakeClock()
        reg = MetricsRegistry(clock=clock)
        sink = JsonlSink(str(tmp_path / "events.jsonl"))
        store = TimeSeriesStore(str(tmp_path / "ts"), clock=clock,
                                registry=reg)
        det = AnomalyDetector(sink=sink, registry=reg, store=store,
                              flight=False, clock=clock)
        sampler = TelemetrySampler(store, registry=reg, interval_s=1.0,
                                   clock=clock, listeners=[det.observe],
                                   flight_tick=False)
        c = reg.counter("deepgo_serving_restarts_total")
        sampler.sample_once()
        clock.advance(1.0)
        c.inc(1, engine="bench")
        sampler.sample_once()
        sink.close()
        assert reg.counter("deepgo_anomaly_total").value(
            metric="deepgo_serving_restarts_total", kind="rate") == 1.0
        events = [json.loads(l) for l in
                  open(tmp_path / "events.jsonl")]
        anomaly = [e for e in events if e["kind"] == "anomaly"]
        assert len(anomaly) == 1
        assert anomaly[0]["detector"] == "rate"
        assert anomaly[0]["series"] == \
            "deepgo_serving_restarts_total{engine=bench}"

    def test_flight_dump_carries_series_window(self, tmp_path):
        clock = FakeClock()
        reg = MetricsRegistry(clock=clock)
        store = TimeSeriesStore(str(tmp_path / "ts"), clock=clock,
                                registry=reg)
        recorder = FlightRecorder(registry=reg, clock=clock)
        recorder.configure(str(tmp_path / "flight"))
        det = AnomalyDetector(registry=reg, store=store, flight=False,
                              clock=clock)
        # wire the section the detector's flight=True path registers on
        # the PROCESS recorder, here against a private one
        recorder.add_section("series_window",
                             lambda: store.recent_window())
        sampler = TelemetrySampler(store, registry=reg, interval_s=1.0,
                                   clock=clock, listeners=[det.observe],
                                   flight_tick=False)
        c = reg.counter("deepgo_serving_restarts_total")
        sampler.sample_once()
        clock.advance(1.0)
        c.inc(1, engine="bench")
        sampler.sample_once()
        assert det.count == 1
        path = recorder.dump("anomaly", **det.first.to_dict())
        dumped = json.load(open(path))
        window = dumped["series_window"]
        assert len(window) == 2
        assert "deepgo_serving_restarts_total{engine=bench}" \
            in window[-1]["values"]
        # the surrounding samples are pinned against future decimation
        assert any(s["t"] in store._pinned or s.get("pin")
                   for s in window)
        recorder.close()

    def test_watchlist_is_declared_and_covers_the_issue_metrics(self):
        families = {w.metric for w in DEFAULT_WATCHLIST}
        assert "deepgo_serving_boards_total" in families       # boards/sec
        assert "deepgo_serving_dispatch_seconds" in families   # p99
        assert "deepgo_fleet_failovers_total" in families      # failovers
        assert "deepgo_loop_games_ingested_total" in families  # games/hour


# ---- federation ----


class TestFederation:
    def test_parse_prometheus_round_trip(self):
        reg = MetricsRegistry()
        reg.counter("deepgo_a_total").inc(7, engine="e")
        reg.gauge("deepgo_b").set(1.5, host="h")
        h = reg.histogram("deepgo_c_seconds")
        for v in (0.01, 0.02, 0.03, 0.2):
            h.observe(v)
        values = parse_prometheus(render_prometheus(reg))
        assert values["deepgo_a_total{engine=e}"] == 7.0
        assert values["deepgo_b{host=h}"] == 1.5
        assert values["deepgo_c_seconds:count"] == 4.0
        assert values["deepgo_c_seconds:sum"] == pytest.approx(0.26)
        assert 0.0 < values["deepgo_c_seconds:p50"] < 0.1
        assert values["deepgo_c_seconds:p99"] <= 0.25

    def test_with_labels_folds_host_into_existing_labelset(self):
        out = with_labels({"deepgo_x{engine=e}:p99": 1.0,
                           "deepgo_y": 2.0}, host="h1")
        assert out == {"deepgo_x{engine=e,host=h1}:p99": 1.0,
                       "deepgo_y{host=h1}": 2.0}

    def test_live_federation_with_dead_endpoint(self, tmp_path):
        regs = []
        exporters = []
        for i in range(3):
            reg = MetricsRegistry()
            reg.gauge("deepgo_fleet_replicas_serving").set(
                3 - i, fleet=f"f{i}")
            exporters.append(ObsExporter(port=0, registry=reg))
            regs.append(reg)
        sink = JsonlSink(str(tmp_path / "fed.jsonl"))
        view = FederatedView(sink=sink, registry=MetricsRegistry())
        for i, exp in enumerate(exporters):
            view.add_scrape(f"host{i}", exp.url)
        dead_port = exporters[0].port  # will be freed below
        view.add_scrape("deadhost", "http://127.0.0.1:9/metrics")
        try:
            collected = view.collect()
        finally:
            for exp in exporters:
                exp.close()
            sink.close()
        assert [collected["hosts"][f"host{i}"]["ok"]
                for i in range(3)] == [True, True, True]
        assert collected["hosts"]["deadhost"]["ok"] is False
        # >= 3 hosts joined into ONE labeled view
        for i in range(3):
            assert collected["values"][
                f"deepgo_fleet_replicas_serving{{fleet=f{i},host=host{i}}}"
            ] == float(3 - i)
        events = [json.loads(l) for l in open(tmp_path / "fed.jsonl")]
        failed = [e for e in events if e["kind"] == "ts_scrape_failed"]
        assert len(failed) == 1 and failed[0]["host"] == "deadhost"
        assert dead_port  # silence the unused warning honestly

    def test_offline_store_federation(self, tmp_path):
        clock = FakeClock()
        dirs = {}
        for host in ("a", "b", "c"):
            d = str(tmp_path / host)
            store = TimeSeriesStore(d, clock=clock,
                                    registry=MetricsRegistry())
            for i in range(4):
                store.append({"deepgo_train_samples_per_sec":
                              100.0 + i}, t=clock.advance(1.0))
            store.close()
            dirs[host] = d
        dirs["empty"] = str(tmp_path / "empty")  # dead store tolerated
        merged = store_series(dirs, "deepgo_train_samples_per_sec")
        assert set(merged) == {
            "deepgo_train_samples_per_sec{host=a}",
            "deepgo_train_samples_per_sec{host=b}",
            "deepgo_train_samples_per_sec{host=c}"}
        assert all(len(v) == 4 for v in merged.values())

    def test_series_route_serves_recent_window(self, tmp_path):
        import urllib.request

        clock = FakeClock()
        reg = MetricsRegistry()
        store = TimeSeriesStore(str(tmp_path), clock=clock, registry=reg)
        for i in range(5):
            store.append({"deepgo_x{engine=e}": float(i)},
                         t=clock.advance(1.0))
        set_live_store(store)
        exporter = ObsExporter(port=0, registry=reg)
        try:
            with urllib.request.urlopen(
                    exporter.url + "/series?metric=deepgo_x") as r:
                payload = json.loads(r.read())
            assert payload["enabled"] is True
            points = payload["series"]["deepgo_x{engine=e}"]
            assert [v for _, v in points] == [0.0, 1.0, 2.0, 3.0, 4.0]
            with urllib.request.urlopen(exporter.url + "/series") as r:
                keys = json.loads(r.read())["keys"]
            assert "deepgo_x{engine=e}" in keys
        finally:
            exporter.close()
            set_live_store(None)
            store.close()


# ---- dash + trend ----


def _write_store_run(tmp_path, clock=None):
    """A run dir with a ts store, anomaly events, and fleet series."""
    clock = clock or FakeClock()
    reg = MetricsRegistry(clock=clock)
    g = reg.gauge("deepgo_fleet_replicas_serving")
    state = reg.gauge("deepgo_fleet_replica_state")
    sps = reg.gauge("deepgo_train_samples_per_sec")
    burn = reg.gauge("deepgo_slo_burn_ratio")
    store = TimeSeriesStore(str(tmp_path), clock=clock, registry=reg)
    sink = JsonlSink(str(tmp_path / "metrics.jsonl"))
    det = AnomalyDetector(sink=sink, registry=reg, store=store,
                          flight=False, clock=clock)
    sampler = TelemetrySampler(store, registry=reg, interval_s=1.0,
                               clock=clock, listeners=[det.observe],
                               flight_tick=False)
    c = reg.counter("deepgo_fleet_failovers_total")
    g.set(3, fleet="f")
    for r in range(3):
        state.set(1.0, fleet="f", replica=str(r))
    burn.set(0.2, slo="dispatch", window="fast")
    for i in range(12):
        sps.set(1000.0 + i)
        clock.advance(1.0)
        sampler.sample_once()
    c.inc(1, fleet="f")  # one failover -> one anomaly event on record
    state.set(0.0, fleet="f", replica="2")
    clock.advance(1.0)
    sampler.sample_once()
    store.close()
    sink.close()
    assert det.count >= 1
    return str(tmp_path)


class TestDash:
    def test_collect_and_render_store_mode(self, tmp_path):
        from deepgo_tpu.obs.dash import collect_dash, render_dash

        run_dir = _write_store_run(tmp_path)
        data = collect_dash(run_dir)
        assert data["mode"] == "store"
        assert data["samples"] == 13
        assert data["anomalies"], "recorded anomaly events surface"
        fleet = data["fleet"]["local"]
        assert fleet["replicas_serving"] == 3.0
        assert fleet["replica_state"]["2"] == 0.0
        out = render_dash(data)
        assert "watchlist:" in out
        assert "fleet health:" in out
        assert "r2:DOWN" in out
        assert "anomalies" in out
        assert "slo burn:" in out
        # sparklines actually render block characters
        assert any(ch in out for ch in "▁▂▃▄▅▆▇█")

    def test_cli_dash_once_and_json_round_trip(self, tmp_path, capsys):
        from deepgo_tpu.cli import main

        run_dir = _write_store_run(tmp_path)
        main(["dash", run_dir, "--once"])
        rendered = capsys.readouterr().out
        assert "fleet health:" in rendered
        main(["dash", run_dir, "--json"])
        data = json.loads(capsys.readouterr().out)
        assert data["mode"] == "store"
        assert data["fleet"]["local"]["replicas_serving"] == 3.0
        assert data["anomalies"][0]["detector"] in ("rate", "step")

    def test_cli_dash_requires_a_source(self):
        from deepgo_tpu.cli import main

        with pytest.raises(SystemExit):
            main(["dash"])

    def test_dash_scrape_mode_grows_history(self):
        from deepgo_tpu.obs.dash import DashHistory, collect_dash

        clock = FakeClock()
        reg = MetricsRegistry()
        g = reg.gauge("deepgo_train_samples_per_sec")
        view = FederatedView(registry=MetricsRegistry(), clock=clock)
        view.add_getter(
            "h1", lambda: flatten_snapshot(reg.snapshot()["metrics"]))
        history = DashHistory()
        for i in range(5):
            g.set(100.0 + i)
            clock.advance(1.0)
            data = collect_dash(view=view, history=history)
        assert data["mode"] == "scrape"
        assert data["samples"] == 5
        key = "deepgo_train_samples_per_sec{host=h1}"
        points = data["watchlist"]["deepgo_train_samples_per_sec"][key]
        assert [v for _, v in points["points"]] == [100, 101, 102, 103, 104]


class TestTrend:
    def _write_rounds(self, root):
        # the r06+ shape
        with open(os.path.join(root, "BENCH_r06.json"), "w") as f:
            json.dump({"round": 6, "captures": {
                "inference": {"metric": "m_boards", "value": 74.2,
                              "unit": "boards/sec", "device": "cpu"},
                "serving": {"metric": "m_serving", "value": 313.1,
                            "unit": "boards/sec", "device": "cpu"},
            }}, f)
        # the r01-r05 driver shape, stale capture
        with open(os.path.join(root, "BENCH_r05.json"), "w") as f:
            json.dump({"n": 5, "rc": 0, "parsed": {
                "metric": "m_boards", "value": 104034.1, "stale": True,
                "last_good": {"device": "tpu"}}}, f)
        with open(os.path.join(root, "BENCH_r04.json"), "w") as f:
            f.write("{not json")
        with open(os.path.join(root, "BENCH_LAST_GOOD.json"), "w") as f:
            json.dump({"m_boards": {"metric": "m_boards",
                                    "value": 104034.1, "device": "tpu",
                                    "timestamp": "T"}}, f)

    def test_collect_and_render(self, tmp_path):
        from deepgo_tpu.obs.dash import collect_trend, render_trend

        self._write_rounds(str(tmp_path))
        data = collect_trend(str(tmp_path))
        assert data["rounds"] == [5, 6]
        assert data["metrics"]["m_boards"][5]["stale"] is True
        assert data["metrics"]["m_boards"][6]["value"] == 74.2
        assert data["last_good"]["m_boards"]["value"] == 104034.1
        assert data["skipped"] == ["BENCH_r04.json"]
        out = render_trend(data)
        assert "m_boards" in out and "m_serving" in out
        assert "104034*" in out.replace(" ", "")  # stale marked
        assert "last-good" in out

    def test_cli_trend_json(self, tmp_path, capsys):
        from deepgo_tpu.cli import main

        self._write_rounds(str(tmp_path))
        main(["trend", "--root", str(tmp_path), "--json"])
        data = json.loads(capsys.readouterr().out)
        assert data["rounds"] == [5, 6]

    def test_trend_over_the_real_repo_history(self):
        from deepgo_tpu.obs.dash import collect_trend

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        data = collect_trend(root)
        assert 7 in data["rounds"]  # the r07 capture of this PR
        assert "policy_inference_boards_per_sec_per_chip" in data["metrics"]


# ---- cli obs sections ----


class TestReportSections:
    def test_obs_report_gains_timeseries_and_anomalies(self, tmp_path):
        from deepgo_tpu.obs.report import format_report, summarize_run

        run_dir = _write_store_run(tmp_path)
        summary = summarize_run(run_dir)
        ts = summary["timeseries"]
        assert ts["samples"] == 13
        assert ts["series"] >= 4
        assert ts["pinned"] >= 1  # the anomaly pinned its window
        assert any(k.startswith("deepgo_train_samples_per_sec")
                   for k in ts["watch"])
        anom = summary["anomalies"]
        assert anom["count"] >= 1
        assert anom["events"][0]["detector"] in ("rate", "step")
        out = format_report(summary)
        assert "telemetry time-series" in out
        assert "anomalies (" in out
