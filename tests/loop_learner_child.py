"""Subprocess driver for the SIGKILL learner-resume test (test_loop.py).

Runs a small windowed-learner schedule over an on-disk replay buffer:
before window w the buffer is grown to a deterministic game-count target
(synthetic games that are a pure function of their gid), then the window
trains. With ``DEEPGO_FAULTS=kill:step@K`` in the environment the
process is SIGKILLed mid-window — the honest preemption, no cleanup —
and re-running the identical command auto-resumes from the learner's
checkpoint + cursor and converges on the same final state as an
uninterrupted run. The parent test compares ``windows.jsonl`` digests
across the killed-and-resumed and uninterrupted directories.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from deepgo_tpu.experiments import ExperimentConfig  # noqa: E402
from deepgo_tpu.loop import (ContinuousLearner, ReplayBuffer,  # noqa: E402
                             read_windows)


def synth_game(gid: int, moves: int = 10):
    """Deterministic synthetic game records keyed on gid alone — the
    ingestion schedule replays identically across process restarts."""
    r = np.random.default_rng(gid + 1000)
    packed = r.integers(0, 3, size=(moves, 9, 19, 19)).astype(np.uint8)
    meta = np.zeros((moves, 6), np.int32)
    meta[:, 0] = r.integers(1, 3, size=moves)
    meta[:, 1] = r.integers(0, 19, size=moves)
    meta[:, 2] = r.integers(0, 19, size=moves)
    meta[:, 3] = 8
    meta[:, 4] = 8
    return packed, meta


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--windows", type=int, default=3)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--games-per-window", type=int, default=4)
    args = ap.parse_args()

    buffer = ReplayBuffer(os.path.join(args.dir, "buf"), segment_games=2)
    config = ExperimentConfig(name="loop-child", num_layers=2, channels=8,
                              batch_size=8, rate=0.05, seed=7)
    learner = ContinuousLearner(
        buffer, os.path.join(args.dir, "run"), config,
        steps_per_window=args.steps, min_window_positions=8)
    while learner.window < args.windows:
        # grow-the-corpus-mid-run schedule, keyed on DURABLE state only:
        # a killed-and-restarted process re-derives exactly this sequence
        target = args.games_per_window * (learner.window + 1)
        while buffer.total_games < target:
            buffer.ingest_game(*synth_game(buffer.total_games))
        learner.train_window()
    digests = [r["digest"] for r in read_windows(os.path.join(args.dir,
                                                              "run"))]
    print("CHILD_DONE " + json.dumps(digests), flush=True)


if __name__ == "__main__":
    main()
