"""Driver-contract tests for __graft_entry__ (subprocess: dryrun mutates
global backend config)."""

import os
import subprocess
import sys

import pytest

from conftest import REPO_ROOT


def _run(code: str, extra_env: dict | None = None, timeout: int = 300):
    # Drop conftest's own CPU forcing so the child genuinely starts from the
    # platform the test case asks for, and drop PYTHONPATH so the terminal's
    # axon sitecustomize never loads: with it, the child could dial the TPU
    # relay at interpreter start and hang the test when the relay is wedged
    # (round-1 verdict item 3) — the relay path is exercised only by the
    # driver itself, never by the hermetic suite.
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", "PYTHONPATH")}
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO_ROOT, env=env, timeout=timeout,
    )


def test_entry_compiles_on_cpu():
    r = _run(
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "assert out.shape == (128, 361), out.shape\n"
        "print('OK')\n",
        {"JAX_PLATFORMS": "cpu"},
    )
    assert "OK" in r.stdout, r.stderr[-2000:]


@pytest.mark.parametrize("preset_env", [True, False])
def test_dryrun_multichip(preset_env):
    env = (
        {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        if preset_env
        else {}
    )
    prelude = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        if preset_env
        else ""
    )
    r = _run(
        prelude + "import __graft_entry__ as g\ng.dryrun_multichip(8)\n",
        env,
    )
    assert "one train step done" in r.stdout, r.stderr[-2000:]


def test_dryrun_never_touches_default_backend():
    # The relay-proofing contract: dryrun must pin the CPU platform before
    # ANY backend initialization. A poisoned platform name stands in for the
    # wedged axon relay — if anything probes jax.devices() before the pin,
    # jax raises (unknown platform) instead of silently using CPU.
    r = _run(
        "import __graft_entry__ as g\ng.dryrun_multichip(8)\n",
        {"JAX_PLATFORMS": "no_such_platform"},
    )
    assert "one train step done" in r.stdout, (r.stdout, r.stderr[-2000:])


def test_watchdog_disarm_survives_past_fuse():
    # the complement of the kill test: an armed-then-DISARMED process must
    # outlive its fuse — a disarm that merely forgets the handle would
    # leave the child to kill a healthy run at timeout
    r = _run(
        "import __graft_entry__ as g, time\n"
        "wd = g._arm_watchdog('test', timeout_s=2)\n"
        "wd.disarm()\n"
        "time.sleep(4)\n"  # well past the 2s fuse
        "print('SURVIVED PAST FUSE')\n",
        {"GRAFT_WATCHDOG": "1"},  # pin against ambient =0
        timeout=30,
    )
    assert "SURVIVED PAST FUSE" in r.stdout, (r.stdout, r.stderr[-2000:])
    assert r.returncode == 0
    assert "watchdog" not in r.stderr


def test_watchdog_kills_wedged_process():
    # Simulate the wedge: arm the watchdog with a short fuse, then block in
    # a C-level sleep. The external watchdog must SIGKILL the process.
    r = _run(
        "import __graft_entry__ as g, time\n"
        "g._arm_watchdog('test', timeout_s=2)\n"
        "time.sleep(60)\n"
        "print('SHOULD NOT REACH')\n",
        {"GRAFT_WATCHDOG": "1"},  # pin against ambient =0
        timeout=30,
    )
    assert "SHOULD NOT REACH" not in r.stdout
    assert r.returncode != 0
    assert "watchdog" in r.stderr
