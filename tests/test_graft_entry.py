"""Driver-contract tests for __graft_entry__ (subprocess: dryrun mutates
global backend config)."""

import os
import subprocess
import sys

import pytest

from conftest import REPO_ROOT


def _run(code: str, extra_env: dict | None = None):
    # drop conftest's own CPU forcing so the child genuinely starts from the
    # platform the test case asks for
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        cwd=REPO_ROOT, env=env, timeout=300,
    )


def test_entry_compiles_on_cpu():
    r = _run(
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "assert out.shape == (128, 361), out.shape\n"
        "print('OK')\n",
        {"JAX_PLATFORMS": "cpu"},
    )
    assert "OK" in r.stdout, r.stderr[-2000:]


@pytest.mark.parametrize("preset_env", [True, False])
def test_dryrun_multichip(preset_env):
    env = (
        {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
        if preset_env
        else {}
    )
    prelude = (
        "import jax; jax.config.update('jax_platforms','cpu')\n"
        if preset_env
        else ""
    )
    r = _run(
        prelude + "import __graft_entry__ as g\ng.dryrun_multichip(8)\n",
        env,
    )
    assert "one train step done" in r.stdout, r.stderr[-2000:]
