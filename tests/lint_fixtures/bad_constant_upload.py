"""Known-bad fixture for constant-upload. Lines pinned by
tests/test_analysis.py."""
import jax
import jax.numpy as jnp

from tables import BIG_TABLE  # AST-only: resolved names never execute


def per_call(x):
    t = jnp.asarray(BIG_TABLE)  # line 10: re-uploads the constant per call
    return x + t


@jax.jit
def jitted(x):
    return x + jnp.array(BIG_TABLE)  # line 16: re-baked per trace


def make_forward():
    table = jnp.asarray(BIG_TABLE)  # factory scope (hoist target): OK

    def forward(x):
        return x + table

    return forward


def lowercase_local(x):
    y = jnp.asarray(x)  # lowercase name: OK (data, not a constant)
    return y


def declared(x):
    # lint: allow[constant-upload] fixture: tiny scalar table, measured irrelevant
    return x + jnp.asarray(BIG_TABLE)  # suppressed
