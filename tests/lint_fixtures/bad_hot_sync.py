"""Known-bad fixture for hot-sync (explicit-path mode treats every
function as hot). Lines pinned by tests/test_analysis.py."""
import jax
import numpy as np


def dispatch(forward, params, batch):
    return np.asarray(forward(params, batch))  # line 8: d2h per dispatch


def peek(loss):
    return loss.item()  # line 12: per-step device sync


def fence(x):
    jax.block_until_ready(x)  # line 16: pipeline stall
    return x


def fence_method(x):
    x.block_until_ready()  # line 21: same stall, method form
    return x


def pull(x):
    return jax.device_get(x)  # line 26: explicit d2h in a hot path


def fold(step, params, batch):
    return float(step(params, batch))  # line 30: float() materializes


def host_math(samples):
    return float(np.percentile(samples, 50))  # host numpy: OK


def declared(forward, params, batch):
    # lint: allow[hot-sync] fixture: the declared materialization point
    return np.asarray(forward(params, batch))  # suppressed
