"""Known-bad fixture for typed-error. Lines pinned by test_analysis.py."""


def swallow(fn):
    try:
        return fn()
    except:  # line 7: bare except
        return None


def check(x):
    assert x > 0  # line 12: assert vanishes under python -O
    return x
