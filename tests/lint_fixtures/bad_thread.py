"""Known-bad fixture for thread-discipline: nothing in this module ever
joins a thread, on purpose. Lines pinned by test_analysis.py."""
import threading


def start_anonymous(fn):
    t = threading.Thread(target=fn)  # line 7: no name, no daemon/join
    t.start()
    return t


def start_named_but_leaked(fn):
    t = threading.Thread(target=fn, name="worker")  # line 13: never joined
    t.start()
    return t
