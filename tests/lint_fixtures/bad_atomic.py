"""Known-bad fixture: every write here violates atomic-write. Line
numbers are pinned by tests/test_analysis.py — edit with care."""
import json

import numpy as np


def write_report(path, rows):
    with open(path, "w") as f:  # line 9: raw truncating write
        json.dump(rows, f)


def save_weights(path, arr):
    np.save(path + ".npy", arr)  # line 14: np.save straight to a path


def save_bundle(path, **arrs):
    np.savez(path + ".npz", **arrs)  # line 18: np.savez to a path expr


def append_log(path, line):
    with open(path, "a") as f:  # append streams are torn-tail tolerant: OK
        f.write(line)
