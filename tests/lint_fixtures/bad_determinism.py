"""Known-bad fixture for the determinism rule (explicit-path mode puts
this file in scope). Lines pinned by tests/test_analysis.py."""
import random
import time

import numpy as np


def stamp():
    return time.time()  # line 10: wall clock in a replay-bearing module


def jitter():
    return random.random()  # line 14: module-level global RNG


def make_rng():
    return random.Random()  # line 18: unseeded instance


def sample(n):
    return np.random.rand(n)  # line 22: numpy global RNG state


def good(seed, n):
    rng = np.random.default_rng(seed)  # seeded, owned stream: OK
    t0 = time.monotonic()  # monotonic interval timing: OK
    _ = random.Random(seed)  # seeded instance: OK
    return rng.random(n), t0
