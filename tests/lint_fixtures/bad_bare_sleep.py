"""Known-bad fixture for the bare-sleep rule (explicit-path mode puts
this file in scope). Lines pinned by tests/test_analysis.py."""
import time
from time import sleep


def backoff():
    time.sleep(0.1)  # line 8: bare sleep — invisible stall, uninjectable


def imported():
    sleep(0.05)  # line 12: from-import does not dodge the rule


def declared():
    # lint: allow[bare-sleep] fixture: the reasoned pragma path
    time.sleep(0.01)


def injectable(wait=time.sleep):
    wait(0.02)  # injected sleep hook: the prescribed fix, not a finding
