"""Known-bad fixture for donation. Lines pinned by tests/test_analysis.py."""
import functools

import jax


@jax.jit
def step(params, opt_state, batch):  # line 8: step-shaped, no donation
    return params, opt_state, 0.0


@jax.jit
def eval_step(params, batch):  # line 13: *step taking params, no donation
    return batch


@functools.partial(jax.jit, donate_argnums=(0, 1))
def good_step(params, opt_state, batch):
    return params, opt_state, 0.0


def run(params, opt_state, batch):
    params2, opt2, loss = good_step(params, opt_state, batch)
    return params, loss  # line 24: donated `params` read after the call


def run_ok(params, opt_state, batch):
    params, opt_state, loss = good_step(params, opt_state, batch)
    return params, loss  # rebound by the call's own targets: OK


@functools.partial(jax.jit, donate_argnums=(0, 1))
# lint: allow[donation] fixture: a reasoned pragma suppresses the def line
def pragma_step(params, opt_state, batch):
    return params, opt_state, 0.0
