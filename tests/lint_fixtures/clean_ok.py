"""Fixture that satisfies every rule even in explicit-path (all-scopes)
mode — the linter must report nothing here."""
import threading
import time

import numpy as np


def sample(seed, n):
    rng = np.random.default_rng(seed)
    return rng.random(n)


def timed(fn):
    t0 = time.monotonic()
    out = fn()
    return out, time.monotonic() - t0


def run_worker(fn):
    t = threading.Thread(target=fn, name="fixture-worker", daemon=True)
    t.start()
    t.join(timeout=1.0)
    return t


def typed(x):
    if x <= 0:
        raise ValueError(f"x must be positive, got {x}")
    return x
