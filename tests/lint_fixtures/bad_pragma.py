"""Pragma-grammar fixture. Lines pinned by test_analysis.py."""
import json


def reasoned(path, rows):
    # lint: allow[atomic-write] fixture: a reasoned pragma suppresses the next line
    with open(path, "w") as f:
        json.dump(rows, f)


def unreasoned(path, rows):
    with open(path, "w") as f:  # lint: allow[atomic-write]
        json.dump(rows, f)  # line 12 pragma has no reason: two findings


def unknown_rule(path, rows):
    # lint: allow[made-up-rule] this rule id does not exist
    with open(path, "w") as f:
        json.dump(rows, f)
