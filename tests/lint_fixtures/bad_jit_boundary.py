"""Known-bad fixture for jit-boundary. Lines pinned by
tests/test_analysis.py — edit with care. AST-only: never imported."""
import functools

import jax
import numpy as np

_TABLE = np.arange(8)      # module-level mutable array state
OK_TUPLE = (1, 2, 3)       # immutable literal: never flagged


class Model:
    @jax.jit
    def forward(self, x):
        return x * self.scale  # line 15: jitted fn reads instance state


@jax.jit
def bake(x):
    return x + _TABLE  # line 20: bakes module-level mutable array


@jax.jit
def bad_flag(x, mode="fast"):  # line 24: str default traced per call
    return x


@functools.partial(jax.jit, static_argnames=("mode",))
def ok_static(x, mode="fast"):  # static string arg: OK
    return x


def _inner(x):
    return x * _TABLE  # line 34: shard_map'd fn bakes module state


mapped = shard_map(_inner, mesh=None, in_specs=None, out_specs=None)


def _wrapped(x):
    return x + _TABLE  # line 41: jit-wrapped-by-assignment fn


wrapped = jax.jit(_wrapped)


@jax.jit
def pragma_ok(x):
    # lint: allow[jit-boundary] fixture: table frozen read-only at module init
    return x + _TABLE  # suppressed by the reasoned pragma above


def plain_host_read(x):
    return x + _TABLE[0]  # not jitted: host code may read module arrays
