"""Fault-injection plan grammar, retry policy, and the fault points
threaded through the loader and checkpoint paths."""

import json
import os

import numpy as np
import pytest

from deepgo_tpu.data.dataset import GoDataset
from deepgo_tpu.utils import faults
from deepgo_tpu.utils.retry import retry_with_backoff


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Each test starts (and leaves) with no active plan and no env."""
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


# ---- grammar ----


def test_plan_parse_full_grammar():
    plan = faults.FaultPlan.parse(
        "ckpt_write:fail@2,loader_io:transient@5,kill:step@7")
    assert [(s.site, s.kind, s.arg) for s in plan.specs] == [
        ("ckpt_write", "fail", 2),
        ("loader_io", "transient", 5),
        ("kill", "step", 7),
    ]
    assert bool(plan)
    assert not bool(faults.FaultPlan.parse(""))


@pytest.mark.parametrize("bad", [
    "ckpt_write",            # no kind
    "ckpt_write:fail",       # no arg
    "ckpt_write:explode@1",  # unknown kind
    "ckpt_write:fail@x",     # non-integer arg
    "ckpt_write:fail@0",     # arg must be >= 1
    "ckpt_write:step@3",     # step@ is kill-only
    "kill:fail@3",           # kill takes step@ only
])
def test_plan_parse_rejects_bad_specs(bad):
    with pytest.raises(ValueError, match="bad fault spec"):
        faults.FaultPlan.parse(bad)


def test_plan_read_from_env(monkeypatch):
    monkeypatch.setenv("DEEPGO_FAULTS", "loader_io:fail@1")
    faults.reset()
    with pytest.raises(faults.InjectedFailure):
        faults.check("loader_io")


# ---- semantics ----


def test_fail_fires_on_nth_hit_only():
    plan = faults.FaultPlan.parse("ckpt_write:fail@2")
    plan.check("ckpt_write")  # hit 1 passes
    with pytest.raises(faults.InjectedFailure):
        plan.check("ckpt_write")  # hit 2 fires
    plan.check("ckpt_write")  # hit 3 passes again (one-shot hard fault)
    plan.check("other_site")  # unrelated sites never fire


def test_transient_fires_first_n_hits():
    plan = faults.FaultPlan.parse("loader_io:transient@2")
    for _ in range(2):
        with pytest.raises(faults.TransientFault):
            plan.check("loader_io")
    plan.check("loader_io")  # recovered
    # transient faults are OSErrors so the production retry policy sees them
    assert issubclass(faults.TransientFault, OSError)
    assert not issubclass(faults.InjectedFailure, OSError)


# ---- retry policy ----


def test_retry_absorbs_transients_with_backoff():
    delays = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 3:
            raise OSError("transient")
        return "ok"

    out = retry_with_backoff(flaky, attempts=5, base_delay=0.05,
                             on_retry=lambda e, a, d: None,
                             sleep=delays.append)
    assert out == "ok" and calls["n"] == 4
    assert delays == [0.05, 0.1, 0.2]  # exponential


def test_retry_exhaustion_reraises():
    def always():
        raise OSError("persistent")

    with pytest.raises(OSError, match="persistent"):
        retry_with_backoff(always, attempts=3, base_delay=0.01,
                           on_retry=lambda e, a, d: None, sleep=lambda d: None)


def test_retry_does_not_catch_logic_errors():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise TypeError("bug, not weather")

    with pytest.raises(TypeError):
        retry_with_backoff(broken, attempts=5, sleep=lambda d: None)
    assert calls["n"] == 1


def test_retry_delay_capped():
    delays = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 4:
            raise OSError("x")
        return 1

    retry_with_backoff(flaky, attempts=5, base_delay=1.0, max_delay=2.0,
                       on_retry=lambda e, a, d: None, sleep=delays.append)
    assert delays == [1.0, 2.0, 2.0, 2.0]


def test_retry_full_jitter_bounded_and_decorrelated():
    # jitter=True draws each sleep U(0, envelope): inside the exponential
    # envelope, reproducible under a seeded rng, and the envelope itself
    # keeps growing (the cap still applies)
    import random

    delays = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 4:
            raise OSError("x")
        return 1

    retry_with_backoff(flaky, attempts=5, base_delay=1.0, max_delay=2.0,
                       on_retry=lambda e, a, d: delays.append(d),
                       sleep=lambda d: None, jitter=True,
                       rng=random.Random(0))
    ref = random.Random(0)
    assert delays == [ref.uniform(0, 1.0), ref.uniform(0, 2.0),
                      ref.uniform(0, 2.0), ref.uniform(0, 2.0)]
    for d, envelope in zip(delays, [1.0, 2.0, 2.0, 2.0]):
        assert 0.0 <= d <= envelope
    # two herd members with different rngs sleep different amounts — the
    # decorrelation that motivates the mode
    other = random.Random(1)
    assert delays != [other.uniform(0, e) for e in [1.0, 2.0, 2.0, 2.0]]


def test_retry_on_retry_sees_actual_jittered_delay():
    # on_retry and sleep must observe the SAME drawn value
    import random

    seen, slept = [], []

    def always():
        raise OSError("x")

    with pytest.raises(OSError):
        retry_with_backoff(always, attempts=3, base_delay=0.5,
                           on_retry=lambda e, a, d: seen.append(d),
                           sleep=slept.append, jitter=True,
                           rng=random.Random(2))
    assert seen == slept and len(seen) == 2


# ---- fault points in real paths ----


def synth_dataset(root) -> GoDataset:
    """A 16-position all-empty-board split, enough to exercise gathers."""
    d = os.path.join(root, "train")
    os.makedirs(d)
    n = 16
    np.zeros((n, 9, 19, 19), np.uint8).tofile(os.path.join(d, "planes.bin"))
    meta = np.zeros((n, 6), np.int32)
    meta[:, 0] = 1  # player
    meta[:, 3] = meta[:, 4] = 1  # ranks
    np.save(os.path.join(d, "meta.npy"), meta)
    with open(os.path.join(d, "games.json"), "w") as f:
        json.dump([{"name": "g", "start": 0, "count": n}], f)
    return GoDataset(root, "train")


def test_loader_io_transient_absorbed_by_batch_at(tmp_path, monkeypatch):
    # cut the real sleeps out of the gather's retry policy
    import deepgo_tpu.data.dataset as dataset_mod

    real = dataset_mod.retry_with_backoff
    monkeypatch.setattr(
        dataset_mod, "retry_with_backoff",
        lambda fn, **kw: real(fn, **{**kw, "sleep": lambda d: None,
                                     "on_retry": lambda e, a, d: None}))
    ds = synth_dataset(str(tmp_path))
    faults.install("loader_io:transient@2")
    packed, player, rank, target = ds.batch_at(np.arange(4))
    assert packed.shape == (4, 9, 19, 19)  # two transients absorbed


def test_loader_io_hard_fault_propagates(tmp_path):
    ds = synth_dataset(str(tmp_path))
    faults.install("loader_io:fail@1")
    with pytest.raises(faults.InjectedFailure):
        ds.batch_at(np.arange(4))
    # one-shot: the next gather works
    packed, *_ = ds.batch_at(np.arange(4))
    assert packed.shape == (4, 9, 19, 19)


def test_ckpt_write_fault_is_atomic(tmp_path):
    from deepgo_tpu.experiments import checkpoint as ckpt

    path = str(tmp_path / "checkpoint-00000005.npz")
    ckpt.save_checkpoint(path, {"w": np.arange(4.0)}, {"m": np.zeros(2)},
                         {"id": "x", "step": 5, "validation_history": [],
                          "config": {}})
    before = open(path, "rb").read()
    faults.install("ckpt_write:fail@1")
    with pytest.raises(faults.InjectedFailure):
        ckpt.save_checkpoint(path, {"w": np.ones(4)}, {"m": np.ones(2)},
                             {"id": "x", "step": 6,
                              "validation_history": [], "config": {}})
    # failed write left the previous artifact byte-identical, no temp files
    assert open(path, "rb").read() == before
    assert [p.name for p in tmp_path.iterdir()] == ["checkpoint-00000005.npz"]
    assert ckpt.verify_checkpoint(path)["step"] == 5
