"""Go rules engine unit tests on hand-written positions.

These cover the paths the bundled fixture corpus cannot: handicap aging,
suicide, multi-chain captures, ladder success/failure with breakers, and the
exact liberties-after/kills semantics at board edges.
"""

import numpy as np
import pytest

from deepgo_tpu.go import (
    BLACK,
    EMPTY,
    WHITE,
    IllegalMoveError,
    group_and_liberties,
    ladder_moves,
    new_board,
    play,
    simulate_play,
    summarize,
)
from deepgo_tpu.go.summarize import kills_and_liberties_after, ladders_and_liberties
from deepgo_tpu.go.board import find_groups


def board_from(rows):
    """Build a stones array from strings of '.XO' (row index = x)."""
    stones, _ = new_board()
    for x, row in enumerate(rows):
        for y, c in enumerate(row):
            stones[x, y] = {".": EMPTY, "X": BLACK, "O": WHITE}[c]
    return stones


def test_single_stone_liberties():
    stones = board_from(["X" + "." * 18] + ["." * 19] * 18)
    _, libs = group_and_liberties(stones, 0, 0)
    assert len(libs) == 2  # corner stone
    stones[9, 9] = WHITE
    _, libs = group_and_liberties(stones, 9, 9)
    assert len(libs) == 4  # center stone


def test_chain_merging_liberties():
    stones, _ = new_board()
    for y in (3, 4, 5):
        stones[3, y] = BLACK
    group, libs = group_and_liberties(stones, 3, 4)
    assert len(group) == 3
    assert len(libs) == 8


def test_capture_single_stone():
    stones, age = new_board()
    play(stones, age, 5, 5, WHITE)
    for x, y in ((4, 5), (6, 5), (5, 4)):
        play(stones, age, x, y, BLACK)
    assert stones[5, 5] == WHITE
    kills = play(stones, age, 5, 6, BLACK)
    assert kills == 1
    assert stones[5, 5] == EMPTY
    assert age[5, 5] == 1  # freed point restarts its age clock


def test_multi_chain_capture_explicit():
    # Two separate white chains share their final liberty at p; one black
    # move captures both.
    stones, age = new_board()
    # chain A: (0,0); chain B: (2,0); both bordered so that (1,0) is last lib
    stones[0, 0] = WHITE
    stones[2, 0] = WHITE
    stones[0, 1] = BLACK
    stones[2, 1] = BLACK
    stones[3, 0] = BLACK
    kills = play(stones, age, 1, 0, BLACK)
    assert kills == 2
    assert stones[0, 0] == EMPTY and stones[2, 0] == EMPTY
    assert stones[1, 0] == BLACK


def test_suicide_removes_own_chain():
    # Point (0,0) surrounded by white: black playing there is suicide and
    # the black stone is removed (reference play_with_f applies the dead
    # check to the played chain too, makedata.lua:234-241).
    stones, age = new_board()
    stones[0, 1] = WHITE
    stones[1, 0] = WHITE
    stones[1, 1] = WHITE  # give whites liberties
    kills = play(stones, age, 0, 0, BLACK)
    assert kills == 0
    assert stones[0, 0] == EMPTY
    assert age[0, 0] == 1


def test_simulate_play_restores_board():
    stones, _ = new_board()
    stones[0, 1] = BLACK
    stones[1, 0] = BLACK
    stones[0, 0] = WHITE  # white in atari at corner
    before = stones.copy()
    kills, libs = simulate_play(stones, 1, 1, BLACK)
    assert kills == 0
    assert np.array_equal(stones, before)
    # black capturing the corner: play at ... corner stone's last liberty is (1,1)? neighbors of (0,0): (0,1)B,(1,0)B -> 0 libs already; instead:
    stones[0, 0] = EMPTY
    stones[1, 1] = WHITE
    before = stones.copy()
    kills, libs = simulate_play(stones, 0, 0, WHITE)
    assert np.array_equal(stones, before)


def test_kills_and_liberties_after_capture_frees_points():
    # White stone at (0,0) in atari; black playing its last liberty captures
    # it and the freed point counts as a liberty of the capturing chain.
    stones, _ = new_board()
    stones[0, 0] = WHITE
    stones[0, 1] = BLACK
    kills, libs_after = simulate_play(stones, 1, 0, BLACK)
    assert kills == 1
    # new black stone at (1,0): neighbors (0,0) freed, (2,0), (1,1); chain
    # merges with nothing.
    assert libs_after == 3


def test_illegal_move_raises():
    stones, age = new_board()
    play(stones, age, 3, 3, BLACK)
    with pytest.raises(IllegalMoveError):
        play(stones, age, 3, 3, WHITE)


def test_age_semantics():
    stones, age = new_board()
    play(stones, age, 0, 0, BLACK)
    play(stones, age, 5, 5, WHITE)
    play(stones, age, 10, 10, BLACK)
    assert age[0, 0] == 3 and age[5, 5] == 2 and age[10, 10] == 1
    assert age[1, 1] == 0  # untouched empty points stay at 0


def test_handicap_aging_matches_sequential_placement():
    # Handicap stones are placed through the same path as moves, so the
    # i-th of H stones has age H-i+1 once all are down.
    from deepgo_tpu import sgf
    from deepgo_tpu.go import replay_positions

    game = sgf.parse("(;BR[9d]WR[9d]AB[pd][dp][pp];B[dd])")
    packed, move = next(replay_positions(game))
    age = packed[6]
    assert age[15, 3] == 3 and age[3, 15] == 2 and age[15, 15] == 1


def test_fast_path_matches_simulation():
    # kills_and_liberties_after's no-capture fast path must agree with the
    # full simulation everywhere on a busy random board.
    rng = np.random.default_rng(0)
    stones, age = new_board()
    for _ in range(120):
        x, y = rng.integers(0, 19, size=2)
        if stones[x, y] == EMPTY:
            play(stones, age, int(x), int(y), int(rng.integers(1, 3)))
    labels, groups = find_groups(stones)
    kills, lib_after = kills_and_liberties_after(stones, labels, groups)
    for x in range(19):
        for y in range(19):
            if stones[x, y] != EMPTY:
                assert kills[0, x, y] == 0 and lib_after[1, x, y] == 0
                continue
            for player in (1, 2):
                k, la = simulate_play(stones, x, y, player)
                assert kills[player - 1, x, y] == min(k, 255), (x, y, player)
                assert lib_after[player - 1, x, y] == min(la, 255), (x, y, player)


def _ladder_board():
    """Classic working ladder: white stone at (2,2) with two liberties,
    hemmed by black so every escape leaves exactly two liberties and the
    chase staircases toward the far corner."""
    stones, _ = new_board()
    stones[2, 2] = WHITE
    stones[1, 2] = BLACK
    stones[2, 1] = BLACK
    stones[1, 3] = BLACK
    return stones


def test_ladder_capture_works_toward_corner():
    stones = _ladder_board()
    _, libs = group_and_liberties(stones, 2, 2)
    assert sorted(libs) == [(2, 3), (3, 2)]
    moves = ladder_moves(stones, 2, 2, libs)
    # only the (3,2) chase works: chasing from (2,3) leaves the chasing
    # stone itself with too few liberties (the > 2 guard).
    assert moves == [(3, 2)]
    # board restored after the search
    assert stones[2, 2] == WHITE and int((stones > 0).sum()) == 4


def test_ladder_breaker_defeats_ladder():
    stones = _ladder_board()
    # a white "ladder breaker" stone on the diagonal escape path
    stones[10, 10] = WHITE
    _, libs = group_and_liberties(stones, 2, 2)
    moves = ladder_moves(stones, 2, 2, libs)
    assert moves == []


def test_ladders_plane_marks_chaser():
    stones = _ladder_board()
    ladders, liberties = ladders_and_liberties(stones)
    # chased chain is white (player 2) of size 1 -> chasing player is black
    # (index 0), marked with the chased-chain size at the working move.
    assert int(ladders[0].sum()) == 1
    assert ladders[0, 3, 2] == 1
    assert int(ladders[1].sum()) == 0
    assert liberties[2, 2] == 2
    assert liberties[1, 2] == 5  # chain {(1,2),(1,3)}
    assert liberties[1, 3] == 5
    assert liberties[2, 1] == 3  # lone stone beside the white chain


def test_summarize_packed_layout():
    stones, age = new_board()
    play(stones, age, 3, 3, BLACK)
    packed = summarize(stones, age)
    assert packed.shape == (9, 19, 19) and packed.dtype == np.uint8
    assert packed[0, 3, 3] == BLACK
    assert packed[1, 3, 3] == 4
    assert packed[6, 3, 3] == 1
    # liberties-after for black at an adjacent point merges with the chain
    assert packed[2, 3, 4] == 6  # black plays (3,4): chain of 2, 6 liberties
    assert packed[3, 3, 4] == 3  # white plays (3,4): single stone, 3 libs
