"""External-process watchdog (utils/watchdog.py): fuse arithmetic.

The kill path itself is pinned by test_graft_entry.py (arm + C-level wedge
-> SIGKILL) and the disarm path by its survive-past-fuse case; what lives
here is the satellite boundary fix: the child's 1-second poll count must
round the budget UP, because an early kill murders a healthy process while
a late one only delays a diagnosis.
"""

import subprocess
import sys
import time

from deepgo_tpu.utils import watchdog


def test_poll_count_rounds_fractional_budgets_up():
    # the regression: int(1.5) == 1 made a 1.5s fuse fire at ~1s
    assert watchdog._poll_count(1.5) == 2
    assert watchdog._poll_count(0.1) == 1
    assert watchdog._poll_count(2.0) == 2
    assert watchdog._poll_count(2.000001) == 3
    # degenerate budgets poll at least once instead of insta-killing
    assert watchdog._poll_count(0.0) == 1
    assert watchdog._poll_count(-3.0) == 1


def test_fractional_fuse_does_not_fire_early():
    """A process armed with timeout_s=1.5 must still be alive at ~1.2s —
    before the fix the truncated fuse had already SIGKILLed it."""
    code = (
        "import sys, time\n"
        "sys.path.insert(0, sys.argv[1])\n"
        "from deepgo_tpu.utils import watchdog\n"
        "wd = watchdog.arm('boundary-test', timeout_s=1.5)\n"
        "time.sleep(1.2)\n"
        "wd.disarm()\n"
        "print('SURVIVED')\n"
    )
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    t0 = time.time()
    r = subprocess.run([sys.executable, "-c", code, repo],
                       capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, (r.returncode, r.stderr[-500:])
    assert "SURVIVED" in r.stdout
    assert time.time() - t0 >= 1.2
