"""Lock-order sanitizer: inversion cycles, long-hold hazards, RLock
re-entry, flight-recorder dumps, and the zero-overhead-when-off contract
(docs/static_analysis.md)."""

import json
import os
import threading

import pytest

from deepgo_tpu.analysis import lockcheck


@pytest.fixture
def sanitizer():
    lockcheck.enable(True)
    lockcheck.reset()
    yield
    lockcheck.enable(None)
    lockcheck.reset()


def test_disabled_returns_plain_locks():
    lockcheck.enable(False)
    try:
        lock = lockcheck.make_lock("plain")
        rlock = lockcheck.make_rlock("plain-r")
        assert not isinstance(lock, lockcheck.TrackedLock)
        assert not isinstance(rlock, lockcheck.TrackedLock)
        with lock, rlock:  # still real locks
            pass
    finally:
        lockcheck.enable(None)


def test_env_var_enables(monkeypatch):
    lockcheck.enable(None)
    monkeypatch.setenv("DEEPGO_LOCKCHECK", "1")
    assert lockcheck.enabled()
    assert isinstance(lockcheck.make_lock("via-env"), lockcheck.TrackedLock)
    monkeypatch.setenv("DEEPGO_LOCKCHECK", "0")
    assert not lockcheck.enabled()


def test_ab_ba_inversion_reports_typed_cycle(sanitizer):
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    with a:
        with b:
            pass
    assert lockcheck.report()["cycles"] == []  # one order alone is fine
    with b:
        with a:
            pass
    report = lockcheck.report()
    assert len(report["cycles"]) == 1
    cycle = report["cycles"][0]
    assert cycle["kind"] == "lock_order_cycle"
    assert set(cycle["cycle"]) == {"A", "B"}
    assert cycle["edge"]["from"] == "B" and cycle["edge"]["to"] == "A"
    assert "test_lockcheck.py" in cycle["edge"]["site"]
    assert report["edges"] == {"A": {"B": 1}, "B": {"A": 1}}


def test_cross_thread_inversion_attributes_thread_name(sanitizer):
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    first_done = threading.Event()

    def forward():
        with a:
            with b:
                pass
        first_done.set()

    def backward():
        first_done.wait(5.0)
        with b:
            with a:
                pass

    t1 = threading.Thread(target=forward, name="lockcheck-fwd", daemon=True)
    t2 = threading.Thread(target=backward, name="lockcheck-bwd", daemon=True)
    t1.start(), t2.start()
    t1.join(5.0), t2.join(5.0)
    cycles = lockcheck.report()["cycles"]
    assert len(cycles) == 1
    assert cycles[0]["thread"] == "lockcheck-bwd"  # the inverting thread


def test_three_lock_cycle(sanitizer):
    a, b, c = (lockcheck.make_lock(n) for n in "ABC")
    for first, second in ((a, b), (b, c)):
        with first:
            with second:
                pass
    assert lockcheck.report()["cycles"] == []  # A->B->C is a clean order
    with c:
        with a:
            pass
    cycles = lockcheck.report()["cycles"]
    assert len(cycles) == 1
    assert set(cycles[0]["cycle"]) == {"A", "B", "C"}


def test_duplicate_cycle_reported_once(sanitizer):
    a = lockcheck.make_lock("A")
    b = lockcheck.make_lock("B")
    for _ in range(3):
        with a:
            with b:
                pass
        with b:
            with a:
                pass
    assert len(lockcheck.report()["cycles"]) == 1


def test_rlock_reentry_is_not_a_self_edge(sanitizer):
    r = lockcheck.make_rlock("R")
    outer = lockcheck.make_lock("outer")
    with outer:
        with r:
            with r:  # re-entry must not edge R->R or crash the stack
                pass
    report = lockcheck.report()
    assert report["cycles"] == []
    assert report["edges"] == {"outer": {"R": 2}}


def test_long_hold_hazard_via_fake_clock():
    t = [0.0]
    lockcheck.enable(True)
    lockcheck.reset(clock=lambda: t[0], hold_warn_s=0.5)
    try:
        lock = lockcheck.make_lock("slow")
        for _ in range(2):  # same site twice: reported once, not per hold
            lock.acquire()
            t[0] += 2.0  # "blocking call" while holding the lock
            lock.release()
        hazards = lockcheck.report()["hazards"]
        assert len(hazards) == 1
        assert hazards[0]["kind"] == "lock_held_across_blocking_call"
        assert hazards[0]["lock"] == "slow"
        assert hazards[0]["held_s"] == 2.0
    finally:
        lockcheck.enable(None)
        lockcheck.reset()


def test_cycle_dumps_through_flight_recorder(sanitizer, tmp_path):
    from deepgo_tpu.obs import sentinel

    recorder = sentinel.FlightRecorder()
    recorder.configure(str(tmp_path))
    old = sentinel._recorder
    sentinel._recorder = recorder
    try:
        a = lockcheck.make_lock("A")
        b = lockcheck.make_lock("B")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        dump = os.path.join(str(tmp_path), "flight-0000.json")
        assert os.path.exists(dump)
        with open(dump) as f:
            record = json.load(f)
        assert record["reason"] == "lock_order_cycle"
        assert set(record["detail"]["cycle"]) == {"A", "B"}
        assert record["detail"]["kind"] == "lock_order_cycle"
    finally:
        recorder.close()
        sentinel._recorder = old


def test_tracked_locks_still_mutually_exclude(sanitizer):
    lock = lockcheck.make_lock("mutex")
    counter = [0]

    def bump():
        for _ in range(200):
            with lock:
                counter[0] += 1

    threads = [threading.Thread(target=bump, name=f"lockcheck-bump-{i}",
                                daemon=True) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10.0)
    assert counter[0] == 800
    assert lockcheck.report()["cycles"] == []


def test_obs_registry_locks_are_tracked_when_enabled(sanitizer):
    from deepgo_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("deepgo_lockcheck_fixture_total", "fixture")
    c.inc(3)
    snap = reg.snapshot()
    assert snap["metrics"]["deepgo_lockcheck_fixture_total"]["series"][""] == 3
    names = lockcheck.report()["locks"]
    assert "obs.registry" in names
    assert "obs.metric.deepgo_lockcheck_fixture_total" in names
