"""Atomic write helper: all-or-nothing file replacement."""

import os

import pytest

from deepgo_tpu.utils.atomicio import atomic_write, atomic_write_bytes


def test_atomic_write_creates_and_replaces(tmp_path):
    path = tmp_path / "f.bin"
    with atomic_write(str(path)) as f:
        f.write(b"one")
    assert path.read_bytes() == b"one"
    with atomic_write(str(path)) as f:
        f.write(b"two")
    assert path.read_bytes() == b"two"
    # no temp residue either way
    assert sorted(p.name for p in tmp_path.iterdir()) == ["f.bin"]


def test_atomic_write_failure_preserves_original(tmp_path):
    path = tmp_path / "f.bin"
    path.write_bytes(b"precious")
    with pytest.raises(RuntimeError, match="mid-write"):
        with atomic_write(str(path)) as f:
            f.write(b"partial garbage")
            raise RuntimeError("crash mid-write")
    # the original is untouched and the partial temp file is gone
    assert path.read_bytes() == b"precious"
    assert sorted(p.name for p in tmp_path.iterdir()) == ["f.bin"]


def test_atomic_write_failure_leaves_no_file_when_new(tmp_path):
    path = tmp_path / "new.bin"
    with pytest.raises(ValueError):
        with atomic_write(str(path)) as f:
            f.write(b"x")
            raise ValueError("boom")
    assert not path.exists()
    assert list(tmp_path.iterdir()) == []


def test_atomic_write_text_mode(tmp_path):
    path = tmp_path / "t.txt"
    with atomic_write(str(path), "w") as f:
        f.write("hello")
    assert path.read_text() == "hello"


def test_atomic_write_bytes(tmp_path):
    path = tmp_path / "b.bin"
    atomic_write_bytes(str(path), b"\x00\x01")
    assert path.read_bytes() == b"\x00\x01"
