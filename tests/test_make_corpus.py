"""Corpus generator: pool construction, splits, ranks, opening diversity."""

import os
import sys

import numpy as np
import pytest

from conftest import REPO_ROOT

sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

import make_corpus  # noqa: E402


def _moves(sgf_text):
    import re

    return re.findall(r";[BW]\[(\w\w)\]", sgf_text)


def test_generate_scripted_splits_ranks_and_openings(tmp_path):
    out = str(tmp_path / "corpus")
    pool = make_corpus.build_pool([], seed=5, temperature=0.0)
    totals = make_corpus.generate(out, target_positions=600, chunk=16,
                                  max_moves=60, seed=5, opening_plies=4,
                                  pool=pool)
    assert totals["games"] >= 16 and totals["positions"] >= 600
    sgfs = []
    for split in ("train", "validation", "test"):
        d = os.path.join(out, "sgf", split)
        sgfs += [os.path.join(d, f) for f in os.listdir(d)]
    assert len(sgfs) == totals["games"]
    # gid % 50 split rule puts gid 1 in validation and gid 2 in test, so
    # both side splits are populated from the very first chunk
    assert os.listdir(os.path.join(out, "sgf", "validation"))
    assert os.listdir(os.path.join(out, "sgf", "test"))
    # the FIRST chunk (gids 0..15, ordered by basename across splits) is
    # the oneply self-pair: 8d vs 8d rank tags from the pool
    texts = [open(f).read()
             for f in sorted(sgfs, key=os.path.basename)[:16]]
    assert all("BR[8d]" in t and "WR[8d]" in t for t in texts)
    # per-game openings: the first 4 moves must NOT be identical across
    # all games of the deterministic self-pair chunk (the diversity the
    # round-4 +6.6-point lever depends on)
    openings = {tuple(_moves(t)[:4]) for t in texts}
    assert len(openings) > 8


def test_build_pool_extra_spec_and_rank():
    pool = make_corpus.build_pool(["model:small=7"], seed=0, temperature=0.5)
    assert set(pool) == {"heuristic", "oneply", "x0-init-small"}
    agent, rank = pool["x0-init-small"]
    assert rank == 7 and agent.temperature == 0.5


def test_build_pool_rejects_malformed_extra():
    with pytest.raises(AssertionError, match="SPEC=RANK"):
        make_corpus.build_pool(["model:small"], seed=0, temperature=0.0)


def test_default_pool_preserves_legacy_pair_cycle(tmp_path):
    # the bit-exact regeneration of the round-4 corpus depends on the
    # default pool ordering strongest-first: (oneply,oneply) must be the
    # first pairing (fresh-machine recipe, RESULTS.md)
    pool = make_corpus.build_pool([], seed=0, temperature=0.0)
    names = sorted(pool, key=lambda n: (-pool[n][1], n))
    pairs = [(a, b) for i, a in enumerate(names) for b in names[i:]]
    assert pairs == [("oneply", "oneply"), ("oneply", "heuristic"),
                     ("heuristic", "heuristic")]
