"""SGF parser unit tests."""

from deepgo_tpu import sgf


def test_basic_moves():
    game = sgf.parse("(;GM[1]FF[4]SZ[19]BR[9d]WR[3d];B[pd];W[dd];B[pq])")
    assert [(m.player, m.x, m.y) for m in game.moves] == [
        (1, 15, 3),
        (2, 3, 3),
        (1, 15, 16),
    ]
    assert game.ranks == (9, 3)
    assert game.handicaps == []


def test_multiline_and_crlf():
    text = "(;GM[1]\r\nFF[4]\r\nBR[5d]\r\nWR[5d]\r\n;B[aa]\r\n;W[ss])"
    game = sgf.parse(text)
    assert [(m.x, m.y) for m in game.moves] == [(0, 0), (18, 18)]
    assert game.ranks == (5, 5)


def test_passes_dropped():
    # Empty value and 'tt' are both passes on 19x19.
    game = sgf.parse("(;BR[1d]WR[1d];B[pd];W[];B[tt];W[dd])")
    assert [(m.player, m.x, m.y) for m in game.moves] == [(1, 15, 3), (2, 3, 3)]


def test_handicap_order_preserved():
    game = sgf.parse("(;BR[2d]WR[2d]AB[pd][dp]AW[dd]AB[pp];B[qq])")
    assert [(m.player, m.x, m.y) for m in game.handicaps] == [
        (1, 15, 3),
        (1, 3, 15),
        (2, 3, 3),
        (1, 15, 15),
    ]


def test_ranks_rejected():
    # Kyu ranks, missing ranks, and out-of-range dan ranks disqualify a game,
    # mirroring the reference's get_ranks/to_rank gate (makedata.lua:92-120).
    assert sgf.parse("(;BR[5k]WR[1d];B[aa])").ranks is None
    assert sgf.parse("(;BR[1d];B[aa])").ranks is None
    assert sgf.parse("(;BR[12d]WR[1d];B[aa])").ranks is None


def test_escaped_bracket_in_comment():
    game = sgf.parse("(;BR[9d]WR[9d]C[a \\] tricky comment];B[cc])")
    assert [(m.x, m.y) for m in game.moves] == [(2, 2)]


def test_property_values_accumulate():
    game = sgf.parse("(;AB[aa][bb]AB[cc];B[dd])")
    assert len(game.handicaps) == 3
