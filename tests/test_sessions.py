"""Durable game sessions (deepgo_tpu/sessions/): the legality edges the
replay engine omits, the WAL acked==durable contract, checkpoint
fallback, and the two services over a stub fleet.

The legality layer is pinned against ``go/replay.py`` ground truth: for
a real recorded game, driving ``GoGame`` through the same moves must
produce bit-identical pre-move planes — the session board is the replay
board plus refusals, never a different board.
"""

import json
import os
import random
from concurrent.futures import Future

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu.go.board import BLACK, WHITE
from deepgo_tpu.go.replay import replay_positions
from deepgo_tpu.go.summarize import summarize
from deepgo_tpu.obs import workload as workload_mod
from deepgo_tpu.sessions import (GameService, GoGame, IllegalMove,
                                 ReplyExhausted, SessionCorrupt,
                                 SessionNotFound, SessionStore,
                                 SgfAnalysisService)
from deepgo_tpu.sessions.analysis import AnalysisCursorError
from deepgo_tpu.sgf import parse_file
from deepgo_tpu.utils import faults

PINNED_SGF = os.path.join(REPO_ROOT, "data", "sgf", "test", "1993",
                          "2000-03-24b.sgf")


@pytest.fixture(autouse=True)
def _clean_faults(monkeypatch):
    """Each test starts (and leaves) with no active plan and no env."""
    monkeypatch.delenv("DEEPGO_FAULTS", raising=False)
    faults.reset()
    yield
    faults.reset()


# ---- legality edges ----


class TestLegality:
    def test_turn_order_and_occupied(self):
        g = GoGame("t")
        assert "out of turn" in g.check_move(3, 3, WHITE)
        g.play_move(3, 3, BLACK)
        assert "occupied" in g.check_move(3, 3, WHITE)
        with pytest.raises(IllegalMove) as ei:
            g.play_move(3, 3, WHITE)
        assert ei.value.session_id == "t"
        assert "occupied" in ei.value.reason

    def test_suicide_refused(self):
        # white walls the (0, 0) corner; black playing into it has zero
        # liberties and captures nothing
        g = GoGame("s", handicaps=((WHITE, 0, 1), (WHITE, 1, 0)))
        g.play_move(10, 10, WHITE)  # handicap setup: white moves first
        reason = g.check_move(0, 0, BLACK)
        assert reason is not None and "suicide" in reason
        with pytest.raises(IllegalMove):
            g.play_move(0, 0, BLACK)
        # the refused move mutated nothing
        assert g.to_play == BLACK and len(g.moves) == 1

    def test_capture_in_corner_is_not_suicide(self):
        # same corner, but the "suicide" point captures a white stone
        # first — the board engine's capture-before-liberty order
        g = GoGame("c", handicaps=((WHITE, 0, 0), (BLACK, 0, 1),
                                   (BLACK, 2, 0)))
        g.play_move(10, 10, WHITE)
        assert g.check_move(1, 0, BLACK) is None
        kills = g.play_move(1, 0, BLACK)
        assert kills == 1 and g.captures[BLACK] == 1

    def test_positional_superko(self):
        # a classic ko at a=(5,5)/b=(5,6): white takes, black may NOT
        # immediately retake (the recreated position is in history) but
        # may after a ko-threat exchange elsewhere changes the position
        g = GoGame("ko", handicaps=(
            (BLACK, 4, 5), (BLACK, 5, 4), (BLACK, 6, 5),   # around a
            (WHITE, 4, 6), (WHITE, 5, 7), (WHITE, 6, 6),   # around b
            (BLACK, 5, 6),                                 # the ko stone
        ))
        assert g.check_move(5, 5, WHITE) is None
        assert g.play_move(5, 5, WHITE) == 1  # takes the ko
        reason = g.check_move(5, 6, BLACK)
        assert reason is not None and "superko" in reason
        with pytest.raises(IllegalMove):
            g.play_move(5, 6, BLACK)
        g.play_move(15, 15, BLACK)  # ko threat
        g.play_move(15, 16, WHITE)  # answered
        assert g.check_move(5, 6, BLACK) is None  # retake now legal
        assert g.play_move(5, 6, BLACK) == 1

    def test_pass_pass_ends_the_game(self):
        g = GoGame("p")
        g.play_move(3, 3, BLACK)
        assert g.play_pass(WHITE) is False
        assert g.play_pass(BLACK) is True
        assert g.over
        assert "over" in g.check_move(4, 4, WHITE)
        with pytest.raises(IllegalMove):
            g.play_pass(WHITE)
        assert g.legal_points() == []

    def test_board_pinned_to_replay_ground_truth(self):
        # the session board must evolve bit-identically to the replay
        # engine for any legal recorded sequence: same planes, move by
        # move, over a real game
        sgf_game = parse_file(PINNED_SGF)
        g = GoGame("pin", handicaps=tuple(
            (m.player, m.x, m.y) for m in sgf_game.handicaps))
        applied = 0
        for packed, move in replay_positions(sgf_game):
            assert np.array_equal(summarize(g.stones, g.age), packed), \
                f"session board diverged from replay before move {applied}"
            if g.check_move(move.x, move.y, move.player) is not None:
                break  # a non-alternating record ends the pin, not the test
            g.play_move(move.x, move.y, move.player)
            applied += 1
            if applied >= 80:
                break
        assert applied >= 40

    def test_snapshot_digest_roundtrip(self):
        g = GoGame("r", handicaps=((BLACK, 3, 3),))
        g.play_move(10, 10, WHITE)
        g.play_move(4, 4, BLACK)
        g.play_pass(WHITE)
        clone = GoGame.from_snapshot(g.snapshot())
        assert clone.digest() == g.digest()
        # the clone is live state, not a frozen copy
        clone.play_move(5, 5, BLACK)
        assert clone.digest() != g.digest()


# ---- the WAL store ----


def drive(store, sid="g"):
    store.open_session(sid)
    store.append_move(sid, BLACK, x=3, y=3)
    store.append_move(sid, WHITE, x=15, y=15)
    store.append_move(sid, BLACK, x=4, y=3)
    return store.get(sid).digest()


class TestSessionStore:
    def test_acked_is_durable_without_checkpoint(self, tmp_path):
        s1 = SessionStore(str(tmp_path), checkpoint_every=1000)
        digest = drive(s1)
        s1.close(final_checkpoint=False)  # crash: WAL only, no compaction
        s2 = SessionStore(str(tmp_path), checkpoint_every=1000)
        assert s2.recovery["wal_records_applied"] == 4
        assert s2.recovery["sessions"] == 1
        assert s2.get("g").digest() == digest
        # appends continue from the recovered seq, no overlap
        assert s2.append_move("g", WHITE, x=16, y=16) == 5

    def test_torn_wal_tail_is_dropped(self, tmp_path):
        s1 = SessionStore(str(tmp_path), checkpoint_every=1000)
        digest = drive(s1)
        s1.close(final_checkpoint=False)
        (_, wal), = [(q, p) for q, p in s1._wal_paths()]
        with open(wal, "ab") as f:  # lint: allow[atomic-write] simulating a torn fsync'd append tail
            f.write(b'{"kind":"session_move","seq":5,"ses')
        s2 = SessionStore(str(tmp_path), checkpoint_every=1000)
        assert s2.recovery["torn_tail"] is True
        assert s2.get("g").digest() == digest
        assert not s2.stats()["corrupt_sessions"]

    def test_checkpoint_compacts_wal_and_prunes(self, tmp_path):
        s = SessionStore(str(tmp_path), checkpoint_every=2,
                         keep_checkpoints=2)
        drive(s)  # 4 records with checkpoint_every=2: compactions ran
        names = sorted(os.listdir(tmp_path))
        assert not [n for n in names if n.startswith("wal-")]
        ckpts = [n for n in names if n.startswith("ckpt-")]
        assert 1 <= len(ckpts) <= 2
        for _ in range(4):
            sid = f"x{_}"
            s.open_session(sid)
            s.append_move(sid, BLACK, x=_, y=0)
        ckpts = [n for n in os.listdir(tmp_path) if n.startswith("ckpt-")]
        assert len(ckpts) <= 2  # pruned to keep_checkpoints
        s.close()

    def test_corrupt_checkpoint_falls_back_to_older(self, tmp_path):
        s = SessionStore(str(tmp_path), checkpoint_every=1000,
                         keep_checkpoints=3)
        digest_a = drive(s)
        s.checkpoint()
        s.append_move("g", WHITE, x=16, y=16)
        s.checkpoint()
        s.close(final_checkpoint=False)
        newest = s._ckpt_paths()[0][1]
        with open(newest, "r+b") as f:  # lint: allow[atomic-write] corrupting a checkpoint on purpose
            f.seek(20)
            f.write(b"XXXXXX")
        s2 = SessionStore(str(tmp_path))
        assert s2.recovery["checkpoints_skipped"] == 1
        assert s2.recovery["checkpoint_seq"] == 4
        assert s2.get("g").digest() == digest_a

    def test_unreplayable_wal_falls_back_to_checkpoint(self, tmp_path):
        s = SessionStore(str(tmp_path), checkpoint_every=1000)
        digest_ckpt = drive(s)
        s.checkpoint()
        s.append_move("g", WHITE, x=16, y=16)
        s.close(final_checkpoint=False)
        (_, wal), = [(q, p) for q, p in s._wal_paths()]
        bad = {"kind": "session_move", "seq": 6, "session": "g",
               "player": WHITE, "x": 3, "y": 3}  # occupied: cannot apply
        with open(wal, "ab") as f:  # lint: allow[atomic-write] appending a poisoned WAL record
            f.write((json.dumps(bad) + "\n").encode())
        s2 = SessionStore(str(tmp_path))
        # find_latest_valid style: the session falls back to its last
        # checkpointed snapshot instead of going corrupt
        assert s2.recovery["restored_from_checkpoint"] == ["g"]
        assert not s2.stats()["corrupt_sessions"]
        assert s2.get("g").digest() == digest_ckpt

    def test_move_for_unopened_session_is_corrupt(self, tmp_path):
        s = SessionStore(str(tmp_path), checkpoint_every=1000)
        drive(s)
        s.close(final_checkpoint=False)
        (_, wal), = [(q, p) for q, p in s._wal_paths()]
        bad = {"kind": "session_move", "seq": 5, "session": "ghost",
               "player": BLACK, "x": 0, "y": 0}
        with open(wal, "ab") as f:  # lint: allow[atomic-write] appending a poisoned WAL record
            f.write((json.dumps(bad) + "\n").encode())
        s2 = SessionStore(str(tmp_path))
        assert s2.recovery["corrupt"] == ["ghost"]
        with pytest.raises(SessionCorrupt):
            s2.get("ghost")
        assert s2.get("g") is not None  # the blast radius is one session

    def test_wal_transient_absorbed_hard_fault_unacked(self, tmp_path):
        s = SessionStore(str(tmp_path), checkpoint_every=1000)
        s.open_session("g")
        faults.install("session_wal:transient@2")
        assert s.append_move("g", BLACK, x=3, y=3) == 2  # acked anyway
        assert s.stats()["wal_retries"] == 2
        faults.reset()
        faults.install("session_wal:fail@1")
        with pytest.raises(faults.InjectedFailure):
            s.append_move("g", WHITE, x=4, y=4)
        # nothing acked, nothing applied: seq and board are untouched
        assert s.seq == 2
        assert len(s.get("g").moves) == 1
        faults.reset()
        assert s.append_move("g", WHITE, x=4, y=4) == 3
        s.close(final_checkpoint=False)
        s2 = SessionStore(str(tmp_path))
        assert s2.get("g").digest() == s.get("g").digest()

    def test_typed_lookup_errors(self, tmp_path):
        s = SessionStore(str(tmp_path))
        with pytest.raises(SessionNotFound):
            s.get("nope")
        with pytest.raises(SessionNotFound):
            s.append_move("nope", BLACK, x=0, y=0)
        s.open_session("g")
        with pytest.raises(IllegalMove):
            s.append_move("g", WHITE, x=0, y=0)  # out of turn
        s.close()


# ---- the services, over a stub fleet ----


class EngineOverloaded(Exception):
    """Local stand-in: the service classifies shed errors by type NAME,
    exactly like the real fleet surface."""


class StubFleet:
    def __init__(self, errors=(), row=None):
        self.errors = list(errors)
        self.calls: list[dict] = []
        self.row = row

    def submit(self, packed, player, rank, tier=None, timeout_s=None,
               session=None, block=True):
        self.calls.append({"tier": tier, "timeout_s": timeout_s,
                           "session": session, "player": player})
        if self.errors:
            raise self.errors.pop(0)
        fut = Future()
        row = self.row if self.row is not None \
            else np.zeros(361, np.float32)
        fut.set_result(row)
        return fut


def make_service(tmp_path, **kw):
    fleet = kw.pop("fleet", StubFleet())
    store = SessionStore(os.path.join(str(tmp_path), "store"),
                         checkpoint_every=1000)
    svc = GameService(fleet, store, sleep=lambda d: None,
                      rng=random.Random(1), **kw)
    return fleet, store, svc


class TestGameService:
    def test_play_acks_then_engine_replies(self, tmp_path):
        fleet, store, svc = make_service(tmp_path)
        sid = svc.new_game("live")
        out = svc.play(sid, 3, 3)
        assert out["seq"] == 2 and "reply" in out
        # zero logits + legality mask: argmax is the first legal point
        assert (out["reply"]["x"], out["reply"]["y"]) == (0, 0)
        assert store.get(sid).moves[-1] == {"player": WHITE, "x": 0, "y": 0}
        call, = fleet.calls
        assert call["tier"] == "interactive"
        assert call["session"] == sid
        assert call["timeout_s"] == svc.budgets_s[0]
        svc.close()

    def test_illegal_client_move_changes_nothing(self, tmp_path):
        fleet, store, svc = make_service(tmp_path)
        sid = svc.new_game()
        svc.play(sid, 3, 3)
        before = store.get(sid).digest()
        game = store.get(sid)
        game_to_play = game.to_play
        with pytest.raises(IllegalMove):
            store.append_move(sid, game_to_play, x=3, y=3)  # occupied
        assert store.get(sid).digest() == before
        assert not fleet.calls[1:]  # no reply for a refused move
        svc.close()

    def test_deadline_tiers_escalate_then_succeed(self, tmp_path):
        fleet = StubFleet(errors=[EngineOverloaded("door"),
                                  TimeoutError("deadline")])
        fleet, store, svc = make_service(tmp_path, fleet=fleet)
        sid = svc.new_game()
        out = svc.play(sid, 3, 3)
        assert "reply" in out
        assert svc.reply_retries == 2
        # each attempt got the next (looser) budget tier
        assert [c["timeout_s"] for c in fleet.calls] == \
            list(svc.budgets_s)
        svc.close()

    def test_reply_exhausted_leaves_session_retriable(self, tmp_path):
        fleet = StubFleet(errors=[EngineOverloaded("x")] * 3)
        fleet, store, svc = make_service(tmp_path, fleet=fleet)
        sid = svc.new_game()
        store.append_move(sid, BLACK, x=3, y=3)
        before = store.get(sid).digest()
        with pytest.raises(ReplyExhausted):
            svc.engine_reply(sid)
        assert store.get(sid).digest() == before
        out = svc.engine_reply(sid)  # stub errors drained: retry works
        assert out["player"] == WHITE
        svc.close()

    def test_reply_fault_site_burns_one_tier(self, tmp_path):
        fleet, store, svc = make_service(tmp_path)
        sid = svc.new_game()
        store.append_move(sid, BLACK, x=3, y=3)
        faults.install("session_reply:transient@1")
        out = svc.engine_reply(sid)
        assert out["player"] == WHITE
        assert svc.reply_retries == 1
        # transient burned the first tier BEFORE the submit reached the
        # fleet: one call, made with the second budget
        assert [c["timeout_s"] for c in fleet.calls] == \
            [svc.budgets_s[1]]
        svc.close()

    def test_health_composes(self, tmp_path):
        fleet, store, svc = make_service(tmp_path)
        svc.new_game("a")
        h = svc.health()
        assert h["healthy"] is True and h["open_sessions"] == 1
        store.corrupt["ghost"] = "damaged"
        assert svc.health()["healthy"] is False
        svc.close()


class TestSgfAnalysis:
    def test_scan_annotates_and_flags_blunders(self, tmp_path):
        d = os.path.join(str(tmp_path), "sgf")
        os.makedirs(d)
        with open(PINNED_SGF, "rb") as f:
            body = f.read()
        with open(os.path.join(d, "a.sgf"), "wb") as f:  # lint: allow[atomic-write] building a test corpus
            f.write(body)
        fleet = StubFleet(row=np.full(361, -10.0, np.float64))
        svc = SgfAnalysisService(fleet, os.path.join(str(tmp_path), "out"),
                                 blunder_top=0, sleep=lambda d: None)
        report = svc.run(d)
        assert report["files_done"] == 1
        assert report["positions"] == report["annotated"] > 50
        # uniform row: every move is rank 1 at logp -10 < blunder_logp,
        # and blunder_top=0 makes every move a blunder
        assert report["blunders"] == report["annotated"]
        assert all(c["tier"] == "batch" and c["session"] == "scan:a.sgf"
                   for c in fleet.calls)
        with open(svc.sink.path, encoding="utf-8") as f:
            kinds = [json.loads(line)["kind"] for line in f]
        assert kinds.count("session_scan") == 1
        assert kinds.count("session_annotation") == report["annotated"]
        svc.close()

    def test_cursor_resumes_and_never_reannotates(self, tmp_path):
        d = os.path.join(str(tmp_path), "sgf")
        os.makedirs(d)
        with open(PINNED_SGF, "rb") as f:
            body = f.read()
        with open(os.path.join(d, "a.sgf"), "wb") as f:  # lint: allow[atomic-write] building a test corpus
            f.write(body)
        out = os.path.join(str(tmp_path), "out")
        fleet = StubFleet()
        svc = SgfAnalysisService(fleet, out, sleep=lambda d: None)
        first = svc.run(d, limit_positions=50)
        assert first["stopped_early"] and first["positions"] == 50
        svc.close()
        fleet2 = StubFleet()
        svc2 = SgfAnalysisService(fleet2, out, sleep=lambda d: None)
        second = svc2.run(d)
        assert second["files_done"] == 1
        total = sum(1 for _ in replay_positions(parse_file(PINNED_SGF)))
        # every move annotated exactly once across the two runs
        assert first["annotated"] + second["annotated"] == total
        third = svc2.run(d)
        assert third["positions"] == 0 and third["files_resumed_past"] == 1
        svc2.close()

    def test_sheds_are_absorbed_outcomes(self, tmp_path):
        d = os.path.join(str(tmp_path), "sgf")
        os.makedirs(d)
        with open(PINNED_SGF, "rb") as f:
            body = f.read()
        with open(os.path.join(d, "a.sgf"), "wb") as f:  # lint: allow[atomic-write] building a test corpus
            f.write(body)

        class SheddingFleet(StubFleet):
            def submit(self, *a, **kw):
                raise EngineOverloaded("door")

        svc = SgfAnalysisService(SheddingFleet(),
                                 os.path.join(str(tmp_path), "out"),
                                 attempts=1, sleep=lambda d: None)
        report = svc.run(d, limit_positions=20)
        assert report["outcomes"] == {"shed": 20}
        assert report["annotated"] == 0
        svc.close()

    def test_bogus_cursor_is_typed(self, tmp_path):
        out = os.path.join(str(tmp_path), "out")
        os.makedirs(out)
        with open(os.path.join(out, "cursor.json"), "w",  # lint: allow[atomic-write] writing a bogus cursor fixture
                  encoding="utf-8") as f:
            f.write("[1, 2, 3]")
        svc = SgfAnalysisService(StubFleet(), out, sleep=lambda d: None)
        with pytest.raises(AnalysisCursorError):
            svc.run(str(tmp_path))
        svc.close()


# ---- the workload observatory's session label ----


class TestSessionWorkload:
    def test_characterize_reports_per_session_burstiness(self):
        recs = []
        t = 0.0
        for i in range(12):  # periodic session traffic: burstiness < 0
            t += 0.04
            recs.append({"digest": f"d{i}", "tier": "interactive",
                         "session": "live-0", "t": t})
        rng = random.Random(7)
        t = 0.0
        for i in range(40):  # bursty scan traffic
            t += rng.choice((0.001, 0.001, 0.001, 0.3))
            recs.append({"digest": f"s{i}", "tier": "batch",
                         "session": "scan:a.sgf", "t": t})
        recs.append({"digest": "x", "tier": "batch", "t": 1.0})  # unlabeled
        out = workload_mod.characterize(recs)
        sess = out["sessions"]
        assert sess["count"] == 2
        assert sess["labeled_requests"] == 52
        assert sess["top"]["live-0"]["requests"] == 12
        assert sess["top"]["live-0"]["burstiness"] < 0
        assert sess["top"]["scan:a.sgf"]["burstiness"] > 0
