"""Driver contract for bench.py: exactly one JSON line, required keys."""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import REPO_ROOT


def _run_bench(extra_env, timeout, args=()):
    # pin BENCH_WATCHDOG so an ambient =0 can't disable the tested
    # mechanism, and point BENCH_LAST_GOOD away from the committed
    # last-good table (failure tests assert the nothing-ever-measured
    # path; the stale-fallback path has its own test). DEEPGO_FLIGHT=0:
    # the watchdog's SIGUSR1 grace would otherwise drop a flight dump
    # into the checkout cwd (the recorder has its own tests)
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="",
               BENCH_WATCHDOG="1", GRAFT_WATCHDOG="1", DEEPGO_FLIGHT="0",
               BENCH_LAST_GOOD="/nonexistent/bench_last_good.json")
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py"), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_watchdog_emits_contract_json_and_fails():
    # a 1s budget guarantees the external watchdog beats any CPU bench; the
    # emitted line must still satisfy the driver's schema. The watchdog
    # SIGKILLs from outside (robust to a GIL-held wedge), so rc is -SIGKILL.
    proc = _run_bench({"BENCH_WATCHDOG_S": "1"}, timeout=120)
    assert proc.returncode != 0
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["metric"] == "policy_inference_boards_per_sec_per_chip"
    assert record["value"] == 0.0 and record["vs_baseline"] == 0.0
    assert "unreachable" in record["error"]


def test_preflight_probe_fails_fast_on_unreachable_device():
    # A bogus platform makes the probe child die quickly; bench must emit
    # one schema-compliant JSON line and exit 1 without ever arming the
    # 900s path. Retries pinned to 1 here; the retry path has its own test.
    proc = _run_bench({"JAX_PLATFORMS": "no_such_platform",
                       "BENCH_PREFLIGHT_TRIES": "1"}, timeout=120)
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["value"] == 0.0
    assert "pre-flight" in record["error"]


def test_preflight_probe_retries_before_giving_up():
    # One transient relay wedge must not zero the round's artifact
    # (BENCH_r03.json): the probe retries with backoff, announcing each
    # retry on stderr, and only the LAST failed attempt emits the JSON.
    proc = _run_bench({"JAX_PLATFORMS": "no_such_platform",
                       "BENCH_PREFLIGHT_TRIES": "3",
                       "BENCH_PREFLIGHT_BACKOFF_S": "0.1"}, timeout=120)
    assert proc.returncode == 1
    assert proc.stderr.count("retrying") == 2
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert "attempt 3/3" in record["error"]


def test_preflight_failure_degrades_to_stale_last_good(tmp_path):
    # Round-3 AND round-4 driver artifacts were zeroed by relay wedges at
    # capture time while the capability had been measured live earlier.
    # With a last-good table present, a capture-time failure must emit the
    # stale-but-real value (flagged stale, error preserved) and exit 0.
    last_good = tmp_path / "last_good.json"
    last_good.write_text(json.dumps({
        "policy_inference_boards_per_sec_per_chip": {
            "metric": "policy_inference_boards_per_sec_per_chip",
            "value": 104034.1, "unit": "boards/sec", "vs_baseline": 10.403,
            "timestamp": "2026-07-31T00:31:12Z", "git_sha": "acc7c87",
            "device": "TPU v5 lite0",
        }}))
    proc = _run_bench({"JAX_PLATFORMS": "no_such_platform",
                       "BENCH_PREFLIGHT_TRIES": "1",
                       "BENCH_LAST_GOOD": str(last_good)}, timeout=120)
    assert proc.returncode == 0
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["value"] == 104034.1
    assert record["stale"] is True
    assert "pre-flight" in record["error"]
    assert record["last_good"]["git_sha"] == "acc7c87"


def test_committed_last_good_table_is_wellformed():
    # the committed table is what a capture-time wedge falls back to; a
    # malformed entry would silently zero the round (the very failure this
    # mechanism exists to prevent)
    with open(os.path.join(REPO_ROOT, "BENCH_LAST_GOOD.json")) as f:
        table = json.load(f)
    assert "policy_inference_boards_per_sec_per_chip" in table
    for metric, entry in table.items():
        assert entry["metric"] == metric
        assert entry["value"] > 0
        assert entry["timestamp"] and entry["git_sha"]
        assert "TPU" in entry["device"]


def test_serving_chaos_bench_contract():
    # the chaos run: --mode serving --faults must survive the injected
    # dispatcher kill + transient forwards (via the supervisor), emit one
    # schema-compliant JSON line whose headline value is GOODPUT, and
    # carry the resilience counters next to it
    proc = _run_bench({"BENCH_PREFLIGHT": "0", "BENCH_WATCHDOG": "0"},
                      timeout=300, args=["--mode", "serving", "--faults"])
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["metric"] == \
        "serving_engine_goodput_under_faults_boards_per_sec"
    assert record["value"] > 0
    assert record["restarts"] >= 1  # the dispatcher kill really fired
    assert record["submitted"] == sum(record["outcomes"].values())
    assert "faults" in record and "poisoned" in record and "breaker" in record


def test_faults_flag_requires_serving_mode():
    proc = _run_bench({}, timeout=120, args=["--mode", "train", "--faults"])
    assert proc.returncode != 0
    assert "--faults only applies" in proc.stderr


@pytest.mark.skipif(not os.environ.get("DEEPGO_BENCH_FULL"),
                    reason="set DEEPGO_BENCH_FULL=1 for the ~2min CPU bench")
def test_cpu_bench_contract():
    proc = _run_bench({}, timeout=600)
    assert proc.returncode == 0
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["metric"] == "policy_inference_boards_per_sec_per_chip"
    assert record["value"] > 0
    assert set(record) >= {"metric", "value", "unit", "vs_baseline"}
