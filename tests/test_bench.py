"""Driver contract for bench.py: exactly one JSON line, required keys."""

import json
import os
import subprocess
import sys

import pytest

from tests.conftest import REPO_ROOT


def _run_bench(extra_env, timeout):
    # pin BENCH_WATCHDOG so an ambient =0 can't disable the tested mechanism
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH="",
               BENCH_WATCHDOG="1", GRAFT_WATCHDOG="1")
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, os.path.join(REPO_ROOT, "bench.py")],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


def test_watchdog_emits_contract_json_and_fails():
    # a 1s budget guarantees the external watchdog beats any CPU bench; the
    # emitted line must still satisfy the driver's schema. The watchdog
    # SIGKILLs from outside (robust to a GIL-held wedge), so rc is -SIGKILL.
    proc = _run_bench({"BENCH_WATCHDOG_S": "1"}, timeout=120)
    assert proc.returncode != 0
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["metric"] == "policy_inference_boards_per_sec_per_chip"
    assert record["value"] == 0.0 and record["vs_baseline"] == 0.0
    assert "unreachable" in record["error"]


def test_preflight_probe_fails_fast_on_unreachable_device():
    # A bogus platform makes the probe child die quickly; bench must emit
    # one schema-compliant JSON line and exit 1 without ever arming the
    # 900s path. Retries pinned to 1 here; the retry path has its own test.
    proc = _run_bench({"JAX_PLATFORMS": "no_such_platform",
                       "BENCH_PREFLIGHT_TRIES": "1"}, timeout=120)
    assert proc.returncode == 1
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["value"] == 0.0
    assert "pre-flight" in record["error"]


def test_preflight_probe_retries_before_giving_up():
    # One transient relay wedge must not zero the round's artifact
    # (BENCH_r03.json): the probe retries with backoff, announcing each
    # retry on stderr, and only the LAST failed attempt emits the JSON.
    proc = _run_bench({"JAX_PLATFORMS": "no_such_platform",
                       "BENCH_PREFLIGHT_TRIES": "3",
                       "BENCH_PREFLIGHT_BACKOFF_S": "0.1"}, timeout=120)
    assert proc.returncode == 1
    assert proc.stderr.count("retrying") == 2
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert "attempt 3/3" in record["error"]


@pytest.mark.skipif(not os.environ.get("DEEPGO_BENCH_FULL"),
                    reason="set DEEPGO_BENCH_FULL=1 for the ~2min CPU bench")
def test_cpu_bench_contract():
    proc = _run_bench({}, timeout=600)
    assert proc.returncode == 0
    lines = [l for l in proc.stdout.splitlines() if l.startswith("{")]
    assert len(lines) == 1
    record = json.loads(lines[0])
    assert record["metric"] == "policy_inference_boards_per_sec_per_chip"
    assert record["value"] > 0
    assert set(record) >= {"metric", "value", "unit", "vs_baseline"}
