"""Property/invariant tests over randomized play.

The reference guards its engine with inline asserts in the hot path
(makedata.lua:309,352,397,418); here the same invariants — plus the global
no-dead-chain board invariant the reference never checks — run over
thousands of random positions.
"""

import numpy as np

from deepgo_tpu.go import (
    EMPTY,
    find_groups,
    group_and_liberties,
    neighbors,
    new_board,
    play,
    simulate_play,
    summarize,
)


def _random_game(seed, n_moves=150):
    rng = np.random.default_rng(seed)
    stones, age = new_board()
    player = 1
    for _ in range(n_moves):
        empties = np.argwhere(stones == EMPTY)
        if len(empties) == 0:
            break
        x, y = empties[rng.integers(0, len(empties))]
        play(stones, age, int(x), int(y), player)
        player = 3 - player
    return stones, age


def test_no_dead_chains_after_any_move():
    """After capture resolution, every chain on the board has >= 1 liberty."""
    for seed in range(25):
        stones, _ = _random_game(seed)
        _, groups = find_groups(stones)
        for g in groups:
            assert len(g["liberties"]) >= 1, (seed, g["points"])


def test_age_consistent_with_occupancy():
    for seed in range(10):
        stones, age = _random_game(seed)
        # occupied points always have age >= 1
        assert (age[stones != EMPTY] >= 1).all()


def test_simulate_play_never_mutates():
    for seed in range(10):
        stones, _ = _random_game(seed, n_moves=80)
        before = stones.copy()
        for x in range(19):
            for y in range(19):
                if stones[x, y] == EMPTY:
                    simulate_play(stones, x, y, 1)
                    simulate_play(stones, x, y, 2)
        assert np.array_equal(stones, before), seed


def test_summarize_internal_consistency():
    for seed in range(5):
        stones, age = _random_game(seed, n_moves=100)
        packed = summarize(stones, age)
        # stones channel is the board
        assert np.array_equal(packed[0], stones)
        # liberties are zero exactly on empty points
        assert ((packed[1] > 0) == (stones != EMPTY)).all()
        # kills/liberties-after are zero on occupied points
        for c in range(2, 6):
            assert (packed[c][stones != EMPTY] == 0).all()
        # a point with kills > 0 must border an opponent chain in atari
        for player in (1, 2):
            kills = packed[4 + player - 1]
            for x, y in np.argwhere(kills > 0):
                neighbors_in_atari = any(
                    stones[nx, ny] == 3 - player
                    and len(group_and_liberties(stones, nx, ny)[1]) == 1
                    for nx, ny in neighbors(int(x), int(y))
                )
                assert neighbors_in_atari, (seed, x, y, player)
