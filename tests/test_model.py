"""Model, on-device expansion, and train-step tests (CPU backend)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deepgo_tpu import features
from deepgo_tpu.go import new_board, play, summarize
from deepgo_tpu.models import ModelConfig, apply, init, num_params
from deepgo_tpu.models.policy_cnn import log_policy
from deepgo_tpu.ops import expand_planes
from deepgo_tpu.training import make_eval_step, make_train_step, sgd, adagrad


def _packed_batch(n=4, seed=0):
    rng = np.random.default_rng(seed)
    out, players, ranks = [], [], []
    stones, age = new_board()
    for i in range(n * 10):
        x, y = rng.integers(0, 19, size=2)
        if stones[x, y] == 0:
            play(stones, age, int(x), int(y), int(i % 2 + 1))
        if i % 10 == 9:
            out.append(summarize(stones, age))
            players.append(i % 2 + 1)
            ranks.append(int(rng.integers(1, 10)))
    return (
        np.stack(out),
        np.array(players, dtype=np.int32),
        np.array(ranks, dtype=np.int32),
    )


def test_expand_matches_numpy_reference():
    packed, player, rank = _packed_batch()
    got = np.asarray(expand_planes(jnp.asarray(packed), jnp.asarray(player),
                                   jnp.asarray(rank), dtype=jnp.float32))
    for i in range(packed.shape[0]):
        want = features.expand_planes_np(packed[i], int(player[i]), int(rank[i]))
        # ours is NHWC; the reference layout is CHW
        assert np.array_equal(got[i].transpose(2, 0, 1), want), f"sample {i}"


def test_model_shapes_and_param_count():
    cfg = ModelConfig(num_layers=3, channels=64)
    params = init(jax.random.key(0), cfg)
    assert len(params["layers"]) == 3
    # 5x5x37x64 + 3x3x64x64 + 3x3x64x1 weights, plus (19,19,C) biases
    expected = (5 * 5 * 37 * 64 + 361 * 64) + (3 * 3 * 64 * 64 + 361 * 64) + (
        3 * 3 * 64 * 1 + 361
    )
    assert num_params(params) == expected

    planes = jnp.zeros((2, 19, 19, 37), jnp.float32)
    logits = apply(params, planes, cfg)
    assert logits.shape == (2, 361) and logits.dtype == jnp.float32


def test_per_layer_channel_schedule():
    # the reference's per-layer channel list (experiments.lua:88-93)
    cfg = ModelConfig(num_layers=4, channels=(32, 16, 8))
    params = init(jax.random.key(0), cfg)
    assert [layer["w"].shape for layer in params["layers"]] == [
        (5, 5, 37, 32), (3, 3, 32, 16), (3, 3, 16, 8), (3, 3, 8, 1)
    ]
    planes = jnp.zeros((2, 19, 19, 37), jnp.float32)
    assert apply(params, planes, cfg).shape == (2, 361)

    with pytest.raises(ValueError):
        ModelConfig(num_layers=3, channels=(32, 16, 8)).layer_shapes()


def test_channel_schedule_from_experiment_config():
    from deepgo_tpu.experiments import ExperimentConfig

    config = ExperimentConfig(num_layers=4, channel_schedule="32,16,8")
    cfg = config.model_config()
    assert cfg.channels == (32, 16, 8)
    # round-trips through the checkpointed config dict
    again = ExperimentConfig.from_dict(config.to_dict())
    assert again.model_config().channels == (32, 16, 8)


def test_log_policy_normalized():
    cfg = ModelConfig(num_layers=3, channels=16)
    params = init(jax.random.key(1), cfg)
    packed, player, rank = _packed_batch()
    planes = expand_planes(jnp.asarray(packed), jnp.asarray(player), jnp.asarray(rank))
    logp = log_policy(params, planes, cfg)
    np.testing.assert_allclose(np.exp(np.asarray(logp)).sum(-1), 1.0, rtol=1e-4)


def test_final_relu_parity_mode():
    cfg = ModelConfig(num_layers=3, channels=16, final_relu=True)
    params = init(jax.random.key(2), cfg)
    packed, player, rank = _packed_batch()
    planes = expand_planes(jnp.asarray(packed), jnp.asarray(player), jnp.asarray(rank))
    logits = apply(params, planes, cfg)
    assert (np.asarray(logits) >= 0).all()  # the reference's clamped head


@pytest.mark.parametrize("opt_name", ["sgd", "adagrad"])
def test_train_step_decreases_loss_and_rate_decay(opt_name):
    cfg = ModelConfig(num_layers=3, channels=16)
    params = init(jax.random.key(0), cfg)
    opt = sgd(0.05, rate_decay=1e-3) if opt_name == "sgd" else adagrad(0.05)
    opt_state = opt.init(params)
    step = make_train_step(cfg, opt)

    packed, player, rank = _packed_batch(n=4)
    batch = {
        "packed": jnp.asarray(packed),
        "player": jnp.asarray(player),
        "rank": jnp.asarray(rank),
        "target": jnp.asarray(np.array([3, 77, 240, 360], dtype=np.int32)),
    }
    losses = []
    for _ in range(25):
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    assert losses[0] > losses[-1], losses
    if opt_name == "sgd":
        # multiplicative rate decay, reference optimizer.lua:26
        np.testing.assert_allclose(
            float(opt_state["rate"]), 0.05 * (1 - 1e-3) ** 25, rtol=1e-5
        )


def test_eval_step_counts():
    cfg = ModelConfig(num_layers=2, channels=8)
    params = init(jax.random.key(0), cfg)
    evaluate = make_eval_step(cfg)
    packed, player, rank = _packed_batch(n=4)
    batch = {
        "packed": jnp.asarray(packed),
        "player": jnp.asarray(player),
        "rank": jnp.asarray(rank),
        "target": jnp.asarray(np.zeros(4, dtype=np.int32)),
    }
    sum_nll, correct = evaluate(params, batch)
    assert sum_nll.shape == () and 0 <= int(correct) <= 4
    assert float(sum_nll) > 0


def test_anchored_step_pulls_toward_anchor():
    """KL-anchored fine-tune: with a strong anchor term, training on
    arbitrary targets keeps the model's predictions close to the frozen
    anchor policy; without it they drift to the targets."""
    cfg = ModelConfig(num_layers=2, channels=8)
    anchor_params = init(jax.random.key(7), cfg)
    packed, player, rank = _packed_batch(n=4)
    batch = {
        "packed": jnp.asarray(packed),
        "player": jnp.asarray(player),
        "rank": jnp.asarray(rank),
        "target": jnp.asarray(np.array([3, 77, 240, 360], dtype=np.int32)),
    }

    from deepgo_tpu.ops import expand_planes

    planes = expand_planes(batch["packed"], batch["player"], batch["rank"],
                           dtype=jnp.float32)
    a_prob = np.asarray(jax.nn.softmax(
        apply(anchor_params, planes, cfg).astype(jnp.float32), axis=-1))

    def ce_to_anchor(p):
        logp = np.asarray(jax.nn.log_softmax(
            apply(p, planes, cfg).astype(jnp.float32), axis=-1))
        return float(-(a_prob * logp).sum(-1).mean())

    def nll_on_targets(p):
        logp = np.asarray(jax.nn.log_softmax(
            apply(p, planes, cfg).astype(jnp.float32), axis=-1))
        return float(-logp[np.arange(4), np.asarray(batch["target"])].mean())

    results = {}
    for weight in (0.0, 20.0):
        params = init(jax.random.key(1), cfg)
        opt = sgd(0.05)
        opt_state = opt.init(params)
        anchor = (anchor_params, cfg, weight) if weight else None
        step = make_train_step(cfg, opt, anchor=anchor)
        for _ in range(40):
            params, opt_state, loss = step(params, opt_state, batch)
        results[weight] = (ce_to_anchor(params), nll_on_targets(params),
                           float(loss))
    # the anchored run stays measurably closer to the anchor distribution
    # and resists overfitting the 4 arbitrary targets; the plain run does
    # the opposite
    assert results[20.0][0] < results[0.0][0] - 0.5
    assert results[20.0][1] > results[0.0][1]
    # the anchored loss includes the extra (positive) CE term
    assert results[20.0][2] > results[0.0][2]
