"""SGF parser robustness: arbitrary bytes must never crash the parser, and
malformed games must be skipped, not transcribed."""

import numpy as np

from deepgo_tpu import sgf
from deepgo_tpu.data.transcribe import transcribe_game


def test_parser_never_raises_on_garbage():
    rng = np.random.default_rng(0)
    for i in range(200):
        n = int(rng.integers(0, 400))
        blob = bytes(rng.integers(0, 256, size=n, dtype=np.uint8))
        text = blob.decode("latin-1")
        game = sgf.parse(text)  # must not raise
        assert isinstance(game.moves, list)


def test_parser_handles_adversarial_fragments():
    cases = [
        "",
        "(;)",
        "(;B[)",
        "(;B[aa",
        ";W[zz];B[a]",          # off-alphabet / wrong-length coords -> dropped
        "(;B[aa];B[aa])",       # same point twice: parser keeps both...
        "(;BR[d]WR[0d];B[aa])",  # malformed / out-of-range ranks
        "(;C[\\]]);B[cc]",
        "(" * 50 + ";B[aa]" + ")" * 50,
    ]
    for text in cases:
        game = sgf.parse(text)
        assert all(0 <= m.x < 19 and 0 <= m.y < 19 for m in game.moves), text


def test_transcribe_rejects_illegal_replay(tmp_path):
    # ...but the rules engine rejects the illegal double-play at replay time
    p = tmp_path / "bad.sgf"
    p.write_text("(;BR[1d]WR[1d];B[aa];W[aa])")
    import pytest
    from deepgo_tpu.go import IllegalMoveError

    with pytest.raises(IllegalMoveError):
        transcribe_game(str(p), engine="python")


def test_transcribe_split_survives_corrupt_file(tmp_path, capsys):
    """A corrupt SGF in a split is skipped with a stderr note; the rest
    transcribe (the pool worker catches per-game errors)."""
    from deepgo_tpu.data.transcribe import transcribe_split

    src = tmp_path / "src"
    src.mkdir()
    (src / "good.sgf").write_text("(;BR[3d]WR[4d];B[pd];W[dd];B[pp])")
    (src / "bad.sgf").write_text("(;BR[1d]WR[1d];B[aa];W[aa])")
    n = transcribe_split(str(src), str(tmp_path / "out"), workers=1,
                         verbose=False)
    assert n == 3  # the good game's moves only
    # pin the error path: bad.sgf must have gone through the exception
    # catch, not a silent None-result skip
    err = capsys.readouterr().err
    assert "bad.sgf" in err and "IllegalMoveError" in err
