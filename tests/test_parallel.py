"""Multi-device tests on the 8-device virtual CPU mesh.

The TPU-native analogue of testing DataParallelTable without a multi-GPU
host (SURVEY.md section 4): conftest forces 8 XLA host devices, and these
tests assert that sharded execution is numerically identical to
single-device execution — i.e. the mesh only changes *where* compute runs.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deepgo_tpu.models import ModelConfig, init
from deepgo_tpu.parallel import (data_sharding, make_mesh,
                                replicated_sharding, shard_opt_state,
                                sharded_fraction)
from deepgo_tpu.parallel.tensor import shard_params
from deepgo_tpu.training import make_train_step, sgd


pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices"
)


def _batch(bs=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "packed": jnp.asarray(
            rng.integers(0, 3, size=(bs, 9, 19, 19), dtype=np.uint8)
        ),
        "player": jnp.asarray(rng.integers(1, 3, size=bs, dtype=np.int32)),
        "rank": jnp.asarray(rng.integers(1, 10, size=bs, dtype=np.int32)),
        "target": jnp.asarray(rng.integers(0, 361, size=bs, dtype=np.int32)),
    }


def _run_steps(mesh, tp=False, steps=3, zero=False, momentum=0.0):
    # float32 compute: bf16 accumulation order would differ across meshes
    cfg = ModelConfig(num_layers=3, channels=16, compute_dtype="float32")
    opt = sgd(0.05, rate_decay=1e-4, momentum=momentum)
    params = init(jax.random.key(0), cfg)
    if tp:
        params = shard_params(params, mesh)
    else:
        params = jax.device_put(params, replicated_sharding(mesh))
    if zero:
        opt_state = shard_opt_state(opt.init(params), mesh)
    else:
        opt_state = jax.device_put(opt.init(params),
                                   replicated_sharding(mesh))
    step = make_train_step(cfg, opt)
    losses = []
    for i in range(steps):
        batch = jax.device_put(_batch(seed=i), data_sharding(mesh))
        params, opt_state, loss = step(params, opt_state, batch)
        losses.append(float(loss))
    return losses, params, opt_state


def test_data_parallel_matches_single_device():
    single, p1, _ = _run_steps(make_mesh(1, 1))
    dp8, p8, _ = _run_steps(make_mesh(8, 1))
    np.testing.assert_allclose(single, dp8, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p8)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_tensor_parallel_matches_single_device():
    single, _, _ = _run_steps(make_mesh(1, 1))
    tp, _, _ = _run_steps(make_mesh(2, 4), tp=True)
    np.testing.assert_allclose(single, tp, rtol=1e-5)


def test_dp_times_tp_mesh():
    losses, params, _ = _run_steps(make_mesh(4, 2), tp=True)
    assert losses[0] > losses[-1] or losses[0] == pytest.approx(losses[-1], abs=1.0)
    # hidden conv weights actually sharded over the model axis
    w1 = params["layers"][1]["w"]
    spec = w1.sharding.spec
    assert spec == P(None, None, None, "model")


def test_batch_sharding_layout():
    mesh = make_mesh(8, 1)
    batch = jax.device_put(_batch(), data_sharding(mesh))
    shard_shapes = {s.data.shape for s in batch["packed"].addressable_shards}
    assert shard_shapes == {(4, 9, 19, 19)}  # 32/8 per device


def test_zero_sharded_update_matches_replicated():
    # ZeRO-1 weight-update sharding (parallel/zero.py, arXiv:2004.13336):
    # placing the optimizer state sharded over the data axis must change
    # WHERE the update computes, never what it computes. Momentum makes
    # the state a full param-shaped buffer, so the test exercises real
    # sharded state, not just the scalar rate.
    rep, p_rep, _ = _run_steps(make_mesh(8, 1), momentum=0.9)
    zero, p_zero, opt_state = _run_steps(make_mesh(8, 1), momentum=0.9,
                                         zero=True)
    np.testing.assert_allclose(rep, zero, rtol=1e-5)
    for a, b in zip(jax.tree.leaves(p_rep), jax.tree.leaves(p_zero)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)
    # the velocity buffers really are distributed (the scalar rate and
    # any indivisible leaves replicate; everything else shards)
    assert sharded_fraction(opt_state) > 0.9
    v1 = opt_state["velocity"]["layers"][1]["w"]
    assert not v1.sharding.is_fully_replicated


def test_zero_composes_with_tensor_parallel():
    # under dp x tp the params are channel-sharded on "model"; ZeRO must
    # ADD "data" on a free axis of each buffer, not reshard "model" away
    losses, _, opt_state = _run_steps(make_mesh(4, 2), tp=True, zero=True,
                                      momentum=0.9)
    assert np.isfinite(losses).all()
    # a hidden conv's velocity carries BOTH axes: in-channels on "data"
    # (ZeRO) and out-channels on "model" (inherited tensor parallelism) —
    # the exact spec is the guard (a bare sharded-fraction check would
    # pass from the inherited "model" sharding alone)
    v1 = opt_state["velocity"]["layers"][1]["w"]
    assert v1.sharding.spec == P(None, None, "data", "model")
