"""Native C++ engine equivalence tests (skipped when no compiler)."""

import os

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu import sgf
from deepgo_tpu.go import native, new_board, play, replay_positions, summarize

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native engine not buildable"
)


def test_summarize_matches_python_random_boards():
    rng = np.random.default_rng(42)
    stones, age = new_board()
    for i in range(200):
        x, y = rng.integers(0, 19, size=2)
        if stones[x, y] == 0:
            play(stones, age, int(x), int(y), int(rng.integers(1, 3)))
        if i % 25 == 24:
            want = summarize(stones, age)
            got = native.summarize_native(stones, age)
            assert np.array_equal(got, want), f"after {i + 1} placements"


def test_transcribe_game_matches_python():
    path = os.path.join(REPO_ROOT, "data/sgf/validation/1950-59/2000-03-24a.sgf")
    game = sgf.parse_file(path)
    got = native.transcribe_game_native(game.handicaps, game.moves)
    want = np.stack([p for p, _ in replay_positions(game)])
    assert np.array_equal(got, want)


def test_transcribe_handicap_game():
    game = sgf.parse("(;BR[9d]WR[9d]AB[pd][dp]AW[dd];B[qq];W[oc])")
    got = native.transcribe_game_native(game.handicaps, game.moves)
    want = np.stack([p for p, _ in replay_positions(game)])
    assert np.array_equal(got, want)
    assert got[0, 6].max() == 3  # first handicap stone aged 3


def test_illegal_move_raises():
    from deepgo_tpu.go import IllegalMoveError

    game = sgf.parse("(;BR[1d]WR[1d];B[aa];W[aa])")
    with pytest.raises(IllegalMoveError):
        native.transcribe_game_native(game.handicaps, game.moves)


def test_transcribe_split_engine_parity(tmp_path):
    from deepgo_tpu.data.transcribe import transcribe_split

    src = os.path.join(REPO_ROOT, "data/sgf/test")
    n1 = transcribe_split(src, str(tmp_path / "native"), engine="native",
                          workers=1, verbose=False)
    n2 = transcribe_split(src, str(tmp_path / "python"), engine="python",
                          workers=1, verbose=False)
    assert n1 == n2 == 125
    a = np.fromfile(tmp_path / "native" / "planes.bin", dtype=np.uint8)
    b = np.fromfile(tmp_path / "python" / "planes.bin", dtype=np.uint8)
    assert np.array_equal(a, b)
