"""Native C++ engine equivalence tests (skipped when no compiler)."""

import os

import numpy as np
import pytest

from conftest import REPO_ROOT
from deepgo_tpu import sgf
from deepgo_tpu.go import native, new_board, play, replay_positions, summarize

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native engine not buildable"
)


def test_summarize_matches_python_random_boards():
    rng = np.random.default_rng(42)
    stones, age = new_board()
    for i in range(200):
        x, y = rng.integers(0, 19, size=2)
        if stones[x, y] == 0:
            play(stones, age, int(x), int(y), int(rng.integers(1, 3)))
        if i % 25 == 24:
            want = summarize(stones, age)
            got = native.summarize_native(stones, age)
            assert np.array_equal(got, want), f"after {i + 1} placements"


def test_transcribe_game_matches_python():
    path = os.path.join(REPO_ROOT, "data/sgf/validation/1950-59/2000-03-24a.sgf")
    game = sgf.parse_file(path)
    got = native.transcribe_game_native(game.handicaps, game.moves)
    want = np.stack([p for p, _ in replay_positions(game)])
    assert np.array_equal(got, want)


def test_transcribe_handicap_game():
    game = sgf.parse("(;BR[9d]WR[9d]AB[pd][dp]AW[dd];B[qq];W[oc])")
    got = native.transcribe_game_native(game.handicaps, game.moves)
    want = np.stack([p for p, _ in replay_positions(game)])
    assert np.array_equal(got, want)
    assert got[0, 6].max() == 3  # first handicap stone aged 3


def test_illegal_move_raises():
    from deepgo_tpu.go import IllegalMoveError

    game = sgf.parse("(;BR[1d]WR[1d];B[aa];W[aa])")
    with pytest.raises(IllegalMoveError):
        native.transcribe_game_native(game.handicaps, game.moves)


def test_transcribe_split_engine_parity(tmp_path):
    from deepgo_tpu.data.transcribe import transcribe_split

    src = os.path.join(REPO_ROOT, "data/sgf/test")
    n1 = transcribe_split(src, str(tmp_path / "native"), engine="native",
                          workers=1, verbose=False)
    n2 = transcribe_split(src, str(tmp_path / "python"), engine="python",
                          workers=1, verbose=False)
    assert n1 == n2 == 125
    a = np.fromfile(tmp_path / "native" / "planes.bin", dtype=np.uint8)
    b = np.fromfile(tmp_path / "python" / "planes.bin", dtype=np.uint8)
    assert np.array_equal(a, b)


def test_summarize_batch_matches_single():
    rng = np.random.default_rng(7)
    boards = []
    for _ in range(16):
        stones, age = new_board()
        for _ in range(int(rng.integers(5, 150))):
            x, y = rng.integers(0, 19, size=2)
            if stones[x, y] == 0:
                play(stones, age, int(x), int(y), int(rng.integers(1, 3)))
        boards.append((stones, age))
    got = native.summarize_batch_native(
        np.stack([b[0] for b in boards]), np.stack([b[1] for b in boards]))
    want = np.stack([native.summarize_native(s, a) for s, a in boards])
    assert np.array_equal(got, want)


def test_play_batch_matches_python_apply_move():
    """Native batched stepping (boards + ages + simple-ko) must be
    bit-identical to the pure-Python apply_move path over whole games."""
    from deepgo_tpu.arena import HeuristicAgent, OnePlyAgent, play_match
    import deepgo_tpu.go.native as nat

    games_n, _, stats_n = play_match(OnePlyAgent(), HeuristicAgent(),
                                     n_games=8, max_moves=120, seed=5)
    orig = nat.batch_available
    nat.batch_available = lambda: False
    try:
        games_p, _, stats_p = play_match(OnePlyAgent(), HeuristicAgent(),
                                         n_games=8, max_moves=120, seed=5)
    finally:
        nat.batch_available = orig
    for a, b in zip(games_n, games_p):
        assert [(m.player, m.x, m.y) for m in a.moves] == [
            (m.player, m.x, m.y) for m in b.moves]
        assert np.array_equal(a.stones, b.stones)
        assert np.array_equal(a.age, b.age)
        assert a.ko_point == b.ko_point
    assert stats_n["truncated"] == stats_p["truncated"]


def test_play_batch_ko_detection():
    """A single-stone capture leaving a lone 1-liberty stone sets the ko
    point; the native answer must match apply_move's."""
    from deepgo_tpu.selfplay import GameState, apply_move

    # classic ko shape: black b1c2d1, white c1 in atari after black plays c2?
    # Build directly: white stone at (2,2) surrounded by black (1,2),(3,2),(2,1)
    # with (2,3) empty; black plays (2,3) capturing nothing... use apply_move
    # as the oracle on a known ko: black captures the lone white stone.
    g = GameState()
    for x, y, p in [(1, 2, 1), (3, 2, 1), (2, 1, 1),  # black walls
                    (1, 3, 2), (3, 3, 2), (2, 4, 2),  # white walls
                    (2, 2, 2)]:  # white stone in the middle
        play(g.stones, g.age, x, y, p)
    g2 = GameState()
    g2.stones[:] = g.stones
    g2.age[:] = g.age
    # black plays (2,3): captures the white (2,2)? no — (2,2) has liberty
    # (2,3) only, so yes: single-stone capture -> ko at (2,2)
    g.player = 1
    apply_move(g, 2, 3)
    stones = g2.stones[None].copy()
    age = g2.age[None].copy()
    ko = native.play_batch_native(
        stones, age, np.array([2 * 19 + 3], dtype=np.int32),
        np.array([1], dtype=np.int32))
    assert np.array_equal(stones[0], g.stones)
    assert np.array_equal(age[0], g.age)
    want = -1 if g.ko_point is None else g.ko_point[0] * 19 + g.ko_point[1]
    assert ko[0] == want
    assert g.ko_point == (2, 2)  # the capture really was a ko


def test_step_games_pass_and_done_handling():
    """Passes, done games, and mixed batches behave identically on the
    native and fallback paths: done games are never touched, passes lift
    ko and count toward double-pass game end."""
    from deepgo_tpu.selfplay import GameState, step_games
    import deepgo_tpu.go.native as nat

    def build():
        gs = [GameState() for _ in range(4)]
        gs[0].done = True  # finished game must stay frozen
        gs[1].passes = 1   # one more pass ends it
        gs[2].ko_point = (3, 3)  # pass lifts the ban
        return gs

    for use_native in (True, False):
        gs = build()
        orig = nat.batch_available
        if not use_native:
            nat.batch_available = lambda: False
        try:
            step_games(gs, [5, -1, -1, 42], max_moves=100)
        finally:
            nat.batch_available = orig
        assert gs[0].moves == [] and gs[0].player == 1  # untouched
        assert gs[1].done and gs[1].passes == 2
        assert gs[2].ko_point is None and not gs[2].done
        assert len(gs[3].moves) == 1 and gs[3].player == 2
        assert gs[3].stones[divmod(42, 19)] == 1
