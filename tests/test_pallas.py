"""Pallas expansion kernel tests (interpret mode on the CPU backend)."""

import numpy as np

import jax.numpy as jnp

from deepgo_tpu.ops import expand_planes, get_expand_fn
from deepgo_tpu.ops.pallas_expand import expand_planes_pallas


def _inputs(b=16, seed=0):
    rng = np.random.default_rng(seed)
    return (
        jnp.asarray(rng.integers(0, 255, size=(b, 9, 19, 19), dtype=np.uint8)),
        jnp.asarray(rng.integers(1, 3, size=b).astype(np.int32)),
        jnp.asarray(rng.integers(1, 10, size=b).astype(np.int32)),
    )


def test_pallas_kernel_matches_xla_interpret():
    packed, player, rank = _inputs()
    want = np.asarray(expand_planes(packed, player, rank, dtype=jnp.float32))
    got = np.asarray(
        expand_planes_pallas(packed, player, rank, dtype=jnp.float32,
                             interpret=True)
    )
    assert np.array_equal(got, want)


def test_pallas_full_value_range_interpret():
    # uint8 extremes (e.g. age 255) must not fall into the match planes
    packed, player, rank = _inputs()
    packed = packed.at[:, 6].set(255)
    want = np.asarray(expand_planes(packed, player, rank, dtype=jnp.float32))
    got = np.asarray(
        expand_planes_pallas(packed, player, rank, dtype=jnp.float32,
                             interpret=True)
    )
    assert np.array_equal(got, want)
    assert want[:, :, :, 21:26].sum() == 0  # no age plane fires at 255


def test_backend_selection_degrades_gracefully():
    # "auto" on CPU (no Mosaic compile) must return the XLA path
    assert get_expand_fn("xla") is expand_planes
    assert get_expand_fn("auto") is expand_planes
